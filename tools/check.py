#!/usr/bin/env python
"""jaxsan driver: run the device-path linter + lock checker over the repo.

    python tools/check.py                 # lint, exit 0 iff clean
    python tools/check.py --fix-hints     # include fix-it hints
    python tools/check.py --list-waivers  # audit the waiver baseline
    python tools/check.py --json          # machine-readable findings

Exit codes: 0 = no unwaived findings; 1 = findings; 2 = configuration
error (a declared JIT entry point no longer reaches a jitted function —
the lint silently lost device-path coverage — or is missing from the
kernel observatory's ENTRY_KERNELS map, so its dispatches would go
unmeasured, or its kernel has no device cost-model entry
(perf/costmodel.py KERNEL_COSTS), so its observatory rows would carry
no compute/memory/comms-bound classification — `cost_model_gaps` — or
the streaming pipeline grew a dispatch path that
bypasses the measured_call/observatory seams — `pipeline_stages` — or a
telemetry surface lost coverage: a registered metric family without a
pre-seeded sample / bench-archive TYPE line, or a journey event/cause
the /debug/pod renderer cannot annotate — `obs_coverage`).

The same analysis runs in tier-1 via tests/test_jaxsan.py, so CI fails
on any unwaived finding; this CLI is the local/fix-up loop. Waiver
syntax (see kubernetes_tpu/analysis/findings.py):

    risky_line()  # jaxsan: waive[rule-id] why this is safe here
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def run_check(root: str = _REPO, package: str = "kubernetes_tpu",
              entry_points=None):
    """Returns (all findings, analyzer) — import surface for the pytest
    wrapper."""
    from kubernetes_tpu.analysis.findings import apply_waivers, parse_waivers
    from kubernetes_tpu.analysis.jaxsan import JaxsanAnalyzer
    from kubernetes_tpu.analysis.locks import LockChecker

    an = JaxsanAnalyzer(root, package=package,
                        entry_points=entry_points).load()
    findings = an.run()
    findings.extend(LockChecker(an.modules).run())
    waivers = {mi.path: parse_waivers(mi.source)
               for mi in an.modules.values()}
    apply_waivers(findings, waivers)
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings, an


def observatory_gaps(entry_points=None) -> list:
    """Entries the kernel observatory cannot attribute (ISSUE 14): every
    jaxsan ENTRY_POINT function must map to a ledger kernel via
    perf/observatory.py ENTRY_KERNELS — a new JIT entry cannot land
    unmeasured. Returns ["mod.fn (reason)", ...]; empty = covered."""
    from kubernetes_tpu.analysis.jaxsan import ENTRY_POINTS
    from kubernetes_tpu.perf.ledger import KERNELS
    from kubernetes_tpu.perf.observatory import ENTRY_KERNELS

    gaps: list[str] = []
    for mod, names in (entry_points or ENTRY_POINTS).items():
        for name in names:
            kernel = ENTRY_KERNELS.get(name)
            if kernel is None:
                gaps.append(f"{mod}.{name} (not in ENTRY_KERNELS)")
            elif kernel not in KERNELS:
                gaps.append(f"{mod}.{name} (maps to unknown kernel "
                            f"{kernel!r})")
    return gaps


def cost_model_gaps(entry_points=None) -> list:
    """ISSUE 20 `cost_model_gaps` check: every jaxsan ENTRY_POINT must
    resolve to a kernel with a host-estimator cost entry
    (perf/costmodel.py KERNEL_COSTS) — a new JIT entry cannot land
    without a flops/bytes model, or its observatory rows would carry no
    bound classification when XLA's cost_analysis is unavailable.
    Mirrors `observatory_gaps`. Returns ["mod.fn (reason)", ...];
    empty = covered."""
    from kubernetes_tpu.analysis.jaxsan import ENTRY_POINTS
    from kubernetes_tpu.perf.costmodel import KERNEL_COSTS
    from kubernetes_tpu.perf.observatory import ENTRY_KERNELS

    gaps: list[str] = []
    for mod, names in (entry_points or ENTRY_POINTS).items():
        for name in names:
            kernel = ENTRY_KERNELS.get(name)
            if kernel is None:
                continue     # observatory_gaps already reports this
            if kernel not in KERNEL_COSTS:
                gaps.append(f"{mod}.{name} (kernel {kernel!r} has no "
                            "perf/costmodel.py KERNEL_COSTS entry)")
    return gaps


# The streaming pipeline's only sanctioned routes to the device: the
# Scheduler seams, which run every kernel through
# CompileLedger.measured_call under the observatory capture installed by
# the scheduler. A stage thread reaching around them dispatches
# unmeasured work.
PIPELINE_DISPATCH_SEAMS = frozenset({
    "dispatch_once", "commit_ready", "schedule_pending",
    "flush_queues", "flush_backoff_completed",
})


def pipeline_stage_gaps(path: str = None, source: str = None) -> list:
    """ISSUE 18 `pipeline_stages` check: kubernetes_tpu/pipeline.py must
    reach the device ONLY through the Scheduler dispatch seams
    (PIPELINE_DISPATCH_SEAMS) — never by importing jax / the ops or
    parallel kernel modules, calling a declared JIT entry point, or
    invoking measured_call itself (attribution context lives in the
    Scheduler). Returns ["pipeline.py:LINE what (why)", ...]; empty =
    every dispatch path keeps measured_call/observatory attribution."""
    import ast

    from kubernetes_tpu.analysis.jaxsan import ENTRY_POINTS
    from kubernetes_tpu.perf.observatory import ENTRY_KERNELS

    if source is None:
        path = path or os.path.join(_REPO, "kubernetes_tpu", "pipeline.py")
        with open(path, encoding="utf-8") as f:
            source = f.read()
    fname = os.path.basename(path or "pipeline.py")
    tree = ast.parse(source, filename=fname)

    entry_names = ({n for names in ENTRY_POINTS.values() for n in names}
                   | set(ENTRY_KERNELS))
    banned_abs = ("jax", "kubernetes_tpu.ops", "kubernetes_tpu.parallel")
    banned_rel = ("ops", "parallel")

    def _banned_module(mod: str, level: int) -> bool:
        if level:                      # relative: from .ops.program import ..
            return any(mod == b or mod.startswith(b + ".")
                       for b in banned_rel)
        return any(mod == b or mod.startswith(b + ".")
                   for b in banned_abs)

    gaps: list[str] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if _banned_module(alias.name, 0):
                    gaps.append(
                        f"{fname}:{node.lineno} import {alias.name} "
                        "(kernel modules are off-limits to stage threads)")
        elif isinstance(node, ast.ImportFrom):
            if _banned_module(node.module or "", node.level):
                gaps.append(
                    f"{fname}:{node.lineno} from "
                    f"{'.' * node.level}{node.module or ''} import ... "
                    "(kernel modules are off-limits to stage threads)")
        elif isinstance(node, ast.Call):
            fn = node.func
            name = (fn.id if isinstance(fn, ast.Name)
                    else fn.attr if isinstance(fn, ast.Attribute) else None)
            if name in entry_names:
                gaps.append(
                    f"{fname}:{node.lineno} direct JIT entry call "
                    f"{name}() (bypasses the Scheduler dispatch seams "
                    f"{sorted(PIPELINE_DISPATCH_SEAMS)})")
            elif name == "measured_call":
                gaps.append(
                    f"{fname}:{node.lineno} raw measured_call() "
                    "(observatory attribution is installed by the "
                    "Scheduler, not the pipeline)")
    return gaps


def obs_coverage(prom_path: str = None) -> list:
    """ISSUE 19 `obs_coverage` check: the fleet-observatory surfaces must
    stay complete. (a) Every registered metric family is pre-seeded — a
    fresh SchedulerMetrics exposition yields at least one sample per
    family (histograms via their `_count` series; the callback gauge
    `scheduler_pending_pods` resolves only against a live scheduler and
    is exempt, mirroring the tier-1 exposition lint) — AND appears as a
    `# TYPE` family in bench_metrics.prom, so dashboards built on the
    bench archive never miss a series. (b) Every journey transition in
    obs/journey.py EVENTS and every requeue cause in CAUSES has a legend
    note in the /debug/pod stitched renderer (obs/stitch.py EVENT_NOTES
    / CAUSE_NOTES) — a new lifecycle event cannot land unrendered — and
    no stale note survives a removed code. Returns gap strings; empty =
    covered."""
    from kubernetes_tpu.metrics import SchedulerMetrics
    from kubernetes_tpu.obs.journey import CAUSES, EVENTS
    from kubernetes_tpu.obs.stitch import CAUSE_NOTES, EVENT_NOTES

    gaps: list[str] = []
    m = SchedulerMetrics()
    sampled = set()
    for line in m.exposition().splitlines():
        if line and not line.startswith("#"):
            sampled.add(line.partition("{")[0].partition(" ")[0])
    families = sorted(m.registry._metrics)
    for fam in families:
        if fam == "scheduler_pending_pods":
            continue               # callback gauge: no callback wired here
        if fam not in sampled and f"{fam}_count" not in sampled:
            gaps.append(f"{fam} (no pre-seeded sample in a fresh "
                        "exposition)")

    prom = prom_path or os.path.join(_REPO, "bench_metrics.prom")
    try:
        with open(prom, encoding="utf-8") as f:
            typed = {parts[2] for parts in
                     (ln.split() for ln in f if ln.startswith("# TYPE "))
                     if len(parts) >= 3}
    except OSError:
        typed = None
    if typed is None:
        gaps.append(f"{os.path.basename(prom)} unreadable (bench archive "
                    "missing — dashboards have no seed scrape)")
    else:
        for fam in families:
            if fam not in typed:
                gaps.append(f"{fam} (no TYPE family in "
                            f"{os.path.basename(prom)})")

    for ev in EVENTS:
        if ev not in EVENT_NOTES:
            gaps.append(f"journey event {ev!r} (no /debug/pod renderer "
                        "note in obs/stitch.py EVENT_NOTES)")
    for ev in EVENT_NOTES:
        if ev not in EVENTS:
            gaps.append(f"EVENT_NOTES entry {ev!r} (stale: not a journey "
                        "event)")
    for cause in CAUSES:
        if cause not in CAUSE_NOTES:
            gaps.append(f"requeue cause {cause!r} (no /debug/pod renderer "
                        "note in obs/stitch.py CAUSE_NOTES)")
    for cause in CAUSE_NOTES:
        if cause not in CAUSES:
            gaps.append(f"CAUSE_NOTES entry {cause!r} (stale: not a "
                        "requeue cause)")
    return gaps


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--root", default=_REPO)
    ap.add_argument("--package", default="kubernetes_tpu")
    ap.add_argument("--json", action="store_true", dest="as_json")
    ap.add_argument("--fix-hints", action="store_true",
                    help="print a fix-it hint under every finding")
    ap.add_argument("--list-waivers", action="store_true",
                    help="also print findings suppressed by inline waivers")
    ap.add_argument("--entries", action="append", default=None,
                    metavar="MOD:NAME,NAME",
                    help="override the JIT entry points (repeatable); "
                         "default: the nine kubernetes_tpu entries")
    args = ap.parse_args(argv)

    entry_points = None
    if args.entries:
        entry_points = {}
        for spec in args.entries:
            mod, _, names = spec.partition(":")
            entry_points[mod] = tuple(n for n in names.split(",") if n)

    findings, an = run_check(args.root, args.package, entry_points)
    live = [f for f in findings if not f.waived]
    waived = [f for f in findings if f.waived]
    # the observatory-coverage gate guards the REPO's declared entry
    # points; an ad-hoc --entries override lints someone else's tree,
    # whose functions have no business in ENTRY_KERNELS
    obs_gaps = [] if entry_points is not None else observatory_gaps()
    cost_gaps = [] if entry_points is not None else cost_model_gaps()
    pipe_gaps = [] if entry_points is not None else pipeline_stage_gaps()
    cov_gaps = [] if entry_points is not None else obs_coverage()

    if args.as_json:
        print(json.dumps({
            "findings": [f.to_dict() for f in live],
            "waived": [f.to_dict() for f in waived],
            "missingEntries": an.missing_entries,
            "observatoryGaps": obs_gaps,
            "costModelGaps": cost_gaps,
            "pipelineStageGaps": pipe_gaps,
            "obsCoverageGaps": cov_gaps,
            "modules": len(an.modules),
            "tracedFunctions": sum(1 for fi in an.fns.values()
                                   if fi.traced),
        }, indent=2))
    else:
        for f in live:
            print(f.format(fix_hints=args.fix_hints))
        if args.list_waivers:
            for f in waived:
                print(f.format(fix_hints=False))
        print(f"jaxsan: {len(an.modules)} modules, "
              f"{sum(1 for fi in an.fns.values() if fi.traced)} traced "
              f"functions, {len(live)} findings "
              f"({len(waived)} waived)")

    if an.missing_entries:
        print("jaxsan: CONFIG ERROR — entries without jit coverage: "
              + ", ".join(an.missing_entries), file=sys.stderr)
        return 2
    if obs_gaps:
        print("jaxsan: CONFIG ERROR — entries invisible to the kernel "
              "observatory (perf/observatory.py ENTRY_KERNELS): "
              + ", ".join(obs_gaps), file=sys.stderr)
        return 2
    if cost_gaps:
        print("jaxsan: CONFIG ERROR — cost_model_gaps: entries without "
              "a device cost-model entry (perf/costmodel.py "
              "KERNEL_COSTS): " + ", ".join(cost_gaps), file=sys.stderr)
        return 2
    if pipe_gaps:
        print("jaxsan: CONFIG ERROR — pipeline_stages: a dispatch path "
              "bypasses measured_call/observatory attribution: "
              + "; ".join(pipe_gaps), file=sys.stderr)
        return 2
    if cov_gaps:
        print("jaxsan: CONFIG ERROR — obs_coverage: a telemetry surface "
              "lost coverage (unseeded metric family, bench-archive "
              "family missing, or journey code without a renderer note): "
              + "; ".join(cov_gaps), file=sys.stderr)
        return 2
    return 1 if live else 0


if __name__ == "__main__":
    sys.exit(main())
