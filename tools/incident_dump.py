#!/usr/bin/env python
"""Render + verify an incident evidence bundle offline.

    python tools/incident_dump.py <bundle.json>           # render summary
    python tools/incident_dump.py <bundle.json> --json    # machine-readable
    python tools/incident_dump.py <bundle.json> --verify-only

A bundle is captured by the IncidentWatchdog (kubernetes_tpu/obs/
incident.py). This tool needs NOTHING from the live cluster: the audit
chain segments embedded in the bundle re-verify from their serialized
fields alone — each record's hash is sha256(prev_hash + canonical
chain bytes), each handoff-annex entry folds (shard|head|seq) from the
genesis hash — so a tampered bundle (or a ledger edited before capture)
is detectable months later from the JSON file.

Exit codes: 0 = chains verify; 1 = usage / unreadable bundle;
2 = a hash chain is broken (record chain, linkage, or handoff annex).
"""

from __future__ import annotations

import argparse
import hashlib
import json
import sys

GENESIS = "0" * 64


def _sha(*parts) -> str:
    h = hashlib.sha256()
    for p in parts:
        h.update(p if isinstance(p, bytes) else str(p).encode())
    return h.hexdigest()


def _chain_bytes(rec: dict) -> bytes:
    # must mirror obs/audit.py AuditRecord.chain_bytes exactly
    return json.dumps({"drain": rec["drainId"],
                       "profile": rec["profile"],
                       "fingerprints": rec["fingerprints"]},
                      sort_keys=True).encode()


def verify_record_chain(dump: dict) -> list[str]:
    """Re-verify one instance's audit slice: linkage record-to-record
    from the slice anchor, each hash recomputed, final hash == head
    (dump() slices from the tail, so the head IS the last record's
    hash). Returns human-readable problems; empty = verified."""
    problems: list[str] = []
    records = dump.get("records") or []
    head = dump.get("head", GENESIS)
    if not records:
        return problems
    prev = records[0].get("prevHash", GENESIS)
    for i, rec in enumerate(records):
        if rec.get("prevHash") != prev:
            problems.append(
                f"record {i} (drain {rec.get('drainId')}): prevHash "
                f"{rec.get('prevHash')!r:.20} does not link to "
                f"predecessor hash {prev!r:.20}")
            prev = rec.get("prevHash", prev)
        want = _sha(prev, _chain_bytes(rec))
        if rec.get("hash") != want:
            problems.append(
                f"record {i} (drain {rec.get('drainId')}): stored hash "
                f"does not match recomputed chain hash (content edited)")
        prev = rec.get("hash", want)
    if prev != head:
        problems.append(
            f"chain tail {prev!r:.20} != ledger head {head!r:.20} "
            "(slice spliced or head rewritten)")
    return problems


def verify_handoffs(entries: list, head: str) -> list[str]:
    """Re-fold the handoff annex chain from GENESIS (obs/audit.py
    record_handoff): each entry hashes (shard|predecessor head|seq)
    onto the previous annex hash."""
    problems: list[str] = []
    prev = GENESIS
    for i, e in enumerate(entries or []):
        if e.get("prev") != prev:
            problems.append(f"handoff {i} (shard {e.get('shard')}): "
                            "prev does not link to predecessor")
            prev = e.get("prev", prev)
        want = _sha(prev, f"{e['shard']}|{e['head']}|{e['seq']}"
                    .encode("utf-8"))
        if e.get("hash") != want:
            problems.append(f"handoff {i} (shard {e.get('shard')}): "
                            "stored hash does not match recomputation")
        prev = e.get("hash", want)
    if (entries or head != GENESIS) and prev != head:
        problems.append("handoff annex tail does not match handoffHead")
    return problems


def verify_bundle(bundle: dict) -> dict:
    """instance → list of problems across record chain + handoff annex."""
    out: dict = {}
    for name, slice_ in (bundle.get("audit") or {}).items():
        problems = verify_record_chain(slice_.get("dump") or {})
        problems += verify_handoffs(slice_.get("handoffs"),
                                    slice_.get("handoffHead", GENESIS))
        if slice_.get("dump", {}).get("chainValid") is False:
            problems.append("capture-time verify() already failed "
                            "(chainValid=false in the live ledger)")
        out[name] = problems
    return out


def render(bundle: dict, verdicts: dict) -> str:
    lines = [
        f"incident bundle: trigger={bundle.get('trigger')} "
        f"seq={bundle.get('sequence')} "
        f"capturedAt={bundle.get('capturedAt')}",
        f"signals: {json.dumps(bundle.get('signals') or {}, sort_keys=True)}",
    ]
    slo = bundle.get("slo") or {}
    breaches = slo.get("breaches") or []
    lines.append(f"federated SLO: {len(breaches)} breach(es)"
                 + ("".join(f"\n  - {b['sli']}/{b['window']} "
                            f"burn={b['burn']} (max {b['threshold']})"
                            for b in breaches)))
    journeys = bundle.get("journeys") or {}
    lines.append(f"stitched journeys: {len(journeys)} pod(s)")
    for uid, j in sorted(journeys.items()):
        lines.append(
            f"  {uid}: {len(j.get('transitions') or [])} transitions "
            f"across {len(j.get('instances') or [])} instance(s), "
            f"fences={j.get('fences')}")
    for name, flight in sorted((bundle.get("flight") or {}).items()):
        lines.append(f"flight[{name}]: {len(flight)} drain record(s)")
    shard_map = bundle.get("shardMap") or {}
    if shard_map:
        cur = shard_map.get("current") or {}
        lines.append(f"shard map: v{cur.get('version')} "
                     f"({cur.get('numShards')} shards), "
                     f"{len(shard_map.get('history') or [])} "
                     "historical version(s)")
    for name, problems in sorted(verdicts.items()):
        if problems:
            lines.append(f"audit[{name}]: CHAIN BROKEN")
            lines.extend(f"  ! {p}" for p in problems)
        else:
            n = len((bundle["audit"][name].get("dump") or {})
                    .get("records") or [])
            nh = len(bundle["audit"][name].get("handoffs") or [])
            lines.append(f"audit[{name}]: chain verified "
                         f"({n} records, {nh} handoffs)")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("bundle", help="incident bundle JSON path")
    ap.add_argument("--json", action="store_true", dest="as_json")
    ap.add_argument("--verify-only", action="store_true",
                    help="no rendering; just the chain verdicts")
    args = ap.parse_args(argv)

    try:
        with open(args.bundle, encoding="utf-8") as f:
            bundle = json.load(f)
    except (OSError, ValueError) as e:
        print(f"incident_dump: cannot read bundle: {e}", file=sys.stderr)
        return 1

    verdicts = verify_bundle(bundle)
    broken = {n: p for n, p in verdicts.items() if p}
    if args.as_json:
        print(json.dumps({"trigger": bundle.get("trigger"),
                          "sequence": bundle.get("sequence"),
                          "verdicts": verdicts,
                          "chainsValid": not broken}, indent=2))
    elif args.verify_only:
        for name, problems in sorted(verdicts.items()):
            status = "BROKEN" if problems else "ok"
            print(f"{name}: {status}")
            for p in problems:
                print(f"  ! {p}")
    else:
        print(render(bundle, verdicts))
    return 2 if broken else 0


if __name__ == "__main__":
    sys.exit(main())
