#!/usr/bin/env python
"""Bench regression sentinel: diff a bench summary against the BENCH_r* trail.

The BENCH_r01..rNN JSONs record every PR's measured throughput; until now
they were archaeology — nothing failed when a PR silently walked the
numbers back. This tool makes the trajectory an enforced contract:

  python tools/bench_compare.py                        # newest vs previous
  python tools/bench_compare.py --baseline BENCH_r04.json --new BENCH_r05.json
  python tools/bench_compare.py --check                # run a FRESH bench
  python tools/bench_compare.py --check --cases SchedulingBasic

It normalizes either format — the driver's BENCH_r wrapper
({"parsed": {...}}), the old headline+extra bench line, or the new
`summary` block — into {workload: {pods_per_s, p50, p99, attempt_p99_ms}}
and fails (exit 2) on:

  * throughput drop beyond the workload's noise threshold (default >10%;
    group/preemption workloads run wider — their pass-to-pass jitter in
    the BENCH history is ±20%, see NOISE);
  * attempt p99 latency growth >25% (when both sides carry the
    attempt_p99_ms extra; older BENCH files predate it and skip the check);
  * queue→bind e2e p99 latency growth >25% (the e2e_p99_ms extra from
    the sli_duration histogram, recorded since r13 — same
    skip-when-absent rule);
  * per-kernel device-time p99 growth >30% (the kernel observatory's
    `kernels` summary block, recorded since r14): one JIT entry
    regressing inside the device phase gates even when the workload's
    aggregate throughput hides it. Skipped for kernels absent on either
    side, and for sub-bucket jitter (<0.05 ms absolute growth);
  * sharded-lane growth >30% (the `lanes` summary block from
    profile_shard_lanes, recorded for the Sharded* cases since r10):
    comms share or lane-time imbalance regressing means the mesh port is
    sliding back toward collective-bound dispatch. Skipped when either
    side lacks the profile;
  * streaming-overlap loss (ISSUE 18, recorded for the Streaming* tiers
    since r11): a pipeline-mode workload whose stage occupancy
    (busy-seconds sum / wall) falls below 1.2 when the baseline held the
    floor — the drain quietly degraded back to lock-step. The Streaming*
    e2e-p99 numbers are DELTA quantiles for the paced window only, and
    ride the ordinary MAX_E2E_P99_GROWTH gate at the same offered load
    (the qps tier is part of the workload name);
  * with --slo: any burn-rate breach recorded in the candidate's per-
    workload `slo` block (obs/slo.py, evaluated at bench end), or ANY
    nonzero shadow-oracle divergence — a bench run whose decisions
    diverged from the host oracle fails regardless of its throughput.
    The SLI set is whatever obs/slo.py configures — with ISSUE 12 that
    gained failover time as a sixth SLI (`failover`: HA takeovers slower
    than the objective burn budget and gate here like any other breach);
    the warm-vs-cold takeover numbers themselves ride the bench extras
    (`HAFailover_*`), which are recorded but never gated. ISSUE 17 adds
    the `shard` block (MultiShardBasic_*): ANY double-bind or shadow-
    oracle divergence recorded by the sharded control plane fails too —
    the chaos matrix's zero-double-bind proof, enforced on every bench.

Workloads present on only one side are reported but never fail (the case
set grows over time); the `Sharded_` CPU-mesh probe is excluded — it is
compile evidence, not a throughput contract. Since r19 the bench payload
carries an `env` fingerprint (cpu model/count, python/jax/numpy
versions, JAX_PLATFORMS — and since r20 the resolved accelerator:
jax backend, device kind, device count): when BOTH sides carry one and
they differ, THROUGHPUT failures are downgraded to warnings — numbers
measured on different silicon are not an A/B — while every
correctness/latency-ratio gate (SLO, divergence, double-bind, p99 growth
ratios) stays strict. Same-fingerprint (same-container) comparisons are
unchanged. `--check` is also wired in as a `slow`-marked pytest
(tests/test_bench_compare.py), so CI enforces the trajectory instead of
trusting the changelog.

`--attribute` (ISSUE 20) adds differential attribution: for every shared
workload carrying a `critical_path` summary block on both sides, the
throughput delta is explained by the cause whose per-drain seconds moved
most ("SchedulingBasic dropped 8%" -> "commit seconds grew 2.1x") —
informational lines, never gates. `--attribute-self-test` verifies the
mode against a synthetic slowed-commit A/B and exits 2 unless it names
'commit'.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# default gates
MAX_THROUGHPUT_DROP = 0.10     # fraction of baseline pods/s
MAX_P99_GROWTH = 0.25          # fraction of baseline attempt_p99_ms
# queue→bind e2e latency gate (ISSUE 13): same shape as the attempt-p99
# gate, fed by the harness e2e_p99_ms extra (the sli_duration histogram's
# p99). Skipped when either side predates the field.
MAX_E2E_P99_GROWTH = 0.25
# host-phase-share gate (ISSUE 9): host_share = (host_build + commit) /
# drain cycle, recorded in the summary block since r08. A relative
# regression beyond this fraction means Python is clawing back the cycle
# the columnar ingest engine vacated. Skipped when either side predates
# the field.
MAX_HOST_SHARE_GROWTH = 0.10
# per-kernel device-time gate (ISSUE 14): the kernel observatory's
# `kernels` summary block records per-JIT-entry warm-dispatch p99 since
# r14. A single kernel's p99 growing past this fraction fails even when
# aggregate throughput hides it (one kernel regressing inside a phase
# another kernel sped up). Skipped when either side lacks the kernel —
# older BENCH files and workloads that never dispatch it.
MAX_KERNEL_P99_GROWTH = 0.30
# per-kernel jitter floor: sub-ms kernels round-trip through log2
# histogram buckets (~sqrt(2) quantile resolution), so growth below this
# many ms never gates
MIN_KERNEL_P99_MS = 0.05
# sharded-lane gate (ISSUE 16): profile_shard_lanes' decomposition rides
# the summary `lanes` block of the Sharded* cases since r10. Comms share
# or lane-time imbalance growing past this fraction means the mesh port
# is sliding back toward collective-bound dispatch even when throughput
# noise hides it. Skipped when either side lacks the profile.
MAX_LANE_GROWTH = 0.30

# per-workload noise thresholds (throughput drop), keyed by case-name
# prefix: the group/preemption workloads' measured passes jitter ±20%
# against sub-second windows (see `passes` in any BENCH_r file), so a 10%
# gate there would cry wolf
NOISE = {
    "TopologySpreading": 0.30,
    "SchedulingPodAntiAffinity": 0.30,
    "PreemptionChurn": 0.30,
    "MixedSchedulingBasePod": 0.20,
    # group-workload jitter applies (spread constraints live on every
    # measured pod); the case lands in r07+
    "MixedHighSignature": 0.30,
    "SchedulingNodeAffinity": 0.20,
    # group-workload gates for the gang suite (r06+): gang drains commit
    # in whole-gang lumps, so their per-window rates jitter like the
    # other group workloads
    "GangTraining": 0.30,
    "CoLocatedInference": 0.30,
    # the 8-virtual-device CPU mesh cases (ShardedBasic r09+, ShardedGang
    # and the 50k tier r10+): subprocess scheduling over XLA
    # host-platform shards jitters with machine load
    "ShardedBasic": 0.30,
    "ShardedGang": 0.30,
    # the sharded control plane (r17+): four instances round-robin one
    # in-process store with a mid-run steal — wall time jitters with
    # machine load like the other multi-process probes
    "MultiShardBasic": 0.30,
    # open-loop streaming tiers (r11 streaming pipeline, ISSUE 18): the
    # Poisson arrival process and adaptive batch-close policy make the
    # sustained rate jitter with machine load; the e2e-p99 gate
    # (MAX_E2E_P99_GROWTH) carries the latency contract at the same
    # offered load — workload names encode the qps tier, so a shared
    # name IS the same offered load
    "StreamingBasic": 0.30,
    "StreamingSharded": 0.30,
}

# streaming-overlap floor (ISSUE 18): pipeline-mode streaming workloads
# record stage-occupancy (busy-seconds sum / wall) in their `pipeline`
# block. Occupancy falling below this floor means the stages stopped
# overlapping — the drain degraded back to lock-step even if throughput
# noise hides it. Gated only when the BASELINE held the floor too, so a
# loaded machine can't make an old green run unreproducible.
MIN_STREAM_OCCUPANCY = 1.2

SKIP_PREFIXES = ("Sharded_",)


def slo_failures(new: dict) -> list:
    """--slo gate (ISSUE 10): a bench run breaching a configured
    burn-rate objective, or recording ANY shadow-oracle divergence,
    fails the sentinel regardless of its throughput numbers."""
    fails: list[str] = []
    for w in sorted(new):
        if w.startswith(SKIP_PREFIXES):
            continue
        slo = new[w].get("slo")
        if not isinstance(slo, dict):
            continue
        for b in slo.get("breaches") or []:
            fails.append(
                f"SLO BREACH {w}: {b.get('sli')}/{b.get('window')} "
                f"burn {b.get('burn')} > {b.get('threshold')}")
        div = int(slo.get("divergence_total",
                          slo.get("divergence_bad", 0)) or 0)
        if div:
            fails.append(f"ORACLE DIVERGENCE {w}: {div} shadow-audit "
                         "divergence(s) recorded")
    # the sharded-control-plane proof block (ISSUE 17): zero double-binds
    # and zero divergence are correctness invariants, not throughput —
    # any nonzero count fails the sentinel outright
    for w in sorted(new):
        shard = new[w].get("shard")
        if not isinstance(shard, dict) or not shard:
            continue
        db = int(shard.get("double_binds", 0) or 0)
        if db:
            fails.append(f"DOUBLE BIND {w}: {db} double-bind(s) recorded "
                         "by the sharded control plane")
        sdiv = int(shard.get("divergence", 0) or 0)
        if sdiv:
            fails.append(f"SHARD DIVERGENCE {w}: {sdiv} shadow-oracle "
                         "divergence(s) across the shard fleet")
        if shard.get("ledgers_verified") is False:
            fails.append(f"LEDGER BREAK {w}: a per-shard drain ledger "
                         "failed verification across a handoff")
        # the stitch proof (ISSUE 19): every bound pod must merge to ONE
        # cross-shard timeline reaching bind_confirm — an orphaned
        # fragment means an instance's lifecycle shard never stitched
        orph = int(shard.get("orphaned_fragments", 0) or 0)
        if orph:
            fails.append(f"ORPHANED JOURNEY {w}: {orph} per-instance "
                         "journey fragment(s) never stitched to a "
                         "confirmed bind")
        total = shard.get("journeys_total")
        stitched = shard.get("journeys_stitched")
        if total is not None and stitched is not None \
                and int(stitched) < int(total):
            fails.append(f"JOURNEY STITCH GAP {w}: {stitched}/{total} "
                         "bound pods stitched to a confirmed bind")
    return fails


def env_fingerprint(payload: dict) -> dict:
    """The bench run's `env` stamp (bench.py _env_fingerprint), {} when
    the payload predates it."""
    bench = payload.get("parsed", payload)
    env = bench.get("env") if isinstance(bench, dict) else None
    return env if isinstance(env, dict) else {}


def fingerprint_mismatch(base_env: dict, new_env: dict) -> list:
    """Fields on which two env fingerprints differ. Empty when they
    match — or when EITHER side lacks a stamp: an unknown environment
    stays strict rather than silently waiving the throughput gate."""
    if not base_env or not new_env:
        return []
    # `accelerator` (ISSUE 20 satellite): the RESOLVED jax backend +
    # device kind/count — a GPU-vs-CPU (or 1-vs-8-device) pair is not an
    # A/B even when JAX_PLATFORMS and the cpu model agree
    fields = ("cpu_model", "cpu_count", "versions", "jax_platforms",
              "accelerator")
    return [f for f in fields if base_env.get(f) != new_env.get(f)]


def throughput_gate(workload: str) -> float:
    for prefix, thr in NOISE.items():
        if workload.startswith(prefix):
            return thr
    return MAX_THROUGHPUT_DROP


def normalize(payload: dict) -> dict:
    """Any bench JSON shape → {workload: {pods_per_s, p50, p99,
    attempt_p50_ms, attempt_p99_ms}}."""
    bench = payload.get("parsed", payload)
    if not isinstance(bench, dict):
        raise ValueError("unrecognized bench payload")
    if isinstance(bench.get("summary"), dict):
        return {k: dict(v) for k, v in bench["summary"].items()
                if isinstance(v, dict)}
    # legacy headline + extra form
    out: dict = {}

    def entry(key: str, d: dict) -> None:
        out[key] = {
            "pods_per_s": float(d["value"]),
            "p50": float(d.get("p50", 0)), "p99": float(d.get("p99", 0)),
            "attempt_p50_ms": float(d.get("attempt_p50_ms", 0.0)),
            "attempt_p99_ms": float(d.get("attempt_p99_ms", 0.0)),
            "e2e_p50_ms": float(d.get("e2e_p50_ms", 0.0)),
            "e2e_p99_ms": float(d.get("e2e_p99_ms", 0.0)),
        }

    metric = bench.get("metric", "")
    if metric.endswith("_throughput") and isinstance(
            bench.get("value"), (int, float)):
        entry(metric[:-len("_throughput")], bench)
    for key, d in (bench.get("extra") or {}).items():
        if isinstance(d, dict) and isinstance(d.get("value"), (int, float)):
            entry(key, d)
    if not out:
        raise ValueError("no workload numbers found in bench payload")
    return out


def load_payload(path: str) -> dict:
    if path == "-":
        return json.load(sys.stdin)
    with open(path) as f:
        return json.load(f)


def load_summary(path: str) -> dict:
    return normalize(load_payload(path))


def bench_files(directory: str = REPO) -> list:
    """BENCH_r*.json paths, oldest → newest by their rNN number."""
    paths = glob.glob(os.path.join(directory, "BENCH_r*.json"))

    def rnum(p: str) -> int:
        m = re.search(r"BENCH_r(\d+)\.json$", p)
        return int(m.group(1)) if m else -1

    return sorted((p for p in paths if rnum(p) >= 0), key=rnum)


def compare(base: dict, new: dict) -> tuple[list, list]:
    """Returns (failures, report_lines); failures empty = sentinel green."""
    failures: list[str] = []
    report: list[str] = []
    shared = [w for w in sorted(set(base) & set(new))
              if not w.startswith(SKIP_PREFIXES)]
    for w in shared:
        b, n = base[w], new[w]
        b_tp, n_tp = float(b["pods_per_s"]), float(n["pods_per_s"])
        if b_tp <= 0:
            continue
        delta = n_tp / b_tp - 1.0
        gate = throughput_gate(w)
        line = (f"{w}: {b_tp:.1f} -> {n_tp:.1f} pods/s "
                f"({delta:+.1%}, gate -{gate:.0%})")
        if delta < -gate:
            failures.append(f"THROUGHPUT REGRESSION {line}")
        report.append(line)
        b_p99 = float(b.get("attempt_p99_ms") or 0.0)
        n_p99 = float(n.get("attempt_p99_ms") or 0.0)
        if b_p99 > 0 and n_p99 > 0:
            growth = n_p99 / b_p99 - 1.0
            line = (f"{w}: attempt p99 {b_p99:.1f} -> {n_p99:.1f} ms "
                    f"({growth:+.1%}, gate +{MAX_P99_GROWTH:.0%})")
            if growth > MAX_P99_GROWTH:
                failures.append(f"P99 LATENCY REGRESSION {line}")
            report.append(line)
        b_e2e = float(b.get("e2e_p99_ms") or 0.0)
        n_e2e = float(n.get("e2e_p99_ms") or 0.0)
        if b_e2e > 0 and n_e2e > 0:
            growth = n_e2e / b_e2e - 1.0
            line = (f"{w}: queue->bind e2e p99 {b_e2e:.1f} -> "
                    f"{n_e2e:.1f} ms "
                    f"({growth:+.1%}, gate +{MAX_E2E_P99_GROWTH:.0%})")
            if growth > MAX_E2E_P99_GROWTH:
                failures.append(f"E2E LATENCY REGRESSION {line}")
            report.append(line)
        b_hs = float(b.get("host_share") or 0.0)
        n_hs = float(n.get("host_share") or 0.0)
        if b_hs > 0 and n_hs > 0:
            growth = n_hs / b_hs - 1.0
            line = (f"{w}: host phase share {b_hs:.3f} -> {n_hs:.3f} "
                    f"({growth:+.1%}, gate +{MAX_HOST_SHARE_GROWTH:.0%})")
            if growth > MAX_HOST_SHARE_GROWTH:
                failures.append(f"HOST PHASE SHARE REGRESSION {line}")
            report.append(line)
        b_l = b.get("lanes") or {}
        n_l = n.get("lanes") or {}
        for field, label in (("commsShare", "comms share"),
                             ("imbalanceRatio", "lane imbalance")):
            b_v = float(b_l.get(field) or 0.0)
            n_v = float(n_l.get(field) or 0.0)
            if b_v <= 0 or n_v <= 0:
                continue
            growth = n_v / b_v - 1.0
            line = (f"{w}: {label} {b_v:.4f} -> {n_v:.4f} "
                    f"({growth:+.1%}, gate +{MAX_LANE_GROWTH:.0%})")
            if growth > MAX_LANE_GROWTH:
                failures.append(f"SHARDED LANE REGRESSION {line}")
            report.append(line)
        b_pipe = b.get("pipeline") or {}
        n_pipe = n.get("pipeline") or {}
        if (b_pipe.get("mode") == "pipeline"
                and n_pipe.get("mode") == "pipeline"):
            b_occ = float(b_pipe.get("occupancy") or 0.0)
            n_occ = float(n_pipe.get("occupancy") or 0.0)
            if b_occ > 0 and n_occ > 0:
                line = (f"{w}: stage occupancy {b_occ:.2f} -> {n_occ:.2f} "
                        f"(floor {MIN_STREAM_OCCUPANCY:.1f})")
                if n_occ < MIN_STREAM_OCCUPANCY <= b_occ:
                    failures.append(f"PIPELINE OVERLAP REGRESSION {line}")
                report.append(line)
        b_k = b.get("kernels") or {}
        n_k = n.get("kernels") or {}
        for kernel in sorted(set(b_k) & set(n_k)):
            b_kp = float(b_k[kernel].get("p99_ms") or 0.0)
            n_kp = float(n_k[kernel].get("p99_ms") or 0.0)
            if b_kp <= 0 or n_kp <= 0:
                continue
            growth = n_kp / b_kp - 1.0
            if growth > MAX_KERNEL_P99_GROWTH \
                    and n_kp - b_kp > MIN_KERNEL_P99_MS:
                failures.append(
                    f"KERNEL P99 REGRESSION {w}/{kernel}: "
                    f"{b_kp:.2f} -> {n_kp:.2f} ms "
                    f"({growth:+.1%}, gate +{MAX_KERNEL_P99_GROWTH:.0%})")
                report.append(
                    f"{w}/{kernel}: device p99 {b_kp:.2f} -> "
                    f"{n_kp:.2f} ms ({growth:+.1%})")
    for w in sorted(set(base) - set(new)):
        report.append(f"{w}: only in baseline (skipped)")
    for w in sorted(set(new) - set(base)):
        report.append(f"{w}: new workload (no baseline)")
    if not shared:
        failures.append("no shared workloads between baseline and new "
                        "summary — nothing was actually compared")
    return failures, report


def attribution_lines(base: dict, new: dict) -> list:
    """--attribute (ISSUE 20): explain each shared workload's throughput
    delta by the critical-path cause whose PER-DRAIN seconds moved most
    (perf/critical_path.attribute_delta over the summary blocks) —
    "SchedulingBasic dropped 8%" becomes "commit seconds grew 2.1x".
    Informational: the THROUGHPUT gate decides pass/fail; this answers
    the reviewer's 'why'. Workloads lacking a critical_path block on
    either side (pre-r20 baselines) are skipped."""
    sys.path.insert(0, REPO)
    from kubernetes_tpu.perf.critical_path import attribute_delta
    lines: list[str] = []
    for w in sorted(set(base) & set(new)):
        if w.startswith(SKIP_PREFIXES):
            continue
        b, n = base[w], new[w]
        moved = attribute_delta(b.get("critical_path") or {},
                                n.get("critical_path") or {})
        if not moved:
            continue
        b_tp = float(b.get("pods_per_s") or 0.0)
        n_tp = float(n.get("pods_per_s") or 0.0)
        tp = (f"throughput {n_tp / b_tp - 1.0:+.1%}" if b_tp > 0
              else "throughput n/a")
        ratio = moved.get("ratio")
        how = f"{ratio:.2f}x" if ratio else "new cause"
        lines.append(
            f"ATTRIBUTION {w}: {tp} <- {moved['cause']} per-drain "
            f"seconds {moved['base_s'] * 1e3:.3f} -> "
            f"{moved['new_s'] * 1e3:.3f} ms ({how})")
    return lines


def attribute_self_test() -> int:
    """--attribute-self-test: a synthetic A/B whose candidate grew its
    commit seconds 2.1x (with a throughput drop) MUST be attributed to
    'commit'; anything else exits 2 — the mode proves itself before
    anyone trusts it on a real regression."""
    def wl(tp: float, commit_s: float) -> dict:
        return {"pods_per_s": tp, "critical_path": {
            "drains": 10,
            "causes": {"host_build": 0.8, "device_compute": 1.2,
                       "device_comms": 0.0, "commit": commit_s,
                       "backpressure": 0.0, "idle": 0.3}}}
    base = {"SchedulingBasic_5000Nodes_10000Pods": wl(5000.0, 1.0)}
    new = {"SchedulingBasic_5000Nodes_10000Pods": wl(4600.0, 2.1)}
    lines = attribution_lines(base, new)
    ok = bool(lines) and "<- commit per-drain" in lines[0]
    for line in lines:
        print(f"  {line}")
    print("attribute self-test:",
          "OK" if ok else
          "FAIL (expected the synthetically slowed commit to be named)")
    return 0 if ok else 2


def run_fresh_bench(cases: str = "") -> dict:
    """Run bench.py in a subprocess; returns the raw payload."""
    cmd = [sys.executable, os.path.join(REPO, "bench.py")]
    if cases:
        cmd += ["--cases", cases]
    out = subprocess.run(cmd, capture_output=True, text=True, cwd=REPO)
    if out.returncode != 0:
        raise RuntimeError(f"bench.py exited {out.returncode}:\n"
                           f"{out.stderr.strip()[-2000:]}")
    line = out.stdout.strip().splitlines()[-1]
    return json.loads(line)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--baseline", default="",
                    help="baseline bench JSON (default: the newest "
                         "BENCH_r*.json — or the second newest when "
                         "--new is omitted)")
    ap.add_argument("--new", default="", dest="new_path",
                    help="candidate bench JSON ('-' = stdin; default: "
                         "the newest BENCH_r*.json)")
    ap.add_argument("--check", action="store_true",
                    help="run a FRESH bench.py as the candidate instead "
                         "of reading a file")
    ap.add_argument("--cases", default="",
                    help="with --check: forwarded to bench.py --cases")
    ap.add_argument("--slo", action="store_true",
                    help="also gate on the candidate's SLO block: fail "
                         "on any burn-rate breach or nonzero "
                         "shadow-oracle divergence (ISSUE 10)")
    ap.add_argument("--attribute", action="store_true",
                    help="differential attribution (ISSUE 20): explain "
                         "each workload's throughput delta by the "
                         "critical-path cause whose per-drain seconds "
                         "moved most")
    ap.add_argument("--attribute-self-test", action="store_true",
                    dest="attribute_self_test",
                    help="verify the attribution mode on a synthetic "
                         "slowed-commit A/B (exit 2 unless it names "
                         "'commit')")
    args = ap.parse_args(argv)

    if args.attribute_self_test:
        return attribute_self_test()

    trail = bench_files()
    if args.check:
        if not (args.baseline or trail):
            print("bench_compare: no BENCH_r*.json baseline found",
                  file=sys.stderr)
            return 3
        base_path = args.baseline or trail[-1]
        base_payload = load_payload(base_path)
        print(f"baseline: {os.path.basename(base_path)}; "
              "running fresh bench...", file=sys.stderr)
        new_payload = run_fresh_bench(args.cases)
    else:
        if args.new_path:
            new_payload = load_payload(args.new_path)
            base_path = args.baseline or (trail[-1] if trail else "")
        else:
            if len(trail) < 2 and not args.baseline:
                print("bench_compare: need two BENCH_r*.json files (or "
                      "--baseline/--new)", file=sys.stderr)
                return 3
            base_path = args.baseline or trail[-2]
            new_payload = load_payload(trail[-1])
            print(f"candidate: {os.path.basename(trail[-1])}",
                  file=sys.stderr)
        if not base_path:
            print("bench_compare: no baseline", file=sys.stderr)
            return 3
        base_payload = load_payload(base_path)
        print(f"baseline: {os.path.basename(base_path)}", file=sys.stderr)

    base = normalize(base_payload)
    new = normalize(new_payload)
    failures, report = compare(base, new)
    # environment fingerprint (ISSUE 19): across containers, a raw
    # pods/s drop proves nothing — downgrade THROUGHPUT failures to
    # warnings on a stamped mismatch. Every other gate (latency growth
    # RATIOS, SLO breaches, divergence, double-binds) stays strict:
    # those compare the run against itself, not against other silicon.
    mismatch = fingerprint_mismatch(env_fingerprint(base_payload),
                                    env_fingerprint(new_payload))
    if mismatch:
        kept = []
        for f in failures:
            if f.startswith("THROUGHPUT REGRESSION"):
                report.append("WARNING (env fingerprint differs on "
                              f"{', '.join(mismatch)} — not an A/B): {f}")
            else:
                kept.append(f)
        failures = kept
    if args.slo:
        slo_fails = slo_failures(new)
        failures.extend(slo_fails)
        report.append(f"SLO gate: {len(slo_fails)} failure(s)")
    if args.attribute:
        report.extend(attribution_lines(base, new) or
                      ["ATTRIBUTION: no shared workload carries a "
                       "critical_path block on both sides"])
    for line in report:
        print(f"  {line}")
    if failures:
        print("\nSENTINEL: FAIL")
        for f in failures:
            print(f"  {f}")
        return 2
    print("\nSENTINEL: OK")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
