#!/usr/bin/env python
"""Kernel sweep: the autotuner's measurement table (ISSUE 14, ROADMAP 6).

Sweeps the scheduler's device-shape knobs one at a time over a fixed
synthetic workload and records, per sweep point, the kernel
observatory's per-JIT-entry device-time delta plus end-to-end
throughput — the measurement substrate a future autotuner searches
instead of re-deriving. Every point runs a FRESH APIServer + Scheduler
(the knobs change compiled shapes; sharing a process-wide jit cache
across points is fine, sharing a scheduler is not).

Knobs (see KNOBS for the sweep lattices):

  wave_min_span   below this span length a group drain takes the
                  per-pod scan instead of a wave dispatch
                  (Scheduler.wave_min_span)
  plan_max_sigs   signature-count ceiling of a compiled DrainPlan; a
                  mix beyond it degrades (DrainCompiler.max_sigs,
                  default compiler/plan.py PLAN_MAX_SIGS)
  batch_size      the drain size, and through pow2_at_least the
                  run_uniform top-L tier (Scheduler._uniform_shape)
  scatter_shift   dirty-row scatter threshold: scatter when
                  dirty ≤ max(N >> shift, 32), else full upload
                  (state/tensorize.py ClusterState.scatter_shift)
  mesh_lanes      node-axis shard count: 0 = single device, else a
                  1-D mesh over that many devices — every drain runs
                  the sharded toolchain (parallel/sharding.py); lane
                  counts the host can't satisfy degrade to 0

Usage:

  python tools/kernel_sweep.py                       # full sweep → stdout
  python tools/kernel_sweep.py --out sweep.json
  python tools/kernel_sweep.py --knobs wave_min_span,plan_max_sigs
  python tools/kernel_sweep.py --nodes 500 --pods 1000
  python tools/kernel_sweep.py --self-test           # tiny 2-point sweep

Output: one JSON object keyed by backend →
{backend, nodes, pods, knobs: {name: {default, points: [{value,
pods_per_s, wall_s, kernels: {kernel: {calls, seconds, p50_ms,
p99_ms}}, cost_model: {kernel: [{plan, flops, bytes, ai, modeledMs,
measuredP50Ms, achievedFraction, bound, source}]}}]}}}. The cost_model
rows (ISSUE 20, perf/costmodel.py) carry XLA cost_analysis-derived
flops/bytes, arithmetic intensity and the achieved-vs-modeled fraction
per compiled plan variant, so a sweep point ranks against the roofline,
not only against its neighbors. CPU numbers rank RELATIVE cost only;
re-run on the TPU backend for absolute tables.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def _mesh_ctor(value):
    """Scheduler ctor kwargs for a `mesh_lanes` sweep point: a 1-D
    node-axis mesh over `value` devices, or single-device when the value
    is 0, the host lacks the devices, or the jax build has no shard_map
    (the point still measures — it just ranks the unsharded baseline)."""
    v = int(value)
    if v < 2:
        return {}
    import jax
    if len(jax.devices()) < v:
        return {}
    if not hasattr(jax, "shard_map"):
        try:
            from jax.experimental.shard_map import shard_map  # noqa: F401
        except ImportError:
            return {}
    from kubernetes_tpu.parallel.sharding import make_mesh
    return {"mesh": make_mesh(v)}


# knob name → (sweep lattice, how to apply the value). `ctor` knobs pass
# through the Scheduler constructor (`ctor_map` computes the kwargs from
# the value); `apply` knobs mutate the fresh instance before the first
# drain (all are consulted per drain).
KNOBS = {
    "wave_min_span": {
        "values": (8, 24, 64, 128),
        "default": 24,
        "apply": lambda sched, v: setattr(sched, "wave_min_span", int(v)),
    },
    "plan_max_sigs": {
        "values": (8, 16, 32, 64),
        "default": 32,
        "apply": lambda sched, v: setattr(sched.compiler, "max_sigs",
                                          int(v)),
    },
    "batch_size": {
        "values": (1024, 4096, 8192),
        "default": 8192,
        "ctor": True,
    },
    "scatter_shift": {
        "values": (1, 3, 6),
        "default": 3,
        "apply": lambda sched, v: setattr(sched.state, "scatter_shift",
                                          int(v)),
    },
    "mesh_lanes": {
        "values": (0, 2, 4),
        "default": 0,
        "ctor_map": _mesh_ctor,
    },
}


def _build(nodes: int, **ctor_kw):
    from kubernetes_tpu.backend.apiserver import APIServer
    from kubernetes_tpu.scheduler import Scheduler
    from kubernetes_tpu.testing.wrappers import make_node

    api = APIServer()
    sched = Scheduler(api, **ctor_kw)
    for i in range(nodes):
        api.create_node(
            make_node(f"n{i}")
            .capacity({"cpu": "16", "memory": "32Gi", "pods": 110})
            .zone(f"z{i % 4}")
            .label("kubernetes.io/hostname", f"n{i}").obj())
    return api, sched


def _feed(api, pods: int, spread_frac: float = 0.25) -> None:
    """Mixed workload: mostly plain pods (the uniform fast path) plus a
    spread slice (group seeding → wave/scan, the wave_min_span
    consumer)."""
    from kubernetes_tpu.testing.wrappers import make_pod

    n_spread = int(pods * spread_frac)
    for i in range(pods):
        w = make_pod(f"p{i}").req({"cpu": "100m", "memory": "64Mi"})
        if i < n_spread:
            w = w.label("app", "sweep").spread_constraint(
                1, "topology.kubernetes.io/zone", "ScheduleAnyway",
                {"app": "sweep"})
        api.create_pod(w.obj())


def run_point(knob: str, value, nodes: int, pods: int) -> dict:
    spec = KNOBS[knob]
    if spec.get("ctor_map"):
        ctor_kw = spec["ctor_map"](value)
    elif spec.get("ctor"):
        ctor_kw = {knob: value}
    else:
        ctor_kw = {}
    api, sched = _build(nodes, **ctor_kw)
    if "apply" in spec:
        spec["apply"](sched, value)
    obs = sched.observatory
    chk = obs.checkpoint()
    _feed(api, pods)
    t0 = time.perf_counter()
    bound = sched.schedule_pending()
    wall = time.perf_counter() - t0
    kernels = obs.delta_since(chk)
    # device cost-model rows for the kernels THIS point dispatched
    # (ISSUE 20): flops/bytes/arithmetic-intensity + achieved-vs-modeled
    # fraction per plan variant — the autotuner ranks measured seconds
    # against the roofline instead of only against other sweep points
    cost = {k: rows for k, rows in obs.cost_view().items() if k in kernels}
    return {
        "value": value,
        "bound": int(bound),
        "wall_s": round(wall, 4),
        "pods_per_s": round(bound / wall, 1) if wall > 0 else 0.0,
        "kernels": kernels,
        "cost_model": cost,
    }


def run_sweep(knobs, nodes: int, pods: int, points_per_knob: int = 0,
              verbose: bool = False) -> dict:
    import jax

    out = {"backend": jax.default_backend(), "nodes": nodes, "pods": pods,
           "knobs": {}}
    for knob in knobs:
        spec = KNOBS[knob]
        values = spec["values"]
        if points_per_knob:
            values = (values[0], values[-1])[:points_per_knob]
        points = []
        for v in values:
            if verbose:
                print(f"  sweep {knob}={v} ...", file=sys.stderr)
            points.append(run_point(knob, v, nodes, pods))
        out["knobs"][knob] = {"default": spec["default"], "points": points}
    return out


def self_test() -> int:
    """Tiny 2-point sweep over every knob; validates the JSON contract
    (tier-1: tests/test_observatory.py runs this)."""
    table = run_sweep(list(KNOBS), nodes=32, pods=48, points_per_knob=2)
    json.dumps(table)   # must be serializable
    assert table["backend"]
    for knob, spec in table["knobs"].items():
        pts = spec["points"]
        assert len(pts) == 2, (knob, pts)
        for p in pts:
            assert p["bound"] == 48, (knob, p)
            assert p["pods_per_s"] > 0, (knob, p)
            assert isinstance(p["kernels"], dict)
            # the drain must have dispatched SOMETHING measurable
            assert sum(k.get("dispatches", 0)
                       for k in p["kernels"].values()) > 0, (knob, p)
            # cost-model contract (ISSUE 20): every dispatched kernel's
            # rows carry the roofline fields
            assert isinstance(p["cost_model"], dict)
            assert p["cost_model"], (knob, p["kernels"].keys())
            for kern, rows in p["cost_model"].items():
                for row in rows:
                    for fld in ("flops", "bytes", "ai",
                                "achievedFraction", "bound", "source"):
                        assert fld in row, (knob, kern, fld, row)
    print("kernel_sweep self-test: OK "
          f"({len(table['knobs'])} knobs x 2 points, "
          f"backend={table['backend']})")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--out", default="", help="write JSON here (default "
                                              "stdout)")
    ap.add_argument("--knobs", default="",
                    help="comma-separated knob subset "
                         f"(default all: {','.join(KNOBS)})")
    ap.add_argument("--nodes", type=int, default=200)
    ap.add_argument("--pods", type=int, default=400)
    ap.add_argument("--self-test", action="store_true",
                    help="tiny 2-point sweep; exit 0 iff the JSON "
                         "contract holds")
    args = ap.parse_args(argv)

    if args.self_test:
        return self_test()

    knobs = [k for k in args.knobs.split(",") if k] or list(KNOBS)
    unknown = [k for k in knobs if k not in KNOBS]
    if unknown:
        print(f"kernel_sweep: unknown knob(s) {unknown} "
              f"(known: {sorted(KNOBS)})", file=sys.stderr)
        return 3
    table = run_sweep(knobs, args.nodes, args.pods, verbose=True)
    text = json.dumps(table, indent=2)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")
        print(f"kernel_sweep: wrote {args.out}", file=sys.stderr)
    else:
        print(text)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
