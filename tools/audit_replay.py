#!/usr/bin/env python
"""Standalone shadow-audit replay: re-run any ledgered drain off-line.

The shadow-oracle audit (kubernetes_tpu/obs/audit.py) writes one pickle
per audited drain when `shadowAuditDir` is set — the captured NodeInfo
clones, the pod list, the input fingerprints and the committed device
decisions. This tool re-executes that record through the host oracle
WITHOUT a live scheduler and reports the diff, so "why did pod X land on
node Y" (or "did drain 1234 really diverge") is answerable from an
artifact, long after the process is gone:

  python tools/audit_replay.py /path/to/drain_00001234.pkl
  python tools/audit_replay.py record.pkl --json          # machine form
  python tools/audit_replay.py record.pkl --cap 0         # full replay

Exit codes: 0 = replay matches the recorded device decisions,
2 = divergence found, 3 = unusable record.

The oracle framework is rebuilt from the default plugin set with the
recorded per-profile weights/strategy — exact for default-plugin
profiles (custom out-of-tree plugin sets need the live scheduler's
ledger instead).
"""

from __future__ import annotations

import argparse
import json
import os
import pickle
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def build_framework(profile_name: str, weights: dict):
    from kubernetes_tpu.framework.runtime import Framework
    from kubernetes_tpu.scheduler import DEFAULT_WEIGHTS, default_plugins
    w = dict(DEFAULT_WEIGHTS)
    w.update(weights or {})
    return Framework(profile_name, default_plugins(), weights=w)


def replay(payload: dict, cap: int = 64) -> dict:
    from kubernetes_tpu.obs.audit import (diff_decisions, replay_decisions,
                                          _sha)
    fwk = build_framework(payload.get("profile", "default-scheduler"),
                          payload.get("weights", {}))
    nodes = [ni.snapshot_clone() for ni in payload["nodes"]]
    oracle, oracle_reasons, truncated = replay_decisions(
        fwk, nodes, payload["pods"], device=payload.get("device"),
        cap=cap)
    diffs = diff_decisions(payload.get("device", {}),
                           payload.get("reasonsDevice", {}),
                           oracle, oracle_reasons,
                           reasons_ok=payload.get("reasonsOk", True)
                           and not truncated)
    # hash integrity: the pickle's chain entry must still hash to itself
    chain = json.dumps({"drain": payload["drainId"],
                        "profile": payload["profile"],
                        "fingerprints": payload["fingerprints"]},
                       sort_keys=True).encode()
    hash_ok = _sha(payload.get("prevHash", ""), chain) \
        == payload.get("hash", "")
    return {
        "drainId": payload["drainId"],
        "profile": payload["profile"],
        "pods": len(payload["pods"]),
        "replayed": min(cap, len(payload["pods"])) if cap
        else len(payload["pods"]),
        "truncated": truncated,
        "hashValid": hash_ok,
        "fingerprints": payload["fingerprints"],
        "oracle": {uid: (v["host"] if v else None)
                   for uid, v in oracle.items()},
        "diffs": diffs,
        "divergences": sum(len(v) for v in diffs.values()),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("record", help="drain_*.pkl written by the audit "
                                   "(shadowAuditDir)")
    ap.add_argument("--cap", type=int, default=64,
                    help="max pods to replay serially (0 = all; default "
                         "matches the live audit's prefix cap)")
    ap.add_argument("--json", action="store_true",
                    help="print the full machine-readable result")
    args = ap.parse_args(argv)

    try:
        with open(args.record, "rb") as f:
            payload = pickle.load(f)
        result = replay(payload, cap=args.cap)
    except Exception as e:
        print(f"audit_replay: unusable record: {e}", file=sys.stderr)
        return 3

    if args.json:
        print(json.dumps(result, indent=2, default=str))
    else:
        print(f"drain {result['drainId']} ({result['profile']}): "
              f"{result['pods']} pods, replayed {result['replayed']}"
              + (" (truncated)" if result["truncated"] else ""))
        print(f"  ledger hash: "
              f"{'VALID' if result['hashValid'] else 'BROKEN'}")
        for kind, items in result["diffs"].items():
            for d in items:
                print(f"  DIVERGENCE [{kind}] {d['pod']}: "
                      f"device={d['device']!r} oracle={d['oracle']!r}")
        if not result["diffs"]:
            print("  decisions identical to the host oracle")
    if not result["hashValid"]:
        return 3
    return 2 if result["diffs"] else 0


if __name__ == "__main__":
    raise SystemExit(main())
