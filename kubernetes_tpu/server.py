"""Serving surface: /healthz /readyz /metrics + leader election.

Mirrors cmd/kube-scheduler/app/server.go's operational endpoints (:190-211
healthz/readyz with handler-sync checks, :358-366 /metrics) and the
client-go leaderelection loop (:221-332), reduced to this framework's
in-process model:

- `SchedulerServer` runs a stdlib ThreadingHTTPServer on a background
  thread. /healthz is liveness (process up); /readyz additionally requires
  the informer handlers to be registered (the reference's
  WaitForHandlersSync analog) and — when leader election is on — this
  instance to hold the lease; /metrics serves the Prometheus exposition.
- /debug/* are the observability surfaces (GET /debug/ for the machine-
  readable index of every endpoint with its gate status): /debug/fleet
  (the fleet observatory: per-member role/journey/SLO/probe, ONE
  federated SLO burn per SLI, the capacity-weighted fleet probe;
  ?exposition=1 serves the shard/role-labeled fleet exposition),
  /debug/flightrecorder (the
  per-drain flight ring), /debug/slowcycles (slow span trees + slowest
  drains), /debug/events (the event recorder, ?reason=FailedScheduling to
  filter), /debug/cachedump (CacheDebugger.dump), /debug/cache (dump +
  full divergence sweep), /debug/hostprofile?seconds=N&format=collapsed|
  speedscope (the continuous host profiler's phase-attributed stacks —
  pipe the collapsed form into flamegraph.pl or drop either form onto
  speedscope.app), /debug/compileledger (per-kernel XLA compile
  seconds, retraces, donation misses, h2d bytes),
  /debug/audit?limit=N&details=1 (the shadow-oracle audit's hash-chained
  drain ledger: recent audits, divergence diffs, chain validity),
  /debug/explain?pod=<ns/name>&k=N (per-bind plugin-level score
  decomposition — exact replay when the drain is in the audit ledger)
  /debug/slo (per-SLI multi-window burn rates + breaches), /debug/ha
  (HA role, lease + fencing token, ledger-tail cursor/lag, takeover
  count and last failover seconds), /debug/shards (the sharded control
  plane: topology + assignment map, per-shard lease holders/generations,
  each instance's held/queued/parked slice), /debug/pod?uid=<ns/name> (the
  journey ledger's full causal timeline for one pod: every transition
  with timestamps + the per-segment e2e decomposition),
  /debug/cluster (the latest resolved cluster_probe snapshot:
  utilization percentiles, fragmentation/stranded indices, domain
  imbalance), /debug/pipeline (the streaming drain pipeline's occupancy
  block: per-stage busy seconds, overlap ratio, backpressure stalls,
  stage depths vs caps), /debug/timeline?seconds=N (the per-second aggregate
  telemetry ring over all SLIs + probe outputs) and
  /debug/kernels?plans=N&lanes=refresh (the kernel observatory:
  per-kernel run-wall histograms keyed by plan/shape signature, compile
  splits, the sharded-lane profile — ?lanes=refresh re-probes) and
  /debug/criticalpath?limit=N (the critical-path observatory: the last-N
  committed drains' bottleneck verdicts with per-cause seconds and
  binding chains, plus the window aggregate — verdict histogram,
  dominant cause, projected speedup ceiling).
- Leader election moved to `kubernetes_tpu/ha/` (ISSUE 12): the Lease
  object lives in the API server (backend/apiserver.py, with generation
  fencing tokens), `LeaderElector` in ha/lease.py (renew deadlines,
  jittered acquire retry, transition metrics). Both are re-exported here
  for back-compat — `from kubernetes_tpu.server import LeaderElector`
  keeps working. Multiple scheduler instances sharing one APIServer
  elect exactly one active scheduler; standbys call `tick()` and take
  over when the holder stops renewing — the active/passive HA pattern
  of the reference, now with the warm-spare takeover (/debug/ha).
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from .backend.apiserver import LEASE_NAME, Lease  # noqa: F401 (re-export)
from .ha.lease import LeaderElector

# every /debug endpoint, enumerated for the /debug/ index. The
# registration test (tests/test_federation.py) asserts this table and
# the do_GET handler chain stay in lockstep — a new endpoint MUST land
# in both or the suite fails.
DEBUG_ENDPOINTS = (
    ("/debug/cache", "cache debugger dump + full divergence sweep"),
    ("/debug/cachedump", "cache dump without the divergence sweep"),
    ("/debug/fleet", "federated fleet view: per-member role/journey/SLO/"
     "probe + ONE cluster SLO burn + capacity-weighted fleet probe "
     "(?exposition=1 for the shard/role-labeled fleet exposition)"),
    ("/debug/flightrecorder", "per-drain flight ring (?limit=N)"),
    ("/debug/slowcycles", "slow span trees + slowest drains"),
    ("/debug/hostprofile", "continuous host profiler stacks "
     "(?seconds=N&format=collapsed|speedscope)"),
    ("/debug/compileledger", "per-kernel XLA compile seconds, retraces, "
     "donation misses, h2d bytes"),
    ("/debug/kernels", "kernel observatory snapshot "
     "(?plans=N&lanes=refresh)"),
    ("/debug/criticalpath", "per-drain critical-path verdicts + window "
     "aggregate: bottleneck histogram, dominant cause, speedup ceiling "
     "(?limit=N)"),
    ("/debug/audit", "shadow-oracle audit's hash-chained drain ledger "
     "(?limit=N&details=1)"),
    ("/debug/explain", "per-bind plugin-level score decomposition "
     "(?pod=<ns/name>&k=N)"),
    ("/debug/ha", "HA role, lease + fencing token, ledger-tail cursor, "
     "takeover count"),
    ("/debug/pod", "pod journey timeline (?uid=<ns/name>) — stitched "
     "across shards when a shard manager is attached"),
    ("/debug/pipeline", "streaming drain pipeline occupancy: stage busy "
     "walls, overlap, backpressure, stall clock"),
    ("/debug/cluster", "latest resolved cluster_probe snapshot"),
    ("/debug/timeline", "per-second aggregate telemetry ring "
     "(?seconds=N)"),
    ("/debug/shards", "shard topology + per-shard leases + instance "
     "slices + incident watchdog summary"),
    ("/debug/slo", "per-SLI multi-window burn rates + breaches"),
    ("/debug/events", "event recorder dump (?reason=&limit=N)"),
)


class SchedulerServer:
    """healthz/readyz/metrics endpoints for one Scheduler instance."""

    def __init__(self, scheduler, host: str = "127.0.0.1", port: int = 0,
                 elector: Optional[LeaderElector] = None,
                 ha=None, shard_manager=None):
        """`ha` is an optional ha.StandbyScheduler whose debug() payload
        backs /debug/ha; without one the endpoint reports the reduced
        role/lease view assembled from `scheduler` + `elector`.
        `shard_manager` is an optional ha.ShardManager backing
        /debug/shards (topology, per-shard leases, instance slices)."""
        self.scheduler = scheduler
        self.elector = elector
        self.ha = ha
        self.shard_manager = shard_manager
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):
                pass

            def _send(self, code: int, body: str,
                      ctype: str = "text/plain; charset=utf-8"):
                data = body.encode()
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                if self.path == "/healthz":
                    self._send(200, "ok")
                elif self.path == "/readyz":
                    ready, why = outer.readiness()
                    self._send(200 if ready else 503, why)
                elif self.path == "/metrics":
                    self._send(200, outer.scheduler.metrics.exposition(),
                               "text/plain; version=0.0.4")
                elif self.path == "/statusz":
                    self._send(200, json.dumps(outer.status(), indent=2),
                               "application/json")
                elif self.path in ("/debug", "/debug/"):
                    # the index MUST be an exact match: every other
                    # /debug route below matches by prefix
                    avail = outer.debug_availability()
                    self._send(200, json.dumps({"endpoints": [
                        {"path": p, "description": d,
                         "available": avail.get(p, True)}
                        for p, d in DEBUG_ENDPOINTS]}, indent=2),
                        "application/json")
                elif self.path == "/debug/cache":
                    # cache debugger dump + comparer (the reference binds
                    # these to SIGUSR2, debugger.go:31-76; an endpoint is
                    # the serving-surface equivalent)
                    self._send(200, json.dumps({
                        "divergence": outer.scheduler.debug_compare(),
                        "dump": outer.scheduler.debugger.dump(),
                    }, indent=2, default=str), "application/json")
                elif self.path.startswith("/debug/cachedump"):
                    # dump WITHOUT the divergence sweep (the sweep quiesces
                    # the commit pipeline; the dump alone is read-only)
                    self._send(200, json.dumps(
                        outer.scheduler.debugger.dump(), indent=2,
                        default=str), "application/json")
                elif self.path.startswith("/debug/fleet"):
                    fleet = getattr(outer.shard_manager, "fleet", None)
                    if fleet is None:
                        self._send(404, "no fleet aggregator (shard "
                                        "manager not attached)")
                        return
                    q = self._query()
                    if q.get("exposition") == "1":
                        self._send(200, fleet.exposition(),
                                   "text/plain; version=0.0.4")
                    else:
                        self._send(200, json.dumps(
                            fleet.fleet_view(), indent=2, default=str),
                            "application/json")
                elif self.path.startswith("/debug/flightrecorder"):
                    q = self._query()
                    self._send(200, json.dumps({
                        "records": outer.scheduler.flight.dump(
                            limit=int(q.get("limit", "0"))),
                    }, indent=2), "application/json")
                elif self.path.startswith("/debug/slowcycles"):
                    tracer = outer.scheduler.tracer
                    self._send(200, json.dumps({
                        "slowCycles": [sp.to_dict()
                                       for sp in tracer.slow_cycles],
                        "slowestDrains": outer.scheduler.flight.slowest(),
                    }, indent=2), "application/json")
                elif self.path.startswith("/debug/hostprofile"):
                    prof = getattr(outer.scheduler, "profiler", None)
                    if prof is None:
                        self._send(404, "host profiler off "
                                        "(ContinuousHostProfiling gate / "
                                        "hostProfilerHz=0)")
                        return
                    q = self._query()
                    secs = (float(q["seconds"])
                            if q.get("seconds") else None)
                    if q.get("format") == "speedscope":
                        self._send(200, json.dumps(
                            prof.speedscope(seconds=secs)),
                            "application/json")
                    else:
                        self._send(200, prof.collapsed(seconds=secs))
                elif self.path.startswith("/debug/compileledger"):
                    from .perf.ledger import GLOBAL as ledger
                    self._send(200, json.dumps(ledger.snapshot(), indent=2),
                               "application/json")
                elif self.path.startswith("/debug/kernels"):
                    obs = outer.scheduler.observatory
                    if not obs.enabled:
                        self._send(404, "kernel observatory off "
                                        "(KernelObservatory gate)")
                        return
                    q = self._query()
                    if q.get("lanes") == "refresh":
                        # re-run the sharded-lane probe on the stashed
                        # dispatch inputs (no-op on unsharded schedulers)
                        outer.scheduler.profile_shard_lanes(force=True)
                    self._send(200, json.dumps(obs.snapshot(
                        top_plans=int(q.get("plans", "5"))),
                        indent=2), "application/json")
                elif self.path.startswith("/debug/criticalpath"):
                    sched = outer.scheduler
                    if not getattr(sched, "critical_path_enabled", False):
                        self._send(404, "critical path observatory off "
                                        "(CriticalPathObservatory gate)")
                        return
                    from .perf.critical_path import aggregate
                    q = self._query()
                    limit = int(q.get("limit", "32"))
                    rows = [
                        {"seq": d["seq"], "drainId": d["drainId"],
                         "pods": d["pods"], "profile": d["profile"],
                         "criticalPath": d["criticalPath"]}
                        for d in sched.flight.dump()
                        if d.get("criticalPath")]
                    if limit and len(rows) > limit:
                        rows = rows[-limit:]
                    self._send(200, json.dumps({
                        "drains": rows,
                        "aggregate": aggregate(
                            r["criticalPath"] for r in rows),
                    }, indent=2), "application/json")
                elif self.path.startswith("/debug/audit"):
                    audit = getattr(outer.scheduler, "audit", None)
                    if audit is None:
                        self._send(404, "shadow audit off "
                                        "(ShadowOracleAudit gate)")
                        return
                    q = self._query()
                    self._send(200, json.dumps(audit.dump(
                        limit=int(q.get("limit", "32")),
                        details=q.get("details") == "1"),
                        indent=2, default=str), "application/json")
                elif self.path.startswith("/debug/explain"):
                    q = self._query()
                    uid = q.get("pod", "")
                    if not uid:
                        self._send(400, "missing ?pod=<namespace/name>")
                        return
                    import time as _t
                    from .obs.explain import explain_pod
                    t0 = _t.perf_counter()
                    out = explain_pod(outer.scheduler, uid,
                                      k=int(q.get("k", "5")))
                    outer.scheduler.metrics.explain_duration.observe(
                        _t.perf_counter() - t0)
                    code = 404 if "error" in out else 200
                    self._send(code, json.dumps(out, indent=2,
                                                default=str),
                               "application/json")
                elif self.path.startswith("/debug/ha"):
                    if outer.ha is not None:
                        payload = outer.ha.debug()
                    else:
                        el = outer.elector
                        lease = (el.lock.get() if el is not None
                                 else None)
                        payload = {
                            "role": getattr(outer.scheduler, "ha_role",
                                            "active"),
                            "identity": (el.identity if el is not None
                                         else None),
                            "leader": (el.is_leader() if el is not None
                                       else True),
                            "fenceToken": (el.fence_token()
                                           if el is not None else None),
                            "lease": None if lease is None else {
                                "holder": lease.holder_identity,
                                "durationSeconds": lease.lease_duration_s,
                                "renewTime": lease.renew_time,
                                "transitions": lease.lease_transitions,
                                "generation": lease.generation,
                            },
                            "fencedRejected":
                                outer.scheduler.dispatcher.fenced,
                        }
                    self._send(200, json.dumps(payload, indent=2),
                               "application/json")
                elif self.path.startswith("/debug/pod"):
                    q = self._query()
                    uid = q.get("uid", "") or q.get("pod", "")
                    if not uid:
                        self._send(400, "missing ?uid=<namespace/name>")
                        return
                    journey = outer.scheduler.journey
                    if not journey.enabled:
                        self._send(404, "journey tracing off "
                                        "(PodJourneyTracing gate)")
                        return
                    # a shard manager's server stitches the fleet's
                    # per-instance ledgers into ONE cross-shard timeline
                    stitcher = getattr(outer.shard_manager, "stitcher",
                                       None)
                    out = (stitcher.pod(uid) if stitcher is not None
                           else journey.pod(uid))
                    code = (200 if out["transitions"]
                            or out["firstEnqueue"] is not None else 404)
                    self._send(code, json.dumps(out, indent=2),
                               "application/json")
                elif self.path.startswith("/debug/pipeline"):
                    pipe = getattr(outer.scheduler, "pipeline", None)
                    if pipe is None:
                        self._send(404, "streaming pipeline not attached "
                                        "(StreamingDrainPipeline gate / "
                                        "no StreamingPipeline started)")
                        return
                    self._send(200, json.dumps(pipe.stats(), indent=2),
                               "application/json")
                elif self.path.startswith("/debug/cluster"):
                    sched = outer.scheduler
                    self._send(200, json.dumps({
                        "probe": sched._last_probe,
                        "probeEnabled": sched._probe_enabled,
                        "journey": sched.journey.stats(),
                    }, indent=2), "application/json")
                elif self.path.startswith("/debug/timeline"):
                    q = self._query()
                    self._send(200, json.dumps(
                        outer.scheduler.timeline.series(
                            seconds=int(q.get("seconds", "60"))),
                        indent=2), "application/json")
                elif self.path.startswith("/debug/shards"):
                    if outer.shard_manager is not None:
                        payload = outer.shard_manager.debug()
                    else:
                        # unsharded instance: report its own slice view
                        sched = outer.scheduler
                        payload = {
                            "numShards": None,
                            "shardIds": list(getattr(sched, "shard_ids",
                                                     ())),
                            "parked": len(getattr(sched, "_shard_parked",
                                                  {})),
                        }
                    self._send(200, json.dumps(payload, indent=2,
                                               default=str),
                               "application/json")
                elif self.path.startswith("/debug/slo"):
                    self._send(200, json.dumps(
                        outer.scheduler.slo.snapshot(), indent=2),
                        "application/json")
                elif self.path.startswith("/debug/events"):
                    q = self._query()
                    self._send(200, json.dumps(
                        outer.scheduler.events.dump(
                            reason=q.get("reason"),
                            limit=int(q.get("limit", "0"))),
                        indent=2), "application/json")
                else:
                    self._send(404, "not found")

            def _query(self) -> dict:
                from urllib.parse import parse_qsl, urlsplit
                return dict(parse_qsl(urlsplit(self.path).query))

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)

    def debug_availability(self) -> dict:
        """Gate status per conditionally-available /debug endpoint
        (anything not listed here always serves). Backs the /debug/
        index so an operator sees WHY an endpoint 404s without curling
        each one."""
        s = self.scheduler
        return {
            "/debug/hostprofile": getattr(s, "profiler", None) is not None,
            "/debug/kernels": s.observatory.enabled,
            "/debug/criticalpath": getattr(s, "critical_path_enabled",
                                           False),
            "/debug/audit": getattr(s, "audit", None) is not None,
            "/debug/pod": s.journey.enabled,
            "/debug/pipeline": getattr(s, "pipeline", None) is not None,
            "/debug/fleet": getattr(self.shard_manager, "fleet",
                                    None) is not None,
        }

    def readiness(self) -> tuple[bool, str]:
        """server.go:190-211: handlers registered + (if elected) leading."""
        if not self.scheduler.client.pod_handlers:
            return False, "informer handlers not registered"
        if self.elector is not None and not self.elector.is_leader():
            return False, "not the leader"
        return True, "ok"

    def status(self) -> dict:
        s = self.scheduler
        return {
            "scheduled": s.scheduled_count,
            "attempts": s.schedule_attempts,
            "unschedulable": s.unschedulable_count,
            "errors": s.error_count,
            "deviceBatches": s.device_batches,
            "hostScheduled": s.host_scheduled,
            "preemptionAttempts": s.preemption_attempts,
            "pendingPods": s.queue.pending_pods()[1],
            "leader": (self.elector.is_leader()
                       if self.elector is not None else True),
        }

    def start(self) -> "SchedulerServer":
        self._thread.start()
        # SIGUSR2 → cache compare + dump to the log (debugger.go
        # ListenForSignal). Only possible from the main thread; embedded
        # uses fall back to the /debug/cache endpoint.
        try:
            import signal

            def on_usr2(signum, frame):
                from .utils.logging import klog
                klog.info("SIGUSR2: cache debugger",
                          divergence=self.scheduler.debug_compare())

            signal.signal(signal.SIGUSR2, on_usr2)
        except (ValueError, AttributeError, OSError):
            pass
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
