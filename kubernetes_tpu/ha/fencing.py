"""Fenced commits: lease generation as a fencing token on every write.

Leader election alone leaves the classic split-brain hole open: a leader
that pauses (GC stall, suspended VM) past its lease can wake up with
bind/patch/delete calls still sitting in its async dispatcher and flush
them AFTER a successor was elected — double-binding pods the new leader
already placed. The classic fix (Kleppmann's fencing tokens; Chubby
sequencers) is a monotonically increasing token issued with the lock and
checked by the resource: the API server bumps the Lease `generation` on
every holder change and rejects writes carrying an older one
(`FencedWrite`, deliberately terminal — the generation only moves
forward, so retrying cannot help).

This module is only the wiring. The mechanism lives in the layers below:

- `APICall.fence_token` is stamped at ENQUEUE time (dispatcher._stamp),
  so a call enqueued before deposition keeps its stale token no matter
  when the flush happens;
- bulk binds are fenced at the OLDEST token enqueued since the last
  flush (generations are monotonic, so that is the conservative choice:
  a batch spanning a depose boundary fails whole, and every member rides
  `on_bind_error`'s forget/requeue path — no assume leaks);
- `APIServer.check_fence` rejects stale tokens and counts
  `fenced_rejections`; the dispatcher surfaces them as
  `fenced_writes_rejected_total`.
"""

from __future__ import annotations

from .lease import LeaderElector


def fence_dispatcher(dispatcher, elector: LeaderElector) -> None:
    """Wire the elector's cached lease generation into the dispatcher as
    its fencing-token provider. Every subsequently-enqueued write is
    stamped with the generation current AT ENQUEUE — the property the
    whole scheme rests on."""
    dispatcher.fence = elector.fence_token


def unfence_dispatcher(dispatcher) -> None:
    """Detach the provider (tests / gate-off fallback). Already-stamped
    pending calls keep their tokens; only future enqueues are unfenced."""
    dispatcher.fence = None
