"""High availability + the sharded control plane (ROADMAP items 4/5).

Four cooperating parts, mirroring how the reference deploys
kube-scheduler replicas behind client-go `tools/leaderelection`:

- `ha.lease`: `LeaseLock` + `LeaderElector` — lease-based election over
  the fake API server with acquire/renew/release, renew deadlines and
  jittered retry (client-go leaderelection.go semantics, including the
  slow path where a deposed leader must stop before its lease expires).
- `ha.fencing`: stamps every dispatched write with the lease generation
  as a fencing token so a paused ex-leader's in-flight commits are
  rejected server-side — the split-brain hole election alone leaves open.
- `ha.standby`: a hot spare that tails the drain ledger + watch events to
  keep cache, device arrays and JIT caches warm, and takes over with a
  delta resync instead of a cold LIST + tensorize + compile warm-up.
- `ha.shards`: N fenced scheduler instances over ONE cluster — per-shard
  leases, a fenced/versioned shard assignment map, and warm lease-handoff
  rebalance (split/merge/steal) built on the standby's dual-stream seam.
"""

from .fencing import fence_dispatcher, unfence_dispatcher
from .lease import LeaderElector, LeaseLock
from .shards import (ShardManager, ShardScheduler, shard_key,
                     shard_lease_name)
from .standby import StandbyScheduler

__all__ = [
    "LeaderElector",
    "LeaseLock",
    "ShardManager",
    "ShardScheduler",
    "StandbyScheduler",
    "fence_dispatcher",
    "shard_key",
    "shard_lease_name",
    "unfence_dispatcher",
]
