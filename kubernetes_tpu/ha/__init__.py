"""Active/standby high availability (ROADMAP item 5).

Three cooperating parts, mirroring how the reference deploys
kube-scheduler replicas behind client-go `tools/leaderelection`:

- `ha.lease`: `LeaseLock` + `LeaderElector` — lease-based election over
  the fake API server with acquire/renew/release, renew deadlines and
  jittered retry (client-go leaderelection.go semantics, including the
  slow path where a deposed leader must stop before its lease expires).
- `ha.fencing`: stamps every dispatched write with the lease generation
  as a fencing token so a paused ex-leader's in-flight commits are
  rejected server-side — the split-brain hole election alone leaves open.
- `ha.standby`: a hot spare that tails the drain ledger + watch events to
  keep cache, device arrays and JIT caches warm, and takes over with a
  delta resync instead of a cold LIST + tensorize + compile warm-up.
"""

from .fencing import fence_dispatcher, unfence_dispatcher
from .lease import LeaderElector, LeaseLock
from .standby import StandbyScheduler

__all__ = [
    "LeaderElector",
    "LeaseLock",
    "StandbyScheduler",
    "fence_dispatcher",
    "unfence_dispatcher",
]
