"""Sharded control plane: N fenced schedulers over ONE cluster.

PR 11's active/standby HA built two seams this module composes into a
horizontally scaled control plane:

- **per-shard leases** (`ha/lease.py` electors against
  `kube-scheduler-shard-<i>` Lease objects): which INSTANCE owns a shard
  is exactly who holds its lease, and the lease's fencing generation —
  bumped on every holder change — is the cross-shard ordering primitive.
  Every write an instance dispatches for a shard's pods is stamped with
  a `(lease_name, generation)` pair (dispatcher `fence_for`), so an
  instance that loses a shard lease mid-flush provably cannot
  double-bind: its late writes arrive with a stale generation and the
  API server rejects them terminally (`FencedWrite`), unwinding the
  assumes through `on_bind_error` — the PR-11 zombie proof, now N-way.

- **the standby dual-stream** (watch + drain-ledger tail): every
  instance registers the normal informer handlers, so peers' pods ride
  its watch stream into the workload/cache state but PARK instead of
  queueing (`Scheduler.shard_filter` / `_shard_parked`). A shard
  rebalance or steal is therefore a lease handoff plus
  `shard_evict()`/`shard_adopt()` — a warm splice, not a cold LIST —
  and the successor anchors the predecessor's audit-chain position via
  `DrainLedger.record_handoff`, so every per-shard ledger verifies
  across every handoff.

WHICH pods belong to WHICH shard is the `ShardMap`: one fenced,
versioned API object keyed by `scheduler_name/namespace` with a stable
hash fallback, CAS'd through `APIServer.put_shard_map` so topology
changes (split 1→N, merge N→1) are themselves fenced writes. Cross-shard
bind races on overlapping nodes surface as `Conflict` (the pod-level
"already assigned" guard in `bind_all`) or `FencedWrite` and unwind
cleanly — both counted as `scheduler_cross_shard_conflicts_total`.
"""

from __future__ import annotations

import time as _time
from typing import Callable, Optional

from ..backend.apiserver import Conflict, FencedWrite, ShardMap
from ..obs.journey import EV_STEAL as _EV_STEAL, EV_TRANSFER as _EV_TRANSFER
from ..scheduler import Scheduler
from .lease import LeaderElector

SHARD_LEASE_PREFIX = "kube-scheduler-shard-"


def shard_lease_name(shard_id: int) -> str:
    """Lease object name for one shard's ownership election."""
    return f"{SHARD_LEASE_PREFIX}{shard_id}"


def shard_key(pod) -> str:
    """The ShardMap routing key: profile/namespace — the multi-tenant
    axis (ROADMAP item 4), so one tenant's burst saturates one shard."""
    return f"{pod.spec.scheduler_name}/{pod.namespace}"


class ShardScheduler:
    """One control-plane instance in the sharded fleet: an inner (active)
    Scheduler plus one elector per shard lease it contends for. An
    instance may hold SEVERAL shard leases at once (a merge collapses
    ownership of all shards onto one instance), which is why the
    dispatcher fences per pod (`fence_for`), not per instance."""

    def __init__(self, client, identity: str,
                 lease_duration_s: float = 15.0,
                 clock: Optional[Callable[[], float]] = None,
                 scheduler: Optional[Scheduler] = None,
                 **scheduler_kwargs):
        if scheduler is None and clock is not None:
            # the fleet's manual clock drives the inner scheduler too
            scheduler_kwargs.setdefault("clock", clock)
        self.scheduler = (scheduler if scheduler is not None
                          else Scheduler(client, **scheduler_kwargs))
        self.client = client
        self.identity = identity
        self.lease_duration_s = lease_duration_s
        self.clock = clock if clock is not None else self.scheduler.clock
        self.electors: dict[int, LeaderElector] = {}
        self._map: Optional[ShardMap] = None
        # peer drain-ledger tails (wired by ShardManager.wire_ledgers):
        # identity -> DrainLedger; the dual-stream's second leg
        self.peer_ledgers: dict[str, object] = {}
        self.cursors: dict[str, int] = {}
        self.conflicts = 0            # cross-shard bind unwinds seen
        sched = self.scheduler
        sched.shard_filter = self._owns_pod
        sched.dispatcher.fence_for = self._fence_for
        # route bind unwinds through the shard-aware wrapper: the
        # scheduler's forget/requeue runs first (the assume MUST unwind),
        # then a pod this shard no longer owns re-parks instead of
        # re-queueing — otherwise the loser of a cross-shard race would
        # keep re-scheduling the winner's pod
        self._chain_bind_error = sched.dispatcher.on_bind_error
        sched.dispatcher.on_bind_error = self._on_bind_error
        # stitching provenance (obs/stitch.py): every journey transition
        # this instance writes carries its identity plus the held-lease
        # fence stamp — a zombie's post-depose transitions remain
        # distinguishable from the new owner's in the merged timeline
        sched.journey.instance = identity
        sched.journey.fence_stamp = self._fence_stamp

    # -- ownership ------------------------------------------------------------

    def refresh_map(self) -> ShardMap:
        self._map = self.client.get_shard_map()
        return self._map

    def _shard_of(self, pod) -> int:
        m = self._map if self._map is not None else self.refresh_map()
        return m.shard_for(shard_key(pod))

    def _owns_pod(self, pod) -> bool:
        e = self.electors.get(self._shard_of(pod))
        return e is not None and e.is_leader()

    def _fence_for(self, pod):
        """The (lease, generation) pair for the pod's shard. An instance
        that does NOT hold the shard's lease stamps generation -1 — any
        such write is fenced the moment the lease exists at all."""
        sid = self._shard_of(pod)
        e = self.electors.get(sid)
        gen = e.fence_token() if e is not None else None
        return (shard_lease_name(sid), gen if gen is not None else -1)

    def _fence_stamp(self) -> str:
        """Journey-ledger fence stamp: the writer's currently HELD
        (lease, generation) set, joined — "" when this instance holds
        no shard lease (unfenced writer)."""
        return ",".join(
            f"{shard_lease_name(sid)}@{e.fence_token()}"
            for sid, e in sorted(self.electors.items()) if e.is_leader())

    def elector_for(self, sid: int) -> LeaderElector:
        e = self.electors.get(sid)
        if e is None:
            e = LeaderElector(self.client, self.identity,
                              lease_duration_s=self.lease_duration_s,
                              clock=self.clock,
                              metrics=self.scheduler.metrics,
                              lease_name=shard_lease_name(sid))
            self.electors[sid] = e
        return e

    def holds(self, sid: int) -> bool:
        e = self.electors.get(sid)
        return e is not None and e.is_leader()

    def held(self) -> tuple:
        return tuple(sorted(sid for sid, e in self.electors.items()
                            if e.is_leader()))

    def tick(self) -> tuple:
        """One election round on every contended shard lease; returns the
        shard ids currently held. A lost lease demotes only that SLICE —
        the instance stays active for the shards it still holds."""
        for e in self.electors.values():
            e.tick()
        self.scheduler.shard_ids = held = self.held()
        return held

    def rebalance(self) -> tuple:
        """React to a topology/lease change: re-read the map, park what
        this instance no longer owns, adopt what it now does. Safe to
        call redundantly (both halves are no-ops at a fixed point)."""
        self.refresh_map()
        evicted = self.scheduler.shard_evict()
        adopted = self.scheduler.shard_adopt()
        self.scheduler.shard_ids = self.held()
        return evicted, adopted

    # -- warmth (the dual-stream's ledger leg) --------------------------------

    def sync(self) -> int:
        """Consume peer drain-ledger tails: per-peer cursors + the lag
        gauge stay current, so a steal annexes an up-to-date chain
        position and the operator can see how warm each peer is."""
        consumed = 0
        worst = 0
        for ident, ledger in self.peer_ledgers.items():
            cur = self.cursors.get(ident, 0)
            for rec in ledger.tail(cur):
                cur = rec.seq
                consumed += 1
            self.cursors[ident] = cur
            worst = max(worst, ledger.lag(cur))
        if self.peer_ledgers:
            self.scheduler.metrics.ha_ledger_tail_lag.set(float(worst))
        return consumed

    def audit_ledger(self):
        a = self.scheduler.audit
        return None if a is None else a.ledger

    # -- cross-shard conflict unwind ------------------------------------------

    def _on_bind_error(self, pod, node_name: str, err: Exception) -> None:
        m = self.scheduler.metrics
        lost = False
        if isinstance(err, FencedWrite):
            # the server PROVED our generation stale: the lease moved,
            # so ownership is gone whatever the elector still believes
            # (a zombie learns it was deposed from the fence, first)
            self.conflicts += 1
            m.cross_shard_conflicts.inc("fenced")
            self.refresh_map()
            lost = True
        elif isinstance(err, Conflict):
            # pod already assigned: a peer won the race — re-read the
            # map before deciding; our cached copy may predate the move
            self.conflicts += 1
            m.cross_shard_conflicts.inc("conflict")
            self.refresh_map()
        if self._chain_bind_error is not None:
            self._chain_bind_error(pod, node_name, err)
        if lost or not self._owns_pod(pod):
            # the unwind requeued it; a peer's pod re-parks instead
            fresh = pod.with_node_name("")
            self.scheduler.queue.delete(fresh)
            self.scheduler._shard_parked[fresh.uid] = fresh
            self.scheduler._journey_park(
                [fresh], detail="fence unwind" if lost
                else "lost ownership")

    # -- serving --------------------------------------------------------------

    def debug(self) -> dict:
        return {"identity": self.identity,
                "held": list(self.held()),
                "queued": len(self.scheduler.queue),
                "parked": len(self.scheduler._shard_parked),
                "crossShardConflicts": self.conflicts,
                "fencedRejected": self.scheduler.dispatcher.fenced,
                "ledgerCursors": dict(self.cursors)}


class ShardManager:
    """The shard topology lifecycle over a fleet of ShardSchedulers:
    split (1→N), merge (N→1), steal/rebalance (lease handoff), all built
    on ONE primitive — `transfer()` — whose ordering IS the correctness
    argument:

      1. predecessor's audit-chain position is captured;
      2. predecessor releases (cooperative) or is force-cleared (steal)
         and parks its queued slice (`shard_evict` drains in-flight
         work first, so no assume ever leaks);
      3. successor acquires → the generation BUMPS → every write the
         predecessor still has in flight for this shard is fenced;
      4. successor annexes the predecessor's chain position
         (`record_handoff`) and adopts the parked slice warm.

    A predecessor killed mid-flush skips step 2 — and that is fine: step
    3 fences its stragglers and its unbound pods are still in the store
    for the successor's watch-parked copy to adopt."""

    def __init__(self, client, instances=None,
                 clock: Optional[Callable[[], float]] = None,
                 metrics=None):
        self.client = client
        self.instances: list[ShardScheduler] = list(instances or [])
        ref = self.instances[0] if self.instances else None
        self.clock = clock if clock is not None else (
            ref.clock if ref is not None else _time.monotonic)
        self.metrics = metrics if metrics is not None else (
            ref.scheduler.metrics if ref is not None else None)
        self.splits = 0
        self.merges = 0
        self.steals = 0
        # fleet observatory (ISSUE 19): telemetry federation + journey
        # stitching over the fleet, and (on demand) the incident
        # watchdog — all fed by the same member list. The
        # `FleetObservatory` gate (read off the reference instance's
        # config) switches the whole plane; off, the manager degrades
        # to the pre-19 per-instance surfaces.
        gates = (ref.scheduler.feature_gates if ref is not None else None)
        self.fleet = None
        self.stitcher = None
        self.watchdog = None
        if gates is None or gates.enabled("FleetObservatory"):
            from ..obs.federation import FleetAggregator
            from ..obs.stitch import JourneyStitcher
            self.fleet = FleetAggregator(self.instances)
            self.stitcher = JourneyStitcher(self.instances)
            # incidentDir in the reference config arms forensics at
            # construction; attach_watchdog() still works for ad-hoc use
            incident_dir = getattr(
                getattr(ref.scheduler, "config", None) if ref is not None
                else None, "incident_dir", "")
            if incident_dir and (gates is None
                                 or gates.enabled("IncidentForensics")):
                self.attach_watchdog(dirpath=incident_dir)

    def attach_watchdog(self, dirpath: str = "", **kwargs):
        """Arm incident forensics: the watchdog polls the federated
        signals at each tick_all and captures evidence bundles to
        `dirpath` (kubernetes_tpu/obs/incident.py). No-op (returns
        None) when the fleet observatory or the `IncidentForensics`
        gate is off."""
        if self.fleet is None:
            return None
        ref = self.instances[0] if self.instances else None
        if (ref is not None and not
                ref.scheduler.feature_gates.enabled("IncidentForensics")):
            return None
        from ..obs.incident import IncidentWatchdog
        self.watchdog = IncidentWatchdog(
            self.fleet, self.stitcher, dirpath=dirpath,
            clock=self.clock, metrics=self.metrics, manager=self,
            **kwargs)
        return self.watchdog

    # -- topology -------------------------------------------------------------

    def shard_map(self) -> ShardMap:
        return self.client.get_shard_map()

    def holder_of(self, sid: int) -> Optional[ShardScheduler]:
        lease = self.client.get_lease(shard_lease_name(sid))
        if lease is None or not lease.holder_identity:
            return None
        for inst in self.instances:
            if inst.identity == lease.holder_identity:
                return inst
        return None

    def _writer_fence(self):
        """A fence pair from any held shard lease in the fleet — topology
        CAS writes are fenced too. None only at bootstrap (no leases
        exist yet: unfenced, like any pre-HA write)."""
        for inst in self.instances:
            for sid, e in inst.electors.items():
                if e.is_leader():
                    return (shard_lease_name(sid), e.fence_token())
        return None

    def set_topology(self, num_shards: int,
                     assignments: Optional[dict] = None) -> ShardMap:
        """Fenced CAS of the shard map; every instance re-reads it."""
        m = self.client.get_shard_map()
        new = ShardMap(num_shards=num_shards,
                       assignments=dict(m.assignments if assignments is None
                                        else assignments))
        out = self.client.put_shard_map(new, expect_version=m.version,
                                        fence_token=self._writer_fence())
        for inst in self.instances:
            inst.refresh_map()
        self._observe_assignments(out)
        return out

    # -- the handoff primitive ------------------------------------------------

    def transfer(self, sid: int, dst: ShardScheduler,
                 reason: str = "rebalance", force: bool = False) -> float:
        """Move shard `sid`'s lease (and its warm queue slice) to `dst`.
        `force=True` clears a non-cooperating holder's lease (the steal
        path: the holder may be mid-drain or dead). Returns the handoff
        wall seconds (also observed as shard_rebalance_seconds)."""
        t0 = _time.perf_counter()
        name = shard_lease_name(sid)
        src = self.holder_of(sid)
        if src is dst and dst.holds(sid):
            return 0.0
        head: Optional[str] = None
        seq = 0
        if src is not None:
            led = src.audit_ledger()
            if led is not None:
                head, seq = led.head_hash(), led.cursor()
            e = src.electors.get(sid)
            if not force and e is not None:
                e.release()
                # cooperative handoff: park the slice (drains in-flight
                # work first, so no assume ever leaks)
                src.rebalance()
            else:
                # steal path: the holder may be mid-drain or DEAD — do
                # not touch its internals, just clear the (possibly
                # unexpired) lease by fiat. The generation bump below
                # fences its stragglers, and the successor adopts from
                # its own watch-parked copies of the slice.
                lease = self.client.get_lease(name)
                if lease is not None:
                    self.client.release_lease(name, lease.holder_identity)
        # holder change → generation bump: THE fence on src's stragglers
        self.client.acquire_lease(name, dst.identity, self.clock(),
                                  lease_duration_s=dst.lease_duration_s)
        e = dst.elector_for(sid)
        e.tick()    # observes the held lease, caches the new generation
        if head is not None and src is not None:
            led = dst.audit_ledger()
            if led is not None and led is not src.audit_ledger():
                led.record_handoff(sid, head, seq)
        # the handoff is a first-class journey transition on the
        # successor: every watch-parked pod of the moving shard gets a
        # steal/transfer mark BEFORE adopt re-enqueues it, so the
        # stitched cross-shard timeline names the handoff that moved it
        moved = [p.uid for p in dst.scheduler._shard_parked.values()
                 if dst._shard_of(p) == sid]
        dst.scheduler.journey.record_bulk(
            moved, _EV_STEAL if reason == "steal" else _EV_TRANSFER,
            dst.clock(),
            detail=f"shard {sid}: "
                   f"{src.identity if src is not None else '?'}"
                   f" -> {dst.identity} ({reason})")
        dst.rebalance()
        dt = _time.perf_counter() - t0
        if self.metrics is not None:
            self.metrics.shard_rebalance.observe(dt)
            self.metrics.shard_steals.inc(reason)
        self._observe_assignments()
        return dt

    # -- lifecycle verbs ------------------------------------------------------

    def split(self, num_shards: int, owners: dict,
              assignments: Optional[dict] = None) -> None:
        """1→N (or N→M): CAS the topology, then hand each shard in
        `owners` (sid → instance) to its designated owner. Instances not
        named keep warming the whole stream parked."""
        self.set_topology(num_shards, assignments=assignments)
        for sid in sorted(owners):
            self.transfer(sid, owners[sid], reason="split")
        for inst in self.instances:
            inst.rebalance()
        self.splits += 1

    def merge(self, dst: ShardScheduler) -> None:
        """N→1 ownership collapse: dst takes every shard lease (the key
        space keeps its shape — collapse it too with set_topology(1))."""
        m = self.client.get_shard_map()
        for sid in range(m.num_shards):
            self.transfer(sid, dst, reason="merge")
        for inst in self.instances:
            inst.rebalance()
        self.merges += 1

    def steal(self, sid: int, dst: ShardScheduler,
              force: bool = True) -> float:
        """Peer takes a (possibly loaded, possibly dead) shard mid-drain."""
        dt = self.transfer(sid, dst, reason="steal", force=force)
        self.steals += 1
        return dt

    # -- fleet plumbing -------------------------------------------------------

    def tick_all(self) -> None:
        for inst in self.instances:
            inst.tick()
        if self.watchdog is not None:
            self.watchdog.check()

    def sync_all(self) -> int:
        return sum(inst.sync() for inst in self.instances)

    def wire_ledgers(self) -> None:
        """In-process dual-stream wiring: every instance tails every
        peer's drain ledger (deployment would stream these; the seam is
        the same DrainLedger.tail the PR-11 standby consumes)."""
        for a in self.instances:
            a.peer_ledgers = {}
            for b in self.instances:
                if b is a:
                    continue
                led = b.audit_ledger()
                if led is not None:
                    a.peer_ledgers[b.identity] = led

    def _observe_assignments(self, m: Optional[ShardMap] = None) -> None:
        if self.metrics is None:
            return
        m = m if m is not None else self.client.get_shard_map()
        counts = {sid: 0 for sid in range(m.num_shards)}
        for _key, sid in m.assignments.items():
            if 0 <= sid < m.num_shards:
                counts[sid] += 1
        for sid, c in counts.items():
            self.metrics.shard_assignments.set(float(c), str(sid))

    # -- serving --------------------------------------------------------------

    def debug(self) -> dict:
        """/debug/shards payload."""
        m = self.client.get_shard_map()
        leases = {}
        for sid in range(m.num_shards):
            lease = self.client.get_lease(shard_lease_name(sid))
            leases[str(sid)] = None if lease is None else {
                "holder": lease.holder_identity,
                "generation": lease.generation,
                "transitions": lease.lease_transitions,
                "renewTime": lease.renew_time,
            }
        return {"numShards": m.num_shards,
                "mapVersion": m.version,
                "assignments": dict(m.assignments),
                "mapHistory": len(getattr(self.client,
                                          "shard_map_history", ())),
                "leases": leases,
                "splits": self.splits, "merges": self.merges,
                "steals": self.steals,
                "incidents": (None if self.watchdog is None
                              else self.watchdog.debug()),
                "instances": [inst.debug() for inst in self.instances]}
