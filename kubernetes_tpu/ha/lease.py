"""Lease-based leader election (client-go tools/leaderelection).

`LeaseLock` is the resourcelock.LeaseLock analog: it speaks the API
server's lease verbs (acquire/renew/release with holderIdentity,
leaseDurationSeconds and a renewTime deadline) and falls back to direct
Lease-store manipulation for clients that predate the verbs, so foreign
stub clients in tests keep working.

`LeaderElector` is the leaderelection.LeaderElector loop
(leaderelection.go:245-282), reduced to the framework's tick-driven
model: callers invoke `tick()` from their own control loop (the
reference loops on RetryPeriod); each tick is one acquire-or-renew
round. The elector implements:

- `OnStartedLeading`/`OnStoppedLeading` callbacks on every transition;
- the deposed-leader slow path (leaderelection.go:278: RenewDeadline <
  LeaseDuration): when renews keep failing transiently, the leader
  steps down at the renew deadline — BEFORE its lease expires — so the
  next leader can never overlap with a half-dead one;
- jittered acquire retry through the dispatcher's `backoff_delay`
  (wait.JitterUntil): a non-leader that just lost an acquire race backs
  off instead of hammering the lease on every tick;
- the fencing token: the lease `generation` is captured at acquire time
  and handed to `ha.fencing` — a deposed leader keeps its STALE cached
  generation, so writes it flushes late are rejected server-side even
  if it has not yet noticed it lost the lease.
"""

from __future__ import annotations

import random
import time as _time
from typing import Callable, Optional

from ..backend.apiserver import (APIError, Conflict, LEASE_NAME, Lease,
                                 NotFound)
from ..backend.dispatcher import backoff_delay


class LeaseLock:
    """coordination.k8s.io Lease lock over the shared API server."""

    def __init__(self, client, identity: str, name: str = LEASE_NAME,
                 lease_duration_s: float = 15.0):
        self.client = client
        self.identity = identity
        self.name = name
        self.lease_duration_s = lease_duration_s

    # -- store access ---------------------------------------------------------

    def _store(self) -> dict:
        """Fallback Lease store for clients without lease verbs."""
        leases = getattr(self.client, "leases", None)
        if leases is None:
            leases = self.client.leases = {}
        return leases

    def get(self) -> Optional[Lease]:
        if hasattr(self.client, "get_lease"):
            return self.client.get_lease(self.name)
        return self._store().get(self.name)

    def acquire_or_renew(self, now: float) -> Lease:
        """One acquire-or-renew attempt; raises Conflict when the lease
        is held (unexpired) by another identity."""
        if hasattr(self.client, "acquire_lease"):
            return self.client.acquire_lease(
                self.name, self.identity, now,
                lease_duration_s=self.lease_duration_s)
        # fallback mirror of APIServer.acquire_lease for foreign clients
        lease = self._store().setdefault(self.name, Lease(
            name=self.name, lease_duration_s=self.lease_duration_s))
        if lease.holder_identity == self.identity:
            lease.renew_time = now
            return lease
        expired = (not lease.holder_identity
                   or now - lease.renew_time > lease.lease_duration_s)
        if not expired:
            raise Conflict(
                f"lease {self.name!r} is held by {lease.holder_identity!r}")
        if lease.holder_identity:
            lease.lease_transitions += 1
        lease.holder_identity = self.identity
        lease.lease_duration_s = self.lease_duration_s
        lease.renew_time = now
        lease.generation += 1
        return lease

    def release(self) -> None:
        if hasattr(self.client, "release_lease"):
            self.client.release_lease(self.name, self.identity)
            return
        lease = self._store().get(self.name)
        if lease is None or lease.holder_identity != self.identity:
            return
        lease.holder_identity = ""
        lease.renew_time = 0.0


class LeaderElector:
    """client-go leaderelection.LeaderElector (tools/leaderelection):
    acquire/renew/release against a shared Lease store."""

    def __init__(self, client, identity: str,
                 lease_duration_s: float = 15.0,
                 renew_deadline_s: Optional[float] = None,
                 retry_period_s: float = 2.0,
                 clock: Callable[[], float] = _time.monotonic,
                 on_started_leading: Optional[Callable[[], None]] = None,
                 on_stopped_leading: Optional[Callable[[], None]] = None,
                 metrics=None,
                 rng: Optional[random.Random] = None,
                 lease_name: str = LEASE_NAME):
        self.client = client
        self.identity = identity
        self.lease_name = lease_name
        self.lease_duration_s = lease_duration_s
        # reference defaults: LeaseDuration 15s / RenewDeadline 10s /
        # RetryPeriod 2s — keep the 2:3 ratio for custom durations
        self.renew_deadline_s = (renew_deadline_s if renew_deadline_s
                                 is not None else lease_duration_s * (2 / 3))
        self.retry_period_s = retry_period_s
        self.clock = clock
        self.on_started_leading = on_started_leading
        self.on_stopped_leading = on_stopped_leading
        self.metrics = metrics
        self.lock = LeaseLock(client, identity, name=lease_name,
                              lease_duration_s=lease_duration_s)
        self._leading = False
        self._last_renew = 0.0      # last SUCCESSFUL acquire/renew
        self._attempt = 0           # consecutive failed acquire attempts
        self._next_acquire = 0.0    # backoff gate for non-leader attempts
        self._generation: Optional[int] = None  # cached at acquire time
        self._rng = rng if rng is not None else random.Random(
            hash(identity) & 0xFFFF)

    # -- state ----------------------------------------------------------------

    def is_leader(self) -> bool:
        return self._leading

    def fence_token(self) -> Optional[int]:
        """The lease generation cached at acquire time. Deliberately NOT
        re-read from the store: a deposed leader that has not ticked yet
        must keep stamping its STALE generation so its late flushes are
        fenced. None only before the first acquire (unfenced legacy)."""
        return self._generation

    # -- transitions ----------------------------------------------------------

    def _transition(self, reason: str) -> None:
        if self.metrics is not None:
            self.metrics.leader_transitions.inc(reason)

    def _start_leading(self, lease: Lease, now: float) -> None:
        self._leading = True
        self._last_renew = now
        self._attempt = 0
        self._generation = lease.generation
        self._transition("acquired")
        if self.on_started_leading:
            self.on_started_leading()

    def _stop_leading(self, reason: str) -> None:
        self._leading = False
        self._transition(reason)
        if self.on_stopped_leading:
            self.on_stopped_leading()

    # -- the loop body --------------------------------------------------------

    def tick(self) -> bool:
        """One acquire-or-renew round; returns leadership after the round.
        The reference loops this on RetryPeriod; callers here invoke it
        from their own control loop."""
        now = self.clock()
        if not self._leading and now < self._next_acquire:
            # acquire backoff (wait.JitterUntil): lost a race recently
            return False
        try:
            lease = self.lock.acquire_or_renew(now)
        except Conflict:
            # held, unexpired, by someone else
            if self._leading:
                # our lease expired and another elector claimed it
                self._stop_leading("lost")
            self._next_acquire = now + backoff_delay(
                self._attempt, self.retry_period_s,
                self.lease_duration_s, self._rng)
            self._attempt += 1
            return False
        except (NotFound, APIError):
            # transient verb failure (chaos: renew latency spikes,
            # expired-lease storms). A non-leader just retries later; a
            # leader holds on until the renew DEADLINE, then steps down
            # — before the lease itself expires — so a successor can
            # never overlap with a leader that still thinks it renews.
            if self._leading:
                if now - self._last_renew >= self.renew_deadline_s:
                    self._stop_leading("renew_deadline")
                    return False
                return True
            self._next_acquire = now + backoff_delay(
                self._attempt, self.retry_period_s,
                self.lease_duration_s, self._rng)
            self._attempt += 1
            return False
        self._last_renew = now
        self._attempt = 0
        if not self._leading:
            # covers both fresh acquire and an elector re-created after
            # restart while its lease is still valid: it IS the holder
            self._start_leading(lease, now)
        else:
            self._generation = lease.generation
        return True

    def release(self) -> None:
        """Voluntary handoff (LeaderElector release on cancel): clear the
        lease so the next candidate acquires immediately."""
        self.lock.release()
        if self._leading:
            self._stop_leading("released")
