"""Ledger-warmed hot spare: a standby Scheduler that takes over warm.

The reference's passive replicas stand fully cold: a standby
kube-scheduler that wins the lease starts from an empty informer cache
and pays a full LIST before its first scheduling cycle. This framework
can do better because the expensive state is REBUILDABLE FROM STREAMS it
can subscribe to while passive:

- the watch stream keeps the standby's cache/queue/PodTable current (its
  inner Scheduler registers the normal informer handlers; bind echoes
  from the active leader land through `_on_pod_update[_bulk]` and
  `confirm_bound` exactly like the leader's own echoes would);
- the drain-ledger tail (obs/audit.py DrainLedger.tail) streams the
  leader's committed drains, giving the standby a lag signal
  (`ha_ledger_tail_lag_drains`), the chain head for handoff continuity,
  and the sync cadence for refreshing its DEVICE state: each `sync()`
  re-tensorizes the snapshot and touches `ensure_arrays()` inside
  SanitizerRails transfer windows, so node arrays stay current and every
  kernel's JIT cache is populated BEFORE takeover ever happens.

Takeover (`OnStartedLeading`) is then cheap: drain the remaining ledger
tail, splice this instance's (empty) audit ledger onto the dead leader's
chain head so the hash chain verifies across the handoff, run `resync()`
— which rides the columnar ingest bulk paths against an already-warm
device tier, reconciling only the delta since the last record and
re-enqueueing the dead leader's uncommitted drains (their binds never
committed, so they are still unbound in the store) — and `promote()`.
`ha_failover_seconds` records the cost; the `failover` SLI burns budget
when it exceeds the objective.

The `ActiveStandbyHA` gate governs the fencing + warm-spare wiring; with
the gate off the elector still works (single-instance back-compat) but
writes go unfenced and takeover degrades to a cold start.
"""

from __future__ import annotations

import time as _time
from typing import Callable, Optional

from ..scheduler import Scheduler
from .fencing import fence_dispatcher
from .lease import LeaderElector


class StandbyScheduler:
    """One standby instance: inner Scheduler (role "standby") + elector
    + ledger-tail subscription. Call `tick()` from the control loop (it
    runs the election round; takeover fires via OnStartedLeading) and
    `sync()` on whatever cadence the deployment wants its spare warmed."""

    def __init__(self, client, identity: str = "scheduler-standby",
                 ledger=None,
                 lease_duration_s: float = 15.0,
                 clock: Optional[Callable[[], float]] = None,
                 scheduler: Optional[Scheduler] = None,
                 **scheduler_kwargs):
        """`ledger` is the active leader's DrainLedger (the streamed
        export; in-process the subscription is direct). None = no ledger
        feed: the standby still warms from the watch stream alone."""
        self.scheduler = (scheduler if scheduler is not None
                          else Scheduler(client, **scheduler_kwargs))
        self.enabled = self.scheduler.feature_gates.enabled(
            "ActiveStandbyHA")
        self.scheduler.ha_role = "standby"
        # federation provenance (obs/federation.py): the standby reports
        # under its own shard label with role="standby" — its mirrored
        # series stay visible but are EXCLUDED from the cluster SLO burn
        self.scheduler.journey.instance = identity
        self.ledger = ledger
        self.cursor = 0              # last consumed ledger seq
        self.last_hash = ""          # hash of the last consumed record
        self.drains_seen = 0
        self.takeovers = 0
        self.failover_s: Optional[float] = None
        self.elector = LeaderElector(
            client, identity,
            lease_duration_s=lease_duration_s,
            clock=clock if clock is not None else self.scheduler.clock,
            metrics=self.scheduler.metrics,
            on_started_leading=self._on_started_leading,
            on_stopped_leading=self._on_stopped_leading)
        if self.enabled:
            fence_dispatcher(self.scheduler.dispatcher, self.elector)

    # -- election -------------------------------------------------------------

    def tick(self) -> bool:
        """One election round; a win runs takeover via the callback."""
        return self.elector.tick()

    def _on_started_leading(self) -> None:
        self.takeover()

    def _on_stopped_leading(self) -> None:
        self.scheduler.demote()

    # -- warm sync ------------------------------------------------------------

    def sync(self, refresh: bool = True) -> int:
        """Consume the ledger tail + (optionally) refresh device state.
        Returns the number of drain records consumed. The refresh is the
        point of the hot spare: snapshot → tensorize → ensure_arrays
        keeps node arrays current and mints every kernel's JIT entry
        while passive, so takeover pays neither."""
        if not self.enabled:
            return 0    # gate off: no ledger tail, no device pre-warm —
            #             takeover degrades to the pre-HA cold resync
        consumed = 0
        sched = self.scheduler
        if self.ledger is not None:
            sched.metrics.ha_ledger_tail_lag.set(
                float(self.ledger.lag(self.cursor)))
            for rec in self.ledger.tail(self.cursor):
                self.cursor = rec.seq
                self.last_hash = rec.hash
                consumed += 1
            self.drains_seen += consumed
        if refresh:
            # the same staged phases the leader's drain loop declares, so
            # the transfer-guard discipline holds on the standby too.
            # The ingest lock covers the FULL rebuild: a watch event
            # mid-re-tensorize would mutate cache/snapshot between
            # update_snapshot and apply_snapshot, leaving the device
            # arrays out of step with the host snapshot they claim to be
            with sched.ingest_lock:
                with sched.rails.declared("host_snapshot"):
                    sched.cache.update_snapshot(sched.snapshot)
                with sched.rails.declared("host_tensorize"):
                    sched.state.apply_snapshot(sched.snapshot)
                    sched.state.ensure_arrays()
        return consumed

    # -- takeover -------------------------------------------------------------

    def takeover(self) -> float:
        """OnStartedLeading: final tail drain, chain splice, delta
        resync, promote. Returns (and records) the failover seconds."""
        sched = self.scheduler
        t0 = _time.perf_counter()
        self.sync(refresh=False)     # catch the tail; device state is
        #                              refreshed by resync() below anyway
        if self.enabled and self.ledger is not None \
                and sched.audit is not None:
            # continue the dead leader's hash chain: our first audited
            # drain links to its last, so verify() holds across handoff
            head = self.ledger.head_hash()
            try:
                sched.audit.ledger.splice(head, seq=self.ledger.cursor())
            except ValueError:
                pass    # this instance audited drains before (re-elect
                #         after a previous reign): its chain continues
        # delta reconcile: the watch stream kept cache/queue current and
        # sync() kept the device tier warm, so the LIST rebuild rides the
        # columnar bulk paths into already-compiled kernels — and
        # re-enqueues the dead leader's uncommitted drains (never bound,
        # so still unbound in the store)
        sched.resync()
        sched.promote()
        dt = _time.perf_counter() - t0
        self.failover_s = dt
        self.takeovers += 1
        sched.metrics.ha_failover.observe(dt)
        if sched.slo is not None:
            obj = sched.slo.objectives.get("failover")
            bad = 1 if (obj is not None and dt > obj.threshold_s) else 0
            sched.slo.observe("failover", good=1 - bad, bad=bad)
        return dt

    # -- serving --------------------------------------------------------------

    def debug(self) -> dict:
        """/debug/ha payload."""
        lease = self.elector.lock.get()
        return {
            "role": self.scheduler.ha_role,
            "gate": self.enabled,
            "identity": self.elector.identity,
            "leader": self.elector.is_leader(),
            "fenceToken": self.elector.fence_token(),
            "lease": None if lease is None else {
                "holder": lease.holder_identity,
                "durationSeconds": lease.lease_duration_s,
                "renewTime": lease.renew_time,
                "transitions": lease.lease_transitions,
                "generation": lease.generation,
            },
            "ledgerCursor": self.cursor,
            "ledgerLag": (self.ledger.lag(self.cursor)
                          if self.ledger is not None else None),
            "drainsSeen": self.drains_seen,
            "takeovers": self.takeovers,
            "failoverSeconds": self.failover_s,
            "fencedRejected": self.scheduler.dispatcher.fenced,
        }
