"""Pod-batch tensorization: compile a queue drain into device tensors.

KEP-5598 taken to its limit (reference runtime/batch.go + signers.go): pods
are interned by SIGNATURE — the canonical tuple of everything the device
kernels can see (requests, nodeName, tolerations, selectors, affinity,
ports). Each distinct signature fills ONE row of a compact PodTable; a drain
of B pods ships only `(valid[B], sig[B], tidx[B])` plus whatever table rows
are new. The scan gathers the row per step, and its signature cache makes
consecutive same-signature pods skip the heavy kernels entirely.

This matters twice over:
- host: `_fill_row`'s selector compilation runs once per signature, not per
  pod (a homogeneous 10k-pod benchmark fills exactly one row);
- transfer: the per-batch upload is O(unique signatures), not O(B·row-width),
  which is what keeps large drains from being PCIe/tunnel-bound.

Arbitrary label selectors compile to padded (term × requirement × value) id
tables evaluated against the node label arrays on device (SURVEY §7
hard-part 6). Pods whose constraints exceed the padding (or use semantics
with no tensor form yet) get `host_fallback=True` and are scheduled by the
host oracle instead — the analog of the reference disabling batching for
plugins without SignPlugin (runtime/framework.go:772-816).

Selector op encoding (0 = padding → vacuously true):
  1=In  2=NotIn  3=Exists  4=DoesNotExist  5=Gt  6=Lt
Toleration op: 1=Equal 2=Exists.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import NamedTuple, Optional

import numpy as np

from ..api import resources as res
from ..api.types import NodeSelectorTerm, Pod, SelectorOperator
from ..state.tensorize import _EFFECTS, ClusterState, pow2_at_least
from ..plugins.node_basics import NodeUnschedulable

OP_IN = 1
OP_NOT_IN = 2
OP_EXISTS = 3
OP_DOES_NOT_EXIST = 4
OP_GT = 5
OP_LT = 6

_SEL_OPS = {
    SelectorOperator.IN.value: OP_IN,
    SelectorOperator.NOT_IN.value: OP_NOT_IN,
    SelectorOperator.EXISTS.value: OP_EXISTS,
    SelectorOperator.DOES_NOT_EXIST.value: OP_DOES_NOT_EXIST,
    SelectorOperator.GT.value: OP_GT,
    SelectorOperator.LT.value: OP_LT,
}

TOL_EQUAL = 1
TOL_EXISTS = 2


@dataclass
class BatchDims:
    table_rows: int = 16   # U — distinct signatures (grows by doubling)
    # growth cap: reaching this many used rows triggers a between-builds
    # reset (compaction) instead of further doubling
    max_table_rows: int = 4096
    images_per_pod: int = 8  # IC — container images per pod
    sel_terms: int = 4     # T — required node affinity terms
    sel_reqs: int = 6      # Q — requirements per term (incl. nodeSelector merge)
    sel_vals: int = 8      # V — values per requirement
    pref_terms: int = 4    # PT — preferred node affinity terms
    tolerations: int = 8   # TT
    ports: int = 8         # P


class PodTable(NamedTuple):
    """One row per distinct pod signature ([U, ...])."""

    req: object              # i64 [U, R]
    nonzero_req: object      # i64 [U, 2]
    node_name_id: object     # i32 [U] (0 = unset)
    tol_key: object          # i32 [U, TT]
    tol_val: object          # i32 [U, TT]
    tol_eff: object          # i32 [U, TT] (0 = all effects)
    tol_op: object           # i32 [U, TT] (0 = padding)
    tolerates_unsched: object  # bool [U]
    ns_sel_val: object       # i32 [U, Q] (kv id; 0 = padding)
    aff_has: object          # bool [U]
    aff_term_valid: object   # bool [U, T]
    aff_key: object          # i32 [U, T, Q]
    aff_op: object           # i32 [U, T, Q]
    aff_num: object          # i64 [U, T, Q]
    aff_val: object          # i32 [U, T, Q, V]
    pref_weight: object      # i64 [U, PT] (0 = unused term)
    pref_key: object         # i32 [U, PT, Q]
    pref_op: object          # i32 [U, PT, Q]
    pref_num: object         # i64 [U, PT, Q]
    pref_val: object         # i32 [U, PT, Q, V]
    port_ids: object         # i32 [U, P]
    skip_balanced: object    # bool [U]
    img_ids: object          # i32 [U, IC] — interned container images (0 = pad)
    img_containers: object   # i32 [U] — container count (score threshold)


class PodBatch(NamedTuple):
    valid: object            # bool [B]
    host_fallback: object    # bool [B] (numpy only; never shipped to device)
    sig: object              # i32 [B] — signature id (0 = fast path ineligible)
    tidx: object             # i32 [B] — row in the table
    table: PodTable          # shared builder table (numpy)
    table_version: int       # bumps when rows are added/table rebuilt


class BatchCapacityError(ValueError):
    pass


class BatchBuilder:
    def __init__(self, state: ClusterState, dims: Optional[BatchDims] = None,
                 spread_plugin=None, ipa_plugin=None, group_dims=None):
        from ..ops.groups import GroupManager
        self.state = state
        self.dims = dims or BatchDims()
        # bumped whenever existing rows are INVALIDATED (reset), as opposed
        # to appended; device-side consumers must reseed their group state
        # and signature caches when this moves
        self.reset_count = 0
        # signature key → ("row", sig_id, tidx) | ("fallback", reason)
        self._sig_cache: dict[tuple, tuple] = {}
        # identity fast path: pods stamped from a shared template (the
        # reference's typical controller-replica shape) share their spec and
        # label dict OBJECTS; (id(spec), id(labels), ns) then implies an
        # identical signature without recomputing the content key. Values
        # hold strong refs to the keyed objects so ids can't be recycled.
        # Relies on the object-model aliasing contract (api/types.py): specs
        # and label dicts are immutable once a pod is created.
        self._ident_cache: dict[tuple, tuple] = {}
        self._next_sig = 1
        self.table = _zero_table(self.dims.table_rows,
                                 state.dims.resources, self.dims)
        self.table_used = 0
        self.table_version = 0
        # columnar pod store, commit-side column (ingest/columns.py): one
        # CommitFacts per interned row, aligned with table_used — the
        # batched assume path reads facts by tidx instead of re-walking
        # the pod object graph per commit. REPLACED (not cleared) on
        # reset: in-flight drains hold the old list by reference.
        self.row_facts: list = []
        self.groups = GroupManager(state, spread_plugin=spread_plugin,
                                   ipa_plugin=ipa_plugin, dims=group_dims,
                                   table_rows=self.dims.table_rows)

    # -- table lifecycle ------------------------------------------------------

    def _reset_table(self) -> None:
        self.reset_count += 1
        self._sig_cache.clear()
        self._ident_cache.clear()
        self.table = _zero_table(self.dims.table_rows,
                                 self.state.dims.resources, self.dims)
        self.table_used = 0
        self.table_version += 1
        self.row_facts = []
        self.groups.reset()

    def _grow_table(self) -> None:
        self.dims.table_rows *= 2
        old = self.table
        self.table = _zero_table(self.dims.table_rows,
                                 self.state.dims.resources, self.dims)
        for name in PodTable._fields:
            getattr(self.table, name)[: self.table_used] = getattr(old, name)[
                : self.table_used]
        self.table_version += 1
        self.groups.grow(self.dims.table_rows)

    # -- build ---------------------------------------------------------------

    def build(self, pods: list[Pod], snapshot=None,
              pad_to: int = 0) -> PodBatch:
        # pad to the caller's standing batch size when given: residual drains
        # then reuse the same compiled program instead of minting a new
        # (smaller) shape bucket
        B = pow2_at_least(max(len(pods), pad_to))
        if self.table_used >= self.dims.max_table_rows:
            # compaction happens BETWEEN builds only (a mid-build reset
            # would zero rows this batch already references): drop every
            # row; the signatures still in use re-intern immediately, dead
            # ones don't come back. Row capacity stays at its high-water
            # bucket, so memory is bounded by MAX_TABLE_ROWS growth.
            self._reset_table()
        if self.table.req.shape[1] != self.state.dims.resources:
            self._reset_table()  # resource table grew: row widths changed
        valid = np.zeros((B,), bool)
        fallback = np.zeros((B,), bool)
        sig = np.zeros((B,), np.int32)
        tidx = np.zeros((B,), np.int32)
        # Chunked interning (ingest/columns.py): ONE identity pass groups
        # the chunk's positions per table entry, new signatures intern
        # through the columnar row filler in first-appearance order (the
        # order mints sig ids — parity with the per-pod path), and the
        # per-pod scalar array stores collapse to one gather/scatter per
        # distinct entry. A homogeneous drain does 3 vector writes total.
        groups: dict = {}
        misses: dict = {}            # content key → (ident, pod, placeholder)
        ident_cache = self._ident_cache
        for i, pod in enumerate(pods):
            ident = (id(pod.spec), id(pod.metadata.labels),
                     pod.metadata.namespace)
            hit = ident_cache.get(ident)
            if hit is not None:
                ent = hit[2]
            else:
                ent = self._intern_key(pod, ident, misses)
            lst = groups.get(ent)
            if lst is None:
                groups[ent] = [i]
            else:
                lst.append(i)
        if misses:
            self._intern_misses(misses, groups)
        last = -1
        for ent, idxs in groups.items():
            if ent[0] == "fallback":
                fallback[idxs] = True
                continue
            valid[idxs] = True
            sig[idxs] = ent[1]
            tidx[idxs] = ent[2]
            if idxs[-1] > last:
                last = idxs[-1]
        if last >= 0 and len(pods) < B:
            # padding rows inherit the last real pod's signature: valid=False
            # keeps them unassigned while the scan's cached fast step makes
            # them near-free instead of running the full kernel set per row
            sig[len(pods):] = sig[last]
            tidx[len(pods):] = tidx[last]
        return PodBatch(valid=valid, host_fallback=fallback, sig=sig,
                        tidx=tidx, table=self.table,
                        table_version=self.table_version)

    def _intern_key(self, pod: Pod, ident: tuple, misses: dict) -> tuple:
        """Identity-miss path of the chunked build: resolve via the
        content key, deferring genuinely NEW signatures to the columnar
        chunk filler. Returns the entry when known, else a per-key
        placeholder entry that `_intern_misses` resolves in place.
        `misses` maps content key → (ident, pod, placeholder) in
        first-appearance order (dicts preserve insertion order)."""
        key = self._sig_key(pod)
        ent = self._sig_cache.get(key)
        if ent is None:
            pending = misses.get(key)
            if pending is not None:
                return pending[2]
            ent = ("miss", len(misses))
            misses[key] = (ident, pod, ent)
            return ent
        if len(self._ident_cache) < 65536:
            self._ident_cache[ident] = (pod.spec, pod.metadata.labels, ent)
        return ent

    def _intern_misses(self, misses: dict, groups: dict) -> None:
        """Resolve the chunk's new signatures through the columnar filler
        (ingest/columns.py fill_rows) and rewrite the placeholder group
        keys to the real entries."""
        from ..ingest.columns import fill_rows
        items = list(misses.items())
        ents = fill_rows(self, [pod for _key, (_i, pod, _e) in items])
        for (key, (ident, pod, placeholder)), ent in zip(items, ents):
            self._sig_cache[key] = ent
            if len(self._ident_cache) < 65536:
                self._ident_cache[ident] = (pod.spec, pod.metadata.labels,
                                            ent)
            idxs = groups.pop(placeholder)
            have = groups.get(ent)
            if have is None:
                groups[ent] = idxs
            else:
                # two content keys can map to one fallback entry string;
                # merge position lists preserving drain order
                have.extend(idxs)
                have.sort()

    def _lookup(self, pod: Pod) -> tuple:
        ident = (id(pod.spec), id(pod.metadata.labels),
                 pod.metadata.namespace)
        hit = self._ident_cache.get(ident)
        if hit is not None:
            return hit[2]
        key = self._sig_key(pod)
        ent = self._sig_cache.get(key)
        if ent is not None:
            if len(self._ident_cache) < 65536:
                self._ident_cache[ident] = (pod.spec, pod.metadata.labels,
                                            ent)
            return ent
        if self.table_used >= self.table.req.shape[0]:
            self._grow_table()
        u = self.table_used
        try:
            self._fill_row(self.table, u, pod)
            self.groups.add_row(u, pod)
        except BatchCapacityError as e:
            for name in PodTable._fields:
                getattr(self.table, name)[u] = 0
            ent = ("fallback", str(e))
        else:
            # host-port pods get signature 0: their feasibility depends on
            # the evolving port carry, which the cached fast step does not
            # refresh — they still share a table row
            sig_id = 0 if self.table.port_ids[u].any() else self._next_sig
            if sig_id:
                self._next_sig += 1
            self.table_used += 1
            self.table_version += 1
            from ..ingest.columns import commit_facts_for_row
            self.row_facts.append(commit_facts_for_row(pod))
            ent = ("row", sig_id, u)
        self._sig_cache[key] = ent
        if len(self._ident_cache) < 65536:
            self._ident_cache[ident] = (pod.spec, pod.metadata.labels, ent)
        return ent

    # -- signature (signers.go analog, content-level) -------------------------

    @staticmethod
    def _sig_key(pod: Pod) -> tuple:
        """Canonical content key. Namespace + labels are part of it because
        spread/affinity matching is SYMMETRIC: a pod's labels determine how
        it feeds other pods' selectors (signers.go includes labels for the
        same reason).

        Cardinality caveat: per-pod-unique labels (statefulset pod-name,
        controller hashes) mint one row each, and every new row costs O(U)
        host selector matching plus a possible table doubling (carry
        reseed). A conditional key (labels only when groups are active) is
        NOT safe — rows persist across the groups on/off transition — so
        high-churn unique-label workloads should bound table growth
        instead; see PodTable growth handling."""
        spec = pod.spec
        aff = spec.affinity
        na = aff.node_affinity if aff else None
        return (
            pod.namespace,
            tuple(sorted(pod.metadata.labels.items())),
            tuple(sorted(res.pod_requests(pod).items())),
            res.pod_requests_nonzero(pod),
            spec.node_name,
            tuple((t.key, t.operator, t.value, t.effect)
                  for t in spec.tolerations),
            tuple(sorted(spec.node_selector.items())),
            _node_affinity_key(na),
            tuple(sorted((p.protocol or "TCP", p.host_port, p.host_ip)
                         for c in spec.containers for p in c.ports
                         if p.host_port > 0)),
            tuple(spec.topology_spread_constraints),
            (aff.pod_affinity, aff.pod_anti_affinity) if aff else None,
            tuple(c.image for c in (list(spec.init_containers)
                                    + list(spec.containers))),
            tuple((v.name, v.claim_name, v.csi_driver)
                  for v in spec.volumes),
            spec.required_node_features,
            spec.resource_claims,
        )

    # -- row compilation ------------------------------------------------------

    def _fill_row(self, b: PodTable, i: int, pod: Pod) -> None:
        d = self.dims
        intr = self.state.interner
        aff = pod.spec.affinity
        if pod.spec.volumes:
            # the PVC/PV binding state machine is API-coupled (SURVEY §2.4
            # volumebinding): volume-bearing pods keep host semantics
            raise BatchCapacityError("pod has volumes")
        if pod.spec.required_node_features:
            raise BatchCapacityError("pod requires declared node features")
        if pod.spec.resource_claims:
            # DRA claims are an API-coupled allocation state machine
            # (plugins/dynamicresources.py): host path, like volumes
            raise BatchCapacityError("pod has resource claims")
        # resources
        reqs = res.pod_requests(pod)
        row = self.state.rtable.vector(reqs)
        if len(row) > b.req.shape[1]:
            raise BatchCapacityError("resource table grew past batch width")
        b.req[i, :len(row)] = row
        nz_cpu, nz_mem = res.pod_requests_nonzero(pod)
        b.nonzero_req[i, 0] = nz_cpu
        b.nonzero_req[i, 1] = nz_mem
        b.skip_balanced[i] = all(v == 0 for v in reqs.values())
        # nodeName
        if pod.spec.node_name:
            b.node_name_id[i] = self.state.node_id(pod.spec.node_name)
        # tolerations
        tols = pod.spec.tolerations
        if len(tols) > d.tolerations:
            raise BatchCapacityError("too many tolerations")
        for t, tol in enumerate(tols):
            b.tol_key[i, t] = intr.key.intern(tol.key) if tol.key else 0
            b.tol_val[i, t] = intr.kv.intern(f"tv:{tol.value}")
            b.tol_eff[i, t] = _EFFECTS.get(tol.effect, 0) if tol.effect else 0
            op = tol.operator or "Equal"
            b.tol_op[i, t] = TOL_EXISTS if op == "Exists" else TOL_EQUAL
        b.tolerates_unsched[i] = any(
            t.tolerates(NodeUnschedulable.TAINT) for t in tols)
        # nodeSelector → equality conjuncts
        sel = pod.spec.node_selector
        if len(sel) > d.sel_reqs:
            raise BatchCapacityError("nodeSelector too wide")
        for q, (k, v) in enumerate(sorted(sel.items())):
            b.ns_sel_val[i, q] = intr.label_kv(k, v)
        # required node affinity
        na = aff.node_affinity if aff else None
        if na and na.required is not None:
            terms = na.required.terms
            if len(terms) > d.sel_terms:
                raise BatchCapacityError("too many nodeAffinity terms")
            b.aff_has[i] = True
            for t, term in enumerate(terms):
                b.aff_term_valid[i, t] = True
                self._fill_term(term, b.aff_key[i, t], b.aff_op[i, t],
                                b.aff_num[i, t], b.aff_val[i, t])
        # preferred node affinity
        if na and na.preferred:
            prefs = na.preferred
            if len(prefs) > d.pref_terms:
                raise BatchCapacityError("too many preferred terms")
            for t, p in enumerate(prefs):
                if p.weight == 0:
                    continue
                b.pref_weight[i, t] = p.weight
                self._fill_term(p.preference, b.pref_key[i, t], b.pref_op[i, t],
                                b.pref_num[i, t], b.pref_val[i, t])
        # ports
        ports = [(p.protocol or "TCP", p.host_port, p.host_ip)
                 for c in pod.spec.containers for p in c.ports if p.host_port > 0]
        if any(ip not in ("", "0.0.0.0") for (_, _, ip) in ports):
            # host-IP-scoped ports keep reference semantics via host path
            raise BatchCapacityError("host-IP-scoped port")
        if len(ports) > d.ports:
            raise BatchCapacityError("too many host ports")
        for q, (proto, port, _ip) in enumerate(ports):
            b.port_ids[i, q] = intr.port_id(proto, port)
        # container images (ImageLocality device kernel; init containers
        # score too, image_locality.go:95)
        from ..plugins.imagelocality import normalized_image_name
        containers = (list(pod.spec.init_containers)
                      + list(pod.spec.containers))
        imgs = [normalized_image_name(c.image) for c in containers if c.image]
        if imgs and len(imgs) > d.images_per_pod:
            raise BatchCapacityError("too many container images")
        b.img_containers[i] = len(containers) if imgs else 0
        for q, img in enumerate(imgs):
            b.img_ids[i, q] = intr.image.intern(img)

    def _fill_term(self, term: NodeSelectorTerm, key_row, op_row, num_row, val_row) -> None:
        d = self.dims
        intr = self.state.interner
        reqs = list(term.match_expressions)
        # matchFields (metadata.name) compile to ordinary requirements against
        # the synthetic metadata.name label (tensorize.py)
        for f in term.match_fields:
            reqs.append(f)
        if len(reqs) > d.sel_reqs:
            raise BatchCapacityError("too many requirements in term")
        for q, r in enumerate(reqs):
            opc = _SEL_OPS.get(r.operator)
            if opc is None:
                raise BatchCapacityError(f"unsupported operator {r.operator}")
            if r.key == "metadata.name":
                key = intr.key.intern("metadata.name")
            else:
                key = intr.key.intern(r.key)
            key_row[q] = key
            op_row[q] = opc
            if opc in (OP_IN, OP_NOT_IN):
                if len(r.values) > d.sel_vals:
                    raise BatchCapacityError("too many values in requirement")
                for v, value in enumerate(r.values):
                    val_row[q, v] = intr.label_kv(r.key, value)
            elif opc in (OP_GT, OP_LT):
                if len(r.values) != 1:
                    raise BatchCapacityError("Gt/Lt needs exactly one value")
                try:
                    num_row[q] = int(r.values[0])
                except ValueError:
                    raise BatchCapacityError("non-integer Gt/Lt value")


def _node_affinity_key(na) -> Optional[tuple]:
    if na is None:
        return None

    def term_key(term):
        return (tuple((r.key, r.operator, tuple(r.values))
                      for r in term.match_expressions),
                tuple((f.key, f.operator, tuple(f.values))
                      for f in term.match_fields))

    required = None
    if na.required is not None:
        required = tuple(term_key(t) for t in na.required.terms)
    preferred = tuple((p.weight, term_key(p.preference))
                      for p in (na.preferred or ()))
    return (required, preferred)


def _zero_table(U: int, R: int, d: BatchDims) -> PodTable:
    return PodTable(
        req=np.zeros((U, R), np.int64),
        nonzero_req=np.zeros((U, 2), np.int64),
        node_name_id=np.zeros((U,), np.int32),
        tol_key=np.zeros((U, d.tolerations), np.int32),
        tol_val=np.zeros((U, d.tolerations), np.int32),
        tol_eff=np.zeros((U, d.tolerations), np.int32),
        tol_op=np.zeros((U, d.tolerations), np.int32),
        tolerates_unsched=np.zeros((U,), bool),
        ns_sel_val=np.zeros((U, d.sel_reqs), np.int32),
        aff_has=np.zeros((U,), bool),
        aff_term_valid=np.zeros((U, d.sel_terms), bool),
        aff_key=np.zeros((U, d.sel_terms, d.sel_reqs), np.int32),
        aff_op=np.zeros((U, d.sel_terms, d.sel_reqs), np.int32),
        aff_num=np.zeros((U, d.sel_terms, d.sel_reqs), np.int64),
        aff_val=np.zeros((U, d.sel_terms, d.sel_reqs, d.sel_vals), np.int32),
        pref_weight=np.zeros((U, d.pref_terms), np.int64),
        pref_key=np.zeros((U, d.pref_terms, d.sel_reqs), np.int32),
        pref_op=np.zeros((U, d.pref_terms, d.sel_reqs), np.int32),
        pref_num=np.zeros((U, d.pref_terms, d.sel_reqs), np.int64),
        pref_val=np.zeros((U, d.pref_terms, d.sel_reqs, d.sel_vals), np.int32),
        port_ids=np.zeros((U, d.ports), np.int32),
        skip_balanced=np.zeros((U,), bool),
        img_ids=np.zeros((U, d.images_per_pod), np.int32),
        img_containers=np.zeros((U,), np.int32),
    )
