"""Pod-batch tensorization: compile a queue drain into device tensors.

Each pending pod becomes a row of fixed-width tensors; arbitrary label
selectors compile to padded (term × requirement × value) id tables evaluated
against the node label arrays on device (SURVEY §7 hard-part 6). Pods whose
constraints exceed the padding (or use semantics with no tensor form yet)
get `host_fallback=True` and are scheduled by the host oracle instead — the
analog of the reference disabling batching for plugins without SignPlugin
(runtime/framework.go:772-816).

Selector op encoding (0 = padding → vacuously true):
  1=In  2=NotIn  3=Exists  4=DoesNotExist  5=Gt  6=Lt
Toleration op: 1=Equal 2=Exists.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import NamedTuple, Optional

import numpy as np

from ..api import resources as res
from ..api.types import NodeSelectorTerm, Pod, SelectorOperator
from ..state.tensorize import _EFFECTS, ClusterState, pow2_at_least
from ..plugins.node_basics import NodeUnschedulable

OP_IN = 1
OP_NOT_IN = 2
OP_EXISTS = 3
OP_DOES_NOT_EXIST = 4
OP_GT = 5
OP_LT = 6

_SEL_OPS = {
    SelectorOperator.IN.value: OP_IN,
    SelectorOperator.NOT_IN.value: OP_NOT_IN,
    SelectorOperator.EXISTS.value: OP_EXISTS,
    SelectorOperator.DOES_NOT_EXIST.value: OP_DOES_NOT_EXIST,
    SelectorOperator.GT.value: OP_GT,
    SelectorOperator.LT.value: OP_LT,
}

TOL_EQUAL = 1
TOL_EXISTS = 2


@dataclass
class BatchDims:
    pods: int = 8          # B (padded)
    sel_terms: int = 4     # T — required node affinity terms
    sel_reqs: int = 6      # Q — requirements per term (incl. nodeSelector merge)
    sel_vals: int = 8      # V — values per requirement
    pref_terms: int = 4    # PT — preferred node affinity terms
    tolerations: int = 8   # TT
    ports: int = 8         # P


class PodBatch(NamedTuple):
    valid: object            # bool [B]
    host_fallback: object    # bool [B] (numpy only; never shipped to device)
    req: object              # i64 [B, R]
    nonzero_req: object      # i64 [B, 2]
    node_name_id: object     # i32 [B] (0 = unset)
    # tolerations
    tol_key: object          # i32 [B, TT]
    tol_val: object          # i32 [B, TT]
    tol_eff: object          # i32 [B, TT] (0 = all effects)
    tol_op: object           # i32 [B, TT] (0 = padding)
    tolerates_unsched: object  # bool [B]
    # required node selector+affinity: nodeSelector is term -1 semantics —
    # compiled as an extra ANDed conjunct via ns_sel_*
    ns_sel_val: object       # i32 [B, Q] (kv id — encodes key=value; 0 = padding)
    aff_has: object          # bool [B] (has required affinity terms)
    aff_term_valid: object   # bool [B, T]
    aff_key: object          # i32 [B, T, Q]
    aff_op: object           # i32 [B, T, Q]
    aff_num: object          # i64 [B, T, Q]
    aff_val: object          # i32 [B, T, Q, V]
    # preferred node affinity
    pref_weight: object      # i64 [B, PT] (0 = unused term)
    pref_key: object         # i32 [B, PT, Q]
    pref_op: object          # i32 [B, PT, Q]
    pref_num: object         # i64 [B, PT, Q]
    pref_val: object         # i32 [B, PT, Q, V]
    # ports
    port_ids: object         # i32 [B, P]
    # score gates
    skip_balanced: object    # bool [B]


class BatchCapacityError(ValueError):
    pass


class BatchBuilder:
    def __init__(self, state: ClusterState, dims: Optional[BatchDims] = None):
        self.state = state
        self.dims = dims or BatchDims()
        self._cluster_has_images = False
        self._cluster_has_affinity_pods = False

    def build(self, pods: list[Pod], snapshot=None,
              pad_to: int = 0) -> PodBatch:
        d = self.dims
        # pad to the caller's standing batch size when given: residual drains
        # then reuse the same compiled program instead of minting a new
        # (smaller) shape bucket
        B = pow2_at_least(max(len(pods), pad_to))
        R = self.state.dims.resources
        arrays = self.state.arrays
        self._cluster_has_images = bool(
            arrays is not None and arrays.image_id.any())
        # InterPodAffinity is symmetric: existing pods carrying required
        # anti-affinity can veto ANY incoming pod (filtering.go:204-228), and
        # existing pods with (anti-)affinity terms feed the score of ANY
        # incoming pod (scoring.go:81-124). Until those count tensors ride the
        # scan carry (ops/groups.py), the whole batch must take the host path
        # whenever such pods exist anywhere in the cluster.
        self._cluster_has_affinity_pods = bool(
            snapshot is not None
            and (snapshot.have_pods_with_affinity_list
                 or snapshot.have_pods_with_required_anti_affinity_list))
        batch = _zero_batch(B, R, d)

        for i, pod in enumerate(pods):
            try:
                self._fill_row(batch, i, pod)
                batch.valid[i] = True
            except BatchCapacityError:
                # zero the partially-filled row; the host oracle schedules it
                for arr in batch:
                    if arr.dtype == bool:
                        arr[i] = False
                    else:
                        arr[i] = 0
                batch.host_fallback[i] = True
        return batch

    def _fill_row(self, b: PodBatch, i: int, pod: Pod) -> None:
        d = self.dims
        intr = self.state.interner
        # constraints the device program doesn't cover yet → host oracle
        # (group tensors for spread/interpod land in ops/groups.py)
        aff = pod.spec.affinity
        if pod.spec.topology_spread_constraints:
            raise BatchCapacityError("topology spread: host path")
        if aff and (aff.pod_affinity or aff.pod_anti_affinity):
            raise BatchCapacityError("inter-pod affinity: host path")
        if self._cluster_has_affinity_pods:
            raise BatchCapacityError(
                "cluster has (anti-)affinity pods: host path")
        if self._cluster_has_images and any(
                c.image for c in pod.spec.containers + pod.spec.init_containers):
            raise BatchCapacityError("image locality: host path")
        # resources
        reqs = res.pod_requests(pod)
        row = self.state.rtable.vector(reqs)
        if len(row) > b.req.shape[1]:
            raise BatchCapacityError("resource table grew past batch width")
        b.req[i, :len(row)] = row
        nz_cpu, nz_mem = res.pod_requests_nonzero(pod)
        b.nonzero_req[i, 0] = nz_cpu
        b.nonzero_req[i, 1] = nz_mem
        b.skip_balanced[i] = all(v == 0 for v in reqs.values())
        # nodeName
        if pod.spec.node_name:
            b.node_name_id[i] = self.state.node_id(pod.spec.node_name)
        # tolerations
        tols = pod.spec.tolerations
        if len(tols) > d.tolerations:
            raise BatchCapacityError("too many tolerations")
        for t, tol in enumerate(tols):
            b.tol_key[i, t] = intr.key.intern(tol.key) if tol.key else 0
            b.tol_val[i, t] = intr.kv.intern(f"tv:{tol.value}")
            b.tol_eff[i, t] = _EFFECTS.get(tol.effect, 0) if tol.effect else 0
            op = tol.operator or "Equal"
            b.tol_op[i, t] = TOL_EXISTS if op == "Exists" else TOL_EQUAL
        b.tolerates_unsched[i] = any(
            t.tolerates(NodeUnschedulable.TAINT) for t in tols)
        # nodeSelector → equality conjuncts
        sel = pod.spec.node_selector
        if len(sel) > d.sel_reqs:
            raise BatchCapacityError("nodeSelector too wide")
        for q, (k, v) in enumerate(sorted(sel.items())):
            b.ns_sel_val[i, q] = intr.label_kv(k, v)
        # required node affinity
        aff = pod.spec.affinity
        na = aff.node_affinity if aff else None
        if na and na.required is not None:
            terms = na.required.terms
            if len(terms) > d.sel_terms:
                raise BatchCapacityError("too many nodeAffinity terms")
            b.aff_has[i] = True
            for t, term in enumerate(terms):
                b.aff_term_valid[i, t] = True
                self._fill_term(term, b.aff_key[i, t], b.aff_op[i, t],
                                b.aff_num[i, t], b.aff_val[i, t])
        # preferred node affinity
        if na and na.preferred:
            prefs = na.preferred
            if len(prefs) > d.pref_terms:
                raise BatchCapacityError("too many preferred terms")
            for t, p in enumerate(prefs):
                if p.weight == 0:
                    continue
                b.pref_weight[i, t] = p.weight
                self._fill_term(p.preference, b.pref_key[i, t], b.pref_op[i, t],
                                b.pref_num[i, t], b.pref_val[i, t])
        # ports
        ports = [(p.protocol or "TCP", p.host_port, p.host_ip)
                 for c in pod.spec.containers for p in c.ports if p.host_port > 0]
        if any(ip not in ("", "0.0.0.0") for (_, _, ip) in ports):
            # host-IP-scoped ports keep reference semantics via host path
            raise BatchCapacityError("host-IP-scoped port")
        if len(ports) > d.ports:
            raise BatchCapacityError("too many host ports")
        for q, (proto, port, _ip) in enumerate(ports):
            b.port_ids[i, q] = intr.port_id(proto, port)
        # pods with inter-pod affinity / spread constraints are handled by the
        # group tensors (ops/groups.py); nothing to do per-row here.

    def _fill_term(self, term: NodeSelectorTerm, key_row, op_row, num_row, val_row) -> None:
        d = self.dims
        intr = self.state.interner
        reqs = list(term.match_expressions)
        # matchFields (metadata.name) compile to ordinary requirements against
        # the synthetic metadata.name label (tensorize.py)
        for f in term.match_fields:
            reqs.append(f)
        if len(reqs) > d.sel_reqs:
            raise BatchCapacityError("too many requirements in term")
        for q, r in enumerate(reqs):
            opc = _SEL_OPS.get(r.operator)
            if opc is None:
                raise BatchCapacityError(f"unsupported operator {r.operator}")
            if r.key == "metadata.name":
                key = intr.key.intern("metadata.name")
            else:
                key = intr.key.intern(r.key)
            key_row[q] = key
            op_row[q] = opc
            if opc in (OP_IN, OP_NOT_IN):
                if len(r.values) > d.sel_vals:
                    raise BatchCapacityError("too many values in requirement")
                for v, value in enumerate(r.values):
                    val_row[q, v] = intr.label_kv(r.key, value)
            elif opc in (OP_GT, OP_LT):
                if len(r.values) != 1:
                    raise BatchCapacityError("Gt/Lt needs exactly one value")
                try:
                    num_row[q] = int(r.values[0])
                except ValueError:
                    raise BatchCapacityError("non-integer Gt/Lt value")


def _zero_batch(B: int, R: int, d: BatchDims) -> PodBatch:
    return PodBatch(
        valid=np.zeros((B,), bool),
        host_fallback=np.zeros((B,), bool),
        req=np.zeros((B, R), np.int64),
        nonzero_req=np.zeros((B, 2), np.int64),
        node_name_id=np.zeros((B,), np.int32),
        tol_key=np.zeros((B, d.tolerations), np.int32),
        tol_val=np.zeros((B, d.tolerations), np.int32),
        tol_eff=np.zeros((B, d.tolerations), np.int32),
        tol_op=np.zeros((B, d.tolerations), np.int32),
        tolerates_unsched=np.zeros((B,), bool),
        ns_sel_val=np.zeros((B, d.sel_reqs), np.int32),
        aff_has=np.zeros((B,), bool),
        aff_term_valid=np.zeros((B, d.sel_terms), bool),
        aff_key=np.zeros((B, d.sel_terms, d.sel_reqs), np.int32),
        aff_op=np.zeros((B, d.sel_terms, d.sel_reqs), np.int32),
        aff_num=np.zeros((B, d.sel_terms, d.sel_reqs), np.int64),
        aff_val=np.zeros((B, d.sel_terms, d.sel_reqs, d.sel_vals), np.int32),
        pref_weight=np.zeros((B, d.pref_terms), np.int64),
        pref_key=np.zeros((B, d.pref_terms, d.sel_reqs), np.int32),
        pref_op=np.zeros((B, d.pref_terms, d.sel_reqs), np.int32),
        pref_num=np.zeros((B, d.pref_terms, d.sel_reqs), np.int64),
        pref_val=np.zeros((B, d.pref_terms, d.sel_reqs, d.sel_vals), np.int32),
        port_ids=np.zeros((B, d.ports), np.int32),
        skip_balanced=np.zeros((B,), bool),
    )
