"""Tensorized cluster state: the device-resident mirror of the cache.

The reference's NodeInfo (pkg/scheduler/framework/types.go:165-208) becomes a
row across a set of padded, statically-shaped arrays:

- cap/used [N, R] int64      — Allocatable / Requested per resource column
- nonzero_used [N, 2] int64  — NonZeroRequested (cpu, mem) for LeastAllocated
- npods / allowed_pods [N]   — pod count vs allocatable "pods"
- taints  [N, T] ×3          — interned (key, value, effect) triples
- labels  [N, L] ×3          — interned (key, key=value, numeric) triples;
  node name is injected as a synthetic `metadata.name` label so NodeAffinity
  matchFields compile to ordinary requirements
- ports   [N, P]             — interned (protocol, port) ids in use
- images  [N, I] ×2          — interned image ids + sizes

Shapes are padded to power-of-two buckets (SURVEY §7 hard-part 3: avoid
recompilation storms); `valid[N]` masks padding rows.

Update path mirrors the incremental snapshot (backend/cache/snapshot.go):
`apply_snapshot` consumes `Snapshot.dirty_nodes` and scatter-writes only the
changed rows. During a batch the *device program itself* carries used/npods/
ports forward (ops/program.py), so steady-state scheduling moves no node
state across PCIe at all — the host only reconciles informer deltas.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import NamedTuple, Optional

import numpy as np

from ..api import resources as res
from ..api.types import Node, TaintEffect
from ..backend.cache import Snapshot
from ..framework.types import NodeInfo
from ..utils.interning import ClusterInterner

# effect encoding (0 = padding)
EFFECT_NO_SCHEDULE = 1
EFFECT_PREFER_NO_SCHEDULE = 2
EFFECT_NO_EXECUTE = 3

_EFFECTS = {
    TaintEffect.NO_SCHEDULE.value: EFFECT_NO_SCHEDULE,
    TaintEffect.PREFER_NO_SCHEDULE.value: EFFECT_PREFER_NO_SCHEDULE,
    TaintEffect.NO_EXECUTE.value: EFFECT_NO_EXECUTE,
}

# sentinel for "label value is not an integer" (Gt/Lt never match)
NON_NUMERIC = np.int64(np.iinfo(np.int64).min)

METADATA_NAME_KEY = "metadata.name"


def pow2_at_least(n: int, floor: int = 8) -> int:
    v = floor
    while v < n:
        v *= 2
    return v


class NodeArrays(NamedTuple):
    """The device (or staging-numpy) arrays. All shapes static."""

    cap: object            # i64 [N, R]
    used: object           # i64 [N, R]
    nonzero_used: object   # i64 [N, 2]
    npods: object          # i32 [N]
    allowed_pods: object   # i32 [N]
    valid: object          # bool [N]
    unschedulable: object  # bool [N]
    name_id: object        # i32 [N] (interned node name, NodeName filter)
    taint_key: object      # i32 [N, T]
    taint_val: object      # i32 [N, T]
    taint_eff: object      # i32 [N, T]
    label_key: object      # i32 [N, L]
    label_kv: object       # i32 [N, L]
    label_num: object      # i64 [N, L]
    ports: object          # i32 [N, P]
    image_id: object       # i32 [N, I]
    image_size: object     # i64 [N, I]


@dataclass
class Dims:
    nodes: int = 8
    resources: int = 16
    taints: int = 8
    labels: int = 16
    ports: int = 8
    images: int = 8


class CapacityError(ValueError):
    """A node exceeded a padded per-row capacity; caller re-pads + rebuilds."""


@dataclass
class ClusterState:
    """Host owner of the tensorized state."""

    interner: ClusterInterner = field(default_factory=ClusterInterner)
    rtable: res.ResourceTable = field(default_factory=res.ResourceTable)
    dims: Dims = field(default_factory=Dims)
    node_index: dict[str, int] = field(default_factory=dict)
    node_names: list[str] = field(default_factory=list)
    row_gen: dict[str, int] = field(default_factory=dict)
    _free: list[int] = field(default_factory=list)
    arrays: Optional[NodeArrays] = None  # numpy staging
    _device: Optional[NodeArrays] = None  # jax device copy (lazy)
    # mesh-placed copy (lazy; ISSUE 16). A scheduler uses exactly ONE
    # placement flavor — single-device or node-sharded — so the two
    # resident copies share the consume-on-read dirty flag and the dirty
    # row set without fighting over them.
    _device_sharded: Optional[NodeArrays] = None
    _device_dirty: bool = True
    # monotonic generation of the STAGING arrays: bumped on every mutation
    # (snapshot writes, growth, adopt_carry) so external caches — e.g. the
    # scheduler's mesh-sharded copy — can invalidate without sharing the
    # single-device cache's consume-on-read flag
    staging_gen: int = 0
    # monotonic generation of the STATIC node columns only (valid, name,
    # labels, taints, images, capacity — everything the carry-independent
    # signature surfaces read): bumped by full row writes, row
    # invalidations and shape growth, but NOT by the per-commit aggregate
    # updates (used/npods/ports) that dominate steady-state drains. The
    # compiler's per-signature SurfaceCache keys on this, so hoisted
    # surfaces survive every placement-only generation bump.
    statics_gen: int = 0
    # name → the Node object whose static fields row `name` reflects
    # (strong refs: identity comparison is only safe while we hold them)
    _row_node: dict = field(default_factory=dict)
    # generation-diff device upload (ISSUE 9): row indices written since
    # the device copy was last refreshed. When the set is small and no
    # shape moved, device_arrays() scatters ONLY these rows through the
    # scatter_rows JIT entry instead of re-uploading the full matrices;
    # None = tracking lost (fall back to a full upload).
    _dirty_rows: Optional[set] = field(default_factory=set)
    # counters mirrored into scheduler metrics by the owner (the state
    # layer must not import the metrics registry)
    rows_scattered_total: int = 0
    full_uploads_total: int = 0
    # scatter only when dirty rows ≤ max(N >> scatter_shift, 32): beyond
    # that the full upload's one big copy beats many-row gathers
    scatter_shift: int = 3
    # optional SchedulerMetrics, wired by the owning Scheduler (the
    # state layer never imports the registry): ingest_rows_scattered /
    # ingest_full_uploads mirror the two counters above
    metrics: object = None
    # (id(snapshot), generation, tree_generation) of the last fully
    # consumed apply_snapshot: an unchanged snapshot skips the O(N) walk
    # entirely (the preemption path applies per failed pod)
    _applied_key: tuple = (0, -1, -1)

    # -- index management -----------------------------------------------------

    def _slot(self, name: str) -> int:
        idx = self.node_index.get(name)
        if idx is not None:
            return idx
        if self._free:
            idx = self._free.pop()
        else:
            idx = len(self.node_names)
            self.node_names.append("")
            if idx >= self.dims.nodes:
                self._grow_nodes()
        self.node_index[name] = idx
        self.node_names[idx] = name
        return idx

    def _grow_nodes(self) -> None:
        old = self.dims.nodes
        self.dims.nodes = pow2_at_least(len(self.node_names), max(8, old * 2))
        if self.arrays is not None:
            self.arrays = _pad_rows(self.arrays, self.dims.nodes)
            self.staging_gen += 1
            self.statics_gen += 1   # [N]-shaped surfaces are stale
            self._dirty_rows = None  # shape moved: full upload

    def node_id(self, name: str) -> int:
        """Interned id used for NodeName filter / matchFields."""
        return self.interner.kv.intern(f"node:{name}")

    # -- build / update -------------------------------------------------------

    def ensure_arrays(self) -> NodeArrays:
        if self.arrays is None:
            self.arrays = _zero_arrays(self.dims)
        return self.arrays

    def apply_snapshot(self, snapshot: Snapshot, full: bool = False) -> None:
        """Scatter-update rows whose NodeInfo generation moved since the last
        apply (pull-based incremental consumption: this consumer owns its own
        progress in `row_gen`, so it never depends on how often the host
        refreshed the snapshot in between)."""
        applied_key = (id(snapshot), snapshot.generation,
                       snapshot.tree_generation)
        if not full and self.arrays is not None \
                and applied_key == self._applied_key:
            return
        self.ensure_arrays()
        list_order = {n.name: i for i, n in enumerate(snapshot.node_info_list)}
        schedulable_names = set(list_order)
        # removed or non-schedulable nodes → invalidate rows
        for name in list(self.node_index):
            if name not in schedulable_names:
                idx = self.node_index.pop(name, None)
                self.row_gen.pop(name, None)
                self._row_node.pop(name, None)
                if idx is not None:
                    self.arrays.valid[idx] = False
                    self.node_names[idx] = ""
                    self._free.append(idx)
                    self.statics_gen += 1
                    # the cleared valid bit must reach the device even
                    # when no other row was written this apply
                    self._device_dirty = True
                    self.staging_gen += 1
                    if self._dirty_rows is not None:
                        self._dirty_rows.add(idx)
        # write in snapshot-list order so freshly-assigned row indices track
        # the host iteration order (argmax tie-breaks then usually agree)
        dirty_writes = False
        full_items: list = []
        agg_items: list = []
        for ni in snapshot.node_info_list:
            prev_gen = self.row_gen.get(ni.name)
            if not full and prev_gen == ni.generation:
                continue
            idx = self._slot(ni.name)
            # fast path: the Node OBJECT is unchanged (labels/taints/
            # capacity/images identical by identity — _row_node holds a
            # strong ref so the id can't be recycled), so only the pod
            # aggregates moved (assume/add/remove): rewrite those alone.
            # This is the common per-drain case — every commit bumps its
            # node's generation, and a full row rewrite costs ~7× the
            # aggregate update.
            if (not full and prev_gen is not None
                    and self._row_node.get(ni.name) is ni.node):
                agg_items.append((idx, ni))
            else:
                full_items.append((idx, ni))
                self._row_node[ni.name] = ni.node
            self.row_gen[ni.name] = ni.generation
            dirty_writes = True
        # columnar batch writers (ingest/noderows.py) take mass updates
        # (prime/resync/churn); small dirty sets and capacity edges keep
        # the per-row writers, which own growth and CapacityError
        if full_items:
            from ..ingest.noderows import write_rows
            if len(full_items) < 16 or not write_rows(self, full_items):
                for idx, ni in full_items:
                    self._write_row(idx, ni)
        if agg_items:
            from ..ingest.noderows import write_aggregate_rows
            if len(agg_items) < 16 or not write_aggregate_rows(
                    self, agg_items):
                for idx, ni in agg_items:
                    self._write_row_aggregates(idx, ni)
        if dirty_writes or full:
            self._device_dirty = True
            self.staging_gen += 1
        self._applied_key = applied_key

    def _write_row_aggregates(self, idx: int, ni: NodeInfo) -> None:
        """Pod-aggregate-only row refresh (used/nonzero/npods/ports) —
        valid only when the Node object itself is unchanged."""
        a = self.arrays
        if self._dirty_rows is not None:
            self._dirty_rows.add(idx)
        used_row = self.rtable.vector(ni.requested)
        if len(used_row) > a.used.shape[1]:
            self._write_row(idx, ni)   # resource table grew: full path
            return
        a.used[idx, :len(used_row)] = used_row
        a.used[idx, len(used_row):] = 0
        a.nonzero_used[idx, 0] = ni.non_zero_cpu
        a.nonzero_used[idx, 1] = ni.non_zero_mem
        a.npods[idx] = len(ni.pods)
        if ni.used_ports.ports or a.ports[idx, 0]:
            port_ids = sorted({self.interner.port_id(p, pt)
                               for (p, pt, _ip) in ni.used_ports.ports})
            if len(port_ids) > self.dims.ports:
                raise CapacityError(
                    f"node {ni.name}: {len(port_ids)} ports > "
                    f"{self.dims.ports}")
            a.ports[idx] = 0
            a.ports[idx, :len(port_ids)] = port_ids

    def _write_row(self, idx: int, ni: NodeInfo) -> None:
        a = self.arrays
        d = self.dims
        node = ni.node
        # full row write touches the static columns: hoisted per-signature
        # surfaces over this node axis must recompute
        self.statics_gen += 1
        if self._dirty_rows is not None:
            self._dirty_rows.add(idx)
        # resources
        cap_row = self.rtable.vector(ni.allocatable)
        used_row = self.rtable.vector(ni.requested)
        if len(cap_row) > d.resources or len(used_row) > d.resources:
            self._grow_resources()
            a = self.arrays  # _grow_resources rebinds the arrays
            cap_row = self.rtable.vector(ni.allocatable)
            used_row = self.rtable.vector(ni.requested)
        a.cap[idx, :len(cap_row)] = cap_row
        a.cap[idx, len(cap_row):] = 0
        a.used[idx, :len(used_row)] = used_row
        a.used[idx, len(used_row):] = 0
        a.nonzero_used[idx, 0] = ni.non_zero_cpu
        a.nonzero_used[idx, 1] = ni.non_zero_mem
        a.npods[idx] = len(ni.pods)
        a.allowed_pods[idx] = ni.allocatable.get(res.PODS, 0)
        a.valid[idx] = True
        a.unschedulable[idx] = node.spec.unschedulable
        a.name_id[idx] = self.node_id(node.metadata.name)
        # taints
        taints = node.spec.taints
        if len(taints) > d.taints:
            raise CapacityError(f"node {ni.name}: {len(taints)} taints > {d.taints}")
        a.taint_key[idx] = 0
        a.taint_val[idx] = 0
        a.taint_eff[idx] = 0
        for t, taint in enumerate(taints):
            a.taint_key[idx, t] = self.interner.key.intern(taint.key)
            a.taint_val[idx, t] = self.interner.kv.intern(f"tv:{taint.value}")
            a.taint_eff[idx, t] = _EFFECTS.get(taint.effect, 0)
        # labels (+ synthetic metadata.name)
        labels = dict(node.metadata.labels)
        labels[METADATA_NAME_KEY] = node.metadata.name
        if len(labels) > d.labels:
            raise CapacityError(f"node {ni.name}: {len(labels)} labels > {d.labels}")
        a.label_key[idx] = 0
        a.label_kv[idx] = 0
        a.label_num[idx] = NON_NUMERIC
        for l, (k, v) in enumerate(sorted(labels.items())):
            a.label_key[idx, l] = self.interner.key.intern(k)
            a.label_kv[idx, l] = self.interner.label_kv(k, v)
            try:
                a.label_num[idx, l] = int(v)
            except ValueError:
                a.label_num[idx, l] = NON_NUMERIC
        # ports
        port_ids = sorted({self.interner.port_id(p, pt)
                           for (p, pt, _ip) in ni.used_ports.ports})
        if len(port_ids) > d.ports:
            raise CapacityError(f"node {ni.name}: {len(port_ids)} ports > {d.ports}")
        a.ports[idx] = 0
        a.ports[idx, :len(port_ids)] = port_ids
        # images
        if len(ni.image_sizes) > d.images:
            # grow rather than truncate: the ImageLocality device kernel is
            # authoritative now (no host fallback), so a dropped image row
            # would silently corrupt scores
            self._grow_images(len(ni.image_sizes))
            a = self.arrays
        a.image_id[idx] = 0
        a.image_size[idx] = 0
        for i, (img, size) in enumerate(sorted(ni.image_sizes.items())):
            a.image_id[idx, i] = self.interner.image.intern(img)
            a.image_size[idx, i] = size

    def _grow_images(self, needed: int) -> None:
        self.dims.images = pow2_at_least(needed)
        if self.arrays is not None:
            a = self.arrays

            def pad(x):
                extra = self.dims.images - x.shape[1]
                if extra <= 0:
                    return x
                return np.concatenate(
                    [x, np.zeros((x.shape[0], extra), x.dtype)], axis=1)

            self.arrays = a._replace(image_id=pad(a.image_id),
                                     image_size=pad(a.image_size))
        self._device_dirty = True
        self.staging_gen += 1
        self.statics_gen += 1
        self._dirty_rows = None

    def _grow_resources(self) -> None:
        self.dims.resources = self.rtable.width
        if self.arrays is not None:
            self.arrays = _pad_cols(self.arrays, self.dims)
            self.staging_gen += 1
            self.statics_gen += 1
            self._dirty_rows = None

    def request_vector(self, requests: dict[str, int]):
        """Dense np.int64 request row at the CURRENT staging width, WITHOUT
        interning side effects: returns None when a resource name is not in
        the table (or sits past the staged width), letting the caller fall
        back to the host path instead of triggering a mid-flight resource
        growth/recompile. Used by the batched preemption dry-run for victim
        and nominated-pod vectors."""
        a = self.ensure_arrays()
        width = a.used.shape[1]
        row = np.zeros((width,), np.int64)
        index = self.rtable.index
        for name, v in requests.items():
            i = index.get(name)
            if i is None or i >= width:
                return None
            row[i] = v
        return row

    # -- device transfer ------------------------------------------------------

    def device_arrays(self) -> NodeArrays:
        """jnp copies (cached until the staging arrays change).

        Generation-diff upload: when only a small set of rows moved since
        the last refresh (tracked in `_dirty_rows` by the row writers),
        ship just those rows through the `scatter_rows` JIT entry
        (ops/program.py) — H2D pays O(dirty × row width), not O(N × row
        width). The scatter does NOT donate the previous device copy:
        in-flight drains and resident carries may still reference it (it
        was handed out by an earlier call), so the entry materializes
        fresh buffers and only the transfer is diffed."""
        import jax.numpy as jnp
        if self._device is None or self._device_dirty:
            a = self.ensure_arrays()
            from ..perf.ledger import GLOBAL as _ledger
            dirty = self._dirty_rows
            N = a.used.shape[0]
            if (self._device is not None and dirty
                    and self._device.used.shape == a.used.shape
                    and self._device.label_key.shape == a.label_key.shape
                    and self._device.image_id.shape == a.image_id.shape
                    and len(dirty) <= max(N >> self.scatter_shift, 32)):
                idx = np.fromiter(dirty, np.int64, len(dirty))
                idx.sort()
                # pow2 index bucket (repeat the first row) so the entry
                # compiles once per bucket, not once per dirty count
                D = pow2_at_least(len(idx))
                pidx = np.full((D,), idx[0], np.int64)
                pidx[:len(idx)] = idx
                rows = NodeArrays(*(x[pidx] for x in a))
                from ..ops.program import scatter_rows
                self._device = scatter_rows(self._device,
                                            pidx.astype(np.int32), rows)
                _ledger.note_h2d_tree("host_snapshot", rows)
                self.rows_scattered_total += len(idx)
                if self.metrics is not None:
                    self.metrics.ingest_rows_scattered.inc(by=len(idx))
            else:
                self._device = NodeArrays(*(jnp.asarray(x) for x in a))
                _ledger.note_h2d_tree("host_snapshot", a)
                self.full_uploads_total += 1
                if self.metrics is not None:
                    self.metrics.ingest_full_uploads.inc()
            self._device_dirty = False
            self._dirty_rows = set()
        return self._device

    def device_arrays_sharded(self, mesh) -> NodeArrays:
        """Mesh-placed copies with the SAME generation-diff upload policy
        as `device_arrays` (ISSUE 16): when only a small set of rows moved
        since the last refresh, ship just those rows through the
        `scatter_rows_sharded` JIT entry — the H2D bytes are the small
        replicated row block and each shard keeps only its own rows —
        instead of re-sharding the full matrices. Mesh drains previously
        paid the full-matrix upload on every staging change; this carries
        the PR-9 columnar-ingest win onto the mesh."""
        if self._device_sharded is None or self._device_dirty:
            a = self.ensure_arrays()
            from ..parallel.sharding import (scatter_rows_sharded,
                                             shard_node_arrays)
            dirty = self._dirty_rows
            N = a.used.shape[0]
            dev = self._device_sharded
            if (dev is not None and dirty
                    and dev.used.shape == a.used.shape
                    and dev.label_key.shape == a.label_key.shape
                    and dev.image_id.shape == a.image_id.shape
                    and len(dirty) <= max(N >> self.scatter_shift, 32)):
                idx = np.fromiter(dirty, np.int64, len(dirty))
                idx.sort()
                D = pow2_at_least(len(idx))
                pidx = np.full((D,), idx[0], np.int64)
                pidx[:len(idx)] = idx
                rows = NodeArrays(*(x[pidx] for x in a))
                self._device_sharded = scatter_rows_sharded(
                    mesh, dev, pidx.astype(np.int32), rows)
                self.rows_scattered_total += len(idx)
                if self.metrics is not None:
                    self.metrics.ingest_rows_scattered.inc(by=len(idx))
            else:
                self._device_sharded = shard_node_arrays(mesh, a)
                self.full_uploads_total += 1
                if self.metrics is not None:
                    self.metrics.ingest_full_uploads.inc()
            self._device_dirty = False
            self._dirty_rows = set()
        return self._device_sharded

    def adopt_carry(self, used, nonzero_used, npods, ports,
                    touched: Optional[dict[str, int]] = None) -> None:
        """After a batch, the scan's carry IS the new truth for the mutable
        arrays — pull it back into staging without a full rebuild. (The host
        cache is updated in parallel via assume; `reconcile` cross-checks.)

        `touched` maps node name → the cache generation reached by the
        parallel assume bookkeeping; recording it marks those rows current,
        which is what lets `reconcile` compare scan-carry content against
        cache content instead of writing the rows off as lagging."""
        a = self.ensure_arrays()
        np.copyto(a.used, np.asarray(used))
        np.copyto(a.nonzero_used, np.asarray(nonzero_used))
        np.copyto(a.npods, np.asarray(npods))
        np.copyto(a.ports, np.asarray(ports))
        self.staging_gen += 1
        if touched:
            self.row_gen.update(touched)
        if self._device is not None:
            self._device = self._device._replace(
                used=used, nonzero_used=nonzero_used, npods=npods, ports=ports)
        if self._device_sharded is not None:
            # a mesh drain's carry arrays are already mesh-placed: adopt
            # them in place, no re-upload
            self._device_sharded = self._device_sharded._replace(
                used=used, nonzero_used=nonzero_used, npods=npods, ports=ports)

    # -- divergence check (cache debugger analog) ----------------------------

    def reconcile(self, snapshot: Snapshot) -> list[str]:
        """Compare staging arrays vs snapshot; returns divergent node names
        (backend/cache/debugger comparer analog). Rows whose generation is
        behind the snapshot are LAG, not divergence — the next apply_snapshot
        refreshes them; only rows claiming to be current are compared."""
        out = []
        a = self.ensure_arrays()
        for name, idx in self.node_index.items():
            ni = snapshot.node_infos.get(name)
            if ni is None:
                out.append(name)
                continue
            if self.row_gen.get(name) != ni.generation:
                continue
            used_row = self.rtable.vector(ni.requested)
            port_ids = sorted({self.interner.port_id(p, pt)
                               for (p, pt, _ip) in ni.used_ports.ports})
            row_ports = sorted(int(x) for x in a.ports[idx] if x != 0)
            if (list(a.used[idx, :len(used_row)]) != used_row
                    or a.npods[idx] != len(ni.pods)
                    or row_ports != port_ids):
                out.append(name)
        return out


def _zero_arrays(d: Dims) -> NodeArrays:
    n = d.nodes
    return NodeArrays(
        cap=np.zeros((n, d.resources), np.int64),
        used=np.zeros((n, d.resources), np.int64),
        nonzero_used=np.zeros((n, 2), np.int64),
        npods=np.zeros((n,), np.int32),
        allowed_pods=np.zeros((n,), np.int32),
        valid=np.zeros((n,), bool),
        unschedulable=np.zeros((n,), bool),
        name_id=np.zeros((n,), np.int32),
        taint_key=np.zeros((n, d.taints), np.int32),
        taint_val=np.zeros((n, d.taints), np.int32),
        taint_eff=np.zeros((n, d.taints), np.int32),
        label_key=np.zeros((n, d.labels), np.int32),
        label_kv=np.zeros((n, d.labels), np.int32),
        label_num=np.full((n, d.labels), NON_NUMERIC, np.int64),
        ports=np.zeros((n, d.ports), np.int32),
        image_id=np.zeros((n, d.images), np.int32),
        image_size=np.zeros((n, d.images), np.int64),
    )


def _pad_rows(a: NodeArrays, n: int) -> NodeArrays:
    def pad(x):
        extra = n - x.shape[0]
        if extra <= 0:
            return x
        fill = NON_NUMERIC if x is a.label_num else 0
        pad_block = np.full((extra,) + x.shape[1:], fill, x.dtype)
        return np.concatenate([x, pad_block], axis=0)
    return NodeArrays(*(pad(x) for x in a))


def _pad_cols(a: NodeArrays, d: Dims) -> NodeArrays:
    def pad(x, want):
        extra = want - x.shape[1]
        if extra <= 0:
            return x
        return np.concatenate(
            [x, np.zeros((x.shape[0], extra), x.dtype)], axis=1)
    return a._replace(cap=pad(a.cap, d.resources), used=pad(a.used, d.resources))
