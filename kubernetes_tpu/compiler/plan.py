"""The drain compiler: one static device program for any pod mix.

ROADMAP item 4 / SURVEY hard part 3. The device path grew as four special
cases of one idea — the lean per-pod scan, the closed-form uniform run,
the same-signature group wave, and the ≤4-signature mixed wave-scan —
and every drain that fit none of them fell off onto the host greedy or a
SigCache-thrashing per-pod scan (the ">4 interacting signatures" cliff:
an alternating mixed drain recomputed the full kernel set every step).

`DrainCompiler.compile_drain` replaces that case dispatch: it takes a
drain's pod mix (signature sequence, group membership, gang span) plus
the feature-gate set and emits a `DrainPlan` — an ordered list of spans,
each mapped to the cheapest EXACT program:

  ("gang", needed)            whole-gang all-or-nothing (ops/gang.py)
  ("wave", u, anti, merge)    same-signature group wave (run_wave)
  ("wavescan", rows, ports)   the plan program (ops/program.py run_plan):
                              any mix of group / group-free / host-port
                              rows, signature count padded to the pow2
                              lattice, surfaces hoisted via SurfaceCache
  ("uniform",)                closed-form top-L same-signature run
  ("scan",)                   the per-pod reference scan (fallback tier)

Padding policy (the static-shape contract): pod spans pad to pow2
buckets, signature sets pad to the pow2 lattice {2, 4, 8, ..., 32}
(`PLAN_MAX_SIGS`), so the whole workload's executable count is
log-bounded per constraint family instead of per observed mix. Plans are
cached by a key over (signature structure, flags, table generation): the
compile ledger then proves a fixed retrace point over a steady workload
— same traffic shape, zero fresh executables.

Fallback matrix (what still routes to "scan"): nominated-pod overlays
and per-pod self-exclusion, invalid rows, spans below `wave_min_span`,
and mixes beyond PLAN_MAX_SIGS distinct signatures. Host-greedy remains
the no-device tier for group drains whose plan is scan-only
(`DrainPlan.scan_only` — gate off or short spans). The sharded mesh is
a first-class backend (ISSUE 16): uniform/wavescan/gang spans dispatch
through their mesh twins in parallel/sharding.py; only the
same-signature merge wave keeps its single-device kernel, so on a mesh
those spans compile to the plan program ("wavescan") instead.
"""

from __future__ import annotations

from collections import OrderedDict
from dataclasses import dataclass, field

from .surfaces import SurfaceCache

# signature-lattice ceiling for one plan span: S pads to the next pow2
# ≤ this; beyond it the span keeps the reference scan (fallback matrix)
PLAN_MAX_SIGS = 32

# plan cache bound (structural keys are small; drains repeat heavily)
PLAN_CACHE_LIMIT = 256


@dataclass
class DrainPlan:
    """A compiled drain: spans in queue order + the static-shape audit."""

    spans: list                  # [(i, j, kind)] — _dispatch_spans layout
    key: tuple = ()
    # padded-slot fraction over the plan's device programs: 1 − (real
    # work slots / padded work slots), the cost of the pow2 lattice
    pad_waste: float = 0.0
    # no compiled program covers the drain (host greedy / oracle tier
    # may take it instead)
    scan_only: bool = False


@dataclass
class DrainCompiler:
    """Maps a drain's pod mix to a DrainPlan (see module docstring).

    Holds the per-signature SurfaceCache (hoisted kernel surfaces with
    generation-diff retention) and the keyed plan cache; both are owned
    by the scheduler and shared by every profile."""

    state: object
    builder: object
    gates: object
    metrics: object = None
    max_sigs: int = PLAN_MAX_SIGS
    surfaces: SurfaceCache = None
    _plans: OrderedDict = field(default_factory=OrderedDict)

    def __post_init__(self):
        if self.surfaces is None:
            self.surfaces = SurfaceCache(self.state, self.builder)

    # -- plan cache ---------------------------------------------------------

    def _cache_get(self, key):
        plan = self._plans.get(key)
        if plan is not None:
            self._plans.move_to_end(key)
            if self.metrics is not None:
                self.metrics.compiler_plan_cache_hits.inc()
        elif self.metrics is not None:
            self.metrics.compiler_plan_cache_misses.inc()
        return plan

    def _cache_put(self, key, plan) -> None:
        self._plans[key] = plan
        if len(self._plans) > PLAN_CACHE_LIMIT:
            self._plans.popitem(last=False)

    # -- compilation --------------------------------------------------------

    def compile_drain(self, batch, n: int, *, groups_needed: bool,
                      gang_needed=None, overlay: bool = False,
                      nominated: bool = False, mesh: bool = False,
                      strategy: str = "LeastAllocated",
                      prefer_taints: bool = False, wave_min_span: int = 24,
                      uniform_min: int = 16) -> DrainPlan:
        """Compile one drain's pod mix into a DrainPlan. Everything the
        emitted spans depend on is either in the cache key or immutable
        per signature row, so a cached plan is always valid."""
        if gang_needed is not None:
            # whole-gang drains are a single span by construction; the
            # tier choice (closed-form vs scan) is data-dependent and
            # made at dispatch (ops/gang.py)
            return DrainPlan(spans=[(0, n, ("gang", int(gang_needed)))])
        wave_on = self.gates.enabled("SpeculativeWavePlacement")
        batching_on = self.gates.enabled("OpportunisticBatching")
        key = (self.builder.reset_count, self.builder.table_used,
               groups_needed, overlay, nominated, mesh, strategy,
               prefer_taints, wave_min_span, uniform_min, wave_on,
               batching_on, n,
               batch.sig[:n].tobytes(), batch.tidx[:n].tobytes(),
               bool(batch.valid[:n].all()))
        plan = self._cache_get(key)
        if plan is None:
            plan = self._compile(batch, n, groups_needed=groups_needed,
                                 overlay=overlay, nominated=nominated,
                                 mesh=mesh, strategy=strategy,
                                 prefer_taints=prefer_taints,
                                 wave_min_span=wave_min_span,
                                 uniform_min=uniform_min, wave_on=wave_on,
                                 batching_on=batching_on)
            plan.key = key
            self._cache_put(key, plan)
        if self.metrics is not None:
            self.metrics.compiler_pad_waste.observe(plan.pad_waste)
        return plan

    def _compile(self, batch, n, *, groups_needed, overlay, nominated,
                 mesh, strategy, prefer_taints, wave_min_span, uniform_min,
                 wave_on, batching_on) -> DrainPlan:
        from ..state.tensorize import pow2_at_least

        spans = None
        if groups_needed and not overlay and not nominated:
            wave = self._classify_wave(batch, n, wave_on, wave_min_span,
                                       mesh=mesh)
            if wave is not None:
                spans = [(0, n, wave)]
        if spans is None:
            # uniform/scan classification (the lean tiers). Nominated
            # per-pod self-exclusion is outside the closed form; overlays
            # ride the scan's fit overlay.
            fast_ok = (not nominated and batching_on
                       and not groups_needed
                       and strategy == "LeastAllocated"
                       and not prefer_taints)
            if not fast_ok:
                spans = [(0, n, ("scan",))]
            else:
                spans = [(i, j, ("uniform",) if uniform else ("scan",))
                         for (i, j, uniform)
                         in self._classify_runs(batch, n, uniform_min)]
            if not groups_needed and not overlay and not nominated:
                # non-interacting signatures in one plan span: the
                # alternating mixed drain that thrashed the scan's
                # one-slot signature cache
                spans = [self._lean_span(batch, s, wave_on, wave_min_span)
                         for s in spans]
        # pad-waste audit: real work slots vs the padded lattice slots of
        # every compiled span (scan spans pad the pod bucket only)
        real = padded = 0
        for (i, j, kind) in spans:
            m = j - i
            if kind[0] == "wavescan":
                S = len(kind[1])
                real += m * S
                padded += pow2_at_least(m) * pow2_at_least(S, 2)
            elif kind[0] in ("scan", "wave"):
                real += m
                padded += pow2_at_least(m)
            else:               # uniform: L is the standing batch bucket
                real += m
                padded += m
        waste = 0.0 if padded == 0 else max(1.0 - real / padded, 0.0)
        scan_only = all(k[0] == "scan" for (_i, _j, k) in spans)
        return DrainPlan(spans=spans, pad_waste=round(waste, 4),
                         scan_only=scan_only)

    # -- classification (formerly scheduler.py case dispatch) ----------------

    def _classify_runs(self, batch, n: int, uniform_min: int):
        """Split [0, n) into maximal same-signature runs; mark each
        uniform (closed-form eligible) or not; merge adjacent non-uniform
        stretches so they cost one dispatch instead of many."""
        sig, tidx = batch.sig, batch.tidx
        pref_w = self.builder.table.pref_weight
        runs: list[tuple[int, int, bool]] = []
        i = 0
        while i < n:
            j = i + 1
            while j < n and sig[j] == sig[i]:
                j += 1
            uniform = (sig[i] != 0 and j - i >= uniform_min
                       and not pref_w[tidx[i]].any())
            if runs and not uniform and not runs[-1][2]:
                runs[-1] = (runs[-1][0], j, False)
            else:
                runs.append((i, j, uniform))
            i = j
        return runs

    def _classify_wave(self, batch, n: int, wave_on: bool,
                       wave_min_span: int, mesh: bool = False):
        """Whole-drain program for a group drain, or None (scan-only →
        host greedy / reference scan). Same-signature port-free drains
        ride the merge wave (single-device only — on a mesh they compile
        to the plan program instead); ANY other mix up to PLAN_MAX_SIGS
        distinct signatures — host-port rows included — compiles to one
        plan program."""
        if not wave_on or n < wave_min_span:
            return None
        if not batch.valid[:n].all():
            return None
        sig = batch.sig[:n]
        has_ports = bool((sig == 0).any())
        uniq = list(dict.fromkeys(batch.tidx[:n].tolist()))
        if len(uniq) == 1 and not has_ports and not mesh:
            mode, anti = self._wave_same_mode(int(uniq[0]))
            if mode is not None:
                return ("wave", int(uniq[0]), anti, mode == "merge")
        if len(uniq) <= self.max_sigs:
            return ("wavescan", tuple(int(u) for u in uniq), has_ports)
        return None

    def _wave_same_mode(self, u: int):
        """(mode, anti_term) for the same-signature kernel: "merge" runs
        the closed-form wave loop (with `anti_term` the row's single
        self-matching required-anti term, -1 = none), "serial" the exact
        in-dispatch scan only, None = the row needs the multi-signature
        program (its in-wave self-interactions — ScheduleAnyway counts,
        required affinity, score terms — are outside the same-signature
        state the kernel maintains)."""
        g = self.builder.groups
        if u >= len(g.rows):
            return None, -1
        if g.spr_s_active[u].any():
            return None, -1
        if g.m_ipa_a[u, u] and g.ipa_ra_active[u].any():
            return None, -1
        if g.w_stc[u, u].any() or g.w_stp[u, u].any():
            return None, -1
        terms = [t for t in range(g.m_ipa_aa.shape[2])
                 if g.m_ipa_aa[u, u, t] or g.m_ipa_exist[u, u, t]]
        if len(terms) > 1:
            return "serial", -1
        return "merge", (terms[0] if terms else -1)

    def _lean_span(self, batch, span, wave_on: bool, wave_min_span: int):
        """Upgrade an eligible scan span of a group-free drain to the
        lean plan program; anything ineligible keeps its kind."""
        i, j, kind = span
        if (kind[0] != "scan" or not wave_on or j - i < wave_min_span):
            return span
        if not batch.valid[i:j].all():
            return span
        has_ports = bool((batch.sig[i:j] == 0).any())
        uniq = list(dict.fromkeys(int(t) for t in batch.tidx[i:j]))
        if len(uniq) > self.max_sigs:
            return span
        return (i, j, ("wavescan", tuple(uniq), has_ports))
