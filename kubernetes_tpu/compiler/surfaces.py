"""Per-signature kernel-surface cache with generation-diff retention.

The wave/plan/gang programs hoist every carry-INDEPENDENT kernel — the
static filter mask (name/unschedulable/taints/selector), the
TaintToleration and preferred-affinity raw counts, the ImageLocality
score — out of the dispatch as per-signature [N] surfaces
(ops/program.py wave_statics). They are pure functions of (signature
table row, static node columns), so they stay valid across every
placement: a commit only moves the aggregate columns (used/npods/ports).

The scheduler's previous ad-hoc cache keyed on the STAGING generation,
which bumps on every aggregate write too — so every committed drain
cleared the whole cache and the expensive broadcast kernels re-ran for
every live signature on the next dispatch. This cache keys on
`ClusterState.statics_gen` instead (bumped only by full row writes, row
invalidations and shape growth), so surfaces are retained across the
steady-state drain cycle and recomputed only when a node's static
fields — or the signature table itself (`reset_count`) — actually move.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..analysis.rails import GLOBAL as RAILS


class SurfaceCache:
    """u (table row) → (static_mask, taint_raw, na_raw, s_img), each [N]."""

    def __init__(self, state, builder):
        self.state = state
        self.builder = builder
        self._rows: dict[int, tuple] = {}
        self._key = (-1, -1)      # (statics_gen, reset_count)
        # observability: generation-diff effectiveness (tests assert the
        # steady state retains; the metrics surface is the plan cache's)
        self.hits = 0
        self.misses = 0

    def invalidate(self) -> None:
        self._rows.clear()
        self._key = (-1, -1)

    def get(self, na, table, rows: tuple) -> list:
        """Cached surfaces for signature table rows `rows` (ordered,
        duplicates allowed), computing only the missing ones. `na` /
        `table` must reflect the current statics generation."""
        from ..ops.program import wave_statics

        key = (self.state.statics_gen, self.builder.reset_count)
        if self._key != key:
            # reset_count remaps every row id; statics_gen means some
            # node's static columns moved, which every [N] surface read
            self._rows.clear()
            self._key = key
        missing = [u for u in dict.fromkeys(rows)
                   if u not in self._rows]
        self.hits += len(dict.fromkeys(rows)) - len(missing)
        self.misses += len(missing)
        t = self.builder.table
        a = self.state.arrays
        has_taints = a is None or bool(
            ((a.taint_key != 0) & a.valid[:, None]).any())
        # host cache maintenance that runs lazily inside the dispatch
        # region: the row-index upload and per-row slice reads are part of
        # the declared host_cache contract, so open its allow window here
        # (no-op with the SanitizerRails gate off)
        with RAILS.declared("host_cache"):
            for c0 in range(0, len(missing), 4):
                chunk = missing[c0:c0 + 4]
                # pad only to the next pow2 row count — the common
                # one-new-sig case must not pay the 4-row kernel 4× over
                S = 1 if len(chunk) == 1 else (2 if len(chunk) == 2 else 4)
                wts = (chunk + [chunk[-1]] * S)[:S]
                # feature flags trim wave_statics to the kernels the rows
                # can actually exercise (an unconstrained signature skips
                # the padded taint/selector/image broadcasts entirely)
                feats = (has_taints,
                         any(bool(t.ns_sel_val[u].any()) or bool(t.aff_has[u])
                             or bool(t.pref_weight[u].any()) for u in chunk),
                         any(bool(t.img_containers[u]) for u in chunk))
                m_, tr, nr, si = wave_statics(
                    na, table, jnp.asarray(np.array(wts, np.int32)), feats)
                for k, u in enumerate(chunk):
                    self._rows[u] = (m_[k], tr[k], nr[k], si[k])
        return [self._rows[u] for u in rows]

    def stacked(self, na, table, rows: tuple) -> tuple:
        """Surfaces for `rows` stacked into ([S, N], ...) — the layout
        run_plan / run_wave_scan / run_gang consume."""
        per_row = self.get(na, table, rows)
        return tuple(jnp.stack([r[f] for r in per_row]) for f in range(4))
