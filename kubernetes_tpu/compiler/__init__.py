"""Drain compiler (ROADMAP item 4): any pod mix → one static device
program. `DrainCompiler.compile_drain` emits a `DrainPlan` over a pow2
signature lattice; `SurfaceCache` hoists the per-signature kernel
surfaces once per node-state statics generation. The compiled program
itself is ops/program.py `run_plan`."""

from .plan import (PLAN_CACHE_LIMIT, PLAN_MAX_SIGS, DrainCompiler,
                   DrainPlan)
from .surfaces import SurfaceCache

__all__ = ["DrainCompiler", "DrainPlan", "SurfaceCache", "PLAN_MAX_SIGS",
           "PLAN_CACHE_LIMIT"]
