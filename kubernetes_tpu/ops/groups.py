"""Group kernels: PodTopologySpread + InterPodAffinity on device.

The reference evaluates these plugins per pod with topologyPair→count hash
maps rebuilt every cycle (podtopologyspread/filtering.go:237-312,
interpodaffinity/filtering.go:204-273). The TPU form replaces each map with a
per-NODE count vector shared across nodes with equal topology value: for a
map keyed (topologyKey, value), `cnt[n] = map[(key, tv(n))]` — the device
never materializes the map, only its gather along the node axis. Counts ride
the scan carry and are updated after every placement with one vectorized
"same-topology-value" broadcast, which reproduces the reference's
AddPod/RemovePod incremental semantics (filtering.go:157-178, :322-341)
without any host round-trip.

Three layers:

- `GroupsDev` — static per-(signature, node) tensors: interned topology
  values per constraint/term, count-eligibility masks (node inclusion
  policies, common.go:43-57), and the pairwise signature match matrices that
  say whether a pod of signature u contributes to the counts of signature v.
  Recomputed host-side when the node set or the signature table changes.
- `GroupCarry` — the dynamic counts (spread match counts per DoNotSchedule /
  ScheduleAnyway constraint, the three inter-pod affinity maps of
  filtering.go:45-57, and the symmetric preferred-affinity score surface of
  scoring.go:81-124). Seeded host-side from the live snapshot by REUSING the
  host plugins' PreFilter/PreScore (guaranteeing seed parity), then carried
  forward on device.
- eval/update kernels called from the scan step in ops/program.py.

`GroupManager` (host) owns signature-row parsing, the match matrices, and
seeding. Pods whose constraints exceed the padded dims fall back to the host
oracle individually — never the whole batch.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import NamedTuple, Optional

import numpy as np

INT32_MAX = np.int32(2**31 - 1)

MAX_NODE_SCORE = 100

LABEL_HOSTNAME = "kubernetes.io/hostname"


# ---------------------------------------------------------------------------
# dims


@dataclass
class GroupDims:
    spread_constraints: int = 2   # SC — per action (DoNotSchedule / ScheduleAnyway)
    ipa_req_terms: int = 2        # TA — required affinity terms
    ipa_anti_terms: int = 2       # TAA — required anti-affinity terms
    ipa_cons_terms: int = 4       # CT — consumer-side preferred (score) terms
    ipa_plcd_terms: int = 6       # PT — placed-side score terms (req_a + preferred)


# ---------------------------------------------------------------------------
# device structures


class GroupsDev(NamedTuple):
    """Static per-table tensors ([U] = signature rows, [N] = node axis)."""

    # spread DoNotSchedule constraints (filtering.go)
    spr_f_active: object      # bool [U, SC]
    spr_f_max_skew: object    # i32 [U, SC]
    spr_f_self: object        # i32 [U, SC] — selfMatchNum (filtering.go:338)
    spr_f_tv: object          # i32 [U, SC, N] — node's interned topo value (0 = absent)
    spr_f_elig: object        # bool [U, SC, N] — counted node (keys + inclusion)
    spr_f_dom: object         # i32 [U, SC, N] — dense domain id (wave fold)
    # spread ScheduleAnyway constraints (scoring.go)
    spr_s_active: object      # bool [U, SC]
    spr_s_max_skew: object    # i32 [U, SC]
    spr_s_is_host: object     # bool [U, SC] — hostname key: per-node counts
    spr_s_tv: object          # i32 [U, SC, N]
    spr_s_elig: object        # bool [U, SC, N]
    spr_s_keys_ok: object     # bool [U, N] — all score topo keys present
    spr_s_dom: object         # i32 [U, SC, N] — dense domain id (first node idx w/ tv)
    # inter-pod affinity required terms (filtering.go)
    ipa_ra_active: object     # bool [U, TA]
    ipa_ra_tv: object         # i32 [U, TA, N]
    ipa_ra_dom: object        # i32 [U, TA, N] — dense domain id (wave fold)
    ipa_raa_active: object    # bool [U, TAA]
    ipa_raa_tv: object        # i32 [U, TAA, N]
    ipa_raa_dom: object       # i32 [U, TAA, N]
    ipa_self_all: object      # bool [U] — pod matches all own affinity terms
    # inter-pod affinity score terms (scoring.go)
    ipa_stc_tv: object        # i32 [U, CT, N] — consumer (incoming) pref terms
    ipa_stc_dom: object       # i32 [U, CT, N]
    ipa_stp_tv: object        # i32 [U, PT, N] — placed (existing) side terms
    ipa_stp_dom: object       # i32 [U, PT, N]
    # pairwise signature match matrices [placed-row, consumer-row, ...]
    m_spr_f: object           # bool [U, U, SC]
    m_spr_s: object           # bool [U, U, SC]
    m_ipa_a: object           # bool [U, U] — placed matches ALL consumer req terms
    m_ipa_aa: object          # bool [U, U, TAA] — per consumer anti term
    m_ipa_exist: object       # bool [U, U, TAA] — placed's anti term matches consumer
    w_stc: object             # i64 [U, U, CT] — signed weight (0 = no match)
    w_stp: object             # i64 [U, U, PT]


class GroupCarry(NamedTuple):
    """Dynamic counts riding the scan carry."""

    spr_f_cnt: object         # i32 [U, SC, N]
    spr_f_min_zero: object    # bool [U, SC] — eligible domains < minDomains
    spr_s_cnt: object         # i32 [U, SC, N]
    ipa_veto: object          # i32 [U, N] — existingAntiAffinityCounts per node
    ipa_a_cnt: object         # i32 [U, TA, N]
    ipa_a_total: object       # i64 [U] — affinityCounts map emptiness tracker
    ipa_aa_cnt: object        # i32 [U, TAA, N]
    ipa_score: object         # i64 [U, N] — symmetric topology score surface


class GroupFamilies(NamedTuple):
    """Static (jit-key) activation mask per constraint family.

    When a family is provably inactive — no signature row carries it and its
    seeded counts are zero — every one of its carry updates is identically
    zero and every one of its mask/score contributions is the identity, so
    the kernels skip it AT TRACE TIME. This matters enormously on TPU: a
    spread-only workload compiles a program with no inter-pod-affinity
    compute at all (≈5-8× per scan step), which is what XLA's constant
    folder would do if the tensors were compile-time constants — but keyed
    on a 5-bool mask instead of the tensor VALUES, so the executable is
    reused across group-state rebuilds.

    Pass-through of an inactive family's counts stays exact across later
    activation: a newly added signature row re-seeds its own counts from the
    live snapshot (scatter_new_rows), and existing rows' counts could only
    have received zero increments while the family was inactive."""

    spr_f: bool = True
    spr_s: bool = True
    ipa_req: bool = True
    ipa_anti: bool = True
    ipa_score: bool = True


ALL_FAMILIES = GroupFamilies()


# ---------------------------------------------------------------------------
# preemption dry-run: victim count tensors (spread deltas)


class DryRunSpread(NamedTuple):
    """PodTopologySpread victim-delta tensors for the batched preemption
    dry-run (ops/program.py dry_run_select_victims). [C] = candidate nodes,
    [V] = padded victim slots, [SC] = the preemptor's DoNotSchedule
    constraints. Built host-side by `spread_dry_run_tensors` from the SAME
    plugin PreFilter state the host oracle seeds, so the device check is by
    construction over the oracle's quantities."""

    max_skew: object      # i32 [SC]
    self_match: object    # i32 [SC] — selfMatchNum (filtering.go:338)
    min_zero: object      # bool [SC] — eligible domains < minDomains
    tv_ok: object         # bool [C, SC] — candidate has the topology key
    cnt0: object          # i32 [C, SC] — seeded match count in the
    #                       candidate's own topology domain
    other_min: object     # i32 [C, SC] — criticalPaths companion minimum
    #                       (see spread_dry_run_tensors)
    vic_match: object     # bool [C, V, SC] — victim moves constraint count


def spread_dry_run_tensors(s, pod, cand_infos, victims, c_pad: int,
                           v_pad: int) -> DryRunSpread:
    """Victim count tensors for the spread deltas of one preemption dry run.

    `s` is the preemptor's seeded podtopologyspread _PreFilterState (the
    host plugin's own PreFilter over ALL nodes), `cand_infos` the candidate
    NodeInfos and `victims[c]` each candidate's potential victims in
    reprieve order.

    criticalPaths closed form: a dry run only ever updates ONE topology
    value per candidate (all victims live on that node), so the evolving
    two-entry min tracker (filtering.go:97-136) reduces exactly to
    min(x, other) where x is the candidate domain's live count and `other`
    is n1 when the candidate's domain IS the tracked minimum (v0) and n0
    otherwise. This covers every update sequence including the
    untracked→tracked transition: once x dips below n1 the host tracker
    evicts its v1 and pairs (d, x) with (v0, n0), so later increases still
    compare against n0 — the same value the formula uses throughout."""
    from ..plugins.podtopologyspread import (_match_node_inclusion_policies,
                                             _node_has_all_topology_keys)

    cons = s.constraints
    SC = len(cons)
    max_skew = np.array([c.max_skew for c in cons], np.int32)
    self_match = np.array(
        [1 if c.selector.matches(pod.metadata.labels) else 0 for c in cons],
        np.int32)
    min_zero = np.array(
        [len(s.tp_value_to_match_num[j]) < c.min_domains
         for j, c in enumerate(cons)], bool)
    tv_ok = np.zeros((c_pad, SC), bool)
    cnt0 = np.zeros((c_pad, SC), np.int32)
    other_min = np.full((c_pad, SC), INT32_MAX, np.int32)
    vic_match = np.zeros((c_pad, v_pad, SC), bool)
    for ci, ni in enumerate(cand_infos):
        labels = ni.node.metadata.labels
        for j, c in enumerate(cons):
            tv = labels.get(c.topology_key)
            if tv is None:
                continue
            tv_ok[ci, j] = True
            cnt0[ci, j] = s.tp_value_to_match_num[j].get(tv, 0)
            cp = s.critical_paths[j]
            other_min[ci, j] = min(cp.n1 if tv == cp.v0 else cp.n0,
                                   int(INT32_MAX))
        # _update_with_pod gates EVERY constraint update on the node having
        # all topology keys (podtopologyspread.py:331) — mirror exactly
        if not _node_has_all_topology_keys(labels, cons):
            continue
        for j, c in enumerate(cons):
            if not _match_node_inclusion_policies(c, pod, ni):
                continue
            for vi, pi in enumerate(victims[ci]):
                vp = pi.pod
                if (vp.namespace == pod.namespace
                        and c.selector.matches(vp.metadata.labels)):
                    vic_match[ci, vi, j] = True
    return DryRunSpread(max_skew=max_skew, self_match=self_match,
                        min_zero=min_zero, tv_ok=tv_ok, cnt0=cnt0,
                        other_min=other_min, vic_match=vic_match)


# ---------------------------------------------------------------------------
# device kernels


class GroupView(NamedTuple):
    """One signature row's gathered group tensors — the shared input of
    `group_mask_view` / `group_scores_view`. Built by `view_of` (gather
    from GroupsDev/GroupCarry by tidx) on the scan path, and from the
    wave kernel's maintained in-scan counters (ops/program.py run_wave) —
    both paths evaluate the SAME formula code."""

    f_act: object       # bool [SC]
    f_skew: object      # i32 [SC]
    f_self: object      # i32 [SC]
    f_minz: object      # bool [SC]
    f_tv: object        # i32 [SC, N]
    f_elig: object      # bool [SC, N]
    f_cnt: object       # i32 [SC, N]
    s_act: object       # bool [SC]
    s_skew: object      # i32 [SC]
    s_is_host: object   # bool [SC]
    s_tv: object        # i32 [SC, N]
    s_keys_ok: object   # bool [N]
    s_dom: object       # i32 [SC, N]
    s_cnt: object       # i32 [SC, N]
    ra_act: object      # bool [TA]
    ra_tv: object       # i32 [TA, N]
    raa_act: object     # bool [TAA]
    raa_tv: object      # i32 [TAA, N]
    self_all: object    # bool
    veto: object        # i32 [N]
    a_cnt: object       # i32 [TA, N]
    a_total: object     # i64
    aa_cnt: object      # i32 [TAA, N]
    iscore: object      # i64 [N]


def view_of(gd: GroupsDev, gc: GroupCarry, tidx) -> GroupView:
    return GroupView(
        f_act=gd.spr_f_active[tidx], f_skew=gd.spr_f_max_skew[tidx],
        f_self=gd.spr_f_self[tidx], f_minz=gc.spr_f_min_zero[tidx],
        f_tv=gd.spr_f_tv[tidx], f_elig=gd.spr_f_elig[tidx],
        f_cnt=gc.spr_f_cnt[tidx],
        s_act=gd.spr_s_active[tidx], s_skew=gd.spr_s_max_skew[tidx],
        s_is_host=gd.spr_s_is_host[tidx], s_tv=gd.spr_s_tv[tidx],
        s_keys_ok=gd.spr_s_keys_ok[tidx], s_dom=gd.spr_s_dom[tidx],
        s_cnt=gc.spr_s_cnt[tidx],
        ra_act=gd.ipa_ra_active[tidx], ra_tv=gd.ipa_ra_tv[tidx],
        raa_act=gd.ipa_raa_active[tidx], raa_tv=gd.ipa_raa_tv[tidx],
        self_all=gd.ipa_self_all[tidx],
        veto=gc.ipa_veto[tidx], a_cnt=gc.ipa_a_cnt[tidx],
        a_total=gc.ipa_a_total[tidx], aa_cnt=gc.ipa_aa_cnt[tidx],
        iscore=gc.ipa_score[tidx])


def group_mask_view(v: GroupView, fam: GroupFamilies,
                    axis: Optional[str] = None):
    import jax.numpy as jnp
    from jax import lax

    n = v.veto.shape[-1]
    mask = jnp.ones((n,), bool)

    if fam.spr_f:
        # -- spread skew (DoNotSchedule)
        minv = jnp.min(jnp.where(v.f_elig, v.f_cnt, INT32_MAX), axis=-1)
        if axis is not None:
            minv = lax.pmin(minv, axis)
        # fewer eligible domains than minDomains (incl. zero domains) ⇒
        # min = 0 (filtering.go:66-77)
        minv = jnp.where(v.f_minz, 0, minv)
        ok = (v.f_cnt + v.f_self[:, None] - minv[:, None]
              <= v.f_skew[:, None])
        # node missing the topology key ⇒ UnschedulableAndUnresolvable
        mask &= jnp.all(~v.f_act[:, None] | ((v.f_tv != 0) & ok), axis=0)

    if fam.ipa_anti:
        # -- existing pods' required anti-affinity (filtering.go:204-228)
        mask &= v.veto == 0
        # -- incoming required anti-affinity
        mask &= ~jnp.any(v.raa_act[:, None] & (v.raa_tv != 0)
                         & (v.aa_cnt > 0), axis=0)

    if fam.ipa_req:
        # -- incoming required affinity (incl. the first-pod-in-series
        # escape hatch, filtering.go:381-397)
        tv_all = jnp.all(~v.ra_act[:, None] | (v.ra_tv != 0), axis=0)
        pods_exist = jnp.all(~v.ra_act[:, None] | (v.a_cnt > 0), axis=0)
        # sum==0 <=> len==0 for the reference's affinityCounts map: seed
        # entries are built by counting (strictly positive) and the device
        # path only ever increments — if a RemovePod-style decrement is
        # ever added, this test must switch to an explicit entry count
        escape = (v.a_total == 0) & v.self_all
        mask &= jnp.where(jnp.any(v.ra_act), tv_all & (pods_exist | escape),
                          True)

    return mask


def group_mask(gd: GroupsDev, gc: GroupCarry, tidx, axis: Optional[str] = None,
               fam: Optional[GroupFamilies] = None):
    """Feasibility over the node axis for the pod signature `tidx`:
    spread skew check (filtering.go:314-360) AND the three inter-pod
    affinity checks (filtering.go:405-432). `fam` statically skips families
    whose contribution is provably the identity (see GroupFamilies)."""
    return group_mask_view(view_of(gd, gc, tidx), fam or ALL_FAMILIES, axis)


def group_reason_masks(gd: GroupsDev, gc: GroupCarry, tidx,
                       fam: Optional[GroupFamilies] = None,
                       axis: Optional[str] = None):
    """Diagnosis companion to `group_mask`: the SAME formulas, split into
    the five per-node failure masks the host filters report —
    (spr_missing, spr_skew, aff_fail, anti_fail, exist_fail), each bool
    [N]. Spread attributes each node to its FIRST failing constraint (the
    host filter iterates constraints in order and returns on the first
    violation, podtopologyspread filtering); the caller layers these under
    the host's plugin order (spread before inter-pod affinity)."""
    import jax.numpy as jnp
    from jax import lax

    fam = fam or ALL_FAMILIES
    v = view_of(gd, gc, tidx)
    n = v.veto.shape[-1]
    false = jnp.zeros((n,), bool)
    spr_missing = spr_skew = aff_fail = anti_fail = exist_fail = false

    if fam.spr_f:
        minv = jnp.min(jnp.where(v.f_elig, v.f_cnt, INT32_MAX), axis=-1)
        if axis is not None:
            minv = lax.pmin(minv, axis)
        minv = jnp.where(v.f_minz, 0, minv)
        ok = (v.f_cnt + v.f_self[:, None] - minv[:, None]
              <= v.f_skew[:, None])
        missing_c = v.f_act[:, None] & (v.f_tv == 0)        # [SC, N]
        fail_c = v.f_act[:, None] & ((v.f_tv == 0) | ~ok)
        any_fail = jnp.any(fail_c, axis=0)
        first_c = jnp.argmax(fail_c, axis=0)                # [N]
        first_missing = jnp.take_along_axis(
            missing_c, first_c[None, :], axis=0)[0]
        spr_missing = any_fail & first_missing
        spr_skew = any_fail & ~first_missing

    if fam.ipa_req:
        tv_all = jnp.all(~v.ra_act[:, None] | (v.ra_tv != 0), axis=0)
        pods_exist = jnp.all(~v.ra_act[:, None] | (v.a_cnt > 0), axis=0)
        escape = (v.a_total == 0) & v.self_all
        aff_fail = jnp.any(v.ra_act) & ~(tv_all & (pods_exist | escape))

    if fam.ipa_anti:
        anti_fail = jnp.any(v.raa_act[:, None] & (v.raa_tv != 0)
                            & (v.aa_cnt > 0), axis=0)
        exist_fail = v.veto != 0

    return spr_missing, spr_skew, aff_fail, anti_fail, exist_fail


def group_scores_view(w_spread: int, w_ipa: int, v: GroupView, feasible,
                      fam: GroupFamilies, axis: Optional[str] = None,
                      n_global: Optional[int] = None):
    import jax.numpy as jnp
    from jax import lax

    N = feasible.shape[0]
    if n_global is None:
        n_global = N

    def _gmin(x):
        return lax.pmin(x, axis) if axis is not None else x

    def _gmax(x):
        return lax.pmax(x, axis) if axis is not None else x

    def _gsum(x):
        return lax.psum(x, axis) if axis is not None else x

    if not fam.spr_s and not fam.ipa_score:
        return jnp.zeros((N,), jnp.int64)
    if not fam.spr_s:
        return w_ipa * _ipa_norm_scores(v.iscore, feasible, _gmin, _gmax)
    # ---- PodTopologySpread (scoring.go:199-271) ----
    has_s = jnp.any(v.s_act)
    scored = feasible & v.s_keys_ok
    npart = _gsum(jnp.sum(scored))
    # per-constraint domain count among scored nodes (topologyNormalizingWeight)
    dom = v.s_dom                                   # [SC, N]
    flags = jnp.zeros((dom.shape[0], n_global), jnp.int32)
    flags = flags.at[jnp.arange(dom.shape[0])[:, None], dom].max(
        jnp.broadcast_to(scored.astype(jnp.int32), dom.shape))
    if axis is not None:
        flags = lax.psum(flags, axis)
    distinct = jnp.sum(flags > 0, axis=1)           # [SC]
    size = jnp.where(v.s_is_host, npart, distinct)
    weight = jnp.log(size.astype(jnp.float64) + 2.0)  # [SC]
    contrib = jnp.where(
        v.s_act[:, None] & (v.s_tv != 0),
        v.s_cnt.astype(jnp.float64) * weight[:, None]
        + (v.s_skew[:, None] - 1).astype(jnp.float64),
        0.0)
    raw = jnp.round(jnp.sum(contrib, axis=0)).astype(jnp.int64)  # [N]
    # normalize (host plugin normalize_scores: MAX·(max+min−s)//max)
    minv = _gmin(jnp.min(jnp.where(scored, raw, INT32_MAX)))
    maxv = _gmax(jnp.max(jnp.where(scored, raw, 0)))
    norm = jnp.where(maxv == 0, MAX_NODE_SCORE,
                     MAX_NODE_SCORE * (maxv + minv - raw) // jnp.maximum(maxv, 1))
    spread_score = jnp.where(has_s & scored, norm, 0)
    # ignored (missing-keys) nodes score 0; infeasible rows are masked later

    if not fam.ipa_score:
        return w_spread * spread_score
    return (w_spread * spread_score
            + w_ipa * _ipa_norm_scores(v.iscore, feasible, _gmin, _gmax))


def group_scores(w_spread: int, w_ipa: int, gd: GroupsDev, gc: GroupCarry,
                 tidx, feasible, axis: Optional[str] = None,
                 n_global: Optional[int] = None,
                 fam: Optional[GroupFamilies] = None):
    """Weighted PodTopologySpread + InterPodAffinity score over the node
    axis, already normalized per the host plugins' Normalize formulas.
    `feasible` is the FULL filtered set (all plugins), matching the host
    runtime's normalize-over-filtered-list semantics. `n_global` is the
    unsharded node-axis length (defaults to the local length)."""
    return group_scores_view(w_spread, w_ipa, view_of(gd, gc, tidx),
                             feasible, fam or ALL_FAMILIES, axis, n_global)


def _ipa_norm_scores(s, feasible, _gmin, _gmax):
    """InterPodAffinity normalized score surface (scoring.go:263-293).
    `s`: the gathered i64 [N] symmetric topology score surface."""
    import jax.numpy as jnp

    big = jnp.iinfo(jnp.int64).max
    minv2 = _gmin(jnp.min(jnp.where(feasible, s, big)))
    maxv2 = _gmax(jnp.max(jnp.where(feasible, s, -big)))
    diff = maxv2 - minv2
    return jnp.where(
        diff > 0,
        (MAX_NODE_SCORE * (s - minv2).astype(jnp.float64)
         / jnp.maximum(diff, 1).astype(jnp.float64)),
        0.0).astype(jnp.int64)


def group_update(gd: GroupsDev, gc: GroupCarry, tidx, pick, is_chosen, gate,
                 fam: Optional[GroupFamilies] = None):
    """Carry update after placing a pod of signature `tidx`.

    `pick(arr)` extracts `arr[..., b]` for the chosen node b (the sharded
    path substitutes a cross-shard broadcast); `is_chosen` is bool[N_local]
    marking the chosen node's row (all-false on non-owning shards); `gate` is
    the placement-happened scalar. Mirrors a fresh recount after the
    placement: counts are additive over pods and node labels are static, so
    the incremental broadcast equals the reference's per-cycle rebuild."""
    import jax.numpy as jnp

    fam = fam or ALL_FAMILIES
    u = tidx
    gate_i = gate.astype(jnp.int32)
    spr_f_cnt, spr_s_cnt = gc.spr_f_cnt, gc.spr_s_cnt
    ipa_veto, ipa_a_cnt = gc.ipa_veto, gc.ipa_a_cnt
    ipa_a_total, ipa_aa_cnt = gc.ipa_a_total, gc.ipa_aa_cnt
    ipa_score = gc.ipa_score

    if fam.spr_f:
        # spread filter counts: +1 at every node sharing the chosen node's
        # topology value, per consumer constraint the placed pod matches,
        # iff the chosen node is count-eligible for that constraint
        tvb_f = pick(gd.spr_f_tv)                   # [U, SC]
        eligb_f = pick(gd.spr_f_elig)               # [U, SC]
        inc_f = ((gd.m_spr_f[u] & eligb_f)[:, :, None]
                 & (gd.spr_f_tv == tvb_f[:, :, None])
                 & (tvb_f[:, :, None] != 0))
        spr_f_cnt = gc.spr_f_cnt + gate_i * inc_f.astype(jnp.int32)

    if fam.spr_s:
        # spread score counts: hostname constraints count the node's own
        # pods (scoring.go score()); other keys share by topology value
        tvb_s = pick(gd.spr_s_tv)
        eligb_s = pick(gd.spr_s_elig)
        is_b = is_chosen[None, None, :]             # [1, 1, N]
        share_s = jnp.where(gd.spr_s_is_host[:, :, None], is_b,
                            (gd.spr_s_tv == tvb_s[:, :, None])
                            & (tvb_s[:, :, None] != 0))
        gate_c = jnp.where(gd.spr_s_is_host, gd.m_spr_s[u],
                           gd.m_spr_s[u] & eligb_s)
        spr_s_cnt = gc.spr_s_cnt + gate_i * (
            gate_c[:, :, None] & share_s).astype(jnp.int32)

    if fam.ipa_anti:
        # existing-anti veto: the placed pod's own required anti terms add
        # a (term.key, tv(b)) pair for every consumer signature they match
        tvb_p_anti = pick(gd.ipa_raa_tv)[u]         # [TAA]
        share_anti = ((gd.ipa_raa_tv[u] == tvb_p_anti[:, None])
                      & (tvb_p_anti[:, None] != 0))  # [TAA, N]
        delta_veto = jnp.sum(
            gd.m_ipa_exist[u][:, :, None] & share_anti[None],
            axis=1).astype(jnp.int32)               # [U, N]
        ipa_veto = gc.ipa_veto + gate_i * delta_veto
        # incoming-anti counts (per consumer term)
        tvb_aa = pick(gd.ipa_raa_tv)                # [U, TAA]
        share_aa = ((gd.ipa_raa_tv == tvb_aa[:, :, None])
                    & (tvb_aa[:, :, None] != 0))
        inc_aa = gd.m_ipa_aa[u][:, :, None] & share_aa
        ipa_aa_cnt = gc.ipa_aa_cnt + gate_i * inc_aa.astype(jnp.int32)

    if fam.ipa_req:
        # incoming-affinity counts: placed pod matching ALL of a consumer's
        # required terms bumps each term's (key, tv(b)) pair
        tvb_a = pick(gd.ipa_ra_tv)                  # [U, TA]
        share_a = ((gd.ipa_ra_tv == tvb_a[:, :, None])
                   & (tvb_a[:, :, None] != 0))
        inc_a = ((gd.m_ipa_a[u][:, None] & gd.ipa_ra_active)[:, :, None]
                 & share_a)
        ipa_a_cnt = gc.ipa_a_cnt + gate_i * inc_a.astype(jnp.int32)
        ipa_a_total = gc.ipa_a_total + (
            gate_i * gd.m_ipa_a[u]
            * jnp.sum(gd.ipa_ra_active & (tvb_a != 0), axis=1)
        ).astype(jnp.int64)

    if fam.ipa_score:
        # symmetric score surface: consumer-side preferred terms matching
        # the placed pod, plus placed-side (req×hardWeight + preferred)
        # terms matching the consumer (scoring.go:81-124)
        tvb_c = pick(gd.ipa_stc_tv)                 # [U, CT]
        share_c = ((gd.ipa_stc_tv == tvb_c[:, :, None])
                   & (tvb_c[:, :, None] != 0))
        d_cons = jnp.sum(gd.w_stc[u][:, :, None] * share_c, axis=1)  # [U, N]
        tvb_p = pick(gd.ipa_stp_tv)[u]              # [PT]
        share_p = (gd.ipa_stp_tv[u] == tvb_p[:, None]) & (tvb_p[:, None] != 0)
        d_plcd = jnp.sum(gd.w_stp[u][:, :, None] * share_p[None], axis=1)
        ipa_score = gc.ipa_score + gate.astype(jnp.int64) * (d_cons + d_plcd)

    return GroupCarry(spr_f_cnt=spr_f_cnt, spr_f_min_zero=gc.spr_f_min_zero,
                      spr_s_cnt=spr_s_cnt, ipa_veto=ipa_veto,
                      ipa_a_cnt=ipa_a_cnt, ipa_a_total=ipa_a_total,
                      ipa_aa_cnt=ipa_aa_cnt, ipa_score=ipa_score)


# ---------------------------------------------------------------------------
# host side: row parsing, match matrices, node data, seeding


@dataclass
class GroupRowInfo:
    """Host-parsed group constraints for one signature row."""

    pod: object                    # representative pod (signature-identical)
    f_constraints: list            # spread _Constraint, DoNotSchedule
    s_constraints: list            # spread _Constraint, ScheduleAnyway
    req_a: list                    # merged-ns ParsedTerm (incoming affinity)
    req_aa: list                   # merged-ns ParsedTerm (incoming anti)
    req_aa_raw: list               # raw ParsedTerm (existing-pod side)
    stc_terms: list                # [(ParsedTerm, ±weight)] consumer score terms
    stp_terms: list                # [(ParsedTerm, ±weight)] placed score terms
    self_all: bool

    @property
    def has_groups(self) -> bool:
        return bool(self.f_constraints or self.s_constraints or self.req_a
                    or self.req_aa or self.stc_terms or self.stp_terms)


class GroupManager:
    """Owns per-signature-row group data + pairwise match matrices.

    Parsing and matching REUSE the host plugins' code paths
    (podtopologyspread._parse_constraints / _count_pods_match_selector,
    interpodaffinity.parse_pod_affinity_terms / ParsedTerm.matches), so the
    device program's inputs are by construction the same quantities the host
    oracle computes."""

    def __init__(self, state, spread_plugin=None, ipa_plugin=None,
                 dims: Optional[GroupDims] = None, table_rows: int = 16):
        from ..plugins.interpodaffinity import InterPodAffinity
        from ..plugins.podtopologyspread import PodTopologySpread

        from ..ingest.groupcols import NodeLabelColumns
        self.state = state
        self.pts = spread_plugin or PodTopologySpread()
        self.ipa = ipa_plugin or InterPodAffinity()
        self.dims = dims or GroupDims()
        self.rows: list[Optional[GroupRowInfo]] = []
        self._alloc(table_rows)
        self.group_row_count = 0   # rows with any group constraints
        # per-statics-generation columnar label views shared by node_data
        # and seed_counts (ingest/groupcols.py): the per-call O(N) tv /
        # dom / presence walks now run once per node-state change
        self.cols = NodeLabelColumns(state)

    # -- storage --------------------------------------------------------------

    def _alloc(self, U: int) -> None:
        d = self.dims
        self.U = U
        self.spr_f_active = np.zeros((U, d.spread_constraints), bool)
        self.spr_f_max_skew = np.zeros((U, d.spread_constraints), np.int32)
        self.spr_f_self = np.zeros((U, d.spread_constraints), np.int32)
        self.spr_s_active = np.zeros((U, d.spread_constraints), bool)
        self.spr_s_max_skew = np.zeros((U, d.spread_constraints), np.int32)
        self.spr_s_is_host = np.zeros((U, d.spread_constraints), bool)
        self.ipa_ra_active = np.zeros((U, d.ipa_req_terms), bool)
        self.ipa_raa_active = np.zeros((U, d.ipa_anti_terms), bool)
        self.ipa_self_all = np.zeros((U,), bool)
        self.m_spr_f = np.zeros((U, U, d.spread_constraints), bool)
        self.m_spr_s = np.zeros((U, U, d.spread_constraints), bool)
        self.m_ipa_a = np.zeros((U, U), bool)
        self.m_ipa_aa = np.zeros((U, U, d.ipa_anti_terms), bool)
        self.m_ipa_exist = np.zeros((U, U, d.ipa_anti_terms), bool)
        self.w_stc = np.zeros((U, U, d.ipa_cons_terms), np.int64)
        self.w_stp = np.zeros((U, U, d.ipa_plcd_terms), np.int64)
        # interaction graph: interacts[p, c] — placing a pod of row p can
        # move row c's group counts/scores (the build-time signature the
        # wave scheduler consults; state/batch.py BatchBuilder.wave_info)
        self.interacts = np.zeros((U, U), bool)

    # pairwise [U, U, ...] matrices vs per-row [U, ...] arrays: classified
    # by NAME, never by shape — a table_rows value that coincides with a
    # term dimension must not flip a per-row array into the pairwise path
    # (cf. sharding.py's _GD_NODE_FIELDS approach)
    _PAIRWISE_FIELDS = frozenset(
        {"m_spr_f", "m_spr_s", "m_ipa_a", "m_ipa_aa", "m_ipa_exist",
         "w_stc", "w_stp"})
    _ROW_FIELDS = ("spr_f_active", "spr_f_max_skew", "spr_f_self",
                   "spr_s_active", "spr_s_max_skew", "spr_s_is_host",
                   "ipa_ra_active", "ipa_raa_active", "ipa_self_all")

    def grow(self, U: int) -> None:
        names = (self._ROW_FIELDS + tuple(self._PAIRWISE_FIELDS)
                 + ("interacts",))
        old = {name: getattr(self, name) for name in names}
        u0 = len(self.rows)
        self._alloc(U)
        for name, arr in old.items():
            new = getattr(self, name)
            if name in self._PAIRWISE_FIELDS or name == "interacts":
                new[:u0, :u0] = arr[:u0, :u0]
            else:
                new[:u0] = arr[:u0]

    def reset(self) -> None:
        self.rows.clear()
        self._alloc(self.U)
        self.group_row_count = 0

    # -- row addition ---------------------------------------------------------

    def add_row(self, u: int, pod) -> None:
        """Parse + store row u; raises BatchCapacityError when the pod's
        constraints exceed the padded dims (that pod goes to the host
        oracle individually)."""
        from ..api.types import UnsatisfiableConstraintAction as UCA
        from ..plugins.interpodaffinity import (
            WeightedTerm, _pod_matches_all_affinity_terms,
            parse_pod_affinity_terms)
        from ..state.batch import BatchCapacityError

        d = self.dims
        f_cons = self.pts._get_constraints(pod, UCA.DO_NOT_SCHEDULE.value)
        s_cons = self.pts._get_constraints(pod, UCA.SCHEDULE_ANYWAY.value)
        if (self.pts.system_defaulted
                and not pod.spec.topology_spread_constraints
                and (f_cons or s_cons)):
            # relaxed require_all semantics of system defaulting have no
            # tensor form (scoring.go requireAllTopologies=false)
            raise BatchCapacityError("system-defaulted spread: host path")
        if len(f_cons) > d.spread_constraints or len(s_cons) > d.spread_constraints:
            raise BatchCapacityError("too many spread constraints")

        req_a, req_aa_raw, pref_a, pref_aa = parse_pod_affinity_terms(pod)
        if self.ipa.args.ignore_preferred_terms_of_existing_pods and (
                req_a or req_aa_raw or pref_a or pref_aa):
            raise BatchCapacityError("ignorePreferredTermsOfExistingPods: host path")
        req_a_m = [self.ipa._merge_term_namespaces(t) for t in req_a]
        req_aa_m = [self.ipa._merge_term_namespaces(t) for t in req_aa_raw]
        if len(req_a_m) > d.ipa_req_terms or len(req_aa_m) > d.ipa_anti_terms:
            raise BatchCapacityError("too many inter-pod affinity terms")
        # consumer-side score terms: incoming pod's MERGED preferred terms
        stc = ([(WeightedTerm(self.ipa._merge_term_namespaces(w.term), w.weight).term,
                 w.weight) for w in pref_a]
               + [(self.ipa._merge_term_namespaces(w.term), -w.weight)
                  for w in pref_aa])
        # placed-side score terms: RAW required (× hard weight) + preferred
        hw = self.ipa.args.hard_pod_affinity_weight
        stp = ([(t, hw) for t in req_a] if hw > 0 else [])
        stp += [(w.term, w.weight) for w in pref_a]
        stp += [(w.term, -w.weight) for w in pref_aa]
        if len(stc) > d.ipa_cons_terms or len(stp) > d.ipa_plcd_terms:
            raise BatchCapacityError("too many preferred affinity terms")

        info = GroupRowInfo(
            pod=pod, f_constraints=f_cons, s_constraints=s_cons,
            req_a=req_a_m, req_aa=req_aa_m, req_aa_raw=req_aa_raw,
            stc_terms=stc, stp_terms=stp,
            self_all=_pod_matches_all_affinity_terms(req_a_m, pod))
        while len(self.rows) <= u:
            self.rows.append(None)
        self.rows[u] = info
        if info.has_groups:
            self.group_row_count += 1

        # per-row scalars
        for j, c in enumerate(f_cons):
            self.spr_f_active[u, j] = True
            self.spr_f_max_skew[u, j] = c.max_skew
            self.spr_f_self[u, j] = 1 if c.selector.matches(pod.metadata.labels) else 0
        for j, c in enumerate(s_cons):
            self.spr_s_active[u, j] = True
            self.spr_s_max_skew[u, j] = c.max_skew
            self.spr_s_is_host[u, j] = c.topology_key == LABEL_HOSTNAME
        for t in range(len(req_a_m)):
            self.ipa_ra_active[u, t] = True
        for t in range(len(req_aa_m)):
            self.ipa_raa_active[u, t] = True
        self.ipa_self_all[u] = info.self_all

        # pairwise match matrices vs every existing row (both directions)
        for v, other in enumerate(self.rows):
            if other is None:
                continue
            self._fill_pair(u, info, v, other)
            if v != u:
                self._fill_pair(v, other, u, info)

    def _fill_pair(self, pu: int, placed: GroupRowInfo,
                   cu: int, cons: GroupRowInfo) -> None:
        """[placed → consumer] match entries."""
        from ..plugins.interpodaffinity import _pod_matches_all_affinity_terms
        from ..plugins.podtopologyspread import (_count_pods_match_selector,
                                                 _selector_empty)

        ppod, cpod = placed.pod, cons.pod
        same_ns = ppod.namespace == cpod.namespace
        for j, c in enumerate(cons.f_constraints):
            self.m_spr_f[pu, cu, j] = (same_ns and not _selector_empty(c.selector)
                                       and c.selector.matches(ppod.metadata.labels))
        for j, c in enumerate(cons.s_constraints):
            self.m_spr_s[pu, cu, j] = (same_ns and not _selector_empty(c.selector)
                                       and c.selector.matches(ppod.metadata.labels))
        self.m_ipa_a[pu, cu] = _pod_matches_all_affinity_terms(cons.req_a, ppod)
        for t, term in enumerate(cons.req_aa):
            self.m_ipa_aa[pu, cu, t] = term.matches(ppod, None)
        ns_labels = self.ipa.ns_lister.labels_of(cpod.namespace)
        for t, term in enumerate(placed.req_aa_raw):
            self.m_ipa_exist[pu, cu, t] = term.matches(cpod, ns_labels)
        for t, (term, w) in enumerate(cons.stc_terms):
            self.w_stc[pu, cu, t] = w if term.matches(ppod, None) else 0
        for t, (term, w) in enumerate(placed.stp_terms):
            self.w_stp[pu, cu, t] = w if term.matches(cpod, ns_labels) else 0
        self.interacts[pu, cu] = bool(
            self.m_spr_f[pu, cu].any() or self.m_spr_s[pu, cu].any()
            or self.m_ipa_a[pu, cu] or self.m_ipa_aa[pu, cu].any()
            or self.m_ipa_exist[pu, cu].any()
            or self.w_stc[pu, cu].any() or self.w_stp[pu, cu].any())

    def any_groups(self) -> bool:
        return self.group_row_count > 0

    # -- node-dependent statics ----------------------------------------------

    def _node_rows(self, snapshot) -> list:
        """[(row index, NodeInfo)] for the snapshot's nodes — built once
        per build/scatter and shared between node_data and seed_counts
        (the 2×O(N) name-lookup walks used to run per call)."""
        st = self.state
        N = st.dims.nodes
        nis = [(st.node_index.get(ni.name), ni)
               for ni in snapshot.node_info_list]
        return [(idx, ni) for idx, ni in nis if idx is not None and idx < N]

    def node_data(self, snapshot, rows: range, nis=None):
        """tv / eligibility / domain arrays for the given row slice against
        the CURRENT node set, laid out in ClusterState row order. Returns a
        dict of numpy arrays shaped like the matching GroupsDev fields but
        with a leading axis of len(rows)."""
        from ..plugins.node_basics import find_matching_untolerated_taint
        from ..plugins.nodeaffinity import required_node_affinity_matches
        from ..plugins.podtopologyspread import HONOR

        d = self.dims
        st = self.state
        N = st.dims.nodes
        SC, TA, TAA = d.spread_constraints, d.ipa_req_terms, d.ipa_anti_terms
        CT, PT = d.ipa_cons_terms, d.ipa_plcd_terms
        R = len(rows)
        out = dict(
            spr_f_tv=np.zeros((R, SC, N), np.int32),
            spr_f_elig=np.zeros((R, SC, N), bool),
            spr_f_dom=np.zeros((R, SC, N), np.int32),
            spr_s_tv=np.zeros((R, SC, N), np.int32),
            spr_s_elig=np.zeros((R, SC, N), bool),
            spr_s_keys_ok=np.zeros((R, N), bool),
            spr_s_dom=np.zeros((R, SC, N), np.int32),
            ipa_ra_tv=np.zeros((R, TA, N), np.int32),
            ipa_ra_dom=np.zeros((R, TA, N), np.int32),
            ipa_raa_tv=np.zeros((R, TAA, N), np.int32),
            ipa_raa_dom=np.zeros((R, TAA, N), np.int32),
            ipa_stc_tv=np.zeros((R, CT, N), np.int32),
            ipa_stc_dom=np.zeros((R, CT, N), np.int32),
            ipa_stp_tv=np.zeros((R, PT, N), np.int32),
            ipa_stp_dom=np.zeros((R, PT, N), np.int32),
        )
        if nis is None:
            nis = self._node_rows(snapshot)
        # persistent per-statics-generation columns (ingest/groupcols.py):
        # a topology key's interned tv vector is a property of the node
        # set, not of the row OR the call — the O(N) label walk now runs
        # once per node-state change instead of once per build_dev call
        # (the scheduler.py reseed/host-greedy/diagnosis sites all land
        # here), and once it did run, every row/constraint/term shares it.
        cols = self.cols.sync(nis)
        tv_vec = cols.tv
        dom_of_key = cols.dom

        def keys_ok_vec(keys: list[str]) -> np.ndarray:
            return cols.keys_ok(tuple(keys))

        def elig_vec(c, pod, keys: list[str]) -> np.ndarray:
            """Count-eligibility per node (common.go:43-57). The common
            case — no required node affinity on the pod, taints policy
            Ignore — is pure vector math; only HONOR policies walk nodes."""
            ok = keys_ok_vec(keys)
            trivial_affinity = (
                c.node_affinity_policy != HONOR
                or (not pod.spec.node_selector
                    and not (pod.spec.affinity
                             and pod.spec.affinity.node_affinity
                             and pod.spec.affinity.node_affinity.required)))
            if trivial_affinity and c.node_taints_policy != HONOR:
                return ok
            ok = ok.copy()   # keys_ok vectors are cached: never mutate
            for idx, ni in nis:
                if not ok[idx]:
                    continue
                labels = ni.node.metadata.labels
                good = True
                if c.node_affinity_policy == HONOR and not trivial_affinity:
                    good = required_node_affinity_matches(pod, labels,
                                                          ni.name)
                if good and c.node_taints_policy == HONOR:
                    good = find_matching_untolerated_taint(
                        ni.node.spec.taints, pod.spec.tolerations,
                        ("NoSchedule", "NoExecute")) is None
                ok[idx] = good
            return ok

        for r, u in enumerate(rows):
            info = self.rows[u] if u < len(self.rows) else None
            if info is None:
                continue
            pod = info.pod
            # spread filter
            if info.f_constraints:
                keys = [c.topology_key for c in info.f_constraints]
                for j, c in enumerate(info.f_constraints):
                    out["spr_f_tv"][r, j] = tv_vec(c.topology_key)
                    out["spr_f_dom"][r, j] = dom_of_key(c.topology_key)
                    out["spr_f_elig"][r, j] = elig_vec(c, pod, keys)
            # spread score
            if info.s_constraints:
                keys = [c.topology_key for c in info.s_constraints]
                out["spr_s_keys_ok"][r] = keys_ok_vec(keys)
                for j, c in enumerate(info.s_constraints):
                    out["spr_s_tv"][r, j] = tv_vec(c.topology_key)
                    out["spr_s_dom"][r, j] = dom_of_key(c.topology_key)
                    out["spr_s_elig"][r, j] = elig_vec(c, pod, keys)
            # inter-pod affinity term topology values
            for t, term in enumerate(info.req_a):
                out["ipa_ra_tv"][r, t] = tv_vec(term.topology_key)
                out["ipa_ra_dom"][r, t] = dom_of_key(term.topology_key)
            for t, term in enumerate(info.req_aa):
                out["ipa_raa_tv"][r, t] = tv_vec(term.topology_key)
                out["ipa_raa_dom"][r, t] = dom_of_key(term.topology_key)
            for t, (term, _w) in enumerate(info.stc_terms):
                out["ipa_stc_tv"][r, t] = tv_vec(term.topology_key)
                out["ipa_stc_dom"][r, t] = dom_of_key(term.topology_key)
            for t, (term, _w) in enumerate(info.stp_terms):
                out["ipa_stp_tv"][r, t] = tv_vec(term.topology_key)
                out["ipa_stp_dom"][r, t] = dom_of_key(term.topology_key)
        return out

    # -- count seeding --------------------------------------------------------

    def seed_counts(self, snapshot, rows: range, nis=None):
        """Count arrays for the given rows from the LIVE snapshot, computed
        by running the host plugins' PreFilter/PreScore on the representative
        pod — the device then carries these forward incrementally."""
        from ..framework.interface import CycleState
        from ..plugins import interpodaffinity as ipa_mod
        from ..plugins import podtopologyspread as pts_mod

        d = self.dims
        st = self.state
        N = st.dims.nodes
        SC, TA, TAA = d.spread_constraints, d.ipa_req_terms, d.ipa_anti_terms
        R = len(rows)
        out = dict(
            spr_f_cnt=np.zeros((R, SC, N), np.int32),
            spr_f_min_zero=np.zeros((R, SC), bool),
            spr_s_cnt=np.zeros((R, SC, N), np.int32),
            ipa_veto=np.zeros((R, N), np.int32),
            ipa_a_cnt=np.zeros((R, TA, N), np.int32),
            ipa_a_total=np.zeros((R,), np.int64),
            ipa_aa_cnt=np.zeros((R, TAA, N), np.int32),
            ipa_score=np.zeros((R, N), np.int64),
        )
        node_list = snapshot.node_info_list
        if nis is None:
            nis = self._node_rows(snapshot)
        # the count surfaces are still computed by the host plugins' own
        # PreFilter/PreScore (shared-code parity contract, class doc) —
        # but the per-NODE scatter of every count map now rides the
        # columnar label store: one sorted-search gather over interned
        # topology-value ids per (row, constraint/term) instead of an
        # O(nodes) Python dict-probe walk per signature
        from ..ingest.groupcols import gather_ids
        cols = self.cols.sync(nis)

        for r, u in enumerate(rows):
            info = self.rows[u] if u < len(self.rows) else None
            if info is None:
                continue
            pod = info.pod
            # spread DoNotSchedule counts via the plugin's own PreFilter
            if info.f_constraints:
                cs = CycleState()
                self.pts.pre_filter(cs, pod, node_list)
                s = cs.read_or_none(pts_mod._PRE_FILTER_KEY)
                if s is not None:
                    for j, c in enumerate(s.constraints):
                        cnts = s.tp_value_to_match_num[j]
                        out["spr_f_min_zero"][r, j] = len(cnts) < c.min_domains
                        if not any(cnts.values()):
                            continue    # all-zero seed: the array is zeros
                        out["spr_f_cnt"][r, j] = gather_ids(
                            cols.tv(c.topology_key),
                            cols.value_ids(c.topology_key, cnts), np.int32)
            # spread ScheduleAnyway counts: hostname keys per node, others
            # accumulated per topology value over count-eligible nodes
            for j, c in enumerate(info.s_constraints):
                if c.topology_key == LABEL_HOSTNAME:
                    for idx, ni in nis:
                        out["spr_s_cnt"][r, j, idx] = \
                            pts_mod._count_pods_match_selector(
                                ni.pods, c.selector, pod.namespace)
                    continue
                keys = [cc.topology_key for cc in info.s_constraints]
                by_tv: dict[str, int] = {}
                for idx, ni in nis:
                    labels = ni.node.metadata.labels
                    if not all(k in labels for k in keys):
                        continue
                    if not pts_mod._match_node_inclusion_policies(c, pod, ni):
                        continue
                    v = labels[c.topology_key]
                    by_tv[v] = by_tv.get(v, 0) + \
                        pts_mod._count_pods_match_selector(
                            ni.pods, c.selector, pod.namespace)
                if not any(by_tv.values()):
                    continue
                out["spr_s_cnt"][r, j] = gather_ids(
                    cols.tv(c.topology_key),
                    cols.value_ids(c.topology_key, by_tv), np.int32)
            # inter-pod affinity maps via the plugin's PreFilter. Empty
            # count maps (the common fresh-workload case) skip their
            # gathers outright — the arrays are zeros.
            cs = CycleState()
            self.ipa.pre_filter(cs, pod, node_list)
            s = cs.read_or_none(ipa_mod._PRE_FILTER_KEY)
            if s is not None:
                out["ipa_a_total"][r] = sum(s.affinity_counts.values())
                if s.existing_anti_affinity_counts:
                    # counts keyed (label key, value): a node contributes
                    # each (k, v) it carries — per distinct k, one gather
                    by_key: dict = {}
                    for (lk, lv), c0 in \
                            s.existing_anti_affinity_counts.items():
                        by_key.setdefault(lk, {})[lv] = c0
                    veto = out["ipa_veto"][r]
                    for lk, vals in by_key.items():
                        veto += gather_ids(cols.tv(lk),
                                           cols.value_ids(lk, vals),
                                           np.int32)
                if s.affinity_counts:
                    by_key = {}
                    for (tk, tv), c0 in s.affinity_counts.items():
                        by_key.setdefault(tk, {})[tv] = c0
                    for t, term in enumerate(info.req_a):
                        vals = by_key.get(term.topology_key)
                        if vals:
                            out["ipa_a_cnt"][r, t] = gather_ids(
                                cols.tv(term.topology_key),
                                cols.value_ids(term.topology_key, vals),
                                np.int32)
                if s.anti_affinity_counts:
                    by_key = {}
                    for (tk, tv), c0 in s.anti_affinity_counts.items():
                        by_key.setdefault(tk, {})[tv] = c0
                    for t, term in enumerate(info.req_aa):
                        vals = by_key.get(term.topology_key)
                        if vals:
                            out["ipa_aa_cnt"][r, t] = gather_ids(
                                cols.tv(term.topology_key),
                                cols.value_ids(term.topology_key, vals),
                                np.int32)
            # symmetric score surface via the plugin's PreScore
            cs = CycleState()
            self.ipa.pre_score(cs, pod, node_list, all_nodes=node_list)
            ps = cs.read_or_none(ipa_mod._PRE_SCORE_KEY)
            if ps is not None and ps.topology_score:
                score = out["ipa_score"][r]
                for tk, tv_scores in ps.topology_score.items():
                    score += gather_ids(cols.tv(tk),
                                        cols.value_ids(tk, tv_scores),
                                        np.int64)
        return out

    # -- assembly -------------------------------------------------------------

    def families(self, snapshot) -> GroupFamilies:
        """Host-side activation analysis (no device readbacks): a family is
        active when some signature row carries it, or — for the symmetric
        inter-pod families — when existing cluster pods seed its counts."""
        return GroupFamilies(
            spr_f=bool(self.spr_f_active.any()),
            spr_s=bool(self.spr_s_active.any()),
            ipa_req=bool(self.ipa_ra_active.any()),
            ipa_anti=bool(
                self.ipa_raa_active.any() or self.m_ipa_exist.any()
                or snapshot.have_pods_with_required_anti_affinity_list),
            ipa_score=bool(
                self.w_stc.any() or self.w_stp.any()
                or snapshot.have_pods_with_affinity_list
                or snapshot.have_pods_with_required_anti_affinity_list),
        )

    def device_rows(self) -> int:
        """Row-axis size of the DEVICE group tensors: the padded count of
        rows that actually exist, not the table's full padded capacity —
        a one-signature spread workload ships [2, SC, N] tensors instead
        of [16, SC, N], cutting every per-step group op by the same
        factor. Crossing a pow2 boundary changes the capacity key, which
        triggers a full reseed (the scheduler's _gd_capacity check)."""
        from ..state.tensorize import pow2_at_least
        return min(pow2_at_least(max(len(self.rows), 1), 2), self.U)

    def build_dev(self, snapshot) -> "tuple[GroupsDev, GroupCarry]":
        """Full (GroupsDev, GroupCarry) numpy build for all rows."""
        rows = range(len(self.rows))
        nis = self._node_rows(snapshot)
        nd = self.node_data(snapshot, rows, nis=nis)
        seeds = self.seed_counts(snapshot, rows, nis=nis)
        U, N = self.device_rows(), self.state.dims.nodes
        d = self.dims

        def full(name, shape, dtype):
            arr = np.zeros(shape, dtype)
            src = nd.get(name) if name in nd else seeds.get(name)
            arr[:src.shape[0]] = src
            return arr

        # host-owned per-row / pairwise fields slice via the SAME field
        # lists grow() and scatter_new_rows use — one classification source
        sliced = {name: getattr(self, name)[:U].copy()
                  for name in self._ROW_FIELDS}
        sliced.update({name: getattr(self, name)[:U, :U].copy()
                       for name in self._PAIRWISE_FIELDS})
        gd = GroupsDev(
            spr_f_tv=full("spr_f_tv", (U, d.spread_constraints, N), np.int32),
            spr_f_elig=full("spr_f_elig", (U, d.spread_constraints, N), bool),
            spr_f_dom=full("spr_f_dom", (U, d.spread_constraints, N), np.int32),
            spr_s_tv=full("spr_s_tv", (U, d.spread_constraints, N), np.int32),
            spr_s_elig=full("spr_s_elig", (U, d.spread_constraints, N), bool),
            spr_s_keys_ok=full("spr_s_keys_ok", (U, N), bool),
            spr_s_dom=full("spr_s_dom", (U, d.spread_constraints, N), np.int32),
            ipa_ra_tv=full("ipa_ra_tv", (U, d.ipa_req_terms, N), np.int32),
            ipa_ra_dom=full("ipa_ra_dom", (U, d.ipa_req_terms, N), np.int32),
            ipa_raa_tv=full("ipa_raa_tv", (U, d.ipa_anti_terms, N), np.int32),
            ipa_raa_dom=full("ipa_raa_dom", (U, d.ipa_anti_terms, N), np.int32),
            ipa_stc_tv=full("ipa_stc_tv", (U, d.ipa_cons_terms, N), np.int32),
            ipa_stc_dom=full("ipa_stc_dom", (U, d.ipa_cons_terms, N), np.int32),
            ipa_stp_tv=full("ipa_stp_tv", (U, d.ipa_plcd_terms, N), np.int32),
            ipa_stp_dom=full("ipa_stp_dom", (U, d.ipa_plcd_terms, N), np.int32),
            **sliced,
        )
        gc = GroupCarry(
            spr_f_cnt=full("spr_f_cnt", (U, d.spread_constraints, N), np.int32),
            spr_f_min_zero=full("spr_f_min_zero", (U, d.spread_constraints), bool),
            spr_s_cnt=full("spr_s_cnt", (U, d.spread_constraints, N), np.int32),
            ipa_veto=full("ipa_veto", (U, N), np.int32),
            ipa_a_cnt=full("ipa_a_cnt", (U, d.ipa_req_terms, N), np.int32),
            ipa_a_total=full("ipa_a_total", (U,), np.int64),
            ipa_aa_cnt=full("ipa_aa_cnt", (U, d.ipa_anti_terms, N), np.int32),
            ipa_score=full("ipa_score", (U, N), np.int64),
        )
        return gd, gc


def to_device(tree):
    """numpy → jnp leaves of a GroupsDev / GroupCarry."""
    import jax.numpy as jnp
    from ..perf.ledger import GLOBAL as _ledger
    _ledger.note_h2d_tree("host_group_seed", tree)
    return type(tree)(*(jnp.asarray(x) for x in tree))


def scatter_new_rows(gd_dev: GroupsDev, gc_dev: GroupCarry,
                     mgr: GroupManager, snapshot, lo: int, hi: int,
                     mesh=None):
    """Seed rows [lo, hi) into resident device group state: node-dependent
    tensors and counts scatter into the row slice; the small per-row scalars
    and pairwise matrices (which gained entries against OLD rows too) are
    re-uploaded whole.

    With `mesh`, the resident tensors are node-axis sharded
    (parallel/sharding.py): each update ships pre-sharded so the row
    scatter stays an in-place per-shard write instead of forcing a
    gather/reshard — the incremental path SURVEY §7.3 calls for, now
    first-class under multi-chip."""
    import jax
    import jax.numpy as jnp

    rows = range(lo, hi)
    U = gd_dev.spr_f_active.shape[0]   # device row axis (compact, pow2)
    nis = mgr._node_rows(snapshot)
    nd = mgr.node_data(snapshot, rows, nis=nis)
    seeds = mgr.seed_counts(snapshot, rows, nis=nis)

    def put(update, like):
        if mesh is None:
            return jnp.asarray(update)
        return jax.device_put(update, like.sharding)

    gd_kw = {name: getattr(gd_dev, name).at[lo:hi].set(
                 put(nd[name], getattr(gd_dev, name)))
             for name in nd}
    for name in GroupManager._ROW_FIELDS:
        gd_kw[name] = put(getattr(mgr, name)[:U], getattr(gd_dev, name))
    for name in GroupManager._PAIRWISE_FIELDS:
        gd_kw[name] = put(getattr(mgr, name)[:U, :U], getattr(gd_dev, name))
    gc_kw = {name: getattr(gc_dev, name).at[lo:hi].set(
                 put(seeds[name], getattr(gc_dev, name)))
             for name in seeds}
    return gd_dev._replace(**gd_kw), gc_dev._replace(**gc_kw)


# ---------------------------------------------------------------------------
# wave fold: batch-apply a wave's accepted placements to the FULL carry
# (ops/program.py run_wave). Every group_update increment is a pure
# gated ADD, so the sequential per-placement updates commute — the whole
# wave folds into the carry with one scatter/gather pass per family
# instead of one [U, SC, N] update per placement.


def _dom_share(tv, dom, w, axis=None, n_seg=None):
    """Σ_m w[m] over nodes m sharing n's topology value (tv ≠ 0 both
    sides) — the "same-topology-value broadcast" of group_update, batched
    over placements via the dense dom ids. tv/dom: int [..., N]; w: int
    [..., N]; returns w's dtype [..., N].

    Sharded (`axis` set): the node dim is the LOCAL shard but `dom` holds
    GLOBAL dense domain ids, so the segment accumulator is widened to
    `n_seg` (a global bound) and all-reduced over `axis` before the
    gather-back — integer adds, so bit-identical to the single-device
    fold in any reduction order."""
    import jax
    import jax.numpy as jnp

    lead = tv.shape[:-1]
    n = tv.shape[-1]
    width = n if n_seg is None else n_seg
    tv2 = tv.reshape(-1, n)
    dom2 = dom.reshape(-1, n)
    w2 = w.reshape(-1, n)

    def one(t, d, x):
        seg = jnp.zeros((width,), x.dtype).at[d].add(jnp.where(t != 0, x, 0))
        if axis is not None:
            seg = jax.lax.psum(seg, axis)
        return jnp.where(t != 0, seg[d], 0)

    return jax.vmap(one)(tv2, dom2, w2).reshape(*lead, n)


def wave_fold(gd: GroupsDev, gc: GroupCarry, wt, cnt_sn,
              fam: Optional[GroupFamilies] = None,
              axis=None, n_seg=None) -> GroupCarry:
    """GroupCarry after a wave: `wt` i32 [S] are the wave's table rows and
    `cnt_sn` i32 [S, N] the accepted placement counts of each wave row per
    node. Exactly equals folding the placements through group_update one
    by one, in any order (additivity; node labels static).

    Sharded (`axis` set, `n_seg` = global node bound): node-last inputs
    are local shards; domain shares are all-reduced inside `_dom_share`
    and the replicated `a_total` scalar sum is psum'd, so the per-node
    carry shards fold exactly as the single-device path does."""
    import jax
    import jax.numpy as jnp

    fam = fam or ALL_FAMILIES
    spr_f_cnt, spr_s_cnt = gc.spr_f_cnt, gc.spr_s_cnt
    ipa_veto, ipa_a_cnt = gc.ipa_veto, gc.ipa_a_cnt
    ipa_a_total, ipa_aa_cnt = gc.ipa_a_total, gc.ipa_aa_cnt
    ipa_score = gc.ipa_score
    cnt32 = cnt_sn.astype(jnp.int32)

    if fam.spr_f:
        # per (consumer u, constraint c): weights at the PLACED node m are
        # Σ_s m_spr_f[placed s → u, c] · cnt[s, m], gated by u's count
        # eligibility of m; shared to every node in m's topology domain
        w_ucn = jnp.einsum("suc,sn->ucn", gd.m_spr_f[wt].astype(jnp.int32),
                           cnt32)
        add = _dom_share(gd.spr_f_tv, gd.spr_f_dom,
                         w_ucn * gd.spr_f_elig, axis, n_seg)
        spr_f_cnt = gc.spr_f_cnt + add

    if fam.spr_s:
        w_ucn = jnp.einsum("suc,sn->ucn", gd.m_spr_s[wt].astype(jnp.int32),
                           cnt32)
        topo = _dom_share(gd.spr_s_tv, gd.spr_s_dom,
                          w_ucn * gd.spr_s_elig, axis, n_seg)
        # hostname constraints count the chosen node's own pods, no
        # eligibility gate (group_update's is_host branch)
        spr_s_cnt = gc.spr_s_cnt + jnp.where(
            gd.spr_s_is_host[:, :, None], w_ucn, topo)

    if fam.ipa_anti:
        # existing-anti veto: shared along the PLACED row's term topology
        raa_tv_w = gd.ipa_raa_tv[wt]                       # [S, TAA, N]
        raa_dom_w = gd.ipa_raa_dom[wt]
        shared_st = _dom_share(
            raa_tv_w, raa_dom_w,
            jnp.broadcast_to(cnt32[:, None, :], raa_tv_w.shape),
            axis, n_seg)
        ipa_veto = gc.ipa_veto + jnp.einsum(
            "sut,stn->un", gd.m_ipa_exist[wt].astype(jnp.int32), shared_st)
        # incoming-anti counts: shared along the CONSUMER's term topology
        w_utn = jnp.einsum("sut,sn->utn", gd.m_ipa_aa[wt].astype(jnp.int32),
                           cnt32)
        ipa_aa_cnt = gc.ipa_aa_cnt + _dom_share(
            gd.ipa_raa_tv, gd.ipa_raa_dom, w_utn, axis, n_seg)

    if fam.ipa_req:
        w_un = jnp.einsum("su,sn->un", gd.m_ipa_a[wt].astype(jnp.int32),
                          cnt32)
        ipa_a_cnt = gc.ipa_a_cnt + _dom_share(
            gd.ipa_ra_tv, gd.ipa_ra_dom,
            w_un[:, None, :] * gd.ipa_ra_active[:, :, None], axis, n_seg)
        # a_total: each placement adds (# active consumer terms whose
        # topology key exists on the placed node) when it matches all of
        # the consumer's terms (group_update's tvb_a != 0 gate)
        k_un = jnp.sum(gd.ipa_ra_active[:, :, None]
                       & (gd.ipa_ra_tv != 0), axis=1)     # [U, N]
        a_add = jnp.einsum(
            "un,un->u", w_un.astype(jnp.int64), k_un.astype(jnp.int64))
        if axis is not None:
            a_add = jax.lax.psum(a_add, axis)
        ipa_a_total = gc.ipa_a_total + a_add

    if fam.ipa_score:
        # consumer-side preferred terms matching the placed pod
        wc_utn = jnp.einsum("sut,sn->utn", gd.w_stc[wt],
                            cnt_sn.astype(jnp.int64))
        cons_add = jnp.sum(_dom_share(gd.ipa_stc_tv, gd.ipa_stc_dom,
                                      wc_utn, axis, n_seg), axis=1)    # [U, N]
        # placed-side terms: share counts along the placed row's term
        # topology, then weight per consumer
        stp_tv_w = gd.ipa_stp_tv[wt]                       # [S, PT, N]
        stp_dom_w = gd.ipa_stp_dom[wt]
        shared_p = _dom_share(
            stp_tv_w, stp_dom_w,
            jnp.broadcast_to(cnt_sn.astype(jnp.int64)[:, None, :],
                             stp_tv_w.shape),
            axis, n_seg)
        plcd_add = jnp.einsum("sut,stn->un", gd.w_stp[wt], shared_p)
        ipa_score = gc.ipa_score + cons_add + plcd_add

    return GroupCarry(spr_f_cnt=spr_f_cnt, spr_f_min_zero=gc.spr_f_min_zero,
                      spr_s_cnt=spr_s_cnt, ipa_veto=ipa_veto,
                      ipa_a_cnt=ipa_a_cnt, ipa_a_total=ipa_a_total,
                      ipa_aa_cnt=ipa_aa_cnt, ipa_score=ipa_score)
