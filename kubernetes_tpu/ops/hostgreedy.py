"""Host-side vectorized greedy for SAME-SIGNATURE group runs.

The device scan (ops/program.py run_batch) pays ~0.4ms of tunneled-TPU
execution per sequential step, which caps spread/anti-affinity workloads at
a few thousand pods/s regardless of host speed. But a run of same-signature
pods has a tiny per-step state delta: one node count, one topology-domain
count vector, one inter-pod-affinity surface update — all O(N) numpy work.
This module is the generalization of the closed-form uniform path
(run_uniform / reference runtime/batch.go:97) to group constraints: the
sequential greedy executes on the HOST over the numpy staging arrays, with
vectorized per-step updates, in exact oracle semantics.

Exactness contract: every formula here mirrors the HOST PLUGINS (the
framework's decision oracle — podtopologyspread/scoring.go port,
interpodaffinity/scoring.go port, least_allocated.go, filtering.go skew
check), which the device scan is itself fuzz-verified against
(tests/test_groups_parity.py). tests/test_hostgreedy_parity.py closes the
triangle by fuzzing this path against the scan.

Eligibility (the caller checks): single signature row, sig != 0 (no host
ports), LeastAllocated strategy, no PreferNoSchedule taints and no
preferred-node-affinity weight on the row (their normalization constants
would shift as nodes saturate — same preconditions as run_uniform's
norm_ok), single device (mesh off), OpportunisticBatching gate on.

After the run the caller commits the assignments through the normal bulk
path and INVALIDATES the device carry: the next device batch reseeds from
the host snapshot, which the commits already updated — no device-side
count reconciliation is needed.
"""

from __future__ import annotations

import math
from typing import Optional

import numpy as np

MAX_NODE_SCORE = 100
INT32_MAX = np.int32(2**31 - 1)

# selector op codes (state/batch.py)
OP_IN, OP_NOT_IN, OP_EXISTS, OP_DOES_NOT_EXIST, OP_GT, OP_LT = 1, 2, 3, 4, 5, 6
TOL_EXISTS = 2
EFFECT_NO_SCHEDULE = 1
EFFECT_PREFER_NO_SCHEDULE = 2
EFFECT_NO_EXECUTE = 3
NON_NUMERIC = np.iinfo(np.int64).min


# ---------------------------------------------------------------------------
# static (carry-independent) parts — numpy mirrors of ops/program.py kernels


def _taint_filter_mask(a, row) -> np.ndarray:
    """taint_filter_mask: no untolerated NoSchedule/NoExecute taint."""
    tk, tv, te = a.taint_key, a.taint_val, a.taint_eff      # [N, T]
    ok_key = (row.tol_key[None, None, :] == 0) | (
        row.tol_key[None, None, :] == tk[:, :, None])
    ok_eff = (row.tol_eff[None, None, :] == 0) | (
        row.tol_eff[None, None, :] == te[:, :, None])
    ok_val = (row.tol_op[None, None, :] == TOL_EXISTS) | (
        row.tol_val[None, None, :] == tv[:, :, None])
    tolerated = ((row.tol_op[None, None, :] != 0)
                 & ok_key & ok_eff & ok_val).any(axis=2)    # [N, T]
    hard = (te == EFFECT_NO_SCHEDULE) | (te == EFFECT_NO_EXECUTE)
    return ~(hard & ~tolerated).any(axis=1)


def _requirements_ok(a, keys, ops, nums, vals) -> np.ndarray:
    """[Q] requirements ANDed, for every node → bool[N]."""
    N = a.label_key.shape[0]
    out = np.ones((N,), bool)
    for q in range(keys.shape[0]):
        op = int(ops[q])
        if op == 0:
            continue
        key_hit = (a.label_key == keys[q]) & (keys[q] != 0)   # [N, L]
        key_present = key_hit.any(axis=1)
        if op == OP_IN or op == OP_NOT_IN:
            v = vals[q]
            kv_match = ((a.label_kv[:, :, None] == v[None, None, :])
                        & (v[None, None, :] != 0)).any(axis=(1, 2))
            out &= kv_match if op == OP_IN else ~kv_match
        elif op == OP_EXISTS:
            out &= key_present
        elif op == OP_DOES_NOT_EXIST:
            out &= ~key_present
        else:  # Gt / Lt
            numeric = np.where(key_hit, a.label_num, NON_NUMERIC).max(axis=1)
            has = key_present & (numeric != NON_NUMERIC)
            out &= has & ((numeric > nums[q]) if op == OP_GT
                          else (numeric < nums[q]))
    return out


def _selector_mask(a, row) -> np.ndarray:
    """nodeSelector conjuncts AND required nodeAffinity terms (ORed)."""
    sel = row.ns_sel_val
    active = sel != 0
    if active.any():
        present = (sel[None, :, None] == a.label_kv[:, None, :]).any(axis=2)
        sel_ok = (~active[None, :] | present).all(axis=1)
    else:
        sel_ok = np.ones((a.label_kv.shape[0],), bool)
    if not row.aff_has:
        return sel_ok
    any_term = np.zeros_like(sel_ok)
    for t in range(row.aff_term_valid.shape[0]):
        if not row.aff_term_valid[t]:
            continue
        any_term |= _requirements_ok(a, row.aff_key[t], row.aff_op[t],
                                     row.aff_num[t], row.aff_val[t])
    return sel_ok & any_term


def _image_score(a, row) -> np.ndarray:
    """image_locality_score numpy mirror (image_locality.go:95-131)."""
    from ..plugins.imagelocality import (MAX_CONTAINER_THRESHOLD,
                                         MIN_THRESHOLD)
    if row.img_containers <= 0:
        return np.zeros((a.image_id.shape[0],), np.int64)
    match = (a.image_id[:, :, None] == row.img_ids[None, None, :]) & (
        row.img_ids[None, None, :] != 0)
    size_c = np.where(match, a.image_size[:, :, None], 0).sum(axis=1)
    present_c = match.any(axis=1)
    num_with = (present_c & a.valid[:, None]).sum(axis=0)
    total = max(int(a.valid.sum()), 1)
    spread = num_with.astype(np.float64) / float(total)
    scaled = (size_c.astype(np.float64) * spread[None, :]).astype(np.int64)
    sum_scores = scaled.sum(axis=1)
    nc = max(int(row.img_containers), 1)
    max_thr = MAX_CONTAINER_THRESHOLD * nc
    clamped = np.clip(sum_scores, MIN_THRESHOLD, max_thr)
    return (MAX_NODE_SCORE * (clamped - MIN_THRESHOLD)
            // max(max_thr - MIN_THRESHOLD, 1))


def static_norm_ok(arrays, pref_weight) -> bool:
    """True when the TaintToleration / preferred-NodeAffinity
    DefaultNormalize constants cannot shift during a same-signature run:
    no valid node carries a PreferNoSchedule taint and the row has no
    preferred-affinity weight. Shared eligibility predicate — HostGreedy
    requires it (self.ok), and the wave kernel (ops/program.py run_wave)
    keys its static `norm_live` variant on it: False here compiles the
    cheap constant-normalization program, True the per-step renormalizing
    one."""
    prefer = ((arrays.taint_eff == EFFECT_PREFER_NO_SCHEDULE)
              & arrays.valid[:, None]).any()
    return (not prefer) and (not pref_weight.any())


class _Row:
    """One signature row of the (numpy) PodTable, attribute access."""

    def __init__(self, table, u: int):
        for f in table._fields:
            setattr(self, f, getattr(table, f)[u])
        self.u = u


# ---------------------------------------------------------------------------
# the greedy


class _DomTerm:
    """Domain compression for one tv-valued row ([N] interned topology
    values): dense domain ids + per-domain node index lists, so a
    placement updates O(N/D) entries and domain-level scalars instead of
    full [N] vectors."""

    __slots__ = ("node_dom", "idx", "D", "tv_ok")

    def __init__(self, tv: np.ndarray):
        self.tv_ok = tv != 0
        uniq = np.unique(tv[self.tv_ok])
        self.D = len(uniq)
        nd = np.searchsorted(uniq, tv)
        nd = np.where(self.tv_ok, nd, self.D)   # sentinel slot D
        self.node_dom = nd.astype(np.int32)
        self.idx = [np.nonzero(nd == d)[0] for d in range(self.D)]

    def dom_of(self, b: int) -> int:
        return int(self.node_dom[b])


class HostGreedy:
    """One run's state. Build once per same-signature run, then `run(k)`
    produces the exact sequential-greedy assignment of k pods.

    All group state is domain-compressed (_DomTerm): spread/affinity
    counts live per topology DOMAIN, masks and scores are gathered [N]
    vectors, and a placement's update cost is O(nodes-in-domain) — the
    node-level formulas of ops/groups.py evaluated sparsely."""

    def __init__(self, cfg, arrays, table, u: int, gd, gc,
                 n_eff: Optional[int] = None):
        """`n_eff`: live node-slot count — every [N]-shaped op runs on the
        occupied prefix of the pow2 node bucket (slots are allocated
        contiguously from 0; freed slots are reused before growth)."""
        self.cfg = cfg
        if n_eff is not None and n_eff < arrays.cap.shape[0]:
            # slice the node axis by FIELD NAME (GroupsDev/GroupCarry
            # specs) — a shape[-1]==N heuristic mis-truncates per-row
            # tensors whenever the row count U happens to equal N
            gd_node = {"spr_f_tv", "spr_f_elig", "spr_f_dom", "spr_s_tv",
                       "spr_s_elig", "spr_s_keys_ok", "spr_s_dom",
                       "ipa_ra_tv", "ipa_ra_dom", "ipa_raa_tv",
                       "ipa_raa_dom", "ipa_stc_tv", "ipa_stc_dom",
                       "ipa_stp_tv", "ipa_stp_dom"}
            gc_node = {"spr_f_cnt", "spr_s_cnt", "ipa_veto", "ipa_a_cnt",
                       "ipa_aa_cnt", "ipa_score"}
            arrays = type(arrays)(*(x[:n_eff] for x in arrays))
            gd = type(gd)(*(
                x[..., :n_eff] if name in gd_node else x
                for name, x in zip(gd._fields, gd)))
            gc = type(gc)(*(
                x[..., :n_eff] if name in gc_node else x
                for name, x in zip(gc._fields, gc)))
        self.a = arrays
        self.row = _Row(table, u)
        self.u = u
        a, row = self.a, self.row
        N = a.cap.shape[0]
        self.N = N

        # -- static feasibility (the SigCache static_mask parts)
        m = a.valid.copy()
        if row.node_name_id != 0:
            m &= a.name_id == row.node_name_id
        m &= ~a.unschedulable | bool(row.tolerates_unsched)
        m &= _taint_filter_mask(a, row)
        m &= _selector_mask(a, row)
        self.static_mask = m
        self.s_img = _image_score(a, row)

        # -- exactness preconditions (run_uniform norm_ok analog)
        self.ok = static_norm_ok(a, row.pref_weight)

        # -- fit state (python scalars per update; vectors at init)
        self.req = row.req.astype(np.int64)
        self.nzreq = row.nonzero_req.astype(np.int64)
        self.j = np.zeros((N,), np.int64)
        cols = np.array(cfg.score_cols, np.int32)
        self.cols = cols
        self.col_w = np.array(cfg.col_weights, np.int64)
        self.col_nz = np.array(cfg.col_nonzero, bool)
        self.nz_slot = np.array(cfg.nonzero_slot, np.int32)
        self.cap_cols = a.cap[:, cols].astype(np.int64)
        self.used_cols0 = a.used[:, cols].astype(np.int64)
        self.nz_used0 = a.nonzero_used[:, self.nz_slot].astype(np.int64)
        self.npods0 = a.npods.astype(np.int64)
        self.allowed = a.allowed_pods.astype(np.int64)
        self.fit_ok = self._fit_ok_vec()
        self.s_fit = self._s_fit_vec()
        self.s_bal = self._s_bal_vec()
        # static part of the total score; s_fit/s_bal entries update at b
        self._static_total = (cfg.w_fit * self.s_fit
                              + cfg.w_balanced * self.s_bal
                              + cfg.w_image * self.s_img)

        # -- spread DoNotSchedule (domain-level)
        self.spr_f = []   # (dom, dom_cnt[D], dom_elig[D], skew, self_n, m_self, elig_node, min_zero)
        for c in np.nonzero(gd.spr_f_active[u])[0]:
            dt = _DomTerm(gd.spr_f_tv[u, c])
            elig = gd.spr_f_elig[u, c]
            dom_cnt = np.zeros((dt.D,), np.int64)
            dom_elig = np.zeros((dt.D,), bool)
            cnt = gc.spr_f_cnt[u, c]
            for d in range(dt.D):
                nodes = dt.idx[d]
                dom_cnt[d] = cnt[nodes[0]] if len(nodes) else 0
                dom_elig[d] = elig[nodes].any() if len(nodes) else False
            self.spr_f.append({
                "dom": dt, "cnt": dom_cnt, "elig_dom": dom_elig,
                "skew": int(gd.spr_f_max_skew[u, c]),
                "selfn": int(gd.spr_f_self[u, c]),
                "m_self": bool(gd.m_spr_f[u, u, c]),
                "elig_node": elig,
                "min_zero": bool(gc.spr_f_min_zero[u, c]),
                "ok_buf": np.zeros((dt.D + 1,), bool)})

        # -- spread ScheduleAnyway (score): host constraints stay
        # node-level (per-node counts); topology constraints domain-level
        self.spr_s = []
        self._raw = np.zeros((N,), np.float64)   # un-normalized spread sum
        self._raw_dirty = True
        for c in np.nonzero(gd.spr_s_active[u])[0]:
            is_host = bool(gd.spr_s_is_host[u, c])
            dt = _DomTerm(gd.spr_s_tv[u, c])
            cnt_node = gc.spr_s_cnt[u, c].astype(np.float64).copy()
            dom_cnt = np.zeros((dt.D,), np.float64)
            for d in range(dt.D):
                nodes = dt.idx[d]
                dom_cnt[d] = cnt_node[nodes[0]] if len(nodes) else 0.0
            self.spr_s.append({
                "dom": dt, "is_host": is_host,
                "cnt_node": cnt_node, "cnt_dom": dom_cnt,
                "skew": int(gd.spr_s_max_skew[u, c]),
                "m_self": bool(gd.m_spr_s[u, u, c]),
                "elig_node": gd.spr_s_elig[u, c],
                "weight": 0.0})
        self.has_spr_s = bool(self.spr_s)
        self.spr_s_keys_ok = gd.spr_s_keys_ok[u]
        self.spr_s_dom_rows = gd.spr_s_dom[u]
        self._prev_scored = None
        self._npart = 0
        self._dom_scored_cnt = np.zeros((0,), np.int64)
        if len(self.spr_s) == 1 and not self.spr_s[0]["is_host"]:
            self._norm_buf = np.zeros(
                (self.spr_s[0]["dom"].D + 1,), np.int64)

        # -- inter-pod affinity (domain-level counters, node-level caches)
        self.ipa_veto = gc.ipa_veto[u].astype(np.int64).copy()
        self.ipa_raa = []
        for t in range(gd.ipa_raa_tv.shape[1]):
            active = bool(gd.ipa_raa_active[u, t])
            exist_self = bool(gd.m_ipa_exist[u, u, t])
            aa_self = bool(gd.m_ipa_aa[u, u, t])
            if not (active or exist_self or aa_self):
                continue
            dt = _DomTerm(gd.ipa_raa_tv[u, t])
            self.ipa_raa.append({
                "dom": dt, "active": active, "exist_self": exist_self,
                "aa_self": aa_self,
                "aa_cnt_node": gc.ipa_aa_cnt[u, t].astype(np.int64).copy()})
        self.ipa_ra = []
        for t in np.nonzero(gd.ipa_ra_active[u])[0]:
            dt = _DomTerm(gd.ipa_ra_tv[u, t])
            self.ipa_ra.append({
                "dom": dt,
                "a_cnt_node": gc.ipa_a_cnt[u, t].astype(np.int64).copy()})
        self.m_ipa_a_self = bool(gd.m_ipa_a[u, u])
        self.ipa_a_total = int(gc.ipa_a_total[u])
        self.ipa_self_all = bool(gd.ipa_self_all[u])
        self.ipa_score = gc.ipa_score[u].astype(np.int64).copy()
        self.ipa_sc_terms = []   # symmetric score surface contributions
        for t in np.nonzero(gd.w_stc[u, u])[0]:
            self.ipa_sc_terms.append((_DomTerm(gd.ipa_stc_tv[u, t]),
                                      int(gd.w_stc[u, u, t])))
        for t in np.nonzero(gd.w_stp[u, u])[0]:
            self.ipa_sc_terms.append((_DomTerm(gd.ipa_stp_tv[u, t]),
                                      int(gd.w_stp[u, u, t])))
        self.has_ipa_score = bool(
            (self.ipa_score != 0).any() or self.ipa_sc_terms)
        self.has_ipa_mask = bool(
            self.ipa_raa or self.ipa_ra or self.ipa_veto.any())

    # -- fit / balanced score vectors (least_allocated.go / balanced_*) ------

    def _used_cols(self, j):
        used_nz = self.nz_used0 + j[:, None] * self.nzreq[self.nz_slot][None, :]
        used_pl = self.used_cols0 + j[:, None] * self.req[self.cols][None, :]
        return np.where(self.col_nz[None, :], used_nz, used_pl), used_pl

    def _fit_ok_vec(self):
        j = self.j
        pods_ok = self.npods0 + j + 1 <= self.allowed
        used1 = self.a.used.astype(np.int64) + (j[:, None] + 1) * self.req[None, :]
        cols_ok = ((self.req[None, :] == 0)
                   | (used1 <= self.a.cap)).all(axis=1)
        return pods_ok & cols_ok

    def _s_fit_vec(self):
        used_cols, _ = self._used_cols(self.j + 1)
        cap = self.cap_cols
        ok = cap > 0
        if self.cfg.strategy == "MostAllocated":
            raw = np.where((cap == 0) | (used_cols > cap), 0,
                           used_cols * MAX_NODE_SCORE // np.maximum(cap, 1))
        else:
            raw = np.where((cap == 0) | (used_cols > cap), 0,
                           (cap - used_cols) * MAX_NODE_SCORE
                           // np.maximum(cap, 1))
        ssum = np.where(ok, raw * self.col_w[None, :], 0).sum(axis=1)
        wsum = np.where(ok, self.col_w[None, :], 0).sum(axis=1)
        return np.where(wsum > 0, ssum // np.maximum(wsum, 1), 0)

    def _s_bal_vec(self):
        if self.row.skip_balanced:
            return np.zeros((self.N,), np.int64)
        _, used_pl = self._used_cols(self.j + 1)
        cap = self.cap_cols
        ok = cap > 0
        frac = np.where(ok, np.minimum(used_pl / np.maximum(cap, 1), 1.0), 0.0)
        cnt = ok.sum(axis=1)
        mean = frac.sum(axis=1) / np.maximum(cnt, 1)
        var = np.where(ok, (frac - mean[:, None]) ** 2, 0.0).sum(axis=1) \
            / np.maximum(cnt, 1)
        std = np.sqrt(var)
        return np.floor((1.0 - std) * MAX_NODE_SCORE + 1e-9).astype(np.int64)

    def _refresh_node(self, b: int) -> None:
        """Python-scalar recompute of fit_ok/s_fit/s_bal/_static_total for
        the one node a placement touched."""
        cfg = self.cfg
        j1 = int(self.j[b]) + 1
        # fit_ok
        ok = int(self.npods0[b]) + j1 <= int(self.allowed[b])
        if ok:
            used_row = self.a.used[b]
            cap_row = self.a.cap[b]
            req = self.req
            for r in range(req.shape[0]):
                rq = int(req[r])
                if rq and int(used_row[r]) + j1 * rq > int(cap_row[r]):
                    ok = False
                    break
        self.fit_ok[b] = ok
        # s_fit / s_bal over the score columns
        C = len(self.cfg.score_cols)
        ssum = wsum = 0
        fracs = []
        nok = 0
        fsum = 0.0
        most = cfg.strategy == "MostAllocated"
        for ci in range(C):
            cap = int(self.cap_cols[b, ci])
            used_pl = int(self.used_cols0[b, ci]) + j1 * int(self.req[self.cols[ci]])
            if self.col_nz[ci]:
                used = int(self.nz_used0[b, ci]) + j1 * int(self.nzreq[self.nz_slot[ci]])
            else:
                used = used_pl
            if cap > 0:
                w = int(self.col_w[ci])
                if used <= cap:
                    raw = (used * MAX_NODE_SCORE // cap if most
                           else (cap - used) * MAX_NODE_SCORE // cap)
                else:
                    raw = 0
                ssum += raw * w
                wsum += w
                f = min(used_pl / cap, 1.0)
                fracs.append(f)
                fsum += f
                nok += 1
        s_fit = ssum // wsum if wsum > 0 else 0
        self.s_fit[b] = s_fit
        if self.row.skip_balanced:
            s_bal = 0
        else:
            mean = fsum / max(nok, 1)
            var = sum((f - mean) ** 2 for f in fracs) / max(nok, 1)
            s_bal = int(math.floor((1.0 - math.sqrt(var)) * MAX_NODE_SCORE
                                   + 1e-9))
            self.s_bal[b] = s_bal
        self._static_total[b] = (cfg.w_fit * s_fit
                                 + cfg.w_balanced * s_bal
                                 + cfg.w_image * int(self.s_img[b]))

    # -- group mask / scores (ops/groups.py formulas, domain-level) ----------

    def _group_mask(self) -> np.ndarray:
        mask = None
        for c in self.spr_f:
            dom_cnt, elig = c["cnt"], c["elig_dom"]
            minv = 0 if c["min_zero"] else (
                int(dom_cnt[elig].min()) if elig.any() else int(INT32_MAX))
            ok_dom = c["ok_buf"]
            np.less_equal(dom_cnt + (c["selfn"] - minv), c["skew"],
                          out=ok_dom[:-1])
            part = ok_dom[c["dom"].node_dom]   # sentinel slot stays False
            mask = part if mask is None else (mask & part)
        if self.has_ipa_mask:
            part = self.ipa_veto == 0
            mask = part if mask is None else (mask & part)
            for t in self.ipa_raa:
                if t["active"]:
                    mask &= ~(t["dom"].tv_ok & (t["aa_cnt_node"] > 0))
            if self.ipa_ra:
                escape = (self.ipa_a_total == 0) and self.ipa_self_all
                for t in self.ipa_ra:
                    if escape:
                        mask &= t["dom"].tv_ok
                    else:
                        mask &= t["dom"].tv_ok & (t["a_cnt_node"] > 0)
        if mask is None:
            mask = np.ones((self.N,), bool)
        return mask

    def _scored_stats(self, scored: np.ndarray):
        """(npart, distinct, weights, per-domain scored counts) with
        incremental updates (the scored set flips rarely)."""
        if self._prev_scored is None or not np.array_equal(
                scored, self._prev_scored):
            self._npart = int(scored.sum())
            for c in self.spr_s:
                if not c["is_host"]:
                    hist = np.bincount(c["dom"].node_dom[scored],
                                       minlength=c["dom"].D + 1)[:c["dom"].D]
                    c["dom_scored"] = hist
                    c["distinct"] = int((hist > 0).sum())
                size = self._npart if c["is_host"] else c["distinct"]
                c["weight"] = math.log(float(size) + 2.0)
            self._prev_scored = scored.copy()
            self._raw_dirty = True
            if len(self.spr_s) == 1 and not self.spr_s[0]["is_host"]:
                self._dom_scored_cnt = self.spr_s[0]["dom_scored"]
        return self._npart

    def _rebuild_raw(self) -> None:
        """Un-normalized spread score sum (scoring.go:199-271): rebuilt
        when weights changed (scored-set flip), else maintained by _apply's
        sparse adds."""
        self._raw.fill(0.0)
        for c in self.spr_s:
            npart = self._npart
            size = npart if c["is_host"] else c.get("distinct", 0)
            w = math.log(float(size) + 2.0)
            c["weight"] = w
            add = np.where(c["dom"].tv_ok,
                           c["cnt_node"] * w + float(c["skew"] - 1), 0.0)
            self._raw += add
        self._raw_dirty = False

    def _group_scores(self, feasible: np.ndarray) -> np.ndarray:
        total = np.zeros((self.N,), np.int64)
        cfg = self.cfg
        if self.has_spr_s:
            scored = feasible & self.spr_s_keys_ok
            self._scored_stats(scored)
            c = self.spr_s[0]
            if len(self.spr_s) == 1 and not c["is_host"]:
                # single topology constraint: raw is domain-constant, so
                # normalize at DOMAIN level (D scalars) and gather — the
                # common一-constraint case drops every [N] float op
                dt = c["dom"]
                w = c["weight"]
                raw_dom = np.round(c["cnt_dom"] * w
                                   + float(c["skew"] - 1)).astype(np.int64)
                present = self._dom_scored_cnt > 0
                if present.any():
                    minv = int(raw_dom[present].min())
                    maxv = int(raw_dom[present].max())
                else:
                    minv, maxv = int(INT32_MAX), 0
                buf = self._norm_buf
                if maxv == 0:
                    buf[:-1] = MAX_NODE_SCORE
                else:
                    buf[:-1] = (MAX_NODE_SCORE * (maxv + minv - raw_dom)
                                // maxv)
                total += cfg.w_spread * np.where(scored,
                                                 buf[dt.node_dom], 0)
            else:
                if self._raw_dirty:
                    self._rebuild_raw()
                raw = np.round(self._raw).astype(np.int64)
                if scored.any():
                    minv = int(raw[scored].min())
                    maxv = int(raw[scored].max())
                else:
                    minv, maxv = int(INT32_MAX), 0
                if maxv == 0:
                    norm = np.full((self.N,), MAX_NODE_SCORE, np.int64)
                else:
                    norm = MAX_NODE_SCORE * (maxv + minv - raw) // maxv
                total += cfg.w_spread * np.where(scored, norm, 0)
        if self.has_ipa_score:
            s = self.ipa_score
            if feasible.any():
                minv = int(s[feasible].min())
                maxv = int(s[feasible].max())
            else:
                minv, maxv = 0, 0
            diff = maxv - minv
            if diff > 0:
                ipa = (MAX_NODE_SCORE * (s - minv).astype(np.float64)
                       / float(diff)).astype(np.int64)
                total += cfg.w_ipa * ipa
        return total

    def _apply(self, b: int) -> None:
        """State update after placing one run-pod on node b (group_update's
        u==consumer slice + fit bookkeeping) — sparse domain updates."""
        self.j[b] += 1
        self._refresh_node(b)
        for c in self.spr_f:
            if c["m_self"] and c["elig_node"][b]:
                d = c["dom"].dom_of(b)
                if d < c["dom"].D:
                    c["cnt"][d] += 1
        raw_touched = None
        for c in self.spr_s:
            if not c["m_self"]:
                continue
            if c["is_host"]:
                c["cnt_node"][b] += 1.0
                raw_touched = (np.array([b]) if raw_touched is None
                               else np.union1d(raw_touched, [b]))
            elif c["elig_node"][b]:
                d = c["dom"].dom_of(b)
                if d < c["dom"].D:
                    idx = c["dom"].idx[d]
                    c["cnt_node"][idx] += 1.0
                    c["cnt_dom"][d] += 1.0
                    raw_touched = (idx if raw_touched is None
                                   else np.union1d(raw_touched, idx))
        if raw_touched is not None and not self._raw_dirty:
            # recompute (not increment) the touched rows: the device/oracle
            # evaluates cnt·w fresh each step, and an accumulated w+w+…
            # can drift an ulp from cnt·w at a round() boundary
            acc = np.zeros((len(raw_touched),), np.float64)
            for c in self.spr_s:
                acc += np.where(c["dom"].tv_ok[raw_touched],
                                c["cnt_node"][raw_touched] * c["weight"]
                                + float(c["skew"] - 1), 0.0)
            self._raw[raw_touched] = acc
        for t in self.ipa_raa:
            d = t["dom"].dom_of(b)
            if d >= t["dom"].D:
                continue
            idx = t["dom"].idx[d]
            if t["exist_self"]:
                self.ipa_veto[idx] += 1
            if t["aa_self"]:
                t["aa_cnt_node"][idx] += 1
        if self.m_ipa_a_self:
            bumped = 0
            for t in self.ipa_ra:
                d = t["dom"].dom_of(b)
                if d < t["dom"].D:
                    t["a_cnt_node"][t["dom"].idx[d]] += 1
                    bumped += 1
            self.ipa_a_total += bumped
        for dt, w in self.ipa_sc_terms:
            d = dt.dom_of(b)
            if d < dt.D:
                self.ipa_score[dt.idx[d]] += w

    # -- the run --------------------------------------------------------------

    def run(self, k: int) -> np.ndarray:
        """Assign k same-signature pods sequentially; returns int32[k]
        (-1 = unschedulable). A failed step leaves state untouched, so
        every later identical step fails too — fill and stop."""
        out = np.full((k,), -1, np.int32)
        base = self.static_mask
        for i in range(k):
            feasible = base & self.fit_ok & self._group_mask()
            if not feasible.any():
                break
            total = self._static_total + self._group_scores(feasible)
            masked = np.where(feasible, total, -1)
            b = int(masked.argmax())
            if masked[b] < 0:
                break
            out[i] = b
            self._apply(b)
        return out
