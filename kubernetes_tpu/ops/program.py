"""The batched scheduling program: one jitted lax.scan over the pod axis.

This is the TPU replacement for the reference's hot loops (SURVEY §3.2):
`findNodesThatPassFilters` (schedule_one.go:630, Parallelizer over nodes) and
`prioritizeNodes`' three score phases (runtime/framework.go:1286-1390) become
node-axis vectorized kernels, and `ScheduleOne`'s serial pod loop becomes the
scan — sequential in pods (exact greedy parity: each placement updates the
carried `used`/`npods`/`ports` before the next pod sees them), parallel in
nodes.

Filter kernels (all → bool[N]):
  fit          noderesources/fit.go:649-738 (per-column compare, pod count)
  node_name    nodename/node_name.go (interned id equality)
  unschedulable node_unschedulable.go (+ toleration escape)
  taints       tainttoleration (NoSchedule/NoExecute untolerated)
  selector     nodeaffinity + spec.nodeSelector (compiled id tables)
  ports        nodeports (interned (proto,port) id collision)

Score kernels (int64, reference formulas + normalization exactly):
  least_allocated   least_allocated.go:30-60 (int division, NonZeroRequested)
  balanced          balanced_allocation.go:195-237 (std of fractions)
  taint_score       PreferNoSchedule count, DefaultNormalize reverse
  node_affinity     preferred term weights, DefaultNormalize

Tie-break: masked argmax picks the FIRST max index — the deterministic
tie-break the host oracle uses (runtime.py), a legal member of the Go score
heap's randomized argmax set (schedule_one.go:940-944).
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from ..ops.groups import (DryRunSpread, GroupCarry, GroupFamilies, GroupsDev,
                          group_mask, group_scores, group_update)
from ..state.batch import (OP_DOES_NOT_EXIST, OP_EXISTS, OP_GT, OP_IN,
                           OP_LT, OP_NOT_IN, TOL_EQUAL, TOL_EXISTS)
from ..state.tensorize import (EFFECT_NO_EXECUTE, EFFECT_NO_SCHEDULE,
                               EFFECT_PREFER_NO_SCHEDULE, NodeArrays)
# compile ledger (perf/ledger.py): every public jit entry below dispatches
# through LEDGER.measured_call so fresh compiles/retraces/donation misses
# are attributed per kernel (scheduler_xla_compiles_total{kernel})
from ..perf.ledger import GLOBAL as LEDGER
# sanitizer rails (analysis/rails.py, `SanitizerRails` gate): with rails
# active, every entry explicitly stages its host-side array args
# (device_put — the declared escape under jax.transfer_guard) and the
# donating entries poison the consumed carry on backends that compiled
# without donation (CPU), so use-after-donate raises here instead of
# corrupting state on a real accelerator
from ..analysis.rails import GLOBAL as RAILS

MAX_SCORE = 100


class ScoreConfig(NamedTuple):
    """Static per-profile scoring configuration (hashable → jit cache key)."""

    score_cols: tuple[int, ...] = (0, 1)        # resource columns to score
    col_weights: tuple[int, ...] = (1, 1)       # per-column weights
    col_nonzero: tuple[bool, ...] = (True, True)  # use NonZeroRequested path
    nonzero_slot: tuple[int, ...] = (0, 1)      # index into nonzero arrays
    w_fit: int = 1
    w_balanced: int = 1
    w_taint: int = 3
    w_node_affinity: int = 2
    w_spread: int = 2                           # PodTopologySpread weight
    w_ipa: int = 2                              # InterPodAffinity weight
    w_image: int = 1                            # ImageLocality weight
    strategy: str = "LeastAllocated"            # or MostAllocated


class SigCache(NamedTuple):
    """Per-signature cached evaluation (KEP-5598 opportunistic batching,
    reference runtime/batch.go:33-240, generalized): consecutive pods with an
    identical device row reuse the carry-independent kernels — only the fit
    mask and fit-derived scores of the single node touched by the previous
    placement are recomputed. sig 0 never matches."""

    sig: jnp.ndarray          # i32 scalar — signature these vectors belong to
    static_mask: jnp.ndarray  # bool [N] — nodename/unsched/taints/selector/ports
    taint_raw: jnp.ndarray    # i64 [N] — PreferNoSchedule counts (pre-normalize)
    na_raw: jnp.ndarray       # i64 [N] — preferred-affinity weights (pre-normalize)
    s_img: jnp.ndarray        # i64 [N] — ImageLocality score (carry-independent)
    fit_ok: jnp.ndarray       # bool [N]
    s_fit: jnp.ndarray        # i64 [N]
    s_bal: jnp.ndarray        # i64 [N]


class Carry(NamedTuple):
    used: jnp.ndarray          # i64 [N, R]
    nonzero_used: jnp.ndarray  # i64 [N, 2]
    npods: jnp.ndarray         # i32 [N]
    ports: jnp.ndarray         # i32 [N, P]
    cache: SigCache
    # PodTopologySpread / InterPodAffinity counts (None when the batch and
    # cluster carry no group constraints — the lean program compiles without
    # any group compute)
    groups: GroupCarry | None = None


# ---------------------------------------------------------------------------
# filter kernels (operate on full node axis)


def fit_mask(cap, used, npods, allowed_pods, req):
    pods_ok = npods + 1 <= allowed_pods
    cols_ok = jnp.all((req[None, :] == 0) | (used + req[None, :] <= cap), axis=1)
    return pods_ok & cols_ok


def tolerates(tol_key, tol_val, tol_eff, tol_op, taint_key, taint_val, taint_eff):
    """toleration.go:29-56 broadcast: [T_n, TT] → does toleration tt cover
    taint tn. Empty toleration key (id 0) matches all keys; empty effect
    (0) matches all effects; Exists ignores value."""
    key_ok = (tol_key[None, :] == 0) | (tol_key[None, :] == taint_key[:, None])
    eff_ok = (tol_eff[None, :] == 0) | (tol_eff[None, :] == taint_eff[:, None])
    val_ok = (tol_op[None, :] == TOL_EXISTS) | (tol_val[None, :] == taint_val[:, None])
    return (tol_op[None, :] != 0) & key_ok & eff_ok & val_ok


def taint_filter_mask(na: NodeArrays, pod):
    """No untolerated NoSchedule/NoExecute taint."""
    # [N, T_n, TT]
    tol = jax.vmap(tolerates, in_axes=(None, None, None, None, 0, 0, 0))(
        pod.tol_key, pod.tol_val, pod.tol_eff, pod.tol_op,
        na.taint_key, na.taint_val, na.taint_eff)
    tolerated = jnp.any(tol, axis=2)                       # [N, T_n]
    hard = ((na.taint_eff == EFFECT_NO_SCHEDULE)
            | (na.taint_eff == EFFECT_NO_EXECUTE))
    return ~jnp.any(hard & ~tolerated, axis=1)


def taint_prefer_count(na: NodeArrays, pod):
    """tainttoleration Score: count untolerated PreferNoSchedule taints;
    only tolerations with empty or PreferNoSchedule effect participate
    (taint_toleration.go getAllTolerationPreferNoSchedule)."""
    prefer_tol_op = jnp.where(
        (pod.tol_eff == 0) | (pod.tol_eff == EFFECT_PREFER_NO_SCHEDULE),
        pod.tol_op, 0)
    tol = jax.vmap(tolerates, in_axes=(None, None, None, None, 0, 0, 0))(
        pod.tol_key, pod.tol_val, pod.tol_eff, prefer_tol_op,
        na.taint_key, na.taint_val, na.taint_eff)
    tolerated = jnp.any(tol, axis=2)
    prefer = na.taint_eff == EFFECT_PREFER_NO_SCHEDULE
    return jnp.sum(prefer & ~tolerated, axis=1).astype(jnp.int64)


def _requirement_ok(label_key, label_kv, label_num, key, op, num, vals):
    """One selector requirement vs one node's label rows.
    label_*: [L]; vals: [V] → bool."""
    key_hit = (label_key == key) & (key != 0)
    key_present = jnp.any(key_hit)
    kv_match = jnp.any((label_kv[:, None] == vals[None, :]) & (vals[None, :] != 0))
    # numeric value of `key` on this node (NON_NUMERIC if absent/non-int)
    numeric = jnp.max(jnp.where(key_hit, label_num, jnp.iinfo(jnp.int64).min))
    has_numeric = key_present & (numeric != jnp.iinfo(jnp.int64).min)
    return jnp.select(
        [op == OP_IN, op == OP_NOT_IN, op == OP_EXISTS, op == OP_DOES_NOT_EXIST,
         op == OP_GT, op == OP_LT],
        [kv_match, ~kv_match, key_present, ~key_present,
         has_numeric & (numeric > num), has_numeric & (numeric < num)],
        default=jnp.array(True),  # op 0 = padding
    )


def _term_ok(label_key, label_kv, label_num, keys, ops, nums, vals):
    """[Q] requirements ANDed."""
    f = jax.vmap(_requirement_ok, in_axes=(None, None, None, 0, 0, 0, 0))
    return jnp.all(f(label_key, label_kv, label_num, keys, ops, nums, vals))


def selector_mask(na: NodeArrays, pod):
    """spec.nodeSelector conjuncts AND required nodeAffinity terms (ORed) —
    component-helpers nodeaffinity.GetRequiredNodeAffinity semantics."""
    # nodeSelector: every (key, kv) must be present
    def one_node_sel(label_kv):
        present = (pod.ns_sel_val[:, None] == label_kv[None, :]).any(axis=1)
        return jnp.all((pod.ns_sel_val == 0) | present)
    sel_ok = jax.vmap(one_node_sel)(na.label_kv)

    def one_node_aff(label_key, label_kv, label_num):
        terms = jax.vmap(_term_ok, in_axes=(None, None, None, 0, 0, 0, 0))(
            label_key, label_kv, label_num,
            pod.aff_key, pod.aff_op, pod.aff_num, pod.aff_val)
        return jnp.any(terms & pod.aff_term_valid)
    aff_ok = jnp.where(pod.aff_has,
                       jax.vmap(one_node_aff)(na.label_key, na.label_kv, na.label_num),
                       True)
    return sel_ok & aff_ok


def preferred_affinity_score(na: NodeArrays, pod):
    """nodeaffinity Score: Σ weight over matching preferred terms."""
    def one_node(label_key, label_kv, label_num):
        match = jax.vmap(_term_ok, in_axes=(None, None, None, 0, 0, 0, 0))(
            label_key, label_kv, label_num,
            pod.pref_key, pod.pref_op, pod.pref_num, pod.pref_val)
        return jnp.sum(jnp.where(match, pod.pref_weight, 0))
    return jax.vmap(one_node)(na.label_key, na.label_kv, na.label_num)


def ports_mask(ports, pod_port_ids):
    """nodeports: no interned (proto,port) id collision. Also requires
    enough free row slots to record the pod's ports — without this a
    placement could silently drop port bookkeeping and let a later pod in
    the batch double-book the port (divergence from the host cache)."""
    collide = (ports[:, :, None] == pod_port_ids[None, None, :]) & (
        pod_port_ids[None, None, :] != 0)
    ok = ~jnp.any(collide, axis=(1, 2))
    free = jnp.sum(ports == 0, axis=1)
    needed = jnp.sum(pod_port_ids != 0)
    return ok & (free >= needed)


# ---------------------------------------------------------------------------
# score kernels


# single source of truth for the reference thresholds: the host plugin
from ..plugins.imagelocality import (MAX_CONTAINER_THRESHOLD as
                                     IMG_MAX_CONTAINER_THRESHOLD,
                                     MIN_THRESHOLD as IMG_MIN_THRESHOLD)


def image_locality_score(na: NodeArrays, pod, axis=None):
    """image_locality.go:95-131 on device: per container image, the node's
    stored size scaled by the image's cluster spread (numNodes/totalNodes,
    float64 then truncated — the host plugin's exact arithmetic), summed,
    clamped to [minThreshold, containers·maxContainerThreshold], mapped to
    [0, 100]. Carry-independent: node images are static per snapshot."""
    # presence[N, IC]: does node n hold image c; sizes via the same match
    match = (na.image_id[:, :, None] == pod.img_ids[None, None, :]) & (
        pod.img_ids[None, None, :] != 0)                     # [N, I, IC]
    size_c = jnp.sum(jnp.where(match, na.image_size[:, :, None], 0),
                     axis=1)                                 # [N, IC]
    present_c = jnp.any(match, axis=1)                       # [N, IC]
    # numNodesWithImage over valid nodes; total = schedulable node count —
    # GLOBAL across shards (the spread ratio is a cluster-wide quantity)
    num_with = jnp.sum(present_c & na.valid[:, None], axis=0)  # [IC]
    total = jnp.sum(na.valid)
    if axis is not None:
        num_with = lax.psum(num_with, axis)
        total = lax.psum(total, axis)
    total = jnp.maximum(total, 1)
    spread = num_with.astype(jnp.float64) / total.astype(jnp.float64)
    scaled = (size_c.astype(jnp.float64) * spread[None, :]).astype(jnp.int64)
    sum_scores = jnp.sum(scaled, axis=1)                     # [N]
    nc = jnp.maximum(pod.img_containers, 1).astype(jnp.int64)
    max_thr = IMG_MAX_CONTAINER_THRESHOLD * nc
    clamped = jnp.clip(sum_scores, IMG_MIN_THRESHOLD, max_thr)
    score = (MAX_SCORE * (clamped - IMG_MIN_THRESHOLD)
             // jnp.maximum(max_thr - IMG_MIN_THRESHOLD, 1))
    return jnp.where(pod.img_containers > 0, score, 0)


def least_allocated(cfg: ScoreConfig, cap, used_cols):
    """least_allocated.go:30-60 exact int64 arithmetic, per node.
    cap/used_cols: [N, C] for the configured score columns. Padding rows
    score 0 harmlessly; feasibility masking excludes them from argmax."""
    w = jnp.array(cfg.col_weights, jnp.int64)
    col_ok = cap > 0
    if cfg.strategy == "MostAllocated":
        raw = jnp.where((cap == 0) | (used_cols > cap), 0,
                        used_cols * MAX_SCORE // jnp.maximum(cap, 1))
    else:
        raw = jnp.where((cap == 0) | (used_cols > cap), 0,
                        (cap - used_cols) * MAX_SCORE // jnp.maximum(cap, 1))
    score_sum = jnp.sum(jnp.where(col_ok, raw * w[None, :], 0), axis=1)
    w_sum = jnp.sum(jnp.where(col_ok, w[None, :], 0), axis=1)
    return jnp.where(w_sum > 0, score_sum // jnp.maximum(w_sum, 1), 0)


def balanced_allocation(cap, used_cols):
    """balanced_allocation.go:195-237: 100·(1−std of utilization fractions)."""
    col_ok = cap > 0
    frac = jnp.where(col_ok, jnp.minimum(used_cols / jnp.maximum(cap, 1), 1.0), 0.0)
    cnt = jnp.sum(col_ok, axis=1)
    total = jnp.sum(frac, axis=1)
    mean = total / jnp.maximum(cnt, 1)
    var = jnp.sum(jnp.where(col_ok, (frac - mean[:, None]) ** 2, 0.0), axis=1) / jnp.maximum(cnt, 1)
    # population std; for the 2-column case this equals the reference's
    # |f0−f1|/2 special case (balanced_allocation.go:224-227) exactly
    std = jnp.sqrt(var)
    # int truncation with epsilon guard against float error at exact integers
    return jnp.floor((1.0 - std) * MAX_SCORE + 1e-9).astype(jnp.int64)


def default_normalize(scores, feasible, reverse: bool, axis: str | None = None):
    """plugins/helper DefaultNormalizeScore over the feasible set.

    `axis`: mesh axis name when the node dimension is sharded — the max must
    be GLOBAL across shards or normalization denominators diverge per device
    (parallel/sharding.py)."""
    maxc = jnp.max(jnp.where(feasible, scores, 0))
    if axis is not None:
        maxc = lax.pmax(maxc, axis)
    scaled = jnp.where(maxc > 0, scores * MAX_SCORE // jnp.maximum(maxc, 1),
                       jnp.where(reverse, MAX_SCORE, scores))
    if reverse:
        scaled = jnp.where(maxc > 0, MAX_SCORE - scaled, scaled)
    return scaled


# ---------------------------------------------------------------------------
# the scan


class PodTableDev(NamedTuple):
    """Device copy of state.batch.PodTable ([U, ...], U = distinct sigs)."""

    req: jnp.ndarray
    nonzero_req: jnp.ndarray
    node_name_id: jnp.ndarray
    tol_key: jnp.ndarray
    tol_val: jnp.ndarray
    tol_eff: jnp.ndarray
    tol_op: jnp.ndarray
    tolerates_unsched: jnp.ndarray
    ns_sel_val: jnp.ndarray
    aff_has: jnp.ndarray
    aff_term_valid: jnp.ndarray
    aff_key: jnp.ndarray
    aff_op: jnp.ndarray
    aff_num: jnp.ndarray
    aff_val: jnp.ndarray
    pref_weight: jnp.ndarray
    pref_key: jnp.ndarray
    pref_op: jnp.ndarray
    pref_num: jnp.ndarray
    pref_val: jnp.ndarray
    port_ids: jnp.ndarray
    skip_balanced: jnp.ndarray
    img_ids: jnp.ndarray
    img_containers: jnp.ndarray


class PodXs(NamedTuple):
    """Per-pod scan xs: the only O(B) upload per batch."""

    valid: jnp.ndarray   # bool [B]
    sig: jnp.ndarray     # i32 [B]
    tidx: jnp.ndarray    # i32 [B] — row into PodTableDev
    # node row of the pod's OWN pending nomination (-1 = none): the overlay
    # must exclude the pod's own nominated resources exactly like the
    # reference two-pass skips the pod's own entry
    # (runtime/framework.go:1183). Nominated pods carry sig 0 so the
    # signature cache neither serves nor stores their per-pod fit.
    nom_idx: jnp.ndarray = None


class PodRow(NamedTuple):
    """One pod's view inside the scan step: table row + per-pod scalars."""

    valid: jnp.ndarray
    sig: jnp.ndarray
    req: jnp.ndarray
    nonzero_req: jnp.ndarray
    node_name_id: jnp.ndarray
    tol_key: jnp.ndarray
    tol_val: jnp.ndarray
    tol_eff: jnp.ndarray
    tol_op: jnp.ndarray
    tolerates_unsched: jnp.ndarray
    ns_sel_val: jnp.ndarray
    aff_has: jnp.ndarray
    aff_term_valid: jnp.ndarray
    aff_key: jnp.ndarray
    aff_op: jnp.ndarray
    aff_num: jnp.ndarray
    aff_val: jnp.ndarray
    pref_weight: jnp.ndarray
    pref_key: jnp.ndarray
    pref_op: jnp.ndarray
    pref_num: jnp.ndarray
    pref_val: jnp.ndarray
    port_ids: jnp.ndarray
    skip_balanced: jnp.ndarray
    img_ids: jnp.ndarray
    img_containers: jnp.ndarray
    nom_idx: jnp.ndarray = None   # see PodXs.nom_idx


def _gather_row(table: PodTableDev, x) -> PodRow:
    fields = {name: getattr(table, name)[x.tidx]
              for name in PodTableDev._fields}
    return PodRow(valid=x.valid, sig=x.sig, nom_idx=x.nom_idx, **fields)


def table_from_batch(batch) -> PodTableDev:
    """PodBatch → device signature table."""
    table = PodTableDev(*(jnp.asarray(getattr(batch.table, f))
                          for f in PodTableDev._fields))
    LEDGER.note_h2d_tree("host_cache", table)
    return table


def pod_rows_from_batch(batch) -> tuple[PodXs, PodTableDev]:
    """PodBatch → (per-pod xs, device signature table)."""
    xs = PodXs(valid=jnp.asarray(batch.valid), sig=jnp.asarray(batch.sig),
               tidx=jnp.asarray(batch.tidx))
    return xs, table_from_batch(batch)


def _fit_scores(cfg: ScoreConfig, na: NodeArrays, carry: Carry, pod: PodRow):
    """LeastAllocated + BalancedAllocation over all nodes → ([N], [N])."""
    cols = jnp.array(cfg.score_cols, jnp.int32)
    cap_cols = na.cap[:, cols]                        # [N, C]
    nz = jnp.array(cfg.col_nonzero)
    slots = jnp.array(cfg.nonzero_slot, jnp.int32)
    used_nonzero = carry.nonzero_used[:, slots] + pod.nonzero_req[slots][None, :]
    used_plain = carry.used[:, cols] + pod.req[cols][None, :]
    used_cols = jnp.where(nz[None, :], used_nonzero, used_plain)
    s_fit = least_allocated(cfg, cap_cols, used_cols)
    used_bal = carry.used[:, cols] + pod.req[cols][None, :]
    s_bal = jnp.where(pod.skip_balanced, 0, balanced_allocation(cap_cols, used_bal))
    return s_fit, s_bal


def _slow_parts(cfg: ScoreConfig, na: NodeArrays, carry: Carry, pod: PodRow,
                axis: str | None = None, overlay=None):
    """The full kernel set: everything SigCache caches, freshly computed.
    ports_mask folds into static_mask — pods eligible for the fast path
    carry no host ports (BatchBuilder gives them sig 0 otherwise), so the
    cached value is vacuously true whenever it can be reused.

    `overlay` = (ovl_used [N,R], ovl_npods [N]) or None: nominated
    (preemptor) pods' resources folded into the FIT check only — the
    with-nominated pass of RunFilterPluginsWithNominatedPods
    (runtime/framework.go:1158); scoring stays overlay-free exactly like
    the reference's prioritizeNodes, which never sees nominated pods."""
    m = na.valid
    m &= (pod.node_name_id == 0) | (na.name_id == pod.node_name_id)
    m &= ~na.unschedulable | pod.tolerates_unsched
    m &= taint_filter_mask(na, pod)
    m &= selector_mask(na, pod)
    m &= ports_mask(carry.ports, pod.port_ids)
    taint_raw = taint_prefer_count(na, pod)
    na_raw = preferred_affinity_score(na, pod)
    s_img = image_locality_score(na, pod, axis=axis)
    if overlay is None:
        fit_used, fit_npods = carry.used, carry.npods
    else:
        # NOTE: no per-pod self-exclusion here — the cached fit_ok must be
        # signature-pure so same-sig pods with different nominations share
        # it; _eval_pod applies the one-row exclusion delta on top
        fit_used = carry.used + overlay[0]
        fit_npods = carry.npods + overlay[1]
    fit_ok = fit_mask(na.cap, fit_used, fit_npods, na.allowed_pods, pod.req)
    s_fit, s_bal = _fit_scores(cfg, na, carry, pod)
    return m, taint_raw, na_raw, s_img, fit_ok, s_fit, s_bal


def _row_refresh(cfg: ScoreConfig, na: NodeArrays, c2: Carry, pod: PodRow,
                 best: jnp.ndarray, gate: jnp.ndarray, cache: SigCache,
                 overlay=None) -> SigCache:
    """Recompute fit_ok/s_fit/s_bal for the single row the placement touched
    (everything else in the cache is carry-independent)."""
    cols = jnp.array(cfg.score_cols, jnp.int32)
    nz = jnp.array(cfg.col_nonzero)
    slots = jnp.array(cfg.nonzero_slot, jnp.int32)
    cap_row = na.cap[best]
    used_row = c2.used[best]
    fit_used_row = used_row if overlay is None else used_row + overlay[0][best]
    fit_npods = (c2.npods[best] if overlay is None
                 else c2.npods[best] + overlay[1][best])
    fit_ok_b = ((fit_npods + 1 <= na.allowed_pods[best])
                & jnp.all((pod.req == 0) | (fit_used_row + pod.req <= cap_row)))
    cap_r = cap_row[cols][None, :]
    used_nz_r = c2.nonzero_used[best][slots] + pod.nonzero_req[slots]
    used_pl_r = used_row[cols] + pod.req[cols]
    used_cols_r = jnp.where(nz, used_nz_r, used_pl_r)[None, :]
    s_fit_b = least_allocated(cfg, cap_r, used_cols_r)[0]
    s_bal_b = jnp.where(pod.skip_balanced, 0,
                        balanced_allocation(cap_r, used_pl_r[None, :])[0])
    return SigCache(
        sig=pod.sig,
        static_mask=cache.static_mask,
        taint_raw=cache.taint_raw,
        na_raw=cache.na_raw,
        s_img=cache.s_img,
        fit_ok=cache.fit_ok.at[best].set(
            jnp.where(gate, fit_ok_b, cache.fit_ok[best])),
        s_fit=cache.s_fit.at[best].set(
            jnp.where(gate, s_fit_b, cache.s_fit[best])),
        s_bal=cache.s_bal.at[best].set(
            jnp.where(gate, s_bal_b, cache.s_bal[best])),
    )


def _eval_pod(cfg: ScoreConfig, na: NodeArrays, carry: Carry, pod: PodRow,
              axis: str | None = None, groups: GroupsDev | None = None,
              tidx=None, n_global: int | None = None,
              fam: GroupFamilies | None = None, overlay=None):
    """Feasibility + total score for one pod over all nodes → (mask, score,
    parts). Consults the signature cache: a pod whose sig matches the carry's
    reuses every carry-independent kernel (the expensive ones). Group kernels
    (spread/inter-pod affinity) are carry-COUPLED — every placement can move
    their counts for every signature — so they always evaluate live and are
    never cached. `axis` names the mesh axis when `na`/`carry` hold one node
    shard."""
    cache = carry.cache
    use_fast = (pod.sig != 0) & (pod.sig == cache.sig)
    m, taint_raw, na_raw, s_img, fit_ok, s_fit, s_bal = lax.cond(
        use_fast,
        lambda: (cache.static_mask, cache.taint_raw, cache.na_raw,
                 cache.s_img, cache.fit_ok, cache.s_fit, cache.s_bal),
        lambda: _slow_parts(cfg, na, carry, pod, axis=axis, overlay=overlay))

    fit_ok_eff = fit_ok
    if overlay is not None and pod.nom_idx is not None:
        # per-pod self-exclusion delta (framework.go:1183 skips the pod's
        # own nomination): recompute fit at the ONE row the pod's own
        # nomination occupies, minus its own contribution. Applied to the
        # EFFECTIVE mask only — the cached fit_ok stays signature-pure so
        # same-sig pods with different nominations share the fast path.
        safe = jnp.maximum(pod.nom_idx, 0)
        own_used = carry.used[safe] + overlay[0][safe] - pod.req
        own_npods = carry.npods[safe] + overlay[1][safe] - 1
        own_fit = ((own_npods + 1 <= na.allowed_pods[safe])
                   & jnp.all((pod.req == 0)
                             | (own_used + pod.req <= na.cap[safe])))
        fit_ok_eff = fit_ok.at[safe].set(
            jnp.where(pod.nom_idx >= 0, own_fit, fit_ok[safe]))

    feasible = m & fit_ok_eff
    if groups is not None:
        # fold in BEFORE normalization: the host runtime normalizes over the
        # fully-filtered node list, so a group-filtered node must not set the
        # normalization max (runtime/framework.go:1286-1390 semantics)
        feasible &= group_mask(groups, carry.groups, tidx, axis=axis,
                               fam=fam)
    s_taint = default_normalize(taint_raw, feasible, reverse=True, axis=axis)
    s_na = default_normalize(na_raw, feasible, reverse=False, axis=axis)
    total = (cfg.w_fit * s_fit + cfg.w_balanced * s_bal
             + cfg.w_taint * s_taint + cfg.w_node_affinity * s_na
             + cfg.w_image * s_img)
    if groups is not None:
        total = total + group_scores(cfg.w_spread, cfg.w_ipa, groups,
                                     carry.groups, tidx, feasible,
                                     axis=axis, n_global=n_global, fam=fam)
    parts = SigCache(sig=pod.sig, static_mask=m, taint_raw=taint_raw,
                     na_raw=na_raw, s_img=s_img, fit_ok=fit_ok, s_fit=s_fit,
                     s_bal=s_bal)
    return feasible, total, parts


# ---------------------------------------------------------------------------
# failure diagnosis reduction
#
# The host oracle recovers "why did this pod fail" by replaying every filter
# plugin over every node in Python (find_nodes_that_pass_filters). The same
# information is already present in the device filter masks; this reduction
# attributes every rejected node to its FIRST failing filter in the host
# plugin order — the sequential-filter semantics of run_filter_plugins,
# where a node's status comes from the first plugin that rejects it — plus
# the per-resource fit detail the NodeResourcesFit reasons need.

# reason slots (host filter order; plugins the device kernels model)
DIAG_FEASIBLE = 0
DIAG_INVALID = -1                 # padding / freed node row
DIAG_NODE_UNSCHEDULABLE = 1
DIAG_NODE_NAME = 2
DIAG_TAINT = 3
DIAG_NODE_AFFINITY = 4
DIAG_PORTS = 5
DIAG_FIT = 6
DIAG_SPREAD_LABEL = 7             # missing topology key (unresolvable)
DIAG_SPREAD_SKEW = 8
DIAG_IPA_AFFINITY = 9
DIAG_IPA_ANTI = 10
DIAG_IPA_EXISTING_ANTI = 11


def _diagnose_masks(na: NodeArrays, pod: PodRow, gd, gc, tidx, fam):
    """Per-node first-failing-filter slot + fit detail, all [N]-shaped."""
    from ..ops.groups import group_reason_masks

    unsched_ok = ~na.unschedulable | pod.tolerates_unsched
    name_ok = (pod.node_name_id == 0) | (na.name_id == pod.node_name_id)
    taint_ok = taint_filter_mask(na, pod)
    sel_ok = selector_mask(na, pod)
    ports_ok = ports_mask(na.ports, pod.port_ids)
    pods_fail = na.npods + 1 > na.allowed_pods
    cols_fail = (pod.req[None, :] != 0) & (na.used + pod.req[None, :]
                                           > na.cap)           # [N, R]
    fit_ok = ~pods_fail & ~jnp.any(cols_fail, axis=1)
    n = na.valid.shape[0]
    false = jnp.zeros((n,), bool)
    if gd is not None:
        spr_missing, spr_skew, aff_f, anti_f, exist_f = group_reason_masks(
            gd, gc, tidx, fam)
    else:
        spr_missing = spr_skew = aff_f = anti_f = exist_f = false
    slot = jnp.select(
        [~na.valid,
         ~unsched_ok, ~name_ok, ~taint_ok, ~sel_ok, ~ports_ok, ~fit_ok,
         spr_missing, spr_skew, aff_f, anti_f, exist_f],
        [jnp.int32(DIAG_INVALID), jnp.int32(DIAG_NODE_UNSCHEDULABLE),
         jnp.int32(DIAG_NODE_NAME), jnp.int32(DIAG_TAINT),
         jnp.int32(DIAG_NODE_AFFINITY), jnp.int32(DIAG_PORTS),
         jnp.int32(DIAG_FIT), jnp.int32(DIAG_SPREAD_LABEL),
         jnp.int32(DIAG_SPREAD_SKEW), jnp.int32(DIAG_IPA_AFFINITY),
         jnp.int32(DIAG_IPA_ANTI), jnp.int32(DIAG_IPA_EXISTING_ANTI)],
        default=jnp.int32(DIAG_FEASIBLE))
    return slot, pods_fail, cols_fail


@functools.partial(jax.jit, static_argnames=("fam",))
def _diagnose_groups(na: NodeArrays, table: PodTableDev, tidx, gd, gc, fam):
    pod = _gather_row(table, PodXs(valid=jnp.bool_(True), sig=jnp.int32(0),
                                   tidx=tidx))
    return _diagnose_masks(na, pod, gd, gc, tidx, fam)


@jax.jit
def _diagnose_lean(na: NodeArrays, table: PodTableDev, tidx):
    pod = _gather_row(table, PodXs(valid=jnp.bool_(True), sig=jnp.int32(0),
                                   tidx=tidx))
    return _diagnose_masks(na, pod, None, None, tidx, None)


def diagnose_row(na: NodeArrays, table: PodTableDev, tidx: int,
                 gd=None, gc=None, fam=None):
    """Reduce the filter masks of signature row `tidx` against node state
    `na` (used/npods/ports = the post-commit truth) into
    (slot i32 [N], fit_pods_fail bool [N], fit_cols_fail bool [N, R]):
    `slot` holds each node's first failing filter (DIAG_*), the fit arrays
    carry the per-reason detail for DIAG_FIT nodes ("Too many pods" /
    per-column Insufficient)."""
    if gd is not None:
        na, table, gd, gc = RAILS.stage((na, table, gd, gc))
        return LEDGER.measured_call("diagnose", _diagnose_groups, na, table,
                                    jnp.int32(tidx), gd, gc, fam)
    na, table = RAILS.stage((na, table))
    return LEDGER.measured_call("diagnose", _diagnose_lean, na, table,
                                jnp.int32(tidx))


# ---------------------------------------------------------------------------
# decision provenance: per-plugin score decomposition (ISSUE 10)
#
# diagnose_row answers "why did every node REJECT this pod"; explain_row
# answers the complement — "why did the winning node WIN": the per-plugin
# score columns of the top-k feasible nodes, evaluated through the exact
# scan-step formula (_eval_pod), so the reported winner and margin are
# bit-identical to the argmax the dispatched program took at the same
# carry state.

# explain column order (host rendering maps these to plugin names):
# weighted Fit, BalancedAllocation, TaintToleration, NodeAffinity,
# ImageLocality, and the combined group contribution
# (PodTopologySpread + InterPodAffinity — group_scores returns their sum)
EXPLAIN_COLUMNS = ("NodeResourcesFit", "NodeResourcesBalancedAllocation",
                   "TaintToleration", "NodeAffinity", "ImageLocality",
                   "PodTopologySpread+InterPodAffinity")


def _explain_masks(cfg: ScoreConfig, na: NodeArrays, carry: Carry, tidx,
                   k: int, table: PodTableDev, groups, fam):
    pod = _gather_row(table, PodXs(valid=jnp.bool_(True),
                                   sig=jnp.int32(0), tidx=tidx))
    feasible, total, parts = _eval_pod(cfg, na, carry, pod, groups=groups,
                                       tidx=tidx, fam=fam)
    masked = jnp.where(feasible, total, jnp.int64(-1))
    s_taint = default_normalize(parts.taint_raw, feasible, reverse=True)
    s_na = default_normalize(parts.na_raw, feasible, reverse=False)
    base = (cfg.w_fit * parts.s_fit + cfg.w_balanced * parts.s_bal
            + cfg.w_taint * s_taint + cfg.w_node_affinity * s_na
            + cfg.w_image * parts.s_img)
    cols = jnp.stack([cfg.w_fit * parts.s_fit,
                      cfg.w_balanced * parts.s_bal,
                      cfg.w_taint * s_taint,
                      cfg.w_node_affinity * s_na,
                      cfg.w_image * parts.s_img,
                      total - base], axis=1)            # [N, 6]
    # scores bounded by 100·Σweights: the int32 top_k (ties → lowest
    # index) reproduces the scan's first-max argmax tie-break exactly
    _, idx = lax.top_k(masked.astype(jnp.int32), k)
    idx = idx.astype(jnp.int32)
    return idx, masked[idx], cols[idx], jnp.sum(feasible).astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("cfg", "k", "fam"))
def _explain_groups(cfg: ScoreConfig, na: NodeArrays, carry: Carry,
                    table: PodTableDev, tidx, k: int, gd, fam):
    return _explain_masks(cfg, na, carry, tidx, k, table, gd, fam)


@functools.partial(jax.jit, static_argnames=("cfg", "k"))
def _explain_lean(cfg: ScoreConfig, na: NodeArrays, carry: Carry,
                  table: PodTableDev, tidx, k: int):
    return _explain_masks(cfg, na, carry, tidx, k, table, None, None)


def explain_row(cfg: ScoreConfig, na: NodeArrays, carry: Carry,
                table: PodTableDev, tidx: int, k: int = 8, gd=None,
                fam=None):
    """Score decomposition of signature row `tidx` against `carry`:
    (topk_idx i32 [k], topk_total i64 [k] (-1 = infeasible slot),
    topk_cols i64 [k, 6] per-plugin weighted contributions in
    EXPLAIN_COLUMNS order, feasible_count i32). topk_idx[0] is
    bit-identical to the argmax the scan/plan program takes for this row
    at this carry (same _eval_pod formula, same tie-break); the win
    margin is topk_total[0] - topk_total[1]."""
    if gd is not None:
        na, carry, table = RAILS.stage((na, carry, table))
        gd = RAILS.stage(gd)
        return LEDGER.measured_call("explain_row", _explain_groups, cfg,
                                    na, carry, table, jnp.int32(tidx), k,
                                    gd, fam)
    na, carry, table = RAILS.stage((na, carry, table))
    return LEDGER.measured_call("explain_row", _explain_lean, cfg, na,
                                carry, table, jnp.int32(tidx), k)


@jax.jit
def _scatter_rows_jit(dev: NodeArrays, idx, rows: NodeArrays) -> NodeArrays:
    return NodeArrays(*(d.at[idx].set(r) for d, r in zip(dev, rows)))


def scatter_rows(dev: NodeArrays, idx, rows: NodeArrays) -> NodeArrays:
    """Generation-diff snapshot upload (ISSUE 9): scatter `rows` (one
    gathered staging row per dirty node, [D, ...] with D a pow2 bucket;
    duplicate indices carry identical rows) into the device-resident
    NodeArrays at `idx` (i32 [D]). The H2D transfer is the rows — O(dirty
    × row width) instead of the O(N × row width) full re-upload.

    Deliberately NON-donating: the previous device copy was handed to
    callers (in-flight drains hold it as `pd.na`; tests hold it across
    mutations), so the entry must materialize fresh output buffers — the
    on-device copy is cheap next to the tunnel transfer it saves."""
    dev, idx, rows = RAILS.stage((dev, idx, rows))
    return LEDGER.measured_call("scatter_rows", _scatter_rows_jit, dev,
                                idx, rows)


@functools.partial(jax.jit, static_argnames=("cfg",))
def _score_probe_jit(cfg: ScoreConfig, na: NodeArrays, carry: Carry,
                     table: PodTableDev, tidx):
    pod = _gather_row(table, PodXs(valid=jnp.bool_(True), sig=jnp.int32(0),
                                   tidx=tidx, nom_idx=jnp.int32(-1)))
    s_fit, s_bal = _fit_scores(cfg, na, carry, pod)
    # re-derive the FLOAT balanced-allocation intermediates: the int
    # floor in balanced_allocation() buries a NaN as garbage, so the
    # probe must observe the std surface before the cast
    cols = jnp.array(cfg.score_cols, jnp.int32)
    cap_cols = na.cap[:, cols]
    used_bal = carry.used[:, cols] + pod.req[cols][None, :]
    col_ok = cap_cols > 0
    frac = jnp.where(col_ok,
                     jnp.minimum(used_bal / jnp.maximum(cap_cols, 1), 1.0),
                     0.0)
    cnt = jnp.sum(col_ok, axis=1)
    mean = jnp.sum(frac, axis=1) / jnp.maximum(cnt, 1)
    var = (jnp.sum(jnp.where(col_ok, (frac - mean[:, None]) ** 2, 0.0),
                   axis=1) / jnp.maximum(cnt, 1))
    std = jnp.sqrt(var)
    total = (cfg.w_fit * s_fit + cfg.w_balanced * s_bal).astype(jnp.float32)
    return total, std.astype(jnp.float32)


def score_probe(cfg: ScoreConfig, na: NodeArrays, carry: Carry,
                table: PodTableDev, tidx):
    """Score surface of signature row `tidx` against `carry`, in float:
    (combined fit+balanced score f32 [N], balanced-allocation std f32
    [N]). The sanitizer rails' NaN/inf probe (analysis/rails.py
    check_scores) — one tiny shape-stable kernel per drain."""
    return LEDGER.measured_call("score_probe", _score_probe_jit, cfg, na,
                                carry, table, tidx)


# ---------------------------------------------------------------------------
# cluster analytics: on-device state probe (ISSUE 13)
#
# The carry resident in HBM after every drain IS the cluster state — one
# reduction over it yields the utilization/fragmentation/imbalance
# signals ROADMAP items 2 and 3 consume, at zero extra h2d. Sampled per
# drain by the scheduler, surfaced via /debug/cluster, the
# scheduler_cluster_* gauge families, the flight recorder and the
# telemetry timeline.
#
# Bit-parity contract (tests/test_cluster_probe.py holds this vs a numpy
# oracle): every cross-node reduction is exact int64 arithmetic (masked
# sums, scatter-adds of integers); floats appear only in elementwise
# division/compare, sort, and gather — all deterministic between XLA and
# numpy, so the probe is bit-reproducible.

# per-resource stat columns of the probe's first output, in order
PROBE_STATS = ("p50", "p90", "p99", "max", "mean", "frag", "stranded")
# nearest-rank percentile ranks (idx = floor(q·(m-1) + 0.5) over the m
# nodes advertising the resource)
_PROBE_QS = (0.5, 0.9, 0.99)
# a node whose bottleneck-resource utilization reaches this is "tight":
# its remaining free capacity in OTHER resources counts as stranded
PROBE_TIGHT = 0.95


def _probe_math(cap_in, valid, used_in, npods, dom, ndom: int):
    """The probe reduction on plain arrays (cap i64 [N, R], valid bool
    [N], used i64 [N, R], npods i32 [N], dom i32 [N]) — shared between
    the single-device jit below and the mesh twin
    (parallel/sharding.py cluster_probe_sharded), which all-gathers its
    shards and runs these exact ops so the outputs stay bit-identical."""
    f32, i64 = jnp.float32, jnp.int64
    # a (node, resource) cell participates when the node is valid and
    # advertises capacity for the resource
    part = valid[:, None] & (cap_in > 0)                        # bool [N, R]
    used = jnp.where(part, used_in, 0).astype(i64)              # i64 [N, R]
    cap = jnp.where(part, cap_in, 0).astype(i64)                # i64 [N, R]
    util = jnp.where(part,
                     used.astype(f32) / jnp.maximum(cap, 1).astype(f32),
                     -1.0).astype(f32)                          # f32 [N, R]
    m = jnp.sum(part, axis=0).astype(jnp.int32)                 # i32 [R]
    n_total = util.shape[0]

    # percentiles: non-participants sort to the front as -1, so the m
    # participants occupy [N-m, N) of each sorted column — nearest-rank
    # gather at N-m+idx. idx math in f64 (exact for these magnitudes) so
    # the numpy oracle lands on the identical element.
    srt = jnp.sort(util, axis=0)                                # f32 [N, R]
    mf = m.astype(jnp.float64)
    qcols = []
    for q in _PROBE_QS + (1.0,):
        idx = jnp.floor(q * (mf - 1.0) + 0.5).astype(jnp.int32)
        at = jnp.clip(n_total - m + idx, 0, n_total - 1)
        qcols.append(jnp.where(m > 0,
                               jnp.take_along_axis(srt, at[None, :],
                                                   axis=0)[0], 0.0))

    # aggregate mean utilization: exact int64 sums, one float division
    sum_used = jnp.sum(used, axis=0)                            # i64 [R]
    sum_cap = jnp.sum(cap, axis=0)                              # i64 [R]
    mean = jnp.where(sum_cap > 0,
                     sum_used.astype(f32) / jnp.maximum(sum_cap, 1).astype(f32),
                     0.0)

    # fragmentation: 1 - (largest single free block / total free) — 0
    # when one node could absorb the whole free pool, → 1 as the free
    # capacity shatters into many small holes
    free = cap - used                                           # i64 [N, R]
    tot_free = jnp.sum(free, axis=0)                            # i64 [R]
    max_free = jnp.max(free, axis=0)                            # i64 [R]
    frag = jnp.where(tot_free > 0,
                     1.0 - max_free.astype(f32) /
                     jnp.maximum(tot_free, 1).astype(f32), 0.0)

    # stranded capacity: free units sitting on nodes whose bottleneck
    # resource is already ≥ PROBE_TIGHT utilized — capacity that exists
    # but cannot host a balanced pod
    bottleneck = jnp.max(jnp.where(part, util, 0.0), axis=1)    # f32 [N]
    tight = valid & (bottleneck >= PROBE_TIGHT)                 # bool [N]
    stranded_free = jnp.sum(jnp.where(tight[:, None], free, 0), axis=0)
    stranded = jnp.where(tot_free > 0,
                         stranded_free.astype(f32) /
                         jnp.maximum(tot_free, 1).astype(f32), 0.0)

    per_res = jnp.stack(qcols + [mean, frag, stranded], axis=1)  # f32 [R, 7]

    # topology-domain imbalance over the gang engine's Tesserae dom-id
    # column: per-domain pod density (pods per valid node), exact int64
    # scatter-adds; spread = max - min over populated domains
    dclip = jnp.clip(dom.astype(jnp.int32), 0, ndom - 1)
    dom_pods = jnp.zeros((ndom,), i64).at[dclip].add(
        jnp.where(valid, npods, 0).astype(i64))
    dom_nodes = jnp.zeros((ndom,), i64).at[dclip].add(valid.astype(i64))
    has = dom_nodes > 0
    load = jnp.where(has,
                     dom_pods.astype(f32) /
                     jnp.maximum(dom_nodes, 1).astype(f32), 0.0)
    any_dom = jnp.any(has)
    dmax = jnp.max(jnp.where(has, load, -jnp.inf))
    dmin = jnp.min(jnp.where(has, load, jnp.inf))
    dom_stats = jnp.stack([
        jnp.sum(has).astype(f32),
        jnp.where(any_dom, dmax, 0.0).astype(f32),
        jnp.where(any_dom, dmin, 0.0).astype(f32),
        jnp.where(any_dom, dmax - dmin, 0.0).astype(f32),
    ])                                                          # f32 [4]
    return per_res, dom_stats, jnp.sum(valid).astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("ndom",))
def _cluster_probe_jit(na: NodeArrays, carry: Carry, dom, ndom: int):
    return _probe_math(na.cap, na.valid, carry.used, carry.npods, dom,
                       ndom)


def cluster_probe(na: NodeArrays, carry: Carry, dom, ndom: int):
    """On-device cluster-state reduction over the resident carry:
    (per_res f32 [R, 7] — PROBE_STATS columns per resource, dom_stats
    f32 [4] — (populated domains, max, min, spread) of per-domain pod
    density, valid_count i32). `dom` is the gang engine's topology
    dom-id column (i32 [N]), `ndom` its static domain count (jit cache
    key — stable per cluster topology). Deliberately NON-donating: the
    carry stays resident for the next drain; the probe only reads it."""
    na, carry, dom = RAILS.stage((na, carry, dom))
    return LEDGER.measured_call("cluster_probe", _cluster_probe_jit, na,
                                carry, dom, ndom)


def _apply_assignment(carry: Carry, pod: PodRow, best: jnp.ndarray,
                      assigned: jnp.ndarray) -> Carry:
    onehot = (jnp.arange(carry.npods.shape[0], dtype=jnp.int32) == best) & assigned
    used = carry.used + jnp.where(onehot[:, None], pod.req[None, :], 0)
    nonzero = carry.nonzero_used + jnp.where(onehot[:, None],
                                             pod.nonzero_req[None, :], 0)
    npods = carry.npods + onehot.astype(carry.npods.dtype)
    # place pod port ids into the first free slots of the chosen node's row
    row = carry.ports[best]
    free = row == 0
    rank = jnp.cumsum(free) - 1
    pod_ports = pod.port_ids
    nport = pod_ports.shape[0]
    incoming = jnp.where((rank >= 0) & (rank < nport) & free,
                         pod_ports[jnp.clip(rank, 0, nport - 1)], 0)
    new_row = jnp.where(free, incoming, row)
    ports = jnp.where(
        (onehot[:, None]) & (jnp.any(pod_ports != 0)),
        jnp.broadcast_to(new_row, carry.ports.shape), carry.ports)
    return carry._replace(used=used, nonzero_used=nonzero, npods=npods,
                          ports=ports)


def _run_batch_impl(cfg: ScoreConfig, na: NodeArrays, carry: Carry, pods: PodXs,
                    table: PodTableDev, groups: GroupsDev | None = None,
                    fam: GroupFamilies | None = None, overlay=None):
    """Scan the batch; returns (final carry, assignments int32[B] (-1 = none)).

    `groups` (with `carry.groups`) enables the PodTopologySpread /
    InterPodAffinity kernels; pass None (and carry.groups None) for the lean
    program — the two compile to distinct executables. `fam` (static)
    trims the group kernels to the active constraint families — a
    spread-only batch compiles a program with zero inter-pod-affinity
    compute (≈5-8× per step on TPU); see groups.GroupFamilies."""

    n = na.npods.shape[0]
    consume_nom = overlay is not None and pods.nom_idx is not None

    def step(state, x: PodXs):
        c, ovl = state
        pod = _gather_row(table, x)
        mask, score, parts = _eval_pod(cfg, na, c, pod, groups=groups,
                                       tidx=x.tidx, fam=fam, overlay=ovl)
        masked = jnp.where(mask, score, -1)
        best = jnp.argmax(masked).astype(jnp.int32)
        assigned = (masked[best] >= 0) & pod.valid
        c2 = _apply_assignment(c, pod, best, assigned)
        if consume_nom:
            # a bound pod's nomination is deleted (the commit calls
            # nominator.delete): consume its contribution so later pods in
            # the scan see the same overlay the host sequential path would
            safe = jnp.maximum(pod.nom_idx, 0)
            gate = assigned & (pod.nom_idx >= 0)
            ovl = (ovl[0].at[safe].add(
                       jnp.where(gate, -pod.req, 0)),
                   ovl[1].at[safe].add(
                       jnp.where(gate, -1, 0).astype(ovl[1].dtype)))
        c2 = c2._replace(cache=_row_refresh(cfg, na, c2, pod, best,
                                            assigned, parts,
                                            overlay=ovl))
        if groups is not None:
            c2 = c2._replace(groups=group_update(
                groups, c2.groups, x.tidx,
                pick=lambda arr: arr[..., best],
                is_chosen=jnp.arange(n, dtype=jnp.int32) == best,
                gate=assigned, fam=fam))
        return (c2, ovl), jnp.where(assigned, best, -1)

    (final, _ovl), assignments = lax.scan(step, (carry, overlay), pods)
    return final, assignments


@functools.lru_cache(maxsize=None)
def _run_batch_fn(donate: bool):
    return jax.jit(_run_batch_impl, static_argnames=("cfg", "fam"),
                   donate_argnums=(2,) if donate else ())


def run_batch(cfg: ScoreConfig, na: NodeArrays, carry: Carry, pods: PodXs,
              table: PodTableDev, groups: GroupsDev | None = None,
              fam: GroupFamilies | None = None, overlay=None):
    """Jitted entry for `_run_batch_impl`; the input carry is DONATED on
    accelerator backends — the scan chain consumes it, so XLA reuses its
    buffers for the output carry instead of copying the resident node
    state on every dispatch. CPU (no donation support) compiles without
    the donation to avoid per-dispatch warnings. Callers that rewind and
    replay (the uniform path's exactness fallback) must therefore never
    reuse a carry already consumed by run_batch — the scheduler keeps
    carry_in only for run_uniform records, which do not donate."""
    donate = jax.default_backend() != "cpu"
    fn = _run_batch_fn(donate)
    na, carry, pods, table, groups, overlay = RAILS.stage(
        (na, carry, pods, table, groups, overlay))
    out = LEDGER.measured_call("run_batch", fn, cfg, na, carry, pods,
                               table, groups, fam, overlay,
                               donated=carry if donate else None)
    if not donate:
        RAILS.poison_donated(carry, out)
    return out


def _uniform_matrix(cfg: ScoreConfig, na: NodeArrays, fit_used, fit_npods,
                    score_used, score_nz, cand, pod: PodRow, J: int):
    """The closed-form score matrix [K, J]: entry j = fit + post-placement
    scores of the (j+1)-th run-pod on candidate k. Built column-by-column
    (static unroll) so every device op is a 2-D [K, J] elementwise — no
    [K, J, C] tensors with a tiny minor dim that would waste the 8×128
    vector tiles. Shared by run_uniform (lean path) and the wave merge
    tier (run_wave). Returns (fit_kj, s_fit_kj, s_bal_kj)."""
    K = cand.shape[0]
    j1 = jnp.arange(1, J + 1, dtype=jnp.int64)[None, :]        # [1, J]
    npods_kj = (fit_npods[cand][:, None]
                + j1.astype(fit_npods.dtype))
    fit_kj = npods_kj <= na.allowed_pods[cand][:, None]
    R = na.cap.shape[1]
    for r in range(R):
        cap_r = na.cap[cand, r][:, None]
        used_r = fit_used[cand, r][:, None] + j1 * pod.req[r]
        fit_kj &= (pod.req[r] == 0) | (used_r <= cap_r)

    # LeastAllocated / MostAllocated (least_allocated.go:30-60) unrolled
    # over the score columns; BalancedAllocation via the 2-column closed
    # form |f0−f1|/2 the reference special-cases (balanced_allocation.go
    # :224-227) when C==2, generic otherwise.
    w = cfg.col_weights
    score_sum = jnp.zeros((K, J), jnp.int64)
    w_sum = jnp.zeros((K, J), jnp.int64)
    fracs = []
    bal_cols_ok = []
    for ci, col in enumerate(cfg.score_cols):
        cap_c = na.cap[cand, col][:, None]                      # [K, 1]
        used_pl = score_used[cand, col][:, None] + j1 * pod.req[col]
        if cfg.col_nonzero[ci]:
            slot = cfg.nonzero_slot[ci]
            used_c = (score_nz[cand, slot][:, None]
                      + j1 * pod.nonzero_req[slot])
        else:
            used_c = used_pl
        col_ok = cap_c > 0
        if cfg.strategy == "MostAllocated":
            raw = jnp.where((cap_c == 0) | (used_c > cap_c), 0,
                            used_c * MAX_SCORE // jnp.maximum(cap_c, 1))
        else:
            raw = jnp.where((cap_c == 0) | (used_c > cap_c), 0,
                            (cap_c - used_c) * MAX_SCORE // jnp.maximum(cap_c, 1))
        score_sum += jnp.where(col_ok, raw * w[ci], 0)
        w_sum += jnp.where(col_ok, jnp.int64(w[ci]), 0)
        fracs.append(jnp.where(
            col_ok, jnp.minimum(used_pl / jnp.maximum(cap_c, 1), 1.0), 0.0))
        bal_cols_ok.append(col_ok)
    s_fit_kj = jnp.where(w_sum > 0, score_sum // jnp.maximum(w_sum, 1), 0)
    # same float-op structure as balanced_allocation() — stacked jnp.sum
    # reductions over the column axis, not a sequential Python sum chain —
    # so XLA lowers the same associativity and results stay bit-identical
    # to the scan's (an |f0−f1|/2 shortcut, or a different reduction order,
    # could differ by an ulp at floor boundaries and break parity)
    frac_kjc = jnp.stack(fracs, axis=-1)                 # [K, J, C]
    ok_kjc = jnp.stack(bal_cols_ok, axis=-1) & jnp.ones(
        frac_kjc.shape, bool)
    cnt = jnp.sum(ok_kjc, axis=-1)
    mean = jnp.sum(frac_kjc, axis=-1) / jnp.maximum(cnt, 1)
    var = jnp.sum(jnp.where(ok_kjc, (frac_kjc - mean[..., None]) ** 2, 0.0),
                  axis=-1) / jnp.maximum(cnt, 1)
    std = jnp.sqrt(var)
    s_bal_kj = jnp.where(
        pod.skip_balanced, 0,
        jnp.floor((1.0 - std) * MAX_SCORE + 1e-9).astype(jnp.int64))
    return fit_kj, s_fit_kj, s_bal_kj


def _uniform_core(cfg: ScoreConfig, na: NodeArrays, carry: Carry, x: PodXs,
                  table: PodTableDev, n_actual, L: int, K: int, J: int,
                  overlay=None):
    """Closed-form batch assignment for a run of SAME-SIGNATURE pods — the
    top-k trick of reference runtime/batch.go:97 (sortedNodes.Pop) taken to
    its TPU limit: the whole run becomes ONE top_k instead of L scan steps.

    Why it is exact (and when): during a same-signature run, a placement
    changes node state only on the chosen node, so every node's score is a
    pure function of how many run-pods it already holds: entry (k, j) =
    score of candidate node k after its (j+1)-th placement. The sequential
    greedy (scan) then consumes entries of this matrix in key order
    (score desc, node idx asc, j asc) — the standard k-way-merge argument,
    valid when each node's entry sequence is non-increasing. Therefore the
    exact greedy assignment = the top-L entries of the keyed matrix, and
    pod i gets the node of the i-th entry. Candidates = top-K initial
    scores suffice because the greedy's touched set is a prefix of that
    ordering (a node is first touched only when it is the argmax at its
    initial score).

    The returned `ok` flag is False — caller must discard the result and
    re-run (bigger J, or the scan) — when an exactness precondition fails
    on the actual data:
      * monotonicity: some candidate's masked score sequence increases in j
        (possible for BalancedAllocation on an unbalanced node, or the
        MostAllocated strategy);
      * normalization constancy: TaintToleration / preferred-NodeAffinity
        raw counts are nonzero over the feasible set, so their
        DefaultNormalize denominators could shift as nodes saturate
        mid-run (the scan recomputes them per pod; this path cannot);
      * depth overflow: some candidate received all J of its matrix
        entries (counts == J), meaning the greedy may have wanted even
        more placements there — the truncated matrix diverted them. J is
        a static depth chosen by the caller (≈ a few × L/nodes, TPU-tiled
        tiny); the scheduler escalates J on this failure.

    `x` carries ONE scalar entry (sig/tidx of the run's row); `n_actual` is
    the true run length (≤ L, the padded static length). Requires sig != 0
    (no host ports — the ports carry is untouched) and a lean carry
    (groups is None)."""
    pod = _gather_row(table, x)
    feasible0, total0, parts = _eval_pod(cfg, na, carry, pod,
                                         overlay=overlay)
    masked0 = jnp.where(feasible0, total0, jnp.int64(-1))
    # scores are bounded by 100·Σweights — int32 keys keep TPU sorts cheap
    _, cand = lax.top_k(masked0.astype(jnp.int32), K)  # ties → lowest index
    cand = cand.astype(jnp.int32)

    # static per-node score components (constant under the norm gate)
    s_taint = default_normalize(parts.taint_raw, feasible0, reverse=True)
    s_na = default_normalize(parts.na_raw, feasible0, reverse=False)
    # ImageLocality is unnormalized and carry-independent: safe to fold
    # into the per-candidate constant
    static_add = (cfg.w_taint * s_taint + cfg.w_node_affinity * s_na
                  + cfg.w_image * parts.s_img)[cand]
    static_m = parts.static_mask[cand]
    norm_ok = (jnp.max(jnp.where(feasible0, parts.taint_raw, 0)) == 0) & (
        jnp.max(jnp.where(feasible0, parts.na_raw, 0)) == 0)

    fit_npods = (carry.npods if overlay is None
                 else carry.npods + overlay[1])
    fit_used = carry.used if overlay is None else carry.used + overlay[0]
    fit_kj, s_fit_kj, s_bal_kj = _uniform_matrix(
        cfg, na, fit_used, fit_npods, carry.used, carry.nonzero_used,
        cand, pod, J)

    score_kj = (cfg.w_fit * s_fit_kj + cfg.w_balanced * s_bal_kj
                + static_add[:, None])
    masked_kj = jnp.where(static_m[:, None] & fit_kj, score_kj,
                          jnp.int64(-1))
    mono_ok = jnp.all(masked_kj[:, 1:] <= masked_kj[:, :-1])

    # key = (score desc, node idx asc, j asc); feasible keys ≥ -(M-1),
    # infeasible ≤ -M — strictly separated. int32 when the range allows
    # (score ≤ 100·Σweights): TPU sorts int32 ~2× faster than int64.
    n_nodes = na.cap.shape[0]
    score_max = MAX_SCORE * (cfg.w_fit + cfg.w_balanced + cfg.w_taint
                             + cfg.w_node_affinity + cfg.w_image)
    M = n_nodes * J
    key_dt = jnp.int32 if (score_max + 2) * M < 2 ** 31 else jnp.int64
    ent_id = (cand[:, None].astype(key_dt) * J
              + jnp.arange(J, dtype=key_dt)[None, :])
    flat_key = (masked_kj.astype(key_dt) * key_dt(M) - ent_id).reshape(K * J)
    top_vals, flat_i = lax.top_k(flat_key, L)
    krank = (flat_i // J).astype(jnp.int32)
    node_of = cand[krank]
    sel_ok = (top_vals > -key_dt(M)) & (jnp.arange(L) < n_actual)
    assignments = jnp.where(sel_ok, node_of, -1).astype(jnp.int32)

    counts = jnp.zeros((K,), jnp.int64).at[krank].add(sel_ok.astype(jnp.int64))
    # a candidate that consumed its whole column is truncation-suspect: the
    # exact greedy may have wanted more placements there
    depth_ok = jnp.all(counts < J)
    used = carry.used.at[cand].add(counts[:, None] * pod.req[None, :])
    nonzero = carry.nonzero_used.at[cand].add(
        counts[:, None] * pod.nonzero_req[None, :])
    npods = carry.npods.at[cand].add(counts.astype(carry.npods.dtype))

    # cache refresh: entry j=counts IS the next-pod evaluation for this sig
    ar = jnp.arange(K)
    cnt_i = jnp.minimum(counts, J - 1).astype(jnp.int32)
    new_cache = SigCache(
        sig=pod.sig,
        static_mask=parts.static_mask, taint_raw=parts.taint_raw,
        na_raw=parts.na_raw, s_img=parts.s_img,
        fit_ok=parts.fit_ok.at[cand].set(fit_kj[ar, cnt_i]),
        s_fit=parts.s_fit.at[cand].set(s_fit_kj[ar, cnt_i]),
        s_bal=parts.s_bal.at[cand].set(s_bal_kj[ar, cnt_i]))
    new_carry = carry._replace(used=used, nonzero_used=nonzero, npods=npods,
                               cache=new_cache)
    return new_carry, assignments, mono_ok & norm_ok, depth_ok


@functools.partial(jax.jit, static_argnames=("cfg", "L", "K", "J"))
def _run_uniform_jit(cfg: ScoreConfig, na: NodeArrays, carry: Carry, x: PodXs,
                     table: PodTableDev, n_actual, L: int, K: int, J: int,
                     overlay=None):
    new_carry, assignments, ok, depth_ok = _uniform_core(
        cfg, na, carry, x, table, n_actual, L, K, J, overlay)
    # pack [assignments; exact; depth] into ONE i32[L+2]: the tunneled-TPU
    # cost model is dominated by device→host round trips (~100ms each once
    # the first readback forces synchronous mode), so a run must cost the
    # caller exactly one readback — and with chained runs, none until the
    # end of the drain. packed[L] = semantic preconditions held (scan
    # otherwise); packed[L+1] = depth sufficed (escalate J otherwise).
    packed = jnp.concatenate([
        assignments,
        jnp.stack([ok, depth_ok]).astype(jnp.int32)])
    return new_carry, packed


def run_uniform(cfg: ScoreConfig, na: NodeArrays, carry: Carry, x: PodXs,
                table: PodTableDev, n_actual, L: int, K: int, J: int,
                overlay=None):
    """Ledger-instrumented entry for `_run_uniform_jit` (the closed-form
    top-L path; see its docstring for the exactness argument). Never
    donates: the scheduler keeps the input carry for rewind/replay."""
    na, carry, x, table, n_actual, overlay = RAILS.stage(
        (na, carry, x, table, n_actual, overlay))
    return LEDGER.measured_call("run_uniform", _run_uniform_jit, cfg, na,
                                carry, x, table, n_actual, L, K, J,
                                overlay=overlay)


# ---------------------------------------------------------------------------
# speculative wave placement: conflict-checked parallel group scheduling
# (arXiv:2508.04953 Tesserae-style batch placement with conflict repair,
# constrained to EXACT serial-greedy parity)


class WaveXs(NamedTuple):
    """Per-pod wave inputs ([W] = wave length, serial priority order)."""

    valid: jnp.ndarray   # bool [W]
    widx: jnp.ndarray    # i32 [W] — slot into the wave row set [S]


class _WaveState(NamedTuple):
    """In-dispatch scan state: node bookkeeping + the wave rows' group
    counters ([S] = distinct signatures in the wave) + conflict stats."""

    used: jnp.ndarray          # i64 [N, R]
    nonzero_used: jnp.ndarray  # i64 [N, 2]
    npods: jnp.ndarray         # i32 [N]
    fit_ok: jnp.ndarray        # bool [S, N]
    s_fit: jnp.ndarray         # i64 [S, N]
    s_bal: jnp.ndarray         # i64 [S, N]
    f_cnt: jnp.ndarray         # i32 [S, SC, N]
    s_cnt: jnp.ndarray         # i32 [S, SC, N]
    veto: jnp.ndarray          # i32 [S, N]
    a_cnt: jnp.ndarray         # i32 [S, TA, N]
    a_total: jnp.ndarray       # i64 [S]
    aa_cnt: jnp.ndarray        # i32 [S, TAA, N]
    iscore: jnp.ndarray        # i64 [S, N]
    cnt_sn: jnp.ndarray        # i32 [S, N] — accepted placements (fold input)
    clean: jnp.ndarray         # bool — no conflict seen yet
    n_conf: jnp.ndarray        # i32 — conflicting pods so far
    prefix: jnp.ndarray        # i32 — conflict-free prefix length
    # host-port bookkeeping (None unless the plan program compiles the
    # has_ports variant — a drain mixing host-port rows into the wave)
    ports: jnp.ndarray = None  # i32 [N, P]


def _run_wave_scan_impl(cfg: ScoreConfig, na: NodeArrays, carry: Carry,
                        xs: WaveXs, table: PodTableDev, wt, gd: GroupsDev,
                        statics, fam: GroupFamilies, norm_live: bool,
                        has_groups: bool, has_ports: bool = False):
    """One wave of group-constrained pods in ONE device dispatch.

    Phase A (speculative parallel scoring): every distinct signature's full
    kernel set — static filters, taint/affinity/image scores, fit scores —
    is evaluated ONCE against the same pre-wave carry ([S, N] surfaces),
    and each signature's speculative argmax is recorded. This is where the
    wave wins: the expensive kernels run S times per wave instead of once
    per pod.

    Phase B (conflict detection + repair, serial priority order): a scan
    over the wave re-derives each pod's EXACT serial decision from the
    Phase-A surfaces plus the accumulated in-wave deltas — fit/score
    refreshed at the touched nodes, group counters carried for the wave's
    consumer rows, normalizations re-reduced per step. A pod whose exact
    argmax differs from its signature's speculative choice is a CONFLICT
    (capacity oversubscription, topology-skew movement, affinity surface
    change); it is repaired in place by taking the exact choice, so the
    wave's assignments are bit-identical to the serial scan in every case
    — an all-conflict wave degenerates to a serial re-evaluation without
    error, it just stops being fast. The conflict count and the
    conflict-free prefix length are returned for observability.

    Epilogue: the accepted placements fold into the FULL group carry with
    one batched pass (ops/groups.py wave_fold — additivity makes the fold
    order-independent), so the next wave (or scan segment) continues from
    an exact resident carry with no host round trip.

    Preconditions (the scheduler gates): single device, no nominated-pod
    overlay, every wave pod sig != 0 (no host ports), groups active, and
    `norm_live=False` only under ops.hostgreedy.static_norm_ok. Returns
    (new carry, packed i32 [W+2]): assignments, then n_conflicts, then the
    conflict-free prefix length."""
    from .groups import (GroupView, group_mask_view, group_scores_view,
                         wave_fold)

    gc = carry.groups
    S = wt.shape[0]
    n = na.cap.shape[0]
    fields = {name: getattr(table, name)[wt] for name in PodTableDev._fields}
    rows = PodRow(valid=jnp.ones((S,), bool),
                  sig=jnp.ones((S,), jnp.int32), **fields)

    # ---- Phase A: per-signature surfaces at the pre-wave carry. The
    # carry-independent ones arrive precomputed (wave_statics, cached by
    # the scheduler per signature); only the fit kernels evaluate here.
    static_mask, taint_raw, na_raw, s_img = statics

    def fit_one(pod: PodRow):
        fit_ok = fit_mask(na.cap, carry.used, carry.npods, na.allowed_pods,
                          pod.req)
        s_fit, s_bal = _fit_scores(cfg, na, carry, pod)
        return fit_ok, s_fit, s_bal

    fit0, sfit0, sbal0 = jax.vmap(fit_one)(rows)

    # wave-local group statics (gathered once; [S, ...]); a LEAN wave
    # (non-interacting signatures, no group constraints anywhere) carries
    # no group state at all — the issue's "disjoint signatures placed in
    # a single wave" case, which previously thrashed the one-slot
    # signature cache with a full kernel recompute on every alternation
    if has_groups:
        f_act = gd.spr_f_active[wt]
        f_skew = gd.spr_f_max_skew[wt]
        f_self = gd.spr_f_self[wt]
        f_minz = gc.spr_f_min_zero[wt]
        f_tv = gd.spr_f_tv[wt]
        f_elig = gd.spr_f_elig[wt]
        s_act = gd.spr_s_active[wt]
        s_skew = gd.spr_s_max_skew[wt]
        s_ishost = gd.spr_s_is_host[wt]
        s_tv = gd.spr_s_tv[wt]
        s_elig = gd.spr_s_elig[wt]
        s_keys = gd.spr_s_keys_ok[wt]
        s_dom = gd.spr_s_dom[wt]
        ra_act = gd.ipa_ra_active[wt]
        ra_tv = gd.ipa_ra_tv[wt]
        raa_act = gd.ipa_raa_active[wt]
        raa_tv = gd.ipa_raa_tv[wt]
        self_all = gd.ipa_self_all[wt]
        stc_tv = gd.ipa_stc_tv[wt]
        stp_tv = gd.ipa_stp_tv[wt]
        # pairwise [placed s → consumer s'] slices
        m_f = gd.m_spr_f[wt][:, wt]
        m_s = gd.m_spr_s[wt][:, wt]
        m_a = gd.m_ipa_a[wt][:, wt]
        m_aa = gd.m_ipa_aa[wt][:, wt]
        m_ex = gd.m_ipa_exist[wt][:, wt]
        w_c = gd.w_stc[wt][:, wt]
        w_p = gd.w_stp[wt][:, wt]

    st0 = _WaveState(
        used=carry.used, nonzero_used=carry.nonzero_used, npods=carry.npods,
        fit_ok=fit0, s_fit=sfit0, s_bal=sbal0,
        f_cnt=gc.spr_f_cnt[wt] if has_groups else None,
        s_cnt=gc.spr_s_cnt[wt] if has_groups else None,
        veto=gc.ipa_veto[wt] if has_groups else None,
        a_cnt=gc.ipa_a_cnt[wt] if has_groups else None,
        a_total=gc.ipa_a_total[wt] if has_groups else None,
        aa_cnt=gc.ipa_aa_cnt[wt] if has_groups else None,
        iscore=gc.ipa_score[wt] if has_groups else None,
        cnt_sn=jnp.zeros((S, n), jnp.int32) if has_groups else None,
        clean=jnp.bool_(True), n_conf=jnp.int32(0), prefix=jnp.int32(0),
        ports=carry.ports if has_ports else None)

    def _eval(stx: _WaveState, w):
        """Feasibility + total score of signature slot `w` at the state —
        the same formula code as the scan's _eval_pod, over the wave's
        maintained counters (GroupView shared with ops/groups.py)."""
        feasible = static_mask[w] & stx.fit_ok[w]
        if has_ports:
            # host-port rows evaluate the live ports carry every step —
            # exactly the scan's slow path for sig-0 pods (port-free rows
            # carry all-zero port_ids, so this is vacuously true for them)
            feasible &= ports_mask(stx.ports, rows.port_ids[w])
        if has_groups:
            view = GroupView(
                f_act=f_act[w], f_skew=f_skew[w], f_self=f_self[w],
                f_minz=f_minz[w], f_tv=f_tv[w], f_elig=f_elig[w],
                f_cnt=stx.f_cnt[w],
                s_act=s_act[w], s_skew=s_skew[w], s_is_host=s_ishost[w],
                s_tv=s_tv[w], s_keys_ok=s_keys[w], s_dom=s_dom[w],
                s_cnt=stx.s_cnt[w],
                ra_act=ra_act[w], ra_tv=ra_tv[w], raa_act=raa_act[w],
                raa_tv=raa_tv[w], self_all=self_all[w],
                veto=stx.veto[w], a_cnt=stx.a_cnt[w], a_total=stx.a_total[w],
                aa_cnt=stx.aa_cnt[w], iscore=stx.iscore[w])
            feasible &= group_mask_view(view, fam)
        if norm_live:
            s_taint = default_normalize(taint_raw[w], feasible, reverse=True)
            s_na = default_normalize(na_raw[w], feasible, reverse=False)
            tn = cfg.w_taint * s_taint + cfg.w_node_affinity * s_na
        else:
            # static_norm_ok precondition: every taint_raw/na_raw is zero,
            # so DefaultNormalize degenerates to the constants 100 / 0
            tn = cfg.w_taint * MAX_SCORE
        total = (cfg.w_fit * stx.s_fit[w] + cfg.w_balanced * stx.s_bal[w]
                 + tn + cfg.w_image * s_img[w])
        if has_groups:
            total = total + group_scores_view(cfg.w_spread, cfg.w_ipa, view,
                                              feasible, fam)
        return feasible, total

    # speculative choice per signature (the parallel argmax of Phase A)
    def spec_one(s):
        feas, tot = _eval(st0, s)
        masked = jnp.where(feas, tot, -1)
        b = jnp.argmax(masked).astype(jnp.int32)
        return jnp.where(masked[b] >= 0, b, jnp.int32(-1))

    spec_y = jax.vmap(spec_one)(jnp.arange(S, dtype=jnp.int32))

    cols = jnp.array(cfg.score_cols, jnp.int32)
    nzm = jnp.array(cfg.col_nonzero)
    slots = jnp.array(cfg.nonzero_slot, jnp.int32)

    def step(stx: _WaveState, x: WaveXs):
        w = x.widx
        feasible, total = _eval(stx, w)
        masked = jnp.where(feasible, total, -1)
        best = jnp.argmax(masked).astype(jnp.int32)
        assigned = (masked[best] >= 0) & x.valid
        g_i = assigned.astype(jnp.int32)
        req_w = rows.req[w]
        used = stx.used.at[best].add(jnp.where(assigned, req_w, 0))
        nzu = stx.nonzero_used.at[best].add(
            jnp.where(assigned, rows.nonzero_req[w], 0))
        npods = stx.npods.at[best].add(g_i.astype(stx.npods.dtype))

        # refresh the fit kernels of EVERY wave signature at the one
        # touched node (_row_refresh semantics, vmapped over rows)
        cap_row = na.cap[best]
        used_row = used[best]
        nz_row = nzu[best]
        npods_b = npods[best]
        allowed_b = na.allowed_pods[best]

        def refresh_one(row_s: PodRow):
            fit_b = ((npods_b + 1 <= allowed_b)
                     & jnp.all((row_s.req == 0)
                               | (used_row + row_s.req <= cap_row)))
            cap_r = cap_row[cols][None, :]
            used_nz_r = nz_row[slots] + row_s.nonzero_req[slots]
            used_pl_r = used_row[cols] + row_s.req[cols]
            used_cols_r = jnp.where(nzm, used_nz_r, used_pl_r)[None, :]
            s_fit_b = least_allocated(cfg, cap_r, used_cols_r)[0]
            s_bal_b = jnp.where(row_s.skip_balanced, 0,
                                balanced_allocation(cap_r,
                                                    used_pl_r[None, :])[0])
            return fit_b, s_fit_b, s_bal_b

        fit_b, sfit_b, sbal_b = jax.vmap(refresh_one)(rows)

        def put_col(arr, new):
            return arr.at[:, best].set(jnp.where(assigned, new,
                                                 arr[:, best]))

        fit_ok = put_col(stx.fit_ok, fit_b)
        s_fit = put_col(stx.s_fit, sfit_b)
        s_bal = put_col(stx.s_bal, sbal_b)

        # group counter updates for the wave's consumer rows — the
        # group_update increments with consumer axis U → S, placed row w
        f_cnt, s_cnt = stx.f_cnt, stx.s_cnt
        veto, a_cnt, a_total = stx.veto, stx.a_cnt, stx.a_total
        aa_cnt, iscore = stx.aa_cnt, stx.iscore
        if has_groups and fam.spr_f:
            tvb_f = f_tv[:, :, best]                  # [S, SC]
            eligb_f = f_elig[:, :, best]
            inc_f = ((m_f[w] & eligb_f)[:, :, None]
                     & (f_tv == tvb_f[:, :, None])
                     & (tvb_f[:, :, None] != 0))
            f_cnt = stx.f_cnt + g_i * inc_f.astype(jnp.int32)
        if has_groups and fam.spr_s:
            tvb_s = s_tv[:, :, best]
            eligb_s = s_elig[:, :, best]
            is_b = (jnp.arange(n, dtype=jnp.int32) == best)[None, None, :]
            share_s = jnp.where(s_ishost[:, :, None], is_b,
                                (s_tv == tvb_s[:, :, None])
                                & (tvb_s[:, :, None] != 0))
            gate_c = jnp.where(s_ishost, m_s[w], m_s[w] & eligb_s)
            s_cnt = stx.s_cnt + g_i * (
                gate_c[:, :, None] & share_s).astype(jnp.int32)
        if has_groups and fam.ipa_anti:
            tvb_p_anti = raa_tv[w, :, best]           # [TAA]
            share_anti = ((raa_tv[w] == tvb_p_anti[:, None])
                          & (tvb_p_anti[:, None] != 0))
            delta_veto = jnp.sum(m_ex[w][:, :, None] & share_anti[None],
                                 axis=1).astype(jnp.int32)
            veto = stx.veto + g_i * delta_veto
            tvb_aa = raa_tv[:, :, best]
            share_aa = ((raa_tv == tvb_aa[:, :, None])
                        & (tvb_aa[:, :, None] != 0))
            inc_aa = m_aa[w][:, :, None] & share_aa
            aa_cnt = stx.aa_cnt + g_i * inc_aa.astype(jnp.int32)
        if has_groups and fam.ipa_req:
            tvb_a = ra_tv[:, :, best]
            share_a = ((ra_tv == tvb_a[:, :, None])
                       & (tvb_a[:, :, None] != 0))
            inc_a = ((m_a[w][:, None] & ra_act)[:, :, None] & share_a)
            a_cnt = stx.a_cnt + g_i * inc_a.astype(jnp.int32)
            a_total = stx.a_total + (
                g_i * m_a[w]
                * jnp.sum(ra_act & (tvb_a != 0), axis=1)).astype(jnp.int64)
        if has_groups and fam.ipa_score:
            tvb_c = stc_tv[:, :, best]
            share_c = ((stc_tv == tvb_c[:, :, None])
                       & (tvb_c[:, :, None] != 0))
            d_cons = jnp.sum(w_c[w][:, :, None] * share_c, axis=1)
            tvb_p = stp_tv[w, :, best]
            share_p = ((stp_tv[w] == tvb_p[:, None])
                       & (tvb_p[:, None] != 0))
            d_plcd = jnp.sum(w_p[w][:, :, None] * share_p[None], axis=1)
            iscore = stx.iscore + assigned.astype(jnp.int64) * (
                d_cons + d_plcd)

        cnt_sn = (stx.cnt_sn.at[w, best].add(g_i) if has_groups else None)
        ports2 = stx.ports
        if has_ports:
            # place the pod's port ids into the first free slots of the
            # chosen node's row (_apply_assignment's exact port logic)
            prow = stx.ports[best]
            free = prow == 0
            rank = jnp.cumsum(free) - 1
            pp = rows.port_ids[w]
            nport = pp.shape[0]
            incoming = jnp.where((rank >= 0) & (rank < nport) & free,
                                 pp[jnp.clip(rank, 0, nport - 1)], 0)
            new_prow = jnp.where(free, incoming, prow)
            ports2 = stx.ports.at[best].set(
                jnp.where(assigned & jnp.any(pp != 0), new_prow, prow))
        y = jnp.where(assigned, best, jnp.int32(-1))
        conflict = x.valid & (y != spec_y[w])
        prefix = stx.prefix + (stx.clean & x.valid
                               & ~conflict).astype(jnp.int32)
        return _WaveState(
            used=used, nonzero_used=nzu, npods=npods,
            fit_ok=fit_ok, s_fit=s_fit, s_bal=s_bal,
            f_cnt=f_cnt, s_cnt=s_cnt, veto=veto, a_cnt=a_cnt,
            a_total=a_total, aa_cnt=aa_cnt, iscore=iscore,
            cnt_sn=cnt_sn, clean=stx.clean & ~conflict,
            n_conf=stx.n_conf + conflict.astype(jnp.int32),
            prefix=prefix, ports=ports2), y

    stf, ys = lax.scan(step, st0, xs)

    # fold the accepted placements into the FULL group carry (batched,
    # order-independent adds — ops/groups.py wave_fold)
    new_gc = (wave_fold(gd, gc, wt, stf.cnt_sn, fam=fam) if has_groups
              else carry.groups)
    new_carry = Carry(used=stf.used, nonzero_used=stf.nonzero_used,
                      npods=stf.npods,
                      ports=stf.ports if has_ports else carry.ports,
                      cache=carry.cache._replace(sig=jnp.int32(0)),
                      groups=new_gc)
    packed = jnp.concatenate(
        [ys, jnp.stack([stf.n_conf, stf.prefix])]).astype(jnp.int32)
    return new_carry, packed


@functools.lru_cache(maxsize=None)
def _run_wave_scan_fn(donate: bool):
    return jax.jit(_run_wave_scan_impl,
                   static_argnames=("cfg", "fam", "norm_live", "has_groups",
                                    "has_ports"),
                   donate_argnums=(2,) if donate else ())


def run_wave_scan(cfg: ScoreConfig, na: NodeArrays, carry: Carry, xs: WaveXs,
                  table: PodTableDev, wt, gd: GroupsDev, statics,
                  fam: GroupFamilies, norm_live: bool,
                  has_groups: bool = True):
    """Jitted entry for `_run_wave_scan_impl`. The input carry is DONATED on
    accelerator backends (the chain consumes it; donation frees the old
    buffers without a device round trip); CPU has no donation support, so
    the CPU variant compiles without it to avoid per-dispatch warnings.
    `statics` is wave_statics(na, table, wt) ([S, N] each), cached by the
    scheduler per signature set. `has_groups=False` compiles the LEAN
    variant — no group state at all (gd may be None) — for drains of
    non-interacting signatures whose alternation would thrash the scan's
    one-slot signature cache."""
    donate = jax.default_backend() != "cpu"
    fn = _run_wave_scan_fn(donate)
    na, carry, xs, table, wt, gd, statics = RAILS.stage(
        (na, carry, xs, table, wt, gd, statics))
    out = LEDGER.measured_call("run_wave_scan", fn, cfg, na, carry, xs,
                               table, wt, gd, statics, fam, norm_live,
                               has_groups,
                               donated=carry if donate else None)
    if not donate:
        RAILS.poison_donated(carry, out)
    return out


@functools.lru_cache(maxsize=None)
def _run_plan_fn(donate: bool):
    # a DISTINCT jit object over the shared wave-scan impl: the compile
    # ledger attributes the drain compiler's plan executables to
    # "run_plan", so the plan lattice's fixed retrace point is provable
    # separately from the legacy run_wave_scan entry
    return jax.jit(_run_wave_scan_impl,
                   static_argnames=("cfg", "fam", "norm_live", "has_groups",
                                    "has_ports"),
                   donate_argnums=(2,) if donate else ())


def run_plan(cfg: ScoreConfig, na: NodeArrays, carry: Carry, xs: WaveXs,
             table: PodTableDev, wt, gd: GroupsDev | None, statics,
             fam: GroupFamilies, norm_live: bool, has_groups: bool = True,
             has_ports: bool = False):
    """The drain compiler's program entry (kubernetes_tpu/compiler/): ONE
    compiled dispatch for an arbitrary mixed-signature span — group rows,
    group-free rows and (with `has_ports`) host-port rows alike, at any
    pow2 signature-lattice width S. Shares the wave-scan implementation
    (per-signature surfaces hoisted via `statics`, exact serial-order
    replay over the maintained counters), compiled with `has_ports` to
    additionally maintain the ports carry so sig-0 rows no longer force
    a span split. The input carry is DONATED on accelerator backends
    (run_batch's contract); CPU compiles without donation."""
    donate = jax.default_backend() != "cpu"
    fn = _run_plan_fn(donate)
    na, carry, xs, table, wt, gd, statics = RAILS.stage(
        (na, carry, xs, table, wt, gd, statics))
    out = LEDGER.measured_call("run_plan", fn, cfg, na, carry, xs, table,
                               wt, gd, statics, fam, norm_live, has_groups,
                               has_ports,
                               donated=carry if donate else None)
    if not donate:
        RAILS.poison_donated(carry, out)
    return out


@functools.partial(jax.jit, static_argnames=("feats",))
def _wave_statics_jit(na: NodeArrays, table: PodTableDev, wt,
                      feats: tuple = (True, True, True)):
    """Carry-independent per-signature surfaces for the wave kernels —
    static filter mask (name/unschedulable/taints/selector; ports vacuous
    for sig != 0 rows), TaintToleration / preferred-affinity raw counts,
    ImageLocality score. `wt` i32 [S] table rows → [S, N] arrays. The
    scheduler caches the result per (table row, staging generation), so
    the expensive broadcast kernels run once per signature per node-state
    change instead of once per dispatch.

    `feats` = (taints, selectors, images): static host-derived flags; a
    False statically skips the matching kernel family — an unconstrained
    signature (no cluster taints, no selectors, no images) pays none of
    the padded broadcast compute."""
    has_taints, has_sel, has_img = feats
    fields = {name: getattr(table, name)[wt] for name in PodTableDev._fields}
    rows = PodRow(valid=jnp.ones(wt.shape, bool),
                  sig=jnp.ones(wt.shape, jnp.int32), **fields)
    n = na.valid.shape[0]

    def one(row: PodRow):
        m = na.valid
        m &= (row.node_name_id == 0) | (na.name_id == row.node_name_id)
        m &= ~na.unschedulable | row.tolerates_unsched
        if has_taints:
            m &= taint_filter_mask(na, row)
            traw = taint_prefer_count(na, row)
        else:
            traw = jnp.zeros((n,), jnp.int64)
        if has_sel:
            m &= selector_mask(na, row)
            naraw = preferred_affinity_score(na, row)
        else:
            naraw = jnp.zeros((n,), jnp.int64)
        simg = (image_locality_score(na, row) if has_img
                else jnp.zeros((n,), jnp.int64))
        return m, traw, naraw, simg

    return jax.vmap(one)(rows)


def wave_statics(na: NodeArrays, table: PodTableDev, wt,
                 feats: tuple = (True, True, True)):
    """Ledger-instrumented entry for `_wave_statics_jit`."""
    na, table, wt = RAILS.stage((na, table, wt))
    return LEDGER.measured_call("wave_statics", _wave_statics_jit, na,
                                table, wt, feats)


class _SameWaveState(NamedTuple):
    """run_wave (same-signature) loop state."""

    used: jnp.ndarray          # i64 [N, R]
    nonzero_used: jnp.ndarray  # i64 [N, 2]
    npods: jnp.ndarray         # i32 [N]
    f_cnt: jnp.ndarray         # i32 [SC, N] — own-row spread filter counts
    veto: jnp.ndarray          # i32 [N] — own-row existing-anti veto
    aa_cnt: jnp.ndarray        # i32 [TAA, N] — own-row incoming-anti counts
    cnt_n: jnp.ndarray         # i32 [N] — accepted placements per node
    out: jnp.ndarray           # i32 [B] — assignments (-1 = none)
    done: jnp.ndarray          # i32 — pods resolved so far
    prog: jnp.ndarray          # bool — last merge wave made progress
    ok: jnp.ndarray            # bool — merge preconditions still hold
    waves: jnp.ndarray         # i32 — merge waves executed
    confs: jnp.ndarray         # i32 — conflict (prefix-cut) events
    first_prefix: jnp.ndarray  # i32 — first wave's accepted prefix length


def _run_wave_same_impl(cfg: ScoreConfig, na: NodeArrays, carry: Carry,
                        valid, table: PodTableDev, wt, gd: GroupsDev,
                        statics, K: int, J: int, Lw: int,
                        fam: GroupFamilies, norm_live: bool,
                        anti_term: int, merge_on: bool):
    """Speculative wave placement for a SAME-SIGNATURE run of group pods,
    one device dispatch for the whole span.

    Merge tier (a device while_loop of closed-form waves): each wave
    speculates the run's next placements in parallel — the run_uniform
    top-L merge over the [K, J] post-placement score matrix, extended with
    the group structure: an `anti_term` (the row's self-matching required
    anti-affinity) turns the merge into champion-per-topology-domain
    selection (each placement vetoes its whole domain, so only a domain's
    best node can ever be chosen), and the spread skew check is replayed
    per speculated placement at DOMAIN level (cnt0 + rank-in-domain vs the
    pre-wave minimum). The longest conflict-free prefix — no skew-mask
    flip, no depth overflow, no domain re-entry — is accepted, its deltas
    fold into the loop state, and the conflicted remainder re-enters the
    next wave re-anchored on the updated counts. Exactness preconditions
    are checked on the live data per wave (score-matrix monotonicity,
    flat inter-pod-affinity score surface over the feasible set, no
    dynamically skew-masked node at wave start); any failure stops the
    merge tier with `ok=False`.

    Serial tier: whatever the merge did not resolve (conflict-heavy or
    precondition-failing remainders — the worst-case all-conflict wave)
    is finished by an in-dispatch serial scan with the exact per-pod
    rule, so the kernel ALWAYS returns the full span's assignments,
    bit-identical to the host oracle's serial order.

    `valid` is a prefix mask (bool [B], B static); `wt` the scalar table
    row. Host-side gates (the scheduler checks): single device, no
    nominations, sig != 0, no ScheduleAnyway constraints on the row, no
    self-matching required affinity, no self score terms, at most one
    self-matching anti term (`anti_term`, -1 = none; static).

    Returns (carry, packed i32 [B + 4]): assignments, then
    [merge_waves, conflict_events, first_wave_prefix, serial_steps]."""
    from .groups import (INT32_MAX, GroupView, _dom_share, group_mask_view,
                         group_scores_view, wave_fold)

    gc = carry.groups
    B = valid.shape[0]
    n = na.cap.shape[0]
    W = jnp.sum(valid).astype(jnp.int32)
    fields = {name: getattr(table, name)[wt] for name in PodTableDev._fields}
    row = PodRow(valid=jnp.bool_(True), sig=jnp.int32(1), **fields)

    # carry-independent surfaces, hoisted out of the dispatch entirely
    # (the scheduler computes them once per signature via wave_statics)
    m0, taint_raw, na_raw, s_img = statics

    # own-row group statics
    f_act = gd.spr_f_active[wt]
    f_skew = gd.spr_f_max_skew[wt]
    f_self = gd.spr_f_self[wt]
    f_minz = gc.spr_f_min_zero[wt]
    f_tv = gd.spr_f_tv[wt]
    f_elig = gd.spr_f_elig[wt]
    f_dom = gd.spr_f_dom[wt]
    s_act = gd.spr_s_active[wt]
    s_skew = gd.spr_s_max_skew[wt]
    s_ishost = gd.spr_s_is_host[wt]
    s_tv = gd.spr_s_tv[wt]
    s_keys = gd.spr_s_keys_ok[wt]
    s_dom = gd.spr_s_dom[wt]
    s_cnt0 = gc.spr_s_cnt[wt]          # static: no self ScheduleAnyway
    ra_act = gd.ipa_ra_active[wt]
    ra_tv = gd.ipa_ra_tv[wt]
    raa_act = gd.ipa_raa_active[wt]
    raa_tv = gd.ipa_raa_tv[wt]
    raa_dom = gd.ipa_raa_dom[wt]
    self_all = gd.ipa_self_all[wt]
    a_cnt0 = gc.ipa_a_cnt[wt]          # static: no self required affinity
    a_total0 = gc.ipa_a_total[wt]
    iscore0 = gc.ipa_score[wt]         # static: no self score terms
    mf_self = gd.m_spr_f[wt, wt]       # [SC]
    mex_self = gd.m_ipa_exist[wt, wt]  # [TAA]
    maa_self = gd.m_ipa_aa[wt, wt]
    if anti_term >= 0:
        anti_tv = raa_tv[anti_term]
        anti_dom = raa_dom[anti_term]

    def eval_row(used, nz, npods, f_cnt, veto, aa_cnt):
        fit_ok = fit_mask(na.cap, used, npods, na.allowed_pods, row.req)
        c2 = carry._replace(used=used, nonzero_used=nz)
        s_fit, s_bal = _fit_scores(cfg, na, c2, row)
        view = GroupView(
            f_act=f_act, f_skew=f_skew, f_self=f_self, f_minz=f_minz,
            f_tv=f_tv, f_elig=f_elig, f_cnt=f_cnt,
            s_act=s_act, s_skew=s_skew, s_is_host=s_ishost, s_tv=s_tv,
            s_keys_ok=s_keys, s_dom=s_dom, s_cnt=s_cnt0,
            ra_act=ra_act, ra_tv=ra_tv, raa_act=raa_act, raa_tv=raa_tv,
            self_all=self_all, veto=veto, a_cnt=a_cnt0, a_total=a_total0,
            aa_cnt=aa_cnt, iscore=iscore0)
        gmask = m0 & group_mask_view(view, fam)
        feasible = gmask & fit_ok
        if norm_live:
            s_taint = default_normalize(taint_raw, feasible, reverse=True)
            s_na = default_normalize(na_raw, feasible, reverse=False)
            tn = cfg.w_taint * s_taint + cfg.w_node_affinity * s_na
        else:
            tn = cfg.w_taint * MAX_SCORE
        total = (cfg.w_fit * s_fit + cfg.w_balanced * s_bal + tn
                 + cfg.w_image * s_img)
        total = total + group_scores_view(cfg.w_spread, cfg.w_ipa, view,
                                          feasible, fam)
        return gmask, feasible, total

    # ---- merge tier -------------------------------------------------------

    def merge_cond(st: _SameWaveState):
        return st.ok & st.prog & (st.done < W)

    def merge_body(st: _SameWaveState):
        gmask, feasible0, total0 = eval_row(st.used, st.nonzero_used,
                                            st.npods, st.f_cnt, st.veto,
                                            st.aa_cnt)
        masked0 = jnp.where(feasible0, total0, jnp.int64(-1))
        # inter-pod score surface must be FLAT over the feasible set: its
        # normalized contribution is then identically 0 and stays 0 as
        # feasibility shrinks (the surface itself is static in-run)
        big = jnp.iinfo(jnp.int64).max
        isc_min = jnp.min(jnp.where(feasible0, iscore0, big))
        isc_max = jnp.max(jnp.where(feasible0, iscore0, -big))
        flat = isc_max <= isc_min
        # spread skew check must not mask ANY keyed node at wave start:
        # counts only grow and the pre-wave minimum only rises, so a wave
        # whose replayed counts stay under the bound never flips a mask bit
        if fam.spr_f:
            minv = jnp.min(jnp.where(f_elig, st.f_cnt, INT32_MAX), axis=-1)
            minv = jnp.where(f_minz, 0, minv)
            ok_cn = (st.f_cnt + f_self[:, None] - minv[:, None]
                     <= f_skew[:, None])
            start_inert = jnp.all(~f_act[:, None] | (f_tv == 0) | ok_cn)
        else:
            minv = jnp.zeros(f_skew.shape, jnp.int32)
            start_inert = jnp.bool_(True)

        _, cand = lax.top_k(masked0.astype(jnp.int32), K)
        cand = cand.astype(jnp.int32)
        if anti_term >= 0:
            # champion per anti-topology domain: every placement vetoes
            # its whole domain, so only a domain's (score desc, idx asc)
            # best can ever be chosen; keyless nodes are unconstrained
            keyN = masked0 * n - jnp.arange(n, dtype=jnp.int64)
            seg = jnp.full((n,), jnp.iinfo(jnp.int64).min).at[anti_dom].max(
                jnp.where(anti_tv != 0, keyN, jnp.iinfo(jnp.int64).min))
            champ = (anti_tv == 0) | ((anti_tv != 0) & (keyN == seg[anti_dom]))
            champ_cand = champ[cand][:, None]
            jcap = 1
        else:
            champ_cand = jnp.ones((K, 1), bool)
            jcap = J
        fit_kj, s_fit_kj, s_bal_kj = _uniform_matrix(
            cfg, na, st.used, st.npods, st.used, st.nonzero_used,
            cand, row, J)
        static_add = (cfg.w_taint * MAX_SCORE + cfg.w_image * s_img)[cand]
        score_kj = (cfg.w_fit * s_fit_kj + cfg.w_balanced * s_bal_kj
                    + static_add[:, None])
        jmask = jnp.arange(J)[None, :] < jcap
        masked_kj = jnp.where(gmask[cand][:, None] & champ_cand & fit_kj
                              & jmask, score_kj, jnp.int64(-1))
        mono_ok = jnp.all(masked_kj[:, 1:] <= masked_kj[:, :-1])

        # key = (score desc, node idx asc, j asc) — run_uniform's merge
        score_max = MAX_SCORE * (cfg.w_fit + cfg.w_balanced + cfg.w_taint
                                 + cfg.w_node_affinity + cfg.w_image)
        M = n * J
        key_dt = jnp.int32 if (score_max + 2) * M < 2 ** 31 else jnp.int64
        ent_id = (cand[:, None].astype(key_dt) * J
                  + jnp.arange(J, dtype=key_dt)[None, :])
        flat_key = (masked_kj.astype(key_dt) * key_dt(M) - ent_id).reshape(K * J)
        top_vals, flat_i = lax.top_k(flat_key, Lw)
        krank = (flat_i // J).astype(jnp.int32)
        node_i = cand[krank]
        j_i = (flat_i % J).astype(jnp.int32)
        avail = W - st.done
        sel_ok = (top_vals > -key_dt(M)) & (jnp.arange(Lw) < avail)

        # conflict detection over the speculated sequence
        if fam.spr_f:
            # replay the skew bound at domain level: entry i's new count =
            # cnt0(dom) + rank-in-domain + 1, against the EXACT evolving
            # minimum — min rises to min0+m once every eligible domain's
            # count reaches min0+m, tracked level by level so a balanced
            # fill (counts rising in lockstep) accepts the whole wave
            gate = (mf_self[None, :] & f_elig[:, node_i].T
                    & sel_ok[:, None])                     # [Lw, SC]
            dom_ic = f_dom[:, node_i].T                    # [Lw, SC]
            eq = dom_ic[None, :, :] == dom_ic[:, None, :]  # [i, j, SC]
            lower = jnp.tril(jnp.ones((Lw, Lw), bool), -1)
            r_ic = jnp.sum(eq & gate[None, :, :] & lower[:, :, None],
                           axis=1).astype(jnp.int32)
            newcnt = st.f_cnt[:, node_i].T + r_ic + 1
            M_CAP = 32
            lvlv = minv[:, None] + jnp.arange(1, M_CAP + 1,
                                              dtype=jnp.int32)[None, :]
            # D_need[c, m]: eligible domains still below min0+m. A domain
            # id IS the index of one of its nodes, so the domain's count
            # can be read at that slot — one scatter marks the domains
            # with an eligible member, then the level compare is
            # elementwise over the marked slots only.
            elig_dom = jax.vmap(
                lambda dom_c, el_c: jnp.zeros((n,), jnp.int32).at[dom_c].max(
                    el_c.astype(jnp.int32)))(f_dom, f_elig)     # [SC, N]
            d_need = jnp.sum(
                (elig_dom[:, None, :] > 0)
                & (st.f_cnt[:, None, :] < lvlv[:, :, None]),
                axis=2).astype(jnp.int32)                        # [SC, M]
            comp = (gate[:, :, None]
                    & (newcnt[:, :, None] == lvlv[None, :, :]))  # [Lw,SC,M]
            cum_excl = jnp.cumsum(comp, axis=0) - comp
            reached = cum_excl >= d_need[None, :, :]
            lvl_up = jnp.sum(reached, axis=2).astype(jnp.int32)  # [Lw, SC]
            min_i = jnp.where(f_minz[None, :], 0, minv[None, :] + lvl_up)
            viol = jnp.any(f_act[None, :] & gate
                           & ((newcnt + f_self[None, :] - min_i
                               > f_skew[None, :])
                              | (lvl_up >= M_CAP)), axis=1)
        else:
            viol = jnp.zeros((Lw,), bool)
        if anti_term >= 0:
            # a keyless (no-topology) node hides its deeper entries from
            # the jcap=1 merge: cut after it so the next wave re-offers it
            viol |= anti_tv[node_i] == 0
        else:
            # depth cut: a candidate consuming its last matrix entry may
            # have deserved more — stop there, the next wave re-anchors
            viol |= j_i == J - 1
        viol &= sel_ok
        excl = jnp.cumsum(viol) - viol
        accept = sel_ok & (excl == 0)
        iter_ok = mono_ok & flat & start_inert
        accept &= iter_ok
        a = jnp.sum(accept).astype(jnp.int32)

        cnt_add = jnp.zeros((n,), jnp.int32).at[node_i].add(
            accept.astype(jnp.int32))
        used2 = st.used + cnt_add[:, None].astype(jnp.int64) * row.req[None, :]
        nz2 = (st.nonzero_used
               + cnt_add[:, None].astype(jnp.int64) * row.nonzero_req[None, :])
        npods2 = st.npods + cnt_add.astype(st.npods.dtype)
        f_cnt2 = st.f_cnt
        if fam.spr_f:
            inc = _dom_share(f_tv, f_dom,
                             f_elig.astype(jnp.int32) * cnt_add[None, :])
            f_cnt2 = st.f_cnt + jnp.where(mf_self[:, None], inc, 0)
        veto2, aa2 = st.veto, st.aa_cnt
        if fam.ipa_anti:
            sh = _dom_share(raa_tv, raa_dom,
                            jnp.broadcast_to(cnt_add[None, :], raa_tv.shape))
            veto2 = st.veto + jnp.sum(
                jnp.where(mex_self[:, None], sh, 0), axis=0).astype(jnp.int32)
            aa2 = st.aa_cnt + jnp.where(maa_self[:, None], sh,
                                        0).astype(jnp.int32)
        rank = jnp.cumsum(accept) - accept
        pos = jnp.where(accept, st.done + rank, B)
        out2 = st.out.at[pos].set(node_i, mode="drop")
        return _SameWaveState(
            used=used2, nonzero_used=nz2, npods=npods2, f_cnt=f_cnt2,
            veto=veto2, aa_cnt=aa2, cnt_n=st.cnt_n + cnt_add, out=out2,
            done=st.done + a, prog=a > 0, ok=st.ok & iter_ok,
            waves=st.waves + 1,
            confs=st.confs + ((a < avail) & iter_ok).astype(jnp.int32),
            first_prefix=jnp.where(st.waves == 0, a, st.first_prefix))

    st = _SameWaveState(
        used=carry.used, nonzero_used=carry.nonzero_used, npods=carry.npods,
        f_cnt=gc.spr_f_cnt[wt], veto=gc.ipa_veto[wt],
        aa_cnt=gc.ipa_aa_cnt[wt], cnt_n=jnp.zeros((n,), jnp.int32),
        out=jnp.full((B,), -1, jnp.int32), done=jnp.int32(0),
        prog=jnp.bool_(True), ok=jnp.bool_(True), waves=jnp.int32(0),
        confs=jnp.int32(0), first_prefix=jnp.int32(-1))
    if merge_on and not norm_live:
        st = lax.while_loop(merge_cond, merge_body, st)

    # ---- serial tier: finish the remainder with the exact per-pod rule
    def serial_cond(sv):
        st, steps = sv
        return st.done < W

    def serial_body(sv):
        st, steps = sv
        _, feasible, total = eval_row(st.used, st.nonzero_used, st.npods,
                                      st.f_cnt, st.veto, st.aa_cnt)
        masked = jnp.where(feasible, total, -1)
        best = jnp.argmax(masked).astype(jnp.int32)
        assigned = masked[best] >= 0
        g = assigned.astype(jnp.int32)
        used2 = st.used.at[best].add(jnp.where(assigned, row.req, 0))
        nz2 = st.nonzero_used.at[best].add(
            jnp.where(assigned, row.nonzero_req, 0))
        npods2 = st.npods.at[best].add(g.astype(st.npods.dtype))
        f_cnt2 = st.f_cnt
        if fam.spr_f:
            tvb = f_tv[:, best]
            inc = ((mf_self & f_elig[:, best])[:, None]
                   & (f_tv == tvb[:, None]) & (tvb[:, None] != 0))
            f_cnt2 = st.f_cnt + g * inc.astype(jnp.int32)
        veto2, aa2 = st.veto, st.aa_cnt
        if fam.ipa_anti:
            tvb_a = raa_tv[:, best]
            share = (raa_tv == tvb_a[:, None]) & (tvb_a[:, None] != 0)
            veto2 = st.veto + g * jnp.sum(
                mex_self[:, None] & share, axis=0).astype(jnp.int32)
            aa2 = st.aa_cnt + g * (maa_self[:, None] & share).astype(jnp.int32)
        out2 = st.out.at[st.done].set(jnp.where(assigned, best, -1))
        st2 = st._replace(used=used2, nonzero_used=nz2, npods=npods2,
                          f_cnt=f_cnt2, veto=veto2, aa_cnt=aa2,
                          cnt_n=st.cnt_n.at[best].add(g), out=out2,
                          done=st.done + 1)
        return st2, steps + 1

    st, serial_steps = lax.while_loop(serial_cond, serial_body,
                                      (st, jnp.int32(0)))

    new_gc = wave_fold(gd, gc, jnp.reshape(wt, (1,)), st.cnt_n[None, :],
                       fam=fam)
    new_carry = Carry(used=st.used, nonzero_used=st.nonzero_used,
                      npods=st.npods, ports=carry.ports,
                      cache=carry.cache._replace(sig=jnp.int32(0)),
                      groups=new_gc)
    packed = jnp.concatenate(
        [st.out, jnp.stack([st.waves, st.confs, st.first_prefix,
                            serial_steps])]).astype(jnp.int32)
    return new_carry, packed


@functools.lru_cache(maxsize=None)
def _run_wave_same_fn(donate: bool):
    return jax.jit(_run_wave_same_impl,
                   static_argnames=("cfg", "K", "J", "Lw", "fam",
                                    "norm_live", "anti_term", "merge_on"),
                   donate_argnums=(2,) if donate else ())


def run_wave(cfg: ScoreConfig, na: NodeArrays, carry: Carry, valid,
             table: PodTableDev, wt, gd: GroupsDev, statics, K: int, J: int,
             fam: GroupFamilies, norm_live: bool, anti_term: int = -1,
             merge_on: bool = True, Lw: int = 512):
    """Jitted entry for the same-signature wave kernel; the input carry is
    donated on accelerator backends (see run_batch). `statics` is the
    signature's wave_statics row ([N] each); `Lw` caps the speculated
    entries per merge wave (span-length independent, so one executable
    serves every drain size)."""
    donate = jax.default_backend() != "cpu"
    fn = _run_wave_same_fn(donate)
    Lw = min(Lw, valid.shape[0])
    na, carry, valid, table, wt, gd, statics = RAILS.stage(
        (na, carry, valid, table, wt, gd, statics))
    out = LEDGER.measured_call("run_wave", fn, cfg, na, carry, valid,
                               table, wt, gd, statics, K, J, Lw, fam,
                               norm_live, anti_term, merge_on,
                               donated=carry if donate else None)
    if not donate:
        RAILS.poison_donated(carry, out)
    return out


# ---------------------------------------------------------------------------
# preemption dry-run kernel family (preemption.go:775 DryRunPreemption,
# SURVEY §7 step 8): the per-candidate-node host loop becomes one gathered
# program over the candidate axis


def pod_row_from_table(table, u: int, sig: int = 0) -> PodRow:
    """One signature row of a (numpy) PodTable as the kernels' PodRow."""
    import numpy as np
    fields = {name: getattr(table, name)[u] for name in PodTableDev._fields}
    return PodRow(valid=np.bool_(True), sig=np.int32(sig), **fields)


def _dry_run_spread_ok(sp: DryRunSpread, removed):
    """Spread feasibility for the preemptor on every candidate, given
    `removed` i32 [C, SC] matching victims currently removed. Mirrors the
    host filter (podtopologyspread.py filter): missing key → infeasible;
    matchNum + selfMatch − min > maxSkew → infeasible, with the
    criticalPaths closed form min(x, other) (groups.spread_dry_run_tensors)
    and the minDomains zero-floor."""
    x = sp.cnt0 - removed
    min_eff = jnp.where(sp.min_zero[None, :], 0,
                        jnp.minimum(x, sp.other_min))
    ok = x + sp.self_match[None, :] - min_eff <= sp.max_skew[None, :]
    return jnp.all(sp.tv_ok & ok, axis=1)


@jax.jit
def _dry_run_select_victims_jit(na: NodeArrays, pod: PodRow, cand,
                                victim_req, victim_valid, ovl_used,
                                ovl_npods,
                                spread: DryRunSpread | None = None):
    """Batched select_victims_on_node (default_preemption.go:583) over the
    candidate-node axis.

    cand         i32 [C]      node-row indices into `na` (padding repeats a
                              real row; the caller ignores padded outputs)
    victim_req   i64 [C,V,R]  potential victims' request vectors, REPRIEVE
                              order (PDB-violating first, then by priority
                              desc / creation asc — built host-side)
    victim_valid bool [C,V]
    ovl_used     i64 [C,R]    nominated-pod resources (the two-pass
    ovl_npods    i32 [C]      RunFilterPluginsWithNominatedPods overlay:
                              only ≥-priority nominations, self excluded)
    spread       victim count tensors when the preemptor carries
                 DoNotSchedule spread constraints (groups.DryRunSpread)

    Returns bool [C, V+1]: column 0 = the preemptor fits with every victim
    removed (candidate viable); column 1+v = victim v was reprieved (added
    back most-important-first while the preemptor still fits). The caller
    must only pass preemptors without host ports (the ports carry is not
    simulated), without pod (anti-)affinity, and on clusters without
    existing required-anti-affinity pods — everything else is exact.

    Monotonicity argument for the overlay: the host runs the filter twice
    (with and without nominated pods); resources and spread counts are
    additive, so with-nominated feasibility implies without-nominated —
    one overlaid pass is exact for the eligible subset."""
    na_c = NodeArrays(*(x[cand] for x in na))
    m = na_c.valid
    m &= (pod.node_name_id == 0) | (na_c.name_id == pod.node_name_id)
    m &= ~na_c.unschedulable | pod.tolerates_unsched
    m &= taint_filter_mask(na_c, pod)
    m &= selector_mask(na_c, pod)
    nv = jnp.sum(victim_valid, axis=1).astype(na_c.npods.dtype)
    total_req = jnp.sum(jnp.where(victim_valid[:, :, None], victim_req, 0),
                        axis=1)
    base_used = na_c.used + ovl_used - total_req
    base_npods = na_c.npods + ovl_npods - nv
    fits = m & fit_mask(na_c.cap, base_used, base_npods, na_c.allowed_pods,
                        pod.req)
    if spread is not None:
        vm = spread.vic_match.astype(jnp.int32)          # [C, V, SC]
        rm0 = jnp.sum(jnp.where(victim_valid[:, :, None], vm, 0), axis=1)
        fits &= _dry_run_spread_ok(spread, rm0)
        xs = (jnp.swapaxes(victim_req, 0, 1), victim_valid.T,
              jnp.swapaxes(vm, 0, 1))
        removed0 = rm0
    else:
        xs = (jnp.swapaxes(victim_req, 0, 1), victim_valid.T,
              jnp.zeros((victim_valid.shape[1], victim_valid.shape[0], 0),
                        jnp.int32))
        removed0 = jnp.zeros((victim_valid.shape[0], 0), jnp.int32)

    def step(carry, x):
        used, npods, removed = carry
        req_v, valid_v, match_v = x
        t_used = used + req_v
        t_npods = npods + 1
        ok = valid_v & (t_npods + 1 <= na_c.allowed_pods)
        ok &= jnp.all((pod.req[None, :] == 0)
                      | (t_used + pod.req[None, :] <= na_c.cap), axis=1)
        t_removed = removed - match_v
        if spread is not None:
            ok &= _dry_run_spread_ok(spread, t_removed)
        used = jnp.where(ok[:, None], t_used, used)
        npods = jnp.where(ok, t_npods, npods)
        removed = jnp.where(ok[:, None], t_removed, removed)
        return (used, npods, removed), ok

    carry0 = (base_used, base_npods, removed0)
    _, reprieved = lax.scan(step, carry0, xs)
    return jnp.concatenate([fits[:, None], reprieved.T], axis=1)


def dry_run_select_victims(na: NodeArrays, pod: PodRow, cand,
                           victim_req, victim_valid, ovl_used, ovl_npods,
                           spread: DryRunSpread | None = None):
    """Ledger-instrumented entry for `_dry_run_select_victims_jit`."""
    (na, pod, cand, victim_req, victim_valid, ovl_used, ovl_npods,
     spread) = RAILS.stage((na, pod, cand, victim_req, victim_valid,
                            ovl_used, ovl_npods, spread))
    return LEDGER.measured_call("dry_run", _dry_run_select_victims_jit,
                                na, pod, cand, victim_req, victim_valid,
                                ovl_used, ovl_npods, spread)


def initial_carry(na: NodeArrays, groups: GroupCarry | None = None) -> Carry:
    n = na.npods.shape[0]
    zero_cache = SigCache(
        sig=jnp.int32(0),
        static_mask=jnp.zeros((n,), bool),
        taint_raw=jnp.zeros((n,), jnp.int64),
        na_raw=jnp.zeros((n,), jnp.int64),
        s_img=jnp.zeros((n,), jnp.int64),
        fit_ok=jnp.zeros((n,), bool),
        s_fit=jnp.zeros((n,), jnp.int64),
        s_bal=jnp.zeros((n,), jnp.int64),
    )
    # COPY the seeded node state: the carry's buffers are donated to the
    # device programs (run_batch/run_wave consume their input carry), so
    # they must never alias the resident NodeArrays
    return Carry(used=jnp.array(na.used), nonzero_used=jnp.array(na.nonzero_used),
                 npods=jnp.array(na.npods), ports=jnp.array(na.ports),
                 cache=zero_cache, groups=groups)
