"""Gang placement: a whole pod group solved as ONE device dispatch.

SURVEY §7 step 7 calls the all-or-nothing gang feasibility check "strictly
easier on device than the reference's Permit-barrier dance": once
PreEnqueue quorum is met on the host, the gang's members are one batched
assignment problem — vmapped per-signature filter masks over the node
matrix, a sequential-greedy placement replay, and a single feasibility
reduction (`placed >= minCount`) that accepts or rejects the ENTIRE gang
atomically. The accepted gang commits through the async dispatcher with
no Reserve/Permit/Unreserve churn; the rejected gang unwinds ON DEVICE
(the returned carry is the input carry, leaf for leaf), so no member ever
holds partial resources — the classic gang-scheduling deadlock cannot
form.

Two tiers behind the one `run_gang` entry:

- **uniform tier** (`uniform=True`): a single-signature gang with the
  LeastAllocated strategy rides the closed-form top-L matrix
  (`program._uniform_core`, the run_uniform exactness argument verbatim)
  with the accept reduction bolted on — the whole 256-pod gang is one
  top_k, not 256 scan steps. Exactness flags are returned like
  run_uniform's; on a failed precondition the scheduler replays on the
  scan tier from the kept input carry.
- **scan tier** (`uniform=False`): the general program. Per-signature
  surfaces (filter masks + carry-independent scores) are hoisted ONCE via
  vmap over the gang's distinct signature rows [S]; the member scan then
  pays only normalization + argmax + a touched-row refresh per step —
  the SigCache fast-path cost, for every member, at any signature mix.

Topology-contiguous slice packing (Tesserae, arXiv:2508.04953): with
`w_contig > 0` the scan tier adds one more masked-argmax column — the
normalized count of gang members already placed in each node's topology
domain (`dom`, host-interned zone ids) — so a training gang prefers
filling domains it already occupies. The weight is 0 by default: the
default decision surface stays bit-identical to the serial Permit-barrier
oracle (the fuzzed parity gate in tests/test_gang_device.py holds
exactly that).
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from ..analysis.rails import GLOBAL as RAILS
from ..perf.ledger import GLOBAL as LEDGER
from ..state.tensorize import NodeArrays
from .program import (Carry, PodTableDev, PodXs, ScoreConfig, _fit_scores,
                      _gather_row, _uniform_core, balanced_allocation,
                      default_normalize, fit_mask, least_allocated)


class GangXs(NamedTuple):
    """Per-member scan xs for one gang ([B] = pow2-padded member count)."""

    valid: jnp.ndarray   # bool [B] — member present (padding rows False)
    tidx: jnp.ndarray    # i32 [B] — row into PodTableDev
    widx: jnp.ndarray    # i32 [B] — slot into the gang's signature set [S]


def _run_gang_scan_impl(cfg: ScoreConfig, na: NodeArrays, carry: Carry,
                        xs: GangXs, table: PodTableDev, wt, needed, dom,
                        statics, w_contig: int):
    """Scan-tier gang assignment; returns (carry', packed i32[B+4]).

    packed[:B] holds each member's RAW greedy assignment (-1 = no feasible
    node) regardless of the gang verdict — the host commit needs to split
    quorum-unwound members from genuinely infeasible ones; packed[B] is
    the accept flag (placed >= needed), packed[B+1] the placed count, and
    packed[B+2:B+4] are always-true exactness flags (layout-compatible
    with the uniform tier). The carry update is CONDITIONAL: a rejected
    gang returns the input carry's values unchanged — the all-or-nothing
    unwind happens on device, with zero host round trips."""
    n = na.npods.shape[0]
    cols = jnp.array(cfg.score_cols, jnp.int32)
    nzmask = jnp.array(cfg.col_nonzero)
    slots = jnp.array(cfg.nonzero_slot, jnp.int32)

    # per-signature surfaces: the carry-INDEPENDENT kernels (static filter
    # mask, taint/affinity raw counts, ImageLocality) arrive precomputed —
    # the drain compiler's SurfaceCache hoists them once per node-state
    # statics generation, shared with the plan/wave programs. Gang rows
    # carry sig != 0 (no host ports), so the ports term the full
    # _slow_parts would fold in is vacuously true. Only the
    # carry-DEPENDENT fit/score columns evaluate here, at the gang's
    # entry state.
    static_m, taint_raw, na_raw, s_img = statics                # each [S, N]

    def _fit_parts(u):
        pod = _gather_row(table, PodXs(valid=jnp.bool_(True),
                                       sig=jnp.int32(0), tidx=u))
        fit_ok = fit_mask(na.cap, carry.used, carry.npods,
                          na.allowed_pods, pod.req)
        s_fit, s_bal = _fit_scores(cfg, na, carry, pod)
        return fit_ok, s_fit, s_bal

    fit_ok0, s_fit0, s_bal0 = jax.vmap(_fit_parts)(wt)          # each [S, N]
    req_s = table.req[wt]                                       # [S, R]
    nzreq_s = table.nonzero_req[wt]                             # [S, 2]
    skipb_s = table.skip_balanced[wt]                           # [S]

    def step(state, x: GangXs):
        used, nz, npods, fit_ok, s_fit, s_bal, domcnt, placed = state
        s = x.widx
        pod = _gather_row(table, PodXs(valid=x.valid, sig=jnp.int32(0),
                                       tidx=x.tidx))
        feasible = static_m[s] & fit_ok[s]
        s_taint = default_normalize(taint_raw[s], feasible, reverse=True)
        s_na = default_normalize(na_raw[s], feasible, reverse=False)
        total = (cfg.w_fit * s_fit[s] + cfg.w_balanced * s_bal[s]
                 + cfg.w_taint * s_taint + cfg.w_node_affinity * s_na
                 + cfg.w_image * s_img[s])
        if w_contig:
            # contiguity = one more masked-argmax column: members already
            # placed in the node's topology domain, DefaultNormalized
            total = total + w_contig * default_normalize(
                domcnt[dom].astype(jnp.int64), feasible, reverse=False)
        masked = jnp.where(feasible, total, jnp.int64(-1))
        best = jnp.argmax(masked).astype(jnp.int32)
        assigned = (masked[best] >= 0) & x.valid
        onehot = (jnp.arange(n, dtype=jnp.int32) == best) & assigned
        used2 = used + jnp.where(onehot[:, None], pod.req[None, :], 0)
        nz2 = nz + jnp.where(onehot[:, None], pod.nonzero_req[None, :], 0)
        npods2 = npods + onehot.astype(npods.dtype)

        # refresh the ONE touched row for every signature slot — the
        # gang-wide analog of program._row_refresh, same arithmetic
        cap_row = na.cap[best]
        used_row = used2[best]
        npods_row = npods2[best]
        nz_row = nz2[best]

        def _refresh(req, nzreq, skipb):
            fit_b = ((npods_row + 1 <= na.allowed_pods[best])
                     & jnp.all((req == 0) | (used_row + req <= cap_row)))
            cap_r = cap_row[cols][None, :]
            used_nz_r = nz_row[slots] + nzreq[slots]
            used_pl_r = used_row[cols] + req[cols]
            used_cols_r = jnp.where(nzmask, used_nz_r, used_pl_r)[None, :]
            s_fit_b = least_allocated(cfg, cap_r, used_cols_r)[0]
            s_bal_b = jnp.where(skipb, 0,
                                balanced_allocation(cap_r,
                                                    used_pl_r[None, :])[0])
            return fit_b, s_fit_b, s_bal_b

        fo_b, sf_b, sb_b = jax.vmap(_refresh)(req_s, nzreq_s, skipb_s)
        fit_ok2 = fit_ok.at[:, best].set(
            jnp.where(assigned, fo_b, fit_ok[:, best]))
        s_fit2 = s_fit.at[:, best].set(
            jnp.where(assigned, sf_b, s_fit[:, best]))
        s_bal2 = s_bal.at[:, best].set(
            jnp.where(assigned, sb_b, s_bal[:, best]))
        if w_contig:
            domcnt2 = domcnt.at[dom[best]].add(
                jnp.where(assigned, 1, 0).astype(domcnt.dtype))
        else:
            domcnt2 = domcnt
        placed2 = placed + assigned.astype(placed.dtype)
        return ((used2, nz2, npods2, fit_ok2, s_fit2, s_bal2, domcnt2,
                 placed2), jnp.where(assigned, best, jnp.int32(-1)))

    state0 = (carry.used, carry.nonzero_used, carry.npods,
              fit_ok0, s_fit0, s_bal0,
              jnp.zeros((n,), jnp.int32), jnp.int32(0))
    (used_f, nz_f, npods_f, _, _, _, _, placed), raw = lax.scan(
        step, state0, xs)
    accept = placed >= needed

    def sel(a, b):
        return jnp.where(accept, a, b)

    # the accepted gang's placements invalidate the resident SigCache
    # (its fit/score columns predate the gang); the rejected gang leaves
    # the carry — cache included — exactly as it arrived
    cache = carry.cache._replace(
        sig=jnp.where(accept, jnp.int32(0), carry.cache.sig))
    carry_out = carry._replace(used=sel(used_f, carry.used),
                               nonzero_used=sel(nz_f, carry.nonzero_used),
                               npods=sel(npods_f, carry.npods),
                               cache=cache)
    packed = jnp.concatenate([
        raw, jnp.stack([accept.astype(jnp.int32), placed,
                        jnp.int32(1), jnp.int32(1)])])
    return carry_out, packed


@functools.lru_cache(maxsize=None)
def _run_gang_scan_fn(donate: bool):
    return jax.jit(_run_gang_scan_impl,
                   static_argnames=("cfg", "w_contig"),
                   donate_argnums=(2,) if donate else ())


@functools.partial(jax.jit, static_argnames=("cfg", "L", "K", "J"))
def _run_gang_uniform_jit(cfg: ScoreConfig, na: NodeArrays, carry: Carry,
                          x: PodXs, table: PodTableDev, n_actual, needed,
                          L: int, K: int, J: int):
    """Closed-form tier: run_uniform's top-L matrix with the gang verdict
    reduction. The carry applies ONLY when the gang is accepted AND the
    exactness preconditions held — a rejected or precondition-failed run
    leaves the input carry untouched (the scheduler replays failed
    preconditions on the scan tier). packed layout matches the scan
    tier: [assignments(L); accept; placed; exact; depth]."""
    new_carry, assignments, ok, depth_ok = _uniform_core(
        cfg, na, carry, x, table, n_actual, L, K, J, None)
    placed = jnp.sum((assignments >= 0).astype(jnp.int32))
    accept = placed >= needed
    apply = accept & ok & depth_ok
    carry_out = jax.tree_util.tree_map(
        lambda a, b: jnp.where(apply, a, b), new_carry, carry)
    packed = jnp.concatenate([
        assignments,
        jnp.stack([accept, placed, ok, depth_ok]).astype(jnp.int32)])
    return carry_out, packed


def run_gang(cfg: ScoreConfig, na: NodeArrays, carry: Carry, xs, table,
             wt=None, needed=None, dom=None, statics=None,
             w_contig: int = 0, uniform: bool = False, n_actual=None,
             L: int = 0, K: int = 0, J: int = 0):
    """JIT entry for whole-gang all-or-nothing assignment.

    `uniform=True` routes a single-signature gang to the closed-form tier
    (`xs` is then a one-row PodXs like run_uniform's, `n_actual` the true
    member count, L/K/J the matrix shape; never donates — the scheduler
    keeps the input carry to replay failed exactness preconditions on the
    scan tier). `uniform=False` runs the general scan tier (`xs` a
    GangXs, `wt` the i32[S] signature rows, `dom` the i32[N] topology
    domain ids for the contiguity column, `statics` the rows' hoisted
    carry-independent surfaces — the drain compiler's SurfaceCache rows,
    stacked [S, N] each exactly like run_plan's); the input carry is
    DONATED on accelerator backends exactly like run_batch — both the
    accept and the reject branch produce fresh output buffers, so the
    all-or-nothing unwind costs nothing. `needed` is the gang's remaining
    quorum (minCount minus already-assigned members), a dynamic i32 so
    quorum values never mint executables."""
    if uniform:
        na, carry, xs, table, n_actual, needed = RAILS.stage(
            (na, carry, xs, table, n_actual, needed))
        return LEDGER.measured_call("run_gang", _run_gang_uniform_jit, cfg,
                                    na, carry, xs, table, n_actual, needed,
                                    L, K, J)
    donate = jax.default_backend() != "cpu"
    fn = _run_gang_scan_fn(donate)
    na, carry, xs, table, wt, needed, dom, statics = RAILS.stage(
        (na, carry, xs, table, wt, needed, dom, statics))
    out = LEDGER.measured_call("run_gang", fn, cfg, na, carry, xs, table,
                               wt, needed, dom, statics, w_contig,
                               donated=carry if donate else None)
    if not donate:
        RAILS.poison_donated(carry, out)
    return out
