"""Batched assume/bind: the columnar commit edge of a drain.

`_commit_assignments_inner` used to classify pods one by one
(`_needs_per_pod_hooks` re-deriving profile facts per pod) and
`_fast_commit` then re-walked every pod's object graph inside
`NodeInfo.add_pod` (affinity property chains, request-dict walks, a
container walk for ports) — per pod, per drain, on the throughput-
bounding path.

`CommitEngine.commit` replaces both with one pass driven by the
columnar pod store's commit facts (state/batch.py `row_facts`, one
`CommitFacts` per signature row): the cache assume inlines to the
minimum mutation set with every signature-level fact hoisted, the bind
enqueue is the existing bulk dispatcher extend, and the event /
flight-recorder feeds stay format-free (object refs + node names only).
Behavior is bit-for-bit the serial path's — tests/test_ingest.py proves
cache, dispatcher-queue and event parity against `_fast_commit` /
`_assume_and_bind`, and the `ColumnarIngest` gate (off) restores the
serial path outright.
"""

from __future__ import annotations

from ..backend.cache import _PodState
from ..framework.types import next_generation
from ..metrics import SCHEDULED
from ..obs.journey import EV_ASSIGN


class CommitEngine:
    """Owned by one Scheduler; stateless between drains except the
    per-profile hook-fact memo."""

    def __init__(self, sched):
        self.sched = sched
        # profile name → (always_hooks, has_rp, has_pb); mirrors
        # _needs_per_pod_hooks — the gates must stay in lockstep
        self._profile_facts: dict = {}

    def _hooks(self, profile) -> tuple:
        facts = self._profile_facts.get(profile.name)
        if facts is None:
            fwk = profile.framework
            has_rp = bool(fwk.reserve_plugins or fwk.permit_plugins)
            has_pb = bool(fwk.pre_bind_plugins)
            always = ((has_rp and not profile.gang_only_hooks)
                      or (has_pb and not profile.volume_only_pre_bind))
            facts = (always, has_rp, has_pb)
            self._profile_facts[profile.name] = facts
        return facts

    def commit(self, pd, out, names, gang_fast: bool) -> tuple:
        """One pass over a resolved drain: hook-free pods take the
        columnar assume + bulk bind enqueue; hook pods route through
        `_assume_and_bind` in drain order (same relative order as the
        serial path: hook binds inline, fast binds batched at the end).
        Returns (bound, failures)."""
        sched = self.sched
        profile = pd.profile
        qpis = pd.qpis
        n = pd.n
        always_hooks, has_rp, has_pb = self._hooks(profile)
        cache = sched.cache
        pod_states = cache.pod_states
        nodes_get = cache.nodes.get
        get_or_create = cache._get_or_create
        move_to_head = cache._move_to_head
        assumed_set = cache.assumed_pods
        ttl = cache.ttl
        queue = sched.queue
        nominated = queue.nominator.nominated_pods
        nominator_delete = queue.nominator.delete
        in_flight = queue.in_flight_pods
        in_flight_pop = in_flight.pop
        now = sched.clock()
        facts_list = pd.facts
        n_facts = len(facts_list) if facts_list is not None else 0
        tidx = pd.batch.tidx[:n].tolist() if pd.batch is not None else None
        out_list = out.tolist()
        bound = 0
        failures: list = []
        bound_pods: list = []
        event_refs: list = []
        sli_by_attempts: dict = {}
        for i in range(n):
            a = out_list[i]
            qpi = qpis[i]
            if a < 0:
                failures.append(qpi)
                continue
            pod = qpi.pod
            spec = pod.spec
            if not gang_fast and (
                    always_hooks
                    or (spec.workload_ref and has_rp)
                    or ((spec.volumes or spec.resource_claims)
                        and (has_rp or has_pb))):
                # full reserve/permit/pre-bind chain, in drain order
                sched._assume_and_bind(qpi, names[a])
                bound += 1
                continue
            uid = pod.metadata.uid
            if uid in pod_states:
                in_flight_pop(uid, None)
                continue
            node_name = names[a]
            assumed = pod.with_node_name(node_name)
            # the queue entry's PodInfo becomes the cache's: rebinding its
            # pod to the assumed copy saves an allocation per commit, and
            # nothing reads the entry after the drain resolves
            pi = qpi.pod_info
            pi.pod = assumed
            qpi.pod = assumed   # keep the slot in sync with pod_info
            if tidx is not None and tidx[i] < n_facts:
                f = facts_list[tidx[i]]
            else:  # row minted outside the batch (defensive): derive
                from .columns import commit_facts_for_row
                f = commit_facts_for_row(pod)
            # -- columnar cache assume (NodeInfo.add_pod inlined over the
            # signature facts; field-for-field the serial mutation set) --
            item = nodes_get(node_name)
            if item is None:
                item = get_or_create(node_name)
            info = item.info
            info.pods.append(pi)
            if f.has_affinity:
                info.pods_with_affinity.append(pi)
            if f.has_anti_affinity:
                info.pods_with_required_anti_affinity.append(pi)
            req = info.requested
            req_get = req.get
            for k, v in f.req_items:
                req[k] = req_get(k, 0) + v
            info.non_zero_cpu += f.cpu_nz
            info.non_zero_mem += f.mem_nz
            if f.has_ports:
                info._update_ports(assumed, add=True)
            info.generation = next_generation()
            move_to_head(item)
            st = _PodState(pod=assumed, assumed=True, binding_finished=True)
            if ttl > 0:
                st.deadline = now + ttl
            pod_states[uid] = st
            assumed_set.add(uid)
            if nominated:
                nominator_delete(pod)
            in_flight_pop(uid, None)
            bound_pods.append((assumed, pod))
            event_refs.append((uid, node_name))
            attempts = qpi.attempts or 1
            slis = sli_by_attempts.get(attempts)
            if slis is None:
                slis = sli_by_attempts[attempts] = []
            slis.append(
                now - (qpi.initial_attempt_timestamp or qpi.timestamp))
            if qpi.unschedulable_plugins:
                qpi.unschedulable_plugins = set()
            qpi.consecutive_errors_count = 0
        if not in_flight:
            queue.in_flight_events.clear()
        nb = len(bound_pods)
        if nb:
            sched.journey.record_bulk(
                [uid for uid, _node in event_refs], EV_ASSIGN, now,
                detail=[node for _uid, node in event_refs])
            sched.dispatcher.add_binds(bound_pods)
            sched.events.scheduled_bulk(event_refs, now=now)
            sched.scheduled_count += nb
            sched.metrics.schedule_attempts.inc(SCHEDULED, profile.name,
                                                by=nb)
            for attempts, values in sli_by_attempts.items():
                sched.metrics.sli_duration.observe_array(values,
                                                         str(attempts))
        return bound + nb, failures
