"""Columnar ingest & commit engine (ISSUE 9 / ROADMAP item 1).

The device path compiles any pod mix into one static program (PR 8), so
Python owns the SchedulingBasic cycle: ~60% of it was pod ingest + commit
— per-pod object walks on both edges of a drain. This package replaces
those edges with columnar, vectorized host pipelines:

- `columns.py` — vectorized signature tensorize: `fill_rows` turns
  `BatchBuilder._fill_row`'s per-pod field walks into numpy batch ops
  over pre-extracted column lists (one write per PodTable column per
  chunk, bit-for-bit equal to the serial filler), plus the per-row
  `CommitFacts` column the commit engine consumes (requests / nonzero /
  port / affinity facts hoisted per signature instead of re-derived per
  pod at commit).
- `noderows.py` — columnar node-row tensorize: `write_rows` batches
  `ClusterState._write_row`'s ~20 scalar array stores per node into one
  scatter per NodeArrays field (prime/resync/mass-update path).
- `commit.py` — the batched assume/bind path: one pass over a resolved
  drain doing the columnar cache assume (inlined NodeInfo bookkeeping
  driven by CommitFacts), one bulk dispatcher enqueue, and the bulk
  bind-echo confirm (`Scheduler._on_pod_update_bulk`) that collapses the
  per-pod informer fan-out after a bulk bind.
- `groupcols.py` — per-statics-generation columnar node label store
  (interned topology-value / domain-id vectors) and the vectorized
  id→count gather that rebuilt `GroupManager.build_dev` seeding without
  its O(nodes)-per-signature Python walks.

The snapshot edge (generation-diff device scatter: upload only dirty
node rows via the `scatter_rows` JIT entry) lives in
`state/tensorize.py` + `ops/program.py`; this package holds the host
columnar machinery.
"""

from .columns import CommitFacts, commit_facts_for_row, fill_rows  # noqa: F401
