"""Columnar node-row tensorize: batch twin of ClusterState._write_row.

`apply_snapshot` walks the generation-dirty NodeInfos and rewrites one
row per node — ~20 scalar array stores each. At prime/resync (every row
dirty) and after mass node events that is the dominant host cost. The
two writers here extract the dirty rows into per-chunk column buffers
(Python still walks the small padded dims — interning forces that) and
then write each NodeArrays field with ONE fancy-index scatter.

Both return False when a capacity edge wants the serial path (resource
or image growth, taint/label/port overflow): the caller then falls back
to the per-row writers, which own growth and raise the same
CapacityError they always did. tests/test_ingest.py fuzzes bit-for-bit
NodeArrays equality between the columnar and serial writers.
"""

from __future__ import annotations

import numpy as np

from ..api import resources as res
from ..state.tensorize import _EFFECTS, NON_NUMERIC


def write_rows(state, items: list) -> bool:
    """Full-row columnar write of [(idx, NodeInfo)]. Mirrors
    `ClusterState._write_row` field-for-field; returns False (no writes)
    when any row needs growth or exceeds a padded dim."""
    a = state.arrays
    d = state.dims
    K = len(items)
    if not K:
        return True
    R = a.cap.shape[1]
    vector = state.rtable.vector
    intr = state.interner
    rows: list = []              # (idx, cap_row, used_row, ni, node)
    for idx, ni in items:
        node = ni.node
        cap_row = vector(ni.allocatable)
        used_row = vector(ni.requested)
        if len(cap_row) > R or len(used_row) > R:
            return False         # resource growth: serial path owns it
        if len(node.spec.taints) > d.taints:
            return False
        if len(ni.image_sizes) > d.images:
            return False         # image growth: serial path owns it
        rows.append((idx, cap_row, used_row, ni, node))
    cap = np.zeros((K, R), np.int64)
    used = np.zeros((K, R), np.int64)
    nonzero = np.zeros((K, 2), np.int64)
    npods = np.zeros((K,), np.int32)
    allowed = np.zeros((K,), np.int32)
    unsched = np.zeros((K,), bool)
    name_id = np.zeros((K,), np.int32)
    taint_key = np.zeros((K, d.taints), np.int32)
    taint_val = np.zeros((K, d.taints), np.int32)
    taint_eff = np.zeros((K, d.taints), np.int32)
    L = a.label_key.shape[1]
    label_key = np.zeros((K, L), np.int32)
    label_kv = np.zeros((K, L), np.int32)
    label_num = np.full((K, L), NON_NUMERIC, np.int64)
    P = a.ports.shape[1]
    ports = np.zeros((K, P), np.int32)
    I = a.image_id.shape[1]
    image_id = np.zeros((K, I), np.int32)
    image_size = np.zeros((K, I), np.int64)
    from ..state.tensorize import METADATA_NAME_KEY
    key_intern = intr.key.intern
    kv_intern = intr.kv.intern
    lab_kv = intr.label_kv
    port_id = intr.port_id
    img_intern = intr.image.intern
    node_id = state.node_id
    for k, (idx, cap_row, used_row, ni, node) in enumerate(rows):
        cap[k, :len(cap_row)] = cap_row
        used[k, :len(used_row)] = used_row
        nonzero[k, 0] = ni.non_zero_cpu
        nonzero[k, 1] = ni.non_zero_mem
        npods[k] = len(ni.pods)
        allowed[k] = ni.allocatable.get(res.PODS, 0)
        unsched[k] = node.spec.unschedulable
        name_id[k] = node_id(node.metadata.name)
        for t, taint in enumerate(node.spec.taints):
            taint_key[k, t] = key_intern(taint.key)
            taint_val[k, t] = kv_intern(f"tv:{taint.value}")
            taint_eff[k, t] = _EFFECTS.get(taint.effect, 0)
        labels = dict(node.metadata.labels)
        labels[METADATA_NAME_KEY] = node.metadata.name
        if len(labels) > d.labels:
            return False         # serial path raises CapacityError
        for li, (lk, lv) in enumerate(sorted(labels.items())):
            label_key[k, li] = key_intern(lk)
            label_kv[k, li] = lab_kv(lk, lv)
            try:
                label_num[k, li] = int(lv)
            except ValueError:
                pass             # buffer pre-filled with NON_NUMERIC
        pids = sorted({port_id(p, pt)
                       for (p, pt, _ip) in ni.used_ports.ports})
        if len(pids) > P:
            return False
        ports[k, :len(pids)] = pids
        for ii, (img, size) in enumerate(sorted(ni.image_sizes.items())):
            image_id[k, ii] = img_intern(img)
            image_size[k, ii] = size
    idxs = np.array([idx for idx, *_ in rows], np.intp)
    a.cap[idxs] = cap
    a.used[idxs] = used
    a.nonzero_used[idxs] = nonzero
    a.npods[idxs] = npods
    a.allowed_pods[idxs] = allowed
    a.valid[idxs] = True
    a.unschedulable[idxs] = unsched
    a.name_id[idxs] = name_id
    a.taint_key[idxs] = taint_key
    a.taint_val[idxs] = taint_val
    a.taint_eff[idxs] = taint_eff
    a.label_key[idxs] = label_key
    a.label_kv[idxs] = label_kv
    a.label_num[idxs] = label_num
    a.ports[idxs] = ports
    a.image_id[idxs] = image_id
    a.image_size[idxs] = image_size
    # bookkeeping the serial writer does per row
    state.statics_gen += K
    if state._dirty_rows is not None:
        state._dirty_rows.update(int(i) for i in idxs)
    return True


def write_aggregate_rows(state, items: list) -> bool:
    """Columnar `_write_row_aggregates` for [(idx, NodeInfo)] whose Node
    object is unchanged (pod aggregates only). Rows with live host ports
    keep the serial path (set-rebuild per row is rare and stateful).
    Returns False (no writes) when any row wants the serial writer."""
    a = state.arrays
    K = len(items)
    if not K:
        return True
    R = a.used.shape[1]
    vector = state.rtable.vector
    rows: list = []
    for idx, ni in items:
        if ni.used_ports.ports or a.ports[idx, 0]:
            return False         # port carry: serial path
        used_row = vector(ni.requested)
        if len(used_row) > R:
            return False         # resource growth: serial path
        rows.append((idx, used_row, ni))
    used = np.zeros((K, R), np.int64)
    nonzero = np.zeros((K, 2), np.int64)
    npods = np.zeros((K,), np.int32)
    for k, (idx, used_row, ni) in enumerate(rows):
        used[k, :len(used_row)] = used_row
        nonzero[k, 0] = ni.non_zero_cpu
        nonzero[k, 1] = ni.non_zero_mem
        npods[k] = len(ni.pods)
    idxs = np.array([idx for idx, *_ in rows], np.intp)
    a.used[idxs] = used
    a.nonzero_used[idxs] = nonzero
    a.npods[idxs] = npods
    if state._dirty_rows is not None:
        state._dirty_rows.update(int(i) for i in idxs)
    return True
