"""Vectorized signature tensorize + the per-row commit-facts column.

`fill_rows` is the columnar twin of `BatchBuilder._fill_row`
(state/batch.py): one chunk of NEW signatures is extracted into per-chunk
column buffers (Python walks the small padded dims exactly like the
serial filler — the interners force that), and each PodTable column is
then written with ONE numpy scatter for the whole chunk instead of ~30
scalar array stores per row. The serial `_fill_row` stays as the
reference implementation; tests/test_ingest.py fuzzes bit-for-bit
PodTable equality between the two (affinity term tables included).

`CommitFacts` is the columnar pod store's commit-side column: everything
the batched assume/bind path (ingest/commit.py) needs per pod, hoisted
per SIGNATURE ROW at interning time — request items, nonzero cpu/mem,
and the port/affinity membership flags `NodeInfo.add_pod` would
otherwise re-derive from the object graph on every single commit.
"""

from __future__ import annotations

from typing import NamedTuple

import numpy as np

from ..api import resources as res
from ..plugins.node_basics import NodeUnschedulable


class CommitFacts(NamedTuple):
    """Per-signature-row facts consumed by the batched commit path."""

    req_items: tuple        # ((resource, qty), ...) — pod_requests items
    cpu_nz: int             # NonZeroRequested cpu contribution
    mem_nz: int             # NonZeroRequested memory contribution
    has_ports: bool         # pod occupies host ports (used_ports update)
    has_affinity: bool      # NodeInfo.pods_with_affinity membership
    has_anti_affinity: bool  # pods_with_required_anti_affinity membership


def commit_facts_for_row(pod) -> CommitFacts:
    """Facts from a row's representative pod. Every field below is part
    of the signature key (state/batch.py _sig_key), so all pods interned
    into the row share them."""
    reqs = res.pod_requests(pod)
    cpu_nz, mem_nz = res.pod_requests_nonzero(pod)
    aff = pod.spec.affinity
    pa = aff.pod_affinity if aff else None
    paa = aff.pod_anti_affinity if aff else None
    has_ports = any(p.host_port > 0 for c in pod.spec.containers
                    for p in c.ports)
    return CommitFacts(
        req_items=tuple(reqs.items()),
        cpu_nz=cpu_nz, mem_nz=mem_nz,
        has_ports=has_ports,
        has_affinity=bool((pa and pa.required)
                          or (pa and pa.preferred)
                          or (paa and paa.preferred)),
        has_anti_affinity=bool(paa and paa.required),
    )


class _RowCols(NamedTuple):
    """Per-chunk extraction buffers (K rows), one per PodTable column."""

    req: np.ndarray
    nonzero_req: np.ndarray
    node_name_id: np.ndarray
    tol_key: np.ndarray
    tol_val: np.ndarray
    tol_eff: np.ndarray
    tol_op: np.ndarray
    tolerates_unsched: np.ndarray
    ns_sel_val: np.ndarray
    aff_has: np.ndarray
    aff_term_valid: np.ndarray
    aff_key: np.ndarray
    aff_op: np.ndarray
    aff_num: np.ndarray
    aff_val: np.ndarray
    pref_weight: np.ndarray
    pref_key: np.ndarray
    pref_op: np.ndarray
    pref_num: np.ndarray
    pref_val: np.ndarray
    port_ids: np.ndarray
    skip_balanced: np.ndarray
    img_ids: np.ndarray
    img_containers: np.ndarray


def _chunk_buffers(K: int, R: int, d) -> _RowCols:
    return _RowCols(
        req=np.zeros((K, R), np.int64),
        nonzero_req=np.zeros((K, 2), np.int64),
        node_name_id=np.zeros((K,), np.int32),
        tol_key=np.zeros((K, d.tolerations), np.int32),
        tol_val=np.zeros((K, d.tolerations), np.int32),
        tol_eff=np.zeros((K, d.tolerations), np.int32),
        tol_op=np.zeros((K, d.tolerations), np.int32),
        tolerates_unsched=np.zeros((K,), bool),
        ns_sel_val=np.zeros((K, d.sel_reqs), np.int32),
        aff_has=np.zeros((K,), bool),
        aff_term_valid=np.zeros((K, d.sel_terms), bool),
        aff_key=np.zeros((K, d.sel_terms, d.sel_reqs), np.int32),
        aff_op=np.zeros((K, d.sel_terms, d.sel_reqs), np.int32),
        aff_num=np.zeros((K, d.sel_terms, d.sel_reqs), np.int64),
        aff_val=np.zeros((K, d.sel_terms, d.sel_reqs, d.sel_vals), np.int32),
        pref_weight=np.zeros((K, d.pref_terms), np.int64),
        pref_key=np.zeros((K, d.pref_terms, d.sel_reqs), np.int32),
        pref_op=np.zeros((K, d.pref_terms, d.sel_reqs), np.int32),
        pref_num=np.zeros((K, d.pref_terms, d.sel_reqs), np.int64),
        pref_val=np.zeros((K, d.pref_terms, d.sel_reqs, d.sel_vals), np.int32),
        port_ids=np.zeros((K, d.ports), np.int32),
        skip_balanced=np.zeros((K,), bool),
        img_ids=np.zeros((K, d.images_per_pod), np.int32),
        img_containers=np.zeros((K,), np.int32),
    )


def _extract_row(builder, cols: _RowCols, k: int, pod) -> None:
    """One pod's fields → buffer row k. Field-for-field mirror of
    `BatchBuilder._fill_row` (the bit-for-bit parity contract); raises
    BatchCapacityError exactly where the serial filler does."""
    from ..state.batch import BatchCapacityError, TOL_EQUAL, TOL_EXISTS
    from ..state.tensorize import _EFFECTS

    d = builder.dims
    intr = builder.state.interner
    aff = pod.spec.affinity
    if pod.spec.volumes:
        raise BatchCapacityError("pod has volumes")
    if pod.spec.required_node_features:
        raise BatchCapacityError("pod requires declared node features")
    if pod.spec.resource_claims:
        raise BatchCapacityError("pod has resource claims")
    reqs = res.pod_requests(pod)
    row = builder.state.rtable.vector(reqs)
    if len(row) > cols.req.shape[1]:
        raise BatchCapacityError("resource table grew past batch width")
    cols.req[k, :len(row)] = row
    nz_cpu, nz_mem = res.pod_requests_nonzero(pod)
    cols.nonzero_req[k, 0] = nz_cpu
    cols.nonzero_req[k, 1] = nz_mem
    cols.skip_balanced[k] = all(v == 0 for v in reqs.values())
    if pod.spec.node_name:
        cols.node_name_id[k] = builder.state.node_id(pod.spec.node_name)
    tols = pod.spec.tolerations
    if len(tols) > d.tolerations:
        raise BatchCapacityError("too many tolerations")
    for t, tol in enumerate(tols):
        cols.tol_key[k, t] = intr.key.intern(tol.key) if tol.key else 0
        cols.tol_val[k, t] = intr.kv.intern(f"tv:{tol.value}")
        cols.tol_eff[k, t] = _EFFECTS.get(tol.effect, 0) if tol.effect else 0
        op = tol.operator or "Equal"
        cols.tol_op[k, t] = TOL_EXISTS if op == "Exists" else TOL_EQUAL
    cols.tolerates_unsched[k] = any(
        t.tolerates(NodeUnschedulable.TAINT) for t in tols)
    sel = pod.spec.node_selector
    if len(sel) > d.sel_reqs:
        raise BatchCapacityError("nodeSelector too wide")
    for q, (key, v) in enumerate(sorted(sel.items())):
        cols.ns_sel_val[k, q] = intr.label_kv(key, v)
    na = aff.node_affinity if aff else None
    if na and na.required is not None:
        terms = na.required.terms
        if len(terms) > d.sel_terms:
            raise BatchCapacityError("too many nodeAffinity terms")
        cols.aff_has[k] = True
        for t, term in enumerate(terms):
            cols.aff_term_valid[k, t] = True
            builder._fill_term(term, cols.aff_key[k, t], cols.aff_op[k, t],
                               cols.aff_num[k, t], cols.aff_val[k, t])
    if na and na.preferred:
        prefs = na.preferred
        if len(prefs) > d.pref_terms:
            raise BatchCapacityError("too many preferred terms")
        for t, p in enumerate(prefs):
            if p.weight == 0:
                continue
            cols.pref_weight[k, t] = p.weight
            builder._fill_term(p.preference, cols.pref_key[k, t],
                               cols.pref_op[k, t], cols.pref_num[k, t],
                               cols.pref_val[k, t])
    ports = [(p.protocol or "TCP", p.host_port, p.host_ip)
             for c in pod.spec.containers for p in c.ports if p.host_port > 0]
    if any(ip not in ("", "0.0.0.0") for (_, _, ip) in ports):
        raise BatchCapacityError("host-IP-scoped port")
    if len(ports) > d.ports:
        raise BatchCapacityError("too many host ports")
    for q, (proto, port, _ip) in enumerate(ports):
        cols.port_ids[k, q] = intr.port_id(proto, port)
    from ..plugins.imagelocality import normalized_image_name
    containers = (list(pod.spec.init_containers) + list(pod.spec.containers))
    imgs = [normalized_image_name(c.image) for c in containers if c.image]
    if imgs and len(imgs) > d.images_per_pod:
        raise BatchCapacityError("too many container images")
    cols.img_containers[k] = len(containers) if imgs else 0
    for q, img in enumerate(imgs):
        cols.img_ids[k, q] = intr.image.intern(img)


def fill_rows(builder, pods: list) -> list:
    """Intern a chunk of NEW-signature pods into the builder's PodTable
    with columnar writes. `pods` are the chunk's first-appearance
    representatives in drain order (the order mints signature ids, so it
    must match what the serial per-pod path would do).

    Returns one entry per input pod:
      ("row", sig_id, row) — interned (the builder's table/groups/facts
                             all updated, table_used advanced);
      ("fallback", reason) — the pod exceeds a padded dim / keeps host
                             semantics (no table row consumed).

    Capacity growth happens exactly like the serial path: a row is
    assigned only after BOTH the field extraction and the group-row parse
    succeed, so a mid-chunk failure never strands a half-written row.
    """
    from ..state.batch import BatchCapacityError

    K = len(pods)
    if not K:
        return []
    # column width follows the TABLE, not the live resource dims: a
    # mid-chunk resource interning past the table width must fall back
    # exactly like the serial filler's width check
    cols = _chunk_buffers(K, builder.table.req.shape[1], builder.dims)
    out: list = [None] * K
    kept: list = []          # (k, pod) that passed extraction
    for k, pod in enumerate(pods):
        try:
            _extract_row(builder, cols, k, pod)
        except BatchCapacityError as e:
            out[k] = ("fallback", str(e))
            continue
        kept.append((k, pod))
    rows: list = []          # (k, assigned row) for the final scatter
    for k, pod in enumerate(pods):
        # grow-before-attempt for EVERY new-signature candidate —
        # including ones whose extraction already fell back — exactly
        # like the serial _lookup (parity of table capacity and the
        # growth-driven carry reseeds)
        if builder.table_used >= builder.table.req.shape[0]:
            builder._grow_table()
        if out[k] is not None:
            continue
        u = builder.table_used
        try:
            builder.groups.add_row(u, pod)
        except BatchCapacityError as e:
            out[k] = ("fallback", str(e))
            continue
        sig_id = 0 if cols.port_ids[k].any() else builder._next_sig
        if sig_id:
            builder._next_sig += 1
        builder.table_used += 1
        builder.table_version += 1
        builder.row_facts.append(commit_facts_for_row(pod))
        rows.append((k, u))
        out[k] = ("row", sig_id, u)
    if rows:
        ks = np.array([k for k, _ in rows], np.intp)
        us = np.array([u for _, u in rows], np.intp)
        table = builder.table
        for name in _RowCols._fields:
            getattr(table, name)[us] = getattr(cols, name)[ks]
    return out
