"""Columnar node label store for group-tensor seeding.

`GroupManager.build_dev` (ops/groups.py) seeds spread / inter-pod
affinity tensors by walking O(nodes) Python per signature row — the
topology-value interning walk, the domain-id walk, and a per-node dict
lookup for every count surface. Those walks ran per build_dev call
(scheduler reseed, host-greedy, diagnosis), every time.

`NodeLabelColumns` hoists the label views into per-statics-generation
columns: one interned topology-value vector and one dense domain-id
vector per topology key, computed once per node-state statics change
(ClusterState.statics_gen — the same key the compiler's SurfaceCache
trusts) and shared by every row, constraint and term that names the
key. `gather_ids` then turns the per-node count-dict lookups into one
sorted-search gather over the interned ids.
"""

from __future__ import annotations

import numpy as np


def gather_ids(tv: np.ndarray, id_values: dict, dtype=np.int64) -> np.ndarray:
    """Vectorized `{interned id: value}` lookup over an id vector:
    out[i] = id_values.get(tv[i], 0). One argsort of the (small) dict +
    one searchsorted over the node axis replaces the per-node Python
    dict probes."""
    out = np.zeros(tv.shape, dtype)
    if not id_values:
        return out
    ids = np.fromiter(id_values.keys(), np.int64, len(id_values))
    vals = np.fromiter(id_values.values(), dtype, len(id_values))
    order = np.argsort(ids)
    ids = ids[order]
    vals = vals[order]
    pos = np.searchsorted(ids, tv)
    pos_c = np.minimum(pos, len(ids) - 1)
    hit = ids[pos_c] == tv
    out[hit] = vals[pos_c[hit]]
    return out


class NodeLabelColumns:
    """Per-statics-generation interned label columns (see module doc).

    Validity contract: a column set is keyed on (statics_gen, node
    bucket). Every node add/remove/label change writes or invalidates a
    row, which bumps statics_gen (state/tensorize.py), so cached vectors
    can never describe a stale node set; snapshot-list ORDER is likewise
    a function of the node tree, which only changes with membership."""

    def __init__(self, state):
        self.state = state
        self._key = (-1, -1)
        self._nis: list = []
        self._tv: dict = {}        # topology key → i32 [N] label_kv ids
        self._dom: dict = {}       # topology key → i32 [N] dense dom ids
        self._keys_ok: dict = {}   # keys tuple → bool [N]
        self._order_idx = np.zeros((0,), np.int64)

    def sync(self, nis: list) -> "NodeLabelColumns":
        """Bind to the current node rows ([(row idx, NodeInfo)] in
        snapshot order); drops the columns when the statics generation
        or node bucket moved."""
        key = (self.state.statics_gen, self.state.dims.nodes)
        if key != self._key:
            self._key = key
            self._tv.clear()
            self._dom.clear()
            self._keys_ok.clear()
            self._order_idx = np.array([idx for idx, _ in nis], np.int64)
        self._nis = nis
        return self

    @property
    def order_idx(self) -> np.ndarray:
        return self._order_idx

    def tv(self, key: str) -> np.ndarray:
        """Interned label_kv id of label `key` per node row (0 = label
        absent) — the O(N) walk runs once per (key, statics_gen)."""
        v = self._tv.get(key)
        if v is None:
            N = self.state.dims.nodes
            v = np.zeros((N,), np.int32)
            kid: dict = {}
            intern = self.state.interner.label_kv
            for idx, ni in self._nis:
                val = ni.node.metadata.labels.get(key)
                if val is not None:
                    t = kid.get(val)
                    if t is None:
                        t = kid[val] = intern(key, val)
                    v[idx] = t
            self._tv[key] = v
        return v

    def dom(self, key: str) -> np.ndarray:
        """Dense domain id per node: the row index of the FIRST node (in
        snapshot order) sharing the key's topology value."""
        d = self._dom.get(key)
        if d is None:
            tvv = self.tv(key)
            N = self.state.dims.nodes
            d = np.zeros((N,), np.int32)
            order_idx = self._order_idx
            if len(order_idx):
                sub = tvv[order_idx]
                uniq, first_pos = np.unique(sub, return_index=True)
                first_row = order_idx[first_pos]
                d[order_idx] = first_row[np.searchsorted(uniq, sub)]
            self._dom[key] = d
        return d

    def keys_ok(self, keys: tuple) -> np.ndarray:
        """bool [N]: node is in the snapshot AND carries every key."""
        ok = self._keys_ok.get(keys)
        if ok is None:
            N = self.state.dims.nodes
            ok = np.zeros((N,), bool)
            ok[self._order_idx] = True
            for k in keys:
                ok = ok & (self.tv(k) != 0)
            self._keys_ok[keys] = ok
        return ok

    def value_ids(self, key: str, values: dict, dtype=np.int64) -> dict:
        """{interned label_kv(key, value): v} for a value-string-keyed
        count/score dict (the seeding surfaces are keyed by raw label
        values; the vectorized gather wants interned ids)."""
        intern = self.state.interner.label_kv
        return {intern(key, val): v for val, v in values.items()}
