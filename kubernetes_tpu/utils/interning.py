"""String interning — the bridge from Kubernetes' stringly-typed label world
to fixed-width integer tensors.

Every label key, key=value pair, taint, topology value and port tuple is
interned to a dense positive int32 id. Selector evaluation on device then
reduces to integer equality against padded id arrays. Id 0 is reserved as
"empty/padding" everywhere, so masks can test `ids != 0`.

This replaces the reference's ubiquitous `labels.Selector.Matches` string
matching (apimachinery labels/selector.go) on the hot path; the host keeps
the strings for the slow/generic fallback paths (Gt/Lt node selectors etc.).
"""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass
class InternTable:
    """Dense interner. Ids start at 1; 0 means empty."""

    index: dict[str, int] = field(default_factory=dict)
    strings: list[str] = field(default_factory=lambda: [""])

    def intern(self, s: str) -> int:
        i = self.index.get(s)
        if i is None:
            i = len(self.strings)
            self.index[s] = i
            self.strings.append(s)
        return i

    def lookup(self, s: str) -> int:
        """0 if never interned (never matches anything on device)."""
        return self.index.get(s, 0)

    def string(self, i: int) -> str:
        return self.strings[i]

    def __len__(self) -> int:
        return len(self.strings)


@dataclass
class ClusterInterner:
    """All intern tables used to tensorize cluster state."""

    # "key=value" pairs for labels (nodes and pods share one table)
    kv: InternTable = field(default_factory=InternTable)
    # bare label keys (Exists / DoesNotExist / topology keys)
    key: InternTable = field(default_factory=InternTable)
    # taint/toleration "key=value" and keys reuse kv/key tables
    # topology VALUES per topology key: interned as "key\x00value" in kv —
    # cheap and collision-free.
    # namespaces
    namespace: InternTable = field(default_factory=InternTable)
    # image names
    image: InternTable = field(default_factory=InternTable)

    def label_kv(self, k: str, v: str) -> int:
        return self.kv.intern(f"{k}={v}")

    def label_kv_lookup(self, k: str, v: str) -> int:
        return self.kv.lookup(f"{k}={v}")

    def label_key(self, k: str) -> int:
        return self.key.intern(k)

    def label_key_lookup(self, k: str) -> int:
        return self.key.lookup(k)

    def topo_value(self, key: str, value: str) -> int:
        return self.kv.intern(f"{key}\x00{value}")

    def port_id(self, protocol: str, port: int) -> int:
        return self.kv.intern(f"port:{protocol}:{port}")

    def ip_id(self, ip: str) -> int:
        # 0.0.0.0 and "" are the wildcard; give them id 0 so device code can
        # treat wildcard as "matches everything".
        if ip in ("", "0.0.0.0"):
            return 0
        return self.kv.intern(f"ip:{ip}")
