"""Tracing: span trees + slow-cycle logging.

The reference wires OpenTelemetry through component-base/tracing (spans
around the scheduling cycle, schedule_one.go) and logs slow cycles via
klog verbosity. This is the dependency-free analog:

- `Tracer.span(name)` context manager builds a per-cycle span tree with
  wall-clock durations and optional attributes.
- finished root spans whose duration exceeds `slow_threshold_s` are kept in
  `slow_cycles` (ring buffer) and handed to `on_slow` (default: stdlib
  logging at WARNING) with a per-child breakdown — the "why was this cycle
  slow" answer the reference gets from attempt-duration histograms plus
  trace sampling.
- `NOOP_TRACER` keeps the hot path branch-free when tracing is off: span()
  returns a reusable null context.
"""

from __future__ import annotations

import logging
import time
from collections import deque
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Callable, Optional

logger = logging.getLogger("kubernetes_tpu.tracing")


@dataclass
class Span:
    name: str
    start: float = 0.0
    duration_s: float = 0.0
    attributes: dict = field(default_factory=dict)
    children: list["Span"] = field(default_factory=list)

    def set(self, **attrs) -> None:
        self.attributes.update(attrs)

    def breakdown(self, indent: int = 0) -> str:
        lines = [f"{'  ' * indent}{self.name}: {self.duration_s * 1e3:.1f}ms"
                 + (f" {self.attributes}" if self.attributes else "")]
        for c in self.children:
            lines.append(c.breakdown(indent + 1))
        return "\n".join(lines)


class _NullSpan:
    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **attrs) -> None:
        pass


_NULL_SPAN = _NullSpan()


class Tracer:
    """Per-component tracer; single-threaded like the host loop it serves."""

    def __init__(self, slow_threshold_s: float = 1.0, keep: int = 32,
                 on_slow: Optional[Callable[[Span], None]] = None,
                 clock: Callable[[], float] = time.perf_counter):
        self.slow_threshold_s = slow_threshold_s
        self.clock = clock
        self.slow_cycles: deque[Span] = deque(maxlen=keep)
        self.on_slow = on_slow or self._log_slow
        self._stack: list[Span] = []

    @contextmanager
    def span(self, name: str, **attributes):
        sp = Span(name=name, start=self.clock(),
                  attributes=dict(attributes))
        parent = self._stack[-1] if self._stack else None
        if parent is not None:
            parent.children.append(sp)
        self._stack.append(sp)
        try:
            yield sp
        finally:
            self._stack.pop()
            sp.duration_s = self.clock() - sp.start
            if parent is None and sp.duration_s >= self.slow_threshold_s:
                self.slow_cycles.append(sp)
                self.on_slow(sp)

    @staticmethod
    def _log_slow(sp: Span) -> None:
        logger.warning("slow scheduling cycle (%.0fms):\n%s",
                       sp.duration_s * 1e3, sp.breakdown())


class NoopTracer:
    slow_cycles: deque = deque()

    def span(self, name: str, **attributes):
        return _NULL_SPAN


NOOP_TRACER = NoopTracer()
