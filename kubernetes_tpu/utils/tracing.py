"""Tracing: span trees + slow-cycle logging.

The reference wires OpenTelemetry through component-base/tracing (spans
around the scheduling cycle, schedule_one.go) and logs slow cycles via
klog verbosity. This is the dependency-free analog:

- `Tracer.span(name)` context manager builds a per-cycle span tree with
  wall-clock durations and optional attributes.
- finished root spans whose duration exceeds `slow_threshold_s` are kept in
  `slow_cycles` (ring buffer) and handed to `on_slow` (default: stdlib
  logging at WARNING) with a per-child breakdown — the "why was this cycle
  slow" answer the reference gets from attempt-duration histograms plus
  trace sampling.
- `NOOP_TRACER` keeps the hot path branch-free when tracing is off: span()
  returns a reusable null context.
- finished span trees export as Chrome-trace / Perfetto JSON
  (`to_chrome_trace` / `export_chrome_trace`): monotonic timestamps, one
  complete ("X") event per span, attributes as args — load the file at
  chrome://tracing or ui.perfetto.dev. `keep_recent` retains the last K
  root spans regardless of duration so a bench run can export its whole
  drain history.
- `jax_profiler_session(dir)` optionally brackets a workload with a
  jax.profiler trace (XLA/TPU-level view under the host spans), gated by
  the `profilerTraceDir` config knob.
- `PhaseTrack` is the continuous-profiler hook: a plain-list span-name
  stack the Scheduler pushes/pops in lockstep with its phase spans
  (host_snapshot/host_tensorize/host_group_seed/host_cache/device/
  commit), readable from ANY thread — the sampling host profiler
  (perf/profiler.py) tags every sample with `current()`. Kept separate
  from Tracer so attribution works even under NOOP_TRACER (two list ops
  per phase per drain — cheap enough to never turn off).
"""

from __future__ import annotations

import json
import logging
import time
from collections import deque
from contextlib import contextmanager, nullcontext
from dataclasses import dataclass, field
from typing import Callable, Optional

logger = logging.getLogger("kubernetes_tpu.tracing")


@dataclass
class Span:
    name: str
    start: float = 0.0
    duration_s: float = 0.0
    attributes: dict = field(default_factory=dict)
    children: list["Span"] = field(default_factory=list)

    def set(self, **attrs) -> None:
        self.attributes.update(attrs)

    def breakdown(self, indent: int = 0) -> str:
        lines = [f"{'  ' * indent}{self.name}: {self.duration_s * 1e3:.1f}ms"
                 + (f" {self.attributes}" if self.attributes else "")]
        for c in self.children:
            lines.append(c.breakdown(indent + 1))
        return "\n".join(lines)

    def to_dict(self) -> dict:
        """Nested-dict form (the /debug/slowcycles serialization)."""
        return {"name": self.name,
                "duration_ms": round(self.duration_s * 1e3, 3),
                "attributes": dict(self.attributes),
                "children": [c.to_dict() for c in self.children]}

    def find(self, name: str) -> Optional["Span"]:
        if self.name == name:
            return self
        for c in self.children:
            hit = c.find(name)
            if hit is not None:
                return hit
        return None


class PhaseTrack:
    """Cross-thread-readable stack of open phase/span names.

    The owner (single-threaded host loop) pushes and pops; the profiler
    thread only reads the top — CPython list append/pop/index are atomic
    under the GIL, so no lock is needed and a torn read is impossible."""

    __slots__ = ("_stack",)

    def __init__(self) -> None:
        self._stack: list = []

    def push(self, name: str) -> None:
        self._stack.append(name)

    def pop(self) -> None:
        if self._stack:
            self._stack.pop()

    def current(self) -> str:
        s = self._stack
        return s[-1] if s else ""

    @contextmanager
    def scope(self, name: str):
        self._stack.append(name)
        try:
            yield
        finally:
            self.pop()


class _NullSpan:
    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **attrs) -> None:
        pass


_NULL_SPAN = _NullSpan()


class Tracer:
    """Per-component tracer; single-threaded like the host loop it serves."""

    def __init__(self, slow_threshold_s: float = 1.0, keep: int = 32,
                 on_slow: Optional[Callable[[Span], None]] = None,
                 clock: Callable[[], float] = time.perf_counter,
                 keep_recent: int = 0):
        self.slow_threshold_s = slow_threshold_s
        self.clock = clock
        self.slow_cycles: deque[Span] = deque(maxlen=keep)
        # every finished ROOT span, slow or not (trace export); off at 0
        self.recent: deque[Span] = deque(maxlen=max(keep_recent, 1))
        self.keep_recent = keep_recent
        self.on_slow = on_slow or self._log_slow
        self._stack: list[Span] = []

    @contextmanager
    def span(self, name: str, **attributes):
        sp = Span(name=name, start=self.clock(),
                  attributes=dict(attributes))
        parent = self._stack[-1] if self._stack else None
        if parent is not None:
            parent.children.append(sp)
        self._stack.append(sp)
        try:
            yield sp
        finally:
            self._stack.pop()
            sp.duration_s = self.clock() - sp.start
            if parent is None:
                if self.keep_recent:
                    self.recent.append(sp)
                if sp.duration_s >= self.slow_threshold_s:
                    self.slow_cycles.append(sp)
                    self.on_slow(sp)

    def export_chrome_trace(self, path: str) -> int:
        """Write the retained root spans (recent if enabled, else the slow
        ring) as Chrome-trace JSON; returns the event count."""
        spans = list(self.recent if self.keep_recent else self.slow_cycles)
        trace = to_chrome_trace(spans)
        with open(path, "w") as f:
            json.dump(trace, f)
        return len(trace["traceEvents"])

    @staticmethod
    def _log_slow(sp: Span) -> None:
        logger.warning("slow scheduling cycle (%.0fms):\n%s",
                       sp.duration_s * 1e3, sp.breakdown())


class NoopTracer:
    slow_cycles: deque = deque()
    recent: deque = deque()
    keep_recent = 0

    def span(self, name: str, **attributes):
        return _NULL_SPAN


NOOP_TRACER = NoopTracer()


# ---------------------------------------------------------------------------
# Chrome-trace / Perfetto export


# tid of the merged device-kernel lane: spans tagged lane="device" (the
# kernel observatory's per-dispatch events, attached as children of the
# drain's device_dispatch span) render as their own Perfetto track under
# the same process, so the host timeline and its device decomposition
# read as ONE trace
DEVICE_LANE_TID = 2


def _span_events(sp: Span, out: list, pid: int, tid: int) -> None:
    if sp.attributes.get("lane") == "device":
        tid = DEVICE_LANE_TID
    out.append({"ph": "X", "cat": "scheduler", "name": sp.name,
                "ts": sp.start * 1e6,            # µs, monotonic base
                "dur": max(sp.duration_s, 0.0) * 1e6,
                "pid": pid, "tid": tid,
                "args": {k: (v if isinstance(v, (int, float, bool, str))
                             else str(v))
                         for k, v in sp.attributes.items()}})
    for c in sp.children:
        _span_events(c, out, pid, tid)


def to_chrome_trace(spans: list[Span], process_name: str = "kube-scheduler-tpu"
                    ) -> dict:
    """Span trees → Chrome-trace JSON object (trace_event format, loadable
    at chrome://tracing / ui.perfetto.dev). Every span becomes one complete
    ("X") event; timestamps keep the tracer's monotonic base. Device-lane
    spans (kernel observatory dispatches) land on their own thread track
    (DEVICE_LANE_TID) nested timewise inside their drain's device span."""
    events: list[dict] = [
        {"ph": "M", "name": "process_name", "pid": 1, "tid": 1,
         "args": {"name": process_name}},
        {"ph": "M", "name": "thread_name", "pid": 1, "tid": 1,
         "args": {"name": "host-loop"}},
        {"ph": "M", "name": "thread_name", "pid": 1,
         "tid": DEVICE_LANE_TID, "args": {"name": "device-lanes"}},
    ]
    for sp in spans:
        _span_events(sp, events, 1, 1)
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def export_chrome_trace(path: str, spans: list[Span]) -> int:
    trace = to_chrome_trace(spans)
    with open(path, "w") as f:
        json.dump(trace, f)
    return len(trace["traceEvents"])


def fleet_chrome_trace(members) -> dict:
    """Merge N instances' span histories onto ONE Chrome trace: each
    instance becomes its own process track (pid = shard index + 1, named
    after the instance) with the usual host-loop / device-lanes threads
    underneath. All tracers share the monotonic clock base (in-process
    fleet; the cross-process step will need a clock offset per scrape),
    so per-shard tracks line up timewise — a steal renders as the drain
    span ending on one track and the adopter's drain starting on the
    next. `members` is an iterable of (name, tracer) pairs; each
    tracer's retained root spans (recent if kept, else slow ring) are
    exported."""
    events: list[dict] = []
    for i, (name, tracer) in enumerate(members):
        pid = i + 1
        events.extend([
            {"ph": "M", "name": "process_name", "pid": pid, "tid": 1,
             "args": {"name": f"shard:{name}"}},
            {"ph": "M", "name": "thread_name", "pid": pid, "tid": 1,
             "args": {"name": "host-loop"}},
            {"ph": "M", "name": "thread_name", "pid": pid,
             "tid": DEVICE_LANE_TID, "args": {"name": "device-lanes"}},
        ])
        keep_recent = getattr(tracer, "keep_recent", 0)
        spans = list(tracer.recent if keep_recent
                     else getattr(tracer, "slow_cycles", ()))
        for sp in spans:
            _span_events(sp, events, pid, 1)
    return {"traceEvents": events, "displayTimeUnit": "ms"}


@contextmanager
def jax_profiler_session(trace_dir: Optional[str]):
    """Bracket a workload with a jax.profiler trace when `trace_dir` is
    set (the config `profilerTraceDir` knob); a no-op otherwise, and any
    profiler failure (unsupported backend, busy session) degrades to the
    no-op instead of sinking the workload."""
    if not trace_dir:
        with nullcontext():
            yield
        return
    import jax
    started = False
    try:
        jax.profiler.start_trace(trace_dir)
        started = True
    except Exception as e:    # pragma: no cover - backend specific
        logger.warning("jax profiler session unavailable: %s", e)
    try:
        yield
    finally:
        if started:
            try:
                jax.profiler.stop_trace()
            except Exception as e:  # pragma: no cover - backend specific
                logger.warning("jax profiler stop failed: %s", e)
