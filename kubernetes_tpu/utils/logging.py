"""klog-style leveled, structured logging.

Mirrors the reference's klog/v2 conventions (contextual key/value logging;
verbosity levels V(2) production, V(4/5) debug, V(10) per-score dumps —
pkg/scheduler/schedule_one.go:830-838) on top of the stdlib logging module:

    from kubernetes_tpu.utils.logging import klog
    klog.v(2).info("Scheduled pod", pod=uid, node=name)
    klog.error("bind failed", err=e, pod=uid)

`set_verbosity(n)` enables V(m) for m <= n (default 2, like a production
kube-scheduler). V-levels map onto stdlib levels beneath INFO so standard
handlers/formatters keep working; key/values render as k=v suffixes the way
klog's structured output does.
"""

from __future__ import annotations

import logging
import os

_logger = logging.getLogger("kubernetes_tpu")
if not _logger.handlers:  # library default: stderr handler, not propagated
    _h = logging.StreamHandler()
    _h.setFormatter(logging.Formatter(
        "%(levelname).1s%(asctime)s.%(msecs)03d %(name)s] %(message)s",
        datefmt="%H:%M:%S"))
    _logger.addHandler(_h)
    _logger.propagate = False

_verbosity = int(os.environ.get("KTPU_VERBOSITY", "2"))


def set_verbosity(v: int) -> None:
    global _verbosity
    _verbosity = v


def verbosity() -> int:
    return _verbosity


def _fmt(msg: str, kv: dict) -> str:
    if not kv:
        return msg
    parts = " ".join(f"{k}={v!r}" if isinstance(v, str) else f"{k}={v}"
                     for k, v in kv.items())
    return f"{msg} {parts}"


class _Verbose:
    """klog.Verbose: a level-gated handle; `enabled` lets callers skip
    expensive argument construction (if klog.v(5).enabled: ...)."""

    __slots__ = ("enabled",)

    def __init__(self, enabled: bool):
        self.enabled = enabled

    def info(self, msg: str, **kv) -> None:
        if self.enabled:
            _logger.info(_fmt(msg, kv))


class _Klog:
    def v(self, level: int) -> _Verbose:
        return _Verbose(level <= _verbosity)

    def info(self, msg: str, **kv) -> None:
        _logger.info(_fmt(msg, kv))

    def warning(self, msg: str, **kv) -> None:
        _logger.warning(_fmt(msg, kv))

    def error(self, msg: str, **kv) -> None:
        _logger.error(_fmt(msg, kv))

    def exception(self, msg: str, **kv) -> None:
        """error + traceback of the active exception (klog.ErrorS with an
        err and stack)."""
        _logger.exception(_fmt(msg, kv))


klog = _Klog()
