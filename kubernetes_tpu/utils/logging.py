"""klog-style leveled, structured logging.

Mirrors the reference's klog/v2 conventions (contextual key/value logging;
verbosity levels V(2) production, V(4/5) debug, V(10) per-score dumps —
pkg/scheduler/schedule_one.go:830-838) on top of the stdlib logging module:

    from kubernetes_tpu.utils.logging import klog
    klog.v(2).info("Scheduled pod", pod=uid, node=name)
    klog.error("bind failed", err=e, pod=uid)

`set_verbosity(n)` enables V(m) for m <= n (default 2, like a production
kube-scheduler). V-levels map onto stdlib levels beneath INFO so standard
handlers/formatters keep working; key/values render as k=v suffixes the way
klog's structured output does.

`log_context(drain=N)` scopes ambient key/values onto every line emitted
inside it (klog's WithValues / logr context analog): the scheduler tags
dispatch and commit blocks with the drain id, so one grep of `drain=17`
correlates log lines with the matching span tree, FlightRecorder entry
and Scheduled/FailedScheduling events.
"""

from __future__ import annotations

import logging
import os
from contextlib import contextmanager

_logger = logging.getLogger("kubernetes_tpu")
if not _logger.handlers:  # library default: stderr handler, not propagated
    _h = logging.StreamHandler()
    _h.setFormatter(logging.Formatter(
        "%(levelname).1s%(asctime)s.%(msecs)03d %(name)s] %(message)s",
        datefmt="%H:%M:%S"))
    _logger.addHandler(_h)
    _logger.propagate = False

_verbosity = int(os.environ.get("KTPU_VERBOSITY", "2"))


def set_verbosity(v: int) -> None:
    global _verbosity
    _verbosity = v


def verbosity() -> int:
    return _verbosity


# ambient key/values appended to every line (log_context); a plain dict —
# the host loop is single-threaded and the profiler/server threads only
# ever emit with an empty context of their own
_context: dict = {}


@contextmanager
def log_context(**kv):
    """Scope ambient key/values onto every klog line emitted inside."""
    saved = {k: _context.get(k, _MISSING) for k in kv}
    _context.update(kv)
    try:
        yield
    finally:
        for k, v in saved.items():
            if v is _MISSING:
                _context.pop(k, None)
            else:
                _context[k] = v


_MISSING = object()


def _fmt(msg: str, kv: dict) -> str:
    if _context:
        kv = {**kv, **{k: v for k, v in _context.items() if k not in kv}}
    if not kv:
        return msg
    parts = " ".join(f"{k}={v!r}" if isinstance(v, str) else f"{k}={v}"
                     for k, v in kv.items())
    return f"{msg} {parts}"


class _Verbose:
    """klog.Verbose: a level-gated handle; `enabled` lets callers skip
    expensive argument construction (if klog.v(5).enabled: ...)."""

    __slots__ = ("enabled",)

    def __init__(self, enabled: bool):
        self.enabled = enabled

    def info(self, msg: str, **kv) -> None:
        if self.enabled:
            _logger.info(_fmt(msg, kv))


class _Klog:
    def v(self, level: int) -> _Verbose:
        return _Verbose(level <= _verbosity)

    def info(self, msg: str, **kv) -> None:
        _logger.info(_fmt(msg, kv))

    def warning(self, msg: str, **kv) -> None:
        _logger.warning(_fmt(msg, kv))

    def error(self, msg: str, **kv) -> None:
        _logger.error(_fmt(msg, kv))

    def exception(self, msg: str, **kv) -> None:
        """error + traceback of the active exception (klog.ErrorS with an
        err and stack)."""
        _logger.exception(_fmt(msg, kv))


klog = _Klog()
