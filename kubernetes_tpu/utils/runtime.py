"""Runtime tuning for the scheduling hot path: GC scheduled like work.

The commit edge allocates ~4 small objects per scheduled pod (the
assume-copy triple + cache state). At drain rates in the tens of
thousands of pods per second that allocation rate drives CPython's
generational collector into scanning the scheduler's long-lived object
graph (cache, snapshot, queue, device staging) once per few hundred
drained pods — measured at 30-45% of the commit phase wall on
SchedulingBasic, and it lands wherever the allocation happens to
trip the threshold, inflating every phase's tail.

A scheduler under sustained load has a better collection point than
"whenever gen0 fills": the windows where the device is busy and the
host is idle. `scheduling_gc_pause()` therefore:

  * `gc.freeze()`s the baseline graph (everything allocated before the
    serving window is effectively immortal — nodes, snapshot, compiled
    plans), so young-gen scans stop re-walking it;
  * disables the automatic collector for the window;
  * leaves EXPLICIT collection to the caller: the streaming pipeline
    runs `opportunistic_collect()` from its commit worker whenever the
    drain pipeline goes idle, and every exit path re-enables the
    collector and runs a full collection.

This is the CPython analog of tuning GOGC on the reference scheduler —
a deployment-level knob, applied here at the two serving entry points
(the perf harness's measured window and the streaming pipeline) rather
than process-wide.
"""

from __future__ import annotations

import contextlib
import gc
import time


@contextlib.contextmanager
def scheduling_gc_pause():
    """Suspend automatic collection for a scheduling window.

    Collects + freezes the pre-window graph on entry; on exit unfreezes,
    re-enables the collector and collects whatever the window minted.
    Re-entrant: nested uses leave the outermost owner in charge.
    """
    was_enabled = gc.isenabled()
    if was_enabled:
        gc.collect()
        gc.freeze()
        gc.disable()
    try:
        yield
    finally:
        if was_enabled:
            gc.enable()
            gc.unfreeze()
            gc.collect()


def opportunistic_collect(max_seconds: float = 0.01) -> bool:
    """One young-generation collection, intended for device-idle windows
    while automatic collection is paused. Returns True when it ran over
    `max_seconds` (callers can back off their idle-GC cadence)."""
    t0 = time.perf_counter()
    gc.collect(0)
    return (time.perf_counter() - t0) > max_seconds
