"""Framework runtime: the plugin runner (host path).

Mirrors pkg/scheduler/framework/runtime/framework.go — RunPreFilterPlugins
(:875-936, Skip set + PreFilterResult merge), RunFilterPlugins (:1046), the
three-phase RunScorePlugins (:1286-1390) — and schedule_one.go's schedulePod
(:426-483) as `schedule_pod`. On the TPU path this code is the *oracle*: the
batched device program must produce bind decisions in `schedule_pod`'s argmax
set; it is also the fallback for pods whose constraints have no tensor form
(the analog of the reference disabling batching when a plugin lacks
SignPlugin, runtime/framework.go:772-816).

One deliberate divergence: the reference breaks score ties with a seeded RNG
(schedule_one.go:940-944). Any tie-break is an acceptable Go outcome, so we
define a deterministic one — smallest node index among the max-score set —
which makes host and device bit-reproducible.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Optional

from ..api.types import Pod
from .interface import Code, CycleState, PreFilterResult, Status
from .types import Diagnosis, FitError, NodeInfo


@dataclass
class Registry:
    """name → factory(args) (reference: runtime/registry.go)."""

    factories: dict[str, Callable] = field(default_factory=dict)

    def register(self, name: str, factory: Callable) -> None:
        if name in self.factories:
            raise ValueError(f"plugin {name} already registered")
        self.factories[name] = factory

    def merge(self, other: "Registry") -> None:
        for name, f in other.factories.items():
            self.register(name, f)


@dataclass
class ScoredNode:
    name: str
    index: int
    score: int


class Framework:
    """One profile's compiled plugin set (reference frameworkImpl)."""

    def __init__(self, profile_name: str, plugins: list, weights: Optional[dict[str, int]] = None):
        self.profile_name = profile_name
        self.plugins = plugins
        self.weights = weights or {}
        # SchedulerMetrics handle (set by the Scheduler): feeds the sampled
        # plugin_execution_duration histogram when a cycle's CycleState has
        # record_plugin_metrics set (instrumented_plugins.go analog)
        self.metrics = None
        self.pre_enqueue_plugins = [p for p in plugins if hasattr(p, "pre_enqueue")]
        self.queue_sort_plugins = [p for p in plugins if hasattr(p, "less")]
        self.pre_filter_plugins = [p for p in plugins if hasattr(p, "pre_filter")]
        self.filter_plugins = [p for p in plugins if hasattr(p, "filter")]
        self.post_filter_plugins = [p for p in plugins if hasattr(p, "post_filter")]
        self.pre_score_plugins = [p for p in plugins if hasattr(p, "pre_score")]
        self.score_plugins = [p for p in plugins if hasattr(p, "score")]
        self.reserve_plugins = [p for p in plugins if hasattr(p, "reserve")]
        self.permit_plugins = [p for p in plugins if hasattr(p, "permit")]
        self.pre_bind_plugins = [p for p in plugins if hasattr(p, "pre_bind")]
        self.bind_plugins = [p for p in plugins if hasattr(p, "bind")]
        self.post_bind_plugins = [p for p in plugins if hasattr(p, "post_bind")]

    def plugin_weight(self, plugin) -> int:
        return self.weights.get(plugin.name(), 1)

    def queue_sort_less(self, a, b) -> bool:
        return self.queue_sort_plugins[0].less(a, b)

    # -- PreEnqueue ----------------------------------------------------------

    def run_pre_enqueue_plugins(self, pod: Pod) -> Status:
        for p in self.pre_enqueue_plugins:
            status = p.pre_enqueue(pod)
            if not status.is_success():
                status.plugin = status.plugin or p.name()
                return status
        return Status.success()

    # -- PreFilter -----------------------------------------------------------

    def run_pre_filter_plugins(self, state: CycleState, pod: Pod, nodes: list[NodeInfo]
                               ) -> tuple[Optional[PreFilterResult], Status]:
        result: Optional[PreFilterResult] = None
        for p in self.pre_filter_plugins:
            r, status = p.pre_filter(state, pod, nodes)
            if status.is_skip():
                state.skip_filter_plugins.add(p.name())
                continue
            if not status.is_success():
                status.plugin = status.plugin or p.name()
                return None, status
            if r is not None and not r.all_nodes():
                result = r if result is None else result.merge(r)
        return result, Status.success()

    # -- Filter --------------------------------------------------------------

    def run_filter_plugins(self, state: CycleState, pod: Pod, node_info: NodeInfo) -> Status:
        if state.record_plugin_metrics and self.metrics is not None:
            return self._run_filter_plugins_instrumented(state, pod,
                                                         node_info)
        for p in self.filter_plugins:
            if p.name() in state.skip_filter_plugins:
                continue
            status = p.filter(state, pod, node_info)
            if not status.is_success():
                status.plugin = status.plugin or p.name()
                return status
        return Status.success()

    def _run_filter_plugins_instrumented(self, state: CycleState, pod: Pod,
                                         node_info: NodeInfo) -> Status:
        """Sampled timing per plugin Filter call (metrics.go:322
        PluginExecutionDuration via the async recorder; here the histogram
        write is cheap enough to take inline on the sampled cycles)."""
        import time as _t
        hist = self.metrics.plugin_execution_duration
        for p in self.filter_plugins:
            if p.name() in state.skip_filter_plugins:
                continue
            t0 = _t.perf_counter()
            status = p.filter(state, pod, node_info)
            hist.observe(_t.perf_counter() - t0, p.name(), "Filter",
                         status.code.name)
            if not status.is_success():
                status.plugin = status.plugin or p.name()
                return status
        return Status.success()

    def find_nodes_that_pass_filters(self, state: CycleState, pod: Pod,
                                     nodes: list[NodeInfo],
                                     pre_result: Optional[PreFilterResult],
                                     diagnosis: Diagnosis,
                                     nominator=None) -> list[NodeInfo]:
        feasible = []
        allowed = pre_result.node_names if pre_result and not pre_result.all_nodes() else None
        for ni in nodes:
            if allowed is not None and ni.name not in allowed:
                continue
            if nominator is not None:
                status = self.run_filter_plugins_with_nominated_pods(
                    state, pod, ni, nominator)
            else:
                status = self.run_filter_plugins(state, pod, ni)
            if status.is_success():
                feasible.append(ni)
            else:
                diagnosis.node_to_status[ni.name] = status
                if status.plugin:
                    diagnosis.unschedulable_plugins.add(status.plugin)
        return feasible

    def run_filter_plugins_with_nominated_pods(self, state: CycleState,
                                               pod: Pod, node_info: NodeInfo,
                                               nominator=None) -> Status:
        """runtime/framework.go:1158-1231 — two-pass filter: first WITH all
        higher-or-equal-priority pods nominated onto this node (their
        resources assumed occupied via the AddPod extensions on a NodeInfo
        copy), then, only if nominated pods existed, again WITHOUT them.
        Both passes must succeed."""
        nominated = (nominator.pods_for_node(node_info.name)
                     if nominator is not None else [])
        relevant = [q for q in nominated
                    if q.pod.spec.priority >= pod.spec.priority
                    and q.pod.uid != pod.uid]
        if relevant:
            ni = node_info.snapshot_clone()
            state_w = state.clone()
            for q in relevant:
                pi = q.pod_info
                ni.add_pod(pi)
                self.run_pre_filter_extensions_add_pod(state_w, pod, pi, ni)
            status = self.run_filter_plugins(state_w, pod, ni)
            if not status.is_success():
                return status
        return self.run_filter_plugins(state, pod, node_info)

    # -- PreFilterExtensions (preemption dry-run support) ---------------------

    def run_pre_filter_extensions_add_pod(self, state: CycleState, pod: Pod,
                                          pi, node_info: NodeInfo) -> Status:
        for p in self.pre_filter_plugins:
            if p.name() in state.skip_filter_plugins:
                continue
            if hasattr(p, "add_pod"):
                status = p.add_pod(state, pod, pi, node_info)
                if not status.is_success():
                    return status
        return Status.success()

    def run_pre_filter_extensions_remove_pod(self, state: CycleState,
                                             pod: Pod, pi,
                                             node_info: NodeInfo) -> Status:
        for p in self.pre_filter_plugins:
            if p.name() in state.skip_filter_plugins:
                continue
            if hasattr(p, "remove_pod"):
                status = p.remove_pod(state, pod, pi, node_info)
                if not status.is_success():
                    return status
        return Status.success()

    # -- PostFilter (runtime/framework.go:1068) --------------------------------

    def run_post_filter_plugins(self, state: CycleState, pod: Pod,
                                filtered_node_status_map
                                ) -> tuple[Optional[str], Status]:
        """Returns (nominated node name | None, status). First plugin that
        succeeds (or errors) short-circuits; Unschedulable statuses merge."""
        statuses = []
        for p in self.post_filter_plugins:
            result, status = p.post_filter(state, pod,
                                           filtered_node_status_map)
            if status.is_success():
                return result, status
            if status.code == Code.ERROR:
                return None, status
            statuses.append(status)
        reasons = tuple(r for s in statuses for r in s.reasons)
        return None, Status.unschedulable(*reasons)

    # -- Score (three phases, reference runtime:1286-1390) -------------------

    def run_pre_score_plugins(self, state: CycleState, pod: Pod,
                              nodes: list[NodeInfo],
                              all_nodes: Optional[list[NodeInfo]] = None) -> Status:
        """`nodes` is the feasible set; `all_nodes` the full snapshot list —
        several plugins count over all nodes (e.g. interpodaffinity
        scoring.go:148 uses the shared lister, not the filtered list)."""
        for p in self.pre_score_plugins:
            status = p.pre_score(state, pod, nodes, all_nodes=all_nodes)
            if status.is_skip():
                state.skip_score_plugins.add(p.name())
                continue
            if not status.is_success():
                status.plugin = status.plugin or p.name()
                return status
        return Status.success()

    def run_score_plugins(self, state: CycleState, pod: Pod, nodes: list[NodeInfo]
                          ) -> tuple[list[int], Status]:
        """Returns the weighted total per node (parallel to `nodes`)."""
        totals = [0] * len(nodes)
        record = state.record_plugin_metrics and self.metrics is not None
        for p in self.score_plugins:
            if p.name() in state.skip_score_plugins:
                continue
            if record:
                import time as _t
                t0 = _t.perf_counter()
            scores = []
            for ni in nodes:
                s, status = p.score(state, pod, ni)
                if not status.is_success():
                    status.plugin = status.plugin or p.name()
                    return totals, status
                scores.append(s)
            if record:
                self.metrics.plugin_execution_duration.observe(
                    _t.perf_counter() - t0, p.name(), "Score",
                    status.code.name)
            status = p.normalize_scores(state, pod, scores,
                                        node_names=[ni.name for ni in nodes])
            if not status.is_success():
                return totals, status
            w = self.plugin_weight(p)
            for i, s in enumerate(scores):
                totals[i] += s * w
        return totals, Status.success()

    # -- Reserve / Permit / Bind --------------------------------------------

    def run_reserve_plugins_reserve(self, state: CycleState, pod: Pod, node_name: str) -> Status:
        for p in self.reserve_plugins:
            status = p.reserve(state, pod, node_name)
            if not status.is_success():
                status.plugin = status.plugin or p.name()
                return status
        return Status.success()

    def run_reserve_plugins_unreserve(self, state: CycleState, pod: Pod, node_name: str) -> None:
        for p in reversed(self.reserve_plugins):
            p.unreserve(state, pod, node_name)

    def run_permit_plugins(self, state: CycleState, pod: Pod,
                           node_name: str) -> tuple[Status, float]:
        """Returns (Success | Wait | rejection, max wait timeout) —
        runtime/framework.go RunPermitPlugins."""
        wait_status: Optional[Status] = None
        max_timeout = 0.0
        for p in self.permit_plugins:
            status, timeout = p.permit(state, pod, node_name)
            if status.code == Code.WAIT:
                wait_status = status
                max_timeout = max(max_timeout, timeout or 0.0)
                continue
            if not status.is_success():
                status.plugin = status.plugin or p.name()
                return status, 0.0
        return wait_status or Status.success(), max_timeout

    def run_pre_bind_plugins(self, state: CycleState, pod: Pod, node_name: str) -> Status:
        for p in self.pre_bind_plugins:
            status = p.pre_bind(state, pod, node_name)
            if not status.is_success():
                status.plugin = status.plugin or p.name()
                return status
        return Status.success()

    def run_bind_plugins(self, state: CycleState, pod: Pod, node_name: str) -> Status:
        for p in self.bind_plugins:
            status = p.bind(state, pod, node_name)
            if status.is_skip():
                continue
            status.plugin = status.plugin or p.name()
            return status
        return Status.success()

    def run_post_bind_plugins(self, state: CycleState, pod: Pod, node_name: str) -> None:
        for p in self.post_bind_plugins:
            p.post_bind(state, pod, node_name)


# ---------------------------------------------------------------------------
# schedulePod (reference schedule_one.go:426-483) — the oracle


@dataclass
class ScheduleResult:
    suggested_host: str
    evaluated_nodes: int
    feasible_nodes: int
    # every node tied at max score: device decisions must land in this set
    argmax_set: frozenset[str] = frozenset()
    scores: dict[str, int] = field(default_factory=dict)


def schedule_pod(fwk: Framework, state: CycleState, pod: Pod,
                 nodes: list[NodeInfo], nominator=None,
                 extenders=()) -> ScheduleResult:
    if not nodes:
        raise FitError(pod, 0)
    diagnosis = Diagnosis()
    pre_result, status = fwk.run_pre_filter_plugins(state, pod, nodes)
    if not status.is_success():
        if status.is_rejected():
            diagnosis.pre_filter_msg = "; ".join(status.reasons)
            if status.plugin:
                diagnosis.unschedulable_plugins.add(status.plugin)
            raise FitError(pod, len(nodes), diagnosis)
        raise RuntimeError(f"prefilter error: {status.reasons}")

    feasible = fwk.find_nodes_that_pass_filters(state, pod, nodes, pre_result,
                                                diagnosis, nominator=nominator)
    if extenders:
        from .extender import find_nodes_that_pass_extenders
        feasible = find_nodes_that_pass_extenders(extenders, pod, feasible,
                                                  diagnosis)
    if not feasible:
        raise FitError(pod, len(nodes), diagnosis)
    if len(feasible) == 1:
        return ScheduleResult(feasible[0].name, len(nodes), 1,
                              frozenset([feasible[0].name]),
                              {feasible[0].name: 0})

    status = fwk.run_pre_score_plugins(state, pod, feasible, all_nodes=nodes)
    if not status.is_success():
        raise RuntimeError(f"prescore error: {status.reasons}")
    totals, status = fwk.run_score_plugins(state, pod, feasible)
    if not status.is_success():
        raise RuntimeError(f"score error: {status.reasons}")
    if extenders:
        from .extender import extender_scores
        ext = extender_scores(extenders, pod, feasible)
        totals = [t + ext.get(ni.name, 0)
                  for t, ni in zip(totals, feasible)]

    best = max(totals)
    argmax = frozenset(ni.name for ni, s in zip(feasible, totals) if s == best)
    # deterministic tie-break: first feasible node at max score
    chosen = next(ni.name for ni, s in zip(feasible, totals) if s == best)
    return ScheduleResult(chosen, len(nodes), len(feasible), argmax,
                          {ni.name: s for ni, s in zip(feasible, totals)})
