"""Plugin ABI: Status codes, extension points, CycleState.

Mirrors the public plugin surface of the reference
(staging/src/k8s.io/kube-scheduler/framework/interface.go:46-824) with the
same extension-point taxonomy. TPU-tensorized plugins additionally implement
the `TensorPlugin` protocols in plugins/tensor.py — a Filter plugin can emit
a vmappable mask, a Score plugin a node-score vector; plugins lacking a
tensor form fall back to the host path (the analog of the reference gating
batching on SignPlugin support, runtime/framework.go:772-816).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Any, Optional, Protocol, runtime_checkable


class Code(enum.IntEnum):
    """Reference: interface.go:46-100."""

    SUCCESS = 0
    ERROR = 1
    UNSCHEDULABLE = 2
    UNSCHEDULABLE_AND_UNRESOLVABLE = 3
    WAIT = 4
    SKIP = 5
    PENDING = 6


@dataclass
class Status:
    code: Code = Code.SUCCESS
    reasons: tuple[str, ...] = ()
    plugin: str = ""

    @staticmethod
    def success() -> "Status":
        return Status()

    @staticmethod
    def unschedulable(*reasons: str, plugin: str = "") -> "Status":
        return Status(Code.UNSCHEDULABLE, reasons, plugin)

    @staticmethod
    def unresolvable(*reasons: str, plugin: str = "") -> "Status":
        return Status(Code.UNSCHEDULABLE_AND_UNRESOLVABLE, reasons, plugin)

    @staticmethod
    def error(*reasons: str, plugin: str = "") -> "Status":
        return Status(Code.ERROR, reasons, plugin)

    @staticmethod
    def skip() -> "Status":
        return Status(Code.SKIP)

    def is_success(self) -> bool:
        return self.code == Code.SUCCESS

    def is_skip(self) -> bool:
        return self.code == Code.SKIP

    def is_rejected(self) -> bool:
        return self.code in (Code.UNSCHEDULABLE, Code.UNSCHEDULABLE_AND_UNRESOLVABLE, Code.PENDING)


MAX_NODE_SCORE = 100  # reference: interface.go MaxNodeScore
MIN_NODE_SCORE = 0


class CycleState:
    """Per-scheduling-cycle typed KV store (reference: cycle_state.go).

    On the TPU path one CycleState serves a whole batch; plugin pre-computed
    state is keyed exactly like the reference ("PreFilter<Plugin>" keys).
    """

    def __init__(self) -> None:
        self._data: dict[str, Any] = {}
        self.skip_filter_plugins: set[str] = set()
        self.skip_score_plugins: set[str] = set()
        # plugin_execution_duration sampling flag: set on ~10% of cycles
        # (reference pluginMetricsSamplePercent, schedule_one.go:51)
        self.record_plugin_metrics: bool = False

    def write(self, key: str, value: Any) -> None:
        self._data[key] = value

    def read(self, key: str) -> Any:
        if key not in self._data:
            raise KeyError(key)
        return self._data[key]

    def read_or_none(self, key: str) -> Any:
        return self._data.get(key)

    def clone(self) -> "CycleState":
        """cycle_state.go Clone: plugin state objects that implement
        clone() are deep-copied (StateData.Clone in the reference) so
        AddPod/RemovePod simulations on the clone never leak into the
        original; immutable values are shared."""
        cs = CycleState()
        cs._data = {k: (v.clone() if hasattr(v, "clone") else v)
                    for k, v in self._data.items()}
        cs.skip_filter_plugins = set(self.skip_filter_plugins)
        cs.skip_score_plugins = set(self.skip_score_plugins)
        return cs


@dataclass
class PreFilterResult:
    """Reference: interface.go PreFilterResult — node-name set shortcut."""

    node_names: Optional[set[str]] = None  # None = all nodes

    def merge(self, other: "PreFilterResult") -> "PreFilterResult":
        if self.node_names is None:
            return other
        if other.node_names is None:
            return self
        return PreFilterResult(self.node_names & other.node_names)

    def all_nodes(self) -> bool:
        return self.node_names is None


# ---------------------------------------------------------------------------
# plugin protocols (host path). NodeInfo / PodInfo types come from
# framework.types; `Any` here avoids a circular import.


@runtime_checkable
class Plugin(Protocol):
    def name(self) -> str: ...


class PreEnqueuePlugin(Protocol):
    def pre_enqueue(self, pod) -> Status: ...


class QueueSortPlugin(Protocol):
    def less(self, a, b) -> bool: ...


class PreFilterPlugin(Protocol):
    def pre_filter(self, state: CycleState, pod, nodes) -> tuple[Optional[PreFilterResult], Status]: ...


class FilterPlugin(Protocol):
    def filter(self, state: CycleState, pod, node_info) -> Status: ...


class PostFilterPlugin(Protocol):
    def post_filter(self, state: CycleState, pod, filtered_node_status_map) -> tuple[Optional[str], Status]: ...


class PreScorePlugin(Protocol):
    def pre_score(self, state: CycleState, pod, nodes) -> Status: ...


class ScorePlugin(Protocol):
    def score(self, state: CycleState, pod, node_info) -> tuple[int, Status]: ...

    def normalize_scores(self, state: CycleState, pod, scores: list[int],
                         node_names: Optional[list[str]] = None) -> Status: ...


class ReservePlugin(Protocol):
    def reserve(self, state: CycleState, pod, node_name: str) -> Status: ...

    def unreserve(self, state: CycleState, pod, node_name: str) -> None: ...


class PermitPlugin(Protocol):
    def permit(self, state: CycleState, pod, node_name: str) -> tuple[Status, float]: ...


class PreBindPlugin(Protocol):
    def pre_bind(self, state: CycleState, pod, node_name: str) -> Status: ...


class BindPlugin(Protocol):
    def bind(self, state: CycleState, pod, node_name: str) -> Status: ...


class PostBindPlugin(Protocol):
    def post_bind(self, state: CycleState, pod, node_name: str) -> None: ...


class EnqueueExtensions(Protocol):
    """Reference: interface.go:412 EventsToRegister → queueing hints."""

    def events_to_register(self) -> list: ...


