"""Preemption Evaluator: the PostFilter path.

Mirrors pkg/scheduler/framework/preemption/preemption.go:
- `Evaluator.preempt` (:268) — eligibility → candidates → pick → prepare.
- `pod_eligible_to_preempt_others` (:431) — preemptionPolicy Never, and the
  nominated-node "victim already terminating" check. Our in-memory API
  server deletes synchronously (no graceful termination window), so the
  terminating-victim branch can only observe pending DELETE calls still
  sitting in the dispatcher queue.
- `dry_run_preemption` (:775) / `select_victims_on_node`
  (plugins/defaultpreemption/default_preemption.go:583) — remove all
  lower-priority pods, check fit with nominated pods, then reprieve victims
  most-important-first.
- `pick_one_node` (:658) — the 5-step ordering; step 1 discriminates by
  `num_pdb_violations` fed by the PDB-violating victim partition
  (`filterPodsWithPDBViolation`, preemption.go:~700). Victim start times
  map to `creation_index` (latest-started = highest index).
- `prepare_candidate` (:180) — victim deletes via the API dispatcher +
  clearing lower-priority nominations on the node; the caller publishes
  NominatedNodeName.

Candidate count follows default_preemption.go:174 GetOffsetAndNumCandidates
with a deterministic offset of 0 (the reference randomizes only for
inter-scheduler fairness; determinism keeps decisions reproducible and is a
legal instance of the randomized choice).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Optional

from ..api.types import Pod
from ..utils.logging import klog
from .interface import Code, CycleState, Status
from .types import Diagnosis, NodeInfo, PodInfo


@dataclass
class Candidate:
    """preemption.go:60 candidate: victims + the node."""

    node_name: str
    victims: list[PodInfo] = field(default_factory=list)
    num_pdb_violations: int = 0


@dataclass
class DeviceDryRunContext:
    """Live handles for the batched device dry-run, wired by the Scheduler
    (the analog of frameworkImpl threading snapshot/cache handles into the
    Evaluator). `state` is the tensorized ClusterState, `builder` the pod
    signature BatchBuilder, `snapshot` the host Snapshot the candidates
    come from."""

    state: object
    builder: object
    snapshot: object
    # jax.sharding.Mesh when the owning scheduler runs node-sharded
    # (ISSUE 16): the dry-run then gathers the candidate rows host-side
    # into a compact single-device NodeArrays block instead of minting a
    # second full-matrix device copy next to the sharded one
    mesh: object = None


@dataclass
class _DryRunPlan:
    """Per-(preemptor signature, cluster state) tensors for the batched dry
    run. A preemptor WAVE (the common shape: many identical-priority pods
    failing against the same snapshot) reuses one plan — only the
    nominated-pod overlay changes between preemptors, so the wave costs one
    tensor build plus one kernel execution per preemptor."""

    key: tuple
    # per candidate, in `nodes` iteration order:
    #   (node_info, victims-in-reprieve-order, violating-prefix-length)
    cands: list
    cand_idx: object          # i32 [Cp] node-row indices
    cand_pos: dict            # node name → candidate position
    victim_req: object        # i64 [Cp, Vp, R]
    victim_valid: object      # bool [Cp, Vp]
    spread: object            # groups.DryRunSpread | None
    constraints: list         # spread DoNotSchedule constraints (host objs)
    # overlay-FREE kernel results for every candidate (np bool [Cp, Vp+1]),
    # computed once per wave: a preemptor only re-evaluates the rows its
    # nomination overlay actually touches (a tiny gathered kernel), so the
    # full-candidate kernel runs once per wave, not once per preemptor
    base_packed: object = None
    # mesh mode only: the candidate rows gathered host-side into a
    # single-device NodeArrays[Cp] block; cand_idx is then positions into
    # THIS block (arange), not global node rows
    cand_na: object = None


class Evaluator:
    """preemption.go:100 — drives one preemption attempt for one pod."""

    # victim-axis cap for the batched path: a node with more potential
    # victims than this (≫ any realistic pods-per-node delta) falls back
    # to the host loop rather than minting huge tensors
    MAX_BATCHED_VICTIMS = 128

    def __init__(self, framework, nominator=None,
                 min_candidate_nodes_percentage: int = 10,
                 min_candidate_nodes_absolute: int = 100,
                 is_delete_pending: Optional[Callable[[str], bool]] = None,
                 pdb_lister: Optional[Callable[[], list]] = None,
                 extenders: tuple = (),
                 device_ctx: Optional[DeviceDryRunContext] = None):
        self.fwk = framework
        self.nominator = nominator
        self.min_pct = min_candidate_nodes_percentage
        self.min_abs = min_candidate_nodes_absolute
        self._is_delete_pending = is_delete_pending or (lambda uid: False)
        # () → [PodDisruptionBudget] with fresh disruptionsAllowed; the
        # reference uses a PDB informer lister (preemption.go:700)
        self.pdb_lister = pdb_lister
        # extenders with the preempt verb adjust/veto candidates
        # (preemption.go:316 callExtenders)
        self.extenders = tuple(extenders)
        # batched device dry-run wiring (None = host loop only)
        self.device_ctx = device_ctx
        self._plan_cache: Optional[_DryRunPlan] = None
        self.batched_dry_runs = 0
        self.host_dry_runs = 0

    # -- entry (preemption.go:268 Preempt) ------------------------------------

    def preempt(self, state: CycleState, pod: Pod,
                nodes: list[NodeInfo], diagnosis: Diagnosis
                ) -> tuple[Optional[Candidate], Status]:
        if not self.pod_eligible_to_preempt_others(pod, nodes):
            return None, Status.unschedulable(
                "pod is not eligible for preemption",
                plugin="DefaultPreemption")
        potential = self.nodes_where_preemption_might_help(nodes, diagnosis)
        if not potential:
            return None, Status.unschedulable(
                "preemption will not help scheduling",
                plugin="DefaultPreemption")
        num = self.get_num_candidates(len(potential))
        candidates = self.dry_run_preemption(state, pod, potential, num,
                                             all_nodes=nodes)
        if not candidates:
            return None, Status.unschedulable(
                "no preemption victims found for incoming pod",
                plugin="DefaultPreemption")
        candidates = self.call_extenders(pod, candidates)
        if not candidates:
            return None, Status.unschedulable(
                "no preemption candidates survived the extenders",
                plugin="DefaultPreemption")
        best = self.pick_one_node(candidates)
        return best, Status.success()

    def call_extenders(self, pod: Pod,
                       candidates: list[Candidate]) -> list[Candidate]:
        """preemption.go:316 callExtenders: each preemption-capable
        extender sees {node: victims} and returns the accepted subset;
        ignorable extender failures are skipped."""
        exts = [e for e in self.extenders if e.supports_preemption()]
        if not exts:
            return candidates
        by_node = {c.node_name: c for c in candidates}
        victims = {c.node_name: list(c.victims) for c in candidates}
        for ext in exts:
            try:
                victims = ext.process_preemption(pod, victims)
            except Exception:
                if ext.is_ignorable():
                    continue
                raise
            if not victims:
                return []
        out = []
        for node, vs in victims.items():
            c = by_node.get(node)
            if c is None:
                continue
            c.victims = list(vs)
            out.append(c)
        return out

    # -- eligibility (preemption.go:431) ---------------------------------------

    def pod_eligible_to_preempt_others(self, pod: Pod,
                                       nodes: list[NodeInfo]) -> bool:
        if pod.spec.preemption_policy == "Never":
            return False
        nominated = pod.status.nominated_node_name
        if nominated:
            # a lower-priority victim already terminating on the nominated
            # node means preemption is in flight — don't preempt again
            ni = next((n for n in nodes if n.name == nominated), None)
            if ni is not None:
                for pi in ni.pods:
                    if (pi.pod.spec.priority < pod.spec.priority
                            and self._is_delete_pending(pi.pod.uid)):
                        return False
        return True

    # -- candidate universe (preemption.go:291) --------------------------------

    @staticmethod
    def nodes_where_preemption_might_help(nodes: list[NodeInfo],
                                          diagnosis: Diagnosis
                                          ) -> list[NodeInfo]:
        """Nodes that failed resolvably. A node absent from node_to_status
        (the device path reports only global infeasibility) is assumed
        resolvable."""
        out = []
        for ni in nodes:
            st = diagnosis.node_to_status.get(ni.name)
            if st is not None and st.code == Code.UNSCHEDULABLE_AND_UNRESOLVABLE:
                continue
            out.append(ni)
        return out

    def get_num_candidates(self, num_nodes: int) -> int:
        """default_preemption.go:174 GetOffsetAndNumCandidates."""
        n = num_nodes * self.min_pct // 100
        n = max(n, self.min_abs)
        return min(n, num_nodes)

    # -- dry run (preemption.go:775) -------------------------------------------

    def dry_run_preemption(self, state: CycleState, pod: Pod,
                           nodes: list[NodeInfo], num_candidates: int,
                           all_nodes: Optional[list[NodeInfo]] = None
                           ) -> list[Candidate]:
        """`nodes` are the preemption candidates; `all_nodes` the FULL
        snapshot list — PreFilter state (spread counts etc.) must be seeded
        over every node exactly like a real scheduling cycle, not over the
        resolvable subset.

        Two tiers (SURVEY §7 step 8): the batched device dry-run evaluates
        every candidate node in one gathered kernel (ops/program.py
        dry_run_select_victims) and is exact for the eligible subset; the
        host loop remains the oracle for everything else, with PreFilter
        seeded ONCE and cloned per candidate (the reference clones
        CycleState the same way, preemption.go:775)."""
        pdbs = self.pdb_lister() if self.pdb_lister is not None else []
        all_nodes = all_nodes or nodes
        try:
            batched = self._dry_run_batched(pod, nodes, num_candidates,
                                            all_nodes, pdbs)
        except Exception as e:
            # a device/XLA fault must not sink preemption: the host loop
            # below is the oracle the kernel replicates (the scheduler's
            # circuit breaker handles the scheduling path separately)
            klog.error("batched dry-run fault; using host loop",
                       pod=pod.uid, err=str(e))
            batched = None
        if batched is not None:
            self.batched_dry_runs += 1
            return batched
        self.host_dry_runs += 1
        seeded = CycleState()
        _, status = self.fwk.run_pre_filter_plugins(seeded, pod, all_nodes)
        if not status.is_success():
            return []
        candidates: list[Candidate] = []
        for ni in nodes:
            victims, pdb_violations, ok = self.select_victims_on_node(
                pod, ni, all_nodes=all_nodes, pdbs=pdbs,
                seeded_state=seeded)
            if ok:
                candidates.append(Candidate(
                    node_name=ni.name, victims=victims,
                    num_pdb_violations=pdb_violations))
                if len(candidates) >= num_candidates:
                    break
        return candidates

    # -- batched device dry run ------------------------------------------------

    def _dry_run_batched(self, pod: Pod, nodes: list[NodeInfo],
                         num_candidates: int, all_nodes: list[NodeInfo],
                         pdbs: list) -> Optional[list[Candidate]]:
        """One kernel execution instead of |candidates| host filter sweeps.
        Returns the candidate list, or None when the case has no tensor
        form (caller falls back to the host loop). Exactness boundary:

        - preemptor: no host ports (sig 0), no pod (anti-)affinity, no
          volumes/claims/declared-features (the builder row gate);
          DoNotSchedule spread constraints ARE handled via victim count
          tensors (ops/groups.py spread_dry_run_tensors);
        - cluster: no existing pods with required anti-affinity (their
          removal could lift a veto the kernel does not model);
        - nominations: ≥-priority nominated pods become a fit-only
          resource overlay; a nominated pod that would move the
          preemptor's spread counts or add anti-affinity vetoes falls
          back."""
        ctx = self.device_ctx
        if ctx is None:
            return None
        spec = pod.spec
        aff = spec.affinity
        if aff is not None and (aff.pod_affinity is not None
                                or aff.pod_anti_affinity is not None):
            return None
        snapshot = ctx.snapshot
        if snapshot is None or snapshot.have_pods_with_required_anti_affinity_list:
            return None
        ent = ctx.builder._lookup(pod)
        if ent[0] != "row" or ent[1] == 0:
            return None
        u = ent[2]
        # staging rows must mirror the snapshot the candidates came from
        ctx.state.apply_snapshot(snapshot)
        arrays = ctx.state.ensure_arrays()
        R = arrays.used.shape[1]
        plan = self._dry_run_plan(pod, nodes, all_nodes, pdbs, u, R, ctx)
        if plan is None:
            return None
        if not plan.cands:
            return []
        ovl = self._dry_run_overlay(pod, plan, R, ctx)
        if ovl is None:
            return None
        overrides = self._dry_run_overrides(pod, plan, ovl, R, u, ctx)
        base = plan.base_packed
        out: list[Candidate] = []
        for c, (ni, ordered, nviol) in enumerate(plan.cands):
            row = overrides.get(c)
            if row is None:
                row = base[c]
            if not row[0]:
                continue
            victims = [pi for v, pi in enumerate(ordered)
                       if not row[1 + v]]
            violations = sum(1 for v in range(nviol) if not row[1 + v])
            out.append(Candidate(node_name=ni.name, victims=victims,
                                 num_pdb_violations=violations))
            if len(out) >= num_candidates:
                break
        return out

    def _dry_run_overrides(self, pod: Pod, plan: _DryRunPlan, ovl: dict,
                           R: int, u: int, ctx) -> dict:
        """Re-evaluate ONLY the overlay-touched candidate rows: gather
        their slices out of the device-resident plan tensors and run the
        kernel over the (tiny) subset. Returns {cand_pos: packed row}."""
        if not ovl:
            return {}
        import jax.numpy as jnp
        import numpy as np
        from ..ops.program import dry_run_select_victims, pod_row_from_table
        from ..state.tensorize import pow2_at_least

        sub = np.fromiter(ovl.keys(), np.int64, count=len(ovl))
        s = len(sub)
        s_pad = pow2_at_least(s)
        sub_pad = np.zeros((s_pad,), np.int64)   # pad repeats row 0;
        sub_pad[:s] = sub                        # padded outputs ignored
        sub_j = jnp.asarray(sub_pad)
        ovl_used = np.zeros((s_pad, R), np.int64)
        ovl_npods = np.zeros((s_pad,), np.int32)
        for i, c in enumerate(sub):
            vec, cnt = ovl[int(c)]
            ovl_used[i] = vec
            ovl_npods[i] = cnt
        spread = plan.spread
        if spread is not None:
            spread = spread._replace(
                tv_ok=spread.tv_ok[sub_j], cnt0=spread.cnt0[sub_j],
                other_min=spread.other_min[sub_j],
                vic_match=spread.vic_match[sub_j])
        prow = pod_row_from_table(ctx.builder.table, u)
        packed = np.asarray(dry_run_select_victims(
            plan.cand_na if plan.cand_na is not None
            else ctx.state.device_arrays(),
            prow, plan.cand_idx[sub_j],
            plan.victim_req[sub_j], plan.victim_valid[sub_j],
            ovl_used, ovl_npods, spread))
        return {int(c): packed[i] for i, c in enumerate(sub)}

    def _dry_run_plan(self, pod: Pod, nodes: list[NodeInfo],
                      all_nodes: list[NodeInfo], pdbs: list, u: int,
                      R: int, ctx) -> Optional[_DryRunPlan]:
        """Build (or reuse) the wave plan: candidate rows, victim request
        tensors in reprieve order, PDB partition, spread delta tensors."""
        import numpy as np
        from ..state.tensorize import pow2_at_least

        prio = pod.spec.priority
        # cheap wave key: snapshot generations cover node content, NodeInfo
        # identities cover the resolvable-subset membership — no per-node
        # tuple building on the per-preemptor path
        key = (u, prio, R,
               tuple((p.uid, p.disruptions_allowed) for p in pdbs),
               id(self.device_ctx.snapshot),
               self.device_ctx.snapshot.generation,
               self.device_ctx.snapshot.tree_generation,
               hash(tuple(map(id, nodes))))
        cached = self._plan_cache
        if cached is not None and cached.key == key:
            return cached
        # one PreFilter over ALL nodes — exactly the host seeding, run once
        # per wave instead of once per candidate node
        cs = CycleState()
        _, status = self.fwk.run_pre_filter_plugins(cs, pod, all_nodes)
        if not status.is_success():
            plan = _DryRunPlan(key=key, cands=[], cand_idx=None,
                               cand_pos={}, victim_req=None,
                               victim_valid=None, spread=None,
                               constraints=[])
            self._plan_cache = plan
            return plan
        from ..plugins import podtopologyspread as pts_mod
        spread_state = cs.read_or_none(pts_mod._PRE_FILTER_KEY)
        constraints = list(spread_state.constraints) if spread_state else []

        key_fn = lambda pi: (-pi.pod.spec.priority,
                             pi.pod.metadata.creation_index)
        cands = []
        idxs = []
        vmax = 0
        for ni in nodes:
            potential = [pi for pi in ni.pods
                         if pi.pod.spec.priority < prio]
            if not potential:
                continue
            idx = ctx.state.node_index.get(ni.name)
            if idx is None:
                return None   # staging out of sync: host path
            violating, non_violating = self._filter_pods_with_pdb_violation(
                potential, pdbs)
            ordered = (sorted(violating, key=key_fn)
                       + sorted(non_violating, key=key_fn))
            cands.append((ni, ordered, len(violating)))
            idxs.append(idx)
            vmax = max(vmax, len(ordered))
        if not cands:
            plan = _DryRunPlan(key=key, cands=[], cand_idx=None,
                               cand_pos={}, victim_req=None,
                               victim_valid=None, spread=None,
                               constraints=constraints)
            self._plan_cache = plan
            return plan
        if vmax > self.MAX_BATCHED_VICTIMS:
            return None
        c_pad = pow2_at_least(len(cands))
        v_pad = pow2_at_least(vmax)
        cand_idx = np.zeros((c_pad,), np.int32)
        cand_idx[:len(idxs)] = idxs
        victim_req = np.zeros((c_pad, v_pad, R), np.int64)
        victim_valid = np.zeros((c_pad, v_pad), bool)
        for c, (_ni, ordered, _nv) in enumerate(cands):
            for v, pi in enumerate(ordered):
                vec = ctx.state.request_vector(pi.requests)
                if vec is None:
                    return None   # resource outside the staging table
                victim_req[c, v] = vec
                victim_valid[c, v] = True
        spread = None
        if constraints:
            from ..ops.groups import spread_dry_run_tensors
            spread = spread_dry_run_tensors(
                spread_state, pod, [c[0] for c in cands],
                [c[1] for c in cands], c_pad, v_pad)
        # ship the wave-constant tensors to the device ONCE and run the
        # full-candidate kernel overlay-free: every preemptor in the wave
        # then pays only a tiny overlay-subset kernel
        import jax.numpy as jnp
        from ..ops.program import dry_run_select_victims, pod_row_from_table
        cand_na = None
        kernel_idx = jnp.asarray(cand_idx)
        if self.device_ctx.mesh is not None:
            # mesh mode (ISSUE 16): gather the candidate rows out of the
            # host staging arrays into a compact single-device block —
            # the kernel is row-local over `cand`, so positions into the
            # gathered block are exact, and the mesh-sharded resident
            # copy is never touched (nor its dirty-row tracking cleared)
            a = ctx.state.ensure_arrays()
            cand_na = type(a)(*(jnp.asarray(x[cand_idx]) for x in a))
            kernel_idx = jnp.arange(c_pad, dtype=jnp.int32)
        plan = _DryRunPlan(
            key=key, cands=cands, cand_idx=kernel_idx,
            cand_pos={ni.name: c for c, (ni, _o, _n) in enumerate(cands)},
            victim_req=jnp.asarray(victim_req),
            victim_valid=jnp.asarray(victim_valid),
            spread=(None if spread is None
                    else type(spread)(*(jnp.asarray(x) for x in spread))),
            constraints=constraints)
        plan.cand_na = cand_na
        prow = pod_row_from_table(ctx.builder.table, u)
        plan.base_packed = np.asarray(dry_run_select_victims(
            cand_na if cand_na is not None else ctx.state.device_arrays(),
            prow, plan.cand_idx,
            plan.victim_req, plan.victim_valid,
            np.zeros((c_pad, R), np.int64), np.zeros((c_pad,), np.int32),
            plan.spread))
        self._plan_cache = plan
        return plan

    def _dry_run_overlay(self, pod: Pod, plan: _DryRunPlan, R: int, ctx):
        """Nominated-pod overlay for the with-nominated filter pass
        (runtime/framework.go:1158): ≥-priority nominations (self excluded)
        fold their resources into the candidate rows. Returns a SPARSE
        {cand_pos: [summed request vec, count]} map — nominations touch few
        nodes, and only those rows deviate from the wave's base kernel
        results — or None when a nomination has effects the overlay cannot
        represent."""
        out: dict = {}
        nom = self.nominator
        if nom is None or not nom.nominated_pods:
            return out
        for node_name, qlist in nom.nominated_per_node.items():
            for q in qlist:
                qpod = q.pod
                if qpod.uid == pod.uid or qpod.spec.priority < pod.spec.priority:
                    continue
                qaff = qpod.spec.affinity
                if (qaff is not None and qaff.pod_anti_affinity is not None
                        and qaff.pod_anti_affinity.required):
                    return None   # would add existing-anti vetoes
                if (plan.spread is not None
                        and qpod.namespace == pod.namespace
                        and any(c.selector.matches(qpod.metadata.labels)
                                for c in plan.constraints)):
                    return None   # would move the preemptor's spread counts
                c = plan.cand_pos.get(node_name)
                if c is None:
                    continue
                vec = ctx.state.request_vector(q.pod_info.requests)
                if vec is None:
                    return None
                cur = out.get(c)
                if cur is None:
                    out[c] = [vec, 1]   # request_vector returns a fresh row
                else:
                    cur[0] += vec
                    cur[1] += 1
        return out

    def select_victims_on_node(self, pod: Pod, node_info: NodeInfo,
                               all_nodes: list[NodeInfo],
                               pdbs: Optional[list] = None,
                               seeded_state: Optional[CycleState] = None
                               ) -> tuple[list[PodInfo], int, bool]:
        """default_preemption.go:583. Returns (victims, pdbViolations, fits).

        Simulation runs on a structural copy of the NodeInfo and a CLONE of
        the seeded CycleState (the reference clones CycleState the same
        way; plugin states that AddPod/RemovePod mutate — spread, inter-pod
        affinity, volumes, DRA — all implement clone()). Callers that don't
        pass `seeded_state` pay a fresh PreFilter per call. The cheap
        potential-victims check runs FIRST so nodes with nothing to preempt
        — the common case when a full cluster rejects a default-priority
        pod — cost no PreFilter work."""
        potential = [pi for pi in node_info.pods
                     if pi.pod.spec.priority < pod.spec.priority]
        if not potential:
            return [], 0, False
        # the clone shares the immutable PodInfo objects: `potential` stays
        # valid against it
        ni = node_info.snapshot_clone()
        if seeded_state is not None:
            state = seeded_state.clone()
        else:
            state = CycleState()
            _, status = self.fwk.run_pre_filter_plugins(state, pod, all_nodes)
            if not status.is_success():
                return [], 0, False
        for pi in potential:
            self._remove_pod(state, pod, pi, ni)
        # preemptor must fit with ALL lower-priority pods gone
        if not self._fits(state, pod, ni):
            return [], 0, False
        # partition by PDB impact, then reprieve pods most-important-first
        # (util.MoreImportantPod: priority desc, then earlier start via
        # creation_index) while the preemptor still fits. PDB-VIOLATING
        # pods are reprieved FIRST (default_preemption.go:640): they get
        # the best chance of being added back, so PDB-protected workloads
        # are disrupted only when nothing else frees enough room.
        violating, non_violating = self._filter_pods_with_pdb_violation(
            potential, pdbs or [])
        key = lambda pi: (-pi.pod.spec.priority,
                          pi.pod.metadata.creation_index)
        victims: list[PodInfo] = []
        num_violating = 0
        for group, counts in ((sorted(violating, key=key), True),
                              (sorted(non_violating, key=key), False)):
            for pi in group:
                self._add_pod(state, pod, pi, ni)
                if not self._fits(state, pod, ni):
                    self._remove_pod(state, pod, pi, ni)
                    victims.append(pi)
                    if counts:
                        num_violating += 1
        return victims, num_violating, True

    @staticmethod
    def _filter_pods_with_pdb_violation(pods: list[PodInfo], pdbs: list
                                        ) -> tuple[list[PodInfo], list[PodInfo]]:
        """preemption.go filterPodsWithPDBViolation: a pod is 'violating'
        if evicting it would push some matching PDB past its
        disruptionsAllowed budget. Exactly like the reference, EVERY
        matching PDB's budget is decremented for EVERY pod — including
        pods already classified violating — so with multi-PDB pods a
        violating pod still consumes the budgets of its other PDBs."""
        if not pdbs:
            return [], list(pods)
        remaining = {id(pdb): pdb.disruptions_allowed for pdb in pdbs}
        violating: list[PodInfo] = []
        non_violating: list[PodInfo] = []
        for pi in pods:
            violates = False
            for pdb in pdbs:
                if not pdb.matches(pi.pod):
                    continue
                remaining[id(pdb)] -= 1
                if remaining[id(pdb)] < 0:
                    violates = True
            (violating if violates else non_violating).append(pi)
        return violating, non_violating

    def _fits(self, state: CycleState, pod: Pod, ni: NodeInfo) -> bool:
        status = self.fwk.run_filter_plugins_with_nominated_pods(
            state, pod, ni, self.nominator)
        return status.is_success()

    def _remove_pod(self, state: CycleState, pod: Pod, pi: PodInfo,
                    ni: NodeInfo) -> None:
        ni.remove_pod(pi)
        self.fwk.run_pre_filter_extensions_remove_pod(state, pod, pi, ni)

    def _add_pod(self, state: CycleState, pod: Pod, pi: PodInfo,
                 ni: NodeInfo) -> None:
        ni.add_pod(pi)
        self.fwk.run_pre_filter_extensions_add_pod(state, pod, pi, ni)

    # -- pick (preemption.go:658 pickOneNodeForPreemption) ---------------------

    @staticmethod
    def pick_one_node(candidates: list[Candidate]) -> Candidate:
        best = candidates
        # 1. fewest PDB violations
        m = min(c.num_pdb_violations for c in best)
        best = [c for c in best if c.num_pdb_violations == m]
        if len(best) == 1:
            return best[0]
        # a node with no victims at all wins outright (preemption.go:672)
        for c in best:
            if not c.victims:
                return c
        # 2. lowest highest-victim priority
        m = min(max(pi.pod.spec.priority for pi in c.victims) for c in best)
        best = [c for c in best
                if max(pi.pod.spec.priority for pi in c.victims) == m]
        if len(best) == 1:
            return best[0]
        # 3. smallest sum of victim priorities
        m = min(sum(pi.pod.spec.priority for pi in c.victims) for c in best)
        best = [c for c in best
                if sum(pi.pod.spec.priority for pi in c.victims) == m]
        if len(best) == 1:
            return best[0]
        # 4. fewest victims
        m = min(len(c.victims) for c in best)
        best = [c for c in best if len(c.victims) == m]
        if len(best) == 1:
            return best[0]
        # 5. latest start time of the highest-priority victim → prefer the
        # node whose top victim started most recently (creation_index max)
        def top_victim_start(c: Candidate) -> int:
            top = max(c.victims, key=lambda pi: (pi.pod.spec.priority,
                                                 -pi.pod.metadata.creation_index))
            return top.pod.metadata.creation_index
        m = max(top_victim_start(c) for c in best)
        best = [c for c in best if top_victim_start(c) == m]
        return best[0]
