"""Preemption Evaluator: the PostFilter path.

Mirrors pkg/scheduler/framework/preemption/preemption.go:
- `Evaluator.preempt` (:268) — eligibility → candidates → pick → prepare.
- `pod_eligible_to_preempt_others` (:431) — preemptionPolicy Never, and the
  nominated-node "victim already terminating" check. Our in-memory API
  server deletes synchronously (no graceful termination window), so the
  terminating-victim branch can only observe pending DELETE calls still
  sitting in the dispatcher queue.
- `dry_run_preemption` (:775) / `select_victims_on_node`
  (plugins/defaultpreemption/default_preemption.go:583) — remove all
  lower-priority pods, check fit with nominated pods, then reprieve victims
  most-important-first.
- `pick_one_node` (:658) — the 5-step ordering; step 1 discriminates by
  `num_pdb_violations` fed by the PDB-violating victim partition
  (`filterPodsWithPDBViolation`, preemption.go:~700). Victim start times
  map to `creation_index` (latest-started = highest index).
- `prepare_candidate` (:180) — victim deletes via the API dispatcher +
  clearing lower-priority nominations on the node; the caller publishes
  NominatedNodeName.

Candidate count follows default_preemption.go:174 GetOffsetAndNumCandidates
with a deterministic offset of 0 (the reference randomizes only for
inter-scheduler fairness; determinism keeps decisions reproducible and is a
legal instance of the randomized choice).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Optional

from ..api.types import Pod
from .interface import Code, CycleState, Status
from .types import Diagnosis, NodeInfo, PodInfo


@dataclass
class Candidate:
    """preemption.go:60 candidate: victims + the node."""

    node_name: str
    victims: list[PodInfo] = field(default_factory=list)
    num_pdb_violations: int = 0


class Evaluator:
    """preemption.go:100 — drives one preemption attempt for one pod."""

    def __init__(self, framework, nominator=None,
                 min_candidate_nodes_percentage: int = 10,
                 min_candidate_nodes_absolute: int = 100,
                 is_delete_pending: Optional[Callable[[str], bool]] = None,
                 pdb_lister: Optional[Callable[[], list]] = None,
                 extenders: tuple = ()):
        self.fwk = framework
        self.nominator = nominator
        self.min_pct = min_candidate_nodes_percentage
        self.min_abs = min_candidate_nodes_absolute
        self._is_delete_pending = is_delete_pending or (lambda uid: False)
        # () → [PodDisruptionBudget] with fresh disruptionsAllowed; the
        # reference uses a PDB informer lister (preemption.go:700)
        self.pdb_lister = pdb_lister
        # extenders with the preempt verb adjust/veto candidates
        # (preemption.go:316 callExtenders)
        self.extenders = tuple(extenders)

    # -- entry (preemption.go:268 Preempt) ------------------------------------

    def preempt(self, state: CycleState, pod: Pod,
                nodes: list[NodeInfo], diagnosis: Diagnosis
                ) -> tuple[Optional[Candidate], Status]:
        if not self.pod_eligible_to_preempt_others(pod, nodes):
            return None, Status.unschedulable(
                "pod is not eligible for preemption",
                plugin="DefaultPreemption")
        potential = self.nodes_where_preemption_might_help(nodes, diagnosis)
        if not potential:
            return None, Status.unschedulable(
                "preemption will not help scheduling",
                plugin="DefaultPreemption")
        num = self.get_num_candidates(len(potential))
        candidates = self.dry_run_preemption(state, pod, potential, num,
                                             all_nodes=nodes)
        if not candidates:
            return None, Status.unschedulable(
                "no preemption victims found for incoming pod",
                plugin="DefaultPreemption")
        candidates = self.call_extenders(pod, candidates)
        if not candidates:
            return None, Status.unschedulable(
                "no preemption candidates survived the extenders",
                plugin="DefaultPreemption")
        best = self.pick_one_node(candidates)
        return best, Status.success()

    def call_extenders(self, pod: Pod,
                       candidates: list[Candidate]) -> list[Candidate]:
        """preemption.go:316 callExtenders: each preemption-capable
        extender sees {node: victims} and returns the accepted subset;
        ignorable extender failures are skipped."""
        exts = [e for e in self.extenders if e.supports_preemption()]
        if not exts:
            return candidates
        by_node = {c.node_name: c for c in candidates}
        victims = {c.node_name: list(c.victims) for c in candidates}
        for ext in exts:
            try:
                victims = ext.process_preemption(pod, victims)
            except Exception:
                if ext.is_ignorable():
                    continue
                raise
            if not victims:
                return []
        out = []
        for node, vs in victims.items():
            c = by_node.get(node)
            if c is None:
                continue
            c.victims = list(vs)
            out.append(c)
        return out

    # -- eligibility (preemption.go:431) ---------------------------------------

    def pod_eligible_to_preempt_others(self, pod: Pod,
                                       nodes: list[NodeInfo]) -> bool:
        if pod.spec.preemption_policy == "Never":
            return False
        nominated = pod.status.nominated_node_name
        if nominated:
            # a lower-priority victim already terminating on the nominated
            # node means preemption is in flight — don't preempt again
            ni = next((n for n in nodes if n.name == nominated), None)
            if ni is not None:
                for pi in ni.pods:
                    if (pi.pod.spec.priority < pod.spec.priority
                            and self._is_delete_pending(pi.pod.uid)):
                        return False
        return True

    # -- candidate universe (preemption.go:291) --------------------------------

    @staticmethod
    def nodes_where_preemption_might_help(nodes: list[NodeInfo],
                                          diagnosis: Diagnosis
                                          ) -> list[NodeInfo]:
        """Nodes that failed resolvably. A node absent from node_to_status
        (the device path reports only global infeasibility) is assumed
        resolvable."""
        out = []
        for ni in nodes:
            st = diagnosis.node_to_status.get(ni.name)
            if st is not None and st.code == Code.UNSCHEDULABLE_AND_UNRESOLVABLE:
                continue
            out.append(ni)
        return out

    def get_num_candidates(self, num_nodes: int) -> int:
        """default_preemption.go:174 GetOffsetAndNumCandidates."""
        n = num_nodes * self.min_pct // 100
        n = max(n, self.min_abs)
        return min(n, num_nodes)

    # -- dry run (preemption.go:775) -------------------------------------------

    def dry_run_preemption(self, state: CycleState, pod: Pod,
                           nodes: list[NodeInfo], num_candidates: int,
                           all_nodes: Optional[list[NodeInfo]] = None
                           ) -> list[Candidate]:
        """`nodes` are the preemption candidates; `all_nodes` the FULL
        snapshot list — PreFilter state (spread counts etc.) must be seeded
        over every node exactly like a real scheduling cycle, not over the
        resolvable subset."""
        pdbs = self.pdb_lister() if self.pdb_lister is not None else []
        candidates: list[Candidate] = []
        for ni in nodes:
            victims, pdb_violations, ok = self.select_victims_on_node(
                pod, ni, all_nodes=all_nodes or nodes, pdbs=pdbs)
            if ok:
                candidates.append(Candidate(
                    node_name=ni.name, victims=victims,
                    num_pdb_violations=pdb_violations))
                if len(candidates) >= num_candidates:
                    break
        return candidates

    def select_victims_on_node(self, pod: Pod, node_info: NodeInfo,
                               all_nodes: list[NodeInfo],
                               pdbs: Optional[list] = None
                               ) -> tuple[list[PodInfo], int, bool]:
        """default_preemption.go:583. Returns (victims, pdbViolations, fits).

        Simulation runs on a structural copy of the NodeInfo and a FRESH
        CycleState re-seeded by PreFilter (the reference clones CycleState;
        re-running PreFilter yields the same plugin state without requiring
        every plugin's state object to implement Clone). The cheap
        potential-victims check runs FIRST so nodes with nothing to preempt
        — the common case when a full cluster rejects a default-priority
        pod — cost no PreFilter work."""
        potential = [pi for pi in node_info.pods
                     if pi.pod.spec.priority < pod.spec.priority]
        if not potential:
            return [], 0, False
        # the clone shares the immutable PodInfo objects: `potential` stays
        # valid against it
        ni = node_info.snapshot_clone()
        state = CycleState()
        _, status = self.fwk.run_pre_filter_plugins(state, pod, all_nodes)
        if not status.is_success():
            return [], 0, False
        for pi in potential:
            self._remove_pod(state, pod, pi, ni)
        # preemptor must fit with ALL lower-priority pods gone
        if not self._fits(state, pod, ni):
            return [], 0, False
        # partition by PDB impact, then reprieve pods most-important-first
        # (util.MoreImportantPod: priority desc, then earlier start via
        # creation_index) while the preemptor still fits. PDB-VIOLATING
        # pods are reprieved FIRST (default_preemption.go:640): they get
        # the best chance of being added back, so PDB-protected workloads
        # are disrupted only when nothing else frees enough room.
        violating, non_violating = self._filter_pods_with_pdb_violation(
            potential, pdbs or [])
        key = lambda pi: (-pi.pod.spec.priority,
                          pi.pod.metadata.creation_index)
        victims: list[PodInfo] = []
        num_violating = 0
        for group, counts in ((sorted(violating, key=key), True),
                              (sorted(non_violating, key=key), False)):
            for pi in group:
                self._add_pod(state, pod, pi, ni)
                if not self._fits(state, pod, ni):
                    self._remove_pod(state, pod, pi, ni)
                    victims.append(pi)
                    if counts:
                        num_violating += 1
        return victims, num_violating, True

    @staticmethod
    def _filter_pods_with_pdb_violation(pods: list[PodInfo], pdbs: list
                                        ) -> tuple[list[PodInfo], list[PodInfo]]:
        """preemption.go filterPodsWithPDBViolation: a pod is 'violating'
        if evicting it would push some matching PDB past its
        disruptionsAllowed budget, accounting for earlier pods in this
        call consuming the same budgets."""
        if not pdbs:
            return [], list(pods)
        remaining = {id(pdb): pdb.disruptions_allowed for pdb in pdbs}
        violating: list[PodInfo] = []
        non_violating: list[PodInfo] = []
        for pi in pods:
            matching = [pdb for pdb in pdbs if pdb.matches(pi.pod)]
            if any(remaining[id(pdb)] <= 0 for pdb in matching):
                violating.append(pi)
            else:
                for pdb in matching:
                    remaining[id(pdb)] -= 1
                non_violating.append(pi)
        return violating, non_violating

    def _fits(self, state: CycleState, pod: Pod, ni: NodeInfo) -> bool:
        status = self.fwk.run_filter_plugins_with_nominated_pods(
            state, pod, ni, self.nominator)
        return status.is_success()

    def _remove_pod(self, state: CycleState, pod: Pod, pi: PodInfo,
                    ni: NodeInfo) -> None:
        ni.remove_pod(pi)
        self.fwk.run_pre_filter_extensions_remove_pod(state, pod, pi, ni)

    def _add_pod(self, state: CycleState, pod: Pod, pi: PodInfo,
                 ni: NodeInfo) -> None:
        ni.add_pod(pi)
        self.fwk.run_pre_filter_extensions_add_pod(state, pod, pi, ni)

    # -- pick (preemption.go:658 pickOneNodeForPreemption) ---------------------

    @staticmethod
    def pick_one_node(candidates: list[Candidate]) -> Candidate:
        best = candidates
        # 1. fewest PDB violations
        m = min(c.num_pdb_violations for c in best)
        best = [c for c in best if c.num_pdb_violations == m]
        if len(best) == 1:
            return best[0]
        # a node with no victims at all wins outright (preemption.go:672)
        for c in best:
            if not c.victims:
                return c
        # 2. lowest highest-victim priority
        m = min(max(pi.pod.spec.priority for pi in c.victims) for c in best)
        best = [c for c in best
                if max(pi.pod.spec.priority for pi in c.victims) == m]
        if len(best) == 1:
            return best[0]
        # 3. smallest sum of victim priorities
        m = min(sum(pi.pod.spec.priority for pi in c.victims) for c in best)
        best = [c for c in best
                if sum(pi.pod.spec.priority for pi in c.victims) == m]
        if len(best) == 1:
            return best[0]
        # 4. fewest victims
        m = min(len(c.victims) for c in best)
        best = [c for c in best if len(c.victims) == m]
        if len(best) == 1:
            return best[0]
        # 5. latest start time of the highest-priority victim → prefer the
        # node whose top victim started most recently (creation_index max)
        def top_victim_start(c: Candidate) -> int:
            top = max(c.victims, key=lambda pi: (pi.pod.spec.priority,
                                                 -pi.pod.metadata.creation_index))
            return top.pod.metadata.creation_index
        m = max(top_victim_start(c) for c in best)
        best = [c for c in best if top_victim_start(c) == m]
        return best[0]
