"""Framework data types: NodeInfo, PodInfo, QueuedPodInfo, events, FitError.

Mirrors pkg/scheduler/framework/types.go (NodeInfo :165-208, PodInfo,
QueuedPodInfo) and the staging ClusterEvent/ActionType bitmask
(staging/.../framework/types.go:33-130). NodeInfo here is the host-side row
mirror of the device capacity matrices; `generation` drives the incremental
scatter-update snapshot (reference: backend/cache/snapshot.go).
"""

from __future__ import annotations

import enum
import itertools
from dataclasses import dataclass, field
from typing import Optional

from ..api import resources as res
from ..api.types import Node, Pod

_generation = itertools.count(1)


def next_generation() -> int:
    return next(_generation)


# ---------------------------------------------------------------------------
# cluster events (reference: staging framework/types.go ActionType bitmask)


class ActionType(enum.IntFlag):
    ADD = 1
    DELETE = 2
    UPDATE_NODE_ALLOCATABLE = 4
    UPDATE_NODE_LABEL = 8
    UPDATE_NODE_TAINT = 16
    UPDATE_NODE_CONDITION = 32
    UPDATE_NODE_ANNOTATION = 64
    UPDATE_POD_LABEL = 128
    UPDATE_POD_SCALE_DOWN = 256
    UPDATE_POD_TOLERATION = 512
    UPDATE_POD_SCHEDULING_GATES = 1024
    UPDATE_NODE_DECLARED_FEATURE = 2048
    UPDATE = (UPDATE_NODE_ALLOCATABLE | UPDATE_NODE_LABEL | UPDATE_NODE_TAINT
              | UPDATE_NODE_CONDITION | UPDATE_NODE_ANNOTATION | UPDATE_POD_LABEL
              | UPDATE_POD_SCALE_DOWN | UPDATE_POD_TOLERATION
              | UPDATE_POD_SCHEDULING_GATES | UPDATE_NODE_DECLARED_FEATURE)
    ALL = ADD | DELETE | UPDATE


class EventResource(str, enum.Enum):
    POD = "Pod"
    ASSIGNED_POD = "AssignedPod"
    UNSCHEDULABLE_POD = "UnschedulablePod"
    NODE = "Node"
    PVC = "PersistentVolumeClaim"
    PV = "PersistentVolume"
    CSI_NODE = "CSINode"
    WORKLOAD = "Workload"
    PDB = "PodDisruptionBudget"
    RESOURCE_CLAIM = "ResourceClaim"
    RESOURCE_SLICE = "ResourceSlice"
    WILDCARD = "*"


@dataclass(frozen=True)
class ClusterEvent:
    resource: EventResource
    action_type: ActionType
    label: str = ""

    def match(self, other: "ClusterEvent") -> bool:
        return ((self.resource == other.resource or self.resource == EventResource.WILDCARD)
                and bool(self.action_type & other.action_type))


class QueueingHint(enum.IntEnum):
    """Reference: staging framework/interface.go QueueingHint."""

    SKIP = 0
    QUEUE = 1


EVENT_UNSCHEDULABLE_TIMEOUT = ClusterEvent(EventResource.WILDCARD, ActionType.ALL, "UnschedulableTimeout")
EVENT_FORCE_ACTIVATE = ClusterEvent(EventResource.WILDCARD, ActionType.ALL, "ForceActivate")


# ---------------------------------------------------------------------------
# PodInfo: pod + pre-parsed scheduling terms (reference types.go PodInfo —
# required affinity terms pre-parsed once at ingest)


@dataclass(slots=True)
class PodInfo:
    pod: Pod
    # flattened request vectors, computed once
    requests: dict[str, int] = field(default_factory=dict)
    cpu_nonzero: int = 0
    mem_nonzero: int = 0
    # lazy parse cache (interpodaffinity existing-anti fast path); slots
    # forbid ad-hoc attributes, so the cache slot is declared here
    _parsed_req_anti_affinity: Optional[tuple] = None

    @staticmethod
    def of(pod: Pod) -> "PodInfo":
        cpu_nz, mem_nz = res.pod_requests_nonzero(pod)
        return PodInfo(pod=pod, requests=res.pod_requests(pod),
                       cpu_nonzero=cpu_nz, mem_nonzero=mem_nz)

    @property
    def required_affinity_terms(self):
        aff = self.pod.spec.affinity
        return aff.pod_affinity.required if aff and aff.pod_affinity else ()

    @property
    def required_anti_affinity_terms(self):
        aff = self.pod.spec.affinity
        return aff.pod_anti_affinity.required if aff and aff.pod_anti_affinity else ()


# ---------------------------------------------------------------------------
# QueuedPodInfo (reference types.go QueuedPodInfo)


@dataclass(slots=True)
class QueuedPodInfo:
    pod_info: PodInfo
    timestamp: float = 0.0          # when added to queue (for queue-sort tie)
    initial_attempt_timestamp: Optional[float] = None
    attempts: int = 0
    unschedulable_count: int = 0    # backoff exponent driver
    consecutive_errors_count: int = 0
    # None means "empty": the ingest hot path creates one QueuedPodInfo
    # per pod, and two set() allocations per pod for fields only the
    # failure path populates are a measurable slice of add_bulk. Readers
    # treat None and empty-set alike (truthiness); writers assign real
    # sets.
    unschedulable_plugins: Optional[set[str]] = None
    pending_plugins: Optional[set[str]] = None
    gated: bool = False
    gating_plugin: str = ""
    # `pod` is a REAL slot, not a property: the queue-sort key and every
    # hot loop read it several times per pod, and the attribute load is
    # ~3× cheaper than a property descriptor call. Kept in sync by
    # __post_init__ and the two pod_info-replacement sites in
    # backend/queue.py update().
    pod: Optional[Pod] = None

    def __post_init__(self) -> None:
        if self.pod is None:
            self.pod = self.pod_info.pod


# ---------------------------------------------------------------------------
# NodeInfo (reference types.go:165-208)


@dataclass
class HostPortInfo:
    """used host ports: set of (protocol, port, ip)."""

    ports: set[tuple[str, int, str]] = field(default_factory=set)

    @staticmethod
    def _ip(ip: str) -> str:
        return ip or "0.0.0.0"

    def add(self, protocol: str, port: int, ip: str = "") -> None:
        if port > 0:
            self.ports.add((protocol or "TCP", port, self._ip(ip)))

    def remove(self, protocol: str, port: int, ip: str = "") -> None:
        self.ports.discard((protocol or "TCP", port, self._ip(ip)))

    def conflicts(self, protocol: str, port: int, ip: str = "") -> bool:
        """Reference: framework/types.go HostPortInfo.CheckConflict —
        wildcard IP conflicts with any IP on same proto/port."""
        if port <= 0:
            return False
        protocol, ip = protocol or "TCP", self._ip(ip)
        if ip == "0.0.0.0":
            return any(p == protocol and pt == port for (p, pt, _) in self.ports)
        return ((protocol, port, ip) in self.ports
                or (protocol, port, "0.0.0.0") in self.ports)


@dataclass
class NodeInfo:
    node: Node
    pods: list[PodInfo] = field(default_factory=list)
    pods_with_affinity: list[PodInfo] = field(default_factory=list)
    pods_with_required_anti_affinity: list[PodInfo] = field(default_factory=list)
    requested: dict[str, int] = field(default_factory=dict)
    non_zero_cpu: int = 0
    non_zero_mem: int = 0
    used_ports: HostPortInfo = field(default_factory=HostPortInfo)
    image_sizes: dict[str, int] = field(default_factory=dict)  # image name → size
    generation: int = 0

    def __post_init__(self) -> None:
        if not self.generation:
            self.generation = next_generation()
        if not self.image_sizes:
            self.sync_images()

    def sync_images(self) -> None:
        """node.status.images → name→size map (cache.go updateImageStates:
        every name of an image entry resolves to its size)."""
        sizes: dict[str, int] = {}
        for img in self.node.status.images:
            for name in img.names:
                sizes[name] = img.size_bytes
        self.image_sizes = sizes

    @property
    def name(self) -> str:
        return self.node.name

    @property
    def allocatable(self) -> dict[str, int]:
        return self.node.status.allocatable

    def bump(self) -> None:
        self.generation = next_generation()

    def snapshot_clone(self) -> "NodeInfo":
        """NodeInfo.Snapshot(): structural copy sharing immutable PodInfos
        (types.go Snapshot) — mutation-safe for preemption dry runs."""
        clone = NodeInfo(node=self.node, generation=self.generation,
                         image_sizes=dict(self.image_sizes))
        clone.pods = list(self.pods)
        clone.pods_with_affinity = list(self.pods_with_affinity)
        clone.pods_with_required_anti_affinity = list(
            self.pods_with_required_anti_affinity)
        clone.requested = dict(self.requested)
        clone.non_zero_cpu = self.non_zero_cpu
        clone.non_zero_mem = self.non_zero_mem
        clone.used_ports.ports = set(self.used_ports.ports)
        return clone

    # -- pod add/remove (reference types.go AddPodInfo/RemovePod) ------------

    def add_pod(self, pi: PodInfo) -> None:
        self.pods.append(pi)
        if pi.required_affinity_terms or self._has_preferred_affinity(pi):
            self.pods_with_affinity.append(pi)
        if pi.required_anti_affinity_terms:
            self.pods_with_required_anti_affinity.append(pi)
        for k, v in pi.requests.items():
            self.requested[k] = self.requested.get(k, 0) + v
        self.non_zero_cpu += pi.cpu_nonzero
        self.non_zero_mem += pi.mem_nonzero
        self._update_ports(pi.pod, add=True)
        self.bump()

    def remove_pod(self, pi: PodInfo) -> bool:
        uid = pi.pod.uid
        found = False
        for lst in (self.pods, self.pods_with_affinity, self.pods_with_required_anti_affinity):
            for i, p in enumerate(lst):
                if p.pod.uid == uid:
                    del lst[i]
                    found = lst is self.pods or found
                    break
        if not found:
            return False
        for k, v in pi.requests.items():
            self.requested[k] = self.requested.get(k, 0) - v
        self.non_zero_cpu -= pi.cpu_nonzero
        self.non_zero_mem -= pi.mem_nonzero
        self._update_ports(pi.pod, add=False)
        self.bump()
        return True

    @staticmethod
    def _has_preferred_affinity(pi: PodInfo) -> bool:
        aff = pi.pod.spec.affinity
        if not aff:
            return False
        return bool((aff.pod_affinity and aff.pod_affinity.preferred)
                    or (aff.pod_anti_affinity and aff.pod_anti_affinity.preferred))

    def _update_ports(self, pod: Pod, add: bool) -> None:
        for c in pod.spec.containers:
            for p in c.ports:
                if p.host_port > 0:
                    if add:
                        self.used_ports.add(p.protocol, p.host_port, p.host_ip)
                    else:
                        self.used_ports.remove(p.protocol, p.host_port, p.host_ip)


# ---------------------------------------------------------------------------
# failures / diagnosis (reference types.go FitError/Diagnosis)


@dataclass
class Diagnosis:
    node_to_status: dict[str, Status] = field(default_factory=dict)
    unschedulable_plugins: set[str] = field(default_factory=set)
    pending_plugins: set[str] = field(default_factory=set)
    pre_filter_msg: str = ""
    # memoized aggregations (one Diagnosis is shared by every same-signature
    # pod of a failed drain; a 5k-node histogram must not be recomputed per
    # pod). Invalidation is unnecessary: node_to_status is write-once.
    _reasons_hist: Optional[dict] = None
    _plugin_counts: Optional[dict] = None

    def reasons_histogram(self) -> dict[str, int]:
        """reason string → node count; a node contributes once per reason
        its status carries (reference types.go FitError.Error histogram)."""
        if self._reasons_hist is None:
            hist: dict[str, int] = {}
            for status in self.node_to_status.values():
                for r in status.reasons:
                    hist[r] = hist.get(r, 0) + 1
            self._reasons_hist = hist
        return self._reasons_hist

    def plugin_node_counts(self) -> dict[str, int]:
        """rejecting plugin → node count (each node counts once, under the
        first plugin that rejected it)."""
        if self._plugin_counts is None:
            counts: dict[str, int] = {}
            for status in self.node_to_status.values():
                p = status.plugin or "?"
                counts[p] = counts.get(p, 0) + 1
            self._plugin_counts = counts
        return self._plugin_counts


@dataclass
class FitError(Exception):
    pod: Pod
    num_all_nodes: int
    diagnosis: Diagnosis = field(default_factory=Diagnosis)

    def __str__(self) -> str:
        """Reference types.go FitError.Error(): '0/N nodes are available:
        <count> <reason>, ...' with reasons sorted alphabetically (the
        FailedScheduling event body)."""
        if self.diagnosis.pre_filter_msg:
            return (f"0/{self.num_all_nodes} nodes are available: "
                    f"{self.diagnosis.pre_filter_msg}.")
        hist = self.diagnosis.reasons_histogram()
        if not hist:
            return (f"0/{self.num_all_nodes} nodes are available for pod "
                    f"{self.pod.namespace}/{self.pod.name}")
        body = ", ".join(f"{count} {reason}"
                         for reason, count in sorted(hist.items()))
        return f"0/{self.num_all_nodes} nodes are available: {body}."


from .interface import Status  # noqa: E402  (bottom import to avoid cycle)
