"""Scheduler extenders: out-of-process filter/prioritize/bind hooks.

Mirrors pkg/scheduler/extender.go + schedule_one.go's extender phases:
- `Extender` is the interface (extender.go:65 SchedulerExtender): Filter
  runs after the plugin filters (findNodesThatPassExtenders,
  schedule_one.go:558,598 — an ignorable extender's failure is skipped,
  a filtered-out node records Unschedulable in the diagnosis), Prioritize
  contributes weighted scores on top of the plugin totals
  (prioritizeNodes, schedule_one.go:611-617), Bind optionally takes over
  the bind call.
- `HTTPExtender` posts ExtenderArgs-shaped JSON to the configured URLs —
  the reference's webhook wire protocol (extender/v1 types), built on
  urllib so it works against any HTTP endpoint.
- `CallableExtender` wraps in-process functions for tests and embedded
  extensions.

Extenders are API-coupled and node-list-shaped, so they have no tensor
form: the scheduler routes every pod of a profile with extenders through
the host oracle path — the exact analog of the reference DISABLING
opportunistic batching when extenders are configured
(runtime/framework.go:775-780).
"""

from __future__ import annotations

import json
import urllib.request
from dataclasses import dataclass, field
from typing import Callable, Optional

from ..api.types import Pod
from .types import NodeInfo


@dataclass
class CallableExtender:
    """In-process extender: filter/prioritize/bind as plain callables."""

    name: str = "extender"
    # filter(pod, nodes) → (feasible nodes, {node name: failure reason})
    filter_fn: Optional[Callable] = None
    # prioritize(pod, nodes) → {node name: score 0..10}
    prioritize_fn: Optional[Callable] = None
    weight: int = 1
    # bind(pod, node_name) → None (raises on failure)
    bind_fn: Optional[Callable] = None
    # preempt(pod, {node: [victim pods]}) → reduced {node: [victim pods]}
    # (extender.go ProcessPreemption: the extender drops nodes whose
    # victims it refuses, or trims victim sets)
    preempt_fn: Optional[Callable] = None
    ignorable: bool = False

    def is_filter(self) -> bool:
        return self.filter_fn is not None

    def is_prioritizer(self) -> bool:
        return self.prioritize_fn is not None

    def is_binder(self) -> bool:
        return self.bind_fn is not None

    def supports_preemption(self) -> bool:
        return self.preempt_fn is not None

    def is_ignorable(self) -> bool:
        return self.ignorable

    def process_preemption(self, pod: Pod, node_to_victims: dict):
        return self.preempt_fn(pod, node_to_victims)

    def filter(self, pod: Pod, nodes: list[NodeInfo]):
        """→ (feasible, failed) or (feasible, failed, unresolvable)."""
        return self.filter_fn(pod, nodes)

    def prioritize(self, pod: Pod, nodes: list[NodeInfo]) -> dict[str, int]:
        return self.prioritize_fn(pod, nodes)

    def bind(self, pod: Pod, node_name: str) -> None:
        self.bind_fn(pod, node_name)


@dataclass
class HTTPExtender:
    """extender.go HTTPExtender: the webhook wire protocol.

    POSTs {"Pod": ..., "NodeNames": [...]} to url_prefix+filter_verb /
    prioritize_verb and expects ExtenderFilterResult / HostPriorityList
    JSON back (extender/v1)."""

    url_prefix: str
    filter_verb: str = ""
    prioritize_verb: str = ""
    bind_verb: str = ""
    preempt_verb: str = ""
    weight: int = 1
    ignorable: bool = False
    timeout_s: float = 5.0
    name_: str = ""

    @property
    def name(self) -> str:
        return self.name_ or self.url_prefix

    def is_filter(self) -> bool:
        return bool(self.filter_verb)

    def is_prioritizer(self) -> bool:
        return bool(self.prioritize_verb)

    def is_binder(self) -> bool:
        return bool(self.bind_verb)

    def supports_preemption(self) -> bool:
        return bool(self.preempt_verb)

    def is_ignorable(self) -> bool:
        return self.ignorable

    def process_preemption(self, pod: Pod, node_to_victims: dict
                           ) -> dict:
        """extender.go ProcessPreemption wire form: victims ship as pod
        identifiers; the response keeps the accepted subset."""
        payload = {
            "Pod": {"name": pod.name, "namespace": pod.namespace,
                    "uid": pod.uid},
            "NodeNameToVictims": {
                node: [{"name": v.pod.name, "uid": v.pod.uid}
                       for v in victims]
                for node, victims in node_to_victims.items()},
        }
        result = self._post(self.preempt_verb, payload)
        if result.get("Error"):
            raise RuntimeError(result["Error"])
        accepted = result.get("NodeNameToVictims")
        if accepted is None:
            return node_to_victims
        out = {}
        for node, victims in node_to_victims.items():
            if node not in accepted:
                continue
            keep = {v["uid"] for v in (accepted[node] or [])}
            out[node] = [v for v in victims if v.pod.uid in keep]
        return out

    def _post(self, verb: str, payload: dict) -> dict:
        req = urllib.request.Request(
            f"{self.url_prefix}/{verb}",
            data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=self.timeout_s) as r:
            return json.loads(r.read().decode())

    def filter(self, pod: Pod, nodes: list[NodeInfo]
               ) -> tuple[list[NodeInfo], dict[str, str], dict[str, str]]:
        result = self._post(self.filter_verb, {
            "Pod": {"name": pod.name, "namespace": pod.namespace,
                    "uid": pod.uid},
            "NodeNames": [ni.name for ni in nodes]})
        if result.get("Error"):
            raise RuntimeError(result["Error"])
        names = result.get("NodeNames")
        # nil means "no opinion"; an EMPTY list is a total veto
        # (extender.go distinguishes nil from empty)
        keep = (set(names) if names is not None
                else {ni.name for ni in nodes})
        failed = dict(result.get("FailedNodes") or {})
        unresolvable = dict(result.get("FailedAndUnresolvableNodes") or {})
        return ([ni for ni in nodes if ni.name in keep], failed,
                unresolvable)

    def prioritize(self, pod: Pod, nodes: list[NodeInfo]) -> dict[str, int]:
        result = self._post(self.prioritize_verb, {
            "Pod": {"name": pod.name, "namespace": pod.namespace,
                    "uid": pod.uid},
            "NodeNames": [ni.name for ni in nodes]})
        return {e["Host"]: int(e["Score"]) for e in result or []}

    def bind(self, pod: Pod, node_name: str) -> None:
        self._post(self.bind_verb, {
            "PodName": pod.name, "PodNamespace": pod.namespace,
            "PodUID": pod.uid, "Node": node_name})


def find_nodes_that_pass_extenders(extenders, pod: Pod,
                                   feasible: list[NodeInfo],
                                   diagnosis) -> list[NodeInfo]:
    """schedule_one.go findNodesThatPassExtenders (:631-676)."""
    from .interface import Status
    for ext in extenders:
        if not ext.is_filter():
            continue
        if not feasible:
            break
        try:
            result = ext.filter(pod, feasible)
        except Exception:
            if ext.is_ignorable():
                continue
            raise
        feasible_after, failed = result[0], result[1]
        unresolvable = result[2] if len(result) > 2 else {}
        ext_name = ext.name if isinstance(ext.name, str) else "extender"
        for name, reason in failed.items():
            diagnosis.node_to_status[name] = Status.unschedulable(
                reason, plugin=ext_name)
        for name, reason in unresolvable.items():
            # permanently-vetoed nodes must not become preemption
            # candidates (nodesWherePreemptionMightHelp skips these)
            diagnosis.node_to_status[name] = Status.unresolvable(
                reason, plugin=ext_name)
        feasible = feasible_after
    return feasible


# extender/v1: extender priorities are 0..MaxExtenderPriority (10) and are
# rescaled to the plugins' 0..MaxNodeScore (100) range when combined
MAX_EXTENDER_PRIORITY = 10
_EXTENDER_SCALE = 100 // MAX_EXTENDER_PRIORITY


def extender_scores(extenders, pod: Pod, nodes: list[NodeInfo]
                    ) -> dict[str, int]:
    """prioritizeNodes' extender loop (schedule_one.go:700-741): each
    prioritizer's 0..10 scores scale by weight × MaxNodeScore/
    MaxExtenderPriority and add to the plugin totals."""
    totals: dict[str, int] = {}
    for ext in extenders:
        if not ext.is_prioritizer():
            continue
        try:
            scores = ext.prioritize(pod, nodes)
        except Exception:
            if ext.is_ignorable():
                continue
            raise
        for name, score in scores.items():
            totals[name] = (totals.get(name, 0)
                            + score * ext.weight * _EXTENDER_SCALE)
    return totals
