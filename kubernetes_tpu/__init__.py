"""kubernetes_tpu — a TPU-native batch scheduling framework.

A from-scratch rebuild of the Kubernetes kube-scheduler's capabilities
(reference: kubernetes/kubernetes, pkg/scheduler) where the per-pod
Filter→Score→Normalize cycle (reference: pkg/scheduler/schedule_one.go) is
lifted into a single batched JAX/XLA program over HBM-resident cluster-state
matrices, and the host keeps the reference's semantics for queueing, backoff,
gang quorum, preemption, assume/bind and async API dispatch.

Quantities (CPU milli-units, memory bytes) are carried as int64 end-to-end:
the reference's fit checks (pkg/scheduler/framework/plugins/noderesources/
fit.go:649-738) and score arithmetic (least_allocated.go:30-60) are exact
int64 math, and decision parity with the Go plugins is a hard requirement
(see BASELINE.json north_star). x64 must therefore be enabled before any
JAX array is created; importing this package does it.
"""

import os

if os.environ.get("KTPU_DISABLE_X64", "0") != "1":
    import jax

    jax.config.update("jax_enable_x64", True)

__version__ = "0.1.0"
