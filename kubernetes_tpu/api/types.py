"""Core API object model — the subset of k8s API types the scheduler reads.

Mirrors the fields consumed by pkg/scheduler in the reference
(staging/src/k8s.io/api/core/v1/types.go); everything irrelevant to
scheduling decisions is omitted. These are plain Python dataclasses: the
"wire format" of this framework is the in-memory object graph fed by the
cluster-state ingestion layer (backend/eventhandlers), exactly as the
reference's scheduler only ever sees decoded informer objects.
"""

from __future__ import annotations

import copy
import dataclasses
import enum


def _shallow(obj):
    """Fast shallow copy for plain (non-slots) dataclass instances."""
    new = object.__new__(type(obj))
    new.__dict__.update(obj.__dict__)
    return new
from dataclasses import dataclass, field
from typing import Optional

# ---------------------------------------------------------------------------
# metadata


@dataclass
class ObjectMeta:
    name: str = ""
    namespace: str = "default"
    uid: str = ""
    labels: dict[str, str] = field(default_factory=dict)
    annotations: dict[str, str] = field(default_factory=dict)
    # creation ordering for queue-sort tie-breaks (reference: queuesort
    # priority_sort.go falls back to QueuedPodInfo timestamp; we also keep
    # object creation order for deterministic tests).
    creation_index: int = 0

    def __post_init__(self) -> None:
        if not self.uid:
            self.uid = f"{self.namespace}/{self.name}"


# ---------------------------------------------------------------------------
# taints & tolerations (reference: staging api core/v1/toleration.go, taint.go)


class TaintEffect(str, enum.Enum):
    NO_SCHEDULE = "NoSchedule"
    PREFER_NO_SCHEDULE = "PreferNoSchedule"
    NO_EXECUTE = "NoExecute"


class TolerationOperator(str, enum.Enum):
    EXISTS = "Exists"
    EQUAL = "Equal"


@dataclass(frozen=True)
class Taint:
    key: str
    value: str = ""
    effect: str = TaintEffect.NO_SCHEDULE.value


@dataclass(frozen=True)
class Toleration:
    key: str = ""
    operator: str = TolerationOperator.EQUAL.value
    value: str = ""
    effect: str = ""  # empty matches all effects
    toleration_seconds: Optional[int] = None

    def tolerates(self, taint: Taint) -> bool:
        """Reference: staging/src/k8s.io/api/core/v1/toleration.go:29-56.

        An empty key with Exists tolerates everything; operator defaults to
        Equal; empty effect matches all effects.
        """
        op = self.operator or TolerationOperator.EQUAL.value
        if self.effect and self.effect != taint.effect:
            return False
        if self.key and self.key != taint.key:
            return False
        if op == TolerationOperator.EXISTS.value:
            return True
        if op == TolerationOperator.EQUAL.value:
            # empty key with Equal: key must match (empty key only valid
            # with Exists), mirror Go behavior of comparing values.
            return self.value == taint.value
        return False


# ---------------------------------------------------------------------------
# label selectors (reference: apimachinery pkg/apis/meta/v1/types.go:1214,
# helpers in pkg/apis/meta/v1/helpers.go LabelSelectorAsSelector)


class SelectorOperator(str, enum.Enum):
    IN = "In"
    NOT_IN = "NotIn"
    EXISTS = "Exists"
    DOES_NOT_EXIST = "DoesNotExist"
    GT = "Gt"  # node-selector only
    LT = "Lt"  # node-selector only


@dataclass(frozen=True)
class LabelSelectorRequirement:
    key: str
    operator: str
    values: tuple[str, ...] = ()


@dataclass(frozen=True)
class LabelSelector:
    """match_labels is ANDed with match_expressions; empty selector matches
    everything, None (absent) matches nothing — callers must distinguish."""

    match_labels: tuple[tuple[str, str], ...] = ()
    match_expressions: tuple[LabelSelectorRequirement, ...] = ()

    @staticmethod
    def of(match_labels: Optional[dict[str, str]] = None,
           match_expressions: tuple[LabelSelectorRequirement, ...] = ()) -> "LabelSelector":
        return LabelSelector(
            match_labels=tuple(sorted((match_labels or {}).items())),
            match_expressions=tuple(match_expressions),
        )

    def matches(self, labels: dict[str, str]) -> bool:
        for k, v in self.match_labels:
            if labels.get(k) != v:
                return False
        for req in self.match_expressions:
            if not _requirement_matches(req, labels):
                return False
        return True


def _requirement_matches(req: LabelSelectorRequirement, labels: dict[str, str]) -> bool:
    op = req.operator
    if op == SelectorOperator.IN.value:
        return req.key in labels and labels[req.key] in req.values
    if op == SelectorOperator.NOT_IN.value:
        # NotIn requires the key to exist per labels.Requirement semantics
        # used by LabelSelectorAsSelector (NotIn -> sel.NotIn which matches
        # when key absent as well).  Reference: apimachinery labels/selector.go
        # Requirement.Matches: NotIn returns true when key is absent.
        return not (req.key in labels and labels[req.key] in req.values)
    if op == SelectorOperator.EXISTS.value:
        return req.key in labels
    if op == SelectorOperator.DOES_NOT_EXIST.value:
        return req.key not in labels
    if op in (SelectorOperator.GT.value, SelectorOperator.LT.value):
        if req.key not in labels or len(req.values) != 1:
            return False
        try:
            lhs = int(labels[req.key])
            rhs = int(req.values[0])
        except ValueError:
            return False
        return lhs > rhs if op == SelectorOperator.GT.value else lhs < rhs
    return False


# ---------------------------------------------------------------------------
# node affinity (reference: core/v1 NodeSelector / NodeAffinity; matching
# helpers in staging/src/k8s.io/component-helpers/scheduling/corev1/nodeaffinity)


@dataclass(frozen=True)
class NodeSelectorTerm:
    # terms are ORed; expressions within a term are ANDed
    match_expressions: tuple[LabelSelectorRequirement, ...] = ()
    match_fields: tuple[LabelSelectorRequirement, ...] = ()  # metadata.name only


@dataclass(frozen=True)
class NodeSelector:
    terms: tuple[NodeSelectorTerm, ...] = ()


@dataclass(frozen=True)
class PreferredSchedulingTerm:
    weight: int
    preference: NodeSelectorTerm


@dataclass(frozen=True)
class NodeAffinity:
    required: Optional[NodeSelector] = None
    preferred: tuple[PreferredSchedulingTerm, ...] = ()


# ---------------------------------------------------------------------------
# pod (anti-)affinity (reference: core/v1 PodAffinity/PodAntiAffinity)


@dataclass(frozen=True)
class PodAffinityTerm:
    topology_key: str
    label_selector: Optional[LabelSelector] = None
    namespaces: tuple[str, ...] = ()  # empty => pod's own namespace
    namespace_selector: Optional[LabelSelector] = None  # None => no ns selection
    match_label_keys: tuple[str, ...] = ()


@dataclass(frozen=True)
class WeightedPodAffinityTerm:
    weight: int
    term: PodAffinityTerm


@dataclass(frozen=True)
class PodAffinity:
    required: tuple[PodAffinityTerm, ...] = ()
    preferred: tuple[WeightedPodAffinityTerm, ...] = ()


@dataclass(frozen=True)
class PodAntiAffinity:
    required: tuple[PodAffinityTerm, ...] = ()
    preferred: tuple[WeightedPodAffinityTerm, ...] = ()


@dataclass(frozen=True)
class Affinity:
    node_affinity: Optional[NodeAffinity] = None
    pod_affinity: Optional[PodAffinity] = None
    pod_anti_affinity: Optional[PodAntiAffinity] = None


# ---------------------------------------------------------------------------
# topology spread (reference: core/v1 TopologySpreadConstraint)


class UnsatisfiableConstraintAction(str, enum.Enum):
    DO_NOT_SCHEDULE = "DoNotSchedule"
    SCHEDULE_ANYWAY = "ScheduleAnyway"


@dataclass(frozen=True)
class TopologySpreadConstraint:
    max_skew: int
    topology_key: str
    when_unsatisfiable: str
    label_selector: Optional[LabelSelector] = None
    min_domains: Optional[int] = None
    match_label_keys: tuple[str, ...] = ()
    # NodeAffinityPolicy / NodeTaintsPolicy: Honor (default) or Ignore
    node_affinity_policy: str = "Honor"
    node_taints_policy: str = "Ignore"


# ---------------------------------------------------------------------------
# containers / ports / resources


@dataclass(frozen=True)
class ContainerPort:
    host_port: int = 0
    container_port: int = 0
    protocol: str = "TCP"
    host_ip: str = ""


@dataclass
class Container:
    name: str = ""
    # resource requests in canonical int64 units (cpu: milli, memory: bytes,
    # anything else: unit count). Parse human strings via api.resources.parse.
    requests: dict[str, int] = field(default_factory=dict)
    limits: dict[str, int] = field(default_factory=dict)
    ports: tuple[ContainerPort, ...] = ()
    image: str = ""


@dataclass(frozen=True)
class PodSchedulingGate:
    name: str


# ---------------------------------------------------------------------------
# pod


DEFAULT_SCHEDULER_NAME = "default-scheduler"  # reference: v1.DefaultSchedulerName


# ---------------------------------------------------------------------------
# storage (reference: core/v1 PersistentVolume[Claim], storage/v1 StorageClass
# — the subset the scheduler's volume plugins consume)


@dataclass
class Volume:
    """core/v1 Volume, reduced to the sources the scheduler inspects."""

    name: str = ""
    # persistentVolumeClaim.claimName ("" = not a PVC-backed volume)
    claim_name: str = ""
    # csi driver for inline CSI volumes (nodevolumelimits counting)
    csi_driver: str = ""


@dataclass
class PersistentVolumeClaim:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    storage_class_name: str = ""
    volume_name: str = ""                  # bound PV ("" = unbound)
    # requested storage bytes (resources.requests["storage"])
    requested_bytes: int = 0
    access_modes: tuple[str, ...] = ("ReadWriteOnce",)
    phase: str = "Pending"                 # Pending | Bound

    @property
    def uid(self) -> str:
        return self.metadata.uid

    @property
    def name(self) -> str:
        return self.metadata.name

    @property
    def namespace(self) -> str:
        return self.metadata.namespace

    def is_bound(self) -> bool:
        return bool(self.volume_name)


@dataclass
class PersistentVolume:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    capacity_bytes: int = 0
    storage_class_name: str = ""
    # claim currently bound to this PV ("" = Available)
    claim_ref: str = ""                    # "<namespace>/<pvc name>"
    access_modes: tuple[str, ...] = ("ReadWriteOnce",)
    # volume.node_affinity.required (PV topology; local volumes / zonal disks)
    node_affinity: Optional[NodeSelector] = None
    csi_driver: str = ""                   # attachable-volume counting

    @property
    def name(self) -> str:
        return self.metadata.name


# storage/v1 VolumeBindingMode
BINDING_IMMEDIATE = "Immediate"
BINDING_WAIT_FOR_FIRST_CONSUMER = "WaitForFirstConsumer"


@dataclass
class StorageClass:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    provisioner: str = ""
    volume_binding_mode: str = BINDING_IMMEDIATE

    @property
    def name(self) -> str:
        return self.metadata.name


@dataclass
class PodSpec:
    containers: list[Container] = field(default_factory=list)
    init_containers: list[Container] = field(default_factory=list)
    node_name: str = ""
    scheduler_name: str = DEFAULT_SCHEDULER_NAME
    priority: int = 0
    node_selector: dict[str, str] = field(default_factory=dict)
    affinity: Optional[Affinity] = None
    tolerations: list[Toleration] = field(default_factory=list)
    topology_spread_constraints: list[TopologySpreadConstraint] = field(default_factory=list)
    scheduling_gates: list[PodSchedulingGate] = field(default_factory=list)
    overhead: dict[str, int] = field(default_factory=dict)
    host_network: bool = False
    # PreemptLowerPriority (default) | Never (core/v1 PreemptionPolicy)
    preemption_policy: str = "PreemptLowerPriority"
    # volumes the scheduler inspects (PVC refs + inline CSI)
    volumes: list[Volume] = field(default_factory=list)
    # node features this pod requires (nodedeclaredfeatures plugin; the
    # reference INFERS these from spec fields via the ndf library — our
    # object model declares them directly)
    required_node_features: tuple[str, ...] = ()
    # gang scheduling: name of the Workload/pod-group this pod belongs to
    # (reference: scheduling/v1alpha1.Workload via pod labels; we model it as
    # a direct field + the label fallback used by workloadmanager).
    workload_ref: str = ""
    # DRA: names of ResourceClaims (same namespace) this pod consumes
    # (core/v1 PodSpec.ResourceClaims → resourceClaimName)
    resource_claims: tuple[str, ...] = ()


@dataclass
class PodStatus:
    phase: str = "Pending"
    nominated_node_name: str = ""
    conditions: list[dict] = field(default_factory=list)


@dataclass
class Pod:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodSpec = field(default_factory=PodSpec)
    status: PodStatus = field(default_factory=PodStatus)

    @property
    def uid(self) -> str:
        return self.metadata.uid

    @property
    def name(self) -> str:
        return self.metadata.name

    @property
    def namespace(self) -> str:
        return self.metadata.namespace

    def with_node_name(self, node_name: str) -> "Pod":
        """Shallow rebind copy for the assume/bind hot path: fresh Pod +
        PodSpec (+ status) shells, node_name set; metadata, containers and
        label dicts are SHARED per the aliasing contract above. The three
        copies are inlined (not _shallow calls): this runs twice per
        scheduled pod and the call overhead is a measurable slice of the
        commit edge."""
        new = object.__new__
        p = new(Pod)
        p.__dict__.update(self.__dict__)
        sp = new(type(self.spec))
        sp.__dict__.update(self.spec.__dict__)
        sp.node_name = node_name
        p.spec = sp
        st = new(type(self.status))
        st.__dict__.update(self.status.__dict__)
        p.status = st
        return p

    def clone(self) -> "Pod":
        # hot path (2 clones per scheduled pod): raw __dict__ copies — both
        # copy.copy (reduce protocol) and dataclasses.replace (re-runs
        # __init__) are several times slower.
        # ALIASING CONTRACT: containers (and their request dicts) are
        # SHARED with the original — treat Container/requests as immutable
        # after creation; any mutation must replace, not update in place.
        p = _shallow(self)
        p.metadata = _shallow(self.metadata)
        p.metadata.labels = dict(self.metadata.labels)
        p.metadata.annotations = dict(self.metadata.annotations)
        p.spec = _shallow(self.spec)
        p.status = _shallow(self.status)
        return p


# ---------------------------------------------------------------------------
# node


@dataclass(frozen=True)
class ContainerImage:
    names: tuple[str, ...]
    size_bytes: int = 0


@dataclass
class NodeSpec:
    unschedulable: bool = False
    taints: list[Taint] = field(default_factory=list)


@dataclass
class NodeStatus:
    # canonical int64 units, keyed by resource name ("cpu", "memory", "pods",
    # "ephemeral-storage", extended resources)
    capacity: dict[str, int] = field(default_factory=dict)
    allocatable: dict[str, int] = field(default_factory=dict)
    images: list[ContainerImage] = field(default_factory=list)
    # features the node runtime declares (node.status.declaredFeatures)
    declared_features: tuple[str, ...] = ()


@dataclass
class Node:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: NodeSpec = field(default_factory=NodeSpec)
    status: NodeStatus = field(default_factory=NodeStatus)

    @property
    def name(self) -> str:
        return self.metadata.name


# ---------------------------------------------------------------------------
# gang scheduling Workload API (reference:
# staging/src/k8s.io/api/scheduling/v1alpha1/types.go:82 `Workload`)


@dataclass
class PodGroup:
    """One gang within a Workload: schedule all-or-nothing once at least
    min_count member pods are available (reference gangscheduling.go:120-158)."""

    name: str
    min_count: int


@dataclass
class Workload:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    pod_groups: list[PodGroup] = field(default_factory=list)


def pod_group_key(pod: Pod) -> str:
    """Identity of the gang a pod belongs to ("" = not gang-scheduled)."""
    return pod.spec.workload_ref or pod.metadata.labels.get("scheduling.k8s.io/workload", "")


# ---------------------------------------------------------------------------
# Dynamic Resource Allocation (reference: staging/src/k8s.io/api/resource/
# v1/types.go — ResourceSlice, ResourceClaim with structured parameters;
# consumed by plugins/dynamicresources/, registry.go:48)


@dataclass(frozen=True)
class Device:
    """resource/v1 Device (basic): a named device with string attributes
    (the structured-parameters selector surface)."""

    name: str
    attributes: tuple[tuple[str, str], ...] = ()

    def attr(self, key: str) -> Optional[str]:
        for k, v in self.attributes:
            if k == key:
                return v
        return None


@dataclass
class ResourceSlice:
    """resource/v1 ResourceSlice: one node's published device pool for one
    driver (types.go ResourceSliceSpec: nodeName + driver + devices)."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    node_name: str = ""
    driver: str = ""
    devices: list[Device] = field(default_factory=list)

    @property
    def name(self) -> str:
        return self.metadata.name


@dataclass
class DeviceRequest:
    """resource/v1 DeviceRequest (exactly-count mode): ask `count` devices
    of `driver` whose attributes match every selector entry."""

    name: str = "req-0"
    driver: str = ""
    count: int = 1
    selectors: dict[str, str] = field(default_factory=dict)

    def matches(self, device: Device) -> bool:
        return all(device.attr(k) == v for k, v in self.selectors.items())


@dataclass
class DeviceAllocation:
    """resource/v1 AllocationResult (reduced): which devices on which node
    satisfied each request."""

    node_name: str = ""
    # request name → (driver, device name) tuples
    results: dict[str, tuple[tuple[str, str], ...]] = field(default_factory=dict)

    def device_ids(self) -> set[tuple[str, str, str]]:
        """(node, driver, device) ids this allocation occupies."""
        return {(self.node_name, drv, dev)
                for devs in self.results.values() for (drv, dev) in devs}


@dataclass
class ResourceClaim:
    """resource/v1 ResourceClaim: device requests + allocation status."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    requests: list[DeviceRequest] = field(default_factory=list)
    allocation: Optional[DeviceAllocation] = None   # status.allocation
    reserved_for: list[str] = field(default_factory=list)  # pod uids

    @property
    def name(self) -> str:
        return self.metadata.name

    @property
    def namespace(self) -> str:
        return self.metadata.namespace

    @property
    def uid(self) -> str:
        return self.metadata.uid


# ---------------------------------------------------------------------------
# PodDisruptionBudget (reference: staging/src/k8s.io/api/policy/v1/types.go
# PodDisruptionBudget; consumed by preemption's PDB-violating victim
# partition, pkg/scheduler/framework/preemption/preemption.go:658)


@dataclass
class PodDisruptionBudget:
    """policy/v1 PDB, the subset preemption reads: a selector over pods in
    the PDB's namespace plus one of min_available / max_unavailable
    (int or "N%" string). `disruptions_allowed` mirrors
    status.disruptionsAllowed and is computed by the API server's mini
    disruption controller at list time (the reference scheduler likewise
    trusts the controller-written status, preemption.go:700)."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    selector: Optional[LabelSelector] = None
    min_available: Optional[int | str] = None
    max_unavailable: Optional[int | str] = None
    disruptions_allowed: int = 0

    @property
    def name(self) -> str:
        return self.metadata.name

    @property
    def namespace(self) -> str:
        return self.metadata.namespace

    @property
    def uid(self) -> str:
        return self.metadata.uid

    def matches(self, pod: Pod) -> bool:
        if pod.metadata.namespace != self.metadata.namespace:
            return False
        if self.selector is None:
            return False  # nil selector matches no pods (policy/v1 semantics)
        return self.selector.matches(pod.metadata.labels)


def _resolve_maybe_percent(value: int | str, total: int,
                           round_up: bool = False) -> int:
    """IntOrString fields (GetScaledValueFromIntOrPercent): the disruption
    controller resolves percentage minAvailable with roundUp=true — a "50%"
    of 3 pods protects 2 — while maxUnavailable keeps the floor. Callers
    pick the direction."""
    if isinstance(value, str) and value.endswith("%"):
        pct = int(value[:-1]) * total
        return (pct + 99) // 100 if round_up else pct // 100
    return int(value)
