"""Resource quantities and the resource-dimension table.

The reference stores quantities as `resource.Quantity` (apimachinery) and the
scheduler flattens them into int64 MilliCPU/Memory/EphemeralStorage plus a
ScalarResources map (pkg/scheduler/framework/types.go `Resource`). We keep
that flattening but go one step further: every resource name is interned into
a fixed column index of the device-resident (nodes × resources) matrices, so
the whole fit check is one int64 compare-and-reduce on the TPU.

Canonical units: cpu → milli-cores, memory/ephemeral-storage/hugepages →
bytes, pods and extended resources → unit count. All int64.
"""

from __future__ import annotations

import functools
import math
import re
from dataclasses import dataclass, field

# well-known resource names (reference: core/v1 types.go ResourceCPU etc.)
CPU = "cpu"
MEMORY = "memory"
EPHEMERAL_STORAGE = "ephemeral-storage"
PODS = "pods"

# Fixed column order for the first four dims of every resource matrix.
# Extended resources are interned after these.
WELL_KNOWN = (CPU, MEMORY, EPHEMERAL_STORAGE, PODS)
CPU_IDX, MEM_IDX, STORAGE_IDX, PODS_IDX = 0, 1, 2, 3

# Reference: pkg/scheduler/util/pod_resources.go (DefaultMilliCPURequest /
# DefaultMemoryRequest): non-zero defaults used by LeastAllocated /
# BalancedAllocation via NodeInfo.NonZeroRequested.
DEFAULT_MILLI_CPU_REQUEST = 100
DEFAULT_MEMORY_REQUEST = 200 * 1024 * 1024

_SUFFIX = {
    "k": 10**3, "M": 10**6, "G": 10**9, "T": 10**12, "P": 10**15, "E": 10**18,
    "Ki": 2**10, "Mi": 2**20, "Gi": 2**30, "Ti": 2**40, "Pi": 2**50, "Ei": 2**60,
}
_QTY_RE = re.compile(r"^([0-9]*\.?[0-9]+)(m|[kMGTPE]i?)?$")


def _ceil(x: float) -> int:
    """Quantity.Value()/MilliValue() round fractional values up; guard float
    noise (1.5*1000 → 1500.0000000000002) before ceiling."""
    return math.ceil(x - 1e-9)


@functools.lru_cache(maxsize=8192)
def _parse_quantity_str(value: str, resource: str) -> int:
    m = _QTY_RE.match(value.strip())
    if not m:
        raise ValueError(f"unparseable quantity {value!r}")
    num, suffix = float(m.group(1)), m.group(2)
    if suffix == "m":
        if resource == CPU:
            return _ceil(num)
        return _ceil(num / 1000)
    scaled = num * _SUFFIX.get(suffix, 1)
    if resource == CPU:
        return _ceil(scaled * 1000)
    return _ceil(scaled)


def parse_quantity(value: str | int | float, resource: str = "") -> int:
    """Parse a k8s quantity string into canonical int64 units.

    "100m" cpu → 100; "2" cpu → 2000; "1Gi" → 2**30; "500M" → 5e8.
    ints/floats: cpu means cores (→ milli), others pass through.
    Fractional values round UP like Quantity.Value()/MilliValue().
    """
    if isinstance(value, int):
        return value * 1000 if resource == CPU else value
    if isinstance(value, float):
        return _ceil(value * 1000) if resource == CPU else _ceil(value)
    return _parse_quantity_str(value, resource)


def parse_resource_dict(d: dict[str, str | int | float]) -> dict[str, int]:
    return {name: parse_quantity(v, name) for name, v in d.items()}


@dataclass
class ResourceTable:
    """Interns resource names → column indices of the device matrices.

    Static width R: growing past R forces a re-pad + recompile, so R defaults
    comfortably above the usual cpu/memory/storage/pods + a few extended
    resources. The first four columns are always WELL_KNOWN.
    """

    width: int = 16
    names: list[str] = field(default_factory=lambda: list(WELL_KNOWN))
    index: dict[str, int] = field(default_factory=lambda: {n: i for i, n in enumerate(WELL_KNOWN)})

    def intern(self, name: str) -> int:
        idx = self.index.get(name)
        if idx is None:
            idx = len(self.names)
            if idx >= self.width:
                # grow to the next power of two; snapshot will re-pad.
                self.width *= 2
            self.names.append(name)
            self.index[name] = idx
        return idx

    def vector(self, requests: dict[str, int]) -> list[int]:
        """Dense row for a request dict (interning unseen names)."""
        idxs = [(self.intern(name), v) for name, v in requests.items()]
        row = [0] * self.width  # sized after interning: intern() may grow width
        for i, v in idxs:
            row[i] = v
        return row


def max_resource_list(a: dict[str, int], b: dict[str, int]) -> dict[str, int]:
    """Element-wise max, used for init-container folding."""
    out = dict(a)
    for k, v in b.items():
        if v > out.get(k, 0):
            out[k] = v
    return out


def add_resource_list(a: dict[str, int], b: dict[str, int]) -> dict[str, int]:
    out = dict(a)
    for k, v in b.items():
        out[k] = out.get(k, 0) + v
    return out


def pod_requests(pod) -> dict[str, int]:
    """Total scheduling-relevant request of a pod.

    Reference: k8s.io/component-helpers resource.PodRequests as used by
    noderesources computePodResourceRequest (fit.go:305): sum of container
    requests, element-wise max with init containers, plus overhead.

    Memoized on the PodSpec (clones share it): computed once per pod no
    matter how many times the queue/builder/cache ask. Treat the returned
    dict as read-only.
    """
    spec = pod.spec
    cached = getattr(spec, "_requests_cache", None)
    if cached is not None:
        return cached
    total: dict[str, int] = {}
    for c in spec.containers:
        total = add_resource_list(total, c.requests)
    for ic in spec.init_containers:
        total = max_resource_list(total, ic.requests)
    if spec.overhead:
        total = add_resource_list(total, spec.overhead)
    try:
        spec._requests_cache = total
    except AttributeError:
        pass
    return total


def _with_nonmissing_defaults(requests: dict[str, int]) -> dict[str, int]:
    # Go only substitutes when the key is ABSENT: an explicit 0 request stays 0.
    out = dict(requests)
    if CPU not in out:
        out[CPU] = DEFAULT_MILLI_CPU_REQUEST
    if MEMORY not in out:
        out[MEMORY] = DEFAULT_MEMORY_REQUEST
    return out


def pod_requests_nonmissing(pod) -> dict[str, int]:
    """Pod requests where every container missing a cpu/memory request gets
    the default (100m / 200Mi) — per container, as resourcehelper.PodRequests
    with NonMissingContainerRequests does (reference:
    noderesources/resource_allocation.go:234-241, and framework/types.go
    calculateResource feeding NodeInfo.NonZeroRequested).
    """
    total: dict[str, int] = {}
    for c in pod.spec.containers:
        total = add_resource_list(total, _with_nonmissing_defaults(c.requests))
    for ic in pod.spec.init_containers:
        total = max_resource_list(total, _with_nonmissing_defaults(ic.requests))
    if pod.spec.overhead:
        total = add_resource_list(total, pod.spec.overhead)
    return total


def pod_requests_nonzero(pod) -> tuple[int, int]:
    """(milli_cpu, memory) contribution to NodeInfo.NonZeroRequested.
    Memoized on the PodSpec like pod_requests."""
    spec = pod.spec
    cached = getattr(spec, "_nonzero_cache", None)
    if cached is not None:
        return cached
    req = pod_requests_nonmissing(pod)
    out = (req.get(CPU, 0), req.get(MEMORY, 0))
    try:
        spec._nonzero_cache = out
    except AttributeError:
        pass
    return out
