"""Cache debugger: compare scheduler state against API-server truth + dump.

Mirrors pkg/scheduler/backend/cache/debugger/ (debugger.go:31-76,
comparer.go, dumper.go): on demand (SIGUSR2 in the reference; an explicit
`compare()`/`dump()` call or the server's debug endpoint here), the host
cache's nodes and pods are diffed against the API server's — the safety net
for cache-vs-informer divergence. The TPU build already has a second
comparer layer (Scheduler.reconcile: device carry vs host cache); this one
closes the remaining gap (host cache vs apiserver).
"""

from __future__ import annotations

from typing import Optional

from ..utils.logging import klog


class CacheDebugger:
    def __init__(self, client, cache, queue, metrics=None):
        self.client = client
        self.cache = cache
        self.queue = queue
        self.metrics = metrics

    # -- comparer (comparer.go CompareNodes/ComparePods) ----------------------

    def compare(self) -> list[str]:
        """Returns human-readable discrepancy strings ([] = clean)."""
        out: list[str] = []
        # nodes: every apiserver node must be cached, and vice versa
        # (imputed placeholder entries are cache-internal, not divergence)
        api_nodes = set(self.client.nodes)
        cached = {name for name, item in self.cache.nodes.items()
                  if name not in self.cache._imputed_nodes}
        for name in sorted(api_nodes - cached):
            out.append(f"node {name} in apiserver but not in cache")
        for name in sorted(cached - api_nodes):
            out.append(f"node {name} in cache but not in apiserver")
        # pods: bound pods must agree on existence and placement; assumed
        # (not yet confirmed) pods are expected to lead the apiserver
        api_bound = {uid: p for uid, p in self.client.pods.items()
                     if p.spec.node_name}
        for uid, p in api_bound.items():
            ps = self.cache.pod_states.get(uid)
            if ps is None:
                out.append(f"pod {uid} bound to {p.spec.node_name} in "
                           "apiserver but not in cache")
            elif ps.pod.spec.node_name != p.spec.node_name:
                out.append(f"pod {uid} on {ps.pod.spec.node_name} in cache "
                           f"but {p.spec.node_name} in apiserver")
        for uid, ps in self.cache.pod_states.items():
            if uid in self.cache.assumed_pods:
                continue  # optimistic entries lead the apiserver by design
            if uid not in api_bound:
                out.append(f"pod {uid} in cache but not bound in apiserver")
        if out:
            if self.metrics is not None:
                self.metrics.cache_divergence.inc("host_vs_apiserver",
                                                  by=len(out))
            for line in out:
                klog.warning("cache divergence", detail=line)
        else:
            klog.v(4).info("cache comparer: clean",
                           nodes=len(api_nodes), pods=len(api_bound))
        return out

    # -- dumper (dumper.go) ----------------------------------------------------

    def dump(self) -> dict:
        """Cache + queue snapshot for post-mortems (dumper.go dumps to the
        log; returning the structure keeps it testable — the server's
        debug endpoint serializes it)."""
        pending, summary = self.queue.pending_pods()
        return {
            "cache": self.cache.dump(),
            "queue": {"summary": summary,
                      "pending": [p.uid for p in pending]},
        }
