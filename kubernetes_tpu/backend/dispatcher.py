"""Async API dispatcher: deferred, deduped API calls off the hot path.

Mirrors pkg/scheduler/backend/api_dispatcher/:
- typed calls with Relevance ordering (framework/api_calls/api_calls.go:33:
  a newer call for the same object either replaces or is suppressed by the
  pending one)
- the scheduler enqueues and keeps going; `flush()` executes the queue
  (the reference uses worker goroutines; at 50k binds/s the batching —
  not the threading — is what decouples device throughput from API latency,
  so the single-threaded deferred model keeps the semantics and the perf
  property while staying GIL-friendly)
- api_cache facade semantics: queue/cache observe call effects immediately
  because the scheduler assumes pods before enqueueing the bind.

Failed binds invoke the scheduler's forget/requeue path exactly like
bindingCycle error handling (schedule_one.go:361-393).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Callable, Optional

from ..api.types import Pod


class CallType(str, enum.Enum):
    BIND = "pod_binding"
    STATUS_PATCH = "pod_status_patch"
    DELETE = "pod_delete"


# relevance ordering (api_calls.go Relevances): a BIND replaces a pending
# STATUS_PATCH for the same pod; a STATUS_PATCH never replaces a BIND; a
# DELETE (preemption victim) supersedes everything for that pod.
_RELEVANCE = {CallType.STATUS_PATCH: 1, CallType.BIND: 2, CallType.DELETE: 3}


@dataclass
class APICall:
    call_type: CallType
    pod: Pod
    node_name: str = ""
    condition: Optional[dict] = None
    # None = leave unchanged; "" = clear (preemption demotion)
    nominated_node_name: Optional[str] = None


@dataclass
class APIDispatcher:
    client: object  # APIServer-shaped
    on_bind_error: Optional[Callable[[Pod, str, Exception], None]] = None
    metrics: Optional[object] = None  # SchedulerMetrics (api_dispatcher_calls)
    _queue: dict[str, APICall] = field(default_factory=dict)  # uid → pending
    executed: int = 0
    errors: int = 0

    def add(self, call: APICall) -> None:
        uid = call.pod.uid
        pending = self._queue.get(uid)
        if pending is not None:
            if _RELEVANCE[call.call_type] < _RELEVANCE[pending.call_type]:
                return  # less relevant than what's queued: suppress
        self._queue[uid] = call

    def flush(self) -> int:
        """Execute all pending calls; returns count executed."""
        calls = list(self._queue.values())
        self._queue.clear()
        for call in calls:
            try:
                if call.call_type == CallType.BIND:
                    self.client.bind(call.pod, call.node_name)
                elif call.call_type == CallType.DELETE:
                    self.client.delete_pod(call.pod.uid)
                else:
                    self.client.patch_pod_status(
                        call.pod, call.condition or {},
                        call.nominated_node_name)
                self.executed += 1
                if self.metrics is not None:
                    self.metrics.api_dispatcher_calls.inc(
                        call.call_type.value, "success")
            except Exception as e:
                self.errors += 1
                if self.metrics is not None:
                    self.metrics.api_dispatcher_calls.inc(
                        call.call_type.value, "error")
                if (call.call_type == CallType.BIND
                        and self.on_bind_error is not None):
                    self.on_bind_error(call.pod, call.node_name, e)
        return len(calls)

    def is_delete_pending(self, uid: str) -> bool:
        """A victim whose DELETE is queued but not flushed is the in-memory
        analog of a terminating pod (preemption.go:431 eligibility)."""
        pending = self._queue.get(uid)
        return pending is not None and pending.call_type == CallType.DELETE

    def __len__(self) -> int:
        return len(self._queue)
