"""Async API dispatcher: deferred, deduped API calls off the hot path.

Mirrors pkg/scheduler/backend/api_dispatcher/:
- typed calls with Relevance ordering (framework/api_calls/api_calls.go:33:
  a newer call for the same object either replaces or is suppressed by the
  pending one)
- the scheduler enqueues and keeps going; `flush()` executes the queue
  (the reference uses worker goroutines; at 50k binds/s the batching —
  not the threading — is what decouples device throughput from API latency,
  so the single-threaded deferred model keeps the semantics and the perf
  property while staying GIL-friendly)
- api_cache facade semantics: queue/cache observe call effects immediately
  because the scheduler assumes pods before enqueueing the bind.

Failed binds invoke the scheduler's forget/requeue path exactly like
bindingCycle error handling (schedule_one.go:361-393).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Callable, Optional

from ..api.types import Pod


class CallType(str, enum.Enum):
    BIND = "pod_binding"
    STATUS_PATCH = "pod_status_patch"
    DELETE = "pod_delete"


# relevance ordering (api_calls.go Relevances): a BIND replaces a pending
# STATUS_PATCH for the same pod; a STATUS_PATCH never replaces a BIND; a
# DELETE (preemption victim) supersedes everything for that pod.
_RELEVANCE = {CallType.STATUS_PATCH: 1, CallType.BIND: 2, CallType.DELETE: 3}


@dataclass
class APICall:
    call_type: CallType
    pod: Pod
    node_name: str = ""
    condition: Optional[dict] = None
    # None = leave unchanged; "" = clear (preemption demotion)
    nominated_node_name: Optional[str] = None


@dataclass
class APIDispatcher:
    client: object  # APIServer-shaped
    on_bind_error: Optional[Callable[[Pod, str, Exception], None]] = None
    metrics: Optional[object] = None  # SchedulerMetrics (api_dispatcher_calls)
    _queue: dict[str, APICall] = field(default_factory=dict)  # uid → pending
    # bulk fast path: (bound pod, the original object it was derived from)
    _binds: list[tuple[Pod, Pod]] = field(default_factory=list)
    executed: int = 0
    errors: int = 0

    def add(self, call: APICall) -> None:
        uid = call.pod.uid
        pending = self._queue.get(uid)
        if pending is not None:
            if _RELEVANCE[call.call_type] < _RELEVANCE[pending.call_type]:
                return  # less relevant than what's queued: suppress
            if (call.call_type == CallType.STATUS_PATCH
                    and pending.call_type == CallType.STATUS_PATCH):
                # merge, don't replace (reference call_queue.go Merge): the
                # newer condition wins, but an unset nominated_node_name
                # must not drop the pending call's
                if call.nominated_node_name is None:
                    call.nominated_node_name = pending.nominated_node_name
                if call.condition is None:
                    call.condition = pending.condition
        self._queue[uid] = call

    def add_binds(self, pairs: list) -> None:
        """Bulk enqueue of bind calls: (assumed pod with node set, the
        original object it was derived from). The hot path of the batch
        commit: one list extend instead of B dict transactions. The
        original lets bind_all prove by identity that no interleaved
        update landed, and reuse the assumed copy as the stored object."""
        if self._queue:
            # a bind supersedes a pending patch — but never a DELETE,
            # which outranks it (same relevance ordering as add())
            for pair in pairs:
                pending = self._queue.get(pair[0].uid)
                if pending is not None:
                    if pending.call_type == CallType.DELETE:
                        continue
                    del self._queue[pair[0].uid]
                self._binds.append(pair)
            return
        self._binds.extend(pairs)

    def flush(self) -> int:
        """Execute all pending calls; returns count executed."""
        n_bulk = 0
        if self._binds:
            binds = self._binds
            self._binds = []
            n_bulk = len(binds)
            if hasattr(self.client, "bind_all"):
                failures = self.client.bind_all(binds)
            else:
                failures = []
                for p, _orig in binds:
                    try:
                        self.client.bind(p, p.spec.node_name)
                    except Exception as e:
                        failures.append((p, e))
            n_fail = len(failures)
            self.executed += n_bulk - n_fail
            self.errors += n_fail
            if self.metrics is not None:
                if n_bulk - n_fail:
                    self.metrics.api_dispatcher_calls.inc(
                        CallType.BIND.value, "success", by=n_bulk - n_fail)
                if n_fail:
                    self.metrics.api_dispatcher_calls.inc(
                        CallType.BIND.value, "error", by=n_fail)
            for pod, e in failures:
                if self.on_bind_error is not None:
                    self.on_bind_error(pod, pod.spec.node_name, e)
        calls = list(self._queue.values())
        self._queue.clear()
        for call in calls:
            try:
                if call.call_type == CallType.BIND:
                    self.client.bind(call.pod, call.node_name)
                elif call.call_type == CallType.DELETE:
                    self.client.delete_pod(call.pod.uid)
                else:
                    self.client.patch_pod_status(
                        call.pod, call.condition or {},
                        call.nominated_node_name)
                self.executed += 1
                if self.metrics is not None:
                    self.metrics.api_dispatcher_calls.inc(
                        call.call_type.value, "success")
            except Exception as e:
                self.errors += 1
                if self.metrics is not None:
                    self.metrics.api_dispatcher_calls.inc(
                        call.call_type.value, "error")
                if (call.call_type == CallType.BIND
                        and self.on_bind_error is not None):
                    self.on_bind_error(call.pod, call.node_name, e)
        return len(calls) + n_bulk

    def is_delete_pending(self, uid: str) -> bool:
        """A victim whose DELETE is queued but not flushed is the in-memory
        analog of a terminating pod (preemption.go:431 eligibility)."""
        pending = self._queue.get(uid)
        return pending is not None and pending.call_type == CallType.DELETE

    def __len__(self) -> int:
        return len(self._queue) + len(self._binds)
