"""Async API dispatcher: deferred, deduped API calls off the hot path.

Mirrors pkg/scheduler/backend/api_dispatcher/:
- typed calls with Relevance ordering (framework/api_calls/api_calls.go:33:
  a newer call for the same object either replaces or is suppressed by the
  pending one)
- the scheduler enqueues and keeps going; `flush()` executes the queue
  (the reference uses worker goroutines; at 50k binds/s the batching —
  not the threading — is what decouples device throughput from API latency,
  so the single-threaded deferred model keeps the semantics and the perf
  property while staying GIL-friendly)
- api_cache facade semantics: queue/cache observe call effects immediately
  because the scheduler assumes pods before enqueueing the bind.

Error handling mirrors client-go: retriable errors (ServerTimeout /
TooManyRequests / ServiceUnavailable — the call did not take effect) retry
with exponential backoff + jitter under a per-call attempt budget; terminal
errors (Conflict, NotFound, anything untyped) route to the scheduler's
forget/requeue path exactly like bindingCycle error handling
(schedule_one.go:361-393). DELETE (preemption victim) calls retry too, so
a transient hiccup cannot half-commit a preemptor wave.

`flush()` executes pending DELETEs BEFORE the bulk binds: a preemptor
wave's victims leave the store before their preemptors bind, matching the
reference's relevance ordering end to end (not just within the queue).
"""

from __future__ import annotations

import enum
import random
import threading
import time as _time
from dataclasses import dataclass, field
from typing import Callable, Optional

from ..api.types import Pod
from ..obs.journey import EV_BIND_FLUSH as _EV_BIND_FLUSH
from .apiserver import LEASE_NAME, Conflict, FencedWrite, is_retriable


def _fence_pairs(token) -> tuple:
    """Normalize a fence token (int / (lease, gen) pair / tuple of pairs —
    the three forms APIServer.check_fence accepts) to a tuple of pairs."""
    if isinstance(token, int):
        return ((LEASE_NAME, token),)
    if token and isinstance(token[0], str):
        return (token,)
    return tuple(token)


def _fence_min(a, b):
    """Merge two fence tokens conservatively: per lease, keep the OLDEST
    generation seen (generations are monotonic, so the oldest token is the
    strictest — a batch spanning a depose boundary fails entirely). Two
    ints stay an int (the single-lease legacy form); any other mix
    normalizes to a sorted tuple of (lease, generation) pairs."""
    if a is None:
        return b
    if b is None:
        return a
    if isinstance(a, int) and isinstance(b, int):
        return min(a, b)
    merged: dict = {}
    for name, gen in _fence_pairs(a) + _fence_pairs(b):
        if name not in merged or gen < merged[name]:
            merged[name] = gen
    return tuple(sorted(merged.items()))


def backoff_delay(attempt: int, base: float, cap: float,
                  rng: random.Random) -> float:
    """Exponential backoff with equal jitter (client-go wait.Backoff
    shape): base·2^attempt capped, then scaled into [0.5, 1.0). Shared by
    the dispatcher's retry loop and the leader elector's acquire retry
    (ha/lease.py) so every client-side retry in the system jitters the
    same way."""
    d = min(base * (2.0 ** attempt), cap)
    return d * (0.5 + 0.5 * rng.random())


class CallType(str, enum.Enum):
    BIND = "pod_binding"
    STATUS_PATCH = "pod_status_patch"
    DELETE = "pod_delete"


# relevance ordering (api_calls.go Relevances): a BIND replaces a pending
# STATUS_PATCH for the same pod; a STATUS_PATCH never replaces a BIND; a
# DELETE (preemption victim) supersedes everything for that pod.
_RELEVANCE = {CallType.STATUS_PATCH: 1, CallType.BIND: 2, CallType.DELETE: 3}


@dataclass
class APICall:
    call_type: CallType
    pod: Pod
    node_name: str = ""
    condition: Optional[dict] = None
    # None = leave unchanged; "" = clear (preemption demotion)
    nominated_node_name: Optional[str] = None
    # fencing token stamped at ENQUEUE time: a call enqueued before the
    # leader was deposed keeps its stale token, so the API server rejects
    # it even if the flush happens much later. Any check_fence form: int
    # (single-lease legacy) or (lease, generation) pair(s).
    fence_token: Optional[object] = None


@dataclass
class APIDispatcher:
    client: object  # APIServer-shaped
    on_bind_error: Optional[Callable[[Pod, str, Exception], None]] = None
    metrics: Optional[object] = None  # SchedulerMetrics (api_dispatcher_calls)
    # obs/journey.py ledger (attached by the scheduler): bind_enqueue /
    # bind_flush transitions + the commit_backlog clock start
    journey: Optional[object] = None
    # retry policy (config knobs apiRetryMaxAttempts/apiRetryBaseSeconds):
    # attempt budget INCLUDES the first try; base doubles per retry with
    # equal jitter, capped at retry_max_delay_seconds
    retry_max_attempts: int = 5
    retry_base_seconds: float = 0.02
    retry_max_delay_seconds: float = 1.0
    sleep: Callable[[float], None] = _time.sleep
    _rng: random.Random = field(default_factory=lambda: random.Random(0))
    # the scheduler enqueues and flushes single-threaded, but __len__ is
    # read by the metrics HTTP thread (dispatcher_inflight callback
    # gauge): the RLock covers the pending structures; execution happens
    # on snapshots taken under it (so retry backoff sleeps never block a
    # scrape), and reentrant on_bind_error callbacks stay safe
    _lock: threading.RLock = field(default_factory=threading.RLock)
    _queue: dict[str, APICall] = field(default_factory=dict)   # guarded_by: _lock
    # bulk fast path: (bound pod, the original object it was derived from)
    _binds: list[tuple[Pod, Pod]] = field(default_factory=list)  # guarded_by: _lock
    # fencing-token provider (ha/fencing.py wires the elector's current
    # lease generation): consulted at enqueue time, None = unfenced
    fence: Optional[Callable[[], Optional[int]]] = None
    # per-pod fencing provider (sharded control plane): one instance may
    # hold MULTIPLE shard leases, so the right token depends on which pod
    # is being written. Takes precedence over `fence` when set; returns
    # any check_fence token form (usually a (lease, generation) pair).
    fence_for: Optional[Callable[[Pod], Optional[object]]] = None
    # the OLDEST token per lease among bulk binds enqueued since the last
    # flush: generations are monotonic, so fencing the whole bulk batch at
    # the oldest token is conservative — a batch spanning a depose
    # boundary fails entirely and every member requeues via on_bind_error
    _bind_fence: Optional[object] = None   # guarded_by: _lock
    executed: int = 0
    errors: int = 0
    retries: int = 0
    fenced: int = 0

    def _stamp(self, call: APICall) -> APICall:
        if call.fence_token is None:
            if self.fence_for is not None:
                call.fence_token = self.fence_for(call.pod)
            elif self.fence is not None:
                call.fence_token = self.fence()
        return call

    def add(self, call: APICall) -> None:
        self._stamp(call)
        uid = call.pod.uid
        if call.call_type == CallType.BIND and self.journey is not None:
            self.journey.bind_enqueued([uid], self.journey.clock())
        with self._lock:
            pending = self._queue.get(uid)
            if pending is not None:
                if _RELEVANCE[call.call_type] < _RELEVANCE[pending.call_type]:
                    # less relevant than what's queued: suppress. A BIND
                    # suppressed by a pending DELETE carries an assumed pod —
                    # silently dropping it would leak the assume; route it
                    # through the forget/requeue path like a failed bind.
                    if (call.call_type == CallType.BIND
                            and pending.call_type == CallType.DELETE
                            and self.on_bind_error is not None):
                        self.on_bind_error(call.pod, call.node_name, Conflict(
                            f"bind of {uid} superseded by pending delete"))
                    return
                if (call.call_type == CallType.STATUS_PATCH
                        and pending.call_type == CallType.STATUS_PATCH):
                    # merge, don't replace (reference call_queue.go Merge):
                    # the newer condition wins, but an unset
                    # nominated_node_name must not drop the pending call's
                    if call.nominated_node_name is None:
                        call.nominated_node_name = pending.nominated_node_name
                    if call.condition is None:
                        call.condition = pending.condition
            self._queue[uid] = call

    def add_binds(self, pairs: list) -> None:
        """Bulk enqueue of bind calls: (assumed pod with node set, the
        original object it was derived from). The hot path of the batch
        commit: one list extend instead of B dict transactions. The
        original lets bind_all prove by identity that no interleaved
        update landed, and reuse the assumed copy as the stored object."""
        if self.journey is not None and pairs:
            self.journey.bind_enqueued([pair[0].uid for pair in pairs],
                                       self.journey.clock())
        if self.fence_for is not None:
            token = None
            for pair in pairs:
                token = _fence_min(token, self.fence_for(pair[0]))
        else:
            token = self.fence() if self.fence is not None else None
        with self._lock:
            if token is not None:
                self._bind_fence = _fence_min(self._bind_fence, token)
            if self._queue:
                # a bind supersedes a pending patch — but never a DELETE,
                # which outranks it (same relevance ordering as add()). The
                # superseded pod was already assumed: forget/requeue it
                # instead of leaking the assume.
                for pair in pairs:
                    pending = self._queue.get(pair[0].uid)
                    if pending is not None:
                        if pending.call_type == CallType.DELETE:
                            if self.on_bind_error is not None:
                                self.on_bind_error(
                                    pair[0], pair[0].spec.node_name, Conflict(
                                        f"bind of {pair[0].uid} superseded by "
                                        "pending delete"))
                            continue
                        del self._queue[pair[0].uid]
                    self._binds.append(pair)
                return
            self._binds.extend(pairs)

    # -- retry machinery ------------------------------------------------------

    def _backoff(self, attempt: int) -> float:
        """Exponential backoff with equal jitter (client-go wait.Backoff
        shape): base·2^attempt capped, then scaled into [0.5, 1.0)."""
        return backoff_delay(attempt, self.retry_base_seconds,
                             self.retry_max_delay_seconds, self._rng)

    def _count_fenced(self, e: Exception) -> None:
        if isinstance(e, FencedWrite):
            self.fenced += 1
            if self.metrics is not None:
                self.metrics.fenced_writes_rejected.inc()

    def _count_retry(self, call_type: CallType) -> None:
        self.retries += 1
        if self.metrics is not None:
            self.metrics.api_retries.inc(call_type.value)

    def _execute_with_retry(self, call_type: CallType,
                            fn: Callable[[], None]) -> Optional[Exception]:
        """Run one API call under the retry policy; returns the terminal
        exception (retriable exhausted or non-retriable) or None."""
        attempt = 0
        while True:
            try:
                fn()
                return None
            except Exception as e:
                if not is_retriable(e) or attempt + 1 >= self.retry_max_attempts:
                    return e
                self._count_retry(call_type)
                self.sleep(self._backoff(attempt))
                attempt += 1

    def _execute_binds(self, binds: list,
                       fence_token: Optional[int] = None
                       ) -> list[tuple[Pod, Exception]]:
        """Bulk bind with per-pod retry of the retriable failures; returns
        the terminal failures."""
        kw = {} if fence_token is None else {"fence_token": fence_token}
        terminal: list[tuple[Pod, Exception]] = []
        pending = binds
        attempt = 0
        while pending:
            if hasattr(self.client, "bind_all"):
                failures = self.client.bind_all(pending, **kw)
            else:
                failures = []
                for p, _orig in pending:
                    try:
                        self.client.bind(p, p.spec.node_name, **kw)
                    except Exception as e:
                        failures.append((p, e))
            if not failures:
                return terminal
            by_uid = {pair[0].uid: pair for pair in pending}
            retry = []
            for p, e in failures:
                if is_retriable(e) and attempt + 1 < self.retry_max_attempts:
                    self._count_retry(CallType.BIND)
                    retry.append(by_uid[p.uid])
                else:
                    terminal.append((p, e))
            if retry:
                self.sleep(self._backoff(attempt))
                attempt += 1
            pending = retry
        return terminal

    # -- flush ----------------------------------------------------------------

    def flush(self) -> int:
        """Execute all pending calls; returns count executed. Order:
        queued DELETEs (preemption victims) → bulk binds → everything
        else (single binds, status patches). Calls execute on snapshots
        taken under the lock — never while holding it (retry backoff
        sleeps must not block the metrics thread's __len__)."""
        n = 0
        with self._lock:
            deletes = [c for c in self._queue.values()
                       if c.call_type == CallType.DELETE]
            for c in deletes:
                del self._queue[c.pod.uid]
        if deletes:
            n += self._execute_calls(deletes)
        n += self._flush_bulk_binds()
        with self._lock:
            calls = list(self._queue.values())
            self._queue.clear()
        if calls:
            n += self._execute_calls(calls)
        return n

    def _flush_bulk_binds(self) -> int:
        with self._lock:
            binds = self._binds
            self._binds = []
            bind_fence = self._bind_fence
            self._bind_fence = None
        if not binds:
            return 0
        n_bulk = len(binds)
        # journey: flush recorded BEFORE execution — the API write is the
        # flush's effect, and the bind-echo confirm must sort after it
        if self.journey is not None:
            self.journey.record_bulk([pair[0].uid for pair in binds],
                                     _EV_BIND_FLUSH, self.journey.clock(),
                                     detail="bulk")
        failures = self._execute_binds(binds, fence_token=bind_fence)
        n_fail = len(failures)
        self.executed += n_bulk - n_fail
        self.errors += n_fail
        if self.metrics is not None:
            if n_bulk - n_fail:
                self.metrics.api_dispatcher_calls.inc(
                    CallType.BIND.value, "success", by=n_bulk - n_fail)
            if n_fail:
                self.metrics.api_dispatcher_calls.inc(
                    CallType.BIND.value, "error", by=n_fail)
        for pod, e in failures:
            self._count_fenced(e)
            if self.on_bind_error is not None:
                self.on_bind_error(pod, pod.spec.node_name, e)
        return n_bulk

    def _execute_calls(self, calls: list[APICall]) -> int:
        for call in calls:
            # fence kwarg only when stamped: stub clients in tests predate
            # the fence_token parameter, and None means unfenced anyway
            kw = ({} if call.fence_token is None
                  else {"fence_token": call.fence_token})
            if call.call_type == CallType.BIND:
                fn = lambda c=call: self.client.bind(c.pod, c.node_name, **kw)
            elif call.call_type == CallType.DELETE:
                fn = lambda c=call: self.client.delete_pod(c.pod.uid, **kw)
            else:
                fn = lambda c=call: self.client.patch_pod_status(
                    c.pod, c.condition or {}, c.nominated_node_name, **kw)
            if call.call_type == CallType.BIND and self.journey is not None:
                self.journey.record(call.pod.uid, _EV_BIND_FLUSH,
                                    self.journey.clock())
            err = self._execute_with_retry(call.call_type, fn)
            if err is None:
                self.executed += 1
                if self.metrics is not None:
                    self.metrics.api_dispatcher_calls.inc(
                        call.call_type.value, "success")
            else:
                self._count_fenced(err)
                self.errors += 1
                if self.metrics is not None:
                    self.metrics.api_dispatcher_calls.inc(
                        call.call_type.value, "error")
                if (call.call_type == CallType.BIND
                        and self.on_bind_error is not None):
                    self.on_bind_error(call.pod, call.node_name, err)
        return len(calls)

    def is_delete_pending(self, uid: str) -> bool:
        """A victim whose DELETE is queued but not flushed is the in-memory
        analog of a terminating pod (preemption.go:431 eligibility)."""
        with self._lock:
            pending = self._queue.get(uid)
        return pending is not None and pending.call_type == CallType.DELETE

    def __len__(self) -> int:
        with self._lock:
            return len(self._queue) + len(self._binds)
