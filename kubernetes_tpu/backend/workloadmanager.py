"""WorkloadManager: gang / pod-group runtime state.

Mirrors pkg/scheduler/backend/workloadmanager/ (workloadmanager.go:32-129,
podgroupinfo.go):
- `PodGroupInfo` tracks the four pod sets per gang — all / unscheduled /
  assumed (passed Reserve, parked at Permit) / assigned (bound) — plus the
  group scheduling deadline, initialized when the first pod reaches Permit.
- `WorkloadManager` is driven explicitly by the scheduler's pod event
  handlers (single-threaded host model: the reference's mutexes collapse
  into call ordering) and keyed by (namespace, workload, podGroup).

`pod.spec.workload_ref` is our WorkloadReference: `"name"` (the workload's
first/default pod group) or `"name/group"`.
"""

from __future__ import annotations

import time as _time
from dataclasses import dataclass, field
from typing import Callable, Optional

from ..api.types import Pod, Workload

# gangscheduling pods wait at Permit this long for quorum before rejection
# (podgroupinfo.go DefaultSchedulingTimeoutDuration)
DEFAULT_SCHEDULING_TIMEOUT = 300.0


def parse_workload_ref(ref: str) -> tuple[str, str]:
    """→ (workload name, pod group name; "" = the workload's first group)."""
    if "/" in ref:
        name, group = ref.split("/", 1)
        return name, group
    return ref, ""


@dataclass
class PodGroupInfo:
    """podgroupinfo.go podGroupInfo — the gang's runtime pod sets."""

    all_pods: dict[str, Pod] = field(default_factory=dict)
    unscheduled: set[str] = field(default_factory=set)
    assumed: set[str] = field(default_factory=set)
    assigned: set[str] = field(default_factory=set)
    scheduling_deadline: Optional[float] = None

    def add_pod(self, pod: Pod) -> None:
        self.all_pods[pod.uid] = pod
        if pod.spec.node_name:
            self.assigned.add(pod.uid)
        else:
            self.unscheduled.add(pod.uid)

    def update_pod(self, old: Pod, new: Pod) -> None:
        self.all_pods[new.uid] = new
        if not old.spec.node_name and new.spec.node_name:
            self.assigned.add(new.uid)
            self.unscheduled.discard(new.uid)
            self.assumed.discard(new.uid)

    def delete_pod(self, uid: str) -> None:
        self.all_pods.pop(uid, None)
        self.unscheduled.discard(uid)
        self.assumed.discard(uid)
        self.assigned.discard(uid)

    def assume_pod(self, uid: str) -> None:
        """Reserve stage: the pod holds resources and waits for the gang."""
        self.assumed.add(uid)
        self.unscheduled.discard(uid)

    def forget_pod(self, uid: str) -> None:
        """Unreserve: back to unscheduled, no longer quorum-eligible."""
        if uid in self.assumed:
            self.assumed.discard(uid)
            if uid in self.all_pods:
                self.unscheduled.add(uid)

    def empty(self) -> bool:
        return not self.all_pods

    def scheduling_timeout(self, now: float,
                           duration: float = DEFAULT_SCHEDULING_TIMEOUT
                           ) -> float:
        """Remaining wait budget; the deadline starts with the group's
        first Permit (podgroupinfo.go SchedulingTimeout)."""
        if self.scheduling_deadline is None:
            self.scheduling_deadline = now + duration
        return max(self.scheduling_deadline - now, 0.0)


class WorkloadManager:
    """workloadmanager.go:32 — source of truth for gang pod state."""

    def __init__(self, clock: Callable[[], float] = _time.monotonic):
        self.clock = clock
        self.pod_group_infos: dict[tuple[str, str, str], PodGroupInfo] = {}

    @staticmethod
    def _key(pod: Pod) -> Optional[tuple[str, str, str]]:
        ref = pod.spec.workload_ref
        if not ref:
            return None
        name, group = parse_workload_ref(ref)
        return (pod.namespace, name, group)

    def add_pod(self, pod: Pod) -> None:
        key = self._key(pod)
        if key is None:
            return
        self.pod_group_infos.setdefault(key, PodGroupInfo()).add_pod(pod)

    def update_pod(self, old: Pod, new: Pod) -> None:
        key = self._key(new)
        if key is None:
            return
        info = self.pod_group_infos.get(key)
        if info is None:
            self.pod_group_infos[key] = info = PodGroupInfo()
            info.add_pod(new)
            return
        info.update_pod(old, new)

    def delete_pod(self, pod: Pod) -> None:
        key = self._key(pod)
        if key is None:
            return
        info = self.pod_group_infos.get(key)
        if info is None:
            return
        info.delete_pod(pod.uid)
        if info.empty():
            del self.pod_group_infos[key]

    def pod_group_info(self, pod: Pod) -> Optional[PodGroupInfo]:
        key = self._key(pod)
        return self.pod_group_infos.get(key) if key else None


def pod_group_min_count(workload: Workload, group_name: str) -> Optional[int]:
    """gangscheduling.go podGroupPolicy: the group's MinCount, or None when
    the named group doesn't exist ("" = first group)."""
    for pg in workload.pod_groups:
        if not group_name or pg.name == group_name:
            return pg.min_count
    return None
