"""In-memory API server + clientset + informer fan-out.

The reference's entire distributed substrate is etcd + watch/list over HTTP/2
(SURVEY §2.7); its scheduler tests talk to an in-process apiserver
(test/integration, apiservertesting.StartTestServer) or a fake clientset with
an object tracker (client-go/kubernetes/fake). This module is both at once:
an object store with Binding/status subresources and synchronous watch
delivery to registered handlers — the process boundary collapses, the
interface shape stays.
"""

from __future__ import annotations

import dataclasses
import zlib as _zlib
from dataclasses import dataclass, field
from typing import Callable, Optional

from ..api.types import (Node, PersistentVolume, PersistentVolumeClaim,
                         Pod, PodDisruptionBudget, ResourceClaim,
                         ResourceSlice, StorageClass, Workload,
                         _resolve_maybe_percent)


class APIError(Exception):
    """Base of the in-memory server's typed errors (apierrors analog)."""


class Conflict(APIError):
    pass


class NotFound(APIError):
    pass


class ServerTimeout(APIError):
    """The server timed out before the call took effect (504-shaped,
    apierrors.IsServerTimeout). Retriable."""


class TooManyRequests(APIError):
    """429: the server sheds load (apierrors.IsTooManyRequests).
    Retriable."""


class ServiceUnavailable(APIError):
    """503: transient unavailability. Retriable."""


class FencedWrite(APIError):
    """A write carried a stale fencing token (lease generation): the
    caller was deposed as leader and a newer holder owns the lease.
    Deliberately TERMINAL — retrying cannot help (the generation only
    moves forward), so the dispatcher routes it through the same
    forget/requeue path as Conflict and the assume unwinds cleanly."""


# the retriable set mirrors client-go's shouldRetry classification
# (util/retry + apierrors.SuggestsClientDelay): the call did NOT take
# effect, so re-issuing it is safe. Conflict/NotFound are terminal — they
# describe state the caller must react to, not a server hiccup.
RETRIABLE_ERRORS = (ServerTimeout, TooManyRequests, ServiceUnavailable)


def is_retriable(err: Exception) -> bool:
    return isinstance(err, RETRIABLE_ERRORS)


# -- coordination.k8s.io/v1 Lease ------------------------------------------

LEASE_NAME = "kube-scheduler"


@dataclass
class Lease:
    """coordination.k8s.io/v1 Lease (consumed subset) + the fencing
    generation: a monotonic counter bumped on every holder CHANGE, handed
    to the new leader as its fencing token. A write stamped with an older
    generation is provably from a deposed leader and is rejected
    (FencedWrite) regardless of how long its flush was paused."""

    name: str = LEASE_NAME
    holder_identity: str = ""
    lease_duration_s: float = 15.0
    renew_time: float = 0.0
    lease_transitions: int = 0
    generation: int = 0


@dataclass
class ShardMap:
    """The control plane's shard topology: which scheduler shard owns
    which profile/namespace slice of the pod stream. Stored as ONE
    versioned API object (optimistic concurrency on `version`, writes
    fenced by the writer's lease generation) so every instance converges
    on the same answer to "whose pod is this?" — the assignment map IS
    the cross-shard routing table. Keys are `scheduler_name/namespace`;
    unknown keys fall back to a stable hash so new tenants land
    deterministically on the same shard from every instance."""

    num_shards: int = 1
    assignments: dict[str, int] = field(default_factory=dict)
    version: int = 0

    def shard_for(self, key: str) -> int:
        sid = self.assignments.get(key)
        if sid is not None and 0 <= sid < self.num_shards:
            return sid
        # process-independent fallback (hash() is salted per process)
        return _zlib.crc32(key.encode("utf-8")) % max(1, self.num_shards)


@dataclass
class WatchHandlers:
    """The informer event-handler triple (client-go ResourceEventHandler).
    `on_add_bulk` is an optional batch form consumed by create_pods —
    semantically equivalent to per-pod on_add calls in order."""

    on_add: Optional[Callable] = None
    on_update: Optional[Callable] = None
    on_delete: Optional[Callable] = None
    on_add_bulk: Optional[Callable] = None
    # optional batch form consumed by bind_all (the bulk Binding echo) —
    # semantically equivalent to per-pod on_update calls in order
    on_update_bulk: Optional[Callable] = None


@dataclass
class APIServer:
    """Object store + watch fan-out."""

    pods: dict[str, Pod] = field(default_factory=dict)
    nodes: dict[str, Node] = field(default_factory=dict)
    workloads: dict[str, Workload] = field(default_factory=dict)
    pvcs: dict[str, PersistentVolumeClaim] = field(default_factory=dict)
    pvs: dict[str, PersistentVolume] = field(default_factory=dict)
    storage_classes: dict[str, StorageClass] = field(default_factory=dict)
    namespaces: dict[str, dict[str, str]] = field(default_factory=dict)
    pdbs: dict[str, PodDisruptionBudget] = field(default_factory=dict)
    resource_slices: dict[str, ResourceSlice] = field(default_factory=dict)
    resource_claims: dict[str, ResourceClaim] = field(default_factory=dict)
    leases: dict[str, Lease] = field(default_factory=dict)
    shard_map: Optional[ShardMap] = None
    # bounded audit trail of accepted shard-map writes (who owned what,
    # when) — captured into incident bundles (obs/incident.py)
    shard_map_history: list[dict] = field(default_factory=list)
    pod_handlers: list[WatchHandlers] = field(default_factory=list)
    node_handlers: list[WatchHandlers] = field(default_factory=list)
    workload_handlers: list[WatchHandlers] = field(default_factory=list)
    pvc_handlers: list[WatchHandlers] = field(default_factory=list)
    pv_handlers: list[WatchHandlers] = field(default_factory=list)
    pdb_handlers: list[WatchHandlers] = field(default_factory=list)
    claim_handlers: list[WatchHandlers] = field(default_factory=list)
    slice_handlers: list[WatchHandlers] = field(default_factory=list)
    binding_count: int = 0
    fenced_rejections: int = 0

    # -- leases (coordination.k8s.io) + fencing -------------------------------

    def get_lease(self, name: str = LEASE_NAME) -> Optional[Lease]:
        return self.leases.get(name)

    def acquire_lease(self, name: str, identity: str, now: float,
                      lease_duration_s: float = 15.0) -> Lease:
        """Take the lease when unheld, expired, or already ours. A holder
        change bumps lease_transitions AND the fencing generation — the
        returned lease carries the token the new leader must stamp on its
        writes. Raises Conflict while another holder's lease is live."""
        lease = self.leases.setdefault(
            name, Lease(name=name, lease_duration_s=lease_duration_s))
        if lease.holder_identity == identity:
            lease.renew_time = now
            return lease
        expired = (not lease.holder_identity
                   or now - lease.renew_time > lease.lease_duration_s)
        if not expired:
            raise Conflict(
                f"lease {name!r} is held by {lease.holder_identity!r}")
        if lease.holder_identity:
            lease.lease_transitions += 1
        lease.holder_identity = identity
        lease.lease_duration_s = lease_duration_s
        lease.renew_time = now
        lease.generation += 1
        return lease

    def renew_lease(self, name: str, identity: str, now: float) -> Lease:
        """Heartbeat an already-held lease. Conflict when the caller no
        longer holds it (stolen / released) — the deposed-leader signal."""
        lease = self.leases.get(name)
        if lease is None:
            raise NotFound(f"lease {name}")
        if lease.holder_identity != identity:
            raise Conflict(
                f"lease {name!r} is held by {lease.holder_identity!r}, "
                f"not {identity!r}")
        lease.renew_time = now
        return lease

    def release_lease(self, name: str, identity: str) -> None:
        """Voluntary handoff: clear the holder so the next acquire wins
        immediately. No-op when the caller isn't the holder."""
        lease = self.leases.get(name)
        if lease is None or lease.holder_identity != identity:
            return
        lease.holder_identity = ""
        lease.renew_time = 0.0

    def check_fence(self, fence_token, name: str = LEASE_NAME) -> None:
        """Reject a write stamped with a stale lease generation. `None`
        passes (unfenced legacy writes); a token only fails once a NEWER
        holder has acquired, so single-leader operation never pays.

        Three token forms (the sharded control plane spans leases):
          * int — legacy, checked against the `name` lease;
          * (lease_name, generation) — one explicit lease;
          * tuple of such pairs — a bulk batch spanning shard leases;
            EVERY pair must be current or the whole write is fenced.
        """
        if fence_token is None:
            return
        if isinstance(fence_token, int):
            pairs = ((name, fence_token),)
        elif fence_token and isinstance(fence_token[0], str):
            pairs = (fence_token,)
        else:
            pairs = tuple(fence_token)
        for lname, gen in pairs:
            lease = self.leases.get(lname)
            if lease is not None and gen != lease.generation:
                self.fenced_rejections += 1
                raise FencedWrite(
                    f"write fenced: token {gen} != lease {lname!r} "
                    f"generation {lease.generation} "
                    f"(holder {lease.holder_identity!r})")

    # -- shard assignment map (sharded control plane) -------------------------

    def get_shard_map(self) -> "ShardMap":
        """Snapshot of the cluster's shard assignment map (a fresh copy —
        callers mutate a draft, then race it back through put_shard_map's
        optimistic-concurrency check). An absent map reads as the trivial
        single-shard map at version 0."""
        cur = self.shard_map
        if cur is None:
            return ShardMap()
        return ShardMap(num_shards=cur.num_shards,
                        assignments=dict(cur.assignments),
                        version=cur.version)

    def put_shard_map(self, new: "ShardMap", expect_version: int,
                      fence_token=None) -> "ShardMap":
        """Compare-and-swap the shard map. The stored version must equal
        expect_version (Conflict otherwise — re-read and retry), and the
        write is fenced like any other: a deposed shard leader cannot
        rewrite the topology. The accepted map is stored at
        expect_version + 1."""
        self.check_fence(fence_token)
        cur_version = 0 if self.shard_map is None else self.shard_map.version
        if cur_version != expect_version:
            raise Conflict(
                f"shard map version {cur_version} != expected "
                f"{expect_version}")
        self.shard_map = ShardMap(num_shards=max(1, new.num_shards),
                                  assignments=dict(new.assignments),
                                  version=expect_version + 1)
        self.shard_map_history.append({
            "version": self.shard_map.version,
            "numShards": self.shard_map.num_shards,
            "assignments": dict(self.shard_map.assignments),
            "fence": str(fence_token) if fence_token is not None else "",
        })
        del self.shard_map_history[:-32]
        return self.get_shard_map()

    # -- watch registration (LIST+WATCH: informer semantics) ------------------
    # client-go informers LIST current state before watching; a handler
    # registered against a live store immediately receives synthetic adds
    # for every existing object. This is what makes scheduler restart
    # recovery work: a fresh Scheduler rebuilds its cache/queue/device
    # state purely from these replays (cache.go's resync story).

    @staticmethod
    def _register(handlers: list, store: dict, h: WatchHandlers) -> None:
        handlers.append(h)
        if h.on_add:
            for obj in list(store.values()):
                h.on_add(obj)

    def watch_pods(self, h: WatchHandlers) -> None:
        self._register(self.pod_handlers, self.pods, h)

    def watch_nodes(self, h: WatchHandlers) -> None:
        self._register(self.node_handlers, self.nodes, h)

    def watch_workloads(self, h: WatchHandlers) -> None:
        self._register(self.workload_handlers, self.workloads, h)

    def watch_pvcs(self, h: WatchHandlers) -> None:
        self._register(self.pvc_handlers, self.pvcs, h)

    def watch_pvs(self, h: WatchHandlers) -> None:
        self._register(self.pv_handlers, self.pvs, h)

    # -- pods -----------------------------------------------------------------

    def create_pod(self, pod: Pod) -> Pod:
        if pod.uid in self.pods:
            raise Conflict(f"pod {pod.uid} exists")
        self.pods[pod.uid] = pod
        for h in self.pod_handlers:
            if h.on_add:
                h.on_add(pod)
        return pod

    def create_pods(self, pods: list[Pod]) -> None:
        """Bulk create: one store pass, then one fan-out pass per handler.
        A handler exposing `on_add_bulk` receives the whole list (the
        scheduler's ingest fast path); others get per-pod on_add."""
        store = self.pods
        for pod in pods:    # validate BEFORE inserting: a mid-batch
            if pod.uid in store:   # Conflict must not strand stored pods
                raise Conflict(f"pod {pod.uid} exists")  # unannounced
        for pod in pods:
            store[pod.uid] = pod
        for h in self.pod_handlers:
            bulk = getattr(h, "on_add_bulk", None)
            if bulk is not None:
                bulk(pods)
            elif h.on_add:
                for pod in pods:
                    h.on_add(pod)

    def update_pod(self, pod: Pod) -> Pod:
        old = self.pods.get(pod.uid)
        if old is None:
            raise NotFound(pod.uid)
        self.pods[pod.uid] = pod
        for h in self.pod_handlers:
            if h.on_update:
                h.on_update(old, pod)
        return pod

    def delete_pod(self, uid: str, fence_token: Optional[int] = None) -> None:
        self.check_fence(fence_token)
        pod = self.pods.pop(uid, None)
        if pod is None:
            raise NotFound(uid)
        for h in self.pod_handlers:
            if h.on_delete:
                h.on_delete(pod)

    def get_pod(self, uid: str) -> Pod:
        pod = self.pods.get(uid)
        if pod is None:
            raise NotFound(uid)
        return pod

    def bind(self, pod: Pod, node_name: str,
             fence_token: Optional[int] = None) -> None:
        """POST pods/<name>/binding (reference default_binder.go:51 →
        registry/core/pod/storage BindingREST: sets spec.nodeName, fails
        on conflict if already bound — EVEN to the same node, so two
        schedulers racing to identical placements still surface the
        race instead of silently double-counting the bind)."""
        self.check_fence(fence_token)
        current = self.pods.get(pod.uid)
        if current is None:
            raise NotFound(pod.uid)
        if current.spec.node_name:
            raise Conflict(
                f"pod {pod.uid} is already assigned to node {current.spec.node_name}")
        if node_name not in self.nodes:
            raise NotFound(f"node {node_name}")
        old = current
        new = current.with_node_name(node_name)
        new.status.phase = "Running"
        self.pods[pod.uid] = new
        self.binding_count += 1
        for h in self.pod_handlers:
            if h.on_update:
                h.on_update(old, new)

    def bind_all(self, pairs: list[tuple[Pod, Pod]],
                 fence_token: Optional[int] = None
                 ) -> list[tuple[Pod, Exception]]:
        """Bulk Binding subresource: (assumed pod with node set, the
        original object it was derived from). When the stored object IS
        that original (identity — the common case), no interleaved client
        update can have landed and the assumed copy becomes the stored
        object directly; otherwise the stored object is derived from
        `current` exactly like bind(), so a post-drain update survives
        with only nodeName/phase changing. Store updates apply first,
        then handlers fan out. Returns per-pod failures. A stale fencing
        token fails the WHOLE batch per-pod (the deposed leader's bulk
        flush must bind nothing, and the per-pod failure list rides the
        caller's existing unwind path)."""
        failures: list[tuple[Pod, Exception]] = []
        if fence_token is not None:
            try:
                self.check_fence(fence_token)
            except FencedWrite as e:
                return [(pod, e) for pod, _original in pairs]
        updates: list[tuple[Pod, Pod]] = []
        store = self.pods
        nodes = self.nodes
        for pod, original in pairs:
            uid = pod.metadata.uid
            current = store.get(uid)
            node_name = pod.spec.node_name
            if current is None:
                failures.append((pod, NotFound(uid)))
                continue
            if current.spec.node_name:
                # already bound — even to the SAME node: a racing
                # scheduler's identical placement is still its loss
                failures.append((pod, Conflict(
                    f"pod {uid} is already assigned to node "
                    f"{current.spec.node_name}")))
                continue
            if node_name not in nodes:
                failures.append((pod, NotFound(f"node {node_name}")))
                continue
            new = pod if current is original else current.with_node_name(node_name)
            new.status.phase = "Running"
            store[uid] = new
            updates.append((current, new))
        self.binding_count += len(updates)
        for h in self.pod_handlers:
            bulk = getattr(h, "on_update_bulk", None)
            if bulk is not None:
                bulk(updates)
                continue
            cb = h.on_update
            if cb:
                for old, new in updates:
                    cb(old, new)
        return failures

    def patch_pod_status(self, pod: Pod, condition: dict,
                         nominated_node_name=None,
                         fence_token: Optional[int] = None) -> None:
        """nominated_node_name: None = leave unchanged, "" = clear (the
        preemption demotion patch), otherwise set."""
        self.check_fence(fence_token)
        current = self.pods.get(pod.uid)
        if current is None:
            raise NotFound(pod.uid)
        if condition:
            conditions = [c for c in current.status.conditions
                          if c.get("type") != condition.get("type")]
            conditions.append(condition)
            current.status.conditions = conditions
        if nominated_node_name is not None:
            current.status.nominated_node_name = nominated_node_name

    # -- nodes ----------------------------------------------------------------

    def create_node(self, node: Node) -> Node:
        if node.name in self.nodes:
            raise Conflict(node.name)
        self.nodes[node.name] = node
        for h in self.node_handlers:
            if h.on_add:
                h.on_add(node)
        return node

    def update_node(self, node: Node) -> Node:
        old = self.nodes.get(node.name)
        if old is None:
            raise NotFound(node.name)
        self.nodes[node.name] = node
        for h in self.node_handlers:
            if h.on_update:
                h.on_update(old, node)
        return node

    def delete_node(self, name: str) -> None:
        node = self.nodes.pop(name, None)
        if node is None:
            raise NotFound(name)
        for h in self.node_handlers:
            if h.on_delete:
                h.on_delete(node)

    # -- workloads (gang API) -------------------------------------------------

    def create_workload(self, w: Workload) -> Workload:
        self.workloads[w.metadata.name] = w
        for h in self.workload_handlers:
            if h.on_add:
                h.on_add(w)
        return w

    def get_workload(self, name: str) -> Optional[Workload]:
        return self.workloads.get(name)

    # -- storage (PVC / PV / StorageClass) ------------------------------------

    def create_pvc(self, pvc: PersistentVolumeClaim) -> PersistentVolumeClaim:
        self.pvcs[pvc.uid] = pvc
        for h in self.pvc_handlers:
            if h.on_add:
                h.on_add(pvc)
        return pvc

    def get_pvc(self, namespace: str, name: str
                ) -> Optional[PersistentVolumeClaim]:
        return self.pvcs.get(f"{namespace}/{name}")

    def bind_pvc(self, pvc: PersistentVolumeClaim,
                 pv: PersistentVolume) -> None:
        """PV controller's bind (the scheduler's PreBind triggers it):
        claimRef + volumeName + phases flip atomically in this in-memory
        model (pv_controller.go bind semantics)."""
        old = dataclasses.replace(pvc)
        pvc.volume_name = pv.name
        pvc.phase = "Bound"
        pv.claim_ref = pvc.uid
        for h in self.pvc_handlers:
            if h.on_update:
                h.on_update(old, pvc)

    def create_pv(self, pv: PersistentVolume) -> PersistentVolume:
        self.pvs[pv.name] = pv
        for h in self.pv_handlers:
            if h.on_add:
                h.on_add(pv)
        return pv

    def get_pv(self, name: str) -> Optional[PersistentVolume]:
        return self.pvs.get(name)

    def list_pvs(self) -> list[PersistentVolume]:
        return list(self.pvs.values())

    def create_storage_class(self, sc: StorageClass) -> StorageClass:
        self.storage_classes[sc.name] = sc
        return sc

    def get_storage_class(self, name: str) -> Optional[StorageClass]:
        return self.storage_classes.get(name)

    # -- DRA: ResourceSlices / ResourceClaims (resource/v1) -------------------

    def watch_resource_claims(self, h: WatchHandlers) -> None:
        self._register(self.claim_handlers, self.resource_claims, h)

    def watch_resource_slices(self, h: WatchHandlers) -> None:
        self._register(self.slice_handlers, self.resource_slices, h)

    def create_resource_slice(self, s: ResourceSlice) -> ResourceSlice:
        self.resource_slices[s.name] = s
        for h in self.slice_handlers:
            if h.on_add:
                h.on_add(s)
        return s

    def list_resource_slices(self) -> list[ResourceSlice]:
        return list(self.resource_slices.values())

    def create_resource_claim(self, c: ResourceClaim) -> ResourceClaim:
        self.resource_claims[c.uid] = c
        for h in self.claim_handlers:
            if h.on_add:
                h.on_add(c)
        return c

    def get_resource_claim(self, namespace: str, name: str
                           ) -> Optional[ResourceClaim]:
        return self.resource_claims.get(f"{namespace}/{name}")

    def list_resource_claims(self) -> list[ResourceClaim]:
        return list(self.resource_claims.values())

    def update_claim_status(self, claim: ResourceClaim) -> ResourceClaim:
        """Write allocation + reservedFor (the PreBind status write,
        dynamicresources.go PreBind → claim status update)."""
        old = self.resource_claims.get(claim.uid)
        if old is None:
            raise NotFound(claim.uid)
        self.resource_claims[claim.uid] = claim
        for h in self.claim_handlers:
            if h.on_update:
                h.on_update(old, claim)
        return claim

    # -- PodDisruptionBudgets (policy/v1) -------------------------------------

    def watch_pdbs(self, h: WatchHandlers) -> None:
        self._register(self.pdb_handlers, self.pdbs, h)

    def create_pdb(self, pdb: PodDisruptionBudget) -> PodDisruptionBudget:
        self.pdbs[pdb.uid] = pdb
        for h in self.pdb_handlers:
            if h.on_add:
                h.on_add(pdb)
        return pdb

    def delete_pdb(self, uid: str) -> None:
        pdb = self.pdbs.pop(uid, None)
        if pdb is None:
            raise NotFound(uid)
        for h in self.pdb_handlers:
            if h.on_delete:
                h.on_delete(pdb)

    def list_pdbs(self) -> list[PodDisruptionBudget]:
        """PDBs with a freshly computed status.disruptionsAllowed — the
        in-memory stand-in for the disruption controller
        (pkg/controller/disruption): expected = pods matching the
        selector, healthy = the bound ones."""
        out = []
        for pdb in self.pdbs.values():
            matched = [p for p in self.pods.values() if pdb.matches(p)]
            expected = len(matched)
            healthy = sum(1 for p in matched if p.spec.node_name)
            if pdb.min_available is not None:
                # percentage minAvailable rounds UP (the reference
                # disruption controller's GetScaledValueFromIntOrPercent
                # roundUp=true), so budgets are never overstated
                want = _resolve_maybe_percent(pdb.min_available, expected,
                                              round_up=True)
                allowed = healthy - want
            elif pdb.max_unavailable is not None:
                cap = _resolve_maybe_percent(pdb.max_unavailable, expected)
                allowed = cap - (expected - healthy)
            else:
                allowed = 0
            pdb.disruptions_allowed = max(allowed, 0)
            out.append(pdb)
        return out
