"""Scheduler cache: assumed pods, node mirror, incremental snapshot.

Mirrors pkg/scheduler/backend/cache/cache.go:
- podStates with an assumed set + TTL deadline (cache.go:61-84); AssumePod
  (:369), FinishBinding (:384), ForgetPod (:412), expiry cleanup (:38-49).
- `nodes` map + generation-ordered doubly-linked list (cache.go:118-167):
  every NodeInfo mutation bumps its generation and moves the entry to the
  list head, so UpdateSnapshot can stop walking at the first entry whose
  generation is already in the snapshot (snapshot.go / cache.go:194-250).
- Snapshot keeps three pre-filtered node lists (all / havePodsWithAffinity /
  haveRequiredAntiAffinity) exactly like snapshot.go:30.

On the TPU path the same generation diff drives scatter-updates of the
device-resident capacity matrices (state/tensorize.py) instead of NodeInfo
copies — the cache emits the list of dirty node indices per snapshot.
"""

from __future__ import annotations

import time as _time
from dataclasses import dataclass, field
from typing import Callable, Optional

from ..api.types import Node, Pod
from ..framework.types import NodeInfo, PodInfo, next_generation


@dataclass
class _PodState:
    pod: Pod
    assumed: bool = False
    deadline: Optional[float] = None  # assumed-pod expiry; None = no expiry
    binding_finished: bool = False


class _NodeItem:
    """Doubly-linked list entry (cache.go nodeInfoListItem)."""

    __slots__ = ("info", "next", "prev")

    def __init__(self, info: NodeInfo):
        self.info = info
        self.next: Optional[_NodeItem] = None
        self.prev: Optional[_NodeItem] = None


@dataclass
class Snapshot:
    """backend/cache/snapshot.go:30."""

    node_infos: dict[str, NodeInfo] = field(default_factory=dict)
    node_info_list: list[NodeInfo] = field(default_factory=list)
    have_pods_with_affinity_list: list[NodeInfo] = field(default_factory=list)
    have_pods_with_required_anti_affinity_list: list[NodeInfo] = field(default_factory=list)
    generation: int = 0
    # node_tree generation at last list rebuild (schedulable-set change marker)
    tree_generation: int = -1
    # node indices whose arrays changed since the previous snapshot — the
    # TPU scatter-update set (not in the reference; our §7.3 addition)
    dirty_nodes: set[str] = field(default_factory=set)

    def get(self, name: str) -> Optional[NodeInfo]:
        return self.node_infos.get(name)


class Cache:
    """cacheImpl (cache.go:61). Single-threaded host model: the reference's
    mutex discipline collapses into call ordering by the scheduler loop."""

    def __init__(self, ttl: float = 0.0, clock: Callable[[], float] = _time.monotonic):
        self.ttl = ttl  # 0 ⇒ assumed pods never expire (scheduler.go:63-67)
        self.clock = clock
        self.pod_states: dict[str, _PodState] = {}
        self.assumed_pods: set[str] = set()
        self.nodes: dict[str, _NodeItem] = {}
        self.head: Optional[_NodeItem] = None
        # nodeTree: zone → node names for zone-round-robin ordering
        # (backend/cache/node_tree.go:32-37)
        self.node_tree: dict[str, list[str]] = {}
        self._tree_generation = 0  # bumped on any node_tree membership change
        self._imputed_nodes: set[str] = set()  # nodes created only by pod adds

    # -- linked-list maintenance (cache.go:118-167) --------------------------

    def _move_to_head(self, item: _NodeItem) -> None:
        if self.head is item:
            return
        if item.prev is not None:
            item.prev.next = item.next
        if item.next is not None:
            item.next.prev = item.prev
        item.prev = None
        item.next = self.head
        if self.head is not None:
            self.head.prev = item
        self.head = item

    def _remove_item(self, item: _NodeItem) -> None:
        if item.prev is not None:
            item.prev.next = item.next
        else:
            self.head = item.next
        if item.next is not None:
            item.next.prev = item.prev
        item.prev = item.next = None

    def _touch(self, item: _NodeItem) -> None:
        item.info.bump()
        self._move_to_head(item)

    def _get_or_create(self, node_name: str) -> _NodeItem:
        item = self.nodes.get(node_name)
        if item is None:
            # pod arrived before its node (cache.go AddPod path): imputed entry
            item = _NodeItem(NodeInfo(node=_placeholder_node(node_name)))
            self.nodes[node_name] = item
            self._imputed_nodes.add(node_name)
            self._move_to_head(item)
        return item

    # -- pods ----------------------------------------------------------------

    def assume_pod(self, pod: Pod) -> None:
        """cache.go:369 — pod must not be known yet."""
        self.assume_pod_info(PodInfo.of(pod))

    def assume_pod_info(self, pi: PodInfo) -> None:
        """assume_pod with a caller-supplied PodInfo — the scheduler's hot
        bind path reuses the queue entry's pre-parsed requests instead of
        re-parsing resource quantities per assume."""
        pod = pi.pod
        uid = pod.uid
        if uid in self.pod_states:
            raise KeyError(f"pod {uid} is in the cache, so can't be assumed")
        self._add_pod_info_to_node(pi)
        ps = _PodState(pod=pod, assumed=True)
        self.pod_states[uid] = ps
        self.assumed_pods.add(uid)

    def finish_binding(self, pod: Pod) -> None:
        """cache.go:384 — start the TTL countdown for the assumed pod."""
        ps = self.pod_states.get(pod.uid)
        if ps is None or not ps.assumed:
            return
        ps.binding_finished = True
        if self.ttl > 0:
            ps.deadline = self.clock() + self.ttl

    def forget_pod(self, pod: Pod) -> None:
        """cache.go:412 — only assumed pods can be forgotten."""
        uid = pod.uid
        ps = self.pod_states.get(uid)
        if ps is None:
            return
        if ps.pod.spec.node_name != pod.spec.node_name:
            raise ValueError(f"pod {uid} was assumed on {ps.pod.spec.node_name} "
                             f"but assigned to {pod.spec.node_name}")
        if not ps.assumed:
            raise KeyError(f"pod {uid} wasn't assumed, so can't be forgotten")
        self._remove_pod_from_node(ps.pod)
        del self.pod_states[uid]
        self.assumed_pods.discard(uid)

    def add_pod(self, pod: Pod) -> None:
        """Informer add of an assigned pod (cache.go AddPod): confirms an
        assumed pod or inserts a new one."""
        uid = pod.uid
        ps = self.pod_states.get(uid)
        if ps is not None and ps.assumed:
            if ps.pod.spec.node_name != pod.spec.node_name:
                # assumed on one node, bound on another: relocate
                self._remove_pod_from_node(ps.pod)
                self._add_pod_to_node(pod)
            self.assumed_pods.discard(uid)
            self.pod_states[uid] = _PodState(pod=pod)
            return
        if ps is not None:
            return  # duplicate add: ignore (cache logs error)
        self._add_pod_to_node(pod)
        self.pod_states[uid] = _PodState(pod=pod)

    def confirm_bound(self, pods: list) -> None:
        """Bulk bind-echo confirm (the columnar commit engine's informer
        path): each pod was assumed on the node it just bound to, so the
        add_pod() assumed-branch reduces to flipping the existing
        _PodState in place — no relocation, no fresh state object. Pods
        that do not match the fast shape (not assumed, or bound
        elsewhere) take the full add_pod path."""
        states = self.pod_states
        assumed = self.assumed_pods
        for pod in pods:
            uid = pod.metadata.uid
            ps = states.get(uid)
            if (ps is None or not ps.assumed
                    or ps.pod.spec.node_name != pod.spec.node_name):
                self.add_pod(pod)
                continue
            assumed.discard(uid)
            ps.pod = pod
            ps.assumed = False
            ps.binding_finished = False
            ps.deadline = None

    def add_pods(self, pods: list) -> None:
        """Bulk informer add of assigned pods (the resync/relist path):
        per-pod `add_pod` semantics with the state probes hoisted."""
        states = self.pod_states
        for pod in pods:
            uid = pod.metadata.uid
            ps = states.get(uid)
            if ps is not None:
                if ps.assumed:
                    self.add_pod(pod)   # assumed-confirm/relocate path
                continue
            self._add_pod_to_node(pod)
            states[uid] = _PodState(pod=pod)

    def update_pod(self, old: Pod, new: Pod) -> None:
        ps = self.pod_states.get(old.uid)
        if ps is None or ps.assumed:
            return
        self._remove_pod_from_node(ps.pod)
        self._add_pod_to_node(new)
        self.pod_states[old.uid] = _PodState(pod=new)

    def remove_pod(self, pod: Pod) -> None:
        ps = self.pod_states.get(pod.uid)
        if ps is None:
            return
        self._remove_pod_from_node(ps.pod)
        del self.pod_states[pod.uid]
        self.assumed_pods.discard(pod.uid)

    def is_assumed_pod(self, pod: Pod) -> bool:
        return pod.uid in self.assumed_pods

    def get_pod(self, uid: str) -> Optional[Pod]:
        ps = self.pod_states.get(uid)
        return ps.pod if ps else None

    def pod_count(self) -> int:
        return len(self.pod_states)

    def _add_pod_to_node(self, pod: Pod) -> None:
        self._add_pod_info_to_node(PodInfo.of(pod))

    def _add_pod_info_to_node(self, pi: PodInfo) -> None:
        pod = pi.pod
        if not pod.spec.node_name:
            raise ValueError(f"pod {pod.uid} has no nodeName")
        item = self._get_or_create(pod.spec.node_name)
        item.info.add_pod(pi)
        self._move_to_head(item)

    def _remove_pod_from_node(self, pod: Pod) -> None:
        item = self.nodes.get(pod.spec.node_name)
        if item is None:
            return
        item.info.remove_pod(PodInfo.of(pod))
        self._move_to_head(item)
        # drop imputed node entries once empty (cache.go removeDeletedNodesFromCache)
        if (pod.spec.node_name in self._imputed_nodes and not item.info.pods):
            self._remove_item(item)
            del self.nodes[pod.spec.node_name]
            self._imputed_nodes.discard(pod.spec.node_name)

    # -- assumed-pod expiry (cache.go cleanupAssumedPods, 1s period) ---------

    def cleanup_expired_assumed_pods(self) -> list[Pod]:
        """Returns the pods that were expired (caller requeues them)."""
        if self.ttl <= 0:
            return []
        now = self.clock()
        expired = []
        for uid in list(self.assumed_pods):
            ps = self.pod_states[uid]
            if ps.binding_finished and ps.deadline is not None and now >= ps.deadline:
                expired.append(ps.pod)
                self._remove_pod_from_node(ps.pod)
                del self.pod_states[uid]
                self.assumed_pods.discard(uid)
        return expired

    # -- nodes ---------------------------------------------------------------

    def add_node(self, node: Node) -> NodeInfo:
        item = self.nodes.get(node.name)
        if item is None:
            item = _NodeItem(NodeInfo(node=node))
            self.nodes[node.name] = item
        else:
            self._imputed_nodes.discard(node.name)
            item.info.node = node
            item.info.sync_images()
        self._touch(item)
        self._node_tree_add(node)
        return item.info

    def update_node(self, old: Node, new: Node) -> NodeInfo:
        item = self.nodes.get(new.name)
        if item is None:
            return self.add_node(new)
        old_zone = _zone_of(item.info.node)
        item.info.node = new
        item.info.sync_images()
        self._touch(item)
        if old_zone != _zone_of(new):
            self._node_tree_remove(new.name, old_zone)
            self._node_tree_add(new)
        return item.info

    def remove_node(self, node: Node) -> None:
        item = self.nodes.get(node.name)
        if item is None:
            return
        # keep the entry if pods are still on it (they'll be removed by
        # their own delete events; cache.go RemoveNode)
        self._node_tree_remove(node.name, _zone_of(node))
        if item.info.pods:
            self._imputed_nodes.add(node.name)
            self._touch(item)
        else:
            self._remove_item(item)
            del self.nodes[node.name]

    def get_node_info(self, name: str) -> Optional[NodeInfo]:
        item = self.nodes.get(name)
        return item.info if item else None

    def node_count(self) -> int:
        return len(self.nodes)

    def _node_tree_add(self, node: Node) -> None:
        zone = _zone_of(node)
        names = self.node_tree.setdefault(zone, [])
        if node.name not in names:
            names.append(node.name)
            self._tree_generation += 1

    def _node_tree_remove(self, name: str, zone: str) -> None:
        names = self.node_tree.get(zone)
        if names and name in names:
            names.remove(name)
            self._tree_generation += 1
            if not names:
                del self.node_tree[zone]

    # -- snapshot (cache.go:194-250) -----------------------------------------

    def update_snapshot(self, snapshot: Snapshot) -> Snapshot:
        """Incremental: walk the generation list head-first, stop at the first
        item whose generation ≤ snapshot.generation; rebuild the flat lists
        only when membership changed."""
        snapshot.dirty_nodes = set()
        update_all = False
        item = self.head
        latest = item.info.generation if item else snapshot.generation
        while item is not None and item.info.generation > snapshot.generation:
            info = item.info
            name = info.name
            existing = snapshot.node_infos.get(name)
            if existing is None:
                update_all = True
            else:
                # membership of the affinity sublists may have changed
                if (bool(existing.pods_with_affinity) != bool(info.pods_with_affinity)
                        or bool(existing.pods_with_required_anti_affinity)
                        != bool(info.pods_with_required_anti_affinity)):
                    update_all = True
            snapshot.node_infos[name] = _snapshot_node_info(info)
            snapshot.dirty_nodes.add(name)
            item = item.next
        # removed nodes
        if len(snapshot.node_infos) > len(self.nodes):
            for name in list(snapshot.node_infos):
                if name not in self.nodes:
                    del snapshot.node_infos[name]
                    snapshot.dirty_nodes.add(name)
                    update_all = True
        if update_all or self._tree_generation != snapshot.tree_generation:
            self._rebuild_lists(snapshot)
            snapshot.tree_generation = self._tree_generation
        elif snapshot.dirty_nodes:
            # refresh references in the flat lists for dirty nodes; the
            # clean case must not walk the lists at all — update_snapshot
            # runs once per scheduling failure, and a 5k-node walk per
            # call was ~3s of a 200-preemptor wave
            for lst in (snapshot.node_info_list,
                        snapshot.have_pods_with_affinity_list,
                        snapshot.have_pods_with_required_anti_affinity_list):
                for i, ni in enumerate(lst):
                    if ni.name in snapshot.dirty_nodes:
                        lst[i] = snapshot.node_infos[ni.name]
        snapshot.generation = latest
        return snapshot

    def _rebuild_lists(self, snapshot: Snapshot) -> None:
        """Zone-round-robin node order (node_tree.go) — matches the
        reference's node iteration order for decision parity."""
        order: list[str] = []
        zone_lists = [list(v) for v in self.node_tree.values()]
        idx = 0
        while any(zone_lists):
            for zl in zone_lists:
                if idx < len(zl):
                    order.append(zl[idx])
            idx += 1
            if all(idx >= len(zl) for zl in zone_lists):
                break
        # the list comes exclusively from the nodeTree (cache.go:229-239):
        # removed-but-still-populated nodes and imputed placeholder entries
        # stay in node_infos for lookups but are not schedulable targets
        snapshot.node_info_list = [snapshot.node_infos[n] for n in order
                                   if n in snapshot.node_infos]
        snapshot.have_pods_with_affinity_list = [
            ni for ni in snapshot.node_info_list if ni.pods_with_affinity]
        snapshot.have_pods_with_required_anti_affinity_list = [
            ni for ni in snapshot.node_info_list
            if ni.pods_with_required_anti_affinity]

    # -- debugger (backend/cache/debugger) -----------------------------------

    def dump(self) -> dict:
        return {
            "nodes": {n: {"pods": [p.pod.uid for p in item.info.pods],
                          "requested": dict(item.info.requested),
                          "generation": item.info.generation}
                      for n, item in self.nodes.items()},
            "assumed_pods": sorted(self.assumed_pods),
            "pod_count": len(self.pod_states),
        }


def _snapshot_node_info(info: NodeInfo) -> NodeInfo:
    """NodeInfo.Snapshot(): structural copy sharing immutable PodInfos."""
    return info.snapshot_clone()


def _zone_of(node: Node) -> str:
    return node.metadata.labels.get("topology.kubernetes.io/zone", "")


def _placeholder_node(name: str) -> Node:
    from ..api.types import NodeSpec, NodeStatus, ObjectMeta
    return Node(metadata=ObjectMeta(name=name), spec=NodeSpec(), status=NodeStatus())
