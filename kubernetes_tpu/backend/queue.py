"""SchedulingQueue: activeQ / backoffQ / unschedulablePods + nominator.

Mirrors pkg/scheduler/backend/queue/:
- PriorityQueue interface & wiring (scheduling_queue.go:94-144, :339).
- activeQ heap ordered by the profile's QueueSort less-fn; Pop falls back to
  an expired backoffQ entry (active_queue.go:272-307) and registers the pod
  in the in-flight list for event tracking (:310-330).
- backoffQ ordered by backoff expiry; per-pod backoff 1s·2^(n−1) capped 10s
  (backoff_queue.go:250, defaults scheduling_queue.go:79-83), with the error
  path keyed on consecutive errors.
- unschedulablePods map with a 5-minute leftover flush every 30s
  (scheduling_queue.go:406-413).
- AddUnschedulableIfNotPresent (:864): consults the in-flight cluster events
  that arrived during the pod's scheduling attempt against the rejector
  plugins' QueueingHintFns; a Queue hint sends the pod to backoffQ,
  otherwise it parks in unschedulablePods.
- MoveAllToActiveOrBackoffQueue (:1188) + isPodWorthRequeuing (:456).
- Nominator (nominator.go): nominated pod UIDs per node.

Host-side by design — the queue *is* the batch boundary on the TPU path:
`drain()` hands the whole activeQ to the device program in one call.
"""

from __future__ import annotations

import heapq
import itertools
import time as _time
from dataclasses import dataclass, field
from typing import Callable, Optional

from ..api.types import Pod
from ..framework.types import (ActionType, ClusterEvent, EventResource,
                               QueuedPodInfo, QueueingHint)
from ..obs.journey import (EV_ENQUEUE as _EV_ENQUEUE, EV_GATE as _EV_GATE,
                           EV_UNGATE as _EV_UNGATE)

DEFAULT_POD_INITIAL_BACKOFF = 1.0
DEFAULT_POD_MAX_BACKOFF = 10.0
DEFAULT_POD_MAX_IN_UNSCHEDULABLE_PODS_DURATION = 300.0

EVENT_UNSCHEDULABLE_TIMEOUT = ClusterEvent(EventResource.WILDCARD, ActionType.ALL,
                                           "UnschedulableTimeout")
EVENT_FORCE_ACTIVATE = ClusterEvent(EventResource.WILDCARD, ActionType.ALL,
                                    "ForceActivate")


@dataclass
class ClusterEventWithHint:
    """staging framework/types.go ClusterEventWithHint: event the plugin
    subscribes to + optional hint fn (pod, old_obj, new_obj) → QueueingHint."""

    event: ClusterEvent
    hint_fn: Optional[Callable] = None


class _Heap:
    """backend/heap/heap.go — keyed heap with a less-fn."""

    def __init__(self, less: Callable):
        self.less = less
        self._items: dict[str, object] = {}
        self._versions: dict[str, int] = {}  # stale-entry detection
        self._heap: list = []
        # adds land here first (key, version, item) and only reach the
        # real heap when an ordered read (peek/pop) needs them: the TPU
        # drain path consumes the whole queue via pop_sorted, which never
        # orders through the heap — deferring the heappush turns the
        # ingest hot path's per-pod O(log n) wrapper push into a list
        # append that is usually thrown away wholesale
        self._staged: list = []
        self._counter = itertools.count()

    def __len__(self) -> int:
        return len(self._items)

    def __contains__(self, key: str) -> bool:
        return key in self._items

    def _push(self, key: str, item) -> None:
        version = self._versions.get(key, 0) + 1
        self._versions[key] = version
        self._items[key] = item
        self._staged.append((key, version, item))

    def _flush_staged(self) -> None:
        """Move staged adds into the real heap (ordered-read barrier).
        Flush order preserves insertion order, so the tie-break counter
        assigns the same relative order an eager push would have."""
        heap = self._heap
        versions = self._versions
        items = self._items
        for key, version, item in self._staged:
            if versions.get(key) == version and items.get(key) is item:
                heapq.heappush(heap, (_Less(item, self.less),
                                      next(self._counter), key, version))
        self._staged.clear()

    def add(self, key: str, item) -> None:
        self._push(key, item)

    def update(self, key: str, item) -> None:
        # re-push under a new version; the old entry becomes stale even if it
        # wraps the same (mutated) object
        self._push(key, item)

    def delete(self, key: str) -> None:
        if self._items.pop(key, None) is not None:
            # bump (never delete) the version so in-heap entries go stale;
            # deleting it would let a future add restart at version 1 and
            # revalidate an old entry
            self._versions[key] = self._versions.get(key, 0) + 1
        if not self._items:
            self._heap.clear()
            self._versions.clear()
            self._staged.clear()

    def get(self, key: str):
        return self._items.get(key)

    def peek(self):
        if self._staged:
            self._flush_staged()
        while self._heap:
            wrapped, _, key, version = self._heap[0]
            if key not in self._items or self._versions.get(key) != version:
                heapq.heappop(self._heap)  # stale entry
                continue
            return self._items[key]
        return None

    def pop(self):
        if self._staged:
            self._flush_staged()
        while self._heap:
            wrapped, _, key, version = heapq.heappop(self._heap)
            if key not in self._items or self._versions.get(key) != version:
                continue
            item = self._items.pop(key)
            self._versions[key] = version + 1
            if not self._items:
                self._heap.clear()
                self._versions.clear()
                self._staged.clear()
            return item
        return None

    def pop_sorted(self, key_fn: Callable, max_items: int = 0) -> list:
        """Pop the best max_items (0 = all) ordered by key_fn — one
        C-level sort instead of per-item heappops through Python
        comparison wrappers (the TPU batch drain's hot path). Only valid
        when key_fn induces the same order as the heap's less-fn. Any
        remainder stays keyed in the heap: popped entries version-bump so
        their stale heap nodes are skipped on later pops."""
        pairs = sorted(self._items.items(), key=lambda kv: key_fn(kv[1]))
        if max_items and max_items < len(pairs):
            take = pairs[:max_items]
            for key, _ in take:
                del self._items[key]
                self._versions[key] = self._versions.get(key, 0) + 1
        else:
            take = pairs
            self._items.clear()
            self._versions.clear()
            self._heap.clear()
            self._staged.clear()
        return [it for _, it in take]

    def items(self):
        return list(self._items.values())


class _Less:
    __slots__ = ("item", "less")

    def __init__(self, item, less):
        self.item = item
        self.less = less

    def __lt__(self, other: "_Less") -> bool:
        return self.less(self.item, other.item)


@dataclass
class _InFlightEvent:
    seq: int
    event: ClusterEvent
    old_obj: object
    new_obj: object


class Nominator:
    """backend/queue/nominator.go — nominated pods per node."""

    def __init__(self) -> None:
        self.nominated_pods: dict[str, str] = {}       # uid → node name
        self.nominated_per_node: dict[str, list[QueuedPodInfo]] = {}
        # monotonic mutation counter: consumers that bake nominations into
        # cached state (the scheduler's resident SigCache overlay) compare
        # this to detect that their overlay went stale
        self.version = 0

    def add(self, qpi: QueuedPodInfo, node_name: str = "") -> None:
        node = node_name or qpi.pod.status.nominated_node_name
        if not node:
            return
        self.delete(qpi.pod)
        self.nominated_pods[qpi.pod.uid] = node
        self.nominated_per_node.setdefault(node, []).append(qpi)
        self.version += 1

    def delete(self, pod: Pod) -> None:
        node = self.nominated_pods.pop(pod.uid, None)
        if node is None:
            return
        self.version += 1
        lst = self.nominated_per_node.get(node, [])
        self.nominated_per_node[node] = [q for q in lst if q.pod.uid != pod.uid]
        if not self.nominated_per_node[node]:
            del self.nominated_per_node[node]

    def pods_for_node(self, node_name: str) -> list[QueuedPodInfo]:
        return list(self.nominated_per_node.get(node_name, ()))

    def nominated_node_for(self, pod: Pod) -> str:
        return self.nominated_pods.get(pod.uid, "")


class SchedulingQueue:
    """PriorityQueue (scheduling_queue.go:339)."""

    def __init__(self,
                 less: Optional[Callable] = None,
                 pre_enqueue: Optional[Callable] = None,
                 queueing_hints: Optional[dict[str, list[ClusterEventWithHint]]] = None,
                 pod_initial_backoff: float = DEFAULT_POD_INITIAL_BACKOFF,
                 pod_max_backoff: float = DEFAULT_POD_MAX_BACKOFF,
                 pod_max_unschedulable_duration: float = DEFAULT_POD_MAX_IN_UNSCHEDULABLE_PODS_DURATION,
                 clock: Callable[[], float] = _time.monotonic):
        self.less = less or default_queue_sort_less
        # pre_enqueue(pod) → Status; gates pods (SchedulingGates plugin)
        self.pre_enqueue = pre_enqueue
        # plugin name → subscribed events+hints (built from EnqueueExtensions)
        self.queueing_hints = queueing_hints or {}
        self.pod_initial_backoff = pod_initial_backoff
        self.pod_max_backoff = pod_max_backoff
        self.pod_max_unschedulable_duration = pod_max_unschedulable_duration
        self.clock = clock

        self.active_q = _Heap(self.less)
        self.backoff_q = _Heap(self._backoff_less)
        self.unschedulable_pods: dict[str, QueuedPodInfo] = {}
        self.unschedulable_since: dict[str, float] = {}
        # gated gang members indexed by workload ref: a member-pod event
        # re-runs PreEnqueue for THAT gang's gated members only (the
        # retry_gated(ref=...) fast path) instead of sweeping every gated
        # pod in the cluster
        self.gated_by_ref: dict[str, set[str]] = {}
        self.nominator = Nominator()

        self.scheduling_cycle = 0
        self._event_seq = itertools.count()
        self.in_flight_pods: dict[str, int] = {}     # uid → pop event seq
        self.in_flight_events: list[_InFlightEvent] = []
        self.moved_in_cycle: dict[str, int] = {}     # uid → cycle when moved by event
        # journey ledger (obs/journey.py), attached by the scheduler: the
        # queue owns the enqueue/gate/ungate/pop transitions AND the
        # first-enqueue e2e SLI clock restore for fresh QueuedPodInfos
        self.journey = None

    # -- ordering ------------------------------------------------------------

    def _backoff_less(self, a: QueuedPodInfo, b: QueuedPodInfo) -> bool:
        return self._backoff_expiry(a) < self._backoff_expiry(b)

    def _backoff_duration(self, qpi: QueuedPodInfo) -> float:
        """backoff_queue.go calculateBackoffDuration: exponential per
        unschedulable attempt, capped."""
        n = max(qpi.unschedulable_count, qpi.consecutive_errors_count)
        if n == 0:
            return 0.0
        duration = self.pod_initial_backoff
        for _ in range(n - 1):
            duration *= 2
            if duration >= self.pod_max_backoff:
                return self.pod_max_backoff
        return min(duration, self.pod_max_backoff)

    def _backoff_expiry(self, qpi: QueuedPodInfo) -> float:
        ts = qpi.timestamp
        return ts + self._backoff_duration(qpi)

    def _is_backing_off(self, qpi: QueuedPodInfo) -> bool:
        return self._backoff_expiry(qpi) > self.clock()

    # -- gated-gang index ------------------------------------------------------

    def _index_gated(self, pod: Pod) -> None:
        ref = pod.spec.workload_ref
        if ref:
            self.gated_by_ref.setdefault(ref, set()).add(pod.uid)

    def _unindex_gated(self, pod: Pod) -> None:
        ref = pod.spec.workload_ref
        if not ref:
            return
        uids = self.gated_by_ref.get(ref)
        if uids is not None:
            uids.discard(pod.uid)
            if not uids:
                del self.gated_by_ref[ref]

    def gated_refs(self) -> set:
        """Workload refs that currently have gated members."""
        return set(self.gated_by_ref)

    # -- add paths -----------------------------------------------------------

    def add(self, pod: Pod) -> None:
        from ..framework.types import PodInfo
        now = self.clock()
        # the e2e SLI clock starts at the pod's FIRST enqueue, not its
        # first pop — and a re-add of a known pod (watch replay, fresh
        # QueuedPodInfo after a bind error) must restore the original
        # clock, not restart it
        t0 = now
        journey = self.journey
        if journey is not None:
            if journey.first_enqueue(pod.uid, now):
                journey.record(pod.uid, _EV_ENQUEUE, now)
            else:
                t0 = journey.e2e_start(pod.uid, now)
        qpi = QueuedPodInfo(pod_info=PodInfo.of(pod), timestamp=now,
                            initial_attempt_timestamp=t0)
        self._add_qpi(qpi)

    def add_bulk(self, pods: list[Pod]) -> int:
        """Batch add (the ingest hot path): one clock read for the whole
        batch (creation_index still orders queue-sort ties), hoisted
        locals, nominator skipped for pods without a nomination. Returns
        the number that were GATED by PreEnqueue."""
        from ..framework.types import PodInfo
        now = self.clock()
        pre = self.pre_enqueue
        active_add = self.active_q.add
        nominator_add = self.nominator.add
        journey = self.journey
        fresh = []
        gates = []
        gated = 0
        for pod in pods:
            qpi = QueuedPodInfo(pod_info=PodInfo.of(pod), timestamp=now,
                                initial_attempt_timestamp=now)
            if journey is not None:
                if journey.first_enqueue(pod.uid, now):
                    fresh.append(pod.uid)
                else:
                    # known pod, fresh QPI (resync rebuild / watch
                    # replay): restore the e2e SLI clock
                    qpi.initial_attempt_timestamp = journey.e2e_start(
                        pod.uid, now)
            if pre is not None:
                status = pre(pod)
                if not status.is_success():
                    qpi.gated = True
                    qpi.gating_plugin = status.plugin
                    self.unschedulable_pods[pod.uid] = qpi
                    self.unschedulable_since[pod.uid] = now
                    self._index_gated(pod)
                    gated += 1
                    if journey is not None:
                        gates.append((pod.uid, status.plugin or ""))
                    continue
            active_add(pod.metadata.uid, qpi)
            if pod.status.nominated_node_name:
                nominator_add(qpi)
        if journey is not None:
            journey.record_bulk(fresh, _EV_ENQUEUE, now)
            if gates:
                journey.record_bulk([u for u, _ in gates], _EV_GATE, now,
                                    detail=[p for _, p in gates])
        return gated

    def _add_qpi(self, qpi: QueuedPodInfo) -> None:
        was_gated = qpi.gated
        journey = self.journey
        if self.pre_enqueue is not None:
            status = self.pre_enqueue(qpi.pod)
            if not status.is_success():
                qpi.gated = True
                qpi.gating_plugin = status.plugin
                self.unschedulable_pods[qpi.pod.uid] = qpi
                self.unschedulable_since[qpi.pod.uid] = self.clock()
                self._index_gated(qpi.pod)
                if journey is not None and not was_gated:
                    journey.record(qpi.pod.uid, _EV_GATE, self.clock(),
                                   detail=status.plugin or "")
                return
        qpi.gated = False
        if journey is not None and was_gated:
            journey.record(qpi.pod.uid, _EV_UNGATE, self.clock())
        self.active_q.add(qpi.pod.uid, qpi)
        self.nominator.add(qpi)

    def update(self, old: Pod, new: Pod) -> None:
        from ..framework.types import PodInfo
        uid = new.uid
        for heap_ in (self.active_q, self.backoff_q):
            existing = heap_.get(uid)
            if existing is not None:
                existing.pod_info = PodInfo.of(new)
                existing.pod = new
                heap_.update(uid, existing)
                return
        existing = self.unschedulable_pods.get(uid)
        if existing is not None:
            was_gated = existing.gated
            if was_gated:
                self._unindex_gated(existing.pod)
            existing.pod_info = PodInfo.of(new)
            existing.pod = new
            # updated pods get re-evaluated (scheduling_queue.go Update:
            # spec change may make it schedulable)
            del self.unschedulable_pods[uid]
            self.unschedulable_since.pop(uid, None)
            if was_gated:
                self._add_qpi(existing)
            elif self._is_backing_off(existing):
                self.backoff_q.add(uid, existing)
            else:
                self.active_q.add(uid, existing)
                self.nominator.add(existing)
            return
        if uid not in self.in_flight_pods:
            self.add(new)

    def delete(self, pod: Pod) -> None:
        uid = pod.uid
        self.active_q.delete(uid)
        self.backoff_q.delete(uid)
        gone = self.unschedulable_pods.pop(uid, None)
        if gone is not None and gone.gated:
            self._unindex_gated(gone.pod)
        self.unschedulable_since.pop(uid, None)
        self.nominator.delete(pod)

    # -- pop / drain ---------------------------------------------------------

    def pop(self) -> Optional[QueuedPodInfo]:
        """active_queue.go:272-307: flush due backoff, then pop best."""
        self.flush_backoff_completed()
        qpi = self.active_q.pop()
        if qpi is None:
            return None
        self._mark_in_flight(qpi)
        if self.journey is not None:
            self.journey.popped([qpi], self.clock())
        return qpi

    def drain(self, max_pods: int = 0) -> list[QueuedPodInfo]:
        """TPU batch path: pop the whole activeQ (queue order preserved) in
        one go — the batch the device program schedules at once. With the
        default queue-sort and no size cap binding, the whole heap drains
        via ONE key-sort (C speed) instead of per-pod heappops."""
        self.flush_backoff_completed()
        if self.less is default_queue_sort_less:
            out = self.active_q.pop_sorted(default_queue_sort_key,
                                           max(max_pods, 0))
            for qpi in out:
                self._mark_in_flight(qpi)
        else:
            out = []
            while max_pods <= 0 or len(out) < max_pods:
                qpi = self.active_q.pop()
                if qpi is None:
                    break
                self._mark_in_flight(qpi)
                out.append(qpi)
        if out and self.journey is not None:
            self.journey.popped(out, self.clock())
        return out

    def _mark_in_flight(self, qpi: QueuedPodInfo) -> None:
        self.scheduling_cycle += 1
        qpi.attempts += 1
        if qpi.initial_attempt_timestamp is None:
            qpi.initial_attempt_timestamp = self.clock()
        self.in_flight_pods[qpi.pod.uid] = next(self._event_seq)

    def done(self, uid: str) -> None:
        """schedule_one.go:324 — release the in-flight event log entry."""
        self.in_flight_pods.pop(uid, None)
        if not self.in_flight_pods:
            self.in_flight_events.clear()

    def activate(self, pods: list[Pod]) -> None:
        """PodActivator: force move specific pods to activeQ."""
        for pod in pods:
            qpi = (self.unschedulable_pods.get(pod.uid)
                   or self.backoff_q.get(pod.uid))
            if qpi is None:
                continue
            self.unschedulable_pods.pop(pod.uid, None)
            self.unschedulable_since.pop(pod.uid, None)
            self.backoff_q.delete(pod.uid)
            if qpi.gated:
                self._unindex_gated(qpi.pod)
            qpi.gated = False
            self.active_q.add(pod.uid, qpi)
            self.nominator.add(qpi)

    # -- unschedulable handling ----------------------------------------------

    def add_unschedulable_if_not_present(self, qpi: QueuedPodInfo,
                                         pod_scheduling_cycle: int = 0) -> None:
        """scheduling_queue.go:864. Decides between unschedulablePods and
        backoffQ by replaying cluster events that arrived while this pod was
        being scheduled against the rejector plugins' hints."""
        uid = qpi.pod.uid
        if uid in self.active_q or uid in self.backoff_q or uid in self.unschedulable_pods:
            self.done(uid)
            return
        qpi.timestamp = self.clock()
        # drive the exponential backoff (the reference increments these in
        # the failure handler before calling AddUnschedulableIfNotPresent;
        # we own it here so no caller can forget)
        if qpi.consecutive_errors_count == 0:
            qpi.unschedulable_count += 1
        pop_seq = self.in_flight_pods.get(uid, -1)
        requeue = False
        if qpi.consecutive_errors_count > 0:
            # errors always back off and retry (no event needed)
            requeue = True
        else:
            for ev in self.in_flight_events:
                if ev.seq < pop_seq:
                    continue
                if self._pod_worth_requeuing(qpi, ev.event, ev.old_obj, ev.new_obj):
                    requeue = True
                    break
        self.done(uid)
        if requeue:
            if self._is_backing_off(qpi):
                self.backoff_q.add(uid, qpi)
            else:
                self.active_q.add(uid, qpi)
            self.nominator.add(qpi)
        else:
            self.unschedulable_pods[uid] = qpi
            self.unschedulable_since[uid] = self.clock()
            self.nominator.add(qpi)

    def _pod_worth_requeuing(self, qpi: QueuedPodInfo, event: ClusterEvent,
                             old_obj, new_obj) -> bool:
        """isPodWorthRequeuing (scheduling_queue.go:456): consult only the
        hints of the plugins that rejected the pod; wildcard events requeue
        unconditionally."""
        if event.resource == EventResource.WILDCARD:
            return not qpi.gated
        up, pp = qpi.unschedulable_plugins, qpi.pending_plugins
        rejectors = (up | pp) if (up and pp) else (up or pp)
        if not rejectors:
            return True
        for plugin in rejectors:
            hints = self.queueing_hints.get(plugin)
            if hints is None:
                # plugin registered no hints → conservative requeue on any
                # event (the QueueingHints-disabled behavior)
                return True
            for ewh in hints:
                if not ewh.event.match(event):
                    continue
                if ewh.hint_fn is None:
                    return True
                if ewh.hint_fn(qpi.pod, old_obj, new_obj) == QueueingHint.QUEUE:
                    return True
        return False

    # -- event-driven moves ---------------------------------------------------

    def move_all_to_active_or_backoff_queue(self, event: ClusterEvent,
                                            old_obj=None, new_obj=None,
                                            precheck: Optional[Callable] = None) -> int:
        """scheduling_queue.go:1188. Returns number of pods moved."""
        if self.in_flight_pods:
            self.in_flight_events.append(_InFlightEvent(
                next(self._event_seq), event, old_obj, new_obj))
        moved = 0
        for uid, qpi in list(self.unschedulable_pods.items()):
            if qpi.gated:
                continue
            if precheck is not None and not precheck(qpi.pod):
                continue
            if not self._pod_worth_requeuing(qpi, event, old_obj, new_obj):
                continue
            del self.unschedulable_pods[uid]
            self.unschedulable_since.pop(uid, None)
            if self._is_backing_off(qpi):
                self.backoff_q.add(uid, qpi)
            else:
                self.active_q.add(uid, qpi)
                self.nominator.add(qpi)
            moved += 1
        return moved

    def gated_pods_could_be_ungated(self) -> list[QueuedPodInfo]:
        return [q for q in self.unschedulable_pods.values() if q.gated]

    def retry_gated(self, predicate=None, ref: Optional[str] = None) -> int:
        """Re-runs PreEnqueue for gated pods (the reference re-evaluates on
        pod-update events; we expose an explicit sweep too). `ref` narrows
        the sweep to ONE gang's gated members via the gated_by_ref index
        (O(gang) on a member-pod add, not O(all gated pods)); `predicate`
        is the general filter for everything else."""
        if ref is not None:
            uids = self.gated_by_ref.get(ref)
            if not uids:
                return 0
            candidates = [(uid, self.unschedulable_pods[uid])
                          for uid in list(uids)
                          if uid in self.unschedulable_pods]
        else:
            candidates = list(self.unschedulable_pods.items())
        moved = 0
        for uid, qpi in candidates:
            if not qpi.gated:
                continue
            if predicate is not None and not predicate(qpi.pod):
                continue
            del self.unschedulable_pods[uid]
            self.unschedulable_since.pop(uid, None)
            self._unindex_gated(qpi.pod)
            self._add_qpi(qpi)
            if not qpi.gated:
                moved += 1
        return moved

    # -- periodic flushes (scheduling_queue.go Run :406-413) ------------------

    def flush_backoff_completed(self) -> int:
        moved = 0
        now = self.clock()
        while True:
            qpi = self.backoff_q.peek()
            if qpi is None or self._backoff_expiry(qpi) > now:
                break
            self.backoff_q.pop()
            self.active_q.add(qpi.pod.uid, qpi)
            self.nominator.add(qpi)
            moved += 1
        return moved

    def flush_unschedulable_leftover(self) -> int:
        now = self.clock()
        moved = 0
        for uid, qpi in list(self.unschedulable_pods.items()):
            if qpi.gated:
                continue
            since = self.unschedulable_since.get(uid, now)
            if now - since >= self.pod_max_unschedulable_duration:
                del self.unschedulable_pods[uid]
                self.unschedulable_since.pop(uid, None)
                qpi.timestamp = now
                if self._is_backing_off(qpi):
                    self.backoff_q.add(uid, qpi)
                else:
                    self.active_q.add(uid, qpi)
                moved += 1
        return moved

    # -- introspection --------------------------------------------------------

    def pending_pods(self) -> tuple[list[Pod], str]:
        active = [q.pod for q in self.active_q.items()]
        backoff = [q.pod for q in self.backoff_q.items()]
        unsched = [q.pod for q in self.unschedulable_pods.values()]
        summary = (f"activeQ:{len(active)} backoffQ:{len(backoff)} "
                   f"unschedulablePods:{len(unsched)}")
        return active + backoff + unsched, summary

    def __len__(self) -> int:
        return (len(self.active_q) + len(self.backoff_q)
                + len(self.unschedulable_pods))


def default_queue_sort_less(a: QueuedPodInfo, b: QueuedPodInfo) -> bool:
    """queuesort/priority_sort.go: priority desc, then enqueue time asc."""
    pa, pb = a.pod.spec.priority, b.pod.spec.priority
    if pa != pb:
        return pa > pb
    if a.timestamp != b.timestamp:
        return a.timestamp < b.timestamp
    return a.pod.metadata.creation_index < b.pod.metadata.creation_index


def default_queue_sort_key(q: QueuedPodInfo):
    """The key form of default_queue_sort_less (kept adjacent so the two
    orderings cannot drift apart; test-enforced)."""
    return (-q.pod.spec.priority, q.timestamp, q.pod.metadata.creation_index)
