"""`python -m kubernetes_tpu` — the scheduler binary.

Mirrors cmd/kube-scheduler (app/server.go): options → Setup → Run with
the operational endpoints up. The in-memory API server stands in for the
cluster API; a demo workload (optional) exercises the scheduling loop so
/metrics and /statusz show live numbers.

    python -m kubernetes_tpu --port 10259
    python -m kubernetes_tpu --config scheduler.yaml --demo 1000

The run loop ticks leader election, flushes queue timers, schedules
pending pods, and sleeps — the synchronous analog of scheduler.Run
(scheduler.go:538) driving ScheduleOne until the context ends.
"""

from __future__ import annotations

import argparse
import sys
import time


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="kubernetes_tpu",
                                 description="TPU-native batch scheduler")
    ap.add_argument("--config", help="KubeSchedulerConfiguration YAML")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=10259,
                    help="healthz/readyz/metrics/statusz port (0 = ephemeral)")
    ap.add_argument("--leader-elect", action="store_true")
    ap.add_argument("--demo", type=int, default=0, metavar="PODS",
                    help="create a demo cluster and schedule PODS pods")
    ap.add_argument("--once", action="store_true",
                    help="run one scheduling pass and exit (for scripting)")
    args = ap.parse_args(argv)

    from .backend.apiserver import APIServer
    from .scheduler import Scheduler
    from .server import LeaderElector, SchedulerServer
    from .utils.tracing import Tracer

    cfg = None
    if args.config:
        from .config import load
        cfg = load(args.config)

    api = APIServer()
    sched = Scheduler(api, config=cfg, tracer=Tracer(slow_threshold_s=1.0))
    elector = (LeaderElector(api, identity=f"scheduler-{id(api):x}")
               if args.leader_elect else None)
    server = SchedulerServer(sched, host=args.host, port=args.port,
                             elector=elector).start()
    print(f"serving on http://{args.host}:{server.port} "
          f"(/healthz /readyz /metrics /statusz)", file=sys.stderr)

    if args.demo:
        from .testing.wrappers import make_node, make_pod
        n_nodes = max(args.demo // 10, 4)
        for i in range(n_nodes):
            api.create_node(make_node(f"node-{i}").capacity(
                {"cpu": 32, "memory": "64Gi", "pods": 110})
                .zone(f"zone-{i % 3}").obj())
        for i in range(args.demo):
            api.create_pod(make_pod(f"demo-{i}").req(
                {"cpu": "900m", "memory": "1Gi"}).obj())
        print(f"demo: {n_nodes} nodes, {args.demo} pods", file=sys.stderr)

    try:
        while True:
            if elector is not None:
                elector.tick()
            if elector is None or elector.is_leader():
                sched.flush_queues()
                bound = sched.schedule_pending()
                if bound:
                    print(f"scheduled {bound} pods "
                          f"(total {sched.scheduled_count})", file=sys.stderr)
            if args.once:
                break
            time.sleep(0.5)
    except KeyboardInterrupt:
        pass
    finally:
        if elector is not None:
            elector.release()
        server.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
