"""The Scheduler: host orchestrator around the batched device program.

Mirrors pkg/scheduler/scheduler.go (struct :74, New :282, Run :538) and
schedule_one.go, with one structural change (SURVEY §7): the serial
`ScheduleOne` loop becomes `schedule_pending`, which drains the whole activeQ
and assigns it in device-sized batches — one `run_batch` call per segment —
while pods whose constraints have no tensor form yet fall back to the host
oracle (`schedule_one_host`) in queue order, preserving the sequential-greedy
semantics end to end.

Cycle anatomy per batch (device segment):
  update_snapshot (incremental, cache.go:194) → apply_snapshot scatter →
  run_batch scan (ops/program.py) → per pod: assume (cache.go:369) +
  enqueue bind (api_dispatcher) | handleSchedulingFailure
  (schedule_one.go:1038) → adopt carry → flush dispatcher.

Bind failures forget the assumed pod and requeue (schedule_one.go:361-393).
Informer events feed the cache/queue exactly like eventhandlers.go and fire
MoveAllToActiveOrBackoffQueue with the matching ClusterEvent.
"""

from __future__ import annotations

import threading
import time as _time
from collections import deque
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Callable, Optional

import jax.numpy as jnp
import numpy as np

from .api.types import DEFAULT_SCHEDULER_NAME, Node, Pod
from .backend.apiserver import APIServer, FencedWrite, WatchHandlers
from .backend.cache import Cache, Snapshot
from .backend.dispatcher import APICall, APIDispatcher, CallType
from .backend.queue import ClusterEventWithHint, SchedulingQueue
from .backend.workloadmanager import (DEFAULT_SCHEDULING_TIMEOUT,
                                      WorkloadManager)
from .framework.interface import Code, CycleState, Status
from .framework.runtime import Framework, schedule_pod
from .framework.types import (ActionType, ClusterEvent, EventResource,
                              FitError, PodInfo, QueuedPodInfo)
from .obs.journey import (EV_ADOPT as _EV_ADOPT, EV_ASSIGN as _EV_ASSIGN,
                          EV_DRAIN as _EV_DRAIN, EV_EVICT as _EV_EVICT,
                          EV_FIT_ERROR as _EV_FIT_ERROR,
                          EV_PARK as _EV_PARK, EV_REQUEUE as _EV_REQUEUE)
from .ops.program import (PROBE_STATS, PodXs, ScoreConfig, WaveXs,
                          cluster_probe, initial_carry, run_batch,
                          run_plan, run_uniform, run_wave,
                          table_from_batch)
from .plugins import noderesources as nr
from .plugins.node_basics import (NodeName, NodePorts, NodeUnschedulable,
                                  PrioritySort, SchedulingGates,
                                  TaintToleration)
from .plugins.imagelocality import ImageLocality
from .plugins.interpodaffinity import InterPodAffinity
from .plugins.nodeaffinity import NodeAffinity
from .plugins.podtopologyspread import PodTopologySpread
from .state.batch import BatchBuilder, BatchDims
from .state.tensorize import (EFFECT_PREFER_NO_SCHEDULE, ClusterState,
                              pow2_at_least)
from .utils.logging import klog

EVENT_NODE_ADD = ClusterEvent(EventResource.NODE, ActionType.ADD)
EVENT_NODE_UPDATE = ClusterEvent(EventResource.NODE, ActionType.UPDATE)
EVENT_ASSIGNED_POD_DELETE = ClusterEvent(EventResource.ASSIGNED_POD, ActionType.DELETE)
EVENT_ASSIGNED_POD_ADD = ClusterEvent(EventResource.ASSIGNED_POD, ActionType.ADD)
EVENT_POD_UPDATE = ClusterEvent(EventResource.POD, ActionType.UPDATE)


def node_update_action(old: Node, new: Node) -> ActionType:
    """Per-property node update flags (eventhandlers.go:88-99
    nodeSchedulingPropertiesChange): precise flags let queueing hints skip
    pods whose rejection the change cannot fix. Unschedulable flips map to
    the taint flag exactly like the reference (cordon == taint)."""
    flags = ActionType(0)
    if new.status.allocatable != old.status.allocatable:
        flags |= ActionType.UPDATE_NODE_ALLOCATABLE
    if new.metadata.labels != old.metadata.labels:
        flags |= ActionType.UPDATE_NODE_LABEL
    if (new.spec.taints != old.spec.taints
            or new.spec.unschedulable != old.spec.unschedulable):
        flags |= ActionType.UPDATE_NODE_TAINT
    if new.status.declared_features != old.status.declared_features:
        flags |= ActionType.UPDATE_NODE_DECLARED_FEATURE
    return flags


def pod_update_action(old: Pod, new: Pod) -> ActionType:
    """Per-property pod update flags (eventhandlers.go
    podSchedulingPropertiesChange)."""
    flags = ActionType(0)
    if new.metadata.labels != old.metadata.labels:
        flags |= ActionType.UPDATE_POD_LABEL
    if new.spec.scheduling_gates != old.spec.scheduling_gates:
        flags |= ActionType.UPDATE_POD_SCHEDULING_GATES
    if new.spec.tolerations != old.spec.tolerations:
        flags |= ActionType.UPDATE_POD_TOLERATION
    from .api import resources as res
    old_req = res.pod_requests(old)
    new_req = res.pod_requests(new)
    if any(new_req.get(k, 0) < v for k, v in old_req.items()):
        flags |= ActionType.UPDATE_POD_SCALE_DOWN
    return flags

# default plugin weights (apis/config/v1/default_plugins.go:30-93)
DEFAULT_WEIGHTS = {
    "TaintToleration": 3,
    "NodeAffinity": 2,
    "PodTopologySpread": 2,
    "InterPodAffinity": 2,
    "NodeResourcesFit": 1,
    "NodeResourcesBalancedAllocation": 1,
    "ImageLocality": 1,
}


def default_plugin_factories(client=None, ns_lister=None) -> list:
    """Ordered ZERO-ARG factories for the default plugin set. Each factory
    call constructs ONE fresh plugin (plugin objects carry per-scheduler
    handles, so instances must never be shared across profiles), without
    building the whole list — config.default_registry previously rebuilt
    the full default_plugins list per lookup (O(n²) across a registry
    walk)."""
    from .plugins.defaultbinder import DefaultBinder
    from .plugins.gangscheduling import GangScheduling
    from .plugins.volume_basics import (NodeVolumeLimits, VolumeRestrictions,
                                        VolumeZone)
    from .plugins.volumebinding import VolumeBinding
    from .plugins.dynamicresources import DynamicResources
    # filter order mirrors apis/config/v1/default_plugins.go:30
    from .plugins.node_basics import NodeDeclaredFeatures
    factories = [
        SchedulingGates, GangScheduling, PrioritySort,
        NodeDeclaredFeatures,
        NodeUnschedulable, NodeName, TaintToleration, NodeAffinity,
        NodePorts, nr.Fit,
        lambda: VolumeRestrictions(client),
        lambda: NodeVolumeLimits(client),
        lambda: VolumeBinding(client),
        lambda: VolumeZone(client),
        lambda: DynamicResources(client),
        nr.BalancedAllocation, PodTopologySpread,
        lambda: InterPodAffinity(ns_lister=ns_lister),
        ImageLocality,
    ]
    if client is not None:
        factories.append(lambda: DefaultBinder(client))
    return factories


def default_plugins(client=None, ns_lister=None) -> list:
    return [f() for f in default_plugin_factories(client, ns_lister)]


@dataclass
class Profile:
    name: str = DEFAULT_SCHEDULER_NAME
    framework: Optional[Framework] = None
    score_config: ScoreConfig = ScoreConfig()
    # True when every reserve/permit plugin is gang-only: non-gang pods can
    # then skip the per-bind framework hooks entirely (hot path)
    gang_only_hooks: bool = False
    # plugin names the config disabled (auto-wiring must not re-add them)
    disabled_plugins: tuple = ()
    # True when VolumeBinding is the only PreBind plugin: volume-free pods
    # can then skip the PreBind phase entirely (hot path)
    volume_only_pre_bind: bool = False
    # out-of-process extenders (framework/extender.py). Extenders are
    # API-coupled, so a profile with any routes ALL its pods through the
    # host oracle — the analog of the reference disabling batching when
    # extenders are configured (runtime/framework.go:775-780)
    extenders: tuple = ()


def _needs_per_pod_hooks(profile: "Profile", spec) -> bool:
    """True when a pod must run the full reserve/permit/pre-bind chain in
    _assume_and_bind. MUST mirror _assume_and_bind's `run_hooks` gate and
    _run_pre_bind's volume skip — _fast_commit bypasses both for pods
    where this returns False, so any change to either gate changes this
    predicate too."""
    fwk = profile.framework
    return bool(
        ((fwk.reserve_plugins or fwk.permit_plugins)
         and (not profile.gang_only_hooks
              or spec.workload_ref or spec.volumes or spec.resource_claims))
        or (fwk.pre_bind_plugins
            and (not profile.volume_only_pre_bind
                 or spec.volumes or spec.resource_claims)))


@dataclass
class _RunRec:
    """One dispatched device run (a uniform top-L call, a scan segment, or
    a wave) awaiting readback. `carry_in` is the device carry the run
    consumed — kept ONLY for uniform runs (the one kind that can rewind
    and replay); scan/wave runs DONATE their input carry on accelerator
    backends, so holding it would be a dangling reference."""

    kind: str                 # "uniform" | "scan" | "wave" | "wavescan"
    i: int
    j: int
    carry_in: object
    result: object            # device array: packed or assignments
    L: int = 0                # uniform L / wave bucket (packed layout)
    J: int = 0
    uniform: bool = False
    span: tuple = ("scan",)   # full span descriptor (replay re-dispatch)


@dataclass
class _PendingDrain:
    """A dispatched-but-uncommitted queue drain: the device results are in
    flight (copy_to_host_async issued); the host commit (assume + bind +
    failure handling) runs when they arrive. This is the TPU analog of the
    reference's async binding cycle (schedule_one.go:123 bindingCycle
    goroutine): the scheduling algorithm races ahead of the commit I/O."""

    qpis: list
    profile: object
    batch: object             # PodBatch (numpy) — kept for replay
    table: object             # PodTableDev
    na: object                # NodeArrays used at dispatch
    n: int
    groups_needed: bool
    records: list = field(default_factory=list)
    dispatched_at: float = 0.0
    # per-phase wall times + wave stats, accumulated from dispatch through
    # commit; the flight recorder persists them per drain
    phases: dict = field(default_factory=dict)
    wave: dict = field(default_factory=dict)
    # nominated-pod resource overlay active at dispatch (None = none);
    # replays must reproduce the dispatch-time overlay
    ovl: object = None
    # per-pod self-nomination rows (i32 [n], -1 = none) paired with ovl
    nom: object = None
    # monotonic drain id: correlates this drain's log lines, spans,
    # FlightRecorder entry and Scheduled/FailedScheduling events
    drain_id: int = 0
    # whole-gang drain (ops/gang.py): (workload ref, remaining quorum,
    # minCount) when this drain is one gang solved all-or-nothing
    gang: object = None
    # the builder's per-row CommitFacts list at dispatch time (the list
    # object is REPLACED on table reset, so this reference stays aligned
    # with batch.tidx even when later drains reset the table)
    facts: object = None
    gang_accepted: bool = False
    gang_raw: object = None      # raw per-member assignments (pre-unwind)
    gang_placed: int = 0
    # shadow-oracle audit record captured for this drain (obs/audit.py);
    # None = unsampled. Submitted with the committed decisions.
    audit: object = None
    # in-flight cluster_probe result (device arrays, ClusterStateProbe
    # gate): dispatched right after the drain over the post-drain carry,
    # resolved to a snapshot dict when this drain commits
    probe: object = None
    # per-kernel dispatch seconds captured inside this drain's
    # device_dispatch span (perf/observatory.py device lane); {} with the
    # KernelObservatory gate off
    kernels: dict = field(default_factory=dict)

    def ready(self) -> bool:
        return all(r.result.is_ready() for r in self.records
                   if hasattr(r.result, "is_ready"))


@dataclass
class _WaitingPodRec:
    """A pod parked at Permit (reference runtime/waiting_pods_map.go): its
    resources stay assumed in the cache until allowed or rejected."""

    qpi: QueuedPodInfo
    assumed: Pod
    node_name: str
    cycle_state: CycleState
    deadline: float
    parked_at: float = 0.0
    wait_plugin: str = ""


class _WaitingPodHandle:
    """The WaitingPod the Permit plugins see (framework.WaitingPod). With a
    single permit plugin per profile, one Allow releases the pod (the
    reference requires every permit plugin's allow; the plugin-set loop in
    run_permit_plugins already serializes them)."""

    def __init__(self, scheduler: "Scheduler", uid: str):
        self._scheduler = scheduler
        self._uid = uid

    def allow(self, plugin_name: str) -> None:
        self._scheduler._allow_waiting(self._uid)

    def reject(self, plugin_name: str, reason: str = "") -> None:
        self._scheduler._reject_waiting(self._uid, reason)


class Scheduler:
    """scheduler.Scheduler (scheduler.go:74)."""

    def __init__(self, client: APIServer,
                 profiles: Optional[list[Profile]] = None,
                 batch_size: Optional[int] = None,
                 batch_dims: Optional[BatchDims] = None,
                 clock: Callable[[], float] = _time.monotonic,
                 percentage_of_nodes_to_score: Optional[int] = None,
                 config=None,
                 metrics=None,
                 tracer=None,
                 mesh=None):
        """`config` is a config.KubeSchedulerConfiguration — when given it
        supplies profiles, batch size, backoffs and sampling percentage;
        explicitly passed arguments win over the config's values.

        `mesh` (a jax.sharding.Mesh) makes multi-chip first-class: every
        drain-plan span — scan buckets, the closed-form uniform tier,
        speculative waves, gang dispatch and the batched preemption
        dry-run — runs the node-axis-sharded program (parallel/sharding.py)
        with XLA collectives over ICI. Decisions are bit-identical to
        single-device scheduling (tests/test_sharding.py +
        tests/test_sharded_mesh_parity.py)."""
        self.client = client
        self.clock = clock
        queue_backoffs = {}
        from .config import apply_compilation_cache
        apply_compilation_cache(
            config.compilation_cache_dir if config is not None else None)
        from .config.features import default_gate
        self.feature_gates = default_gate(
            config.feature_gates if config is not None else None)
        # columnar ingest & commit engine gate (kubernetes_tpu/ingest/):
        # consulted by the event-handler wiring below and the commit path
        self.columnar_ingest = self.feature_gates.enabled("ColumnarIngest")
        if config is not None:
            config.validate()
            from .config import build_profiles
            if profiles is None:
                profiles = build_profiles(config, client)
            if batch_size is None:
                batch_size = config.batch_size
            if percentage_of_nodes_to_score is None:
                percentage_of_nodes_to_score = config.percentage_of_nodes_to_score
            queue_backoffs = dict(
                pod_initial_backoff=config.pod_initial_backoff_seconds,
                pod_max_backoff=config.pod_max_backoff_seconds)
        self.batch_size = 512 if batch_size is None else batch_size
        self.mesh = mesh
        if mesh is not None:
            n_dev = int(mesh.devices.size)
            if n_dev & (n_dev - 1):
                raise ValueError(
                    f"mesh size {n_dev} must be a power of two: the pow2 "
                    "node-bucket padding guarantees shard divisibility "
                    "only then (run_batch_sharded precondition)")

        self._na_sharded = None      # mesh-placed NodeArrays cache
        self._na_sharded_gen = -1    # staging generation it was built from
        # Compatibility knob (types.go:62): the reference samples nodes to
        # bound filter cost; the TPU program filters ALL nodes in one
        # vectorized pass, so 100 is both the default and the fast path.
        # Values < 100 are accepted for config parity and treated as 100 —
        # SURVEY §7: adaptive sampling is deliberately dropped because the
        # full filter is cheaper than the bookkeeping it would save.
        self.percentage_of_nodes_to_score = (
            100 if percentage_of_nodes_to_score is None
            else percentage_of_nodes_to_score)
        if self.percentage_of_nodes_to_score < 100:
            # startup honesty: a config asking for sampling gets the full
            # vectorized pass, which changes decisions vs a sampling
            # reference (different node subset → different winner)
            klog.warning(
                "percentageOfNodesToScore below 100 is treated as 100: "
                "the device program filters and scores every node in one "
                "vectorized pass (SURVEY §7: sampling deliberately dropped)",
                requested=self.percentage_of_nodes_to_score)
        if profiles is None:
            fwk = Framework(DEFAULT_SCHEDULER_NAME, default_plugins(client),
                            weights=dict(DEFAULT_WEIGHTS))
            profiles = [Profile(framework=fwk)]
        self.profiles: dict[str, Profile] = {p.name: p for p in profiles}

        self.cache = Cache(clock=clock)
        self.snapshot = Snapshot()
        self.state = ClusterState()
        if mesh is not None:
            # the node bucket must never be smaller than the mesh
            self.state.dims.nodes = max(self.state.dims.nodes,
                                        int(mesh.devices.size))
        default_plugins_list = next(iter(self.profiles.values())).framework.plugins
        spread_p = next((p for p in default_plugins_list
                         if p.name() == "PodTopologySpread"), None)
        ipa_p = next((p for p in default_plugins_list
                      if p.name() == "InterPodAffinity"), None)
        self.builder = BatchBuilder(self.state, batch_dims,
                                    spread_plugin=spread_p, ipa_plugin=ipa_p)
        self.dispatcher = APIDispatcher(
            client=client, on_bind_error=self._on_bind_error)
        self.config = config    # retained: ShardManager reads incident_dir
        if config is not None:
            self.dispatcher.retry_max_attempts = config.api_retry_max_attempts
            self.dispatcher.retry_base_seconds = config.api_retry_base_seconds

        default_fwk = next(iter(self.profiles.values())).framework
        # SchedulerQueueingHints off → empty hint map → every event
        # requeues conservatively (the gate-off behavior in the reference,
        # scheduling_queue.go isPodWorthRequeuing without hints)
        hints = (self._build_queueing_hints(default_fwk)
                 if self.feature_gates.enabled("SchedulerQueueingHints")
                 else {})
        # kept so resync() can rebuild the queue with identical wiring
        self._queue_kwargs = dict(
            pre_enqueue=self._make_pre_enqueue(default_fwk),
            queueing_hints=hints,
            clock=clock, **queue_backoffs)
        self.queue = SchedulingQueue(**self._queue_kwargs)

        from .metrics import SchedulerMetrics
        self.metrics = metrics or SchedulerMetrics(
            queue_depths=self._queue_depths,
            inflight=self._inflight_depths)
        self.dispatcher.metrics = self.metrics
        # generation-diff upload counters (state/tensorize.py): the state
        # layer counts, the registry exposes
        self.state.metrics = self.metrics
        for prof in self.profiles.values():
            prof.framework.metrics = self.metrics
        from .backend.debugger import CacheDebugger
        self.debugger = CacheDebugger(client, self.cache, self.queue,
                                      metrics=self.metrics)
        from .utils.tracing import NOOP_TRACER
        self.tracer = tracer or NOOP_TRACER
        # decision provenance + drain telemetry (events.py): Scheduled /
        # FailedScheduling events and the per-drain flight ring, both
        # served by the SchedulerServer's /debug endpoints
        from .events import EventRecorder, FlightRecorder
        self.events = EventRecorder(clock=clock, metrics=self.metrics)
        self.flight = FlightRecorder()
        # SLO burn-rate engine (obs/slo.py): SLI good/bad streams through
        # multi-window (5m/1h/6h) burn tracking; the burn-rate gauge is a
        # scrape-time callback and /debug/slo serves the full snapshot
        from .obs.slo import SLOEngine
        self.slo = SLOEngine(
            clock=clock,
            objectives=(config.slo_objectives if config is not None
                        else None))
        self.metrics.slo_burn_rate.callback = self.slo.gauge_callback
        # pod-journey tracing (obs/journey.py, `PodJourneyTracing` gate):
        # the columnar lifecycle ring behind /debug/pod and the
        # e2e_segment families. The ledger also OWNS the first-enqueue
        # e2e SLI clock, which stays on even with the gate off (the
        # requeue-restarts-the-clock bugfix must hold regardless), so
        # the ledger object always exists.
        from .obs.journey import JourneyLedger
        from .obs.timeline import Timeline
        self.journey = JourneyLedger(
            clock=clock, metrics=self.metrics,
            enabled=self.feature_gates.enabled("PodJourneyTracing"))
        self.queue.journey = self.journey
        self.dispatcher.journey = self.journey
        # per-second telemetry timeline (obs/timeline.py,
        # `TelemetryTimeline` gate): /debug/timeline + the config-gated
        # JSON-lines exporter; SLO samples stamp each closing bucket
        self.timeline = Timeline(
            horizon=(config.timeline_horizon_seconds
                     if config is not None else 900),
            clock=clock,
            export_path=(config.timeline_export_path
                         if config is not None else ""),
            slo_sample=self._timeline_slo_sample,
            enabled=self.feature_gates.enabled("TelemetryTimeline"))
        self.journey.timeline = self.timeline
        # on-device cluster analytics (ops cluster_probe,
        # `ClusterStateProbe` gate): one reduction over the resident
        # carry per device drain; resolved async at commit
        self._probe_enabled = self.feature_gates.enabled(
            "ClusterStateProbe")
        self._last_probe = None      # latest resolved snapshot (dict)
        # streaming drain pipeline (kubernetes_tpu/pipeline.py): attached
        # by StreamingPipeline.start(); backs /debug/pipeline
        self.pipeline = None
        # external-mutation counter: bumped with every device-state
        # invalidation; the shadow audit compares it across a drain's
        # dispatch→commit window (reason diffs are only valid when the
        # snapshot the device diagnosis read didn't move underneath)
        self._ext_mutations = 0
        # shadow-oracle audit (obs/audit.py, `ShadowOracleAudit` gate):
        # sampled drains are captured into the hash-chained ledger and
        # re-executed through the host oracle on a background worker
        self.audit = None
        if self.feature_gates.enabled("ShadowOracleAudit"):
            from .obs.audit import ShadowOracleAudit
            self.audit = ShadowOracleAudit(
                sample_rate=(config.shadow_audit_sample_rate
                             if config is not None else 1.0 / 64.0),
                max_replay_pods=(config.shadow_audit_max_replay_pods
                                 if config is not None else 64),
                dirpath=(config.shadow_audit_dir
                         if config is not None else ""),
                metrics=self.metrics, slo=self.slo,
                gates=self.feature_gates)
        # test-only decision-perturbation hook (tests/test_chaos.py):
        # a callable(pd, out) mutating resolved assignments in place —
        # proof that the shadow audit can actually fail
        self._test_assignment_perturb = None
        # jax.profiler session directory (config profilerTraceDir; "" = off)
        self.profiler_trace_dir = (
            config.profiler_trace_dir if config is not None else "")
        # continuous host profiling (perf/profiler.py): a sampling thread
        # follows the host-loop thread, tagging every stack sample with
        # the open drain phase (PhaseTrack, pushed in lockstep with the
        # tracer spans) and the dispatching drain's signature-cardinality
        # bucket. The thread starts lazily on the first schedule call and
        # exits when this Scheduler is collected (weakref owner).
        from .utils.tracing import PhaseTrack
        self.phase_track = PhaseTrack()
        self._drain_seq = 0          # monotonic drain id (drain_id=0: none)
        self._sig_bucket_cell = [0]  # profiler-visible drain sig count
        self.profiler = None
        self.host_profiler_hz = (
            config.host_profiler_hz if config is not None else 200.0)
        if (self.host_profiler_hz > 0
                and self.feature_gates.enabled("ContinuousHostProfiling")):
            from .perf.profiler import HostProfiler
            cell = self._sig_bucket_cell
            self.profiler = HostProfiler(
                hz=self.host_profiler_hz,
                phase_fn=self.phase_track.current,
                bucket_fn=(lambda c=cell: c[0]),
                owner=self)
        # runtime sanitizer rails (analysis/rails.py): like the compile
        # ledger, the instance is process-global (the jit caches and the
        # transfer-guard config it drives are process-global) — the gate
        # of the most recently constructed Scheduler wins
        from .analysis.rails import GLOBAL as _rails
        self.rails = _rails
        self.rails.enable(self.feature_gates.enabled("SanitizerRails"))
        # kernel observatory (perf/observatory.py, `KernelObservatory`
        # gate): per-dispatch run-time attribution fed by the compile
        # ledger's measured_call. Process-global like the rails/ledger —
        # the most recently constructed Scheduler's gate wins.
        from .perf.observatory import GLOBAL as _observatory
        self.observatory = _observatory
        self.observatory.enable(
            self.feature_gates.enabled("KernelObservatory"))
        # critical-path observatory (perf/critical_path.py,
        # `CriticalPathObservatory` gate): per-drain bottleneck verdicts
        # stamped at commit, plus the device cost model fed by compile
        # events (perf/costmodel.py via the observatory — process-global,
        # most recent Scheduler's gate wins like the rails/observatory)
        self.critical_path_enabled = self.feature_gates.enabled(
            "CriticalPathObservatory")
        self.observatory.enable_cost_model(self.critical_path_enabled)
        # pipeline backpressure stall seconds already attributed to a
        # committed drain's verdict (delta baseline; StreamingPipeline
        # .start() zeroes it when a fresh pipeline attaches)
        self._bp_stall_committed = 0.0
        # sharded-lane profile (parallel/sharding.py profile_shard_lanes):
        # the first sharded dispatch stashes its inputs; the profile runs
        # ONCE after that drain commits (and on demand via
        # profile_shard_lanes(force=True) or /debug/kernels?lanes=refresh).
        # shard_profile_auto=False defers the auto-run — the probe
        # re-dispatches the scan-shaped program, so a throughput harness
        # (bench.py) measures first and profiles after the clock stops
        self._shard_profile_args = None
        self._shard_profile_done = False
        self.shard_profile_auto = True

        self.workload_manager = WorkloadManager(clock=clock)
        # pods parked at Permit (WaitOnPermit): uid -> _WaitingPodRec
        self._waiting_pods: dict[str, _WaitingPodRec] = {}
        # gang device placement (ops/gang.py run_gang): whole pod groups
        # solved as ONE all-or-nothing device dispatch once PreEnqueue
        # quorum is met — no Reserve/Permit/Unreserve churn on either the
        # accept or the reject path. Ineligible gangs (host-fallback
        # signatures, group constraints, pending nominations, parked
        # members) keep the reference's Permit-barrier host path.
        self.gang_device_enabled = (
            self.feature_gates.enabled("GenericWorkload")
            and self.feature_gates.enabled("GangDevicePlacement"))
        # Tesserae-style topology-contiguous slice packing: weight of the
        # per-domain member-count score column in the gang scan (0 = off,
        # keeping gang placements bit-identical to the serial oracle)
        self.gang_contiguity_weight = 0
        self._gang_dom = None        # device i32[N] node→domain ids
        self._gang_dom_key = (-1, -1)  # (staging_gen, node bucket) it fits
        self._gang_ndom = 1          # static domain count (probe jit key)
        # first-gated time per workload ref → gang_quorum_wait_seconds
        self._gang_gated_since: dict[str, float] = {}
        # HA role lifecycle (ha/standby.py, ActiveStandbyHA gate):
        # "active" schedules; "standby" only consumes watch events to keep
        # cache/queue/device state warm — schedule_pending refuses to
        # dispatch until promote() flips the role at takeover
        self.ha_role = "active"
        # sharded control plane (ha/shards.py): when shard_filter is set,
        # the watch stream forks — owned unbound pods queue; peers' pods
        # PARK in _shard_parked while workload/cache state still ingests
        # them, so a shard steal is shard_adopt() from the parked set (a
        # warm handoff), not a cold LIST + re-tensorize
        self.shard_filter: Optional[Callable[[Pod], bool]] = None
        self.shard_ids: tuple = ()   # owned shard ids (flight tag/debug)
        self._shard_parked: dict[str, Pod] = {}
        # ingest lock: watch handlers mutate queue/cache/workload state
        # from the API thread while sync()/resync() rebuild the same
        # structures — both sides hold this for their full critical
        # section (reentrant: a handler can fire inside resync's LIST)
        self.ingest_lock = threading.RLock()
        # hand every GangScheduling plugin its Handle (this Scheduler)
        from .plugins.gangscheduling import GangScheduling
        for prof in self.profiles.values():
            for p in prof.framework.plugins:
                if isinstance(p, GangScheduling):
                    p.handle = self
            from .plugins.dynamicresources import DynamicResources
            from .plugins.volumebinding import VolumeBinding
            # "gang_only": every reserve/permit plugin is scoped to gang,
            # volume or claim pods, so a pod with none of those skips the
            # hook chain (paired with _needs_per_pod_hooks)
            prof.gang_only_hooks = all(
                isinstance(p, (GangScheduling, VolumeBinding,
                               DynamicResources))
                for p in (prof.framework.reserve_plugins
                          + prof.framework.permit_plugins))
            prof.volume_only_pre_bind = all(
                isinstance(p, (VolumeBinding, DynamicResources))
                for p in prof.framework.pre_bind_plugins)

        # wire preemption (PostFilter) into every profile: the Evaluator
        # needs live handles (dispatcher, nominator, snapshot) that exist
        # only now — the reference threads the same deps through
        # frameworkImpl (default_preemption.go New)
        from .plugins.defaultpreemption import DefaultPreemption
        for prof in self.profiles.values():
            fwk = prof.framework
            dp = next((p for p in fwk.plugins
                       if isinstance(p, DefaultPreemption)), None)
            if dp is None:
                if "DefaultPreemption" in prof.disabled_plugins:
                    continue  # config turned preemption off for this profile
                dp = DefaultPreemption()
                fwk.plugins.append(dp)
                fwk.post_filter_plugins.append(dp)
            dp.dispatcher = self.dispatcher
            dp.nominator = self.queue.nominator
            dp.snapshot = self.snapshot
            if hasattr(client, "list_pdbs"):
                dp.pdb_lister = client.list_pdbs
            dp.extenders = tuple(prof.extenders)
            # batched device dry-run (SURVEY §7 step 8): the Evaluator's
            # candidate sweep runs as one gathered kernel against the
            # tensorized state; gated for config parity. On a mesh the
            # candidate rows are gathered host-side into a compact
            # single-device block (ISSUE 16) so the dry-run never mints a
            # second full-matrix device copy next to the sharded one
            if self.feature_gates.enabled("BatchedPreemptionDryRun"):
                from .framework.preemption import DeviceDryRunContext
                dp.device_ctx = DeviceDryRunContext(
                    state=self.state, builder=self.builder,
                    snapshot=self.snapshot, mesh=mesh)
            dp.set_framework(fwk)

        self._register_event_handlers()
        # stats (metrics/metrics.go essentials; full registry in metrics/)
        self.schedule_attempts = 0
        self.scheduled_count = 0
        self.unschedulable_count = 0
        self.error_count = 0
        self.device_batches = 0
        self.host_greedy_runs = 0
        self.host_scheduled = 0
        self.preemption_attempts = 0
        # device-tier degradation: an XLA fault (or garbage assignment
        # tensor) falls the batch back to the host oracle; K consecutive
        # faults open a circuit breaker that routes every drain to the
        # host path until a cooldown expires, after which ONE probe drain
        # re-tries the device tier (half-open)
        self.device_fault_threshold = 3
        self.device_fault_cooldown = 30.0
        self.device_fallbacks = 0
        self._device_faults = 0          # consecutive
        self._breaker_open_until = 0.0
        self._breaker_open = False
        # per-pod consecutive bind-error count → escalating error backoff
        self._bind_errors: dict[str, int] = {}
        # Device-resident scan carry, reused across batches while no event
        # outside the device's own placements touches node state. This is
        # what keeps steady-state scheduling free of host→device uploads and
        # device→host carry readbacks (SURVEY §7 hard-part 4: the round-trip
        # budget). Any external mutation invalidates it; the next device
        # segment reseeds from the host snapshot.
        self._device_carry = None
        self._carry_profile = None   # profile whose cfg filled the sig cache
        # nominator version the resident carry's SigCache overlay was
        # computed under (-1 = no nominations): _slow_parts/_row_refresh
        # bake the dispatch-time overlay into the cached fit_ok, so any
        # nomination change must zero the sig exactly like a profile
        # switch (ADVICE r5 high)
        self._carry_ovl_fp = -1
        # dispatched-but-uncommitted drains (async commit pipeline). Depth
        # bounds the optimism: device results stream back via
        # copy_to_host_async while later drains are created/dispatched, so
        # the ~100ms tunneled readback latency pipelines instead of gating
        # every drain (SURVEY §7 hard-part 4).
        self._pending: deque[_PendingDrain] = deque()
        # SchedulerAsyncAPICalls off = no optimism: every dispatch commits
        # before the next (the reference's synchronous API-call mode)
        self.max_inflight_drains = (
            8 if self.feature_gates.enabled("SchedulerAsyncAPICalls") else 0)
        # device-resident PodTable cache: rows only append and the version
        # bumps on every mutation, so one upload serves every drain until
        # a new signature appears (the per-drain re-upload was ~25 tunnel
        # transfers each)
        self._table_dev = None
        self._table_dev_version = -1
        # group (spread / inter-pod affinity) device state lifecycle
        self._builder_reset_seen = 0  # builder.reset_count already consumed
        self._gd_dev = None          # GroupsDev (jnp) for the current carry
        self._gd_fam = None          # static active-family mask (jit key)
        self._gd_capacity = None     # (groups.device_rows(), node_bucket)
        #                              the resident group tensors were built
        #                              for; any pow2 crossing of the live
        #                              row count (or node growth) reseeds
        self._seeded_rows = 0        # signature rows whose counts are seeded
        # the drain compiler (kubernetes_tpu/compiler/): maps every
        # drain's pod mix to a static device program over the pow2
        # signature lattice — the case dispatch (uniform/scan/wave/
        # wavescan, the >4-signature cliff, the host-greedy gate) lives
        # there now. It owns the per-signature SurfaceCache (hoisted
        # kernel surfaces, retained across placement-only generations)
        # and the keyed plan cache the compile ledger's fixed retrace
        # point rests on.
        from .compiler import DrainCompiler
        self.compiler = DrainCompiler(state=self.state,
                                      builder=self.builder,
                                      gates=self.feature_gates,
                                      metrics=self.metrics)
        # columnar ingest & commit engine (kubernetes_tpu/ingest/): the
        # batched assume/bind path + the bulk bind-echo confirm; off
        # restores the serial per-pod paths (parity-test oracle)
        from .ingest.commit import CommitEngine
        self.commit_engine = (CommitEngine(self)
                              if self.columnar_ingest else None)
        # below this span length the per-pod scan beats a wave dispatch
        self.wave_min_span = 24

    # -- wiring ---------------------------------------------------------------

    @staticmethod
    def _make_pre_enqueue(fwk: Framework):
        """PreEnqueue gate with a constant-time fast path: when the only
        PreEnqueue plugins are the standard pair (SchedulingGates gates on
        spec.schedulingGates, GangScheduling on spec.workloadRef), a pod
        with neither field set cannot be gated — skip the plugin loop
        entirely (this runs once per created pod, on the ingest hot
        path)."""
        std_only = all(p.name() in ("SchedulingGates", "GangScheduling")
                       for p in fwk.pre_enqueue_plugins)
        if not std_only:
            return fwk.run_pre_enqueue_plugins
        run = fwk.run_pre_enqueue_plugins
        ok = Status.success()

        def pre_enqueue(pod: Pod) -> Status:
            spec = pod.spec
            if not spec.scheduling_gates and not spec.workload_ref:
                return ok
            return run(pod)
        return pre_enqueue

    @staticmethod
    def _build_queueing_hints(fwk: Framework) -> dict[str, list[ClusterEventWithHint]]:
        hints: dict[str, list[ClusterEventWithHint]] = {}
        for p in fwk.plugins:
            if hasattr(p, "events_to_register"):
                hints[p.name()] = list(p.events_to_register())
        return hints

    def _queue_depths(self) -> dict:
        gated = sum(1 for q in self.queue.unschedulable_pods.values()
                    if q.gated)
        return {("active",): float(len(self.queue.active_q)),
                ("backoff",): float(len(self.queue.backoff_q)),
                ("unschedulable",): float(
                    len(self.queue.unschedulable_pods) - gated),
                ("gated",): float(gated)}

    def _inflight_depths(self) -> dict:
        """scheduler_dispatcher_inflight{kind} callback: the async commit
        pipeline's live depth at scrape time."""
        return {("api_calls",): float(len(self.dispatcher)),
                ("drains",): float(len(self._pending))}

    # -- framework.Handle surface for Permit plugins --------------------------

    def get_workload(self, namespace: str, name: str):
        return self.client.get_workload(name)

    def activate(self, pods: list[Pod]) -> None:
        self.queue.activate(pods)

    def now(self) -> float:
        return self.clock()

    def get_waiting_pod(self, uid: str):
        if uid in self._waiting_pods:
            return _WaitingPodHandle(self, uid)
        return None

    def _allow_waiting(self, uid: str) -> None:
        """WaitOnPermit resolved positively: complete the parked pod's
        binding (schedule_one.go:302 onward)."""
        rec = self._waiting_pods.pop(uid, None)
        if rec is None:
            return
        self.metrics.permit_wait_duration.observe(
            max(self.clock() - rec.parked_at, 0.0), "allowed")
        profile = self.profiles.get(rec.qpi.pod.spec.scheduler_name)
        if profile is not None and not self._run_pre_bind(
                profile, rec.cycle_state, rec.qpi, rec.assumed,
                rec.node_name):
            return
        self.cache.finish_binding(rec.assumed)
        self.dispatcher.add(APICall(CallType.BIND, rec.assumed,
                                    node_name=rec.node_name))
        self.scheduled_count += 1
        self.events.scheduled(rec.qpi.pod.uid, rec.node_name)
        from .metrics import SCHEDULED
        pod = rec.qpi.pod
        self.metrics.schedule_attempts.inc(SCHEDULED,
                                           pod.spec.scheduler_name)
        start = rec.qpi.initial_attempt_timestamp or rec.qpi.timestamp
        self.metrics.sli_duration.observe(max(self.clock() - start, 0.0),
                                          str(rec.qpi.attempts or 1))
        rec.qpi.unschedulable_plugins = set()
        rec.qpi.consecutive_errors_count = 0

    def _reject_waiting(self, uid: str, reason: str = "") -> None:
        """WaitOnPermit rejection (timeout or plugin): unreserve, release
        the assumed resources, requeue as unschedulable."""
        rec = self._waiting_pods.pop(uid, None)
        if rec is None:
            return
        self.metrics.permit_wait_duration.observe(
            max(self.clock() - rec.parked_at, 0.0), "rejected")
        pod = rec.qpi.pod
        profile = self.profiles.get(pod.spec.scheduler_name)
        if profile is not None:
            profile.framework.run_reserve_plugins_unreserve(
                rec.cycle_state, rec.assumed, rec.node_name)
        try:
            self.cache.forget_pod(rec.assumed)
        except (KeyError, ValueError):
            pass
        self._invalidate_device_state()
        err = FitError(pod, 0)
        err.diagnosis.unschedulable_plugins = {rec.wait_plugin or "Permit"}
        self._handle_failure(rec.qpi, err, try_preempt=False)

    def _locked(self, fn):
        """Wrap a watch handler so it holds the ingest lock: handlers fire
        on the API thread while sync()/resync() rebuild queue/cache/device
        state — without the lock a watch event interleaves with the
        rebuild and lands on a structure about to be thrown away."""
        def wrapper(*args, **kw):
            with self.ingest_lock:
                return fn(*args, **kw)
        return wrapper

    def _register_event_handlers(self) -> None:
        """eventhandlers.go:499 addAllEventHandlers. Registration order
        matters on a live store: nodes replay before pods so bound pods
        land on real cache entries instead of imputed placeholders."""
        self.client.watch_nodes(WatchHandlers(
            on_add=self._locked(self._on_node_add),
            on_update=self._locked(self._on_node_update),
            on_delete=self._locked(self._on_node_delete)))
        self.client.watch_pods(WatchHandlers(
            on_add=self._locked(self._on_pod_add),
            on_update=self._locked(self._on_pod_update),
            on_delete=self._locked(self._on_pod_delete),
            on_add_bulk=self._locked(self._on_pod_add_bulk),
            on_update_bulk=(self._locked(self._on_pod_update_bulk)
                            if self.columnar_ingest else None)))
        if hasattr(self.client, "watch_workloads"):
            self.client.watch_workloads(WatchHandlers(
                on_add=self._on_workload_add))
        if hasattr(self.client, "watch_pvcs"):
            self.client.watch_pvcs(WatchHandlers(
                on_add=self._on_pvc_change, on_update=self._on_pvc_change))
        if hasattr(self.client, "watch_pvs"):
            self.client.watch_pvs(WatchHandlers(on_add=self._on_pv_add))
        if hasattr(self.client, "watch_pdbs"):
            self.client.watch_pdbs(WatchHandlers(
                on_add=self._on_pdb_change, on_update=self._on_pdb_change,
                on_delete=self._on_pdb_change))
        if hasattr(self.client, "watch_resource_claims"):
            self.client.watch_resource_claims(WatchHandlers(
                on_add=lambda c: self.queue.move_all_to_active_or_backoff_queue(
                    ClusterEvent(EventResource.RESOURCE_CLAIM, ActionType.ADD),
                    None, c),
                on_update=lambda o, n: self.queue.move_all_to_active_or_backoff_queue(
                    ClusterEvent(EventResource.RESOURCE_CLAIM, ActionType.UPDATE),
                    o, n)))
        if hasattr(self.client, "watch_resource_slices"):
            self.client.watch_resource_slices(WatchHandlers(
                on_add=lambda s: self.queue.move_all_to_active_or_backoff_queue(
                    ClusterEvent(EventResource.RESOURCE_SLICE, ActionType.ADD),
                    None, s)))

    def _responsible(self, pod: Pod) -> bool:
        return pod.spec.scheduler_name in self.profiles

    def _shard_owns(self, pod: Pod) -> bool:
        """Does this instance's shard slice cover the pod? Unsharded
        operation (shard_filter unset) owns everything."""
        return self.shard_filter is None or self.shard_filter(pod)

    # -- event handlers (eventhandlers.go) ------------------------------------

    def _invalidate_device_state(self) -> None:
        self._device_carry = None
        self._ext_mutations += 1

    def _on_pod_add(self, pod: Pod) -> None:
        self.workload_manager.add_pod(pod)
        if pod.spec.node_name:
            self.cache.add_pod(pod)
            self._invalidate_device_state()
            self.queue.move_all_to_active_or_backoff_queue(
                EVENT_ASSIGNED_POD_ADD, None, pod)
        elif self._responsible(pod):
            if not self._shard_owns(pod):
                # a peer shard's pod: stay warm (workload state above,
                # node/cache state via the bind echo) but don't schedule
                self._shard_parked[pod.uid] = pod
                self._journey_park([pod], detail="peer shard's pod")
                return
            self.queue.add(pod)
            gated = (pod.uid in self.queue.unschedulable_pods)
            self.metrics.queue_incoming_pods.inc(
                "gated" if gated else "active", "PodAdd")
            if pod.spec.workload_ref:
                ref = pod.spec.workload_ref
                if gated:
                    self._gang_gated_since.setdefault(ref, self.clock())
                # a new gang member can un-gate ITS group (PreEnqueue
                # quorum); other gangs' quorums are unaffected — and only
                # once the group can actually reach quorum (below
                # minCount the retry cannot move anything, so a 512-pod
                # gang's ingest skips 511 pointless sweeps)
                if self._gang_quorum_possible(pod):
                    self.queue.retry_gated(ref=ref)
                    self._observe_quorum_waits()

    def _on_pod_add_bulk(self, pods: list[Pod]) -> None:
        """Batch ingest (create_pods fan-out): plain unbound pods owned by
        this scheduler take the queue's bulk add; bound or foreign pods
        fall back to the per-pod path. Gang members also bulk-add, but
        their WorkloadManager registration happens FIRST for the whole
        chunk — a gang arriving complete in one chunk then passes
        PreEnqueue quorum at its own add (no gate → un-gate churn at
        all), and the quorum retry runs once per gang, not per member."""
        plain: list[Pod] = []
        gang_pods: list[Pod] = []
        parked: list[Pod] = []
        for pod in pods:
            if pod.spec.node_name or not self._responsible(pod):
                self._on_pod_add(pod)
            elif not self._shard_owns(pod):
                self.workload_manager.add_pod(pod)
                self._shard_parked[pod.uid] = pod
                parked.append(pod)
            elif pod.spec.workload_ref:
                self.workload_manager.add_pod(pod)
                gang_pods.append(pod)
            else:
                self.workload_manager.add_pod(pod)
                plain.append(pod)
        if parked:
            self._journey_park(parked, detail="peer shard's pod")
        if plain:
            n = self.queue.add_bulk(plain)
            self.metrics.queue_incoming_pods.inc("active", "PodAdd",
                                                 by=len(plain) - n)
            if n:
                self.metrics.queue_incoming_pods.inc("gated", "PodAdd", by=n)
        if gang_pods:
            n = self.queue.add_bulk(gang_pods)
            self.metrics.queue_incoming_pods.inc("active", "PodAdd",
                                                 by=len(gang_pods) - n)
            if n:
                self.metrics.queue_incoming_pods.inc("gated", "PodAdd",
                                                     by=n)
            now = self.clock()
            refs = dict.fromkeys(p.spec.workload_ref for p in gang_pods)
            gated_refs = self.queue.gated_refs() if n else set()
            for ref in refs:
                if ref in gated_refs:
                    self._gang_gated_since.setdefault(ref, now)
            for ref in refs:
                member = next(p for p in gang_pods
                              if p.spec.workload_ref == ref)
                if self._gang_quorum_possible(member):
                    self.queue.retry_gated(ref=ref)
            self._observe_quorum_waits()

    def _on_pod_update(self, old: Pod, new: Pod) -> None:
        self.workload_manager.update_pod(old, new)
        if new.spec.node_name:
            if old.spec.node_name:
                self.cache.update_pod(old, new)
                self._invalidate_device_state()
                flags = pod_update_action(old, new)
                if flags:
                    self.queue.move_all_to_active_or_backoff_queue(
                        ClusterEvent(EventResource.ASSIGNED_POD, flags),
                        old, new)
            else:
                # became bound. Our own bind echo confirms a pod the device
                # carry already accounts for (it was assumed before the bind
                # was dispatched); anything else is an external mutation.
                if not self.cache.is_assumed_pod(new):
                    self._invalidate_device_state()
                self._bind_errors.pop(new.uid, None)
                self._shard_parked.pop(new.uid, None)  # peer bound it
                self.cache.add_pod(new)
                self.queue.delete(new)
                self._journey_confirm([new.uid])
                self.queue.move_all_to_active_or_backoff_queue(
                    EVENT_ASSIGNED_POD_ADD, old, new)
        elif self._responsible(new):
            if not self._shard_owns(new) and new.uid in self._shard_parked:
                self._shard_parked[new.uid] = new  # keep the park fresh
                return
            if self._shard_parked.pop(new.uid, None) is not None:
                # ownership arrived between park and this update
                self.queue.add(new)
                return
            self.queue.update(old, new)
            flags = pod_update_action(old, new)
            if flags:
                # gate removal needs no special-casing: queue.update above
                # already re-ran PreEnqueue for the gated entry
                self.queue.move_all_to_active_or_backoff_queue(
                    ClusterEvent(EventResource.POD, flags), old, new)

    def _on_pod_update_bulk(self, pairs: list) -> None:
        """Bulk Binding echo (apiserver.bind_all fan-out): the common
        shape — our own bulk bind confirming pods we assumed — collapses
        to one pass over the batch instead of the per-pod informer dance
        (workload bookkeeping, a fresh _PodState, four queue dict probes
        and a move_all sweep per pod). Anything off-shape, or any queue
        state the per-pod path would consult (unschedulable pods whose
        queueing hints need the individual pod, in-flight event logging,
        pending bind errors), falls back to `_on_pod_update` per pod —
        semantics stay identical by construction."""
        q = self.queue
        if (q.unschedulable_pods or q.in_flight_pods
                or self._bind_errors or self._waiting_pods):
            for old, new in pairs:
                self._on_pod_update(old, new)
            return
        assumed = self.cache.assumed_pods
        wm_update = self.workload_manager.update_pod
        active = q.active_q
        backoff = q.backoff_q
        confirm: list = []
        for old, new in pairs:
            uid = new.metadata.uid
            if (not new.spec.node_name or old.spec.node_name
                    or uid not in assumed):
                self._on_pod_update(old, new)
                continue
            wm_update(old, new)
            confirm.append(new)
            if uid in active or uid in backoff:
                q.delete(new)
        if confirm:
            self.cache.confirm_bound(confirm)
            self._journey_confirm([p.uid for p in confirm])
            # EVENT_ASSIGNED_POD_ADD move sweep: with no unschedulable
            # pods and no in-flight event log (checked above) the per-pod
            # move_all calls are no-ops — elided wholesale

    def _on_pod_delete(self, pod: Pod) -> None:
        self.workload_manager.delete_pod(pod)
        if pod.uid in self._waiting_pods:
            self._reject_waiting(pod.uid, "pod deleted")
        self._bind_errors.pop(pod.uid, None)
        self._shard_parked.pop(pod.uid, None)
        self.journey.forget(pod.uid)
        if pod.spec.node_name:
            self.cache.remove_pod(pod)
            self._invalidate_device_state()
            self.queue.move_all_to_active_or_backoff_queue(
                EVENT_ASSIGNED_POD_DELETE, pod, None)
        else:
            self.queue.delete(pod)

    def _on_pvc_change(self, *args) -> None:
        """PVC add/update can unblock VolumeBinding rejects
        (volume_binding.go EventsToRegister)."""
        old, new = (args[0], args[1]) if len(args) == 2 else (None, args[0])
        self.queue.move_all_to_active_or_backoff_queue(
            ClusterEvent(EventResource.PVC, ActionType.ADD | ActionType.UPDATE),
            old, new)

    def _on_pv_add(self, pv) -> None:
        """A new PV can satisfy a WFFC claim that had no match
        (volume_binding.go EventsToRegister: PV Add)."""
        self.queue.move_all_to_active_or_backoff_queue(
            ClusterEvent(EventResource.PV, ActionType.ADD), None, pv)

    def _on_pdb_change(self, *args) -> None:
        """A PDB change can alter preemption viability for pods rejected by
        DefaultPreemption (its budget freed up → a candidate now exists).
        Unschedulable pods carry the FILTER plugins as rejectors, whose
        hints don't cover PDB events — so this uses the wildcard event
        (conservative requeue), not EventResource.PDB which every hint map
        would veto. PDB changes are rare; the broad sweep is cheap."""
        old, new = (args[0], args[1]) if len(args) == 2 else (None, args[0])
        self.queue.move_all_to_active_or_backoff_queue(
            ClusterEvent(EventResource.WILDCARD, ActionType.ALL,
                         "PodDisruptionBudgetChange"),
            old, new)

    def _on_workload_add(self, workload) -> None:
        """A Workload's arrival can un-gate its gang's pods (PreEnqueue)
        and requeue unschedulable members (gangscheduling.go:100). Only
        the arriving workload's refs are re-evaluated (gated_by_ref
        index) — other gangs' quorums are unaffected by this event."""
        from .backend.workloadmanager import parse_workload_ref
        name = workload.metadata.name
        for ref in self.queue.gated_refs():
            if parse_workload_ref(ref)[0] == name:
                self.queue.retry_gated(ref=ref)
        self._observe_quorum_waits()
        self.queue.move_all_to_active_or_backoff_queue(
            ClusterEvent(EventResource.WORKLOAD, ActionType.ADD),
            None, workload)

    def _gang_quorum_possible(self, pod: Pod) -> bool:
        """True when the pod's group has reached its minCount in KNOWN
        pods — the only state in which a gated-member retry can move
        anything (PreEnqueue quorum, gangscheduling.go:120-158)."""
        from .backend.workloadmanager import (parse_workload_ref,
                                              pod_group_min_count)
        name, group = parse_workload_ref(pod.spec.workload_ref)
        workload = self.client.get_workload(name)
        if workload is None:
            return False
        min_count = pod_group_min_count(workload, group)
        if min_count is None:
            return False
        info = self.workload_manager.pod_group_info(pod)
        return info is not None and len(info.all_pods) >= min_count

    def _observe_quorum_waits(self) -> None:
        """Record gang_quorum_wait_seconds for every gang whose gated
        members just cleared (quorum met → retry_gated moved them)."""
        if not self._gang_gated_since:
            return
        live = self.queue.gated_refs()
        for ref in list(self._gang_gated_since):
            if ref not in live:
                now = self.clock()
                wait = max(now - self._gang_gated_since.pop(ref), 0.0)
                self.metrics.gang_quorum_wait.observe(wait)
                self.metrics.e2e_segment.observe(wait, "gate_wait")
                self.timeline.segment(now, "gate_wait", wait, 1)
                bad = wait > self.slo.threshold("gang_quorum_wait")
                self.slo.observe("gang_quorum_wait",
                                 good=0 if bad else 1, bad=1 if bad else 0)

    # -- journey / timeline plumbing (obs/journey.py, ISSUE 13) ---------------

    def _journey_confirm(self, uids: list) -> None:
        """Bind-echo confirms: the journey's bind_confirm transition, the
        commit_backlog segment (dispatcher enqueue → echo), and the
        per-pod clock cleanup."""
        now = self.clock()
        waits = self.journey.bind_confirmed(uids, now)
        if waits:
            self.metrics.e2e_segment.observe_array(waits, "commit_backlog")
            self.timeline.segment(now, "commit_backlog", sum(waits),
                                  len(waits))
        # the timeline's binds cell counts CONFIRMED binds (the watch
        # echo), not drain assignments — a bind-error retry must not
        # double-count the pod
        self.timeline.bump(now, "binds", len(uids))

    def _journey_requeue(self, uids: list, cause: str,
                         detail: str = "") -> None:
        """A pod (or batch) re-entered the queue: requeue transition with
        its cause + the requeue counter + the timeline sample."""
        if not uids:
            return
        now = self.clock()
        self.metrics.pod_requeues.inc(cause, by=len(uids))
        self.timeline.requeue(now, cause, by=len(uids))
        # the transition detail always LEADS with the cause so a
        # /debug/pod timeline names it even when an error string rides
        # along ("fence_unwind: write fenced: ...")
        self.journey.record_bulk(uids, _EV_REQUEUE, now,
                                 detail=f"{cause}: {detail}" if detail
                                 else cause)

    def _journey_park(self, pods: list, detail: str = "") -> None:
        """A peer shard's pods parked: first-class park transition AND
        the e2e SLI clock seed — a pod first sighted parked starts its
        clock at park time, so the stitched cross-shard timeline's
        firstEnqueue (the min across instances) anchors at the earliest
        sighting anywhere in the fleet, steal or no steal."""
        if not pods:
            return
        now = self.clock()
        for pod in pods:
            self.journey.first_enqueue(pod.uid, now)
        self.journey.record_bulk([p.uid for p in pods], _EV_PARK, now,
                                 detail=detail)

    def _timeline_slo_sample(self) -> dict:
        """Compact SLO sample stamped onto each closing timeline bucket:
        only the nonzero burn rates, keyed sli:window."""
        return {f"{sli}:{window}": round(rate, 4)
                for (sli, window), rate in
                self.slo.gauge_callback().items() if rate}

    def _resolve_probe(self, pd: "_PendingDrain") -> dict:
        """Resolve a drain's in-flight cluster_probe device result into
        the snapshot dict served at /debug/cluster, and publish it to the
        scheduler_cluster_* gauge families. A failed readback drops the
        sample (the commit itself never aborts on probe faults)."""
        if pd.probe is None:
            return {}
        from .metrics import (CLUSTER_DOM_STATS, CLUSTER_FRAG_KINDS,
                              CLUSTER_UTIL_STATS)
        try:
            per_res = np.asarray(pd.probe[0])
            dom = np.asarray(pd.probe[1])
            valid = int(np.asarray(pd.probe[2]))
        except Exception as e:
            klog.v(2).info("cluster probe readback failed", err=str(e))
            return {}
        rnames = self.state.rtable.names
        resources: dict = {}
        for r in range(min(len(rnames), per_res.shape[0])):
            row = per_res[r]
            resources[rnames[r]] = {
                stat: round(float(row[i]), 6)
                for i, stat in enumerate(PROBE_STATS)}
            for i, stat in enumerate(CLUSTER_UTIL_STATS):
                self.metrics.cluster_utilization.set(
                    float(row[i]), rnames[r], stat)
            for i, kind in enumerate(CLUSTER_FRAG_KINDS):
                self.metrics.cluster_fragmentation.set(
                    float(row[len(CLUSTER_UTIL_STATS) + i]),
                    rnames[r], kind)
        domains = {stat: round(float(dom[i]), 6)
                   for i, stat in enumerate(CLUSTER_DOM_STATS)}
        for i, stat in enumerate(CLUSTER_DOM_STATS):
            self.metrics.cluster_domain_imbalance.set(float(dom[i]), stat)
        return {"t": round(self.clock(), 6), "drainId": pd.drain_id,
                "validNodes": valid, "resources": resources,
                "domains": domains}

    def _on_node_add(self, node: Node) -> None:
        self.cache.add_node(node)
        self._invalidate_device_state()
        self.queue.move_all_to_active_or_backoff_queue(EVENT_NODE_ADD, None, node)

    def _on_node_update(self, old: Node, new: Node) -> None:
        self.cache.update_node(old, new)
        self._invalidate_device_state()
        flags = node_update_action(old, new)
        if flags:
            self.queue.move_all_to_active_or_backoff_queue(
                ClusterEvent(EventResource.NODE, flags), old, new)

    def _on_node_delete(self, node: Node) -> None:
        self.cache.remove_node(node)
        self._invalidate_device_state()

    # -- scheduling: batch path ----------------------------------------------

    def schedule_pending(self, max_batches: int = 0, wait: bool = True) -> int:
        """Drain + schedule everything currently pending. Returns the net
        number of successful binds committed so far (flush failures are not
        counted). With `wait=False` the call returns after dispatching:
        device results still in flight commit on a later call (or
        `wait_pending()`), which is what lets ingestion of the next pod
        chunk overlap the tunneled device readback."""
        if self.ha_role == "standby":
            # a standby never writes: binds from a non-leader would race
            # the active scheduler (and be fenced anyway). Takeover calls
            # promote() before resuming the loop.
            return 0
        if self.profiler is not None:
            self.profiler.ensure_running()
        start = self.scheduled_count
        batches = 0
        while True:
            # commit whatever has already landed
            self.commit_ready()
            self.queue.flush_backoff_completed()
            if not len(self.queue.active_q):
                if not wait or not self._pending:
                    break
                self.wait_pending()
                continue    # a commit may have re-activated pods
            qlen = len(self.queue.active_q)
            if not wait and qlen < self.batch_size:
                # adaptive batching: let the queue accumulate so the next
                # dispatch amortizes the tunnel round trip over more pods
                # (each device execution costs ~100ms wall through the
                # tunnel regardless of size — execution COUNT is the cost).
                # Dispatch early only to fill an idle pipeline, and only
                # once half a drain is available: a lower bar fragments
                # the workload into more executions than the latency they
                # hide is worth.
                if self._pending or qlen < max(self.batch_size // 2, 1):
                    break
            # device shapes are drain-size independent (uniform L comes
            # from batch_size, scan buckets from pow2 padding), so take
            # everything up to the cap — one execution per drain
            qpis = self.queue.drain(self.batch_size)
            if not qpis:
                break
            with self.tracer.span("scheduling_cycle",
                                  pods=len(qpis)) as cycle:
                before = self.scheduled_count
                with self.tracer.span("schedule_batch"):
                    self._schedule_batch(qpis)
                while len(self._pending) > self.max_inflight_drains:
                    self._commit_next()
                with self.tracer.span("dispatcher_flush"):
                    self.dispatcher.flush()
                cycle.set(bound=self.scheduled_count - before)
            batches += 1
            if max_batches and batches >= max_batches:
                break
        if wait:
            self.wait_pending()
        elif len(self.dispatcher):
            self.dispatcher.flush()
        if (self._shard_profile_args is not None
                and not self._shard_profile_done
                and self.shard_profile_auto
                and not self._pending):
            # one-shot sharded-lane profile (perf/observatory.py), off the
            # dispatch path: the first sharded drain armed it, the quiesced
            # pipeline runs it — per-lane seconds, imbalance and the comms
            # share behind scheduler_shard_* and /debug/kernels
            self.profile_shard_lanes()
        return self.scheduled_count - start

    def commit_ready(self, limit: int = 0) -> int:
        """Commit in-flight drains whose device results have landed, head
        first (commit order IS dispatch order — the carry/ledger contract).
        Stops at the first drain still executing; `limit` caps the number
        of commits (0 = all ready). Returns drains committed. This is the
        commit stage's entry for the streaming pipeline's worker; the
        lock-step loop uses it for its opportunistic head-drain."""
        done = 0
        while self._pending and self._pending[0].ready():
            self._commit_next()
            done += 1
            if limit and done >= limit:
                break
        return done

    def dispatch_once(self, max_pods: int = 0) -> int:
        """Close the current batch and dispatch it as one drain WITHOUT
        committing anything: the ingest stage's entry for the streaming
        pipeline (kubernetes_tpu/pipeline.py), which runs BatchBuilder +
        DrainCompiler work for the next drain while the device executes
        the current one and leaves every commit to the pipeline's commit
        worker. Returns the number of pods taken from the queue (0 =
        nothing eligible). Depth capping is the CALLER's job — the
        pipeline enforces its backpressure before calling; direct users
        get the `max_inflight_drains` safety net."""
        if self.ha_role == "standby":
            return 0
        self.queue.flush_backoff_completed()
        if not len(self.queue.active_q):
            return 0
        qpis = self.queue.drain(max_pods or self.batch_size)
        if not qpis:
            return 0
        with self.tracer.span("scheduling_cycle", pods=len(qpis)) as cycle:
            before = self.scheduled_count
            with self.tracer.span("schedule_batch"):
                self._schedule_batch(qpis)
            while len(self._pending) > self.max_inflight_drains:
                self._commit_next()
            cycle.set(bound=self.scheduled_count - before)
        return len(qpis)

    def profile_shard_lanes(self, force: bool = False):
        """Run the sharded-lane profile on the latest sharded dispatch's
        inputs (parallel/sharding.py profile_shard_lanes). Auto-runs once
        after the first sharded drain; `force=True` re-profiles (the
        /debug/kernels?lanes=refresh hook). Returns the profile dict, or
        None when no sharded dispatch has happened yet."""
        if self._shard_profile_args is None:
            return None
        if self._shard_profile_done and not force:
            return self.observatory.shard_profile() or None
        self._shard_profile_done = True
        from .parallel.sharding import profile_shard_lanes
        prof = profile_shard_lanes(*self._shard_profile_args)
        self.observatory.set_shard_profile(prof)
        return prof

    def wait_pending(self) -> None:
        """Commit every in-flight drain and flush the dispatcher — the
        pipeline barrier (reference WaitForCacheSync-style quiescence)."""
        self._drain_pending()
        self.dispatcher.flush()

    def prime(self) -> None:
        """Pre-build the host snapshot and device staging arrays from the
        current cluster state — the analog of the reference waiting for
        informer cache sync before serving (WaitForCacheSync,
        app/server.go): node ingestion cost lands here, not in the first
        scheduling cycle."""
        self._drain_pending()
        self.cache.update_snapshot(self.snapshot)
        self.state.apply_snapshot(self.snapshot)
        self.state.ensure_arrays()

    def _schedule_batch(self, qpis: list[QueuedPodInfo]) -> int:
        if (self.queue.nominator.nominated_pods
                and not self._overlay_eligible(qpis)):
            # nominated (preemptor) pods change OTHER pods' filter results
            # (two-pass RunFilterPluginsWithNominatedPods). The device
            # path models them as a fit-only resource OVERLAY; drains the
            # overlay cannot represent exactly (host-port or
            # lower-priority nominated pods, a nominated pod inside the
            # drain itself, a sharded mesh) take the host oracle —
            # nominations are short-lived (victim deletes flush at the
            # end of the previous cycle)
            self._drain_pending()
            return sum(1 if self._schedule_one_host(q) else 0 for q in qpis)
        # route per profile (profile.go:46 Map lookup): a drain can mix
        # schedulerNames; each maximal same-profile stretch runs with ITS
        # weights/strategy, in queue order
        bound = 0
        i = 0
        while i < len(qpis):
            name = qpis[i].pod.spec.scheduler_name
            j = i + 1
            while (j < len(qpis)
                   and qpis[j].pod.spec.scheduler_name == name):
                j += 1
            profile = self.profiles.get(name)
            if profile is None:
                self._drain_pending()
                for q in qpis[i:j]:
                    self._schedule_one_host(q)  # drops unowned pods
            else:
                bound += self._schedule_profile_batch(qpis[i:j], profile)
            i = j
        return bound

    def _schedule_profile_batch(self, qpis: list[QueuedPodInfo],
                                profile: Profile) -> int:
        if profile.extenders:
            # no tensor form for webhook hooks: host path, batching off
            self._drain_pending()
            return sum(1 if self._schedule_one_host(q) else 0
                       for q in qpis)
        bound = 0
        gangs, qpis = self._extract_gangs(qpis)
        for members, ref, needed, min_count in gangs:
            # whole pod group → ONE all-or-nothing device dispatch
            bound += self._dispatch_device_drain(
                members, profile, gang=(ref, needed, min_count))
        if not qpis:
            return bound
        pods = [q.pod for q in qpis]
        batch = self.builder.build(pods, pad_to=self.batch_size)
        if not batch.host_fallback.any():
            # common case: whole drain is device-eligible; reuse this build
            return bound + self._dispatch_device_drain(qpis, profile,
                                                       prebuilt=batch)
        fallback = batch.host_fallback
        i = 0
        while i < len(qpis):
            if fallback[i]:
                self._drain_pending()
                ok = self._schedule_one_host(qpis[i])
                bound += 1 if ok else 0
                i += 1
                continue
            j = i + 1
            while j < len(qpis) and not fallback[j]:
                j += 1
            bound += self._dispatch_device_drain(qpis[i:j], profile)
            # host pods interleave the drain: commit the device stretch now
            # so queue order is preserved end to end
            self._drain_pending()
            i = j
        return bound

    def _extract_gangs(self, qpis: list[QueuedPodInfo]):
        """Partition a profile stretch into whole-gang drains and the
        rest. A gang is extracted when the drain holds at least its
        remaining quorum of members and the group is device-eligible
        (gates on, no parked members, no volumes/claims — the hook chain
        the atomic commit bypasses must be vacuous; mesh drains dispatch
        through run_gang_sharded, ISSUE 16).
        Ineligible gangs stay in the generic flow: per-pod placement with
        the reference's Permit-barrier dance at commit."""
        if (not self.gang_device_enabled
                or self.queue.nominator.nominated_pods
                or not any(q.pod.spec.workload_ref for q in qpis)):
            return [], qpis
        from .backend.workloadmanager import (parse_workload_ref,
                                              pod_group_min_count)
        groups: dict[str, list] = {}
        order: list[str] = []
        rest: list[QueuedPodInfo] = []
        for q in qpis:
            ref = q.pod.spec.workload_ref
            if ref:
                if ref not in groups:
                    groups[ref] = []
                    order.append(ref)
                groups[ref].append(q)
            else:
                rest.append(q)
        out = []
        for ref in order:
            members = groups[ref]
            name, group = parse_workload_ref(ref)
            workload = self.client.get_workload(name)
            min_count = (pod_group_min_count(workload, group)
                         if workload is not None else None)
            if min_count is None:
                rest.extend(members)
                continue
            info = self.workload_manager.pod_group_info(members[0].pod)
            assigned = len(info.assigned) if info is not None else 0
            needed = max(min_count - assigned, 0)
            if needed == 0:
                # quorum already satisfied by bound members: the surplus
                # members schedule individually (Permit passes instantly)
                rest.extend(members)
                continue
            if (len(members) < needed
                    or any(m.pod.uid in self._waiting_pods
                           for m in members)
                    or any(m.pod.spec.volumes or m.pod.spec.resource_claims
                           for m in members)):
                self.metrics.gang_dispatch.inc("fallback")
                rest.extend(members)
                continue
            out.append((members, ref, needed, min_count))
        return out, rest

    def _dispatch_device_drain(self, qpis: list[QueuedPodInfo],
                               profile: Profile, prebuilt=None,
                               gang=None) -> int:
        """Build + dispatch one drain's device programs WITHOUT waiting for
        the results; appends a _PendingDrain whose commit happens when the
        async host copies land. Returns binds committed inside this call
        (only the host-fallback retry path commits synchronously)."""
        from .ops.groups import scatter_new_rows, to_device
        from .utils.logging import log_context

        t_entry = _time.perf_counter()
        did = self._drain_seq = self._drain_seq + 1
        if not self._device_available():
            # circuit breaker open: the device tier is sidelined until the
            # cooldown expires; the host oracle takes the drain
            self.device_fallbacks += 1
            self.metrics.device_fallbacks.inc("circuit_open")
            if gang is not None:
                self.metrics.gang_dispatch.inc("fallback")
            self.flight.record(
                profile=profile.name, pods=len(qpis), bound=0, failed=0,
                signatures=0, kinds=(), groups=False, phases={},
                breaker_open=True, consecutive_faults=self._device_faults,
                fallback="circuit_open", drain_id=did)
            self._drain_pending()
            # journey: the drain re-routed to the host oracle — the pods'
            # device attempt is abandoned, not retried
            self._journey_requeue([q.pod.uid for q in qpis],
                                  "breaker_fallback")
            self.journey.record_bulk([q.pod.uid for q in qpis], _EV_DRAIN,
                                     self.clock(), detail="breaker_host",
                                     drain=did)
            return sum(1 if self._schedule_one_host(q) else 0 for q in qpis)

        with log_context(drain=did):
            return self._dispatch_device_drain_inner(qpis, profile, prebuilt,
                                                     t_entry, did, gang)

    def _dispatch_device_drain_inner(self, qpis, profile, prebuilt,
                                     t_entry, did, gang=None):
        from .ops.groups import scatter_new_rows, to_device

        ph: dict[str, float] = {}
        # shadow-audit sampling decision: a sampled drain quiesces the
        # commit pipeline FIRST so the snapshot clone captured below is
        # exactly the state the device carry encodes (obs/audit.py) —
        # divergence then means a decision difference, never capture skew
        audit_want = (self.audit is not None and gang is None
                      and self.audit.want())
        audit_rec = None
        if audit_want:
            with self.tracer.span("audit_quiesce", drain=did):
                self._drain_pending()
                self.cache.update_snapshot(self.snapshot)
        with self.tracer.span("host_build", pods=len(qpis), drain=did), \
                self.phase_track.scope("host_build"):
            carry = self._device_carry
            nominator = self.queue.nominator
            ovl_fp = nominator.version if nominator.nominated_pods else -1
            if carry is not None and (self._carry_profile != profile.name
                                      or self._carry_ovl_fp != ovl_fp):
                # the signature cache's s_fit/s_bal were computed under
                # another profile's ScoreConfig — or its fit_ok under a
                # different nominated-pod overlay: invalidate (sig 0 never
                # matches)
                carry = carry._replace(
                    cache=carry.cache._replace(sig=jnp.int32(0)))
                self._device_carry = carry
            self._carry_profile = profile.name
            self._carry_ovl_fp = ovl_fp
            if carry is None:
                # reseed device state from the host snapshot (first batch,
                # or an external event invalidated the resident carry).
                # Pending commits mutate the host cache the snapshot is
                # built from, so they must land first.
                with self._phase("host_snapshot", ph):
                    self._drain_pending()
                    self.cache.update_snapshot(self.snapshot)
                    self.state.apply_snapshot(self.snapshot)
            with self._phase("host_tensorize", ph,
                             cached=prebuilt is not None):
                if (prebuilt is not None
                        and prebuilt.table.req.shape[1]
                        == self.state.dims.resources):
                    segment_batch = prebuilt
                else:
                    segment_batch = self.builder.build(
                        [q.pod for q in qpis], pad_to=self.batch_size)
            if segment_batch.host_fallback.any():
                # state moved between routing and segment build (e.g. a node
                # update surfaced images): honor queue order and let the
                # oracle take the segment
                if gang is not None:
                    self.metrics.gang_dispatch.inc("fallback")
                self._drain_pending()
                return sum(1 if self._schedule_one_host(q) else 0
                           for q in qpis)
            if audit_want:
                # clone + fingerprint + hash-chain append: the snapshot
                # was refreshed at the quiesce above and nothing between
                # there and here mutates the cache
                with self.tracer.span("audit_capture", drain=did):
                    audit_rec = self.audit.capture(
                        did, profile, qpis, self.snapshot, segment_batch,
                        len(qpis), self.state, self.builder,
                        self._ext_mutations)
            if (self.mesh is not None
                    and (self._na_sharded is None
                         or self._na_sharded_gen != self.state.staging_gen)):
                # the mesh-placed node upload is a real drain phase:
                # cover it with the same span/ledger surface as the
                # single-device snapshot uploads (run_batch_sharded
                # previously had no drain_phase/h2d attribution)
                with self._phase("host_snapshot", ph):
                    na = self._node_arrays()
            else:
                na = self._node_arrays()
            # group kernels are needed when any signature row carries spread
            # or inter-pod affinity constraints, or when existing cluster
            # pods do (affinity is symmetric: they veto/score ANY incoming
            # pod)
            groups_needed = (
                self.builder.groups.any_groups()
                or bool(self.snapshot.have_pods_with_affinity_list)
                or bool(
                    self.snapshot.have_pods_with_required_anti_affinity_list))
            if gang is not None and (
                    groups_needed
                    or (segment_batch.sig[:len(qpis)] == 0).any()
                    or not segment_batch.valid[:len(qpis)].all()):
                # group kernels / host-port signatures are outside the gang
                # program: this gang rides the generic path instead (per-pod
                # placement + the Permit barrier at commit)
                self.metrics.gang_dispatch.inc("fallback")
                gang = None
            if groups_needed and self._device_plan(
                    segment_batch, len(qpis), profile).scan_only:
                # host greedy is the FALLBACK tier for group drains no
                # compiled program covers (gate off, short spans, mixes
                # beyond the plan lattice). A sampled drain stays audited
                # — the greedy's decisions face the same oracle replay.
                bound = self._try_host_greedy(qpis, profile, segment_batch,
                                              audit=audit_rec)
                if bound is not None:
                    return bound
            table_reset = self.builder.reset_count != self._builder_reset_seen
            self._builder_reset_seen = self.builder.reset_count
            capacity = (self.builder.groups.device_rows(), na.used.shape[0])
            if carry is not None and (
                    table_reset  # every signature id / group row invalidated
                    or carry.used.shape != na.used.shape
                    or groups_needed != (carry.groups is not None)
                    or (groups_needed and capacity != self._gd_capacity)):
                # structural change: reseed from the host snapshot
                carry = None
                with self._phase("host_snapshot", ph):
                    self._drain_pending()
                    self.cache.update_snapshot(self.snapshot)
                    self.state.apply_snapshot(self.snapshot)
                na = self._node_arrays()
            with self._phase("host_group_seed", ph, groups=groups_needed):
                if carry is None:
                    gcarry = None
                    if groups_needed:
                        gd_np, gc_np = self.builder.groups.build_dev(
                            self.snapshot)
                        if self.mesh is not None:
                            from .parallel.sharding import (shard_group_carry,
                                                            shard_groups)
                            self._gd_dev = shard_groups(self.mesh,
                                                        to_device(gd_np))
                            gcarry = shard_group_carry(self.mesh,
                                                       to_device(gc_np))
                        else:
                            self._gd_dev = to_device(gd_np)
                            gcarry = to_device(gc_np)
                        self._gd_fam = self.builder.groups.families(
                            self.snapshot)
                    else:
                        self._gd_dev = None
                        self._gd_fam = None
                    self._gd_capacity = capacity
                    self._seeded_rows = self.builder.table_used
                    carry = initial_carry(na, gcarry)
                elif (groups_needed
                      and self.builder.table_used > self._seeded_rows):
                    # new signature rows while the carry is resident: seed
                    # just those rows from the live snapshot (assumes
                    # included) and scatter in. Pending commits must land
                    # first — the seeds count them.
                    self._drain_pending()
                    carry = self._device_carry
                    if carry is None:
                        # a bind error during the drain invalidated the
                        # carry: restart this dispatch against the reseeded
                        # state
                        if audit_rec is not None:
                            self.audit.abandon(audit_rec, "restarted")
                        return self._dispatch_device_drain(qpis, profile,
                                                           prebuilt)
                    if (self.builder.groups.device_rows(),
                            na.used.shape[0]) != self._gd_capacity:
                        # the commits above can intern NEW signature rows
                        # (e.g. preemption's batched dry-run row for a
                        # failed pod): a pow2 capacity crossing means the
                        # resident group tensors are too small to scatter
                        # into — reseed instead
                        self._invalidate_device_state()
                        if audit_rec is not None:
                            self.audit.abandon(audit_rec, "restarted")
                        return self._dispatch_device_drain(qpis, profile,
                                                           prebuilt)
                    self.cache.update_snapshot(self.snapshot)
                    self._gd_dev, gcarry = scatter_new_rows(
                        self._gd_dev, carry.groups, self.builder.groups,
                        self.snapshot, self._seeded_rows,
                        self.builder.table_used, mesh=self.mesh)
                    self._gd_fam = self.builder.groups.families(self.snapshot)
                    carry = carry._replace(groups=gcarry)
                    self._seeded_rows = self.builder.table_used
            with self._phase("host_cache", ph):
                if (self._table_dev is None
                        or self._table_dev_version
                        != segment_batch.table_version):
                    self._table_dev = table_from_batch(segment_batch)
                    self._table_dev_version = segment_batch.table_version
                table = self._table_dev
                n = len(qpis)
                ovl = None
                nom = None
                if self.queue.nominator.nominated_pods:
                    # re-validate at the DISPATCH site: interleaved
                    # host-path scheduling (mixed drains, fallback segments)
                    # can nominate mid-batch, after _schedule_batch's entry
                    # check ran
                    if groups_needed or not self._overlay_eligible(qpis):
                        # groups: nominated pods' labels feed group counts,
                        # which the resource-only overlay cannot represent
                        if audit_rec is not None:
                            self.audit.abandon(audit_rec, "host_path")
                        self._drain_pending()
                        return sum(1 if self._schedule_one_host(q) else 0
                                   for q in qpis)
                    ovl = self._build_overlay(na)
                    nom = self._nominated_rows(qpis)
                    if audit_rec is not None:
                        # the nominated-pod overlay is outside the audit's
                        # replay model (the oracle would need the
                        # nominator state frozen at dispatch)
                        self.audit.abandon(audit_rec, "overlay")
                        audit_rec = None
                    if gang is not None:
                        # the overlay two-pass is outside the gang program
                        self.metrics.gang_dispatch.inc("fallback")
                        gang = None
        t0 = _time.perf_counter()
        self.metrics.drain_phase.observe(max(t0 - t_entry, 0.0),
                                         "host_build")
        for name, dt in ph.items():
            self.metrics.drain_phase.observe(dt, name)
        ph["host_build"] = t0 - t_entry
        if self.profiler is not None:
            # profiler tag: this drain's distinct-signature count (pow2
            # bucketed by the profiler) — host cost per cardinality regime
            self._sig_bucket_cell[0] = int(
                np.unique(segment_batch.tidx[:n]).size)
        if audit_rec is not None:
            # keep the PRE-dispatch device inputs (carry copied on
            # device) so /debug/explain can replay any pod's exact step
            self.audit.attach_device(
                audit_rec, profile.score_config, na, carry, table,
                segment_batch, n, self._gd_dev, self._gd_fam,
                names=self.state.node_names)
        try:
            # kernel observatory: capture every measured_call dispatched
            # inside the device span as a device-lane event — the span's
            # wall decomposes into named kernel dispatches
            self.observatory.begin_drain()
            with self.tracer.span("device_dispatch", pods=n,
                                  groups=groups_needed, drain=did,
                                  batch_bucket=len(segment_batch.valid)) as ds:
                # rails: the dispatch region must consume only
                # device-resident (or explicitly staged) inputs — implicit
                # transfers raise here with the SanitizerRails gate on
                with self.phase_track.scope("device"), \
                        self.rails.guard_dispatch():
                    carry, records = self._dispatch_runs(
                        profile, na, carry, segment_batch, table, n,
                        groups_needed, ovl=ovl, nom=nom,
                        gang=(gang[1] if gang is not None else None))
                if self.rails.active and n > 0:
                    # NaN/inf probe of the drain's first signature row
                    # against the post-dispatch carry
                    self.rails.check_scores(
                        profile.score_config, na, carry, table,
                        int(segment_batch.tidx[0]))
                ds.set(runs=",".join(r.kind for r in records))
        except Exception as e:
            self.observatory.end_drain()
            # a sanitizer rail tripping is a finding, not a device fault:
            # degrading to the host oracle would mask exactly the bug the
            # rails exist to surface
            from .analysis.rails import SanitizerError
            if self.rails.active and (
                    isinstance(e, SanitizerError)
                    or "Disallowed host-to-device" in str(e)):
                raise
            # XLA/dispatch fault: earlier in-flight drains predate the
            # fault and commit normally; THIS drain degrades to the host
            # oracle and the resident carry reseeds on the next dispatch
            self._record_device_fault("dispatch", e)
            if audit_rec is not None:
                self.audit.abandon(audit_rec, "device_fault")
            if gang is not None:
                self.metrics.gang_dispatch.inc("fallback")
            self._drain_pending()
            return sum(1 if self._schedule_one_host(q) else 0 for q in qpis)
        ph["device_dispatch"] = _time.perf_counter() - t0
        self.metrics.drain_phase.observe(
            max(_time.perf_counter() - t0, 0.0), "device")
        # close the device-lane capture: per-kernel seconds ride the
        # flight record, and the events become lane="device" child spans
        # of the dispatch span (one host+device Chrome-trace timeline)
        lane_events = self.observatory.end_drain()
        kernels = self.observatory.lane_seconds(lane_events)
        if lane_events and hasattr(ds, "children"):
            ds.children.extend(
                self.observatory.lane_spans(lane_events, drain_id=did))
        self._device_carry = carry
        self.device_batches += 1
        self.metrics.device_batch_size.observe(n)
        probe = None
        if self._probe_enabled:
            # on-device cluster analytics over the post-drain carry: every
            # input (na, carry, dom) is already device-resident, so the
            # probe costs zero extra h2d; the result rides the drain's
            # async copy window and resolves at commit
            with self.tracer.span("cluster_probe", drain=did):
                dom = self._gang_domains(na, need=True)
                if self.mesh is not None:
                    # the mesh twin: feeding node-sharded inputs to the
                    # single-device probe jit makes GSPMD reshard around
                    # the cross-node sort — ~10× the whole probe's cost
                    from .parallel.sharding import cluster_probe_sharded
                    probe = cluster_probe_sharded(self.mesh, na, carry,
                                                  dom, self._gang_ndom)
                else:
                    probe = cluster_probe(na, carry, dom, self._gang_ndom)
        self.journey.record_bulk([q.pod.uid for q in qpis], _EV_DRAIN,
                                 self.clock(), detail="device", drain=did)
        self._pending.append(_PendingDrain(
            qpis=qpis, profile=profile, batch=segment_batch, table=table,
            na=na, n=n, groups_needed=groups_needed, records=records,
            dispatched_at=t0, ovl=ovl, nom=nom, phases=ph, drain_id=did,
            gang=gang, facts=self.builder.row_facts, audit=audit_rec,
            probe=probe, kernels=kernels))
        return 0

    @contextmanager
    def _phase(self, name: str, ph: dict, **attrs):
        """Time one host-build sub-phase: tracer child span, an entry in
        `ph` (flight recorder + drain_phase sub-phase series), and the
        PhaseTrack mark the sampling profiler attributes against."""
        t0 = _time.perf_counter()
        self.phase_track.push(name)
        try:
            # rails.declared opens a transfer-guard allow window for the
            # host phases whose uploads are part of the drain contract
            # (no-op with the SanitizerRails gate off)
            with self.tracer.span(name, **attrs), self.rails.declared(name):
                yield
        finally:
            self.phase_track.pop()
            ph[name] = ph.get(name, 0.0) + (_time.perf_counter() - t0)

    def _nominated_rows(self, qpis: list[QueuedPodInfo]):
        """i32 [n] row index of each drain pod's OWN nomination (-1 =
        none), or None when no drain pod is nominated — the device
        self-exclusion companion to the overlay (PodXs.nom_idx)."""
        nominated = self.queue.nominator.nominated_pods
        out = None
        for i, q in enumerate(qpis):
            node = nominated.get(q.pod.uid)
            if node is None:
                continue
            idx = self.state.node_index.get(node)
            if idx is None:
                continue
            if out is None:
                out = np.full((len(qpis),), -1, np.int32)
            out[i] = idx
        return out

    # below this run length the scan's per-step cost beats the matrix setup
    UNIFORM_RUN_MIN = 16

    def _overlay_eligible(self, qpis: list[QueuedPodInfo]) -> bool:
        """True when the nominated pods' effect on this drain reduces to a
        fit-only resource overlay (the reference adds nominated pods with
        priority >= the incoming pod's to the NodeInfo,
        runtime/framework.go:1183-1200): every nominated pod outranks every
        drain pod and none carries host ports (the ports carry isn't
        overlaid). A drain pod that IS nominated — the renominated
        preemptor wave, the hottest preemption shape — is handled by
        per-pod self-exclusion (PodXs.nom_idx): its own nominated row is
        subtracted back out of the overlay, mirroring the reference
        skipping the pod's own nomination."""
        if self.mesh is not None:
            return False
        nom = self.queue.nominator
        max_prio = max(q.pod.spec.priority for q in qpis)
        for qlist in nom.nominated_per_node.values():
            for q in qlist:
                if q.pod.spec.priority < max_prio:
                    return False
                for c in q.pod.spec.containers:
                    for p in c.ports:
                        if p.host_port > 0:
                            return False
        return True

    def _build_overlay(self, na):
        """(ovl_used [N,R], ovl_npods [N]) from the current nominations —
        fresh per dispatch (nominations are few and short-lived)."""
        N, R = na.used.shape
        ovl_used = np.zeros((N, R), np.int64)
        ovl_npods = np.zeros((N,), np.int32)
        for node_name, qlist in self.queue.nominator.nominated_per_node.items():
            idx = self.state.node_index.get(node_name)
            if idx is None or idx >= N:
                continue
            for q in qlist:
                vec = self.state.rtable.vector(q.pod_info.requests)
                ovl_used[idx, :len(vec)] += vec
                ovl_npods[idx] += 1
        return (jnp.asarray(ovl_used), jnp.asarray(ovl_npods))

    def _try_host_greedy(self, qpis: list[QueuedPodInfo], profile: Profile,
                         batch, audit=None) -> Optional[int]:
        """Host-side vectorized greedy for a SAME-SIGNATURE drain with
        group constraints (ops/hostgreedy.py) — the group analog of the
        closed-form uniform path. The device scan pays ~0.4ms of tunneled
        execution per sequential step; the host replays the exact oracle
        formulas at ~40µs/step. Returns binds committed, or None when the
        drain isn't eligible (caller continues on the device path)."""
        n = len(qpis)
        # mesh mode is NOT excluded: the greedy reads the full numpy
        # staging arrays, which the host owns regardless of how the device
        # copies are sharded; the post-run invalidation reseeds the shards.
        # Both scoring strategies are supported — the greedy recomputes
        # scores per step, so MostAllocated's non-monotone score sequences
        # (which bar the closed-form uniform path) are exact here.
        if (self.queue.nominator.nominated_pods
                or not self.feature_gates.enabled("OpportunisticBatching")
                or n < self.UNIFORM_RUN_MIN):
            return None
        sig = batch.sig[:n]
        if sig[0] == 0 or not (sig == sig[0]).all():
            return None
        # cheap precondition pre-checks BEFORE quiescing the pipeline: a
        # cluster with PreferNoSchedule taints (or a row with preferred
        # node affinity) would fail hg.ok after paying the full drain +
        # snapshot + group-tensor build on every single drain
        if (self._cluster_has_prefer_taints()
                or batch.table.pref_weight[int(batch.tidx[0])].any()):
            return None
        from .ops.hostgreedy import HostGreedy
        # commits mutate the host cache the greedy reads — quiesce first
        self._drain_pending()
        self.cache.update_snapshot(self.snapshot)
        self.state.apply_snapshot(self.snapshot)
        self.state.ensure_arrays()
        gd, gc = self.builder.groups.build_dev(self.snapshot)
        t0 = _time.perf_counter()
        hg = HostGreedy(profile.score_config, self.state.arrays,
                        batch.table, int(batch.tidx[0]), gd, gc,
                        n_eff=len(self.state.node_names))
        if not hg.ok:
            return None   # normalization preconditions failed: scan path
        with self.tracer.span("host_greedy", pods=n):
            out = hg.run(n)
        # placements live only in the upcoming commits; the resident device
        # carry (if any) knows nothing of them
        self._invalidate_device_state()
        self.device_batches += 1
        self.host_greedy_runs += 1
        self.metrics.device_batch_size.observe(n)
        self.metrics.device_batch_duration.observe(
            max(_time.perf_counter() - t0, 0.0))
        self._drain_seq += 1
        pd = _PendingDrain(qpis=qpis, profile=profile, batch=batch,
                           table=None, na=None, n=n, groups_needed=True,
                           records=[], dispatched_at=t0,
                           drain_id=self._drain_seq,
                           facts=self.builder.row_facts, audit=audit)
        return self._commit_assignments(pd, out)

    def _node_arrays(self):
        """Device (or mesh-placed) node arrays, cached until the staging
        generation moves (adopt_carry and every staging write bump it).
        The resident copies live in ClusterState — device_arrays /
        device_arrays_sharded — and a scheduler only ever uses one
        flavor, so they share the dirty-row diff tracking."""
        if self.mesh is None:
            return self.state.device_arrays()
        if (self._na_sharded is None
                or self._na_sharded_gen != self.state.staging_gen):
            self.state.ensure_arrays()
            self._na_sharded_gen = self.state.staging_gen
            # generation-diff upload (ISSUE 16): small dirty sets ride
            # scatter_rows_sharded instead of a full-matrix re-shard
            self._na_sharded = self.state.device_arrays_sharded(self.mesh)
        return self._na_sharded

    def _cluster_has_prefer_taints(self) -> bool:
        # mask by valid: freed rows of removed nodes keep their taint
        # columns until the slot is rewritten and must not disable the
        # uniform fast path forever
        a = self.state.arrays
        return a is not None and bool(
            ((a.taint_eff == EFFECT_PREFER_NO_SCHEDULE)
             & a.valid[:, None]).any())

    # -- speculative wave placement (group drains) ----------------------------

    def _wave_enabled(self) -> bool:
        return self.feature_gates.enabled("SpeculativeWavePlacement")

    def _device_plan(self, batch, n: int, profile: Profile):
        """The drain compiler's plan for this group drain under the
        current gates (no overlay/nomination/gang context — those route
        at the dispatch site)."""
        return self.compiler.compile_drain(
            batch, n, groups_needed=True,
            mesh=self.mesh is not None,
            strategy=profile.score_config.strategy,
            prefer_taints=self._cluster_has_prefer_taints(),
            wave_min_span=self.wave_min_span,
            uniform_min=self.UNIFORM_RUN_MIN)

    def _wave_norm_static(self, rows: tuple) -> bool:
        from .ops.hostgreedy import static_norm_ok
        pref_w = self.builder.table.pref_weight
        return all(static_norm_ok(self.state.arrays, pref_w[u])
                   for u in rows)

    def _get_wave_statics(self, na, table, rows: tuple) -> list:
        """Hoisted per-signature surfaces ([N] tuples per signature) from
        the compiler's SurfaceCache — retained across placement-only
        generation bumps (compiler/surfaces.py), recomputed only when a
        node's static columns or the signature table move."""
        return self.compiler.surfaces.get(na, table, rows)

    def _wave_dispatch(self, cfg: ScoreConfig, na, carry, batch, i: int,
                       j: int, table, span):
        """Dispatch the same-signature wave kernel over pods [i:j)."""
        _, u, anti_term, merge_on = span
        m = j - i
        bucket = pow2_at_least(m)
        valid = np.zeros((bucket,), bool)
        valid[:m] = batch.valid[i:j]
        statics = self._get_wave_statics(na, table, (u,))[0]
        # spread replay carries an [Lw, Lw, SC] rank tensor — cap the wave
        # width under it; without it wider waves just cut dispatch count
        Lw = min(512 if self._gd_fam.spr_f else 1024, bucket)
        K = min(Lw, na.cap.shape[0])
        if anti_term >= 0 and not self._gd_fam.spr_f:
            # domain-veto waves accept one entry per node (jcap=1): the
            # deeper matrix columns would be masked — don't build them
            J = 1
        else:
            _L, _K, J = self._uniform_shape(na)
        norm_live = not self._wave_norm_static((u,))
        carry2, packed = run_wave(
            cfg, na, carry, jnp.asarray(valid), table, jnp.int32(u),
            self._gd_dev, statics, K, J, self._gd_fam, norm_live,
            anti_term=anti_term, merge_on=merge_on, Lw=Lw)
        return carry2, packed, bucket

    def _wavescan_dispatch(self, cfg: ScoreConfig, na, carry, batch,
                           i: int, j: int, table, span):
        """Dispatch the compiler's plan program (ops/program.py run_plan)
        over pods [i:j): an arbitrary mixed-signature span — group rows
        ride the resident group tensors, LEAN spans (non-interacting
        signatures of a group-free drain) compile the variant without any
        group state, and spans holding host-port rows compile the
        ports-carry variant. The signature set pads to the compiler's
        pow2 lattice so the executable count stays log-bounded."""
        from .ops.groups import GroupFamilies

        _, uniq, has_ports = span
        uniq = list(uniq)
        m = j - i
        bucket = pow2_at_least(m)
        S = pow2_at_least(len(uniq), 2)
        wt_list = (uniq + [uniq[-1]] * S)[:S]
        slot: dict = {}
        for s, u in enumerate(wt_list):
            slot.setdefault(u, s)
        widx = np.zeros((bucket,), np.int32)
        tid = batch.tidx
        for k in range(m):
            widx[k] = slot[int(tid[i + k])]
        widx[m:] = widx[m - 1]
        valid = np.zeros((bucket,), bool)
        valid[:m] = batch.valid[i:j]
        statics = self.compiler.surfaces.stacked(na, table, tuple(wt_list))
        norm_live = not self._wave_norm_static(tuple(wt_list))
        xs = WaveXs(valid=jnp.asarray(valid), widx=jnp.asarray(widx))
        has_groups = self._gd_dev is not None
        fam = self._gd_fam if has_groups else GroupFamilies(
            False, False, False, False, False)
        if self.mesh is not None:
            from .parallel.sharding import run_plan_sharded
            carry2, packed = run_plan_sharded(
                cfg, self.mesh, na, carry, xs, table,
                jnp.asarray(np.array(wt_list, np.int32)), self._gd_dev,
                statics, fam, norm_live, has_groups=has_groups,
                has_ports=has_ports)
            return carry2, packed, bucket
        carry2, packed = run_plan(
            cfg, na, carry, xs, table,
            jnp.asarray(np.array(wt_list, np.int32)), self._gd_dev,
            statics, fam, norm_live, has_groups=has_groups,
            has_ports=has_ports)
        return carry2, packed, bucket

    # -- gang placement (whole-group all-or-nothing dispatch) ------------------

    def _gang_domains(self, na, need: bool):
        """Device i32[N] topology-domain id per node row for the gang
        contiguity column: the node's interned zone label, or a unique
        per-node domain when unlabeled (contiguity then has no surface to
        prefer). Cached until node state moves; identity ids when the
        contiguity weight is off (the kernel never reads them).

        Keyed on statics_gen, not staging_gen: zone labels are static
        columns, and the per-commit aggregate bumps that dominate
        steady-state drains must not force the 5k-entry host rebuild
        (the cluster probe reads this EVERY drain — a staging_gen key
        made it one of the largest host costs of a sharded drain)."""
        key = (self.state.statics_gen, na.used.shape[0])
        if self._gang_dom is not None and self._gang_dom_key == key:
            return self._gang_dom
        N = na.used.shape[0]
        dom = np.arange(N, dtype=np.int32)
        if need:
            ids: dict[str, int] = {}
            for name, idx in self.state.node_index.items():
                if idx >= N:
                    continue
                ni = self.snapshot.get(name)
                labels = (ni.node.metadata.labels if ni is not None else {})
                zone = (labels.get("topology.kubernetes.io/zone")
                        or f"\x00{idx}")
                dom[idx] = ids.setdefault(zone, len(ids))
        self._gang_dom = jnp.asarray(dom)
        self._gang_dom_key = key
        # static domain count for the cluster_probe jit cache key: stable
        # per topology (changes only when the id mapping is rebuilt)
        self._gang_ndom = int(dom.max()) + 1 if N else 1
        return self._gang_dom

    def _gang_dispatch(self, cfg: ScoreConfig, na, carry, batch, i: int,
                       j: int, table, span, force_scan: bool = False):
        """Dispatch ops/gang.py run_gang over members [i:j). Returns
        (carry', packed, pack_width, uniform_tier). A single-signature
        gang under LeastAllocated rides the closed-form top-L tier (the
        whole gang is one top_k); anything else — mixed member roles, a
        live contiguity column, MostAllocated, preferred surfaces — takes
        the scan tier with per-signature surfaces hoisted once."""
        from .ops.gang import GangXs, run_gang

        _, needed = span
        m = j - i
        w_contig = int(self.gang_contiguity_weight)
        tid = batch.tidx[i:j]
        uniq = list(dict.fromkeys(int(t) for t in tid))
        # gang-sized matrix, not the full batch bucket: a 256-member gang
        # must not pay an 8192-wide top-L. Gang sizes quantize to pow2, so
        # the executable count stays log-bounded per workload.
        L = pow2_at_least(m, 16)
        K = min(L, na.cap.shape[0])
        n_q = pow2_at_least(max(self.cache.node_count(), 1))
        J = min(max(pow2_at_least(4 * L // n_q + 4), 8), L + 1)
        if (not force_scan and len(uniq) == 1 and w_contig == 0 and m <= L
                and cfg.strategy == "LeastAllocated"
                and self.feature_gates.enabled("OpportunisticBatching")
                and not self._cluster_has_prefer_taints()
                and not self.builder.table.pref_weight[uniq[0]].any()):
            if self.mesh is not None:
                from .parallel.sharding import run_gang_sharded
                c2, packed = run_gang_sharded(
                    cfg, self.mesh, na, carry, self._xone(batch, i),
                    table, needed=np.int32(needed), uniform=True,
                    n_actual=np.int32(m), L=L, K=K, J=J)
            else:
                c2, packed = run_gang(cfg, na, carry, self._xone(batch, i),
                                      table, needed=np.int32(needed),
                                      uniform=True, n_actual=np.int32(m),
                                      L=L, K=K, J=J)
            return c2, packed, L, True
        bucket = pow2_at_least(m)
        S = pow2_at_least(len(uniq), 1)
        wt_list = (uniq + [uniq[-1]] * S)[:S]
        slot: dict = {}
        for s, u in enumerate(wt_list):
            slot.setdefault(u, s)
        widx = np.zeros((bucket,), np.int32)
        for k in range(m):
            widx[k] = slot[int(tid[k])]
        widx[m:] = widx[m - 1]
        tidx = np.full((bucket,), tid[m - 1], np.int32)
        tidx[:m] = tid
        valid = np.zeros((bucket,), bool)
        valid[:m] = batch.valid[i:j]
        xs = GangXs(valid=jnp.asarray(valid), tidx=jnp.asarray(tidx),
                    widx=jnp.asarray(widx))
        dom = self._gang_domains(na, need=w_contig > 0)
        # the gang scan tier consumes the SAME hoisted surfaces as the
        # plan program — its per-dispatch cost collapses to the fit
        # columns + the member scan (ROADMAP item 3's remaining headroom)
        statics = self.compiler.surfaces.stacked(na, table, tuple(wt_list))
        if self.mesh is not None:
            from .parallel.sharding import run_gang_sharded
            c2, packed = run_gang_sharded(
                cfg, self.mesh, na, carry, xs, table,
                wt=jnp.asarray(np.array(wt_list, np.int32)),
                needed=np.int32(needed), dom=dom, statics=statics,
                w_contig=w_contig)
        else:
            c2, packed = run_gang(
                cfg, na, carry, xs, table,
                wt=jnp.asarray(np.array(wt_list, np.int32)),
                needed=np.int32(needed), dom=dom, statics=statics,
                w_contig=w_contig)
        return c2, packed, bucket, False

    def _dispatch_runs(self, profile: Profile, na, carry, batch, table,
                       n: int, groups_needed: bool, ovl=None, nom=None,
                       gang=None):
        """Dispatch the drain through its compiled DrainPlan with ZERO
        host synchronization — results stream back asynchronously and the
        carry chains device-side.

        The drain compiler (kubernetes_tpu/compiler/) maps the pod mix to
        spans: maximal same-signature runs collapse to closed-form top-L
        assignment (run_uniform), group/mixed/host-port spans compile to
        the plan program (run_plan) over the pow2 signature lattice,
        same-signature group spans ride the merge wave (run_wave), gangs
        dispatch all-or-nothing (run_gang), and only the fallback matrix
        (overlays, mesh, short spans, beyond-lattice mixes) keeps the
        per-pod scan. Each uniform record carries its input carry so
        commit-time validation (rare failures: BalancedAllocation
        non-monotonicity, depth-J overflow) can rewind and replay.
        Returns (chain carry, [_RunRec])."""
        cfg = profile.score_config
        plan = self.compiler.compile_drain(
            batch, n, groups_needed=groups_needed, gang_needed=gang,
            overlay=ovl is not None, nominated=nom is not None,
            mesh=self.mesh is not None, strategy=cfg.strategy,
            prefer_taints=self._cluster_has_prefer_taints(),
            wave_min_span=self.wave_min_span,
            uniform_min=self.UNIFORM_RUN_MIN)
        return self._dispatch_spans(cfg, na, batch, table, plan.spans,
                                    carry, ovl=ovl, nom=nom)

    def _uniform_shape(self, na) -> tuple[int, int, int]:
        """(L, K, J) for run_uniform, chosen to be STABLE across drains:
        L is the standing batch bucket (run length only masks via
        n_actual), and J quantizes the node count to its pow2 bucket — so
        the whole workload compiles ONE uniform executable instead of one
        per observed run length. On a tunneled TPU a fresh XLA compile
        costs 20-40s; shape stability is worth more than a minimal J."""
        L = pow2_at_least(self.batch_size)
        K = min(L, na.cap.shape[0])
        n_q = pow2_at_least(max(self.cache.node_count(), 1))
        J = min(max(pow2_at_least(4 * L // n_q + 4), 8), L + 1)
        return L, K, J

    def _dispatch_spans(self, cfg: ScoreConfig, na, batch, table,
                        spans, carry, ovl=None, nom=None):
        """Dispatch the given (i, j, kind) spans back-to-back, chaining
        the carry on device; issues async host copies so the tunnel
        transfer overlaps whatever the host does next. Only uniform
        records keep their input carry (rewind support) — scan/wave runs
        donate it on accelerator backends."""
        records = []
        for (i, j, kind) in spans:
            tag = kind[0]
            if tag == "uniform":
                L, K, J = self._uniform_shape(na)
                if self.mesh is not None:
                    # overlays never reach the mesh (_overlay_eligible)
                    from .parallel.sharding import run_uniform_sharded
                    c2, packed = run_uniform_sharded(
                        cfg, self.mesh, na, carry, self._xone(batch, i),
                        table, np.int32(j - i), L, K, J)
                else:
                    c2, packed = run_uniform(
                        cfg, na, carry, self._xone(batch, i), table,
                        np.int32(j - i), L, K, J, overlay=ovl)
                records.append(_RunRec("uniform", i, j, carry, packed,
                                       L, J, True, span=kind))
            elif tag == "wave":
                c2, packed, bucket = self._wave_dispatch(
                    cfg, na, carry, batch, i, j, table, kind)
                records.append(_RunRec("wave", i, j, None, packed,
                                       bucket, span=kind))
            elif tag == "wavescan":
                c2, packed, bucket = self._wavescan_dispatch(
                    cfg, na, carry, batch, i, j, table, kind)
                records.append(_RunRec("wavescan", i, j, None, packed,
                                       bucket, span=kind))
            elif tag == "gang":
                c2, packed, Lp, uni = self._gang_dispatch(
                    cfg, na, carry, batch, i, j, table, kind)
                # the uniform tier keeps its input carry (exactness-flag
                # replay on the scan tier); the scan tier donates it
                records.append(_RunRec("gang", i, j, carry if uni else None,
                                       packed, Lp, uniform=uni, span=kind))
            else:
                c2, assigns = self._scan_dispatch(cfg, na, carry, batch,
                                                  i, j, table, ovl=ovl,
                                                  nom=nom)
                records.append(_RunRec("scan", i, j, None, assigns,
                                       span=kind))
            carry = c2
        for rec in records:
            if hasattr(rec.result, "copy_to_host_async"):
                rec.result.copy_to_host_async()
        if (self.mesh is not None and records
                and self.observatory.enabled and not self._shard_profile_done
                and self._shard_profile_args is None):
            # arm the one-shot lane profile even when no span rode the
            # scan (the mesh kernels displaced it, ISSUE 16): the probe
            # times the scan-shaped program on a twin of the first span
            self._arm_shard_profile(cfg, na, carry, batch,
                                    records[0].i, records[0].j, table)
        return carry, records

    def _arm_shard_profile(self, cfg: ScoreConfig, na, carry, batch,
                           i: int, j: int, table) -> None:
        """Arm the one-shot sharded-lane profile (perf/observatory.py)
        with a scan-shaped PodXs twin of pods [i:j) — profile_shard_lanes
        times run_batch_sharded's program, whatever kernel the span
        itself rode, so the compute/comms/imbalance decomposition stays
        comparable across dispatch tiers. The twin is capped at 1024
        pods: the probe samples the per-step lane split, and an
        uncapped twin of a 10^5-pod uniform span would re-dispatch a
        10^5-step scan just to measure it."""
        j = min(j, i + 1024)
        bucket = pow2_at_least(j - i)
        m = j - i
        valid = np.zeros((bucket,), bool)
        valid[:m] = batch.valid[i:j]
        sig = np.full((bucket,), batch.sig[j - 1], np.int32)
        sig[:m] = batch.sig[i:j]
        tidx = np.full((bucket,), batch.tidx[j - 1], np.int32)
        tidx[:m] = batch.tidx[i:j]
        xs = PodXs(valid=valid, sig=sig, tidx=tidx)
        self._shard_profile_args = (cfg, self.mesh, na, carry, xs, table,
                                    self._gd_dev, self._gd_fam)

    # -- device-tier degradation (circuit breaker) ----------------------------

    def _device_available(self) -> bool:
        """False while the circuit breaker is open; once the cooldown
        expires, True again so ONE drain probes the device tier
        (half-open) — its commit outcome closes or re-opens the breaker."""
        if self._device_faults < self.device_fault_threshold:
            return True
        return self.clock() >= self._breaker_open_until

    def _record_device_fault(self, reason: str, err: Exception) -> None:
        self._device_faults += 1
        self.device_fallbacks += 1
        self.metrics.device_fallbacks.inc(reason)
        self.slo.observe("device_fallback", bad=1)
        self._invalidate_device_state()
        self.flight.record(
            profile="", pods=0, bound=0, failed=0, signatures=0, kinds=(),
            groups=False, phases={}, breaker_open=self._breaker_open,
            consecutive_faults=self._device_faults, fallback=reason)
        klog.error("device batch fault; degrading drain to host path",
                   reason=reason, err=str(err),
                   consecutive=self._device_faults)
        if self._device_faults >= self.device_fault_threshold:
            self._breaker_open_until = (self.clock()
                                        + self.device_fault_cooldown)
            if not self._breaker_open:
                self._breaker_open = True
                self.metrics.circuit_breaker_transitions.inc("open")
                klog.warning("device tier circuit breaker OPEN",
                             cooldown_s=self.device_fault_cooldown)

    def _record_device_success(self) -> None:
        if not self._device_faults:
            return
        self._device_faults = 0
        if self._breaker_open:
            self._breaker_open = False
            self.metrics.circuit_breaker_transitions.inc("closed")
            klog.info("device tier circuit breaker closed (probe drain "
                      "committed cleanly)")

    def _device_fault_abort(self, pd: "_PendingDrain", reason: str,
                            err: Exception) -> None:
        """A fault while resolving an in-flight drain: degrade ITS pods —
        and every later pending drain, whose carries chain off the faulted
        device state — to the host-oracle path. No pod is lost: each either
        host-binds or goes through the normal failure handler."""
        self._record_device_fault(reason, err)
        victims = [pd, *self._pending]
        self._pending.clear()
        for d in victims:
            if d.audit is not None:
                self.audit.abandon(d.audit, "device_fault")
            if d.gang is not None:
                # the gang degrades to the serial Permit-barrier path
                self.metrics.gang_dispatch.inc("fallback")
            for q in d.qpis:
                self._schedule_one_host(q)

    def promote(self) -> None:
        """Standby → active (the OnStartedLeading takeover hook —
        ha/standby.py calls this after its ledger-warmed reconcile)."""
        self.ha_role = "active"

    def demote(self) -> None:
        """Active → standby (deposed leader: OnStoppedLeading). Pending
        drains stay in flight — their commits carry the old fencing token
        and are rejected server-side, unwinding through on_bind_error."""
        self.ha_role = "standby"

    # -- shard slice lifecycle (ha/shards.py) ---------------------------------

    def shard_adopt(self) -> int:
        """Move parked pods this shard NOW owns into the queue — the warm
        half of a shard rebalance/steal. No LIST, no re-tensorize: the
        parked pods rode the watch stream the whole time, so adoption is
        one bulk enqueue (gang members re-derive quorum in the same
        pass). Returns the number of pods adopted."""
        with self.ingest_lock:
            owned = [p for p in self._shard_parked.values()
                     if self._shard_owns(p)]
            if not owned:
                return 0
            for p in owned:
                self._shard_parked.pop(p.uid, None)
            # adopt precedes the (re-)enqueue in the stitched timeline;
            # queue.add_bulk below restores each known pod's original
            # first-enqueue e2e clock (parking seeded it), so the SLI
            # clock survives the handoff like it survives requeues
            self.journey.record_bulk(
                [p.uid for p in owned], _EV_ADOPT, self.clock(),
                detail=f"{len(owned)} pod(s) from parked set")
            n_gated = self.queue.add_bulk(owned)
            self.metrics.queue_incoming_pods.inc(
                "active", "PodAdd", by=len(owned) - n_gated)
            if n_gated:
                self.metrics.queue_incoming_pods.inc("gated", "PodAdd",
                                                     by=n_gated)
            now = self.clock()
            for ref in dict.fromkeys(p.spec.workload_ref for p in owned
                                     if p.spec.workload_ref):
                if ref in self.queue.gated_refs():
                    self._gang_gated_since.setdefault(ref, now)
                self.queue.retry_gated(ref=ref)
            return len(owned)

    def shard_evict(self) -> int:
        """Park queued pods this shard no longer owns — the release half
        of a rebalance/steal handoff. In-flight drains commit and the
        dispatcher flushes FIRST, so an evicted pod is never left
        assumed; what remains queued here simply moves to the parked set
        (the new owner's adopt is its mirror image). Returns the number
        of pods evicted."""
        with self.ingest_lock:
            self._drain_pending()
            self.dispatcher.flush()
            pods, _ = self.queue.pending_pods()
            moved = 0
            evicted: list = []
            for pod in pods:
                if pod.spec.node_name or self._shard_owns(pod):
                    continue
                self.queue.delete(pod)
                self._shard_parked[pod.uid] = pod
                evicted.append(pod.uid)
                moved += 1
            if evicted:
                self.journey.record_bulk(evicted, _EV_EVICT, self.clock(),
                                         detail="shard handoff")
            return moved

    def resync(self) -> None:
        """Rebuild cache + queue from a fresh LIST of the API server — the
        reflector relist path (client-go Reflector.ListAndWatch after
        watch-stream loss). Call when the watch layer reports loss (e.g.
        dropped events): in-flight drains commit, the dispatcher flushes,
        parked pods are rejected, then cluster state is rebuilt from the
        store's current truth and the device tier reseeds from scratch.
        Holds the ingest lock end to end: a watch event delivered during
        the rebuild must not land on a structure about to be replaced."""
        with self.ingest_lock:
            self._resync_locked()

    def _resync_locked(self) -> None:
        self._drain_pending()
        self.dispatcher.flush()
        for uid in list(self._waiting_pods):
            self._reject_waiting(uid, "resync")
        self.dispatcher.flush()   # the rejects enqueue status patches
        # gang continuity (HA takeover correctness): the fresh queue
        # re-derives the gated_by_ref index deterministically below, but
        # two pieces of gang state live OUTSIDE the queue and would
        # silently reset with it — the quorum-wait start times (dropping
        # the gang_quorum_wait observation for any gang that ungates
        # after the resync) and each surviving group's scheduling
        # deadline (restarting the Permit timeout from zero). Carry both.
        gated_since = dict(self._gang_gated_since)
        old_wm = self.workload_manager
        self.cache = Cache(clock=self.clock)
        self.snapshot = Snapshot()
        self.queue = SchedulingQueue(**self._queue_kwargs)
        # re-attach the journey ledger BEFORE any add_bulk below: the
        # rebuilt queue mints fresh QueuedPodInfos, and add/add_bulk
        # restore each known pod's first-enqueue e2e clock from the
        # ledger (the SLI must not restart at a watch-loss resync)
        self.queue.journey = self.journey
        self.workload_manager = WorkloadManager(clock=self.clock)
        self._gang_gated_since.clear()
        from .backend.debugger import CacheDebugger
        self.debugger = CacheDebugger(self.client, self.cache, self.queue,
                                      metrics=self.metrics)
        # rewire the preemption plugins' live handles onto the new objects
        from .plugins.defaultpreemption import DefaultPreemption
        for prof in self.profiles.values():
            for p in prof.framework.plugins:
                if isinstance(p, DefaultPreemption):
                    p.nominator = self.queue.nominator
                    p.snapshot = self.snapshot
                    if getattr(p, "device_ctx", None) is not None:
                        p.device_ctx.snapshot = self.snapshot
        self._bind_errors.clear()
        # LIST order matters: nodes before pods so bound pods land on real
        # cache entries instead of imputed placeholders. The pod re-ingest
        # rides the columnar bulk paths (cache.add_pods + queue.add_bulk —
        # the same pipeline the ingest hot path uses), so watch-loss
        # recovery scales with the columnar engine instead of paying the
        # per-pod object walk O(all pods) the serial loop did.
        for node in list(self.client.nodes.values()):
            self.cache.add_node(node)
        bound_pods: list[Pod] = []
        unbound_pods: list[Pod] = []
        wm_add = self.workload_manager.add_pod
        # ORDERING CONTRACT (guarded by the resync regression tests in
        # tests/test_gang_device.py): every pod registers in the fresh
        # WorkloadManager BEFORE queue.add_bulk re-runs PreEnqueue, so
        # gang gating re-derives against complete membership — a gang
        # whose quorum already arrived re-gates then ungates in the same
        # add_bulk pass instead of stranding behind PreEnqueue.
        self._shard_parked.clear()
        reparked: list[Pod] = []
        for pod in self.client.pods.values():
            wm_add(pod)
            if pod.spec.node_name:
                bound_pods.append(pod)
            elif self._responsible(pod):
                if self._shard_owns(pod):
                    unbound_pods.append(pod)
                else:
                    self._shard_parked[pod.uid] = pod
                    reparked.append(pod)
        if reparked:
            self._journey_park(reparked, detail="resync")
        self.cache.add_pods(bound_pods)
        if unbound_pods:
            # journey: every unbound pod re-enters the queue because of
            # the resync itself — record the cause before add_bulk so
            # the requeue precedes the (re-)enqueue in the timeline
            self._journey_requeue(
                [p.uid for p in unbound_pods if
                 self.journey.e2e_start(p.uid) is not None],
                "resync")
            n_gated = self.queue.add_bulk(unbound_pods)
            self.metrics.queue_incoming_pods.inc(
                "active", "PodAdd", by=len(unbound_pods) - n_gated)
            if n_gated:
                self.metrics.queue_incoming_pods.inc("gated", "PodAdd",
                                                     by=n_gated)
        # restore the carried gang state for groups that survived the
        # rebuild: quorum-wait clocks for refs STILL gated (a ref whose
        # gate cleared during the rebuild was already observed or its
        # pods are gone), and Permit deadlines for surviving groups
        now = self.clock()
        for ref in self.queue.gated_refs():
            self._gang_gated_since[ref] = gated_since.get(ref, now)
        for key, info in old_wm.pod_group_infos.items():
            fresh = self.workload_manager.pod_group_infos.get(key)
            if fresh is not None:
                fresh.scheduling_deadline = info.scheduling_deadline
        self._invalidate_device_state()
        self.cache.update_snapshot(self.snapshot)
        # full=True: the fresh cache restarts its generation counters, so
        # incremental row-gen diffing against the old state could alias
        self.state.apply_snapshot(self.snapshot, full=True)
        self.metrics.resyncs.inc()
        klog.warning("resync: cache+queue rebuilt from fresh LIST",
                     nodes=len(self.client.nodes),
                     pods=len(self.client.pods))

    # -- commit pipeline ------------------------------------------------------

    def _drain_pending(self) -> None:
        while self._pending:
            self._commit_next()

    def _commit_next(self) -> None:
        """Commit the oldest in-flight drain: resolve its device results
        (blocking only if the async copy hasn't landed), validate the
        uniform runs' exactness flags, and run the host commit. An inexact
        run rewinds to its input carry, replays synchronously, then
        re-dispatches everything downstream — including later pending
        drains — against the corrected chain."""
        pd = self._pending.popleft()
        out = np.full((pd.n,), -1, np.int32)
        t0 = _time.perf_counter()
        try:
            with self.phase_track.scope("device"):
                self._resolve_records(pd, out)
        except Exception as e:
            # XLA fault surfacing at readback/replay: degrade this drain
            # (and the chained later ones) to the host oracle
            self._device_fault_abort(pd, "commit", e)
            return
        names = self.state.node_names
        assigned = out[out >= 0]
        if ((out < -1).any() or (out >= len(names)).any()
                or any(not names[int(a)] for a in assigned)):
            # a garbage assignment tensor (the argmax of a non-finite
            # score column lands here) must never reach the cache
            self._device_fault_abort(pd, "invalid_assignment", ValueError(
                f"device assignments out of range: {out.tolist()}"))
            return
        if self._test_assignment_perturb is not None:
            # test-only hook: inject a wrong-but-valid decision AFTER
            # resolution, BEFORE commit — the shadow audit must catch it
            self._test_assignment_perturb(pd, out)
        if pd.records:
            self._record_device_success()
            # readback wait (zero when the async copy already landed)
            wait = max(_time.perf_counter() - t0, 0.0)
            pd.phases["device_wait"] = wait
            self.metrics.drain_phase.observe(wait, "device")
        self.metrics.device_batch_duration.observe(
            max(_time.perf_counter() - pd.dispatched_at, 0.0))
        self._commit_assignments(pd, out)

    def _resolve_records(self, pd: "_PendingDrain", out) -> None:
        """Resolve a drain's device results into `out`, replaying inexact
        uniform runs (and everything chained downstream) as needed."""
        from .perf.ledger import GLOBAL as _ledger
        idx = 0
        while idx < len(pd.records):
            rec = pd.records[idx]
            r = np.asarray(rec.result)
            _ledger.note_h2d("device_readback", r.nbytes)
            m = rec.j - rec.i
            if rec.kind == "scan":
                out[rec.i:rec.j] = r[:m]
                idx += 1
                continue
            if rec.kind in ("wave", "wavescan"):
                out[rec.i:rec.j] = r[:m]
                self._observe_wave(rec, r, m, pd)
                idx += 1
                continue
            if rec.kind == "gang":
                Lp = rec.L
                if not (r[Lp + 2] and r[Lp + 3]):
                    # the closed-form tier's exactness preconditions
                    # failed on the data: replay on the scan tier from
                    # the kept input carry and re-chain downstream
                    cfg = pd.profile.score_config
                    carry, packed, Lp, _ = self._gang_dispatch(
                        cfg, pd.na, rec.carry_in, pd.batch, rec.i, rec.j,
                        pd.table, rec.span, force_scan=True)
                    r = np.asarray(packed)
                    _ledger.note_h2d("device_readback", r.nbytes)
                    self._replay_downstream(pd, idx, carry)
                accepted = bool(r[Lp])
                raw = np.array(r[:m], np.int32)
                pd.gang_accepted = accepted
                pd.gang_raw = raw
                pd.gang_placed = int(r[Lp + 1])
                # the all-or-nothing verdict: a rejected gang was already
                # unwound ON DEVICE — the host only masks the assignments
                out[rec.i:rec.j] = raw if accepted else np.int32(-1)
                idx += 1
                continue
            exact, depth = bool(r[rec.L]), bool(r[rec.L + 1])
            if exact and depth:
                out[rec.i:rec.j] = r[:m]
                idx += 1
                continue
            # rollback: resolve THIS run synchronously from its input carry
            cfg = pd.profile.score_config
            carry = rec.carry_in
            if exact:
                carry = self._uniform_escalate(cfg, pd.na, carry, pd.batch,
                                               rec.i, rec.j, pd.table, out,
                                               rec.J, ovl=pd.ovl)
            else:
                carry, a = self._scan_dispatch(cfg, pd.na, carry, pd.batch,
                                               rec.i, rec.j, pd.table,
                                               ovl=pd.ovl, nom=pd.nom)
                out[rec.i:rec.j] = np.asarray(a)[:m]
            self._replay_downstream(pd, idx, carry)
            idx += 1

    def _replay_downstream(self, pd: "_PendingDrain", idx: int,
                           carry) -> None:
        """Re-dispatch everything chained after record `idx`: the rest of
        this drain's spans, then every later pending drain, against the
        corrected carry. A profile OR overlay change between drains
        invalidates the sig cache, mirroring the dispatch-site checks."""
        cfg = pd.profile.score_config
        spans = [(q.i, q.j, q.span) for q in pd.records[idx + 1:]]
        carry, new_recs = self._dispatch_spans(cfg, pd.na, pd.batch,
                                               pd.table, spans, carry,
                                               ovl=pd.ovl, nom=pd.nom)
        pd.records[idx + 1:] = new_recs
        prev_profile = pd.profile
        prev_ovl = pd.ovl
        for pd2 in self._pending:
            if pd2.profile is not prev_profile or pd2.ovl is not prev_ovl:
                carry = carry._replace(
                    cache=carry.cache._replace(sig=jnp.int32(0)))
                prev_profile = pd2.profile
                prev_ovl = pd2.ovl
            carry, pd2.records = self._dispatch_runs(
                pd2.profile, pd2.na, carry, pd2.batch, pd2.table,
                pd2.n, pd2.groups_needed, ovl=pd2.ovl, nom=pd2.nom,
                gang=(pd2.gang[1] if pd2.gang is not None else None))
        if self._device_carry is not None:
            self._device_carry = carry

    def _observe_wave(self, rec: _RunRec, r, m: int,
                      pd: Optional["_PendingDrain"] = None) -> None:
        """Record a resolved wave's stats: waves executed, conflict ratio
        (conflict-cut events + serially repaired pods over the span), and
        the first wave's accepted conflict-free prefix length. Also folds
        the raw numbers into the drain's flight-recorder entry."""
        B = rec.L
        if rec.kind == "wave":
            waves, confs = int(r[B]), int(r[B + 1])
            prefix, serial = int(r[B + 2]), int(r[B + 3])
            self.metrics.wave_placement_waves.inc(by=max(waves, 1))
            self.metrics.wave_conflict_ratio.observe(
                min((confs + serial) / max(m, 1), 1.0))
            self.metrics.wave_accepted_prefix.observe(max(prefix, 0))
        else:
            waves, serial = 1, 0
            confs, prefix = int(r[B]), int(r[B + 1])
            self.metrics.wave_placement_waves.inc()
            self.metrics.wave_conflict_ratio.observe(
                min(confs / max(m, 1), 1.0))
            self.metrics.wave_accepted_prefix.observe(max(prefix, 0))
        if pd is not None:
            w = pd.wave
            w["waves"] = w.get("waves", 0) + max(waves, 1)
            w["conflicts"] = w.get("conflicts", 0) + confs + serial
            w.setdefault("first_prefix", max(prefix, 0))

    def _commit_assignments(self, pd: _PendingDrain, out) -> int:
        """Host commit of a resolved drain: bulk assume + bind enqueue for
        hook-free pods, the full reserve/permit/pre-bind chain for the
        rest, failure handling for the unassigned. Runs under the drain's
        id (log context + event tagging) and the `commit` phase mark."""
        from .utils.logging import log_context
        self.events.current_drain = pd.drain_id
        try:
            with log_context(drain=pd.drain_id), \
                    self.phase_track.scope("commit"):
                return self._commit_assignments_inner(pd, out)
        finally:
            self.events.current_drain = 0

    def _backpressure_stall_delta(self) -> float:
        """Pipeline stall seconds not yet attributed to a committed
        drain: the monotonic stall total minus the checkpoint the last
        commit left. Every stall second lands on exactly ONE drain (the
        next to commit), so the per-cause metric sums stay conserved.
        0.0 in lock-step operation — no pipeline, no backpressure."""
        pipe = self.pipeline
        if pipe is None:
            return 0.0
        total = pipe.backpressure_stall_seconds()
        delta = total - self._bp_stall_committed
        self._bp_stall_committed = total
        return max(delta, 0.0)

    def _critical_path_verdict(self, pd: "_PendingDrain") -> dict:
        """Per-drain bottleneck attribution (perf/critical_path.py,
        ISSUE 20), computed at commit when every segment of the drain's
        wall is known: host_build and its children, device_dispatch with
        the sharded lane profile's comms split, the readback wait, the
        commit tail, and the pipeline's backpressure stall delta. The
        verdict rides the FlightRecord and the two
        scheduler_critical_path_* families. {} with the gate off."""
        if not self.critical_path_enabled:
            return {}
        from .perf.critical_path import attribute_drain
        comms = 0.0
        if self.mesh is not None:
            comms = float((self.observatory.shard_profile() or {}).get(
                "commsShare", 0.0) or 0.0)
        cp = attribute_drain(pd.phases, kernels=pd.kernels,
                             comms_share=comms,
                             backpressure_s=self._backpressure_stall_delta())
        m = self.metrics
        for cause, secs in cp["causes"].items():
            if secs > 0.0:
                m.critical_path_seconds.inc(cause, by=secs)
        m.bottleneck_drains.inc(cp["verdict"])
        return cp

    def _commit_assignments_inner(self, pd: _PendingDrain, out) -> int:
        t_commit = _time.perf_counter()
        qpis = pd.qpis
        profile = pd.profile
        fwk = profile.framework
        n = pd.n
        self.schedule_attempts += n
        from .metrics import SCHEDULED, UNSCHEDULABLE
        n_ok = int((out >= 0).sum())
        # attempt latency = dispatch→commit wall time split over the drain.
        # NOTE: with the async pipeline this includes time the result sat
        # in flight behind other work — an SLI-style number (queue-to-bind),
        # deliberately not a device-busy-time measurement.
        per_pod = max(_time.perf_counter() - pd.dispatched_at, 0.0) / max(n, 1)
        if n_ok:
            self.metrics.attempt_duration.observe(per_pod, SCHEDULED,
                                                  profile.name)
        if n - n_ok:
            self.metrics.attempt_duration.observe(per_pod, UNSCHEDULABLE,
                                                  profile.name)
        names = self.state.node_names
        diag_cache: dict = {}
        # an accepted gang commits atomically through the fast path: the
        # quorum the Permit barrier would enforce per pod was already
        # proven by the device verdict, so the Reserve/Permit chain is
        # vacuous (members with volumes/claims never reach a gang drain)
        gang_fast = pd.gang is not None and pd.gang_accepted
        if self.commit_engine is not None:
            # columnar commit engine (ingest/commit.py): one pass, the
            # cache assume driven by the per-signature commit facts
            bound, failures = self.commit_engine.commit(pd, out, names,
                                                        gang_fast)
        else:
            fast: list[tuple[QueuedPodInfo, str]] = []
            bound = 0
            failures = []
            for i in range(n):
                a = out[i]
                qpi = qpis[i]
                if a < 0:
                    failures.append(qpi)
                    continue
                if not gang_fast and _needs_per_pod_hooks(profile,
                                                          qpi.pod.spec):
                    self._assume_and_bind(qpi, names[int(a)])
                    bound += 1
                else:
                    fast.append((qpi, names[int(a)]))
            bound += self._fast_commit(fast, profile)
        # every device batch evaluates every kernel-modeled filter/score
        # plugin for every pod (PluginEvaluationTotal,
        # instrumented_plugins.go:83 — batch-granular here)
        for p in fwk.filter_plugins:
            self.metrics.plugin_evaluation_total.inc(
                p.name(), "Filter", profile.name, by=n)
        for p in fwk.score_plugins:
            self.metrics.plugin_evaluation_total.inc(
                p.name(), "Score", profile.name, by=n)
        if pd.gang is not None:
            self.metrics.gang_dispatch.inc(
                "placed" if pd.gang_accepted else "rejected")
        fail_msgs: dict = {}
        if failures:
            # diagnosis reads the live snapshot (assumes included)
            self.cache.update_snapshot(self.snapshot)
            if pd.gang is not None and not pd.gang_accepted:
                self._fail_rejected_gang(pd, qpis, diag_cache)
            else:
                for qpi in failures:
                    err = self._device_fit_error(qpi, profile, diag_cache)
                    if pd.audit is not None:
                        # the reference-format message the audit diffs
                        # against the oracle replay's
                        fail_msgs[qpi.pod.uid] = str(err)
                    self._handle_failure(qpi, err)
        commit_s = max(_time.perf_counter() - t_commit, 0.0)
        self.metrics.drain_phase.observe(commit_s, "commit")
        pd.phases["commit"] = pd.phases.get("commit", 0.0) + commit_s
        # SLO engine feeds (obs/slo.py): attempt latency, queue→bind e2e
        # and the device-tier health, one observation batch per drain
        slo = self.slo
        bad_a = n if per_pod > slo.threshold("attempt_latency") else 0
        slo.observe("attempt_latency", good=n - bad_a, bad=bad_a)
        thr_e = slo.threshold("e2e_latency")
        now = self.clock()
        bad_e = 0
        for qpi in qpis:
            if now - (qpi.initial_attempt_timestamp
                      or qpi.timestamp) > thr_e:
                bad_e += 1
        slo.observe("e2e_latency", good=n - bad_e, bad=bad_e)
        slo.observe("device_fallback", good=1)
        # journey: the drain segment is the dispatch→commit wall window,
        # shared by every pod in the drain (the device solves them as one
        # batch); plus the per-second timeline counters and the resolved
        # cluster-probe sample
        window = per_pod * max(n, 1)
        self.metrics.e2e_segment.observe_array([window] * n, "drain")
        self.timeline.segment(now, "drain", window * n, n)
        self.timeline.bump(now, "failures", len(failures))
        self.timeline.bump(now, "drains", 1)
        probe_snap = self._resolve_probe(pd)
        if probe_snap:
            self._last_probe = probe_snap
            self.timeline.probe(now, probe_snap)
        hot: tuple = ()
        if self.profiler is not None:
            total_s = sum(pd.phases.values())
            if total_s >= self.profiler.slow_drain_s:
                # pin the hottest frames of the drain's wall window onto
                # the flight entry — "slow drain 17" answers itself
                hot = tuple(self.profiler.top_frames(
                    5, seconds=max(total_s, 1.0) + 1.0))
        cp = self._critical_path_verdict(pd)
        frec = self.flight.record(
            profile=profile.name, pods=n, bound=bound,
            failed=len(failures),
            signatures=(int(np.unique(pd.batch.tidx[:n]).size)
                        if pd.batch is not None else 0),
            kinds=tuple(r.kind for r in pd.records) or ("host_greedy",),
            groups=pd.groups_needed, phases=dict(pd.phases),
            wave=dict(pd.wave), breaker_open=self._breaker_open,
            consecutive_faults=self._device_faults,
            fallback="" if pd.records else "host_greedy",
            events={"Scheduled": bound,
                    "FailedScheduling": len(failures)},
            drain_id=pd.drain_id, hot_frames=hot, probe=probe_snap,
            kernels=dict(pd.kernels), shard=tuple(self.shard_ids),
            critical_path=cp)
        if pd.audit is not None:
            # hand the committed decisions to the shadow-audit worker;
            # the replay + diff run off the hot path
            self.audit.submit(pd.audit, out=out,
                              names=self.state.node_names,
                              fail_msgs=fail_msgs, flight_rec=frec,
                              ext_gen=self._ext_mutations)
        klog.v(2).info("batch committed", profile=profile.name, pods=n,
                       bound=bound, unschedulable=len(failures),
                       latency_ms=round(per_pod * n * 1e3, 1))
        if klog.v(5).enabled and failures:
            for qpi in failures:
                klog.v(5).info("unschedulable", pod=qpi.pod.uid,
                               plugins=sorted(qpi.unschedulable_plugins
                                              or ()))
        return bound

    def _fail_rejected_gang(self, pd: _PendingDrain, qpis: list,
                            diag_cache: dict) -> None:
        """All-or-nothing rejection commit: no member binds, none ever
        reserved — the Permit-barrier's partial-failure churn (Reserve →
        park → timeout → Unreserve) collapses to straight failure
        handling. Members with NO feasible node fail with the device mask
        diagnosis (reference-format reasons histogram; preemption runs —
        this is how a higher-priority gang preempts a lower one), while
        members whose placement the quorum verdict unwound fail with the
        gang reason and no preemption (the analog of a Permit rejection,
        which never runs PostFilter)."""
        from .framework.types import Diagnosis
        profile = pd.profile
        ref, _needed, min_count = pd.gang
        raw = pd.gang_raw
        # the infeasible members' rejector plugins become the whole gang's
        # requeue triggers: the cluster event that could fix the stuck
        # member is exactly the event that un-sticks the gang
        plugins: set = {"GangScheduling"}
        infeasible: list = []
        unwound: list = []
        names = self.state.node_names
        for i, qpi in enumerate(qpis):
            if raw is not None and i < len(raw) and raw[i] >= 0:
                unwound.append((qpi, names[int(raw[i])]))
            else:
                infeasible.append(qpi)
        # Diagnose the infeasible members against the state the serial
        # oracle would have seen: the unwound members' placements
        # TEMPORARILY assumed (parked members hold resources there), so
        # the reasons histogram reads "2 Insufficient cpu", not "cluster
        # empty". The assumes are forgotten before any failure handling —
        # preemption must never see the phantom members as victims.
        errs: list = []
        if infeasible:
            temp: list = []
            for qpi, node_name in unwound:
                pi = PodInfo(pod=qpi.pod.with_node_name(node_name),
                             requests=qpi.pod_info.requests,
                             cpu_nonzero=qpi.pod_info.cpu_nonzero,
                             mem_nonzero=qpi.pod_info.mem_nonzero)
                try:
                    self.cache.assume_pod_info(pi)
                    temp.append(pi.pod)
                except KeyError:
                    pass
            try:
                self.cache.update_snapshot(self.snapshot)
                for qpi in infeasible:
                    errs.append(self._device_fit_error(qpi, profile,
                                                       diag_cache))
            finally:
                for pod in temp:
                    try:
                        self.cache.forget_pod(pod)
                    except (KeyError, ValueError):
                        pass
                self.cache.update_snapshot(self.snapshot)
                # the diagnosis context refreshed the staging arrays with
                # the phantom members in them: restore the real truth
                self.state.apply_snapshot(self.snapshot)
        for qpi, err in zip(infeasible, errs):
            plugins |= err.diagnosis.unschedulable_plugins
            self._handle_failure(qpi, err)
        n_nodes = len(self.snapshot.node_info_list)
        msg = (f"gang {ref!r} rejected: {pd.gang_placed} of {min_count} "
               f"required members placeable")
        for qpi, _node in unwound:
            err = FitError(qpi.pod, n_nodes)
            err.diagnosis = Diagnosis(unschedulable_plugins=set(plugins),
                                      pre_filter_msg=msg)
            self._handle_failure(qpi, err, try_preempt=False,
                                 requeue_cause="gang_split")

    def _fast_commit(self, pairs: list, profile: Profile) -> int:
        """Vectorized commit for hook-free pods: the per-pod work of
        assume (cache.go:369) + FinishBinding + bind enqueue collapsed to
        the minimum — this loop bounds the whole scheduler's throughput
        (schedule_one.go:65-136's responsibilities at batch scale)."""
        if not pairs:
            return 0
        from .backend.cache import _PodState
        cache = self.cache
        pod_states = cache.pod_states
        assumed_set = cache.assumed_pods
        ttl = cache.ttl
        nominated = self.queue.nominator.nominated_pods
        in_flight = self.queue.in_flight_pods
        now = self.clock()
        bound_pods: list[tuple[Pod, Pod]] = []
        sli_by_attempts: dict[int, list] = {}
        for qpi, node_name in pairs:
            pod = qpi.pod
            uid = pod.uid
            if uid in pod_states:
                in_flight.pop(uid, None)
                continue
            assumed = pod.with_node_name(node_name)
            pi = PodInfo(pod=assumed, requests=qpi.pod_info.requests,
                         cpu_nonzero=qpi.pod_info.cpu_nonzero,
                         mem_nonzero=qpi.pod_info.mem_nonzero)
            cache._add_pod_info_to_node(pi)
            st = _PodState(pod=assumed, assumed=True, binding_finished=True)
            if ttl > 0:
                st.deadline = now + ttl
            pod_states[uid] = st
            assumed_set.add(uid)
            if nominated:
                self.queue.nominator.delete(pod)
            in_flight.pop(uid, None)
            bound_pods.append((assumed, pod))
            sli_by_attempts.setdefault(qpi.attempts or 1, []).append(
                now - (qpi.initial_attempt_timestamp or qpi.timestamp))
            if qpi.unschedulable_plugins:
                qpi.unschedulable_plugins = set()
            qpi.consecutive_errors_count = 0
        if not in_flight:
            self.queue.in_flight_events.clear()
        self.journey.record_bulk(
            [pod.uid for _assumed, pod in bound_pods], _EV_ASSIGN, now,
            detail=[assumed.spec.node_name for assumed, _pod in bound_pods])
        self.dispatcher.add_binds(bound_pods)
        # Scheduled events, bulk + lazy-formatted (pod.uid is already the
        # "ns/name" object ref — no per-pod string building here)
        self.events.scheduled_bulk(
            [(pod.uid, assumed.spec.node_name)
             for assumed, pod in bound_pods], now=now)
        nb = len(bound_pods)
        self.scheduled_count += nb
        from .metrics import SCHEDULED
        self.metrics.schedule_attempts.inc(SCHEDULED, profile.name, by=nb)
        for attempts, values in sli_by_attempts.items():
            self.metrics.sli_duration.observe_array(values, str(attempts))
        return nb

    def _xone(self, batch, i: int) -> PodXs:
        return PodXs(valid=np.bool_(True), sig=np.int32(batch.sig[i]),
                     tidx=np.int32(batch.tidx[i]))

    def _uniform_escalate(self, cfg: ScoreConfig, na, carry, batch,
                          i: int, j: int, table, out, j_failed: int,
                          ovl=None):
        """Depth-J overflow recovery: retry the run with a deeper matrix
        (synchronous — this path is rare, and the only one that mints
        non-standard J shapes), falling back to the scan if even J=L+1
        reports failure (can't happen semantically, but belt and
        braces)."""
        L, K, _ = self._uniform_shape(na)
        J = j_failed
        while J < L + 1:
            J = min(8 * J, L + 1)
            if self.mesh is not None:
                from .parallel.sharding import run_uniform_sharded
                c2, packed = run_uniform_sharded(
                    cfg, self.mesh, na, carry, self._xone(batch, i),
                    table, np.int32(j - i), L, K, J)
            else:
                c2, packed = run_uniform(cfg, na, carry,
                                         self._xone(batch, i), table,
                                         np.int32(j - i), L, K, J,
                                         overlay=ovl)
            r = np.asarray(packed)
            if r[L] and r[L + 1]:
                out[i:j] = r[:j - i]
                return c2
            if not r[L]:
                break
        carry, a = self._scan_dispatch(cfg, na, carry, batch, i, j, table,
                                       ovl=ovl)
        out[i:j] = np.asarray(a)[:j - i]
        return carry

    def _scan_dispatch(self, cfg: ScoreConfig, na, carry, batch, i: int,
                       j: int, table, ovl=None, nom=None):
        """Dispatch run_batch over pods [i:j) padded to a pow2 bucket;
        returns (carry, device assignments) without synchronizing."""
        bucket = pow2_at_least(j - i)
        m = j - i
        valid = np.zeros((bucket,), bool)
        valid[:m] = batch.valid[i:j]
        sig = np.full((bucket,), batch.sig[j - 1], np.int32)
        sig[:m] = batch.sig[i:j]
        tidx = np.full((bucket,), batch.tidx[j - 1], np.int32)
        tidx[:m] = batch.tidx[i:j]
        # self-nominated pods keep their signature: the cached fit_ok is
        # overlay-pure and the per-pod exclusion is a one-row delta in
        # _eval_pod, so the fast path still serves them
        nom_idx = None
        if nom is not None:
            nom_idx = np.full((bucket,), -1, np.int32)
            nom_idx[:m] = nom[i:j]
        xs = PodXs(valid=valid, sig=sig, tidx=tidx, nom_idx=nom_idx)
        if self.mesh is not None:
            from .parallel.sharding import run_batch_sharded
            c2, a = run_batch_sharded(cfg, self.mesh, na, carry, xs, table,
                                      groups=self._gd_dev, fam=self._gd_fam)
            if self.observatory.enabled and not self._shard_profile_done:
                # arm the one-shot lane profile with this dispatch's inputs
                # (run_batch_sharded does not donate the carry, and c2 keeps
                # the POST-dispatch state alive for the probe)
                self._shard_profile_args = (cfg, self.mesh, na, c2, xs,
                                            table, self._gd_dev, self._gd_fam)
            return c2, a
        return run_batch(cfg, na, carry, xs, table, groups=self._gd_dev,
                         fam=self._gd_fam, overlay=ovl)

    def reconcile(self) -> list:
        """Debug/divergence check (cache debugger analog): pull the resident
        device carry into staging and compare against the host cache truth.
        Returns divergent node names; [] when scan bookkeeping matches."""
        self._drain_pending()
        self.cache.update_snapshot(self.snapshot)
        if self._device_carry is not None:
            c = self._device_carry
            gens = {ni.name: ni.generation
                    for ni in self.snapshot.node_info_list}
            self.state.adopt_carry(c.used, c.nonzero_used, c.npods, c.ports,
                                   touched=gens)
        divergent = self.state.reconcile(self.snapshot)
        if divergent:
            self.metrics.cache_divergence.inc("device_vs_host",
                                              by=len(divergent))
            klog.warning("device carry diverges from host cache",
                         nodes=divergent)
        return divergent

    def debug_compare(self) -> dict:
        """Full divergence sweep (cache debugger analog, SIGUSR2 in the
        reference): device-carry vs host cache AND host cache vs
        apiserver truth."""
        return {"device_vs_host": self.reconcile(),
                "host_vs_apiserver": self.debugger.compare()}

    def profile_session(self):
        """jax.profiler session context, gated by the config
        `profilerTraceDir` knob (a no-op context when unset): wrap a
        stretch of scheduling with it to get the XLA/TPU-level trace
        under the host spans."""
        from .utils.tracing import jax_profiler_session
        return jax_profiler_session(self.profiler_trace_dir)

    def _device_fit_error(self, qpi: QueuedPodInfo, profile: Profile,
                          diag_cache: dict) -> FitError:
        """The device reports only global infeasibility; the diagnosis —
        exact per-node statuses and rejecting plugins, which queueing
        hints, preemption's resolvable-node pruning and the
        FailedScheduling event all need — comes from the mask-derived
        device reduction (ops/program.py diagnose_row) when the signature
        is tensorizable, else from a host-oracle filter replay. Identical
        signatures share identical filter outcomes, so the dict lookup
        makes mass failures (a full cluster rejecting a homogeneous tail)
        cost ONE reduction per signature per batch instead of one per
        pod."""
        from .framework.types import Diagnosis
        # content key, not the numeric sig id: host-port pods carry sig 0
        # yet still share identical filter outcomes
        sig = BatchBuilder._sig_key(qpi.pod)
        cached = diag_cache.get(sig)
        if cached is None:
            cached = self._mask_diagnosis(qpi, diag_cache)
            if cached is None:
                cached = self._host_replay_diagnosis(qpi, profile)
            if not cached.unschedulable_plugins:
                cached.unschedulable_plugins = {"NodeResourcesFit"}
            diag_cache[sig] = cached
        err = FitError(qpi.pod, len(self.snapshot.node_info_list))
        err.diagnosis = cached
        return err

    def _host_replay_diagnosis(self, qpi: QueuedPodInfo, profile: Profile):
        """Host-oracle filter replay over the live snapshot — the fallback
        diagnosis tier (gate off, non-tensorizable signature, reduction
        fault)."""
        from .framework.types import Diagnosis
        fwk = profile.framework
        nodes = self.snapshot.node_info_list
        diagnosis = Diagnosis()
        state = CycleState()
        pre_result, status = fwk.run_pre_filter_plugins(
            state, qpi.pod, nodes)
        if not status.is_success():
            diagnosis.pre_filter_msg = "; ".join(status.reasons)
            if status.plugin:
                diagnosis.unschedulable_plugins.add(status.plugin)
        else:
            fwk.find_nodes_that_pass_filters(state, qpi.pod, nodes,
                                             pre_result, diagnosis)
        return diagnosis

    def _mask_diagnosis(self, qpi: QueuedPodInfo, diag_cache: dict):
        """Diagnosis from the device filter masks: one diagnose_row
        reduction against the post-commit node state attributes every
        rejected node to its first failing plugin (host filter order) with
        exact per-reason detail. Returns None when the reduction cannot
        represent the pod (host-fallback signature, gate off, sharded
        mesh) or faults — the caller then replays on the host."""
        if (self.mesh is not None
                or not self.feature_gates.enabled("DeviceMaskDiagnosis")):
            return None
        ent = self.builder._lookup(qpi.pod)
        if ent[0] != "row":
            return None
        tidx = ent[2]
        ctx = diag_cache.get("_device_ctx")
        if ctx is None:
            try:
                ctx = self._diagnosis_context()
            except Exception as e:
                klog.warning("device diagnosis context build failed; "
                             "falling back to host filter replay",
                             err=str(e))
                ctx = False
            diag_cache["_device_ctx"] = ctx
        if ctx is False:
            return None
        na, table, gd, gc, fam = ctx
        try:
            from .ops.program import diagnose_row
            slot, pods_fail, cols_fail = diagnose_row(na, table, tidx,
                                                      gd=gd, gc=gc, fam=fam)
            slot = np.asarray(slot)
            pods_fail = np.asarray(pods_fail)
            cols_fail = np.asarray(cols_fail)
        except Exception as e:
            klog.warning("device diagnosis reduction failed; falling back "
                         "to host filter replay", err=str(e))
            return None
        return self._assemble_diagnosis(qpi, tidx, slot, pods_fail,
                                        cols_fail)

    def _diagnosis_context(self):
        """Post-commit device state for diagnose_row, built once per
        failed drain (cached in the drain's diag_cache): staging node
        arrays refreshed from the live snapshot, the signature table, and
        — when group constraints are live — fresh group tensors."""
        from .ops.groups import to_device
        from .ops.program import PodTableDev
        self.state.apply_snapshot(self.snapshot)
        self.state.ensure_arrays()
        na = self.state.arrays
        table = PodTableDev(*(jnp.asarray(getattr(self.builder.table, f))
                              for f in PodTableDev._fields))
        gd = gc = fam = None
        groups_needed = (
            self.builder.groups.any_groups()
            or bool(self.snapshot.have_pods_with_affinity_list)
            or bool(
                self.snapshot.have_pods_with_required_anti_affinity_list))
        if groups_needed:
            gd_np, gc_np = self.builder.groups.build_dev(self.snapshot)
            gd, gc = to_device(gd_np), to_device(gc_np)
            fam = self.builder.groups.families(self.snapshot)
        return na, table, gd, gc, fam

    def _assemble_diagnosis(self, qpi: QueuedPodInfo, tidx: int, slot,
                            pods_fail, cols_fail):
        """slot/fit arrays → Diagnosis with per-node Statuses carrying the
        host plugins' exact reason strings and codes."""
        from .framework.types import Diagnosis
        from .ops import program as prog
        from .plugins.node_basics import (TaintToleration as TTPlugin,
                                          find_matching_untolerated_taint)
        from .plugins.nodeaffinity import ERR_REASON as NA_ERR
        from .plugins.podtopologyspread import (
            ERR_REASON_CONSTRAINTS_NOT_MATCH, ERR_REASON_NODE_LABEL_NOT_MATCH)
        from .plugins.interpodaffinity import (ERR_AFFINITY,
                                               ERR_ANTI_AFFINITY,
                                               ERR_EXISTING_ANTI_AFFINITY)
        pod = qpi.pod
        diagnosis = Diagnosis()
        names = self.state.node_names
        # one shared Status per identical (slot, detail) — a 5k-node mass
        # rejection allocates a handful of Status objects, not 5k
        shared: dict = {}
        simple = {
            prog.DIAG_NODE_UNSCHEDULABLE: (
                Status.unresolvable, "node(s) were unschedulable",
                "NodeUnschedulable"),
            prog.DIAG_NODE_NAME: (
                Status.unresolvable,
                "node(s) didn't match the requested node name", "NodeName"),
            prog.DIAG_NODE_AFFINITY: (
                Status.unresolvable, NA_ERR, "NodeAffinity"),
            prog.DIAG_PORTS: (
                Status.unschedulable,
                "node(s) didn't have free ports for the requested pod ports",
                "NodePorts"),
            prog.DIAG_SPREAD_LABEL: (
                Status.unresolvable, ERR_REASON_NODE_LABEL_NOT_MATCH,
                "PodTopologySpread"),
            prog.DIAG_SPREAD_SKEW: (
                Status.unschedulable, ERR_REASON_CONSTRAINTS_NOT_MATCH,
                "PodTopologySpread"),
            prog.DIAG_IPA_AFFINITY: (
                Status.unresolvable, ERR_AFFINITY, "InterPodAffinity"),
            prog.DIAG_IPA_ANTI: (
                Status.unschedulable, ERR_ANTI_AFFINITY, "InterPodAffinity"),
            prog.DIAG_IPA_EXISTING_ANTI: (
                Status.unschedulable, ERR_EXISTING_ANTI_AFFINITY,
                "InterPodAffinity"),
        }
        req_row = self.builder.table.req[tidx]
        cap = self.state.arrays.cap
        rnames = self.state.rtable.names
        for i in np.nonzero(slot > 0)[0]:
            i = int(i)
            name = names[i] if i < len(names) else ""
            if not name:
                continue
            s = int(slot[i])
            if s == prog.DIAG_TAINT:
                # reason carries the taint content — resolve it from the
                # node itself, exactly like the host plugin
                ni = self.snapshot.get(name)
                taint = find_matching_untolerated_taint(
                    ni.node.spec.taints, pod.spec.tolerations,
                    TTPlugin.FILTER_EFFECTS) if ni is not None else None
                key = (s, taint.key if taint else "",
                       taint.value if taint else "")
                status = shared.get(key)
                if status is None:
                    reason = (f"node(s) had untolerated taint "
                              f"{{{taint.key}: {taint.value}}}" if taint
                              else "node(s) had untolerated taint")
                    status = shared[key] = Status.unresolvable(
                        reason, plugin="TaintToleration")
            elif s == prog.DIAG_FIT:
                # per-reason fit detail (fit.go insufficient_resources):
                # Too many pods + per-column Insufficient <resource>;
                # unresolvable when a request exceeds this node's raw
                # allocatable (per-node, so it keys the sharing too)
                cols = tuple(int(c) for c in np.nonzero(cols_fail[i])[0])
                unresolvable = any(int(req_row[c]) > int(cap[i, c])
                                   for c in cols)
                key = (s, bool(pods_fail[i]), cols, unresolvable)
                status = shared.get(key)
                if status is None:
                    reasons = []
                    if pods_fail[i]:
                        reasons.append("Too many pods")
                    reasons.extend(
                        "Insufficient " + (rnames[c] if c < len(rnames)
                                           else f"resource-{c}")
                        for c in cols)
                    mk = (Status.unresolvable if unresolvable
                          else Status.unschedulable)
                    status = shared[key] = mk(*reasons,
                                              plugin="NodeResourcesFit")
            else:
                status = shared.get(s)
                if status is None:
                    mk, reason, plugin = simple[s]
                    status = shared[s] = mk(reason, plugin=plugin)
            diagnosis.node_to_status[name] = status
            if status.plugin:
                diagnosis.unschedulable_plugins.add(status.plugin)
        return diagnosis

    # -- scheduling: host path (oracle + fallback) ----------------------------

    def schedule_one(self) -> bool:
        """Reference ScheduleOne: pop + host-schedule a single pod."""
        if self.profiler is not None:
            self.profiler.ensure_running()
        self._drain_pending()
        qpi = self.queue.pop()
        if qpi is None:
            return False
        ok = self._schedule_one_host(qpi)
        self.dispatcher.flush()
        return ok

    def _schedule_one_host(self, qpi: QueuedPodInfo) -> bool:
        self.schedule_attempts += 1
        pod = qpi.pod
        profile = self.profiles.get(pod.spec.scheduler_name)
        if profile is None:
            self.queue.done(pod.uid)
            return False
        if self._skip_pod_schedule(pod):
            self.queue.done(pod.uid)
            return False
        self.cache.update_snapshot(self.snapshot)
        state = CycleState()
        # plugin_execution_duration sampling: ~10% of host cycles
        # (pluginMetricsSamplePercent, schedule_one.go:51,104-107)
        state.record_plugin_metrics = (self.schedule_attempts % 10 == 0)
        try:
            result = schedule_pod(profile.framework, state, pod,
                                  self.snapshot.node_info_list,
                                  nominator=self.queue.nominator,
                                  extenders=profile.extenders)
        except FitError as err:
            self._handle_failure(qpi, err, state)
            return False
        except Exception:
            # a plugin blew up (schedule_one.go:161 err path): record it —
            # silent requeue makes plugin bugs undebuggable
            klog.exception("scheduling attempt failed with plugin error",
                           pod=pod.uid,
                           errors=qpi.consecutive_errors_count + 1)
            qpi.consecutive_errors_count += 1
            self.error_count += 1
            self.queue.add_unschedulable_if_not_present(qpi)
            return False
        self.host_scheduled += 1
        self._assume_and_bind(qpi, result.suggested_host, state)
        # a host-path assume mutates node state outside the device carry
        self._invalidate_device_state()
        return True

    def _skip_pod_schedule(self, pod: Pod) -> bool:
        """schedule_one.go:404: deleted or already-assumed pods."""
        return self.cache.is_assumed_pod(pod)

    # -- assume + bind (shared) -----------------------------------------------

    def _assume_and_bind(self, qpi: QueuedPodInfo,
                         node_name: str,
                         state: Optional[CycleState] = None) -> None:
        pod = qpi.pod
        assumed = pod.with_node_name(node_name)
        # reuse the queue entry's pre-parsed requests — no quantity
        # re-parsing on the per-bind hot path
        pi = PodInfo(pod=assumed, requests=qpi.pod_info.requests,
                     cpu_nonzero=qpi.pod_info.cpu_nonzero,
                     mem_nonzero=qpi.pod_info.mem_nonzero)
        try:
            self.cache.assume_pod_info(pi)
        except KeyError:
            self.queue.done(pod.uid)
            return
        self.queue.nominator.delete(pod)
        profile = self.profiles.get(pod.spec.scheduler_name)
        fwk = profile.framework
        cs = state or CycleState()
        # volume-free pods under gang-only hooks skip reserve/permit; a pod
        # with PVC volumes always runs the full chain (VolumeBinding holds
        # its per-node decisions in the CycleState from the host filter).
        # Mirrored by _needs_per_pod_hooks — keep the gates in lockstep.
        run_hooks = (fwk.reserve_plugins or fwk.permit_plugins) and (
            pod.spec.workload_ref or pod.spec.volumes
            or pod.spec.resource_claims
            or not profile.gang_only_hooks)
        if run_hooks:
            status = fwk.run_reserve_plugins_reserve(cs, assumed, node_name)
            if not status.is_success():
                fwk.run_reserve_plugins_unreserve(cs, assumed, node_name)
                self.cache.forget_pod(assumed)
                self._invalidate_device_state()
                self._handle_failure(qpi, FitError(pod, 0),
                                     try_preempt=False)
                return
            status, wait_timeout = fwk.run_permit_plugins(cs, assumed,
                                                          node_name)
            if status.code == Code.WAIT and wait_timeout <= 0:
                # the group's scheduling deadline already expired: reject
                # instead of parking for another round (the reference's
                # WaitOnPermit timer fires immediately at timeout 0)
                status = Status.unschedulable(
                    "gang scheduling deadline expired",
                    plugin=status.plugin)
            if not status.is_success() and status.code != Code.WAIT:
                # rejection OR plugin error: either way the pod must not
                # bind — unreserve, release the assumed resources, requeue
                fwk.run_reserve_plugins_unreserve(cs, assumed, node_name)
                self.cache.forget_pod(assumed)
                self._invalidate_device_state()
                if status.code == Code.ERROR:
                    self.error_count += 1
                self._handle_failure(qpi, FitError(pod, 0),
                                     try_preempt=False)
                return
            if status.code == Code.WAIT:
                # WaitOnPermit (schedule_one.go:302): park; resources stay
                # assumed; a later gang member's Permit (or the timeout
                # sweep in flush_queues) resolves it
                self.queue.done(pod.uid)
                now = self.clock()
                self._waiting_pods[pod.uid] = _WaitingPodRec(
                    qpi=qpi, assumed=assumed, node_name=node_name,
                    cycle_state=cs, deadline=now + wait_timeout,
                    parked_at=now, wait_plugin=status.plugin)
                return
        if not self._run_pre_bind(profile, cs, qpi, assumed, node_name):
            return
        self.queue.done(pod.uid)
        self.cache.finish_binding(assumed)
        binder = next((e for e in profile.extenders if e.is_binder()), None)
        if binder is not None:
            # a binder extender takes over the bind call (extender.go
            # IsBinder; schedule_one.go extendersBinding) — synchronously,
            # since the webhook owns the API write
            try:
                binder.bind(assumed, node_name)
            except Exception as e:
                self._on_bind_error(assumed, node_name, e)
                self.scheduled_count += 1   # _on_bind_error decrements
                return
        else:
            self.dispatcher.add(APICall(CallType.BIND, assumed,
                                        node_name=node_name))
        self.scheduled_count += 1
        self.events.scheduled(pod.uid, node_name)
        self.journey.record(pod.uid, _EV_ASSIGN, self.clock(),
                            detail=node_name)
        from .metrics import SCHEDULED
        self.metrics.schedule_attempts.inc(
            SCHEDULED, pod.spec.scheduler_name)
        start = qpi.initial_attempt_timestamp or qpi.timestamp
        self.metrics.sli_duration.observe(
            max(self.clock() - start, 0.0), str(qpi.attempts or 1))
        qpi.unschedulable_plugins = set()
        qpi.consecutive_errors_count = 0

    def _run_pre_bind(self, profile: Profile, cs: CycleState,
                      qpi: QueuedPodInfo, assumed: Pod,
                      node_name: str) -> bool:
        """PreBind (schedule_one.go:327): VolumeBinding's API writes.
        Volume-free pods skip it when VolumeBinding is the only PreBind
        plugin. On failure: unreserve, release the assumed resources,
        requeue — returns False so the caller aborts the bind."""
        fwk = profile.framework
        pod = qpi.pod
        if not fwk.pre_bind_plugins or (
                profile.volume_only_pre_bind
                and not pod.spec.volumes
                and not pod.spec.resource_claims):
            return True
        status = fwk.run_pre_bind_plugins(cs, assumed, node_name)
        if status.is_success():
            return True
        fwk.run_reserve_plugins_unreserve(cs, assumed, node_name)
        try:
            self.cache.forget_pod(assumed)
        except (KeyError, ValueError):
            pass
        self._invalidate_device_state()
        self.error_count += 1
        self._handle_failure(qpi, FitError(pod, 0), try_preempt=False)
        return False

    def _on_bind_error(self, pod: Pod, node_name: str, err: Exception) -> None:
        """schedule_one.go:361-393: forget + requeue via the failure handler.

        The requeue MUST apply error backoff (consecutive_errors_count) — a
        straight activeQ re-add livelocks schedule_pending when the bind
        error is persistent (drain → bind fail → re-add → drain ...)."""
        self.scheduled_count -= 1
        self.error_count += 1
        klog.error("bind failed; forgetting assumed pod and requeueing",
                   pod=pod.uid, node=node_name, err=str(err))
        try:
            self.cache.forget_pod(pod)
        except (KeyError, ValueError):
            pass
        self._invalidate_device_state()
        fresh = pod.with_node_name("")
        errors = self._bind_errors.get(pod.uid, 0) + 1
        self._bind_errors[pod.uid] = errors
        # the fresh QueuedPodInfo must NOT restart the queue→bind e2e SLI
        # clock: the journey ledger holds the pod's first-enqueue time
        # across the unwind (None = never seen, falls back to timestamp)
        qpi = QueuedPodInfo(pod_info=PodInfo.of(fresh),
                            timestamp=self.clock(),
                            initial_attempt_timestamp=self.journey.e2e_start(
                                pod.uid),
                            consecutive_errors_count=errors)
        self._journey_requeue(
            [pod.uid],
            "fence_unwind" if isinstance(err, FencedWrite) else "bind_error",
            detail=str(err)[:120])
        self.queue.add_unschedulable_if_not_present(qpi)
        self.queue.move_all_to_active_or_backoff_queue(
            EVENT_ASSIGNED_POD_DELETE, pod, None)

    # -- failure path ---------------------------------------------------------

    def _handle_failure(self, qpi: QueuedPodInfo, err: FitError,
                        state: Optional[CycleState] = None,
                        try_preempt: bool = True,
                        requeue_cause: str = "") -> None:
        """schedule_one.go:1038 handleSchedulingFailure. A genuine
        scheduling FitError runs the PostFilter (preemption) path first —
        reserve/permit failures pass try_preempt=False, matching the
        reference where PostFilter only follows schedulePod failures
        (schedule_one.go:150-170). `requeue_cause` overrides the journey
        requeue cause (gang unwinds pass "gang_split"); otherwise the
        cause is "preemption" when this failure nominated a node, else
        "unschedulable"."""
        self.unschedulable_count += 1
        qpi.unschedulable_plugins = set(err.diagnosis.unschedulable_plugins)
        qpi.pending_plugins = set(err.diagnosis.pending_plugins)
        pod = qpi.pod
        nominated = pod.status.nominated_node_name
        preempted = False
        profile = self.profiles.get(pod.spec.scheduler_name)
        if (try_preempt and err.num_all_nodes > 0 and profile is not None
                and profile.framework.post_filter_plugins):
            if self._pending:
                # never compute victims on optimistic state that excludes
                # in-flight drains' assignments: an already-dispatched drain
                # may be about to fill the very nodes the Evaluator would
                # evict from (ADVICE r5 medium). Each nested commit pops
                # before it handles failures, so the recursion terminates.
                self._drain_pending()
            self.cache.update_snapshot(self.snapshot)
            result, status = profile.framework.run_post_filter_plugins(
                state or CycleState(), pod, err.diagnosis.node_to_status)
            if status.is_success() and result:
                nominated = result
                pod.status.nominated_node_name = nominated
                self.queue.nominator.add(qpi, nominated)
                self.preemption_attempts += 1
                preempted = True
                self.metrics.preemption_attempts.inc()
                klog.v(2).info("preemption nominated node", pod=pod.uid,
                               node=nominated)
        from .metrics import UNSCHEDULABLE
        self.metrics.schedule_attempts.inc(
            UNSCHEDULABLE, pod.spec.scheduler_name)
        self.metrics.queue_incoming_pods.inc("unschedulable",
                                             "ScheduleAttemptFailure")
        # FailedScheduling event with the reference-format message
        # ("0/N nodes are available: X Insufficient cpu, ...") + the
        # per-plugin rejected-node counts behind it
        from .events import EVENT_WARNING, REASON_FAILED_SCHEDULING
        msg = str(err)
        self.events.event(pod.uid, EVENT_WARNING, REASON_FAILED_SCHEDULING,
                          msg)
        for plugin, count in err.diagnosis.plugin_node_counts().items():
            self.metrics.unschedulable_nodes.observe(count, plugin)
        # journey: the FitError transition (detail = rejector plugins)
        # then the requeue with its cause — the pair /debug/pod renders
        # as "why it failed" + "why it's back in the queue"
        self.journey.record(
            pod.uid, _EV_FIT_ERROR, self.clock(),
            detail=",".join(sorted(qpi.unschedulable_plugins or ())))
        self._journey_requeue(
            [pod.uid],
            requeue_cause or ("preemption" if preempted
                              else "unschedulable"),
            detail=nominated or "")
        self.queue.add_unschedulable_if_not_present(qpi)
        self.dispatcher.add(APICall(
            CallType.STATUS_PATCH, qpi.pod,
            condition={"type": "PodScheduled", "status": "False",
                       "reason": "Unschedulable", "message": msg},
            nominated_node_name=nominated))

    # -- housekeeping ---------------------------------------------------------

    def flush_queues(self) -> None:
        """SchedulingQueue.Run periodic work (scheduling_queue.go:406-413)
        + the WaitOnPermit timeout sweep (waiting_pods_map.go timers)."""
        self._drain_pending()
        now = self.clock()
        for uid, rec in list(self._waiting_pods.items()):
            if rec.deadline <= now:
                self._reject_waiting(uid, "permit wait timeout")
        self.queue.flush_backoff_completed()
        self.queue.flush_unschedulable_leftover()

    def pending_summary(self) -> str:
        return self.queue.pending_pods()[1]
