"""Streaming drain pipeline: ingest, device dispatch and commit overlapped.

Every drain used to be a lock-step phase train — host_build, then device,
then commit — with the host idle while the device executed and the device
idle while the host committed. BENCH_r10 put commit at 65% of the
SchedulingBasic cycle, and ROADMAP item 2 names the fix: double-buffer the
three stages so drain N's device execution overlaps drain N+1's columnar
ingest / plan compile and drain N-1's commit tail. The `_PendingDrain`
queue (scheduler.py) already detaches the commit tail; this module extends
it into a bounded 3-stage pipeline under a sustained arrival process:

  arrival feed ──> [ingest worker] ──> [device (async)] ──> [commit worker]
     feed()         dispatch_once()      _PendingDrain         commit_ready()
                                                               dispatcher.flush()

* The INGEST worker closes the accumulating batch under an adaptive
  policy — device idle, batch full, or latency budget expired, whichever
  first — and runs `BatchBuilder` + `DrainCompiler` signature/plan work
  (`Scheduler.dispatch_once`) for the next drain while the device
  executes the current one.
* The DEVICE stage is JAX's own async dispatch: `dispatch_once` returns
  as soon as the programs are enqueued; `_PendingDrain.ready()` polls
  completion without blocking.
* The COMMIT worker detects landed drains off the hot path, commits them
  head-first (`Scheduler.commit_ready` — commit order IS dispatch order,
  preserving the carry/ledger/shadow-oracle bind-for-bind contract), and
  flushes the dispatcher's bulk bind-echo.

Backpressure is explicit and depth-capped in both directions: commit
backlog (un-echoed binds) caps dispatch, dispatch depth (in-flight
drains) caps ingest. Each stall increments
`scheduler_pipeline_backpressure_total{stage=<stalled stage>}` and each
stage's wall time accrues to
`scheduler_pipeline_stage_busy_seconds{stage}` — the occupancy block
served at /debug/pipeline (sum of busy seconds > wall == measured
overlap).

Threading contract: ONE lock (`self._lock`) serializes every touch of
the scheduler's host state (queue, cache, snapshot, dispatch, commit).
The overlap is host/device, not host/host — the GIL would serialize
host stages anyway; what the pipeline buys is the device never waiting
on commit tails and the host never spinning on device readbacks. Pod
creation MUST go through `feed()`: watch handlers run synchronously on
the caller thread and mutate the queue/snapshot.

CPython's generational GC is paused for the serving window
(utils/runtime.py `scheduling_gc_pause`) — the commit edge's ~4 small
allocations per pod otherwise trip young-gen scans of the scheduler's
long-lived graph mid-drain, measured at up to 45% of commit wall. The
commit worker runs `opportunistic_collect()` in device-idle windows
instead: GC scheduled like any other background work.
"""

from __future__ import annotations

import threading
import time
from contextlib import ExitStack
from typing import Optional

from .utils.logging import klog
from .utils.runtime import opportunistic_collect, scheduling_gc_pause

# pipeline stage names — the exact label set of the
# scheduler_pipeline_stage_busy_seconds / _backpressure_total families
# (exposition lint asserts these; tools/check.py pipeline_stages pins the
# stage threads to the measured_call/observatory entry discipline)
STAGES = ("ingest", "device", "commit")


class PipelineStopped(RuntimeError):
    """Raised by feed() after stop() or a worker fault."""


class StreamingPipeline:
    """A streaming drain loop over one Scheduler (module docstring)."""

    def __init__(self, sched, *,
                 dispatch_depth: int = 3,
                 commit_backlog_pods: int = 16384,
                 latency_budget_s: float = 0.005,
                 close_min_pods: int = 1,
                 poll_s: float = 0.0002,
                 gc_pause: bool = True):
        if not sched.feature_gates.enabled("StreamingDrainPipeline"):
            raise RuntimeError(
                "StreamingDrainPipeline feature gate is disabled; use the "
                "lock-step schedule_pending() loop")
        self.sched = sched
        # commit backlog depth caps dispatch; dispatch depth caps ingest
        self.dispatch_depth = max(1, int(dispatch_depth))
        self.commit_backlog_pods = int(commit_backlog_pods)
        self.latency_budget_s = float(latency_budget_s)
        self.close_min_pods = max(1, int(close_min_pods))
        self.poll_s = float(poll_s)
        self.gc_pause = gc_pause
        self._lock = threading.RLock()
        self._work = threading.Condition(self._lock)
        self._stop = False
        self._started = False
        self.errors: list[tuple[str, BaseException]] = []
        # per-stage busy walls (each key written by exactly one thread)
        self._busy = {s: 0.0 for s in STAGES}
        self._backpressure = {s: 0 for s in STAGES}
        # measured wall actually spent in backpressure waits, per stalled
        # stage (ISSUE 20): each `_backpressure` increment brackets one
        # bounded `_work.wait`, so stall seconds <= count * poll_s * 10;
        # the critical-path extractor attributes the delta across a
        # drain's dispatch->commit window to its `backpressure` cause
        self._stall_s = {s: 0.0 for s in STAGES}
        self._close_reasons = {"full": 0, "idle": 0, "budget": 0,
                               "feed": 0}
        self._batches = 0
        self._commits = 0
        self._started_at = 0.0
        self._stopped_at: Optional[float] = None
        self._oldest_arrival: Optional[float] = None
        # last forward progress (a dispatched batch or a committed
        # drain): the incident watchdog's pipeline_stall signal reads
        # the age of this stamp while work is queued
        self._last_progress = 0.0
        # device-busy accounting: non-overlapping [dispatched, ready)
        # windows (the device executes drains serially)
        self._last_ready = 0.0
        self._threads: list[threading.Thread] = []
        self._stack = ExitStack()

    # -- lifecycle -------------------------------------------------------------

    def start(self) -> "StreamingPipeline":
        if self._started:
            return self
        self._started = True
        self._started_at = time.perf_counter()
        self._last_ready = self._started_at
        self._last_progress = self._started_at
        if self.gc_pause:
            self._stack.enter_context(scheduling_gc_pause())
        self.sched.pipeline = self
        # critical-path attribution baseline (scheduler.py): stall
        # seconds are attributed drain-by-drain as deltas against the
        # last committed checkpoint; a fresh pipeline starts the clock
        self.sched._bp_stall_committed = 0.0
        for name, target in (("pipeline-ingest", self._ingest_loop),
                             ("pipeline-commit", self._commit_loop)):
            t = threading.Thread(target=target, name=name, daemon=True)
            t.start()
            self._threads.append(t)
        return self

    def stop(self) -> None:
        """Signal the workers, join them, restore the GC. Does NOT drain:
        call `drain()` first for a clean quiescent shutdown."""
        with self._work:
            self._stop = True
            self._work.notify_all()
        for t in self._threads:
            t.join(timeout=30.0)
        self._threads.clear()
        self._stopped_at = time.perf_counter()
        self.sched.pipeline = self   # keep last stats reachable at /debug
        self.publish_metrics()
        self._stack.close()

    def _check(self) -> None:
        if self.errors:
            raise self.errors[0][1]
        if self._stop:
            raise PipelineStopped("pipeline stopped")

    # -- arrival feed (ingest stage, caller side) ------------------------------

    def feed(self, pods: list, close: bool = False) -> None:
        """Admit an arrival chunk: create the pods (watch handlers enqueue
        them under the pipeline lock) and wake the ingest worker. With
        `close=True` the batch closes and dispatches inline on the caller
        thread — deterministic batch boundaries for the parity suites
        (still committed asynchronously by the commit worker)."""
        self._check()
        with self._work:
            t0 = time.perf_counter()
            self.sched.client.create_pods(pods)
            if self._oldest_arrival is None:
                self._oldest_arrival = t0
            self._busy["ingest"] += time.perf_counter() - t0
            if close:
                self._dispatch_locked("feed")
            else:
                self._work.notify_all()

    def feed_workload(self, workload) -> None:
        """Admit a Workload object (gang quorum source) ahead of its
        member pods — the trace-replay opcode's workload events."""
        self._check()
        with self._lock:
            self.sched.client.create_workload(workload)

    # -- ingest worker: adaptive batch close + dispatch ------------------------

    def _ingest_loop(self) -> None:
        try:
            while True:
                with self._work:
                    if self._stop:
                        return
                    sched = self.sched
                    sched.queue.flush_backoff_completed()
                    qlen = len(sched.queue.active_q)
                    reason = self._close_reason(qlen)
                    if reason is None:
                        # nothing to close yet: wake on feed/commit or at
                        # the latency-budget horizon, whichever first
                        self._work.wait(timeout=self._wait_horizon(qlen))
                        continue
                    self._dispatch_locked(reason)
        except BaseException as e:   # noqa: BLE001 — surfaced via errors
            self.errors.append(("ingest", e))
            klog.error("pipeline ingest worker died", error=repr(e))

    def _close_reason(self, qlen: int) -> Optional[str]:
        """Adaptive batch-close policy: full batch, idle device, or an
        expired latency budget — whichever first (None = keep
        accumulating)."""
        if qlen < self.close_min_pods:
            return None
        sched = self.sched
        if qlen >= sched.batch_size:
            return "full"
        if not sched._pending:
            return "idle"
        if (self._oldest_arrival is not None
                and time.perf_counter() - self._oldest_arrival
                >= self.latency_budget_s):
            return "budget"
        return None

    def _wait_horizon(self, qlen: int) -> float:
        if qlen and self._oldest_arrival is not None:
            due = (self._oldest_arrival + self.latency_budget_s
                   - time.perf_counter())
            return max(min(due, self.latency_budget_s), self.poll_s)
        return self.latency_budget_s or 0.05

    def _dispatch_locked(self, reason: str) -> None:
        """Dispatch one closed batch, honoring both depth caps. Caller
        holds the lock; waits (releasing it) while a cap blocks."""
        sched = self.sched
        while not self._stop:
            if len(sched._pending) >= self.dispatch_depth:
                # dispatch depth caps ingest
                self._backpressure["ingest"] += 1
                t0 = time.perf_counter()
                self._work.wait(timeout=self.poll_s * 10)
                self._stall_s["ingest"] += time.perf_counter() - t0
                continue
            if len(sched.dispatcher) >= self.commit_backlog_pods:
                # commit backlog caps dispatch
                self._backpressure["device"] += 1
                t0 = time.perf_counter()
                self._work.wait(timeout=self.poll_s * 10)
                self._stall_s["device"] += time.perf_counter() - t0
                continue
            break
        if self._stop:
            return
        t0 = time.perf_counter()
        took = sched.dispatch_once()
        self._busy["ingest"] += time.perf_counter() - t0
        if took:
            self._batches += 1
            self._last_progress = time.perf_counter()
            self._close_reasons[reason] = (
                self._close_reasons.get(reason, 0) + 1)
        self._oldest_arrival = (
            None if not len(sched.queue.active_q) else time.perf_counter())
        self._work.notify_all()

    # -- commit worker: off-critical-path commit + bind-echo flush -------------

    def _commit_loop(self) -> None:
        sched = self.sched
        idle_streak = 0
        try:
            while not self._stop:
                try:
                    head = sched._pending[0]
                except IndexError:
                    head = None
                if head is None:
                    idle_streak += 1
                    if len(sched.dispatcher):
                        with self._lock:
                            t0 = time.perf_counter()
                            sched.dispatcher.flush()
                            self._busy["commit"] += (
                                time.perf_counter() - t0)
                        self._work_notify()
                    elif self.gc_pause and idle_streak == 50:
                        # device-idle window: run the young-gen collection
                        # the paused automatic collector isn't doing
                        opportunistic_collect()
                    time.sleep(self.poll_s)
                    continue
                if not head.ready():
                    # device still executing: the commit stage stalls on
                    # the device, not the other way around
                    idle_streak = 0
                    time.sleep(self.poll_s)
                    continue
                idle_streak = 0
                t_ready = time.perf_counter()
                # serial-device busy accounting: non-overlapping windows
                dt = t_ready - max(head.dispatched_at, self._last_ready)
                if dt > 0:
                    self._busy["device"] += dt
                self._last_ready = t_ready
                if not self._lock.acquire(blocking=False):
                    # ingest holds the host: commit is the stalled stage
                    self._backpressure["commit"] += 1
                    t0 = time.perf_counter()
                    self._lock.acquire()
                    self._stall_s["commit"] += time.perf_counter() - t0
                try:
                    t0 = time.perf_counter()
                    if sched._pending and sched._pending[0] is head:
                        # commit every landed drain in one lock hold
                        # (head-first: commit order IS dispatch order)
                        self._commits += sched.commit_ready()
                        self._last_progress = time.perf_counter()
                    sched.dispatcher.flush()
                    self._busy["commit"] += time.perf_counter() - t0
                finally:
                    self._lock.release()
                self.publish_metrics()
                self._work_notify()
        except BaseException as e:   # noqa: BLE001 — surfaced via errors
            self.errors.append(("commit", e))
            klog.error("pipeline commit worker died", error=repr(e))

    def _work_notify(self) -> None:
        with self._work:
            self._work.notify_all()

    # -- quiescence ------------------------------------------------------------

    def drain(self, timeout: float = 120.0) -> None:
        """Block until the pipeline is quiescent: active queue empty,
        no in-flight drains, dispatcher flushed. Raises the first worker
        fault, if any (the chaos suites catch it here)."""
        deadline = time.monotonic() + timeout
        sched = self.sched
        while True:
            if self.errors:
                raise self.errors[0][1]
            with self._work:
                sched.queue.flush_backoff_completed()
                quiescent = (not len(sched.queue.active_q)
                             and not sched._pending
                             and not len(sched.dispatcher))
                self._work.notify_all()
            if quiescent:
                return
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"pipeline not quiescent after {timeout}s: "
                    f"queue={len(sched.queue.active_q)} "
                    f"pending={len(sched._pending)} "
                    f"dispatcher={len(sched.dispatcher)}")
            time.sleep(self.poll_s * 5)

    # -- observability ---------------------------------------------------------

    def publish_metrics(self) -> None:
        """Mirror the pipeline's per-stage counters into the
        scheduler_pipeline_* families — absolute assignment (the pipeline
        owns the monotonic totals, same contract as the ledger sync)."""
        m = self.sched.metrics
        for stage in STAGES:
            m.pipeline_stage_busy._values[(stage,)] = self._busy[stage]
            m.pipeline_backpressure._values[(stage,)] = float(
                self._backpressure[stage])

    def backpressure_stall_seconds(self) -> float:
        """Total measured wall spent in backpressure waits across all
        stages — monotonic while the pipeline runs. The scheduler diffs
        this across each drain's commit to attribute stall seconds to
        the drain's `backpressure` critical-path cause."""
        return sum(self._stall_s.values())

    def stall_seconds(self) -> float:
        """Age of the last forward progress (dispatched batch or
        committed drain) while work is queued; 0.0 when the pipeline is
        idle-empty, stopped, or progressing. The incident watchdog trips
        its pipeline_stall trigger when this exceeds the stall budget."""
        if not self._started or self._stop:
            return 0.0
        sched = self.sched
        if (not len(sched.queue.active_q) and not sched._pending
                and not len(sched.dispatcher)):
            return 0.0
        return max(time.perf_counter() - self._last_progress, 0.0)

    def stats(self) -> dict:
        """The /debug/pipeline occupancy block."""
        # stage-share math is shared with bench.py's phase_pct/host_share
        # summary (perf/critical_path.py phase_shares — the ISSUE 20
        # bugfix: both surfaces must agree on the same window)
        from .perf.critical_path import phase_shares
        self.publish_metrics()
        wall = ((self._stopped_at or time.perf_counter())
                - self._started_at) if self._started_at else 0.0
        shares = phase_shares(self._busy, wall=wall)
        busy_sum = shares["total"]
        return {
            "running": self._started and not self._stop,
            "wallSeconds": round(wall, 6),
            "busySeconds": {s: round(v, 6) for s, v in self._busy.items()},
            "busySum": round(busy_sum, 6),
            "busyShares": shares["shares"],
            # >1.0 == measured stage overlap (the acceptance gate reads
            # this: sum of per-stage busy seconds vs wall)
            "occupancy": shares["occupancy"] if wall > 0 else 0.0,
            "backpressure": dict(self._backpressure),
            "backpressureStallSeconds": {
                s: round(v, 6) for s, v in self._stall_s.items()},
            "stallSeconds": round(self.stall_seconds(), 6),
            "batchClose": dict(self._close_reasons),
            "batches": self._batches,
            "commits": self._commits,
            "depths": {
                "queue": len(self.sched.queue.active_q),
                "dispatch": len(self.sched._pending),
                "commitBacklog": len(self.sched.dispatcher),
            },
            "caps": {
                "dispatchDepth": self.dispatch_depth,
                "commitBacklogPods": self.commit_backlog_pods,
                "latencyBudgetMs": self.latency_budget_s * 1e3,
            },
            "errors": [f"{stage}: {exc!r}" for stage, exc in self.errors],
        }
