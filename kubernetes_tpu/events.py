"""Decision provenance: event recorder + per-drain flight recorder.

Mirrors the event surface of the reference scheduler —
`record.EventBroadcaster` (client-go tools/record, events.go) feeding
`Scheduled` / `FailedScheduling` events through an aggregating sink — in
this framework's in-process model:

- `EventRecorder` is a ring-buffered, queryable sink. Events aggregate by
  (object, type, reason, message) exactly like the reference
  EventAggregator's correlator key, so a pod failing the same way across
  retries holds ONE entry with a rising `count` instead of flooding the
  ring. `Scheduled` events take a dedicated cheap path (the per-bind hot
  loop must not pay message formatting; the message renders at dump time).
- `FlightRecorder` keeps the last K drains' worth of "what did the
  scheduler just do": batch size, signature count, per-phase wall times,
  run kinds, wave conflict stats, fallback/circuit-breaker state and event
  counts — the post-mortem the reference reconstructs from attempt
  histograms plus trace sampling, kept resident here because the batched
  device path makes the DRAIN (not the pod) the unit worth replaying.

Both are served by the SchedulerServer's /debug endpoints.
"""

from __future__ import annotations

import itertools
import threading
import time as _time
from collections import OrderedDict, deque
from dataclasses import dataclass, field
from typing import Callable, Optional

EVENT_NORMAL = "Normal"
EVENT_WARNING = "Warning"

REASON_SCHEDULED = "Scheduled"
REASON_FAILED_SCHEDULING = "FailedScheduling"
REASON_PREEMPTED = "Preempted"


@dataclass(slots=True)
class Event:
    """One aggregated event (events.go Event, consumed subset)."""

    object_ref: str           # "namespace/name" of the involved pod
    type: str                 # Normal | Warning
    reason: str               # Scheduled | FailedScheduling | ...
    message: str
    count: int = 1
    first_timestamp: float = 0.0
    last_timestamp: float = 0.0
    # drain that emitted the LAST occurrence (0 = outside a drain commit):
    # correlates the event with log lines, spans and the flight entry
    drain_id: int = 0

    def to_dict(self) -> dict:
        return {"object": self.object_ref, "type": self.type,
                "reason": self.reason, "message": self.message,
                "count": self.count,
                "firstTimestamp": round(self.first_timestamp, 6),
                "lastTimestamp": round(self.last_timestamp, 6),
                "drainId": self.drain_id}


class EventRecorder:
    """Aggregating ring of scheduling events (EventBroadcaster analog).

    `capacity` bounds distinct aggregation keys; the oldest key is evicted
    on overflow (the reference relies on apiserver TTL instead). `metrics`
    is a SchedulerMetrics — every recorded event increments
    scheduler_events_total{type,reason} (including aggregated repeats,
    matching the reference where each Eventf call counts)."""

    def __init__(self, capacity: int = 4096,
                 clock: Callable[[], float] = _time.monotonic,
                 metrics=None):
        self.capacity = capacity
        self.clock = clock
        self.metrics = metrics
        # the recorder is written by the scheduling thread and read by
        # the debug HTTP thread (/debug/events): one lock covers the
        # ring, the fast-path deque and the counters
        self._lock = threading.Lock()
        self._events: "OrderedDict[tuple, Event]" = OrderedDict()  # guarded_by: _lock
        # Scheduled fast path: (object_ref, node_name, timestamp, drain)
        # tuples; message formatting deferred to query time
        self._scheduled: deque = deque(maxlen=capacity)  # guarded_by: _lock
        self.counts: dict[tuple[str, str], int] = {}     # guarded_by: _lock
        # the drain whose commit is currently emitting (scheduler-set;
        # only the scheduling thread reads or writes it)
        self.current_drain = 0

    # -- recording ------------------------------------------------------------

    def event(self, object_ref: str, type_: str, reason: str,
              message: str) -> None:
        """Record one event, aggregating with prior identical ones."""
        now = self.clock()
        key = (object_ref, type_, reason, message)
        with self._lock:
            ev = self._events.get(key)
            if ev is not None:
                ev.count += 1
                ev.last_timestamp = now
                ev.drain_id = self.current_drain
                self._events.move_to_end(key)
            else:
                self._events[key] = Event(object_ref=object_ref, type=type_,
                                          reason=reason, message=message,
                                          first_timestamp=now,
                                          last_timestamp=now,
                                          drain_id=self.current_drain)
                while len(self._events) > self.capacity:
                    self._events.popitem(last=False)
            self._count(type_, reason)

    def scheduled(self, object_ref: str, node_name: str) -> None:
        """Cheap Scheduled event (per-bind hot path): no string formatting,
        one deque append + one counter bump."""
        with self._lock:
            self._scheduled.append((object_ref, node_name, self.clock(),
                                    self.current_drain))
            self._count(EVENT_NORMAL, REASON_SCHEDULED)

    def scheduled_bulk(self, refs_nodes: list, now: Optional[float] = None
                       ) -> None:
        """Batched Scheduled events for a committed drain ([(ref, node)])."""
        if not refs_nodes:
            return
        t = self.clock() if now is None else now
        did = self.current_drain
        with self._lock:
            self._scheduled.extend((ref, node, t, did)
                                   for ref, node in refs_nodes)
            self._count(EVENT_NORMAL, REASON_SCHEDULED, by=len(refs_nodes))

    def _count(self, type_: str, reason: str, by: int = 1) -> None:  # jaxsan: holds _lock
        key = (type_, reason)
        self.counts[key] = self.counts.get(key, 0) + by
        if self.metrics is not None:
            self.metrics.events_total.inc(type_, reason, by=by)

    # -- querying -------------------------------------------------------------

    @staticmethod
    def scheduled_message(object_ref: str, node_name: str) -> str:
        # reference schedule_one.go: "Successfully assigned <ns>/<name> to
        # <node>"
        return f"Successfully assigned {object_ref} to {node_name}"

    def events(self, reason: Optional[str] = None,
               object_ref: Optional[str] = None,
               limit: int = 0) -> list[Event]:
        """Newest-last event list, optionally filtered; Scheduled fast-path
        entries are materialized into full Events here."""
        out: list[Event] = []
        with self._lock:
            scheduled = list(self._scheduled)
            ring = list(self._events.values())
        if reason in (None, REASON_SCHEDULED):
            for ref, node, t, did in scheduled:
                if object_ref is not None and ref != object_ref:
                    continue
                out.append(Event(object_ref=ref, type=EVENT_NORMAL,
                                 reason=REASON_SCHEDULED,
                                 message=self.scheduled_message(ref, node),
                                 first_timestamp=t, last_timestamp=t,
                                 drain_id=did))
        for ev in ring:
            if reason is not None and ev.reason != reason:
                continue
            if object_ref is not None and ev.object_ref != object_ref:
                continue
            out.append(ev)
        out.sort(key=lambda e: e.last_timestamp)
        if limit and len(out) > limit:
            out = out[-limit:]
        return out

    def dump(self, reason: Optional[str] = None, limit: int = 0) -> dict:
        with self._lock:
            counts = {f"{t}/{r}": c
                      for (t, r), c in sorted(self.counts.items())}
        return {"counts": counts,
                "events": [e.to_dict()
                           for e in self.events(reason=reason, limit=limit)]}


# ---------------------------------------------------------------------------
# flight recorder


@dataclass(slots=True)
class FlightRecord:
    """One drain's telemetry (fixed-size row of the flight ring)."""

    seq: int
    wall_time: float          # time.time() at commit (human correlation)
    profile: str
    pods: int                 # drain size
    bound: int
    failed: int
    signatures: int           # distinct signature rows in the drain
    kinds: tuple              # run kinds ("uniform"/"scan"/"wave"/...)
    groups: bool
    phases: dict              # phase name → seconds
    wave: dict = field(default_factory=dict)   # waves/conflicts/prefix
    breaker_open: bool = False
    consecutive_faults: int = 0
    fallback: str = ""        # "" = device path; else degradation reason
    events: dict = field(default_factory=dict)  # reason → count this drain
    drain_id: int = 0         # the scheduler's monotonic drain id
    # hottest host frames over the drain's wall window, attached only to
    # SLOW drains by the continuous profiler ("frame self/total" strings)
    hot_frames: tuple = ()
    # shadow-oracle audit verdict + full diffs (obs/audit.py), attached
    # by the audit worker AFTER the replay lands ({} = unsampled).
    # Single reference assignment by the worker; readers snapshot it.
    audit: dict = field(default_factory=dict)
    # resolved cluster_probe snapshot for this drain (scheduler
    # _resolve_probe): utilization percentiles / fragmentation / domain
    # imbalance over the post-drain carry. {} = probe off or dropped.
    probe: dict = field(default_factory=dict)
    # per-kernel dispatch seconds inside this drain's device span
    # (perf/observatory.py device lane); {} = KernelObservatory off or
    # host-path drain. Sums to ≤ phases["device_dispatch"] — the named
    # decomposition of the device phase wall.
    kernels: dict = field(default_factory=dict)
    # shard ids the committing instance owned at commit time (sharded
    # control plane, ha/shards.py); () = unsharded operation
    shard: tuple = ()
    # critical-path verdict for this drain (perf/critical_path.py,
    # `CriticalPathObservatory` gate): {"verdict": cause, "causes":
    # {cause: seconds}, "chain": [...]}; {} = gate off or host-path
    # commit predating the stamp
    critical_path: dict = field(default_factory=dict)

    def total_seconds(self) -> float:
        return float(sum(self.phases.values()))

    def to_dict(self) -> dict:
        return {"seq": self.seq, "wallTime": round(self.wall_time, 6),
                "profile": self.profile, "pods": self.pods,
                "bound": self.bound, "failed": self.failed,
                "signatures": self.signatures,
                "kinds": list(self.kinds), "groups": self.groups,
                "phases": {k: round(v, 6) for k, v in self.phases.items()},
                "wave": self.wave, "breakerOpen": self.breaker_open,
                "consecutiveFaults": self.consecutive_faults,
                "fallback": self.fallback, "events": self.events,
                "drainId": self.drain_id,
                "hotFrames": list(self.hot_frames),
                "audit": dict(self.audit),
                "probe": dict(self.probe),
                "kernels": {k: round(v, 6)
                            for k, v in self.kernels.items()},
                "shard": list(self.shard),
                "criticalPath": dict(self.critical_path)}


class FlightRecorder:
    """Fixed-size ring of per-drain FlightRecords.

    Written by the scheduling thread at commit time, read by the debug
    HTTP thread (/debug/flightrecorder, /debug/slowcycles)."""

    def __init__(self, capacity: int = 256):
        self._lock = threading.Lock()
        self.ring: deque = deque(maxlen=capacity)  # guarded_by: _lock
        self._seq = itertools.count(1)

    def record(self, **kw) -> FlightRecord:
        rec = FlightRecord(seq=next(self._seq), wall_time=_time.time(), **kw)
        with self._lock:
            self.ring.append(rec)
        return rec

    def dump(self, limit: int = 0) -> list[dict]:
        with self._lock:
            records = list(self.ring)
        if limit and len(records) > limit:
            records = records[-limit:]
        return [r.to_dict() for r in records]

    def slowest(self, n: int = 16) -> list[dict]:
        """The n slowest recorded drains by total phase time."""
        with self._lock:
            records = list(self.ring)
        return [r.to_dict()
                for r in sorted(records, key=FlightRecord.total_seconds,
                                reverse=True)[:n]]
