"""Scheduler metrics: labeled counters/gauges/histograms + text exposition.

Mirrors pkg/scheduler/metrics/metrics.go (:196-460) in spirit and naming —
the ~dozen series the reference dashboards actually read — on a minimal
Prometheus-style registry (component-base/metrics stand-in):

  scheduler_schedule_attempts_total{result,profile}
  scheduler_scheduling_attempt_duration_seconds{result,profile}
  scheduler_pod_scheduling_sli_duration_seconds{attempts}
  scheduler_pending_pods{queue}
  scheduler_preemption_attempts_total / scheduler_preemption_victims
  scheduler_queue_incoming_pods_total{event,queue}
  scheduler_permit_wait_duration_seconds{result}
  scheduler_device_batch_size / scheduler_device_batch_duration_seconds
  scheduler_api_dispatcher_calls_total{call_type,result}

The TPU-specific device_* series replace the reference's goroutines/
plugin-execution timers: on this architecture the device batch IS the
execution unit worth observing.

The reference offloads observations via MetricAsyncRecorder
(metric_recorder.go) so the hot path never touches Prometheus locks; the
single-threaded host loop has no lock contention, so observations are
direct writes into plain dicts (cheaper than the reference's channel hop)
and `Registry.exposition()` renders on scrape.
"""

from __future__ import annotations

import bisect
from dataclasses import dataclass, field
from typing import Callable, Optional

# reference metrics.go:73 SchedulerSubsystem
SUBSYSTEM = "scheduler"


def _escape_label_value(v: str) -> str:
    """Prometheus text-format label-value escaping (exposition format spec):
    backslash, double-quote and line-feed must be escaped — raw values
    break every scrape parser on the first quote or newline."""
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _escape_help(h: str) -> str:
    """HELP text escaping: backslash and line-feed only (quotes are legal
    in HELP per the text-format spec)."""
    return h.replace("\\", "\\\\").replace("\n", "\\n")


def _fmt_labels(names: tuple[str, ...], values: tuple[str, ...]) -> str:
    if not names:
        return ""
    inner = ",".join(f'{n}="{_escape_label_value(v)}"'
                     for n, v in zip(names, values))
    return "{" + inner + "}"


@dataclass
class Counter:
    name: str
    help: str
    label_names: tuple[str, ...] = ()
    _values: dict[tuple[str, ...], float] = field(default_factory=dict)

    def inc(self, *labels: str, by: float = 1.0) -> None:
        key = tuple(labels)
        self._values[key] = self._values.get(key, 0.0) + by

    def value(self, *labels: str) -> float:
        return self._values.get(tuple(labels), 0.0)

    def expose(self) -> list[str]:
        out = [f"# HELP {self.name} {_escape_help(self.help)}",
               f"# TYPE {self.name} counter"]
        for key, v in sorted(self._values.items()):
            out.append(f"{self.name}{_fmt_labels(self.label_names, key)} {v:g}")
        return out


@dataclass
class Gauge:
    name: str
    help: str
    label_names: tuple[str, ...] = ()
    # a callback gauge computes its value at scrape time (queue depths)
    callback: Optional[Callable[[], dict[tuple[str, ...], float]]] = None
    _values: dict[tuple[str, ...], float] = field(default_factory=dict)

    def set(self, value: float, *labels: str) -> None:
        self._values[tuple(labels)] = value

    def value(self, *labels: str) -> float:
        if self.callback is not None:
            return self.callback().get(tuple(labels), 0.0)
        return self._values.get(tuple(labels), 0.0)

    def expose(self) -> list[str]:
        values = self.callback() if self.callback is not None else self._values
        out = [f"# HELP {self.name} {_escape_help(self.help)}",
               f"# TYPE {self.name} gauge"]
        for key, v in sorted(values.items()):
            out.append(f"{self.name}{_fmt_labels(self.label_names, key)} {v:g}")
        return out


# metrics.go attempt-duration buckets: exponential 0.001 * 2^i
def exponential_buckets(start: float, factor: float, count: int) -> list[float]:
    return [start * factor ** i for i in range(count)]


@dataclass
class Histogram:
    name: str
    help: str
    buckets: list[float] = field(
        default_factory=lambda: exponential_buckets(0.001, 2, 15))
    label_names: tuple[str, ...] = ()
    _counts: dict[tuple[str, ...], list[int]] = field(default_factory=dict)
    _sums: dict[tuple[str, ...], float] = field(default_factory=dict)
    _totals: dict[tuple[str, ...], int] = field(default_factory=dict)

    def observe(self, value: float, *labels: str) -> None:
        key = tuple(labels)
        counts = self._counts.get(key)
        if counts is None:
            counts = self._counts[key] = [0] * (len(self.buckets) + 1)
        counts[bisect.bisect_left(self.buckets, value)] += 1
        self._sums[key] = self._sums.get(key, 0.0) + value
        self._totals[key] = self._totals.get(key, 0) + 1

    def observe_array(self, values, *labels: str) -> None:
        """Vectorized observe (metric_recorder.go's batched flush analog):
        one numpy bucket-count pass for a whole drain's worth of samples
        instead of a Python observe() per pod."""
        import numpy as np
        v = np.asarray(values, float)
        if v.size == 0:
            return
        key = tuple(labels)
        counts = self._counts.get(key)
        if counts is None:
            counts = self._counts[key] = [0] * (len(self.buckets) + 1)
        idx = np.searchsorted(self.buckets, v, side="left")
        for b, c in zip(*np.unique(idx, return_counts=True)):
            counts[int(b)] += int(c)
        self._sums[key] = self._sums.get(key, 0.0) + float(v.sum())
        self._totals[key] = self._totals.get(key, 0) + int(v.size)

    def seed(self, *labels: str) -> None:
        """Materialize a zero-count series so exposition always carries it
        (dashboards and bench_metrics.prom see the series before the first
        observation)."""
        key = tuple(labels)
        if key not in self._counts:
            self._counts[key] = [0] * (len(self.buckets) + 1)
            self._sums[key] = 0.0
            self._totals[key] = 0

    def count(self, *labels: str) -> int:
        return self._totals.get(tuple(labels), 0)

    def sum(self, *labels: str) -> float:
        return self._sums.get(tuple(labels), 0.0)

    def merged_counts(self) -> list[int]:
        """All label sets' bucket counts merged — a checkpoint for
        quantile(since=): the streaming bench reports per-tier e2e
        quantiles as deltas so the warmup phase can't pollute them."""
        merged = [0] * (len(self.buckets) + 1)
        for counts in self._counts.values():
            for i, c in enumerate(counts):
                merged[i] += c
        return merged

    def quantile(self, q: float, since: Optional[list[int]] = None) -> float:
        """histogram_quantile over ALL label sets merged (bench reporting):
        the value of the bucket upper edge holding the q-th observation,
        linearly interpolated inside the bucket like PromQL. Returns 0.0
        with no observations; the top bucket clamps to its lower edge.
        `since` (a merged_counts() checkpoint) restricts the quantile to
        observations made after the checkpoint."""
        merged = self.merged_counts()
        if since is not None:
            merged = [m - s for m, s in zip(merged, since)]
        total = sum(merged)
        if total == 0:
            return 0.0
        rank = q * total
        cum = 0.0
        for i, c in enumerate(merged):
            prev = cum
            cum += c
            if cum >= rank and c > 0:
                lo = self.buckets[i - 1] if i > 0 else 0.0
                hi = self.buckets[i] if i < len(self.buckets) else lo
                return lo + (hi - lo) * ((rank - prev) / c)
        return self.buckets[-1]

    def expose(self) -> list[str]:
        out = [f"# HELP {self.name} {_escape_help(self.help)}",
               f"# TYPE {self.name} histogram"]
        for key, counts in sorted(self._counts.items()):
            cumulative = 0
            names = self.label_names + ("le",)
            for le, c in zip(self.buckets, counts):
                cumulative += c
                out.append(
                    f"{self.name}_bucket"
                    f"{_fmt_labels(names, key + (f'{le:g}',))} {cumulative}")
            out.append(f"{self.name}_bucket"
                       f"{_fmt_labels(names, key + ('+Inf',))} "
                       f"{self._totals[key]}")
            out.append(f"{self.name}_sum{_fmt_labels(self.label_names, key)} "
                       f"{self._sums[key]:g}")
            out.append(f"{self.name}_count{_fmt_labels(self.label_names, key)} "
                       f"{self._totals[key]}")
        return out


class Registry:
    """component-base metrics registry stand-in + /metrics exposition."""

    def __init__(self) -> None:
        self._metrics: dict[str, object] = {}

    def register(self, metric):
        if metric.name in self._metrics:
            raise ValueError(f"metric {metric.name} already registered")
        self._metrics[metric.name] = metric
        return metric

    def get(self, name: str):
        return self._metrics.get(name)

    def exposition(self) -> str:
        lines: list[str] = []
        for m in self._metrics.values():
            lines.extend(m.expose())
        return "\n".join(lines) + "\n"


# scheduled result labels (metrics.go:76-86)
SCHEDULED = "scheduled"
UNSCHEDULABLE = "unschedulable"
ERROR = "error"

DEFAULT_PROFILE = "default-scheduler"

# the device-modeled plugin set, used to pre-seed per-plugin series (the
# kernel-backed filters/scorers every device batch evaluates)
DEVICE_FILTER_PLUGINS = (
    "NodeUnschedulable", "NodeName", "TaintToleration", "NodeAffinity",
    "NodePorts", "NodeResourcesFit", "PodTopologySpread", "InterPodAffinity")
DEVICE_SCORE_PLUGINS = (
    "TaintToleration", "NodeAffinity", "NodeResourcesFit",
    "NodeResourcesBalancedAllocation", "PodTopologySpread",
    "InterPodAffinity", "ImageLocality")

# cluster_probe gauge label sets (ops/program.py PROBE_STATS columns
# split across the two per-resource families + the domain family); the
# exposition lint asserts these exact sets
CLUSTER_UTIL_STATS = ("p50", "p90", "p99", "max", "mean")
CLUSTER_FRAG_KINDS = ("fragmentation", "stranded")
CLUSTER_DOM_STATS = ("domains", "max", "min", "spread")
# resources every cluster exposes — pre-seeded so the series exist
# before the first probe; the live probe adds the rtable's real set
CLUSTER_SEED_RESOURCES = ("cpu", "memory")

# shard-lane gauge labels pre-seeded at construction (one TPU host's
# worth of lanes); a live profile on a wider mesh adds its real set
SHARD_SEED_LANES = tuple(str(i) for i in range(8))

# sharded control plane (ha/shards.py, ISSUE 17): pre-seeded label sets
# so dashboards see the series before the first split/steal/conflict
SHARD_SEED_IDS = tuple(str(i) for i in range(4))
SHARD_STEAL_REASONS = ("split", "merge", "steal", "rebalance")
CROSS_SHARD_OUTCOMES = ("conflict", "fenced")


class SchedulerMetrics:
    """The scheduler's series, bound to one Registry (metrics.go Register)."""

    def __init__(self, registry: Optional[Registry] = None,
                 queue_depths: Optional[Callable[[], dict]] = None,
                 inflight: Optional[Callable[[], dict]] = None):
        r = self.registry = registry or Registry()
        n = f"{SUBSYSTEM}_"
        self.schedule_attempts = r.register(Counter(
            n + "schedule_attempts_total",
            "Number of attempts to schedule pods, by result and profile.",
            ("result", "profile")))
        self.attempt_duration = r.register(Histogram(
            n + "scheduling_attempt_duration_seconds",
            "Scheduling attempt latency (scheduling algorithm + binding).",
            label_names=("result", "profile")))
        self.sli_duration = r.register(Histogram(
            n + "pod_scheduling_sli_duration_seconds",
            "E2e latency from first queue add to binding, by attempt count.",
            buckets=exponential_buckets(0.01, 2, 20),
            label_names=("attempts",)))
        self.pending_pods = r.register(Gauge(
            n + "pending_pods",
            "Pending pods by queue (active/backoff/unschedulable/gated).",
            ("queue",), callback=queue_depths))
        self.preemption_attempts = r.register(Counter(
            n + "preemption_attempts_total",
            "Total preemption attempts in the cluster."))
        self.preemption_victims = r.register(Histogram(
            n + "preemption_victims",
            "Number of selected preemption victims.",
            buckets=[1, 2, 4, 8, 16, 32, 64]))
        self.queue_incoming_pods = r.register(Counter(
            n + "queue_incoming_pods_total",
            "Pods added to scheduling queues by event and queue.",
            ("queue", "event")))
        self.permit_wait_duration = r.register(Histogram(
            n + "permit_wait_duration_seconds",
            "Time pods spend parked at WaitOnPermit.",
            label_names=("result",)))
        self.device_batch_size = r.register(Histogram(
            n + "device_batch_size",
            "Pods assigned per device program dispatch.",
            buckets=[1, 8, 32, 128, 512, 1024, 2048, 4096, 8192]))
        self.device_batch_duration = r.register(Histogram(
            n + "device_batch_duration_seconds",
            "Wall time of one device batch (dispatch to readback)."))
        self.api_dispatcher_calls = r.register(Counter(
            n + "api_dispatcher_calls_total",
            "API calls flushed by the dispatcher, by type and result.",
            ("call_type", "result")))
        self.plugin_execution_duration = r.register(Histogram(
            n + "plugin_execution_duration_seconds",
            "Duration of running a plugin at a specific extension point, "
            "sampled on ~10% of host scheduling cycles "
            "(metrics.go:322 PluginExecutionDuration).",
            buckets=exponential_buckets(0.00001, 1.5, 20),
            label_names=("plugin", "extension_point", "status")))
        self.plugin_evaluation_total = r.register(Counter(
            n + "plugin_evaluation_total",
            "Number of plugin evaluations at filter/score extension "
            "points, by plugin and profile "
            "(metrics.go PluginEvaluationTotal).",
            ("plugin", "extension_point", "profile")))
        self.cache_divergence = r.register(Counter(
            n + "cache_divergence_total",
            "Discrepancies found by the cache comparer, by kind."
            , ("kind",)))
        self.api_retries = r.register(Counter(
            n + "api_retries_total",
            "Retried API calls (retriable errors: ServerTimeout/"
            "TooManyRequests/ServiceUnavailable), by call type.",
            ("call_type",)))
        self.device_fallbacks = r.register(Counter(
            n + "device_fallbacks_total",
            "Device batches degraded to the host-oracle path, by reason "
            "(dispatch/commit fault, invalid assignment, open circuit "
            "breaker).",
            ("reason",)))
        self.circuit_breaker_transitions = r.register(Counter(
            n + "device_circuit_breaker_transitions_total",
            "Device-tier circuit breaker state transitions.",
            ("state",)))
        self.resyncs = r.register(Counter(
            n + "resyncs_total",
            "Full cache+queue rebuilds from a fresh LIST (watch-stream "
            "loss recovery)."))
        self.wave_placement_waves = r.register(Counter(
            n + "wave_placement_waves_total",
            "Speculative placement waves executed on device (group "
            "drains: merge waves + wave-scan dispatches)."))
        self.wave_conflict_ratio = r.register(Histogram(
            n + "wave_conflict_ratio",
            "Per-drain fraction of pods whose speculative wave placement "
            "conflicted (prefix cuts + serially repaired pods over the "
            "span).",
            buckets=[0.0, 0.01, 0.05, 0.1, 0.25, 0.5, 0.75, 1.0]))
        self.wave_accepted_prefix = r.register(Histogram(
            n + "wave_accepted_prefix_len",
            "Conflict-free prefix length accepted by the first wave of "
            "each group drain.",
            buckets=[1, 4, 16, 64, 256, 1024, 4096]))
        self.gang_dispatch = r.register(Counter(
            n + "gang_dispatch_total",
            "Whole-gang device dispatches by outcome: placed (all-or-"
            "nothing accept committed), rejected (quorum infeasible, "
            "unwound on device), fallback (gang degraded to the serial "
            "Permit-barrier host path).",
            ("outcome",)))
        self.gang_quorum_wait = r.register(Histogram(
            n + "gang_quorum_wait_seconds",
            "Time a gang's members spent PreEnqueue-gated before quorum "
            "was met (first gated member to un-gate).",
            buckets=exponential_buckets(0.001, 4, 12)))
        # drain compiler (kubernetes_tpu/compiler/): plan-cache traffic +
        # the cost of the pow2 padding lattice
        self.compiler_plan_cache_hits = r.register(Counter(
            n + "compiler_plan_cache_hits_total",
            "Drain-compiler plan cache hits (a drain whose pod-mix "
            "structure matched a previously compiled DrainPlan)."))
        self.compiler_plan_cache_misses = r.register(Counter(
            n + "compiler_plan_cache_misses_total",
            "Drain-compiler plan cache misses (a fresh pod-mix structure "
            "compiled into a new DrainPlan)."))
        self.compiler_pad_waste = r.register(Histogram(
            n + "compiler_pad_waste_ratio",
            "Per-drain fraction of padded work slots in the compiled "
            "plan's device programs (pow2 pod buckets x pow2 signature "
            "lattice): 1 - real/padded.",
            buckets=[0.0, 0.05, 0.1, 0.25, 0.5, 0.75, 0.9, 1.0]))
        # columnar ingest & commit engine (kubernetes_tpu/ingest/):
        # generation-diff snapshot upload traffic — scattered rows vs
        # full matrix re-uploads (the 50k/s-vs-upload-bound split)
        self.ingest_rows_scattered = r.register(Counter(
            n + "ingest_rows_scattered_total",
            "Dirty node rows shipped to the device via the generation-"
            "diff scatter_rows entry instead of a full NodeArrays "
            "re-upload."))
        self.ingest_full_uploads = r.register(Counter(
            n + "ingest_full_uploads_total",
            "Full NodeArrays device uploads (first build, shape growth, "
            "or a dirty-row set too large for the incremental scatter)."))
        self.drain_phase = r.register(Histogram(
            n + "drain_phase_seconds",
            "Per-drain wall time by phase: host_build (snapshot + batch "
            "+ group seeding), device (dispatch + readback wait), commit "
            "(assume + bind enqueue + failure handling). host_build "
            "decomposes into host_snapshot / host_tensorize / "
            "host_group_seed / host_cache children.",
            label_names=("phase",)))
        self.events_total = r.register(Counter(
            n + "events_total",
            "Scheduling events emitted by the event recorder, by type "
            "(Normal/Warning) and reason (events.go analog).",
            ("type", "reason")))
        self.unschedulable_nodes = r.register(Histogram(
            n + "unschedulable_nodes",
            "Per-FailedScheduling rejected-node count, by the plugin that "
            "rejected them (device mask-derived diagnosis).",
            buckets=[1, 8, 64, 512, 2048, 8192, 32768],
            label_names=("plugin",)))
        # device/compile cost capture (perf/ledger.py): mirrored from the
        # process-global compile ledger at exposition time
        self.xla_compiles = r.register(Counter(
            n + "xla_compiles_total",
            "XLA executables compiled per kernel entry point (fresh "
            "jit-cache entries; >1 per kernel = retraces).",
            ("kernel",)))
        self.xla_compile_seconds = r.register(Counter(
            n + "xla_compile_seconds",
            "Wall seconds spent in dispatches that minted a fresh XLA "
            "executable (trace + lower + compile), per kernel.",
            ("kernel",)))
        self.h2d_bytes = r.register(Counter(
            n + "h2d_bytes_total",
            "Host-device transfer bytes by the drain phase that paid "
            "them (node-array/group/table uploads; device_readback is "
            "the d2h direction).",
            ("phase",)))
        # kernel observatory (perf/observatory.py, ISSUE 14): mirrored
        # from the process-global observatory at exposition time
        self.kernel_device_seconds = r.register(Counter(
            n + "kernel_device_seconds",
            "Cumulative warm dispatch wall seconds per JIT kernel entry "
            "point (compiling calls excluded — xla_compile_seconds "
            "carries those).",
            ("kernel",)))
        self.kernel_dispatch_total = r.register(Counter(
            n + "kernel_dispatch_total",
            "Device dispatches per JIT kernel entry point (warm + "
            "compiling).",
            ("kernel",)))
        self.shard_lane_seconds = r.register(Gauge(
            n + "shard_lane_seconds",
            "Per-device local compute seconds from the latest "
            "sharded-lane profile (parallel/sharding.py "
            "profile_shard_lanes); 0 = unprofiled or unsharded.",
            ("lane",)))
        self.shard_imbalance_ratio = r.register(Gauge(
            n + "shard_imbalance_ratio",
            "Peak-lane over mean-lane local compute time from the "
            "latest sharded-lane profile (1.0 = perfectly balanced; "
            "0 = unprofiled)."))
        # shadow-oracle audit + decision provenance + SLO engine
        # (kubernetes_tpu/obs/, ISSUE 10)
        self.oracle_divergence = r.register(Counter(
            n + "oracle_divergence_total",
            "Shadow-oracle audit divergences between committed device "
            "decisions and the host-oracle replay, by kind: assignment "
            "(both bound, different node), reason (same verdict, "
            "different FailedScheduling histogram), verdict (bound vs "
            "unschedulable).",
            ("kind",)))
        self.shadow_audit_drains = r.register(Counter(
            n + "shadow_audit_drains_total",
            "Drains sampled by the shadow-oracle audit, by outcome "
            "(clean/divergent/skipped/error).",
            ("outcome",)))
        self.audit_replay_duration = r.register(Histogram(
            n + "audit_replay_seconds",
            "Wall time of one shadow-audit host-oracle replay "
            "(background worker, off the hot path)."))
        self.explain_duration = r.register(Histogram(
            n + "explain_seconds",
            "Wall time of one /debug/explain decision decomposition "
            "(prefix replay + explain_row kernel)."))
        self.slo_burn_rate = r.register(Gauge(
            n + "slo_burn_rate",
            "Error-budget burn rate per SLI and look-back window "
            "(obs/slo.py): error_rate / (1 - objective); 1.0 = consuming "
            "exactly the budget.",
            ("sli", "window")))
        # active/standby HA (kubernetes_tpu/ha/, ISSUE 12)
        self.leader_transitions = r.register(Counter(
            n + "leader_election_transitions_total",
            "Leader-elector state transitions, by reason: acquired "
            "(took the lease), released (voluntary handoff), lost "
            "(another holder claimed an expired lease), renew_deadline "
            "(deposed-leader slow path: renews kept failing past the "
            "renew deadline, stepped down before lease expiry).",
            ("reason",)))
        self.ha_failover = r.register(Histogram(
            n + "ha_failover_seconds",
            "Wall time of one standby takeover: final ledger tail drain "
            "+ delta resync + promotion (ha/standby.py). The warm-spare "
            "contract: well under a cold LIST + tensorize + JIT warm-up."))
        self.ha_ledger_tail_lag = r.register(Gauge(
            n + "ha_ledger_tail_lag_drains",
            "Drains the standby's ledger-tail cursor is behind the "
            "leader's drain ledger head, measured at each sync."))
        self.fenced_writes_rejected = r.register(Counter(
            n + "fenced_writes_rejected_total",
            "Dispatcher writes rejected by the API server for carrying "
            "a stale fencing token (lease generation) — a deposed "
            "leader's late flush, unwound through on_bind_error."))
        # sharded control plane (kubernetes_tpu/ha/shards.py, ISSUE 17)
        self.shard_assignments = r.register(Gauge(
            n + "shard_assignments",
            "Explicit profile/namespace keys routed to each shard by the "
            "fenced ShardMap (keys not listed route by stable hash).",
            ("shard",)))
        self.shard_rebalance = r.register(Histogram(
            n + "shard_rebalance_seconds",
            "Wall time of one shard lease handoff (split/merge/steal): "
            "predecessor park + generation-bump acquire + ledger annex + "
            "warm adopt from the parked set (ha/shards.py transfer)."))
        self.shard_steals = r.register(Counter(
            n + "shard_steals_total",
            "Shard lease handoffs, by reason: split (1→N topology "
            "change), merge (N→1 collapse), steal (peer takes a loaded "
            "or dead shard), rebalance (planned move).",
            ("reason",)))
        self.cross_shard_conflicts = r.register(Counter(
            n + "cross_shard_conflicts_total",
            "Cross-shard bind races detected at commit, by outcome: "
            "conflict (pod already bound by a peer — the pod-level "
            "guard) or fenced (stale shard-lease generation — the "
            "ordering primitive). Both unwind through on_bind_error.",
            ("outcome",)))
        self.incidents = r.register(Counter(
            n + "incidents_total",
            "Incident-watchdog evidence-bundle captures, by trigger: "
            "slo_breach (federated SLO ladder trip), divergence "
            "(shadow-oracle divergence growth), fence_storm "
            "(fenced-write burst over threshold), pipeline_stall (no "
            "pipeline forward progress beyond budget). Each capture "
            "writes one bounded bundle to incidentDir "
            "(kubernetes_tpu/obs/incident.py).",
            ("trigger",)))
        # streaming drain pipeline (kubernetes_tpu/pipeline.py, ISSUE 18):
        # per-stage busy walls + backpressure stalls, mirrored from the
        # pipeline's own counters at exposition time (publish_metrics)
        self.pipeline_stage_busy = r.register(Counter(
            n + "pipeline_stage_busy_seconds",
            "Cumulative busy wall seconds per streaming-pipeline stage: "
            "ingest (arrival admit + batch build + plan compile + "
            "dispatch enqueue), device (non-overlapping dispatch-to-"
            "ready execution windows), commit (assume/bind commit + "
            "bulk bind-echo flush). Sum across stages exceeding the "
            "pipeline wall == measured stage overlap.",
            ("stage",)))
        self.pipeline_backpressure = r.register(Counter(
            n + "pipeline_backpressure_total",
            "Streaming-pipeline stalls, labeled by the STALLED stage: "
            "ingest (batch close deferred: dispatch depth at cap), "
            "device (dispatch deferred: commit backlog at cap), commit "
            "(commit worker waited on the host lock).",
            ("stage",)))
        self.dispatcher_inflight = r.register(Gauge(
            n + "dispatcher_inflight",
            "In-flight work of the async commit pipeline at scrape time: "
            "queued api_calls (dispatcher) and dispatched-but-uncommitted "
            "drains.",
            ("kind",), callback=inflight))
        # pod-journey tracing + on-device cluster analytics
        # (kubernetes_tpu/obs/journey.py + ops cluster_probe, ISSUE 13)
        self.e2e_segment = r.register(Histogram(
            n + "e2e_segment_seconds",
            "Queue→bind e2e latency decomposition by segment: queue_wait "
            "(ready in queue to pop), gate_wait (PreEnqueue-gated, incl. "
            "gang quorum), drain (device dispatch to commit), "
            "commit_backlog (dispatcher enqueue to bind-echo confirm).",
            buckets=exponential_buckets(0.0001, 2, 22),
            label_names=("segment",)))
        self.pod_requeues = r.register(Counter(
            n + "pod_requeues_total",
            "Pods re-entering the scheduling queue, by cause (journey "
            "ledger requeue transitions: preemption nomination, "
            "FencedWrite unwind, breaker fallback, gang split, resync, "
            "bind error, plain unschedulable).",
            ("cause",)))
        self.journey_transitions = r.register(Counter(
            n + "journey_transitions_total",
            "Pod lifecycle transitions recorded by the journey ledger, "
            "by event.",
            ("event",)))
        self.cluster_utilization = r.register(Gauge(
            n + "cluster_utilization_ratio",
            "cluster_probe per-resource utilization at the latest drain "
            "sample: nearest-rank percentiles over nodes advertising the "
            "resource, plus the exact aggregate mean (sum used / sum "
            "capacity).",
            ("resource", "stat")))
        self.cluster_fragmentation = r.register(Gauge(
            n + "cluster_fragmentation_index",
            "cluster_probe free-capacity health per resource: "
            "fragmentation = 1 - largest single free block / total free; "
            "stranded = free capacity on bottleneck-tight nodes / total "
            "free.",
            ("resource", "kind")))
        self.cluster_domain_imbalance = r.register(Gauge(
            n + "cluster_domain_imbalance",
            "cluster_probe topology-domain pod-density stats (pods per "
            "valid node per domain) over the gang engine's Tesserae "
            "dom-id column.",
            ("stat",)))
        # critical-path observatory (kubernetes_tpu/perf/critical_path.py,
        # `CriticalPathObservatory` gate, ISSUE 20): per-drain bottleneck
        # attribution stamped on the flight record and summed here
        self.critical_path_seconds = r.register(Counter(
            n + "critical_path_seconds",
            "Seconds attributed to each critical-path cause across "
            "committed drains: host_build (snapshot/tensorize/group-seed/"
            "cache), device_compute / device_comms (device dispatch wall "
            "split by the sharded-lane comms share), commit (assume/bind "
            "+ bind-echo flush), backpressure (streaming-pipeline stage "
            "stalls), idle (lock-step readback wait — the overlap the "
            "pipeline reclaims).",
            ("cause",)))
        self.bottleneck_drains = r.register(Counter(
            n + "bottleneck_drains_total",
            "Committed drains by dominant critical-path verdict (argmax "
            "of the per-cause seconds above; all-zero drains count as "
            "idle).",
            ("cause",)))
        # pre-seed the zero samples so dashboards (and bench_metrics.prom)
        # always carry the fault-path series, faults or not
        from ..backend.dispatcher import CallType
        for ct in CallType:
            self.api_retries.inc(ct.value, by=0)
        for reason in ("dispatch", "commit", "invalid_assignment",
                       "circuit_open"):
            self.device_fallbacks.inc(reason, by=0)
        self.resyncs.inc(by=0)
        for outcome in ("placed", "rejected", "fallback"):
            self.gang_dispatch.inc(outcome, by=0)
        self.gang_quorum_wait.seed()
        self.compiler_plan_cache_hits.inc(by=0)
        self.compiler_plan_cache_misses.inc(by=0)
        self.ingest_rows_scattered.inc(by=0)
        self.ingest_full_uploads.inc(by=0)
        self.compiler_pad_waste.seed()
        self.wave_placement_waves.inc(by=0)
        self.wave_conflict_ratio.seed()
        self.wave_accepted_prefix.seed()
        for phase in ("host_build", "device", "commit",
                      "host_snapshot", "host_tensorize",
                      "host_group_seed", "host_cache"):
            self.drain_phase.seed(phase)
        # remaining registered-but-unseeded series: dashboards and
        # bench_metrics.prom must carry every series even when the run
        # never observes them (no permit waits, no divergence, no events)
        for result in (SCHEDULED, UNSCHEDULABLE, ERROR):
            self.schedule_attempts.inc(result, DEFAULT_PROFILE, by=0)
            self.attempt_duration.seed(result, DEFAULT_PROFILE)
        for result in ("allowed", "rejected"):
            self.permit_wait_duration.seed(result)
        self.sli_duration.seed("1")
        self.device_batch_size.seed()
        self.device_batch_duration.seed()
        self.preemption_victims.seed()
        self.preemption_attempts.inc(by=0)
        for state in ("open", "closed"):
            self.circuit_breaker_transitions.inc(state, by=0)
        for queue, event in (("active", "PodAdd"), ("gated", "PodAdd"),
                             ("unschedulable", "ScheduleAttemptFailure")):
            self.queue_incoming_pods.inc(queue, event, by=0)
        from ..backend.dispatcher import CallType
        for ct in CallType:
            self.api_dispatcher_calls.inc(ct.value, "success", by=0)
        for kind in ("device_vs_host", "host_vs_apiserver"):
            self.cache_divergence.inc(kind, by=0)
        for etype, reason in (("Normal", "Scheduled"),
                              ("Warning", "FailedScheduling")):
            self.events_total.inc(etype, reason, by=0)
        for plugin in DEVICE_FILTER_PLUGINS:
            self.unschedulable_nodes.seed(plugin)
        for plugin in DEVICE_FILTER_PLUGINS:
            self.plugin_execution_duration.seed(plugin, "Filter", "SUCCESS")
            self.plugin_evaluation_total.inc(plugin, "Filter",
                                             DEFAULT_PROFILE, by=0)
        for plugin in DEVICE_SCORE_PLUGINS:
            self.plugin_execution_duration.seed(plugin, "Score", "SUCCESS")
            self.plugin_evaluation_total.inc(plugin, "Score",
                                             DEFAULT_PROFILE, by=0)
        from ..perf.ledger import H2D_PHASES, KERNELS
        for kernel in KERNELS:
            self.xla_compiles.inc(kernel, by=0)
            self.xla_compile_seconds.inc(kernel, by=0)
            self.kernel_device_seconds.inc(kernel, by=0)
            self.kernel_dispatch_total.inc(kernel, by=0)
        for phase in H2D_PHASES:
            self.h2d_bytes.inc(phase, by=0)
        for lane in SHARD_SEED_LANES:
            self.shard_lane_seconds.set(0.0, lane)
        self.shard_imbalance_ratio.set(0.0)
        # seed the static fallback values; a wired callback (the live
        # scheduler) takes precedence at scrape time
        for kind in ("api_calls", "drains"):
            self.dispatcher_inflight.set(0.0, kind)
        from ..pipeline import STAGES as PIPELINE_STAGES
        for stage in PIPELINE_STAGES:
            self.pipeline_stage_busy.inc(stage, by=0)
            self.pipeline_backpressure.inc(stage, by=0)
        from ..perf.critical_path import CAUSES as CP_CAUSES
        for cause in CP_CAUSES:
            self.critical_path_seconds.inc(cause, by=0)
            self.bottleneck_drains.inc(cause, by=0)
        for kind in ("assignment", "reason", "verdict"):
            self.oracle_divergence.inc(kind, by=0)
        for outcome in ("clean", "divergent", "skipped", "error"):
            self.shadow_audit_drains.inc(outcome, by=0)
        self.audit_replay_duration.seed()
        self.explain_duration.seed()
        from ..obs.slo import DEFAULT_OBJECTIVES, WINDOWS
        for sli in DEFAULT_OBJECTIVES:
            for _secs, window in WINDOWS:
                self.slo_burn_rate.set(0.0, sli, window)
        for reason in ("acquired", "released", "lost", "renew_deadline"):
            self.leader_transitions.inc(reason, by=0)
        self.ha_failover.seed()
        self.ha_ledger_tail_lag.set(0.0)
        self.fenced_writes_rejected.inc(by=0)
        for shard in SHARD_SEED_IDS:
            self.shard_assignments.set(0.0, shard)
        self.shard_rebalance.seed()
        for reason in SHARD_STEAL_REASONS:
            self.shard_steals.inc(reason, by=0)
        for outcome in CROSS_SHARD_OUTCOMES:
            self.cross_shard_conflicts.inc(outcome, by=0)
        from ..obs.incident import TRIGGERS
        for trigger in TRIGGERS:
            self.incidents.inc(trigger, by=0)
        from ..obs.journey import CAUSES, EVENTS, SEGMENTS
        for segment in SEGMENTS:
            self.e2e_segment.seed(segment)
        for cause in CAUSES:
            self.pod_requeues.inc(cause, by=0)
        for event in EVENTS:
            self.journey_transitions.inc(event, by=0)
        for res in CLUSTER_SEED_RESOURCES:
            for stat in CLUSTER_UTIL_STATS:
                self.cluster_utilization.set(0.0, res, stat)
            for kind in CLUSTER_FRAG_KINDS:
                self.cluster_fragmentation.set(0.0, res, kind)
        for stat in CLUSTER_DOM_STATS:
            self.cluster_domain_imbalance.set(0.0, stat)

    def sync_compile_ledger(self) -> None:
        """Mirror the process-global compile ledger (perf/ledger.py) into
        the xla_*/h2d series. Absolute assignment, not increment: the
        ledger owns the monotonic totals (jit caches are process-wide, so
        per-Scheduler deltas would under-report shared compiles)."""
        from ..perf.ledger import GLOBAL
        for kernel, rec in GLOBAL.kernels.items():
            self.xla_compiles._values[(kernel,)] = float(rec.compiles)
            self.xla_compile_seconds._values[(kernel,)] = rec.compile_seconds
        for phase, nbytes in GLOBAL.h2d.items():
            self.h2d_bytes._values[(phase,)] = float(nbytes)

    def sync_observatory(self) -> None:
        """Mirror the kernel observatory (perf/observatory.py) into the
        kernel_*/shard_* series — absolute assignment for the same
        process-global reason as the ledger sync above."""
        from ..perf.observatory import GLOBAL
        kernels, shard = GLOBAL.metrics_view()
        for kernel, (dispatches, seconds) in kernels.items():
            self.kernel_dispatch_total._values[(kernel,)] = float(dispatches)
            self.kernel_device_seconds._values[(kernel,)] = seconds
        for i, secs in enumerate(shard.get("laneSeconds", ())):
            self.shard_lane_seconds.set(float(secs), str(i))
        ratio = shard.get("imbalanceRatio")
        if ratio is not None:
            self.shard_imbalance_ratio.set(float(ratio))

    def exposition(self) -> str:
        self.sync_compile_ledger()
        self.sync_observatory()
        return self.registry.exposition()
